"""DispatchMeta — the chunk->rank assignment and its permutations.

Ref: magi_attention/meta/collection/dispatch_meta.py:24-122. For the TPU
build the permutation lives as host numpy index arrays that become static
gather indices inside the sharded dispatch/undispatch ops.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ...common.enum import AttnType
from ...common.ranges import AttnRanges


@dataclass
class DispatchMeta:
    """Assignment of sequence chunks to CP ranks.

    Attributes:
        attn_type: self or cross attention.
        total_seqlen: global (padded) sequence length.
        chunk_size: rows per chunk.
        cp_size: number of CP ranks.
        partitions: chunk ids per rank, sorted ascending within each rank.
        position_ids: ``(cp_size, shard_len)`` int32 — global row index of
            each local row, per rank (the dispatch gather indices).
        host_ranges_per_rank: merged global row ranges owned by each rank.
    """

    attn_type: AttnType
    total_seqlen: int
    chunk_size: int
    cp_size: int
    partitions: list[list[int]]
    _position_ids: np.ndarray | None = field(default=None, repr=False)
    _host_ranges: list[AttnRanges] | None = field(default=None, repr=False)
    _unpermute_index: np.ndarray | None = field(default=None, repr=False)

    @property
    def num_chunks(self) -> int:
        return self.total_seqlen // self.chunk_size

    @property
    def is_uneven(self) -> bool:
        """Ranks own different chunk counts (DispatchConfig.uneven_shard)."""
        lens = {len(p) for p in self.partitions}
        return len(lens) > 1

    @property
    def shard_seqlen(self) -> int:
        """Padded on-device rows per rank (max over ranks when uneven)."""
        return max(len(p) for p in self.partitions) * self.chunk_size

    @property
    def shard_lens(self) -> list[int]:
        """Valid (unpadded) rows per rank."""
        return [len(p) * self.chunk_size for p in self.partitions]

    @property
    def position_ids(self) -> np.ndarray:
        """(cp, shard_seqlen) global row per local row; pad rows index 0
        (their attention output is never read back — dummy-tile rows)."""
        if self._position_ids is None:
            cs = self.chunk_size
            sp = self.shard_seqlen
            out = np.zeros((self.cp_size, sp), dtype=np.int32)
            for r, chunks in enumerate(self.partitions):
                rows = [np.arange(c * cs, (c + 1) * cs, dtype=np.int32) for c in chunks]
                cat = np.concatenate(rows) if rows else np.zeros(0, np.int32)
                out[r, : len(cat)] = cat
            self._position_ids = out
        return self._position_ids

    @property
    def host_ranges_per_rank(self) -> list[AttnRanges]:
        if self._host_ranges is None:
            cs = self.chunk_size
            self._host_ranges = [
                AttnRanges.from_ranges(
                    [(c * cs, (c + 1) * cs) for c in chunks]
                ).merge()
                for chunks in self.partitions
            ]
        return self._host_ranges

    @property
    def unpermute_index(self) -> np.ndarray:
        """``(total_seqlen,)`` int32: for each global row, its index in the
        rank-major concatenation of all (padded) local shards (the undispatch
        gather). Pad rows are simply never selected."""
        if self._unpermute_index is None:
            sp = self.shard_seqlen
            pos = self.position_ids  # (cp, sp), pads point at row 0
            inv = np.empty(self.total_seqlen, dtype=np.int32)
            flat_pos = pos.reshape(-1)
            flat_idx = np.arange(len(flat_pos), dtype=np.int32)
            valid = np.ones(len(flat_pos), dtype=bool)
            # pads (uneven shard) duplicate global row 0: keep only each
            # rank's true rows
            for r, n in enumerate(self.shard_lens):
                valid[r * sp + n: (r + 1) * sp] = False
            inv[flat_pos[valid]] = flat_idx[valid]
            self._unpermute_index = inv
        return self._unpermute_index

    def global_row_owner(self) -> np.ndarray:
        """``(total_seqlen,)`` int32 rank owning each global row."""
        owner = np.empty(self.total_seqlen, dtype=np.int32)
        cs = self.chunk_size
        for r, chunks in enumerate(self.partitions):
            for c in chunks:
                owner[c * cs : (c + 1) * cs] = r
        return owner
