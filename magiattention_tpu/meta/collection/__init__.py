"""Planning outputs: DispatchMeta, CalcMeta, CommMeta."""
