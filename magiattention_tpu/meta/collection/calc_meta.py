"""CalcMeta / AttnArg — per-rank kernel arguments in local coordinates.

Ref: magi_attention/meta/collection/calc_meta.py:67-918. An AttnArg is the
band-slice list one kernel invocation replays; the CP runtime stacks per-rank
args (padded to a common slice count) into sharded device arrays.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ...kernels.mask_utils import BAND_INF


@dataclass
class AttnArg:
    """Band slices in local coordinates for one kernel call."""

    q_ranges: np.ndarray  # (N, 2) int32
    k_ranges: np.ndarray  # (N, 2) int32
    d_lo: np.ndarray  # (N,) int32
    d_hi: np.ndarray  # (N,) int32
    total_seqlen_q: int = 0
    total_seqlen_k: int = 0

    @classmethod
    def empty(cls, total_seqlen_q: int = 0, total_seqlen_k: int = 0) -> "AttnArg":
        return cls(
            q_ranges=np.zeros((0, 2), dtype=np.int32),
            k_ranges=np.zeros((0, 2), dtype=np.int32),
            d_lo=np.zeros((0,), dtype=np.int32),
            d_hi=np.zeros((0,), dtype=np.int32),
            total_seqlen_q=total_seqlen_q,
            total_seqlen_k=total_seqlen_k,
        )

    @classmethod
    def from_slices(
        cls,
        slices: list[tuple[int, int, int, int, int, int]],
        total_seqlen_q: int,
        total_seqlen_k: int,
    ) -> "AttnArg":
        """slices: (n, 6) rows of (qs, qe, ks, ke, d_lo, d_hi) in local
        coords — a list of tuples or an int array."""
        if len(slices) == 0:
            return cls.empty(total_seqlen_q, total_seqlen_k)
        arr = np.asarray(slices, dtype=np.int64)
        return cls(
            q_ranges=arr[:, 0:2].astype(np.int32),
            k_ranges=arr[:, 2:4].astype(np.int32),
            d_lo=np.clip(arr[:, 4], -BAND_INF, BAND_INF).astype(np.int32),
            d_hi=np.clip(arr[:, 5], -BAND_INF, BAND_INF).astype(np.int32),
            total_seqlen_q=total_seqlen_q,
            total_seqlen_k=total_seqlen_k,
        )

    @property
    def num_slices(self) -> int:
        return len(self.q_ranges)

    def pad_to(self, n: int) -> "AttnArg":
        """Pad with empty slices to a static count (SPMD stacking)."""
        cur = self.num_slices
        if cur > n:
            raise ValueError(f"{cur} slices > pad target {n}")
        if cur == n:
            return self
        pad = n - cur
        return AttnArg(
            q_ranges=np.concatenate(
                [self.q_ranges, np.zeros((pad, 2), dtype=np.int32)]
            ),
            k_ranges=np.concatenate(
                [self.k_ranges, np.zeros((pad, 2), dtype=np.int32)]
            ),
            d_lo=np.concatenate(
                [self.d_lo, np.full((pad,), -BAND_INF, dtype=np.int32)]
            ),
            d_hi=np.concatenate(
                [self.d_hi, np.full((pad,), BAND_INF, dtype=np.int32)]
            ),
            total_seqlen_q=self.total_seqlen_q,
            total_seqlen_k=self.total_seqlen_k,
        )

    def area(self) -> int:
        from ..container.slice import band_area

        return sum(
            band_area(
                int(self.q_ranges[i, 0]), int(self.q_ranges[i, 1]),
                int(self.k_ranges[i, 0]), int(self.k_ranges[i, 1]),
                int(self.d_lo[i]), int(self.d_hi[i]),
            )
            for i in range(self.num_slices)
        )


@dataclass
class CalcMeta:
    """Per-rank kernel args for the CP engine (self-attention).

    Attributes:
        host_args: rank -> slices over (local q, local kv shard).
        remote_args_per_stage: stage -> rank -> slices over (local q, that
            stage's remote-kv receive buffer).
        merged_args: rank -> slices over (local q, [kv shard | all remote kv])
            — the single-kernel concat path (ref dist_attn.py:3305 no-overlap).
        shard_len: local q rows per rank.
        kv_shard_len: local kv rows per rank (== shard_len for self-attn).
        recv_len_per_stage: stage -> padded remote-kv rows (same on all ranks).
    """

    host_args: list[AttnArg]
    remote_args_per_stage: list[list[AttnArg]]
    merged_args: list[AttnArg]
    shard_len: int
    recv_len_per_stage: list[int] = field(default_factory=list)
    kv_shard_len: int | None = None

    def __post_init__(self) -> None:
        if self.kv_shard_len is None:
            self.kv_shard_len = self.shard_len
