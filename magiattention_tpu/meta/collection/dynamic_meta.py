"""DynamicAttnPlan — the executable plan emitted by the dynamic (qo-comm)
solver.

Ref: magi_attention/meta/solver/dynamic_attn_solver.py:47-608 builds
group-collective args for q, o, do, dq and kv; on TPU the backward-direction
collectives need no separate args — they are the linear transposes of the two
forward casts (q_cast, kv_cast) plus the return gather (ret), so the plan
carries exactly three GroupCollectiveArgs and one merge-index matrix.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .calc_meta import AttnArg
from .comm_meta import GroupCollectiveArg


@dataclass
class DynamicAttnPlan:
    """Host plan for one dynamic-solver solve.

    Execution contract (per rank, inside shard_map):

    1. ``q_buf = [local q shard | group_cast(q, q_cast)]``  (q_buf_len rows)
    2. ``k_buf/v_buf = [local kv shard | group_cast(k/v, kv_cast)]``
    3. ``out_buf, lse_buf = FFA(q_buf, k_buf, v_buf, attn_args[rank])``
    4. ``ret_out/lse = group_cast(out_buf/lse_buf, ret)`` — partials return
       to their q owners
    5. per local row, lse-merge the rows selected by ``merge_idx`` from
       ``[out_buf | ret_buf | dummy]`` (dummy = 0 / -inf).

    Backward is the exact transpose: (do, lse, delta) re-distribute via
    ``q_cast``; dq/dkv partial rows reduce back via the transposes of
    ``q_cast`` / ``kv_cast``.
    """

    q_cast: GroupCollectiveArg
    kv_cast: GroupCollectiveArg
    ret: GroupCollectiveArg
    attn_args: list[AttnArg]
    merge_idx: np.ndarray  # (cp, shard, M) int32
    shard_len: int
    kv_shard_len: int
    q_buf_len: int
    k_buf_len: int
    ret_len: int
    # solver carryover (DynSolveState): the input rectangles + per-rank tile
    # buckets behind this plan, fed back as prev_state for the next step's
    # incremental re-solve. Not part of the executable contract.
    solver_state: object | None = None

    @property
    def cp_size(self) -> int:
        return len(self.attn_args)

    @property
    def dummy_index(self) -> int:
        return self.q_buf_len + self.ret_len

    def comm_rows(self) -> dict[str, int]:
        """Total communicated rows by stream (plan-quality metric)."""
        return {
            "q": int(self.q_cast.send_counts.sum()),
            "kv": 2 * int(self.kv_cast.send_counts.sum()),
            "out_lse": 2 * int(self.ret.send_counts.sum()),
        }
