"""CommMeta / GroupCollectiveArg — the group-collective plan.

Ref: magi_attention/meta/collection/comm_meta.py:41-765. A
GroupCollectiveArg describes one GroupCast stage as whole-mesh index arrays
that lower onto ``jax.lax.all_to_all`` inside shard_map:

  send:  every rank gathers ``send_idx[rank]`` rows of its kv shard into a
         (cp, A) buffer (A = aligned max rows per (src,dst) pair)
  a2a:   all_to_all over the cp axis
  recv:  every rank gathers ``recv_sel[rank]`` rows of the flattened (cp*A)
         receive buffer into its remote-kv buffer (R_max rows)

The transpose of this program under jax AD is exactly GroupReduce (scatter-add
back through the gathers + reverse all_to_all), so the backward dkv reduction
needs no hand-written comm (XLA replaces the reference's
group_reduce/_reduce_partial_dkv machinery, dist_attn.py:2123).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING

import numpy as np

from ...common.ranges import AttnRanges

if TYPE_CHECKING:  # pragma: no cover - import cycle guard (comm -> meta)
    from ...comm.hier import HierGroupCastPlan


@dataclass
class GroupCollectiveArg:
    """One GroupCast stage over the whole mesh.

    Three interchangeable wire lowerings are planned host-side and the
    cheapest available one is picked per stage (``lowering``):

    - ``a2a``: dense equal-split ``jax.lax.all_to_all`` — every (src,dst)
      pair padded to ``a_cap`` (max pair rows). Wire rows/rank = cp * a_cap.
    - ``ppermute``: one ``jax.lax.ppermute`` round per active ring distance
      delta, each padded only to that distance's max pair (``pp_caps``).
      Wire rows/rank = sum(pp_caps). For skewed masks (causal) this is near
      zero-redundant instead of cp x max-pair. Portable to every backend.
    - ``ragged``: ``jax.lax.ragged_all_to_all`` — true per-pair split sizes,
      exactly zero padding on the wire (the TPU counterpart of the
      reference's native grpcoll kernels, csrc/comm/grpcoll/, splits per
      grpcoll/utils.py:593). TPU-only (XLA:CPU lacks the op — verified
      UNIMPLEMENTED in XLA:CPU ThunkEmitter as of jax 0.9), so it enters
      the candidate set only when env_comm.is_ragged_grpcoll_enable().
    """

    # [dst][src] -> global k ranges src sends to dst (the transfer table,
    # ref meta/container/transfer_table.py)
    transfer_table: list[list[AttnRanges]]
    # a2a lowering arrays
    send_idx: np.ndarray  # (cp, cp, A) int32 — [src][dst] local row indices
    send_counts: np.ndarray  # (cp, cp) int32
    recv_sel: np.ndarray  # (cp, R_max) int32 — [dst] flat src*A+pos selects
    recv_len: np.ndarray  # (cp,) int32 — valid rows per dst
    a_cap: int  # per-pair aligned capacity A
    r_max: int  # padded receive length
    # ppermute lowering arrays (None when cp == 1 / no remote traffic)
    pp_deltas: tuple[int, ...] = ()  # active ring distances (1..cp-1)
    pp_caps: tuple[int, ...] = ()  # per-delta aligned capacity
    pp_send_idx: np.ndarray | None = None  # (cp, sum_caps) int32
    pp_recv_sel: np.ndarray | None = None  # (cp, R_max) int32
    lowering: str = "a2a"  # chosen wire lowering for this stage
    # two-level plans: the solver-built phase-A/phase-B split for this stage
    # on a (dcn, ici) mesh. None on flat meshes; when set, the runtime uses
    # it directly instead of re-planning from the transfer table.
    hier_plan: "HierGroupCastPlan | None" = None

    def total_send_rows(self) -> int:
        return int(self.send_counts.sum())

    def comm_volume_bytes(self, row_bytes: int) -> int:
        """Payload actually needed (excludes alignment padding)."""
        return self.payload_rows() * row_bytes

    def payload_rows(self) -> int:
        """True off-diagonal payload rows (whole mesh)."""
        off_diag = self.send_counts.copy()
        np.fill_diagonal(off_diag, 0)
        return int(off_diag.sum())

    def wire_rows(self, lowering: str | None = None) -> int:
        """Rows crossing the wire (whole mesh) under a lowering, padding
        included — the denominator of the zero-redundancy claim."""
        cp = self.send_counts.shape[0]
        kind = lowering or self.lowering
        if kind == "ppermute":
            return cp * int(sum(self.pp_caps))
        if kind == "ragged":
            # true per-pair splits: only off-diagonal payload crosses the
            # wire (src==dst segments are local copies)
            return self.payload_rows()
        return cp * cp * self.a_cap

    def wire_ratio(self) -> float:
        """wire/payload under the chosen lowering (1.0 = zero-redundant)."""
        payload = self.payload_rows()
        return self.wire_rows() / payload if payload else 1.0

    def padding_rows(self, lowering: str | None = None) -> int:
        """Alignment-padding waste on the wire: rows transferred that carry
        no payload (wire - payload) under a lowering."""
        return max(self.wire_rows(lowering) - self.payload_rows(), 0)

    def telemetry_dict(self, executed: str | None = None) -> dict:
        """One stage's comm-volume summary for the telemetry registry
        (rows; multiply by row_bytes for bytes — the runtime does, once
        tensor dtypes are known). ``executed`` is the lowering the runtime
        actually runs when it overrides the solver's portable choice."""
        kind = executed or self.lowering
        wire = (
            self.wire_rows(kind)
            if kind in ("a2a", "ppermute", "ragged")
            else self.wire_rows(self.lowering)  # e.g. hier: flat # is a bound
        )
        payload = self.payload_rows()
        out = {
            "lowering_planned": self.lowering,
            "lowering_executed": kind,
            "payload_rows": payload,
            "wire_rows": wire,
            "padding_rows": max(wire - payload, 0),
            "wire_ratio": wire / payload if payload else 1.0,
            "a2a_wire_rows": self.wire_rows("a2a"),
            "a_cap": self.a_cap,
            "r_max": self.r_max,
            "send_rows_per_rank": self.send_counts.sum(axis=1).tolist(),
            "recv_rows_per_rank": self.recv_len.tolist(),
        }
        if self.hier_plan is not None:
            out["dcn_rows"] = self.hier_plan.dcn_rows()
        return out


def pick_lowering(arg: GroupCollectiveArg) -> str:
    """Per-stage AUTO wire-tier choice, shared by the static and dynamic
    solvers: cheapest available lowering by wire rows. The ragged tier's
    wire volume is the true payload (zero padding) so it wins whenever
    available (TPU); ties also go to it."""
    from ...env import comm as env_comm

    candidates = ["a2a"]
    if sum(arg.pp_caps):
        candidates.insert(0, "ppermute")
    if env_comm.is_ragged_grpcoll_enable():
        candidates.insert(0, "ragged")
    return min(candidates, key=arg.wire_rows)


def build_pp_lowering(
    pair_counts: np.ndarray,
    rows_for,
    recv_parts: list[list[tuple[int, int, int]]],
    r_max: int,
    align: int,
) -> tuple[tuple[int, ...], tuple[int, ...], np.ndarray | None, np.ndarray | None]:
    """Shared ppermute-lowering planner (used by both the static and the
    dynamic solver — one implementation of the per-distance packing).

    Args:
        pair_counts: (cp, cp) [src][dst] row counts.
        rows_for: callable (src, dst) -> int32 array of local row indices in
            pair order (only called for non-empty pairs).
        recv_parts: [dst] -> (src, start_pos_in_pair, n) in buffer order.
        r_max: padded receive length.
        align: per-delta capacity alignment.

    Returns:
        (deltas, caps, pp_send_idx (cp, sum_caps), pp_recv_sel (cp, r_max)),
        with the arrays None when there is no remote traffic.
    """
    cp = pair_counts.shape[0]
    deltas, caps = [], []
    for delta in range(1, cp):
        mx = max(int(pair_counts[s, (s + delta) % cp]) for s in range(cp))
        if mx > 0:
            deltas.append(delta)
            caps.append(-(-mx // align) * align)
    cum = {}
    off = 0
    for delta, c in zip(deltas, caps):
        cum[delta] = off
        off += c
    sum_caps = off
    if not sum_caps:
        return (), (), None, None
    pp_send_idx = np.zeros((cp, sum_caps), dtype=np.int32)
    for s in range(cp):
        for delta in deltas:
            d = (s + delta) % cp
            n = int(pair_counts[s, d])
            if n:
                pp_send_idx[s, cum[delta]: cum[delta] + n] = rows_for(s, d)
    pp_recv_sel = np.zeros((cp, r_max), dtype=np.int32)
    for d in range(cp):
        parts = [
            cum[(d - src) % cp] + start_pos + np.arange(n, dtype=np.int32)
            for src, start_pos, n in recv_parts[d]
            if n
        ]
        if parts:
            flat = np.concatenate(parts)
            pp_recv_sel[d, : flat.size] = flat
    return tuple(deltas), tuple(caps), pp_send_idx, pp_recv_sel


@dataclass
class CommMeta:
    """All GroupCast stages of the forward pass (kv; qo-comm adds more).

    ``kv_host_ranges`` (per-rank merged global kv ownership) rides along so
    the runtime can re-plan any stage hierarchically (comm/hier.py) from its
    transfer table without consulting the solver again.
    """

    kv_stages: list[GroupCollectiveArg] = field(default_factory=list)
    kv_host_ranges: list | None = None

    @property
    def overlap_degree(self) -> int:
        return len(self.kv_stages)
