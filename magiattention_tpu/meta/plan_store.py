"""Crash-safe on-disk plan store — the durable tier of the plan control
plane (docs/plan_control_plane.md).

A :class:`PlanStore` is a flat directory of ``plan-<digest>.bin`` blobs,
keyed by the hex sha256 of the mask signature
(``plan_io.plan_signature_digest``), shared by every process pointed at the
same directory (``MAGI_ATTENTION_PLAN_STORE_DIR``). Its two contracts:

- **Writes never corrupt readers.** Every write goes to a process-unique
  ``.tmp-<pid>-<n>`` sibling and lands via ``os.replace`` — the same atomic
  snapshot idiom as ``telemetry/store.py`` — so a concurrent reader sees
  either the old complete blob or the new complete blob, never a torn one.
  A crash mid-write leaves only an orphan ``.tmp`` file, which the next
  store open garbage-collects once it is older than
  :data:`ORPHAN_TMP_TTL_S` (the TTL keeps a live writer's in-flight tmp
  safe from a concurrently opening process).
- **Reads never raise.** Absent file, I/O error, truncation, bit flip,
  stale wire schema, mismatched env signature — every failure mode decodes
  to a typed :class:`PlanStoreMiss` the caller treats as a cache miss and
  cold-solves through. The single deliberate exception is
  :class:`~..resilience.errors.InjectedFault` from the ``plan_cache_read``
  chaos site, which follows the standard recover-or-typed-raise contract in
  the manager layer.
"""

from __future__ import annotations

import itertools
import os
import time
from dataclasses import dataclass
from typing import Any

from .. import telemetry
from ..env import general as env_general
from . import plan_io

# an orphan .tmp older than this is a crash leftover, not an in-flight write
ORPHAN_TMP_TTL_S = 600.0

MISS_ABSENT = "absent"
MISS_IO_ERROR = "io_error"
MISS_SCHEMA = "schema"
MISS_CHECKSUM = "checksum"
MISS_ENV_MISMATCH = "env_mismatch"
MISS_SIG_MISMATCH = "sig_mismatch"
MISS_DECODE = "decode_error"
MISS_VERIFY = "verify_reject"  # recorded by the manager after R1-R5 rejects

_tmp_counter = itertools.count()


@dataclass(frozen=True)
class PlanStoreMiss:
    """Typed read miss: why the store had no usable plan for a digest."""

    reason: str
    detail: str = ""


class PlanStore:
    """One shared plan directory. Construction never raises: an unusable
    directory just makes every read a miss and every write a no-op."""

    def __init__(self, directory: str) -> None:
        self.directory = directory
        self._usable = True
        try:
            os.makedirs(directory, exist_ok=True)
        except OSError:
            self._usable = False
            return
        self._cleanup_orphans()

    # -- paths -------------------------------------------------------------

    def path_for(self, digest: str) -> str:
        return os.path.join(self.directory, f"plan-{digest}.bin")

    def _cleanup_orphans(self) -> None:
        """Remove crash leftovers: ``*.tmp-*`` siblings past the TTL."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        now = time.time()
        removed = 0
        for name in names:
            if ".tmp-" not in name:
                continue
            path = os.path.join(self.directory, name)
            try:
                if now - os.path.getmtime(path) >= ORPHAN_TMP_TTL_S:
                    os.remove(path)
                    removed += 1
            except OSError:
                continue
        if removed and telemetry.enabled():
            telemetry.record_event(
                "plan_store", op="cleanup", outcome="ok", removed=removed,
            )

    # -- read / write ------------------------------------------------------

    def read(
        self, digest: str, env_sig: Any = ()
    ) -> tuple[Any | None, PlanStoreMiss | None]:
        """Load + integrity-check one entry. Returns ``(entry, None)`` on a
        hit and ``(None, PlanStoreMiss)`` on ANY failure; only the
        ``plan_cache_read`` injection site may raise (chaos contract)."""
        from ..resilience.inject import maybe_inject

        maybe_inject("plan_cache_read")
        miss: PlanStoreMiss
        if not self._usable:
            miss = PlanStoreMiss(MISS_IO_ERROR, "store directory unusable")
            self._record("read", miss=miss)
            return None, miss
        try:
            with open(self.path_for(digest), "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            miss = PlanStoreMiss(MISS_ABSENT)
            self._record("read", miss=miss)
            return None, miss
        except OSError as e:
            miss = PlanStoreMiss(MISS_IO_ERROR, type(e).__name__)
            self._record("read", miss=miss)
            return None, miss
        try:
            entry = plan_io.decode_plan(
                blob, env_sig=env_sig, expect_digest=digest
            )
        except plan_io.PlanEnvMismatchError as e:
            miss = PlanStoreMiss(MISS_ENV_MISMATCH, str(e))
        except plan_io.PlanSigMismatchError as e:
            miss = PlanStoreMiss(MISS_SIG_MISMATCH, str(e))
        except plan_io.PlanSchemaError as e:
            miss = PlanStoreMiss(MISS_SCHEMA, str(e))
        except plan_io.PlanChecksumError as e:
            miss = PlanStoreMiss(MISS_CHECKSUM, str(e))
        except plan_io.PlanDecodeError as e:
            miss = PlanStoreMiss(MISS_DECODE, str(e))
        else:
            self._record("read", outcome="hit", bytes=len(blob))
            return entry, None
        self._record("read", miss=miss)
        return None, miss

    def write(self, digest: str, blob: bytes) -> bool:
        """Atomically publish one encoded entry; returns success. Never
        raises — a failed persist costs durability, not the step."""
        if not self._usable:
            return False
        path = self.path_for(digest)
        tmp = f"{path}.tmp-{os.getpid()}-{next(_tmp_counter)}"
        try:
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError as e:
            try:
                os.remove(tmp)
            except OSError:
                pass
            self._record(
                "write", outcome="error",
                miss=PlanStoreMiss(MISS_IO_ERROR, type(e).__name__),
            )
            return False
        self._record("write", outcome="ok", bytes=len(blob))
        return True

    def _record(
        self,
        op: str,
        outcome: str | None = None,
        miss: PlanStoreMiss | None = None,
        **extra,
    ) -> None:
        if not telemetry.enabled():
            return
        payload: dict[str, Any] = dict(extra)
        if miss is not None:
            outcome = outcome or "miss"
            payload["reason"] = miss.reason
            if miss.detail:
                payload["detail"] = miss.detail
        telemetry.record_event(
            "plan_store", op=op, outcome=outcome or "ok", **payload,
        )
        telemetry.inc(f"plan_store.{op}_{outcome or 'ok'}")


_stores: dict[str, PlanStore] = {}


def get_store() -> PlanStore | None:
    """The env-configured store, or None when the disk tier is off
    (``MAGI_ATTENTION_PLAN_STORE=1`` + ``MAGI_ATTENTION_PLAN_STORE_DIR``).
    One instance per directory per process — orphan cleanup runs on first
    open only."""
    if not env_general.is_plan_store_enable():
        return None
    directory = env_general.plan_store_dir()
    store = _stores.get(directory)
    if store is None:
        store = PlanStore(directory)
        _stores[directory] = store
    return store


def reset() -> None:
    """Drop per-process store handles (tests: fresh orphan cleanup)."""
    _stores.clear()
