"""Meta/solver layer: host-side planning from slice metadata to device args.

Pipeline (ref: SURVEY §3.1):
  make_dispatch_meta_from_qk_ranges  -> DispatchMeta (chunk->rank assignment)
  make_attn_meta_from_dispatch_meta  -> CommMeta + CalcMeta (per-rank plans)
"""

from ._make_dispatch_meta import (  # noqa: F401
    make_dispatch_meta_from_qk_ranges,
    make_global_bucket_from_qk_ranges,
)
from ._make_attn_meta import make_attn_meta_from_dispatch_meta  # noqa: F401
from .collection.dispatch_meta import DispatchMeta  # noqa: F401
from .collection.calc_meta import AttnArg, CalcMeta  # noqa: F401
from .collection.comm_meta import CommMeta, GroupCollectiveArg  # noqa: F401
