"""Dispatch meta construction (ref: magi_attention/meta/_make_dispatch_meta.py:56-405).

Chunks the global sequence, computes per-chunk attention areas from the slice
metadata (the global AttnBucket), runs the DispatchSolver, and emits the
DispatchMeta with permutation indices.
"""

from __future__ import annotations

from .. import env as _env
from ..common.enum import AttnMaskType, AttnType, DispatchAlgType
from ..common.range import AttnRange
from ..common.ranges import AttnRanges
from .collection.dispatch_meta import DispatchMeta
from .container.bucket import AttnBucket, AttnChunk
from .container.slice import AttnSlice
from .solver.dispatch_solver import DispatchConfig, DispatchSolver


def make_global_bucket_from_qk_ranges(
    q_ranges: AttnRanges,
    k_ranges: AttnRanges,
    attn_mask_type: list[AttnMaskType],
    total_seqlen_q: int,
    chunk_size: int,
) -> AttnBucket:
    """Per-chunk slice lists + areas (ref: _make_dispatch_meta.py:450).

    Each global slice is clipped to every chunk it intersects; band encoding
    keeps the clip exact (no type re-derivation).
    """
    num_chunks = -(-total_seqlen_q // chunk_size)
    chunks = [
        AttnChunk(
            chunk_id=c,
            q_range=AttnRange(
                c * chunk_size, min((c + 1) * chunk_size, total_seqlen_q)
            ),
        )
        for c in range(num_chunks)
    ]
    slices = [
        AttnSlice.from_mask_type(qr, kr, AttnMaskType.normalize(mt))
        for qr, kr, mt in zip(q_ranges, k_ranges, attn_mask_type)
    ]
    for s in slices:
        if s.q_range.is_empty():
            continue
        c_lo = s.q_range.start // chunk_size
        c_hi = -(-s.q_range.end // chunk_size)
        for c in range(c_lo, min(c_hi, num_chunks)):
            clipped = s.clip_q(chunks[c].q_range.start, chunks[c].q_range.end)
            if not clipped.q_range.is_empty() and clipped.area > 0:
                chunks[c].attn_slices.append(clipped)
    return AttnBucket(cp_rank=None, q_chunks=chunks)


def make_dispatch_meta_from_qk_ranges(
    q_ranges: AttnRanges,
    k_ranges: AttnRanges,
    attn_mask_type: list[AttnMaskType],
    total_seqlen_q: int,
    total_seqlen_k: int,
    chunk_size: int,
    cp_size: int,
    dispatch_config: DispatchConfig | None = None,
    preset_partitions: list[list[int]] | None = None,
) -> tuple[DispatchMeta, DispatchMeta, AttnBucket]:
    """Build (q_meta, kv_meta, global_bucket) for self-attention.

    For self-attention q and kv share the same chunk assignment (the reference
    dispatches q/o and k/v with the same DispatchMeta for SELF_ATTN).
    """
    if total_seqlen_q % chunk_size != 0:
        raise ValueError(
            f"total_seqlen_q {total_seqlen_q} not divisible by chunk_size "
            f"{chunk_size}; pad first (api.compute_pad_size)"
        )
    dispatch_config = dispatch_config or DispatchConfig()
    num_chunks = total_seqlen_q // chunk_size
    if not dispatch_config.uneven_shard and num_chunks % cp_size != 0:
        raise ValueError(
            f"num_chunks {num_chunks} not divisible by cp_size {cp_size} "
            f"(use DispatchConfig(uneven_shard=True) or pad)"
        )
    bucket = make_global_bucket_from_qk_ranges(
        q_ranges, k_ranges, attn_mask_type, total_seqlen_q, chunk_size
    )
    areas = bucket.areas_per_chunk

    if preset_partitions is not None:
        # re-keying after dispatch: reuse a prior dispatch solution for a
        # new mask (ref api :1172) — no balance guarantee for the new mask
        partitions = [sorted(p) for p in preset_partitions]
    elif cp_size == 1:
        partitions = [list(range(num_chunks))]
    else:
        partitions = None
        if (
            dispatch_config.alg == DispatchAlgType.MIN_HEAP
            and not dispatch_config.uneven_shard
            and _env.general.is_cpp_backend_enable()
        ):
            try:  # native hot loop (csrc/magi_host.cpp magi_minheap_solve)
                from ..csrc_backend.ops import minheap_solve_native
                import numpy as _np

                partitions = [
                    sorted(p)
                    for p in minheap_solve_native(
                        _np.asarray(areas, dtype=_np.int64),
                        cp_size,
                        num_chunks // cp_size,
                    )
                ]
            except ImportError:
                partitions = None
        if partitions is None:
            solver = DispatchSolver(
                alg=dispatch_config.alg, config=dispatch_config
            )
            affinities = None
            if dispatch_config.alg in (
                DispatchAlgType.TOPP_HEAP,
                DispatchAlgType.BATCH_TOPP_HEAP,
            ) and not dispatch_config.uneven_shard:
                # (the uneven solve path balances by pure LPT and does not
                # consume affinities)
                # IOU affinity: each chunk's kv coverage — co-locating
                # overlapping coverage deduplicates GroupCast volume
                from .solver.dispatch_solver import IOUAffinity

                affinities = [
                    IOUAffinity.from_ranges(
                        AttnRanges(
                            [AttnRange(s.k_range.start, s.k_range.end)
                             for s in chunk.attn_slices]
                        )
                    )
                    for chunk in bucket.q_chunks
                ]
            partitions = solver.solve(
                areas, cp_size, affinities=affinities
            ).partitions

    is_cross = total_seqlen_k != total_seqlen_q
    meta_q = DispatchMeta(
        attn_type=AttnType.CROSS_ATTN if is_cross else AttnType.SELF_ATTN,
        total_seqlen=total_seqlen_q,
        chunk_size=chunk_size,
        cp_size=cp_size,
        partitions=partitions,
    )
    if is_cross:
        # cross-attn: kv has its own (sequential, evenly chunked) dispatch —
        # kv rows carry no per-row workload of their own
        if total_seqlen_k % cp_size != 0:
            raise ValueError(
                f"total_seqlen_k {total_seqlen_k} not divisible by cp_size"
            )
        meta_kv = DispatchMeta(
            attn_type=AttnType.CROSS_ATTN,
            total_seqlen=total_seqlen_k,
            chunk_size=total_seqlen_k // cp_size,
            cp_size=cp_size,
            partitions=[[r] for r in range(cp_size)],
        )
    else:
        # self-attn: kv follows q's assignment
        meta_kv = meta_q
    return meta_q, meta_kv, bucket
