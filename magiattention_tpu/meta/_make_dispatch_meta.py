"""Dispatch meta construction (ref: magi_attention/meta/_make_dispatch_meta.py:56-405).

Chunks the global sequence, computes per-chunk attention areas from the slice
metadata (the global AttnBucket), runs the DispatchSolver, and emits the
DispatchMeta with permutation indices.
"""

from __future__ import annotations

import logging

from .. import env as _env
from .. import telemetry
from ..common.enum import AttnMaskType, AttnType, DispatchAlgType
from ..common.range import AttnRange
from ..common.ranges import AttnRanges
from .collection.dispatch_meta import DispatchMeta
from .container.bucket import AttnBucket, AttnChunk
from .container.slice import AttnSlice
from .solver.dispatch_solver import (
    DispatchConfig,
    DispatchSolution,
    DispatchSolver,
    normalize_capacities,
)
from ..utils.profiling import instrument_host

_logger = logging.getLogger("magiattention_tpu.dispatch")


def make_global_bucket_from_qk_ranges(
    q_ranges: AttnRanges,
    k_ranges: AttnRanges,
    attn_mask_type: list[AttnMaskType],
    total_seqlen_q: int,
    chunk_size: int,
) -> AttnBucket:
    """Per-chunk slice lists + areas (ref: _make_dispatch_meta.py:450).

    Each global slice is clipped to every chunk it intersects; band encoding
    keeps the clip exact (no type re-derivation).
    """
    num_chunks = -(-total_seqlen_q // chunk_size)
    chunks = [
        AttnChunk(
            chunk_id=c,
            q_range=AttnRange(
                c * chunk_size, min((c + 1) * chunk_size, total_seqlen_q)
            ),
        )
        for c in range(num_chunks)
    ]
    slices = [
        AttnSlice.from_mask_type(qr, kr, AttnMaskType.normalize(mt))
        for qr, kr, mt in zip(q_ranges, k_ranges, attn_mask_type)
    ]
    for s in slices:
        if s.q_range.is_empty():
            continue
        c_lo = s.q_range.start // chunk_size
        c_hi = -(-s.q_range.end // chunk_size)
        for c in range(c_lo, min(c_hi, num_chunks)):
            clipped = s.clip_q(chunks[c].q_range.start, chunks[c].q_range.end)
            if not clipped.q_range.is_empty() and clipped.area > 0:
                chunks[c].attn_slices.append(clipped)
    return AttnBucket(cp_rank=None, q_chunks=chunks)


def _solve_partitions_with_alg(
    bucket: AttnBucket,
    areas: list[int],
    cp_size: int,
    num_chunks: int,
    dispatch_config: DispatchConfig,
    alg: DispatchAlgType,
) -> list[list[int]]:
    """Chunk->rank partitions under one concrete algorithm."""
    if (
        alg == DispatchAlgType.MIN_HEAP
        and not dispatch_config.uneven_shard
        and _env.general.is_cpp_backend_enable()
    ):
        try:  # native hot loop (csrc/magi_host.cpp magi_minheap_solve)
            from ..csrc_backend.ops import minheap_solve_native
            import numpy as _np

            return [
                sorted(p)
                for p in minheap_solve_native(
                    _np.asarray(areas, dtype=_np.int64),
                    cp_size,
                    num_chunks // cp_size,
                )
            ]
        except ImportError:
            pass
    solver = DispatchSolver(alg=alg, config=dispatch_config)
    affinities = None
    if alg in (
        DispatchAlgType.TOPP_HEAP,
        DispatchAlgType.BATCH_TOPP_HEAP,
    ) and not dispatch_config.uneven_shard:
        # (the uneven solve path balances by pure LPT and does not
        # consume affinities)
        # IOU affinity: each chunk's kv coverage — co-locating
        # overlapping coverage deduplicates GroupCast volume
        from .solver.dispatch_solver import IOUAffinity

        affinities = [
            IOUAffinity.from_ranges(
                AttnRanges(
                    [AttnRange(s.k_range.start, s.k_range.end)
                     for s in chunk.attn_slices]
                )
            )
            for chunk in bucket.q_chunks
        ]
    return solver.solve(areas, cp_size, affinities=affinities).partitions


def _solve_weighted_partitions(
    areas: list[int],
    cp_size: int,
    dispatch_config: DispatchConfig,
    caps: tuple[float, ...],
) -> DispatchSolution | None:
    """Capacity-weighted solve. Always the weighted LPT — it bypasses AUTO
    and the native minheap, both of which solve the equal-count uniform
    problem. The ``weighted_solve`` chaos site covers this path: an injected
    fault degrades to the uniform all-ones solve (returns None) when
    fallback is enabled, else propagates typed."""
    from ..resilience.inject import maybe_inject

    try:
        maybe_inject("weighted_solve")
    except Exception as e:
        from ..resilience.errors import InjectedFault

        if not isinstance(e, InjectedFault):
            raise
        from ..env import resilience as env_resilience

        if not env_resilience.is_fallback_enable():
            raise
        from ..resilience.fallback import record_resilience_event

        record_resilience_event(
            "fallback", "weighted_solve",
            action_detail="uniform_solve", error=type(e).__name__,
        )
        return None
    solver = DispatchSolver(alg=dispatch_config.alg, config=dispatch_config)
    return solver.solve(areas, cp_size, capacities=caps)


def estimate_remote_rows_per_rank(
    bucket: AttnBucket,
    partitions: list[list[int]],
    kv_own_ranges: list[AttnRanges] | None = None,
) -> list[int]:
    """Per-rank remote-KV row estimate for a candidate chunk assignment.

    For each rank: the union of its chunks' band-effective k coverage
    (AttnSlice.needed_k_range), minus the KV rows the rank itself owns.
    Ownership defaults to the rank's own q ranges (self-attention: kv
    follows the q assignment); cross-attention callers pass the sequential
    kv shard ownership via ``kv_own_ranges``. This is the GroupCast payload
    the dist_attn_solver will plan, estimated without running the solver —
    cheap enough to evaluate several candidate dispatches.
    """
    out = []
    for r, part in enumerate(partitions):
        if kv_own_ranges is not None:
            own = kv_own_ranges[r]
        else:
            own = AttnRanges(
                [bucket.q_chunks[c].q_range for c in part]
            ).merge()
        need = AttnRanges(
            [
                s.needed_k_range()
                for c in part
                for s in bucket.q_chunks[c].attn_slices
            ]
        ).merge()
        out.append(need.total_seqlen - need.intersect_size_with(own))
    return out


def _auto_select_partitions(
    bucket: AttnBucket,
    areas: list[int],
    cp_size: int,
    num_chunks: int,
    dispatch_config: DispatchConfig,
    kv_own_ranges: list[AttnRanges] | None = None,
) -> tuple[list[list[int]], DispatchAlgType]:
    """AUTO dispatch: pick the algorithm by a modeled compute/comm cost.

    This build's addition (the reference leaves the algorithm to the user,
    dispatch_solver.py:359). Rationale: the best algorithm depends on the
    mask — MIN_HEAP perfectly balances area but scatters chunks, which on
    *local* masks (sliding-window, block-local video) inflates remote-KV
    volume by an order of magnitude over SEQUENTIAL, whose balance on those
    masks is already near-perfect (see benchmarks/comm_volume_report.py).

    Model: rank busy-time = max(area_r, comm_area_per_row * remote_rows_r)
    (comm overlaps compute in the multi-stage runtime); mesh cost = max over
    ranks. A candidate replaces the incumbent when it is clearly cheaper
    (rel. auto_tol), or stays within tolerance *of the cheapest cost seen*
    and moves fewer total rows (anchoring to the minimum prevents the
    tolerance from ratcheting across candidates).
    """
    candidates = [
        DispatchAlgType.MIN_HEAP,
        DispatchAlgType.TOPP_HEAP,
        DispatchAlgType.SEQUENTIAL_SELECT,
    ]
    lam = dispatch_config.auto_comm_area_per_row
    tol = dispatch_config.auto_tol
    best = None  # (cost, total_rows, partitions, alg)
    min_cost = None
    seen: set[tuple] = set()
    for alg in candidates:
        parts = _solve_partitions_with_alg(
            bucket, areas, cp_size, num_chunks, dispatch_config, alg
        )
        # under uneven_shard several candidates collapse to the same LPT
        # partition — don't estimate (or "select") duplicates
        sig = tuple(tuple(p) for p in parts)
        if sig in seen:
            continue
        seen.add(sig)
        remote = estimate_remote_rows_per_rank(
            bucket, parts, kv_own_ranges=kv_own_ranges
        )
        rank_area = [sum(areas[c] for c in p) for p in parts]
        cost = max(
            max(a, lam * r) for a, r in zip(rank_area, remote)
        )
        rows = sum(remote)
        min_cost = cost if min_cost is None else min(min_cost, cost)
        if (
            best is None
            or cost < best[0] * (1 - tol)
            or (cost <= min_cost * (1 + tol) and rows < best[1])
        ):
            best = (cost, rows, parts, alg)
    assert best is not None  # MIN_HEAP always solves
    _logger.info(
        "AUTO dispatch chose %s (modeled cost %.3g, est. remote rows %d)",
        best[3].value, best[0], best[1],
    )
    return best[2], best[3]


@instrument_host
def make_dispatch_meta_from_qk_ranges(
    q_ranges: AttnRanges,
    k_ranges: AttnRanges,
    attn_mask_type: list[AttnMaskType],
    total_seqlen_q: int,
    total_seqlen_k: int,
    chunk_size: int,
    cp_size: int,
    dispatch_config: DispatchConfig | None = None,
    preset_partitions: list[list[int]] | None = None,
    capacities: list[float] | None = None,
) -> tuple[DispatchMeta, DispatchMeta, AttnBucket]:
    """Build (q_meta, kv_meta, global_bucket) for self-attention.

    For self-attention q and kv share the same chunk assignment (the reference
    dispatches q/o and k/v with the same DispatchMeta for SELF_ATTN).
    """
    if total_seqlen_q % chunk_size != 0:
        raise ValueError(
            f"total_seqlen_q {total_seqlen_q} not divisible by chunk_size "
            f"{chunk_size}; pad first (api.compute_pad_size)"
        )
    dispatch_config = dispatch_config or DispatchConfig()
    num_chunks = total_seqlen_q // chunk_size
    if not dispatch_config.uneven_shard and num_chunks % cp_size != 0:
        raise ValueError(
            f"num_chunks {num_chunks} not divisible by cp_size {cp_size} "
            f"(use DispatchConfig(uneven_shard=True) or pad)"
        )
    bucket = make_global_bucket_from_qk_ranges(
        q_ranges, k_ranges, attn_mask_type, total_seqlen_q, chunk_size
    )
    areas = bucket.areas_per_chunk

    chosen_alg = dispatch_config.alg
    weighted_sol: DispatchSolution | None = None
    caps = normalize_capacities(capacities, cp_size)
    if caps is not None and preset_partitions is None and cp_size > 1:
        weighted_sol = _solve_weighted_partitions(
            areas, cp_size, dispatch_config, caps
        )
        if weighted_sol is None:
            caps = None  # chaos degraded: uniform all-ones plan

    if preset_partitions is not None:
        # re-keying after dispatch: reuse a prior dispatch solution for a
        # new mask (ref api :1172) — no balance guarantee for the new mask
        partitions = [sorted(p) for p in preset_partitions]
        chosen_alg = None
    elif cp_size == 1:
        partitions = [list(range(num_chunks))]
        chosen_alg = None
    elif weighted_sol is not None:
        partitions = weighted_sol.partitions
        chosen_alg = None
    elif dispatch_config.alg == DispatchAlgType.AUTO:
        kv_own = None
        if total_seqlen_k != total_seqlen_q:
            # cross-attn: kv ownership is the sequential even shard in
            # k-space (see meta_kv below), not the rank's q ranges
            if total_seqlen_k % cp_size != 0:
                raise ValueError(
                    f"total_seqlen_k {total_seqlen_k} not divisible by "
                    f"cp_size"
                )
            sz = total_seqlen_k // cp_size
            kv_own = [
                AttnRanges([AttnRange(r * sz, (r + 1) * sz)])
                for r in range(cp_size)
            ]
        partitions, chosen_alg = _auto_select_partitions(
            bucket, areas, cp_size, num_chunks, dispatch_config,
            kv_own_ranges=kv_own,
        )
    else:
        partitions = _solve_partitions_with_alg(
            bucket, areas, cp_size, num_chunks, dispatch_config,
            dispatch_config.alg,
        )

    if telemetry.enabled():
        # the CHOSEN assignment (the dispatch_solve kinds above are per
        # candidate/algorithm; the native minheap path bypasses them)
        per_rank = [sum(areas[c] for c in p) for p in partitions]
        max_area = max(per_rank, default=0)
        lb = max(
            -(-sum(areas) // cp_size), max(areas, default=0)
        ) if areas else 0
        extra = {}
        if weighted_sol is not None:
            extra = {
                "capacities": list(weighted_sol.capacities or ()),
                "weighted_makespan": weighted_sol.weighted_makespan,
                "weighted_lower_bound": weighted_sol.weighted_lower_bound,
            }
        telemetry.record_event(
            "dispatch_meta",
            alg=(
                chosen_alg.value
                if isinstance(chosen_alg, DispatchAlgType)
                else (
                    "weighted" if weighted_sol is not None
                    else "preset" if preset_partitions is not None
                    else "trivial"
                )
            ),
            total_seqlen_q=total_seqlen_q,
            total_seqlen_k=total_seqlen_k,
            chunk_size=chunk_size,
            num_chunks=num_chunks,
            cp_size=cp_size,
            per_rank_area=per_rank,
            max_area=max_area,
            lower_bound=lb,
            balance_ratio=(
                weighted_sol.balance_ratio if weighted_sol is not None
                else (lb / max_area) if max_area else 1.0
            ),
            **extra,
        )

    is_cross = total_seqlen_k != total_seqlen_q
    meta_q = DispatchMeta(
        attn_type=AttnType.CROSS_ATTN if is_cross else AttnType.SELF_ATTN,
        total_seqlen=total_seqlen_q,
        chunk_size=chunk_size,
        cp_size=cp_size,
        partitions=partitions,
    )
    if is_cross:
        # cross-attn: kv has its own (sequential, evenly chunked) dispatch —
        # kv rows carry no per-row workload of their own
        if total_seqlen_k % cp_size != 0:
            raise ValueError(
                f"total_seqlen_k {total_seqlen_k} not divisible by cp_size"
            )
        meta_kv = DispatchMeta(
            attn_type=AttnType.CROSS_ATTN,
            total_seqlen=total_seqlen_k,
            chunk_size=total_seqlen_k // cp_size,
            cp_size=cp_size,
            partitions=[[r] for r in range(cp_size)],
        )
    else:
        # self-attn: kv follows q's assignment
        meta_kv = meta_q
    return meta_q, meta_kv, bucket
