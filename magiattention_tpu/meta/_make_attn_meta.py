"""Attn meta construction (ref: magi_attention/meta/_make_attn_meta.py:40-133).

Picks the CP planner (static DistAttnSolver, or the dynamic qo-comm
DynamicAttnSolver iff qo-comm is enabled — ref :62), runs solve().
"""

from __future__ import annotations

from ..common.rectangle import AttnRectangles
from ..config import DistAttnConfig
from .collection.calc_meta import CalcMeta
from .collection.comm_meta import CommMeta
from .collection.dispatch_meta import DispatchMeta
from .collection.dynamic_meta import DynamicAttnPlan
from .container.bucket import AttnBucket
from .solver.dist_attn_solver import DistAttnSolver
from ..resilience.inject import maybe_inject
from ..utils.profiling import instrument_host


@instrument_host
def make_attn_meta_from_dispatch_meta(
    bucket: AttnBucket,
    dispatch_meta: DispatchMeta,
    config: DistAttnConfig | None = None,
    dispatch_meta_kv: DispatchMeta | None = None,
    mesh_shape: tuple[int, int] | None = None,
) -> tuple[CommMeta, CalcMeta]:
    maybe_inject("comm_plan_build")
    config = config or DistAttnConfig()
    solver = DistAttnSolver(
        bucket=bucket,
        dispatch_meta=dispatch_meta,
        overlap_config=config.overlap_config,
        split_alignment=config.grpcoll_config.split_alignment,
        dispatch_meta_kv=dispatch_meta_kv,
        mesh_shape=mesh_shape,
    )
    return solver.solve()


def make_dynamic_attn_plan(
    q_ranges,
    k_ranges,
    attn_mask_type,
    dispatch_meta: DispatchMeta,
    config: DistAttnConfig | None = None,
    dispatch_meta_kv: DispatchMeta | None = None,
    prev_state=None,
) -> DynamicAttnPlan:
    """Build the qo-comm plan from global mask metadata (ref
    dynamic_attn_solver.py:236 solve — rectangles-based global assignment).

    ``prev_state`` (a DynSolveState from a previous step's solve) enables
    the incremental re-solve: rectangles unchanged since the previous mask
    keep their rank assignment and only new ones run the algorithm.
    """
    from .solver.dynamic_attn_solver import DynamicAttnSolver

    maybe_inject("dynamic_plan_solve")
    config = config or DistAttnConfig()
    rects = AttnRectangles.from_ranges(q_ranges, k_ranges, attn_mask_type)
    solver = DynamicAttnSolver(
        rects=rects,
        dispatch_meta_q=dispatch_meta,
        dispatch_meta_kv=dispatch_meta_kv,
        alg=config.dynamic_config.alg,
        split_alignment=config.grpcoll_config.split_alignment,
    )
    return solver.solve(prev_state=prev_state)
