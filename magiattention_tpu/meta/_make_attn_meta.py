"""Attn meta construction (ref: magi_attention/meta/_make_attn_meta.py:40-133).

Picks the CP planner (static DistAttnSolver; the dynamic qo-comm solver plugs
in here later), runs solve(), returns (CommMeta, CalcMeta).
"""

from __future__ import annotations

from ..config import DistAttnConfig
from .collection.calc_meta import CalcMeta
from .collection.comm_meta import CommMeta
from .collection.dispatch_meta import DispatchMeta
from .container.bucket import AttnBucket
from .solver.dist_attn_solver import DistAttnSolver


def make_attn_meta_from_dispatch_meta(
    bucket: AttnBucket,
    dispatch_meta: DispatchMeta,
    config: DistAttnConfig | None = None,
    dispatch_meta_kv: DispatchMeta | None = None,
) -> tuple[CommMeta, CalcMeta]:
    config = config or DistAttnConfig()
    solver = DistAttnSolver(
        bucket=bucket,
        dispatch_meta=dispatch_meta,
        overlap_config=config.overlap_config,
        split_alignment=config.grpcoll_config.split_alignment,
        dispatch_meta_kv=dispatch_meta_kv,
    )
    return solver.solve()
