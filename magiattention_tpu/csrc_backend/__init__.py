"""Native host backend: JIT-built C++ planning hot loops + ctypes bindings.

Counterpart of the reference's C++ common backend + JIT build system
(magi_attention/common/jit/core.py:244, csrc/extensions/magi_attn_ext.cpp):
``csrc/magi_host.cpp`` is compiled on first use with g++ into a cache
directory keyed by source hash (rebuilds automatically when the source
changes), then bound through ctypes. ``CppAttnRange``/``CppAttnRanges``
conform to ``common.protocols`` and are swapped in by ``common/__init__``
when ``MAGI_ATTENTION_CPP_BACKEND=1`` (default).
"""

from .build import get_lib  # noqa: F401
from .ranges import CppAttnRange, CppAttnRanges  # noqa: F401
from .ops import band_area_native, chunk_areas_native, minheap_solve_native  # noqa: F401
