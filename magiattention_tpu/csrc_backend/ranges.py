"""C++-backed AttnRange / AttnRanges conforming to common.protocols.

Counterpart of the reference's C++ common backend
(csrc/extensions/attn_ranges.hpp, toggled by MAGI_ATTENTION_CPP_BACKEND —
common/__init__.py:17-34). The set-algebra hot paths (merge / holes /
overlaps / make-local) run in native code over (n,2) int32 buffers; scalar
interval methods subclass the Python implementation (they are O(1) and not
worth crossing the FFI for).

Measured guidance (r2): at the 1M-token/1024-chunk planning scale the
static solver's range lists stay SMALL (tens of entries), where per-call
ctypes marshalling costs more than it saves — routing the solver through
these classes measured 17.7s vs 8.3s for the pure-Python + bisect-index
implementation. The solver therefore imports the Python classes directly;
the C++ backend remains the package-root export for API users with large
range lists and for protocol-conformance parity with the reference.
"""

from __future__ import annotations

from typing import Sequence


from ..common.range import AttnRange as _PyAttnRange
from ..common.ranges import AttnRanges as _PyAttnRanges
from . import ops


class CppAttnRange(_PyAttnRange):
    """Scalar interval — same semantics as the Python backend."""

    __slots__ = ()


class CppAttnRanges(_PyAttnRanges):
    """Range-list with native set algebra."""

    def merge(self) -> "CppAttnRanges":
        if not self._ranges:
            return CppAttnRanges()
        merged = ops.ranges_merge_native(self.to_array())
        return CppAttnRanges.from_ranges(merged.tolist())

    def find_hole_ranges(
        self, other: _PyAttnRanges, is_self_merged: bool = False
    ) -> "CppAttnRanges":
        mine = self if is_self_merged else self.merge()
        holes = ops.ranges_holes_native(
            mine.to_array(), other.merge().to_array()
        )
        return CppAttnRanges.from_ranges(holes.tolist())

    def find_overlap_ranges(self, other: _PyAttnRanges) -> "CppAttnRanges":
        out = ops.ranges_overlap_native(
            self.merge().to_array(), other.merge().to_array()
        )
        return CppAttnRanges.from_ranges(out.tolist())

    def make_ranges_local(
        self, ranges: _PyAttnRanges, is_self_merged: bool = False
    ) -> "CppAttnRanges":
        host = self if is_self_merged else self.merge()
        out = ops.ranges_make_local_native(host.to_array(), ranges.to_array())
        return CppAttnRanges.from_ranges(out.tolist())

    @classmethod
    def from_ranges(
        cls, ranges: Sequence[Sequence[int]] | Sequence[_PyAttnRange], check: bool = False
    ) -> "CppAttnRanges":
        out = cls()
        for r in ranges:
            if isinstance(r, _PyAttnRange):
                out.append(CppAttnRange(r.start, r.end), check=check)
            else:
                out.append(CppAttnRange(int(r[0]), int(r[1])), check=check)
        return out

    def sort(self) -> "CppAttnRanges":
        return CppAttnRanges(sorted(self._ranges, key=lambda r: (r.start, r.end)))

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return CppAttnRanges(self._ranges[idx])
        return self._ranges[idx]
