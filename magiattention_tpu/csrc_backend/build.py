"""JIT C++ build core (ref: magi_attention/common/jit/core.py).

Compiles csrc/magi_host.cpp with g++ -O3 into a per-source-hash cache dir
(MAGI_ATTENTION_JIT_CACHE_DIR, default ~/.cache/magiattention_tpu) and loads
it via ctypes. Thread-safe single build per process; a failed toolchain
falls back to the pure-Python implementations (common/__init__ catches the
ImportError).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from pathlib import Path

_SRC = Path(__file__).resolve().parents[2] / "csrc" / "magi_host.cpp"
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_LIB_ERR: ImportError | None = None  # memoized failure: never retry builds


def _cache_dir() -> Path:
    from ..env.general import jit_cache_dir

    return Path(jit_cache_dir())


def _build(src: Path, out: Path) -> None:
    out.parent.mkdir(parents=True, exist_ok=True)
    tmp = out.with_suffix(".so.tmp")
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        "-o", str(tmp), str(src),
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    tmp.replace(out)


def get_lib() -> ctypes.CDLL:
    """Build (once, cached by source hash) and load the native library.

    Failures are memoized (raised as the same ImportError on every later
    call) so hot paths with a Python fallback — e.g. the default-on native
    FFA plan builder — never retry a failing toolchain per call.
    """
    global _LIB, _LIB_ERR
    if _LIB is not None:
        return _LIB
    if _LIB_ERR is not None:
        raise _LIB_ERR
    with _LOCK:
        if _LIB is not None:
            return _LIB
        if _LIB_ERR is not None:
            raise _LIB_ERR
        try:
            if not _SRC.exists():
                raise ImportError(f"native source missing: {_SRC}")
            digest = hashlib.sha256(_SRC.read_bytes()).hexdigest()[:16]
            so = _cache_dir() / f"magi_host_{digest}.so"
            if not so.exists():
                try:
                    _build(_SRC, so)
                except (subprocess.CalledProcessError, FileNotFoundError) as e:
                    raise ImportError(f"native build failed: {e}") from e
            try:
                lib = ctypes.CDLL(str(so))
            except OSError as e:  # stale/foreign .so in a shared cache
                raise ImportError(f"native lib unloadable: {e}") from e
            _declare(lib)
        except ImportError as e:
            _LIB_ERR = e
            raise
        _LIB = lib
        return lib


def _declare(lib: ctypes.CDLL) -> None:
    i64, i32p, i64p = ctypes.c_int64, ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64)
    lib.magi_band_area.restype = i64
    lib.magi_band_area.argtypes = [i64] * 6
    lib.magi_chunk_areas.restype = None
    lib.magi_chunk_areas.argtypes = [i64p, i64, i64, i64, i64p]
    lib.magi_ranges_merge.restype = i64
    lib.magi_ranges_merge.argtypes = [i32p, i64, i32p]
    lib.magi_ranges_holes.restype = i64
    lib.magi_ranges_holes.argtypes = [i32p, i64, i32p, i64, i32p]
    lib.magi_ranges_overlap.restype = i64
    lib.magi_ranges_overlap.argtypes = [i32p, i64, i32p, i64, i32p]
    lib.magi_ranges_make_local.restype = i64
    lib.magi_ranges_make_local.argtypes = [i32p, i64, i32p, i64, i32p]
    lib.magi_minheap_solve.restype = None
    lib.magi_minheap_solve.argtypes = [i64p, i64, i64, i64, i32p]
    lib.magi_binary_greedy_solve.restype = ctypes.c_int32
    lib.magi_binary_greedy_solve.argtypes = [
        i64p, i64p, i64p, i64p, i64p, i32p, i32p,
        i64, i64, ctypes.c_double, i64, i32p,
    ]
    lib.magi_ffa_plan_count.restype = ctypes.c_int32
    lib.magi_ffa_plan_count.argtypes = [
        i32p, i32p, i32p, i32p, i64, i64, i64, i64, i64, i64p, i64p,
    ]
    lib.magi_ffa_plan_fill.restype = None
    lib.magi_ffa_plan_fill.argtypes = [
        i32p, i32p, i32p, i32p, i64, i64, i64, i64, i64,
        i64p, i64p, i64p, i64p,
        i32p, i32p, i32p, i32p, i32p, i32p,
    ]
