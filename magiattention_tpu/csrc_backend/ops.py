"""Numpy-facing wrappers over the native hot loops."""

from __future__ import annotations

import ctypes

import numpy as np

from .build import get_lib


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def band_area_native(i0, i1, j0, j1, lo, hi) -> int:
    return int(get_lib().magi_band_area(i0, i1, j0, j1, lo, hi))


def chunk_areas_native(
    slices: np.ndarray, chunk_size: int, num_chunks: int
) -> np.ndarray:
    """slices: (n, 6) int64 (qs, qe, ks, ke, lo, hi) -> (num_chunks,) areas."""
    s = np.ascontiguousarray(slices, dtype=np.int64)
    out = np.zeros(num_chunks, dtype=np.int64)
    get_lib().magi_chunk_areas(
        _i64p(s), len(s), chunk_size, num_chunks, _i64p(out)
    )
    return out


def minheap_solve_native(
    areas: np.ndarray, cp_size: int, per_rank: int
) -> list[list[int]]:
    a = np.ascontiguousarray(areas, dtype=np.int64)
    assign = np.zeros(len(a), dtype=np.int32)
    get_lib().magi_minheap_solve(
        _i64p(a), len(a), cp_size, per_rank, _i32p(assign)
    )
    return [np.nonzero(assign == r)[0].tolist() for r in range(cp_size)]


def binary_greedy_solve(
    qs: np.ndarray, qe: np.ndarray, ks: np.ndarray, ke: np.ndarray,
    area: np.ndarray, q_owner: np.ndarray, k_owner: np.ndarray,
    cp_size: int, slack: float, max_iters: int,
) -> np.ndarray | None:
    """Native BinaryGreedyParallel hot loop (ref dyn_solver_alg.cpp:644)."""
    n = len(area)
    out = np.empty(n, dtype=np.int32)
    rc = get_lib().magi_binary_greedy_solve(
        _i64p(np.ascontiguousarray(qs)), _i64p(np.ascontiguousarray(qe)),
        _i64p(np.ascontiguousarray(ks)), _i64p(np.ascontiguousarray(ke)),
        _i64p(np.ascontiguousarray(area)),
        _i32p(np.ascontiguousarray(q_owner)),
        _i32p(np.ascontiguousarray(k_owner)),
        n, cp_size, float(slack), int(max_iters), _i32p(out),
    )
    return out if rc == 0 else None


def ranges_merge_native(ranges: np.ndarray) -> np.ndarray:
    r = np.ascontiguousarray(ranges, dtype=np.int32).reshape(-1, 2)
    out = np.empty_like(r)
    m = get_lib().magi_ranges_merge(_i32p(r), len(r), _i32p(out))
    return out[:m].copy()


def ranges_holes_native(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Both inputs must be merged."""
    a = np.ascontiguousarray(a, dtype=np.int32).reshape(-1, 2)
    b = np.ascontiguousarray(b, dtype=np.int32).reshape(-1, 2)
    out = np.empty((len(a) + len(b) + 1, 2), dtype=np.int32)
    m = get_lib().magi_ranges_holes(
        _i32p(a), len(a), _i32p(b), len(b), _i32p(out)
    )
    return out[:m].copy()


def ranges_overlap_native(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Both inputs must be merged."""
    a = np.ascontiguousarray(a, dtype=np.int32).reshape(-1, 2)
    b = np.ascontiguousarray(b, dtype=np.int32).reshape(-1, 2)
    out = np.empty((len(a) + len(b) + 1, 2), dtype=np.int32)
    m = get_lib().magi_ranges_overlap(
        _i32p(a), len(a), _i32p(b), len(b), _i32p(out)
    )
    return out[:m].copy()


def ranges_make_local_native(host: np.ndarray, ranges: np.ndarray) -> np.ndarray:
    """host must be merged; raises if a range is not covered."""
    h = np.ascontiguousarray(host, dtype=np.int32).reshape(-1, 2)
    r = np.ascontiguousarray(ranges, dtype=np.int32).reshape(-1, 2)
    out = np.empty(((len(r) + 1) * (len(h) + 1), 2), dtype=np.int32)
    m = get_lib().magi_ranges_make_local(
        _i32p(h), len(h), _i32p(r), len(r), _i32p(out)
    )
    if m < 0:
        from ..common.range import RangeError

        raise RangeError("range not fully covered by host ranges")
    return out[:m].copy()


def ffa_plan_native(
    q_ranges: np.ndarray,
    k_ranges: np.ndarray,
    d_lo: np.ndarray,
    d_hi: np.ndarray,
    num_q_tiles: int,
    num_k_tiles: int,
    block_q: int,
    block_k: int,
    band_inf: int,
):
    """Native FFA work-list builder (csrc magi_ffa_plan_{count,fill}).

    Returns the 6 plan arrays (work_qt, work_kt, meta, work_qt_t,
    work_kt_t, meta_t) with dummy items inserted for empty tiles, matching
    the first 9 meta columns of kernels/ffa_plan.build_ffa_plan exactly.
    The meta arrays are 9 columns wide — the fixed row stride the C fill
    routine writes (csrc/magi_host.cpp magi_ffa_plan_fill); the caller
    (build_ffa_plan) appends the live-extent columns host-side.
    """
    from ..kernels.ffa_plan import DHI, DLO, IS_FIRST, IS_LAST

    native_meta_dim = 9  # must match the `meta + p * 9` stride in C

    lib = get_lib()
    qr = np.ascontiguousarray(q_ranges, dtype=np.int32)
    kr = np.ascontiguousarray(k_ranges, dtype=np.int32)
    lo = np.ascontiguousarray(d_lo, dtype=np.int32)
    hi = np.ascontiguousarray(d_hi, dtype=np.int32)
    n = len(qr)
    q_counts = np.zeros(num_q_tiles, dtype=np.int64)
    k_counts = np.zeros(num_k_tiles, dtype=np.int64)
    rc = lib.magi_ffa_plan_count(
        _i32p(qr), _i32p(kr), _i32p(lo), _i32p(hi), n,
        block_q, block_k, num_q_tiles, num_k_tiles,
        _i64p(q_counts), _i64p(k_counts),
    )
    if rc != 0:
        raise ValueError(
            "slice metadata outside the tile grid (negative range or "
            "beyond seqlen)"
        )

    def alloc(counts, major_is_q: bool):
        # every empty tile still gets one dummy item (finalize writes zeros)
        sizes = np.maximum(counts, 1)
        offsets = np.zeros_like(sizes)
        np.cumsum(sizes[:-1], out=offsets[1:])
        total = int(sizes.sum())
        work_a = np.zeros(total, dtype=np.int32)
        work_b = np.zeros(total, dtype=np.int32)
        meta = np.zeros((total, native_meta_dim), dtype=np.int32)
        empty = counts == 0
        if empty.any():
            pos = offsets[empty]
            tiles = np.nonzero(empty)[0].astype(np.int32)
            if major_is_q:
                work_a[pos] = tiles
            else:
                work_b[pos] = tiles
            meta[pos, DLO] = -band_inf
            meta[pos, DHI] = band_inf
            meta[pos, IS_FIRST] = 1
            meta[pos, IS_LAST] = 1
        return work_a, work_b, meta, offsets

    work_qt, work_kt, meta, q_off = alloc(q_counts, True)
    work_qt_t, work_kt_t, meta_t, k_off = alloc(k_counts, False)
    lib.magi_ffa_plan_fill(
        _i32p(qr), _i32p(kr), _i32p(lo), _i32p(hi), n,
        block_q, block_k, num_q_tiles, num_k_tiles,
        _i64p(q_off), _i64p(q_counts), _i64p(k_off), _i64p(k_counts),
        _i32p(work_qt), _i32p(work_kt), _i32p(meta),
        _i32p(work_qt_t), _i32p(work_kt_t), _i32p(meta_t),
    )
    return work_qt, work_kt, meta, work_qt_t, work_kt_t, meta_t
