"""Numpy-facing wrappers over the native hot loops."""

from __future__ import annotations

import ctypes

import numpy as np

from .build import get_lib


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def band_area_native(i0, i1, j0, j1, lo, hi) -> int:
    return int(get_lib().magi_band_area(i0, i1, j0, j1, lo, hi))


def chunk_areas_native(
    slices: np.ndarray, chunk_size: int, num_chunks: int
) -> np.ndarray:
    """slices: (n, 6) int64 (qs, qe, ks, ke, lo, hi) -> (num_chunks,) areas."""
    s = np.ascontiguousarray(slices, dtype=np.int64)
    out = np.zeros(num_chunks, dtype=np.int64)
    get_lib().magi_chunk_areas(
        _i64p(s), len(s), chunk_size, num_chunks, _i64p(out)
    )
    return out


def minheap_solve_native(
    areas: np.ndarray, cp_size: int, per_rank: int
) -> list[list[int]]:
    a = np.ascontiguousarray(areas, dtype=np.int64)
    assign = np.zeros(len(a), dtype=np.int32)
    get_lib().magi_minheap_solve(
        _i64p(a), len(a), cp_size, per_rank, _i32p(assign)
    )
    return [np.nonzero(assign == r)[0].tolist() for r in range(cp_size)]


def binary_greedy_solve(
    qs: np.ndarray, qe: np.ndarray, ks: np.ndarray, ke: np.ndarray,
    area: np.ndarray, q_owner: np.ndarray, k_owner: np.ndarray,
    cp_size: int, slack: float, max_iters: int,
) -> np.ndarray | None:
    """Native BinaryGreedyParallel hot loop (ref dyn_solver_alg.cpp:644)."""
    n = len(area)
    out = np.empty(n, dtype=np.int32)
    rc = get_lib().magi_binary_greedy_solve(
        _i64p(np.ascontiguousarray(qs)), _i64p(np.ascontiguousarray(qe)),
        _i64p(np.ascontiguousarray(ks)), _i64p(np.ascontiguousarray(ke)),
        _i64p(np.ascontiguousarray(area)),
        _i32p(np.ascontiguousarray(q_owner)),
        _i32p(np.ascontiguousarray(k_owner)),
        n, cp_size, float(slack), int(max_iters), _i32p(out),
    )
    return out if rc == 0 else None


def ranges_merge_native(ranges: np.ndarray) -> np.ndarray:
    r = np.ascontiguousarray(ranges, dtype=np.int32).reshape(-1, 2)
    out = np.empty_like(r)
    m = get_lib().magi_ranges_merge(_i32p(r), len(r), _i32p(out))
    return out[:m].copy()


def ranges_holes_native(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Both inputs must be merged."""
    a = np.ascontiguousarray(a, dtype=np.int32).reshape(-1, 2)
    b = np.ascontiguousarray(b, dtype=np.int32).reshape(-1, 2)
    out = np.empty((len(a) + len(b) + 1, 2), dtype=np.int32)
    m = get_lib().magi_ranges_holes(
        _i32p(a), len(a), _i32p(b), len(b), _i32p(out)
    )
    return out[:m].copy()


def ranges_overlap_native(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Both inputs must be merged."""
    a = np.ascontiguousarray(a, dtype=np.int32).reshape(-1, 2)
    b = np.ascontiguousarray(b, dtype=np.int32).reshape(-1, 2)
    out = np.empty((len(a) + len(b) + 1, 2), dtype=np.int32)
    m = get_lib().magi_ranges_overlap(
        _i32p(a), len(a), _i32p(b), len(b), _i32p(out)
    )
    return out[:m].copy()


def ranges_make_local_native(host: np.ndarray, ranges: np.ndarray) -> np.ndarray:
    """host must be merged; raises if a range is not covered."""
    h = np.ascontiguousarray(host, dtype=np.int32).reshape(-1, 2)
    r = np.ascontiguousarray(ranges, dtype=np.int32).reshape(-1, 2)
    out = np.empty(((len(r) + 1) * (len(h) + 1), 2), dtype=np.int32)
    m = get_lib().magi_ranges_make_local(
        _i32p(h), len(h), _i32p(r), len(r), _i32p(out)
    )
    if m < 0:
        from ..common.range import RangeError

        raise RangeError("range not fully covered by host ranges")
    return out[:m].copy()
