"""Structured per-call configuration (ref: magi_attention/config.py:54-71)."""

from __future__ import annotations

from dataclasses import dataclass, field

from .common.enum import (
    AttnOverlapMode,
    DispatchAlgType,
    DynamicAttnAlgType,
    OverlapAlgType,
)
from .env.comm import split_alignment as _env_split_alignment


@dataclass(frozen=True)
class DispatchConfig:
    """Config for the load-balance dispatch solver (ref dispatch_solver.py:359).

    Attributes:
        alg: chunk->rank assignment algorithm.
        chunk_size: sequence chunk granularity; None = auto-derive.
        top_p: candidate-pool fraction for the TOPP_HEAP algorithms.
        max_backtracks: node budget for BACKTRACKING_PRUNING.
        uneven_shard: allow ranks to own different chunk counts (shards are
            padded to the max on-device; ref DispatchConfig.uneven_shard).
        auto_comm_area_per_row: AUTO-mode cost model — attention-area units
            one remote-KV row costs in wall-clock. Default derived for v5e
            ICI: a K|V row (hk=8, d=dv=128, bf16 = 4 KiB) at ~90 GB/s is
            ~45 ns, one area unit (fwd+bwd ~28 kFLOP at hq=16, d=128) at
            197 TFLOP/s is ~0.15 ns -> ~300. Raise for DCN-dominated
            meshes, lower for small heads.
        auto_tol: AUTO-mode relative cost tolerance within which the
            candidate moving fewer total rows wins the tie.
    """

    alg: DispatchAlgType = DispatchAlgType.MIN_HEAP
    chunk_size: int | None = None
    top_p: float = 0.25
    max_backtracks: int = 10_000
    uneven_shard: bool = False
    auto_comm_area_per_row: float = 300.0
    auto_tol: float = 0.05


@dataclass(frozen=True)
class OverlapConfig:
    """Config for multi-stage compute/comm overlap.

    Attributes:
        enable: False collapses to the single-stage (no-overlap) path.
        mode: static (fixed degree) or dynamic (solver-chosen).
        degree: number of remote stages when static; None = solver decides.
        min_chunk_size / max_num_chunks: remote-workload chunking bounds.
        alg: stage-grouping algorithm.
    """

    enable: bool = True
    mode: AttnOverlapMode = AttnOverlapMode.STATIC
    degree: int | None = 1
    min_chunk_size: int = 512
    max_num_chunks: int = 64
    alg: OverlapAlgType = OverlapAlgType.UNIFORM


@dataclass(frozen=True)
class GrpCollConfig:
    """Config for the group-collective lowering.

    Attributes:
        split_alignment: pad per-destination split sizes to this multiple so
            `jax.lax.all_to_all` sees equal static splits (TPU lane = 128).
            Defaults from ``MAGI_ATTENTION_SPLIT_ALIGNMENT``
            (env.comm.split_alignment); an explicit value here wins.
    """

    split_alignment: int = field(default_factory=_env_split_alignment)


@dataclass(frozen=True)
class DynamicAttnConfig:
    """Config for the dynamic (qo-comm) solver.

    Active when ``MAGI_ATTENTION_QO_COMM=1`` (env.comm.is_qo_comm_enable);
    the reference forces overlap degree 1 under qo-comm (ref config.py:67-71)
    and so do we — the dynamic plan is single-stage by construction.
    """

    alg: DynamicAttnAlgType = DynamicAttnAlgType.BINARY_GREEDY


@dataclass(frozen=True)
class DistAttnConfig:
    """Top-level distributed-attention config (passed per key-init)."""

    dispatch_config: DispatchConfig = field(default_factory=DispatchConfig)
    overlap_config: OverlapConfig = field(default_factory=OverlapConfig)
    grpcoll_config: GrpCollConfig = field(default_factory=GrpCollConfig)
    dynamic_config: DynamicAttnConfig = field(
        default_factory=DynamicAttnConfig
    )
