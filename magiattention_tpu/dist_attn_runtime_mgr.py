"""Runtime key + manager (ref: magi_attention/dist_attn_runtime_mgr.py:62,164).

``DistAttnRuntimeKey`` is a frozen hashable key over (mask metadata, mesh
signature, chunking, config, env-flag snapshot); ``DistAttnRuntimeMgr`` owns
the planning pipeline output (dispatch meta -> attn meta -> DistAttnRuntime)
and the dispatch/undispatch/calc_attn methods. Managers are memoized in an
LRU keyed by the runtime key — this is what caches traced/compiled plans
across steps.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from typing import Any

import jax
from jax.sharding import Mesh

from . import telemetry
from .common.enum import AttnMaskType
from .common.ranges import AttnRanges
from .config import DistAttnConfig
from .env import general as env_general
from .env import resilience as env_resilience
from .functional.dispatch import dispatch_func, undispatch_func
from .functional.dist_attn import DistAttnRuntime
from .meta import (
    make_attn_meta_from_dispatch_meta,
    make_dispatch_meta_from_qk_ranges,
)
from .meta import plan_broadcast, plan_io, plan_store


def _plan_build_retries() -> int:
    from .resilience.fallback import PLAN_BUILD_RETRIES

    return PLAN_BUILD_RETRIES


def _mesh_signature(mesh: Mesh) -> tuple:
    """Canonical mesh identity: per-axis (name, size) pairs + device ids.

    Pairing name with size (instead of separate name/shape tuples) keeps a
    flat cp=8 mesh and a 2x4 two-level (dcn, ici) mesh from ever colliding,
    and makes the axis-extent lookup for two-level planning unambiguous."""
    return (
        tuple(zip(mesh.axis_names, mesh.devices.shape)),
        tuple(d.id for d in mesh.devices.flat),
    )


def _mesh_shape_for(key: "DistAttnRuntimeKey", mesh: Mesh) -> tuple[int, int] | None:
    """(n_outer, n_inner) for two-level planning, or None on flat meshes.

    Two-level plans are built exactly when the runtime will execute them:
    tuple cp_axis + MAGI_ATTENTION_HIERARCHICAL_COMM=1 (both are part of
    the cache keys, so flat and two-level plans never mix)."""
    from .env import comm as env_comm

    if (
        isinstance(key.cp_axis, tuple)
        and env_comm.is_hierarchical_comm_enable()
    ):
        dcn_axis, ici_axis = key.cp_axis
        return (int(mesh.shape[dcn_axis]), int(mesh.shape[ici_axis]))
    return None


@dataclass(frozen=True)
class DistAttnRuntimeKey:
    """Hashable identity of one planned runtime (ref :62-121)."""

    q_ranges: tuple[tuple[int, int], ...]
    k_ranges: tuple[tuple[int, int], ...]
    attn_mask_type: tuple[int, ...]
    total_seqlen_q: int
    total_seqlen_k: int
    chunk_size: int
    cp_size: int
    cp_axis: str | tuple[str, str]
    head_axis: str | None
    mesh_sig: tuple
    config: DistAttnConfig
    env_snapshot: tuple
    # pinned chunk->rank assignment: set when re-keying a new mask after
    # dispatch (ref api :1172 make_*_key_for_new_mask_after_dispatch) so the
    # new mask reuses the old dispatch solution
    fixed_partitions: tuple[tuple[int, ...], ...] | None = None
    # per-rank capacity vector from straggler detection (telemetry/health):
    # None = uniform. A changed vector is a changed key, so the runtime
    # re-solves exactly when the vector changes and the plan control plane
    # caches/persists/broadcasts weighted plans like any other.
    capacities: tuple[float, ...] | None = None


def _plan_signature(key: DistAttnRuntimeKey) -> tuple:
    """Everything the host-side solved plan depends on.

    The runtime key minus the parts that only affect traced execution:
    device ids (mesh_sig[1] — the same plan is valid on any device
    assignment of the same axis layout) and head_axis (TP sharding of the
    already-solved plan). The capacity vector is appended ONLY when
    non-uniform: uniform signatures stay byte-identical to builds without
    capacity support, so warm plan stores are never invalidated."""
    sig = (
        key.q_ranges,
        key.k_ranges,
        key.attn_mask_type,
        key.total_seqlen_q,
        key.total_seqlen_k,
        key.chunk_size,
        key.cp_size,
        key.cp_axis,
        key.mesh_sig[0],
        key.config,
        key.env_snapshot,
        key.fixed_partitions,
    )
    if key.capacities is not None:
        sig = sig + (("capacities", key.capacities),)
    return sig


def _mask_family(sig: tuple) -> tuple:
    """Signature minus the mask itself (q/k ranges + types): dynamic-mask
    steps of the same workload share a family, so a new signature can seed
    its incremental re-solve from the family's previous solve state."""
    return sig[3:]


class _PlanCache:
    """Mask-signature-keyed solved-plan LRU, one level below the runtime
    LRU (DistAttnRuntimeDict).

    The runtime LRU caches traced managers per full runtime key; this cache
    holds only the host-solved artifacts (dispatch metas + static attn
    metas / dynamic plan), so a repeated mask signature skips every solver
    pass even when the traced runtime was evicted or is keyed differently
    (e.g. same plan on a different device assignment). It also remembers
    each mask family's latest dynamic solve state to seed incremental
    re-solves on a miss. Reuse is exact — a hit returns the identical plan
    objects a cold solve produced — and every reusing manager still runs
    the R1-R5 verifier on its plan (MAGI_ATTENTION_VERIFY_PLANS=1)."""

    def __init__(self) -> None:
        self._d: OrderedDict[tuple, dict] = OrderedDict()
        self._prev_dyn: dict[tuple, Any] = {}
        self._hits = 0
        self._misses = 0

    def lookup(self, sig: tuple) -> dict | None:
        if sig in self._d:
            self._d.move_to_end(sig)
            self._hits += 1
            telemetry.inc("plan_solve.cache_hit")
            return self._d[sig]
        self._misses += 1
        telemetry.inc("plan_solve.cache_miss")
        return None

    def store(self, sig: tuple, entry: dict) -> None:
        self._d[sig] = entry
        self._d.move_to_end(sig)
        while len(self._d) > env_general.plan_cache_size():
            self._d.popitem(last=False)

    def prev_dyn_state(self, family: tuple):
        return self._prev_dyn.get(family)

    def set_dyn_state(self, family: tuple, state) -> None:
        if state is not None:
            self._prev_dyn[family] = state

    def get_stats(self) -> dict[str, int]:
        return {
            "hits": self._hits,
            "misses": self._misses,
            "size": len(self._d),
        }

    def clear(self) -> None:
        self._d.clear()
        self._prev_dyn.clear()
        self._hits = 0
        self._misses = 0


# module-level: plans outlive any one runtime dict (api/magi_attn_interface
# builds one DistAttnRuntimeDict; tests may build their own)
_PLAN_CACHE = _PlanCache()


# ---------------------------------------------------------------------------
# plan control plane: memory LRU -> disk store -> broadcast -> cold solve
# (docs/plan_control_plane.md). Every tier below memory is byte-serialized
# (meta/plan_io.py), so every loaded entry is integrity-checked at decode
# and re-verified by R1-R5/check_hier_plan before first use. Every failure
# on the way down the ladder is a recorded miss, never a crash — the cold
# solver is always reachable.
# ---------------------------------------------------------------------------


def _chaos_miss(site: str, err: Exception) -> None:
    """Recover-or-typed-raise for an InjectedFault at a control-plane site:
    with MAGI_ATTENTION_FALLBACK=1 the fault becomes a recorded miss, else
    it propagates typed (the standard chaos contract)."""
    if not env_resilience.is_fallback_enable():
        raise err
    from .resilience.fallback import record_resilience_event

    record_resilience_event(
        "fallback", getattr(err, "site", site),
        action_detail="cold_solve", error=type(err).__name__,
    )


def _verify_loaded_entry(entry: dict, key: DistAttnRuntimeKey) -> bool:
    """R1-R5 (+ check_hier_plan for two-level stages) over a disk/wire
    loaded entry — unconditional, unlike MAGI_ATTENTION_VERIFY_PLANS: a
    deserialized plan is only trusted after it verifies exactly like a
    cold-solved one. Any verifier error (or malformed entry) rejects the
    entry back to a miss."""
    from .analysis.verifier import verify_dynamic_plan, verify_plan

    align = key.config.grpcoll_config.split_alignment
    try:
        meta_q, meta_kv, bucket = entry["dispatch"]
        dynamic = entry.get("dynamic")
        if dynamic is not None and not verify_dynamic_plan(
            dynamic, split_alignment=align
        ).ok():
            return False
        static = entry.get("static")
        comm_meta, calc_meta = static if static is not None else (None, None)
        report = verify_plan(
            dispatch_meta=meta_q,
            bucket=bucket,
            comm_meta=comm_meta,
            calc_meta=calc_meta,
            dispatch_meta_kv=(meta_kv if meta_kv is not meta_q else None),
            split_alignment=align,
        )
        return report.ok()
    except Exception:
        return False


def _reject_loaded_entry(site: str, reason: str) -> None:
    from .resilience.fallback import record_resilience_event

    record_resilience_event("reject", site, reason=reason)


def _control_plane_lookup(
    sig: tuple, key: DistAttnRuntimeKey, entry: dict | None, source: str
) -> tuple[dict | None, str, dict, bool]:
    """Run the disk + broadcast tiers for one plan resolution.

    ``entry``/``source`` are the memory tier's result; returns the
    (possibly upgraded) ``(entry, source, telemetry_extra, exchanged)``,
    where ``exchanged`` records that this resolution's one collective
    broadcast exchange already happened (so ``_persist_entry`` must not
    publish again — hosts pair ``broadcast_one_to_all`` calls one-to-one,
    and a second leader-side exchange would desync every later pairing).
    Loaded entries are verified here; a broadcast-received entry is
    written through to the disk store so later processes warm-start
    locally."""
    env_sig = key.env_snapshot
    digest: str | None = None
    extra: dict = {}

    store = plan_store.get_store()
    if entry is None and store is not None:
        digest = plan_io.plan_signature_digest(sig)
        try:
            candidate, miss = store.read(digest, env_sig=env_sig)
        except Exception as e:
            from .resilience.errors import InjectedFault

            if not isinstance(e, InjectedFault):
                raise
            _chaos_miss("plan_cache_read", e)
            candidate, miss = None, None
        if candidate is not None:
            if _verify_loaded_entry(candidate, key):
                entry, source = candidate, "disk"
            else:
                _reject_loaded_entry("plan_cache_read", plan_store.MISS_VERIFY)

    transport = plan_broadcast.get_transport()
    if transport is None:
        return entry, source, extra, False
    leader = plan_broadcast.is_leader()
    multihost = isinstance(transport, plan_broadcast.MultihostTransport)
    if digest is None:
        digest = plan_io.plan_signature_digest(sig)
    if leader:
        exchanged = False
        if multihost and entry is not None:
            # the multihost transport is collective — the leader must
            # exchange on EVERY resolution (hits included) so follower
            # receive counts align. This publish IS the resolution's one
            # exchange: the returned flag makes any later _persist_entry
            # (e.g. a dynamic re-solve over a static-fallback hit) skip
            # its publish instead of exchanging a second time.
            exchanged = _persist_entry(sig, key, entry, store=None)
        elif (
            entry is not None
            and isinstance(transport, plan_broadcast.FileTransport)
            and not transport.published_ok(digest, env_sig)
        ):
            # warm leader, file transport: the published blob is missing
            # or corrupt (e.g. a crash raced the publish) — heal it so
            # followers stop burning the full retry path on this digest
            _persist_entry(sig, key, entry, store=None)
        return entry, source, extra, exchanged
    if entry is not None and not multihost:
        return entry, source, extra, False
    try:
        result = plan_broadcast.exchange_plan(digest, None)
    except Exception as e:
        from .resilience.errors import InjectedFault

        if not isinstance(e, InjectedFault):
            raise
        _chaos_miss("plan_broadcast", e)
        return entry, source, extra, False
    if result.attempts > 1:
        extra["attempts"] = result.attempts
        extra["backoff_ms"] = result.backoff_ms
    if entry is not None or result.blob is None:
        if result.blob is None:
            from .resilience.fallback import record_resilience_event

            record_resilience_event(
                "exhausted", "plan_broadcast",
                action_detail="cold_solve", attempts=result.attempts,
            )
        return entry, source, extra, False
    try:
        candidate = plan_io.decode_plan(
            result.blob, env_sig=env_sig, expect_digest=digest
        )
    except plan_io.PlanDecodeError as e:
        _reject_loaded_entry("plan_broadcast", type(e).__name__)
        return entry, source, extra, False
    if not _verify_loaded_entry(candidate, key):
        _reject_loaded_entry("plan_broadcast", plan_store.MISS_VERIFY)
        return entry, source, extra, False
    if store is not None:  # write-through: future processes warm-start
        store.write(digest, result.blob)
    return candidate, "broadcast", extra, False


def _persist_failure(
    site: str, err: Exception, collective_transport, digest: str
) -> None:
    """Failure tail of ``_persist_entry``: an InjectedFault follows the
    chaos contract (recover under fallback, typed raise without), any
    genuine error is a recorded degradation — persisting is write-through
    and must never cost the step. Either way, when a collective transport
    is mid-resolution (followers already blocked in their receive), the
    exchange is completed with a zero-length blob so their collective call
    pairs off and they degrade to a local cold solve instead of hanging."""
    from .resilience.errors import InjectedFault

    try:
        if isinstance(err, InjectedFault):
            _chaos_miss(site, err)
        else:
            from .resilience.fallback import record_resilience_event

            record_resilience_event(
                "fallback", site, action_detail="skip_persist",
                error=type(err).__name__,
            )
    finally:
        if collective_transport is not None:
            try:
                collective_transport.exchange(digest, b"")
            except Exception:
                pass


def _persist_entry(
    sig: tuple,
    key: DistAttnRuntimeKey,
    entry: dict,
    store: plan_store.PlanStore | None = ...,
    exchanged: bool = False,
) -> bool:
    """Write-through after a solve: serialize once, land in the disk
    store, and (as broadcast leader) publish to the other hosts — unless
    ``exchanged`` says this resolution's one collective exchange already
    happened. Never costs the step — every failure is a recorded
    degradation except the chaos contract's typed raise, and on a
    collective transport even the failure paths complete the exchange
    (zero-length blob) so followers never hang. Returns True when this
    call performed (or completed) the resolution's broadcast exchange."""
    if store is ...:
        store = plan_store.get_store()
    transport = plan_broadcast.get_transport()
    multihost = isinstance(transport, plan_broadcast.MultihostTransport)
    publish = (
        transport is not None
        and plan_broadcast.is_leader()
        and not exchanged
    )
    if store is None and not publish:
        return False
    digest = plan_io.plan_signature_digest(sig)
    wire_entry = {
        k: v for k, v in entry.items() if k in ("dispatch", "static", "dynamic")
    }
    try:
        blob = plan_io.encode_plan(
            wire_entry, env_sig=key.env_snapshot, sig_digest=digest
        )
    except Exception as e:
        _persist_failure(
            "plan_serialize", e,
            transport if (publish and multihost) else None, digest,
        )
        return publish and multihost
    if store is not None:
        store.write(digest, blob)
    if not publish:
        return False
    try:
        plan_broadcast.exchange_plan(digest, blob)
    except Exception as e:
        _persist_failure(
            "plan_broadcast", e, transport if multihost else None, digest
        )
    return True


class DistAttnRuntimeMgr:
    """Owns metas + runtime for one key (ref :164-483)."""

    def __init__(self, key: DistAttnRuntimeKey, mesh: Mesh) -> None:
        self.key = key
        self.mesh = mesh
        q_ranges = AttnRanges.from_ranges(key.q_ranges)
        k_ranges = AttnRanges.from_ranges(key.k_ranges)
        mask_types = [AttnMaskType.from_int_type(t) for t in key.attn_mask_type]

        cache_on = env_general.is_plan_cache_enable()
        sig = _plan_signature(key) if cache_on else None
        entry = _PLAN_CACHE.lookup(sig) if cache_on else None
        # where this manager's solved plan came from:
        # memory | disk | broadcast | cold (stamped on plan_solve telemetry)
        self.plan_source = "memory" if entry is not None else "cold"
        self._plan_meta: dict = {}
        # True once this resolution's single collective broadcast exchange
        # happened (leader publish-on-hit): later persists must not
        # exchange again or hosts pair collectives off-by-one
        bcast_exchanged = False
        if cache_on:
            fetched, src, extra, bcast_exchanged = _control_plane_lookup(
                sig, key, entry, self.plan_source
            )
            if entry is None and fetched is not None:
                entry = fetched
                self.plan_source = src
                self._plan_meta = extra
                # promote into the memory tier: the next resolution of this
                # signature is a plain LRU hit
                _PLAN_CACHE.store(sig, entry)

        if entry is not None:
            # solved-plan cache hit: the whole solver pipeline (dispatch +
            # attn plan) is skipped; verification below still runs
            self.dispatch_meta_q, self.dispatch_meta_kv, self.bucket = (
                entry["dispatch"]
            )
        else:
            self.dispatch_meta_q, self.dispatch_meta_kv, self.bucket = (
                make_dispatch_meta_from_qk_ranges(
                    q_ranges,
                    k_ranges,
                    mask_types,
                    key.total_seqlen_q,
                    key.total_seqlen_k,
                    key.chunk_size,
                    key.cp_size,
                    key.config.dispatch_config,
                    preset_partitions=(
                        [list(p) for p in key.fixed_partitions]
                        if key.fixed_partitions is not None
                        else None
                    ),
                    capacities=(
                        list(key.capacities)
                        if key.capacities is not None
                        else None
                    ),
                )
            )
        from .env import comm as env_comm

        if env_comm.is_qo_comm_enable():
            # dynamic (qo-comm) planner: q/o rows may move, overlap degree 1
            # (ref config.py:67-71)
            from .functional.dynamic_dist_attn import DynamicDistAttnRuntime
            from .meta._make_attn_meta import make_dynamic_attn_plan

            # the dynamic runtime supports neither TP head sharding nor
            # hierarchical comm yet — fail loudly instead of silently
            # dropping the requested config
            if key.head_axis is not None:
                raise NotImplementedError(
                    "MAGI_ATTENTION_QO_COMM=1 does not support head_axis "
                    "(TP head sharding) yet; unset one of the two"
                )
            if env_comm.is_hierarchical_comm_enable():
                raise NotImplementedError(
                    "MAGI_ATTENTION_QO_COMM=1 does not support "
                    "MAGI_ATTENTION_HIERARCHICAL_COMM=1 yet; unset one"
                )

            cached_plan = entry.get("dynamic") if entry is not None else None
            if cached_plan is not None:
                self.dynamic_plan = cached_plan
                if telemetry.enabled():
                    telemetry.record_event(
                        "plan_solve", planner="dynamic", event="cache_hit",
                        source=self.plan_source, incremental=False,
                        wall_ms=0.0, rows_resolved=0, **self._plan_meta,
                    )
                built_dynamic = True
            else:
                built_dynamic = False
                try:
                    self.dynamic_plan = make_dynamic_attn_plan(
                        q_ranges, k_ranges, mask_types,
                        self.dispatch_meta_q, key.config,
                        dispatch_meta_kv=self.dispatch_meta_kv,
                        prev_state=(
                            _PLAN_CACHE.prev_dyn_state(_mask_family(sig))
                            if cache_on
                            else None
                        ),
                    )
                except Exception as e:
                    # degradation chain 2 (docs/resilience.md): a failed
                    # dynamic solve falls back to the static solver plan —
                    # same mask, kv-comm execution instead of qo-comm
                    if not env_resilience.is_fallback_enable():
                        raise
                    from .resilience.fallback import record_resilience_event

                    record_resilience_event(
                        "fallback", "dynamic_plan_solve",
                        action_detail="static_plan", error=type(e).__name__,
                    )
                else:
                    built_dynamic = True
                    if cache_on:
                        new_entry = {
                            "dispatch": (
                                self.dispatch_meta_q,
                                self.dispatch_meta_kv,
                                self.bucket,
                            ),
                            "dynamic": self.dynamic_plan,
                        }
                        _PLAN_CACHE.store(sig, new_entry)
                        _PLAN_CACHE.set_dyn_state(
                            _mask_family(sig),
                            self.dynamic_plan.solver_state,
                        )
                        _persist_entry(
                            sig, key, new_entry, exchanged=bcast_exchanged
                        )
            if built_dynamic:
                self.comm_meta = self.calc_meta = None
                self.runtime = DynamicDistAttnRuntime(
                    plan=self.dynamic_plan, mesh=mesh, cp_axis=key.cp_axis
                )
                if telemetry.enabled():
                    p = self.dynamic_plan
                    telemetry.record_event(
                        "plan_build",
                        planner="dynamic",
                        cp_size=key.cp_size,
                        overlap_degree=1,
                        stages=[
                            {"name": name, **cast.telemetry_dict()}
                            for name, cast in (
                                ("q_cast", p.q_cast),
                                ("kv_cast", p.kv_cast),
                                ("ret", p.ret),
                            )
                        ],
                    )
                self._maybe_verify()
                return

        self.dynamic_plan = None
        cached_metas = entry.get("static") if entry is not None else None
        if cached_metas is not None:
            self.comm_meta, self.calc_meta = cached_metas
            if telemetry.enabled():
                telemetry.record_event(
                    "plan_solve", planner="static", event="cache_hit",
                    source=self.plan_source, incremental=False,
                    wall_ms=0.0, rows_resolved=0, **self._plan_meta,
                )
        else:
            self.comm_meta, self.calc_meta = make_attn_meta_from_dispatch_meta(
                self.bucket, self.dispatch_meta_q, key.config,
                dispatch_meta_kv=self.dispatch_meta_kv,
                mesh_shape=_mesh_shape_for(key, mesh),
            )
            if cache_on:
                new_entry = dict(entry) if entry is not None else {}
                new_entry["dispatch"] = (
                    self.dispatch_meta_q, self.dispatch_meta_kv, self.bucket
                )
                new_entry["static"] = (self.comm_meta, self.calc_meta)
                _PLAN_CACHE.store(sig, new_entry)
                _persist_entry(sig, key, new_entry, exchanged=bcast_exchanged)
        overlap_cfg = key.config.overlap_config
        self.runtime = DistAttnRuntime(
            comm_meta=self.comm_meta,
            calc_meta=self.calc_meta,
            mesh=mesh,
            cp_axis=key.cp_axis,
            head_axis=key.head_axis,
            # auto (overlap iff the solver produced >1 stage) when enabled,
            # forced single merged kernel when disabled
            use_overlap=None if overlap_cfg.enable else False,
        )
        self._record_comm_plan()
        self._maybe_verify()

    def _maybe_verify(self) -> None:
        """Opt-in static verification of the freshly built plan
        (MAGI_ATTENTION_VERIFY_PLANS=1, analysis/verifier.py): raises
        PlanVerificationError on error-severity violations so a malformed
        plan fails at build time instead of as a wrong loss inside
        shard_map."""
        from .analysis import maybe_verify_runtime

        maybe_verify_runtime(self)

    def _stage_telemetry_dicts(self) -> list[dict]:
        """Per-stage comm summaries with the EXECUTED lowering: the runtime
        may override the solver's portable choice with the backend-dependent
        ragged/hier tier — report what actually runs."""
        kinds = getattr(self.runtime, "_cast_kinds", None)
        names = {"pp": "ppermute", "a2a": "a2a", "ragged": "ragged",
                 "hier": "hier"}
        out = []
        for st, s in enumerate(self.comm_meta.kv_stages):
            executed = (
                names.get(kinds[st][0], kinds[st][0])
                if kinds and st < len(kinds)
                else s.lowering
            )
            out.append(
                {
                    "stage": st,
                    "xprof_scope": f"group_cast_stage{st}",
                    **s.telemetry_dict(executed=executed),
                }
            )
        return out

    def _record_comm_plan(self) -> None:
        """The init-time comm-plan dump (ref dist_attn_runtime_mgr.py:
        673-1033 meta dumps + comm_meta.py:86-155 send/recv token counts):
        per-stage payload rows, wire rows, padding ratio, chosen lowering —
        emitted to the telemetry registry when MAGI_ATTENTION_TELEMETRY=1
        and to the INFO log when enabled (one source of numbers for both)."""
        import logging

        logger = logging.getLogger("magiattention_tpu.runtime")
        log_on = logger.isEnabledFor(logging.INFO)
        if not (log_on or telemetry.enabled()):
            return
        stages = self._stage_telemetry_dicts()
        if telemetry.enabled():
            telemetry.record_event(
                "plan_build",
                planner="static",
                cp_size=self.key.cp_size,
                overlap_degree=self.comm_meta.overlap_degree,
                stages=stages,
            )
        if log_on:
            for d in stages:
                logger.info(
                    "comm plan stage %d/%d: executed=%s planned=%s "
                    "payload_rows=%d wire_rows=%d ratio=%.3f (a2a would be "
                    "%d) a_cap=%d r_max=%d per-rank send rows=%s recv "
                    "rows=%s",
                    d["stage"], len(stages), d["lowering_executed"],
                    d["lowering_planned"], d["payload_rows"], d["wire_rows"],
                    d["wire_ratio"], d["a2a_wire_rows"], d["a_cap"],
                    d["r_max"], d["send_rows_per_rank"],
                    d["recv_rows_per_rank"],
                )

    # -- ops ---------------------------------------------------------------

    def dispatch_qo(self, x: jax.Array) -> jax.Array:
        return dispatch_func(
            x, self.dispatch_meta_q.position_ids, self.mesh, self.key.cp_axis
        )

    def dispatch_kv(self, x: jax.Array) -> jax.Array:
        return dispatch_func(
            x, self.dispatch_meta_kv.position_ids, self.mesh, self.key.cp_axis
        )

    def undispatch_qo(self, x: jax.Array) -> jax.Array:
        return undispatch_func(
            x, self.dispatch_meta_q.unpermute_index, self.mesh, self.key.cp_axis
        )

    def undispatch_kv(self, x: jax.Array) -> jax.Array:
        return undispatch_func(
            x, self.dispatch_meta_kv.unpermute_index, self.mesh, self.key.cp_axis
        )

    def calc_attn(
        self,
        q: jax.Array,
        k: jax.Array,
        v: jax.Array,
        return_max_logits: bool = False,
    ):
        if env_general.precision() == "bf16":
            # precision override (ref dist_attn.py:3760-3786) — applied at
            # the manager chokepoint so every entry path honors it
            import jax.numpy as jnp

            q, k, v = (t.astype(jnp.bfloat16) for t in (q, k, v))
        return self.runtime.calc_attn(
            q, k, v, return_max_logits=return_max_logits
        )

    def roll(self, x: jax.Array, shifts: int) -> jax.Array:
        from .functional.roll import roll_func

        return roll_func(
            x, self.dispatch_meta_q, shifts, self.mesh, self.key.cp_axis
        )

    def get_position_ids(self) -> jax.Array:
        import jax.numpy as jnp

        return jnp.asarray(self.dispatch_meta_q.position_ids.reshape(-1))

    def get_xattn_args(
        self,
        ref_xattn_q_ranges: AttnRanges,
        ref_xattn_k_ranges: AttnRanges,
        attn_mask_type=None,
    ) -> Any:
        """Cross-attention args for the dispatched q layout (ref :269-357).

        The dispatched q tensor is chunk-permuted; to cross-attend it
        against a NEW (replicated, undistributed) kv tensor, each global
        (q_range, k_range) pair must be re-expressed in local dispatched q
        coordinates. Only FULL masks are supported (ref asserts the same).

        Returns:
            The rank-stacked list of per-rank :class:`AttnArg` — this API
            is SPMD; the caller selects its shard inside shard_map.
        """
        from .common.enum import AttnMaskType as _MT
        from .kernels.mask_utils import BAND_INF
        from .meta.collection.calc_meta import AttnArg

        if len(ref_xattn_q_ranges) != len(ref_xattn_k_ranges):
            raise ValueError(
                f"q/k range count mismatch: {len(ref_xattn_q_ranges)} vs "
                f"{len(ref_xattn_k_ranges)}"
            )
        if attn_mask_type is not None:
            types = (
                attn_mask_type
                if isinstance(attn_mask_type, list)
                else [attn_mask_type] * len(ref_xattn_q_ranges)
            )
            assert all(
                _MT.normalize(t) == _MT.FULL for t in types
            ), "only FULL cross-attn masks supported (ref :293)"

        meta = self.dispatch_meta_q
        shard = meta.shard_seqlen
        sk = ref_xattn_k_ranges.end
        args = []
        for rank in range(meta.cp_size):
            own = meta.host_ranges_per_rank[rank]
            slices = []
            for qr, kr in zip(ref_xattn_q_ranges, ref_xattn_k_ranges):
                for piece in AttnRanges([qr]).find_overlap_ranges(own):
                    q_loc = own.make_range_local(piece)
                    slices.append(
                        (q_loc.start, q_loc.end, kr.start, kr.end,
                         -BAND_INF, BAND_INF)
                    )
            args.append(AttnArg.from_slices(slices, shard, sk))
        return args


class DistAttnRuntimeDict:
    """LRU cache of managers (ref :412; api/magi_attn_interface.py:64)."""

    def __init__(self, maxsize: int | None = None) -> None:
        self.maxsize = maxsize or env_general.runtime_dict_size()
        self._d: OrderedDict[DistAttnRuntimeKey, DistAttnRuntimeMgr] = OrderedDict()
        # plain int counters: always maintained (no timers / file I/O, so
        # the telemetry-off contract holds); exported via get_stats() and,
        # when MAGI_ATTENTION_TELEMETRY=1, mirrored into the registry
        self._hits = 0
        self._misses = 0
        self._evictions = 0

    def get_or_create(
        self, key: DistAttnRuntimeKey, mesh: Mesh
    ) -> DistAttnRuntimeMgr:
        if key in self._d:
            self._d.move_to_end(key)
            self._hits += 1
            telemetry.inc("runtime_cache.hit")
            return self._d[key]
        self._misses += 1
        telemetry.inc("runtime_cache.miss")
        with telemetry.stage_timer("runtime_mgr_init"):
            try:
                mgr = self._build_mgr(key, mesh)
            except Exception:
                # invariant: a build that raised must never leave an
                # entry behind — the next get_or_create must rebuild
                self._d.pop(key, None)
                raise
        self._d[key] = mgr
        while len(self._d) > self.maxsize:
            self._d.popitem(last=False)
            self._evictions += 1
            telemetry.inc("runtime_cache.evict")
        if telemetry.enabled():
            telemetry.record_event("runtime_cache", **self.get_stats())
        return mgr

    def _build_mgr(self, key: DistAttnRuntimeKey, mesh: Mesh):
        """One manager build, with the resilience layer's bounded retry
        (MAGI_ATTENTION_FALLBACK=1: one extra attempt — enough to absorb
        a transient plan-build failure, never an infinite loop). The
        manager class is resolved by NAME at call time so tests can
        monkeypatch the module global."""
        retries = (
            0 if not env_resilience.is_fallback_enable()
            else _plan_build_retries()
        )
        for attempt in range(retries + 1):
            try:
                mgr = DistAttnRuntimeMgr(key, mesh)
            except Exception as e:
                if attempt >= retries:
                    raise
                from .resilience.fallback import record_resilience_event

                record_resilience_event(
                    "retry", "plan_build", attempt=attempt + 1,
                    error=type(e).__name__,
                )
                continue
            if attempt:
                from .resilience.fallback import record_resilience_event

                record_resilience_event(
                    "recovered", "plan_build", attempt=attempt,
                )
            return mgr

    def get(self, key: DistAttnRuntimeKey) -> DistAttnRuntimeMgr | None:
        return self._d.get(key)

    def get_stats(self) -> dict[str, int]:
        """Cache behavior counters (the cache is keyed on mask + mesh +
        config + ENV_KEYS_AFFECTING_RUNTIME snapshot, so a surprise miss
        rate usually means env flags are churning between steps)."""
        return {
            "hits": self._hits,
            "misses": self._misses,
            "evictions": self._evictions,
            "size": len(self._d),
            "maxsize": self.maxsize,
        }

    def clear(self) -> None:
        self._d.clear()

    def __len__(self) -> int:
        return len(self._d)
