"""Perf harness (ref: magi_attention/benchmarking/bench.py:47-1378).

Triton-style ``do_bench`` / ``perf_report`` re-designed for JAX/TPU: no CUDA
graphs or events — functions are jitted once, inputs rotate through a pool so
neither XLA nor the execution tunnel can memoize results, and timing brackets
``block_until_ready`` with host perf counters (the dispatch overhead is
amortized over ``rep`` launches).
"""

from __future__ import annotations

import sys
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

import jax
import numpy as np


def _echo(msg: str) -> None:
    """Benchmark-table output channel. The harness's tables and timing
    lines ARE its product (chip-window logs consume them), so they must
    not be gated behind MAGI_ATTENTION_LOG_LEVEL like library logging."""
    sys.stdout.write(msg + "\n")
    sys.stdout.flush()


def do_bench(
    fn: Callable[[], Any],
    warmup: int = 3,
    rep: int = 20,
    quantiles: Sequence[float] = (0.5, 0.2, 0.8),
) -> list[float]:
    """Time fn() in milliseconds; returns the requested quantiles."""
    for _ in range(warmup):
        out = fn()
    jax.block_until_ready(out)
    times = []
    for _ in range(rep):
        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out)
        times.append((time.perf_counter() - t0) * 1e3)
    return [float(np.quantile(times, q)) for q in quantiles]


def do_bench_flops(
    fn: Callable[[], Any], flops: float, **kwargs
) -> tuple[float, float]:
    """(median ms, TFLOP/s)."""
    ms = do_bench(fn, **kwargs)[0]
    return ms, flops / (ms * 1e-3) / 1e12


def do_bench_mem(
    fn: Callable[[], Any], bytes_moved: float, **kwargs
) -> tuple[float, float]:
    """(median ms, GB/s)."""
    ms = do_bench(fn, **kwargs)[0]
    return ms, bytes_moved / (ms * 1e-3) / 1e9


def _make_scan_runner(
    body: Callable[[Any], Any], carry0: Any, length: int
) -> Callable[[], float]:
    """Compile + warm a ``length``-step chained scan of ``body``; returns a
    closure that executes it once and returns total wall SECONDS. The one
    place the tunnel-proof timing mechanics live: carried data dependence
    defeats memoization, and the trailing value fetch defeats
    block_until_ready returning before remote execution completes."""
    import jax.numpy as jnp

    @jax.jit
    def run(c):
        def f(c, _):
            return body(c), None

        c, _ = jax.lax.scan(f, c, None, length=length)
        return c

    out = run(carry0)  # compile + warm
    jax.block_until_ready(out)

    def time_once() -> float:
        t0 = time.perf_counter()
        o = run(carry0)
        jax.block_until_ready(o)
        # force a real value fetch (block_until_ready alone can return
        # before remote execution on tunneled backends)
        jnp.asarray(jax.tree_util.tree_leaves(o)[0]).ravel()[0].item()
        return time.perf_counter() - t0

    return time_once


def do_bench_scan(
    body: Callable[[Any], Any],
    carry0: Any,
    length: int = 8,
    reps: int = 3,
) -> float:
    """Per-iteration ms of ``body`` chained ``length`` times inside ONE jit
    via ``lax.scan`` — the robust timing mode on remote-tunneled devices:
    per-dispatch RPC overhead amortizes over the scan. ``body`` must map
    carry -> carry of identical shape/dtype."""
    time_once = _make_scan_runner(body, carry0, length)
    return min(time_once() for _ in range(reps)) / length * 1e3


def do_bench_scan_slope(
    body: Callable[[Any], Any],
    carry0: Any,
    lengths: tuple[int, int] = (24, 96),
    reps: int = 3,
    verbose: bool = False,
    min_credible_ms: float | None = None,
) -> float:
    """Overhead-robust per-iteration ms of ``body``.

    The execution tunnel charges a large FIXED cost per executable launch
    (~170 ms measured 2026-07-31: a 4096^3 matmul "takes" 28.6 ms/step in
    a length-6 scan but 2.2 ms/step in a length-96 scan —
    benchmarks/history/chip_calibration.csv). Any single-scan timing folds
    that cost into the per-step number, understating fast kernels by up to
    an order of magnitude.

    This helper times the SAME scanned body at two trip counts and
    returns the slope (T_long - T_short) / (L_long - L_short): the fixed
    launch cost appears in both totals and cancels exactly. Per-step cost
    must be trip-count-independent (it is: identical program, carried data
    dependence defeats memoization) for the slope to equal the true
    kernel time.

    Off-TPU there is no launch cost to cancel and interpret-mode steps
    cost seconds, so a short single scan is the right measurement — the
    backend dispatch lives HERE so every harness gets it.

    ``min_credible_ms``: physical floor on the per-step time (the caller
    knows its flop count and the chip ceiling; the slope does not). A
    slope BELOW the floor is an under-cancelled pair (observed 2026-08-01:
    250 TF/s reported on a 197 TF/s chip) and triggers the same fallback
    as the noise guard — the long-scan per-step time, a true upper bound.
    """
    if jax.default_backend() != "tpu":
        return do_bench_scan(body, carry0, length=2, reps=reps)
    short, long_ = lengths
    assert long_ > short
    t0 = time.perf_counter()

    run_short = _make_scan_runner(body, carry0, short)
    run_long = _make_scan_runner(body, carry0, long_)
    # PAIRED reps: each rep times short and long back-to-back so both see
    # the same tunnel conditions, then contributes its own slope; the
    # median rejects a rep whose overhead drifted mid-pair. (Independent
    # best-of-reps runs would subtract overhead samples from different
    # moments — a 50 ms drift over the 72-step delta fakes ~0.7 ms/step.)
    slopes = []
    t_long_best = float("inf")
    for _ in range(max(reps, 2)):
        ts = run_short() * 1e3
        tl = run_long() * 1e3
        t_long_best = min(t_long_best, tl / long_)
        slopes.append((tl - ts) / (long_ - short))
    slope = float(np.median(slopes))
    ok = 0.0 < slope <= t_long_best
    floor_hit = (
        ok and min_credible_ms is not None and slope < min_credible_ms
    )
    if floor_hit:
        ok = False
    if verbose:
        if floor_hit:
            from .perf_report import MEASURED_CEILING_TFLOPS

            # the floor is anchored at the measured chip ceiling, so the
            # implied rate scales as floor/slope
            implied_tf = MEASURED_CEILING_TFLOPS * min_credible_ms / slope
        guard = "" if ok else (
            f" -> CREDIBILITY FLOOR ({min_credible_ms:.3f} ms): slope "
            f"implies a rate above the chip ceiling "
            f"({implied_tf:.0f} TF/s > {MEASURED_CEILING_TFLOPS:.0f}) — "
            f"under-cancelled pair, fallback to "
            f"len{long_} upper bound {t_long_best:.3f}"
            if floor_hit else
            f" -> NOISE GUARD: fallback to len{long_} upper bound "
            f"{t_long_best:.3f}"
        )
        _echo(
            f"  [slope timing incl compile {time.perf_counter()-t0:.0f}s: "
            f"per-rep slopes {[round(s, 3) for s in slopes]} ms/step"
            + guard
        )
    # noise guard: non-positive slope (long ran FASTER than short) or slope
    # above the long-scan per-step time (negative implied overhead) means
    # the pair medians are still contaminated; the long-scan per-step time
    # is a true upper bound on the kernel time.
    if not ok:
        return t_long_best
    return slope


def do_bench_scan_verbose(body, carry0, length=8, reps=3):
    """:func:`do_bench_scan` + a one-line wall-clock print (chip-window
    scripts want compile time visible in their logs)."""
    t0 = time.perf_counter()
    ms = do_bench_scan(body, carry0, length=length, reps=reps)
    _echo(f"  [total incl compile {time.perf_counter()-t0:.0f}s]")
    return ms


def make_consume_all_grads_body(grad_fn, dtype):
    """Timing body ``q -> q`` that consumes ALL of (dq, dk, dv).

    Load-bearing anti-DCE measurement logic: dk/dv come from a separate
    pallas_call that XLA dead-code-eliminates when unused, silently
    dropping ~60% of the backward from the measured program (caught on
    silicon when fwd+bwd timed faster than fwd alone). Every fwd+bwd
    timing harness must build its body through this helper or its
    sibling `make_consume_all_grads_kv_body` — use THIS one only when
    the closed-over operands are small (closure capture lowers them as
    HLO constants); at >~100 MB switch to the kv/carry variant.

    ``grad_fn(q) -> (dq, dk, dv)``; dk/dv enter the carry as a 1e-30-scaled
    scalar — numerically invisible, but a real data dependence XLA cannot
    fold away (mul-by-zero would be simplifiable; 1e-30 is not).
    """
    import jax.numpy as jnp

    def body(q):
        dq, dk, dv = grad_fn(q)
        touch = (jnp.sum(dk) + jnp.sum(dv)) * 1e-30
        return (q + 1e-3 * dq.astype(dtype) + touch.astype(dtype)).astype(dtype)

    return body


def make_consume_all_grads_kv_body(grad_fn, dtype):
    """`make_consume_all_grads_body` variant whose carry is ``(q, k, v)``.

    A jitted body that merely *closes over* a jax.Array lowers it as an
    HLO constant; at GB scale that payload breaks the tunnel's
    remote-compile helper (2026-08-01 config5 window: 2.15 GB of captured
    kv chunks -> "Broken pipe" from the compile endpoint, the whole probe
    lost). Carrying k/v through the scan makes them jit ARGUMENTS — zero
    per-step cost (XLA aliases unmodified carry leaves) and a
    constant-free executable. Same anti-DCE contract as the q-only
    helper: ``grad_fn(q, k, v, *aux) -> (dq, dk, dv)``, all three
    consumed; any further carry leaves (e.g. a large cotangent seed w)
    ride through unchanged so they too stay arguments.
    """
    import jax.numpy as jnp

    def body(carry):
        q, k, v, *aux = carry
        dq, dk, dv = grad_fn(q, k, v, *aux)
        touch = (jnp.sum(dk) + jnp.sum(dv)) * 1e-30
        qn = (
            q + 1e-3 * dq.astype(dtype) + touch.astype(dtype)
        ).astype(dtype)
        return (qn, k, v, *aux)

    return body


def make_fwd_kv_body(fwd_fn, dtype):
    """Forward-only timing body with a ``(q, k, v, *aux)`` carry.

    Same no-captured-constants rationale as
    `make_consume_all_grads_kv_body`: ``fwd_fn(q, k, v, *aux) -> out``
    (out must be q-shaped) is called with every operand as a scan-carry
    leaf so GB-scale k/v lower as jit arguments, and the out->q chain
    provides the data dependence that defeats tunnel memoization.
    """

    def body(carry):
        q, k, v, *aux = carry
        return (fwd_fn(q, k, v, *aux).astype(dtype), k, v, *aux)

    return body


@dataclass
class Benchmark:
    """Declarative sweep spec (ref Benchmark/Mark :372)."""

    x_names: list[str]
    x_vals: list[Any]
    line_arg: str
    line_vals: list[Any]
    line_names: list[str]
    ylabel: str = "TFLOP/s"
    plot_name: str = "bench"
    args: dict[str, Any] = field(default_factory=dict)


def perf_report(benchmark: Benchmark):
    """Decorator: fn(**point) -> float; run() sweeps and returns rows."""

    def wrap(fn):
        def run(print_data: bool = True, save_path: str | None = None):
            rows = []
            for xv in benchmark.x_vals:
                row = {benchmark.x_names[0]: xv}
                for lv, ln in zip(benchmark.line_vals, benchmark.line_names):
                    kwargs = dict(benchmark.args)
                    kwargs[benchmark.x_names[0]] = xv
                    kwargs[benchmark.line_arg] = lv
                    try:
                        row[ln] = fn(**kwargs)
                    except Exception as e:  # noqa: BLE001
                        row[ln] = float("nan")
                        row[f"{ln}_error"] = type(e).__name__
                rows.append(row)
            if print_data:
                _print_table(rows)
            if save_path:
                _save_csv(rows, save_path)
            return rows

        fn.run = run
        return fn

    return wrap


def _print_table(rows: list[dict]) -> None:
    if not rows:
        return
    keys = list(rows[0].keys())
    widths = [max(len(str(k)), 12) for k in keys]
    _echo("  ".join(str(k).ljust(w) for k, w in zip(keys, widths)))
    for row in rows:
        _echo(
            "  ".join(
                (f"{row.get(k, ''):.2f}" if isinstance(row.get(k), float)
                 else str(row.get(k, ""))).ljust(w)
                for k, w in zip(keys, widths)
            )
        )


def _save_csv(rows: list[dict], path: str) -> None:
    import csv

    with open(path, "w", newline="") as f:
        writer = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
        writer.writeheader()
        writer.writerows(rows)
