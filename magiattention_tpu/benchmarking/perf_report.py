"""Append-only perf history + round-over-round deltas.

TPU-native replacement for the reference's Benchmark/Mark/perf_report
harness (magi_attention/benchmarking/bench.py:372-1378, CSV + plots): every
measurement appends one row to a CSV under ``benchmarks/history/`` (kept in
git), so each chip window extends a comparable record instead of
overwriting a JSON blob. ``history_report`` renders the latest row per config
with a delta against the previous measurement of the same config.

Dual MFU convention (VERDICT r2 item 10): rows carry the reference's FLOP
counting (fwd = 4*area*d*hq, bwd = 2.5x) for comparability, plus the
hardware matmul convention (the TPU backward runs 3.5x the fwd matmul work
— separate dq and dkv passes, docs/performance.md) so kernel progress is
not obscured by accounting: ``hw_tflops = tflops * HW_FWD_BWD_RATIO``.
"""

from __future__ import annotations

import csv
import datetime
import os
import subprocess

HISTORY_DIR = os.path.join(
    os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ),
    "benchmarks",
    "history",
)

# actual matmul work per reported (reference-convention) FLOP for fwd+bwd:
# reported = fwd * 3.5 (fwd + 2.5x bwd), executed = fwd * 4.5 (fwd + 3.5x
# bwd: dq pass 3 matmuls + dkv pass 4 vs fwd's 2)
HW_FWD_BWD_RATIO = 4.5 / 3.5

# nominal bf16 peak of the one attached chip (TPU v5 lite), TFLOP/s — the
# ONE definition every harness's MFU figures use (silicon measures ~105%
# of it on a 4096^3 matmul: true_rate.csv mm4096)
PEAK_TFLOPS = 197.0

# silicon-MEASURED matmul ceiling of the attached chip (true_rate.csv
# mm4096 slope: 207.98 TF/s ≈ 105.6% of nominal) — the ONE anchor for
# credibility floors and the roofline's ambient derate. Anchoring to the
# measured ceiling (not PEAK * slack) means a genuine measurement at the
# chip's real matmul rate can never be classified unphysical.
MEASURED_CEILING_TFLOPS = 208.0


def credible_floor_ms(
    flops: float, ceiling_tflops: float = MEASURED_CEILING_TFLOPS
) -> float:
    """Physical lower bound on a measurement of ``flops`` of matmul work:
    time implying a rate above the measured chip ceiling is unphysical
    (pass as ``do_bench_scan_slope(min_credible_ms=...)``). ``flops``
    must be EXECUTED flops — for fwd+bwd that is 4.5x fwd
    (HW_FWD_BWD_RATIO x the reference-convention 3.5x), or the floor sits
    ~29% below the physical bound it claims."""
    return flops / (ceiling_tflops * 1e9)


def _git_rev() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(HISTORY_DIR),
        ).stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def append_row(name: str, row: dict) -> str:
    """Append one measurement to ``benchmarks/history/<name>.csv``.

    Adds ``utc`` and ``commit`` columns automatically. The header is the
    union of all keys ever seen for this file (the file is rewritten with
    an extended header when a new key appears — files are small).
    Never raises: history is best-effort and must not cost a measurement.
    """
    try:
        os.makedirs(HISTORY_DIR, exist_ok=True)
        path = os.path.join(HISTORY_DIR, f"{name}.csv")
        full = {
            "utc": datetime.datetime.now(datetime.timezone.utc).strftime(
                "%Y-%m-%d %H:%M:%S"
            ),
            "commit": _git_rev(),
            **row,
        }
        # MAGI_ATTENTION_TELEMETRY=1: stamp the row with the run's comm /
        # balance context (tel_* columns) so a perf number carries the plan
        # that produced it. Empty dict (no extra columns) when off.
        from .. import telemetry

        full.update(
            {k: v for k, v in telemetry.flat_summary().items()
             if k not in full}
        )
        rows: list[dict] = []
        header: list[str] = []
        if os.path.exists(path):
            with open(path, newline="") as f:
                reader = csv.DictReader(f)
                header = list(reader.fieldnames or [])
                rows = list(reader)
        new_keys = [k for k in full if k not in header]
        if new_keys:
            header = header + new_keys
            with open(path, "w", newline="") as f:
                w = csv.DictWriter(f, fieldnames=header, restval="")
                w.writeheader()
                for r in rows:
                    w.writerow(r)
                w.writerow(full)
        else:
            with open(path, "a", newline="") as f:
                csv.DictWriter(f, fieldnames=header, restval="").writerow(
                    full
                )
        return path
    except Exception:
        return ""


def history_report(name: str, key_cols: list[str], value_col: str) -> str:
    """Latest row per config key with a delta vs the previous measurement.

    Returns a plain-text table (empty string when no history exists).
    """
    path = os.path.join(HISTORY_DIR, f"{name}.csv")
    if not os.path.exists(path):
        return ""
    with open(path, newline="") as f:
        rows = list(csv.DictReader(f))
    phase = value_col.split("_")[0]  # fwd_tflops -> fwd, fwdbwd_ms -> fwdbwd
    by_key: dict[tuple, list[dict]] = {}
    for r in rows:
        if r.get("suspect") or r.get(f"suspect_{phase}"):
            # harness marked the measurement unphysical (rate above the
            # chip ceiling even at the long-scan upper bound) — keep the
            # raw row in the CSV but never let it set a baseline. Plain
            # "suspect" taints the whole row; "suspect_<phase>" taints
            # only that phase's columns, so a bad fwd slope doesn't
            # suppress the same row's valid fwdbwd measurement.
            continue
        by_key.setdefault(tuple(r.get(k, "") for k in key_cols), []).append(r)
    lines = [
        f"# {name}: latest {value_col} per ({', '.join(key_cols)}) "
        f"with delta vs previous"
    ]
    for key, rs in sorted(by_key.items()):
        cur = rs[-1]
        try:
            val = float(cur.get(value_col) or "nan")
        except ValueError:
            continue
        delta = ""
        for prev in reversed(rs[:-1]):
            try:
                pv = float(prev.get(value_col) or "nan")
            except ValueError:
                continue
            if pv == pv and pv != 0:
                delta = f" ({(val - pv) / pv * 100:+.1f}% vs {prev['utc']})"
                break
        lines.append(
            f"{'/'.join(key)}: {value_col}={val:g} [{cur['utc']} "
            f"{cur.get('commit', '')}]{delta}"
        )
    return "\n".join(lines)
