"""Benchmark harness (ref: magi_attention/benchmarking/bench.py)."""

from .bench import Benchmark, do_bench, do_bench_flops, perf_report  # noqa: F401
