"""Deterministic single-attention-layer toy model for the serving loop.

The engine is model-agnostic: it needs exactly three maps — hidden rows to
q/k/v heads, attention output back to a hidden row, and a hidden row to the
next step's input. This module provides the smallest deterministic model
with that interface, used by the serve-smoke loop, the scheduler tests and
``benchmarks/serve_bench.py``. Float32 throughout so the serve-smoke
bitwise-equality criterion is about the serving machinery, not dtype
rounding; k/v for a token depend only on that token's input, which is what
makes chunked prefill and continuous batching exactly replayable.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class ToyModel:
    """One attention layer's projections: x -> (q, k, v) -> out -> x'."""

    wq: jax.Array  # (d_model, n_heads * head_dim)
    wk: jax.Array  # (d_model, n_kv_heads * head_dim)
    wv: jax.Array  # (d_model, n_kv_heads * head_dim)
    wo: jax.Array  # (n_heads * head_dim, d_model)
    n_heads: int
    n_kv_heads: int
    head_dim: int

    @property
    def d_model(self) -> int:
        return self.wq.shape[0]

    @classmethod
    def create(
        cls,
        d_model: int = 32,
        n_heads: int = 4,
        n_kv_heads: int = 2,
        head_dim: int = 16,
        seed: int = 0,
    ) -> "ToyModel":
        rng = np.random.default_rng(seed)
        scale = d_model ** -0.5

        def w(rows: int, cols: int) -> jax.Array:
            return jnp.asarray(
                (rng.standard_normal((rows, cols)) * scale).astype(np.float32)
            )

        return cls(
            wq=w(d_model, n_heads * head_dim),
            wk=w(d_model, n_kv_heads * head_dim),
            wv=w(d_model, n_kv_heads * head_dim),
            wo=w(n_heads * head_dim, d_model),
            n_heads=n_heads,
            n_kv_heads=n_kv_heads,
            head_dim=head_dim,
        )

    def qkv(self, x: jax.Array) -> tuple[jax.Array, jax.Array, jax.Array]:
        """``(t, d_model)`` hidden rows -> q ``(t, hq, d)``, k/v ``(t, hk, d)``."""
        t = x.shape[0]
        q = (x @ self.wq).reshape(t, self.n_heads, self.head_dim)
        k = (x @ self.wk).reshape(t, self.n_kv_heads, self.head_dim)
        v = (x @ self.wv).reshape(t, self.n_kv_heads, self.head_dim)
        return q, k, v

    def project(self, attn_out: jax.Array) -> jax.Array:
        """Attention output ``(t, hq, dv)`` -> hidden rows ``(t, d_model)``."""
        t = attn_out.shape[0]
        return attn_out.reshape(t, -1) @ self.wo

    def next_input(self, hidden: jax.Array) -> jax.Array:
        """The autoregressive closure: a generated hidden row becomes the
        next step's input row (tanh keeps magnitudes bounded so long
        generations stay finite)."""
        return jnp.tanh(hidden)

    def draft_next(self, x: jax.Array) -> jax.Array:
        """Greedy zero-context draft of the next input: run the layer as if
        ``x`` were the only token (softmax over one position makes the
        attention output just ``v``), then close the loop with
        ``next_input``. Cheap (no cache access), deterministic, and right
        whenever attention is locally dominated by the current token — the
        speculative-verify accept rate measures exactly how often."""
        _, _, v = self.qkv(x[None])  # v: (1, hk, d)
        g = self.n_heads // self.n_kv_heads
        out = jnp.repeat(v[0], g, axis=0)[None]  # (1, hq, d)
        return self.next_input(self.project(out)[0])

    def prompt(self, length: int, seed: int) -> jax.Array:
        """A deterministic synthetic prompt ``(length, d_model)``."""
        rng = np.random.default_rng(seed)
        return jnp.asarray(
            rng.standard_normal((length, self.d_model)).astype(np.float32)
        )
