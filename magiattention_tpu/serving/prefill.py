"""Chunked prefill over the paged cache (serving layer).

Reuses the existing FFA forward via :func:`~..kernels.paged_kv.paged_attn`:
each chunk's k/v are appended to the request's pages functionally, then the
chunk's queries attend causally over everything stored so far. The chunk
schedule is a pure function of (prompt length, chunk size) and this module
is shared by the engine AND the sequential reference replay — schedule
identity is what makes the serve-smoke bitwise-equality criterion hold by
construction (per-row FFA online softmax is invariant to the extra masked
rows of a shared pool's garbage pages).
"""

from __future__ import annotations

import jax

from ..kernels.paged_kv import PagedKVCache, append_kv, paged_attn
from .model import ToyModel


def prefill_schedule(total: int, chunk: int) -> list[tuple[int, int]]:
    """(start, size) chunks covering ``[0, total)`` in ``chunk`` steps."""
    if total <= 0:
        return []
    chunk = max(1, chunk)
    return [
        (start, min(chunk, total - start))
        for start in range(0, total, chunk)
    ]


def prefill_request(
    model: ToyModel,
    cache: PagedKVCache,
    slot: int,
    prompt: jax.Array,
    chunk: int,
    softmax_scale: float | None = None,
) -> tuple[PagedKVCache, jax.Array]:
    """Prefill one request's prompt into its slot, chunk by chunk.

    Pages must be pre-assigned (scheduler admission). Returns the updated
    cache and the LAST prompt position's hidden row ``(d_model,)`` — the
    seed of the first generated token.
    """
    last_out = None
    for start, size in prefill_schedule(int(prompt.shape[0]), chunk):
        x = prompt[start : start + size]
        q, k, v = model.qkv(x)
        cache = append_kv(cache, slot, k, v)
        out, _ = paged_attn(
            q, cache, slot,
            q_start=start,
            max_pages=cache.page_table.shape[1],
            softmax_scale=softmax_scale,
        )
        last_out = out[-1:]  # (1, hq, dv)
    assert last_out is not None, "empty prompt"
    return cache, model.project(last_out)[0]
