"""Continuous-batching scheduler: admission, growth, eviction.

Policy (all deterministic, so tests can assert exact orderings):

- **Admission** is FIFO with head-of-line blocking: the oldest waiting
  request admits only when a batch slot is free AND the pool covers its
  whole prompt; nothing behind it may jump the queue (determinism beats
  utilization at this scale).
- **Growth** is lazy: a decoding request allocates one page exactly when
  its next token crosses a page boundary.
- **Eviction** is LIFO by admission sequence: when growth finds the pool
  empty, the most-recently-admitted OTHER active request is restarted —
  its pages freed, its slot's table row reset, the request pushed back to
  the FRONT of the waiting queue. Restart semantics (recompute from the
  prompt) are safe because generation is deterministic, so a re-admitted
  request reproduces its earlier tokens exactly. If nothing is evictable
  the typed :class:`~..resilience.errors.PageExhaustedError` propagates to
  the caller — the pool genuinely cannot serve the workload.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

import jax
import numpy as np

from ..kernels.paged_kv import PagedKVCache, assign_pages
from ..resilience.errors import PageExhaustedError
from .cache import PagePool, pages_needed, release_slot, reset_page_scales


@dataclass
class ServeRequest:
    """One generation request plus its runtime state in the engine."""

    req_id: int
    prompt: jax.Array  # (prompt_len, d_model)
    max_new_tokens: int

    # runtime state (engine/scheduler owned)
    slot: int | None = None
    page_ids: list[int] = field(default_factory=list)
    length: int = 0  # tokens currently stored in the cache
    generated: list[np.ndarray] = field(default_factory=list)
    pending_x: jax.Array | None = None  # next decode step's input row
    admit_seq: int = -1
    evictions: int = 0
    shard: int = 0  # page-pool shard all of this request's pages live on

    # latency bookkeeping (serve_bench)
    submit_time: float | None = None
    first_token_time: float | None = None
    finish_time: float | None = None

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def done(self) -> bool:
        return len(self.generated) >= self.max_new_tokens

    def reset_runtime(self) -> None:
        """Back to the waiting-queue state (eviction restart)."""
        self.slot = None
        self.page_ids = []
        self.length = 0
        self.generated = []
        self.pending_x = None
        self.shard = 0


class Scheduler:
    """Owns the page pool, the slot table and the waiting queue."""

    def __init__(self, pool: PagePool, max_slots: int, page_size: int) -> None:
        self.pool = pool
        self.max_slots = max_slots
        self.page_size = page_size
        self.slots: list[ServeRequest | None] = [None] * max_slots
        self.waiting: deque[ServeRequest] = deque()
        self._admit_counter = 0

    # -- queries ----------------------------------------------------------
    @property
    def active(self) -> list[ServeRequest]:
        return [r for r in self.slots if r is not None]

    def has_work(self) -> bool:
        return bool(self.waiting) or any(r is not None for r in self.slots)

    def submit_request(self, req: ServeRequest) -> None:
        self.waiting.append(req)

    # -- admission --------------------------------------------------------
    def admit(
        self, cache: PagedKVCache
    ) -> tuple[PagedKVCache, list[ServeRequest]]:
        """Admit FIFO head-of-line requests while a slot and the prompt's
        pages are both available. Installs each request's pages in the
        device cache; prefill itself is the engine's job."""
        admitted: list[ServeRequest] = []
        while self.waiting:
            req = self.waiting[0]
            slot = next(
                (i for i, r in enumerate(self.slots) if r is None), None
            )
            if slot is None:
                break
            need = pages_needed(req.prompt_len, self.page_size)
            if need > cache.page_table.shape[1]:
                raise ValueError(
                    f"request {req.req_id}: prompt needs {need} pages, "
                    f"table width is {cache.page_table.shape[1]}"
                )
            shard = self.pool.best_shard(need)
            if shard is None:
                break
            self.waiting.popleft()
            req.shard = shard
            req.page_ids = self.pool.alloc(need, shard=shard)
            req.slot = slot
            req.length = 0
            req.admit_seq = self._admit_counter
            self._admit_counter += 1
            self.slots[slot] = req
            cache = assign_pages(cache, slot, req.page_ids)
            admitted.append(req)
        return cache, admitted

    # -- growth / eviction ------------------------------------------------
    def ensure_capacity(
        self, cache: PagedKVCache, req: ServeRequest, new_length: int
    ) -> tuple[PagedKVCache, int]:
        """Grow ``req``'s page list to cover ``new_length`` tokens, evicting
        other requests LIFO when the pool is dry. Returns the cache and the
        number of evictions performed."""
        evicted = 0
        need = pages_needed(new_length, self.page_size)
        if need > cache.page_table.shape[1]:
            raise ValueError(
                f"request {req.req_id}: {new_length} tokens need {need} "
                f"pages, table width is {cache.page_table.shape[1]}"
            )
        while len(req.page_ids) < need:
            try:
                new_pages = self.pool.alloc(1, shard=req.shard)
            except PageExhaustedError:
                cache = self.evict_one(cache, exclude=req, shard=req.shard)
                evicted += 1
                continue
            req.page_ids.extend(new_pages)
            cache = assign_pages(cache, req.slot, req.page_ids)
        return cache, evicted

    def evict_one(
        self,
        cache: PagedKVCache,
        exclude: ServeRequest,
        shard: int | None = None,
    ) -> PagedKVCache:
        """Restart the most-recently-admitted active request other than
        ``exclude`` (on ``shard`` when given — growth can only use its own
        shard's pages); raises :class:`PageExhaustedError` when none
        exists."""
        victims = [
            r
            for r in self.slots
            if r is not None
            and r is not exclude
            and (shard is None or r.shard == shard)
        ]
        if not victims:
            free = (
                self.pool.free_count
                if shard is None
                else self.pool.free_count_shard(shard)
            )
            raise PageExhaustedError(requested=1, free=free)
        victim = max(victims, key=lambda r: r.admit_seq)
        self.pool.release(victim.page_ids)
        cache = reset_page_scales(cache, victim.page_ids)
        cache = release_slot(cache, victim.slot)
        self.slots[victim.slot] = None
        victim.reset_runtime()
        victim.evictions += 1
        self.waiting.appendleft(victim)
        return cache

    def shrink_to_length(
        self, cache: PagedKVCache, req: ServeRequest
    ) -> PagedKVCache:
        """Release pages past ``pages_needed(req.length)`` back to the pool
        (speculative-verify page-level rollback). The table entries beyond
        the kept prefix go back to -1 so a re-grown request re-installs
        fresh ids, and released quantized pages get their scales reset."""
        need = pages_needed(req.length, self.page_size)
        extra = req.page_ids[need:]
        if not extra:
            return cache
        req.page_ids = req.page_ids[:need]
        self.pool.release(extra)
        cache = reset_page_scales(cache, extra)
        cache = PagedKVCache(
            cache.k_pages,
            cache.v_pages,
            cache.page_table.at[req.slot].set(-1),
            cache.lengths,
            cache.k_scales,
            cache.v_scales,
        )
        return assign_pages(cache, req.slot, req.page_ids)

    # -- completion -------------------------------------------------------
    def finish(
        self, cache: PagedKVCache, req: ServeRequest
    ) -> PagedKVCache:
        """Free a completed request's resources (its outputs stay on the
        request object)."""
        self.pool.release(req.page_ids)
        cache = reset_page_scales(cache, req.page_ids)
        cache = release_slot(cache, req.slot)
        self.slots[req.slot] = None
        req.page_ids = []
        return cache
