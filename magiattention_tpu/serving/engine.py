"""Continuous-batching serving engine.

``ServeEngine.step()`` is one scheduler tick: admit + prefill newly
admitted requests, then run ONE decode step for every occupied slot (the
batch is a static ``(max_slots, hq, d)`` block — empty slots carry zero
queries and length 0), then retire completed requests. Interleaving
prefill and decode inside one tick is what "continuous batching" means
here: a long prompt never stalls other requests for more than a tick.

Scale knobs (docs/serving_scale.md), all off by default and composable:

- ``spec_tokens`` k > 1 switches the decode phase to speculative verify:
  each tick drafts k-1 extra inputs (``draft_fn``, default the model's
  greedy self-draft), appends all k rows, verifies them in ONE
  multi-row-q launch, commits the longest accepted prefix, and rolls the
  rejected rows back (length + page-level — freed pages return to the
  pool with their quantization scales reset). Because draft input 0 is
  always the true ``pending_x``, at least one token commits per tick, and
  commits are bitwise-identical to the one-token-per-tick engine.
- ``kv_dtype='int8'`` stores KV pages quantized (per-page symmetric
  scales), roughly quadrupling slots per HBM budget; decode runs the
  dequant-in-kernel rung.
- ``decode_shards`` > 1 runs the decode kernel under a kv-head
  ``shard_map`` (one launch per device); ``pool_shards`` partitions the
  page pool with per-shard routing in the scheduler.

Every tick emits a ``serve_step`` telemetry record (docs/observability.md)
when telemetry is enabled; wall-clock timing uses ``time.perf_counter``
directly — serving/ is host orchestration, outside the kernels/functional
no-host-clock lint boundary (MAGI-L002).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable

import jax.numpy as jnp
import numpy as np

from .. import telemetry
from ..env import serve as env_serve
from ..kernels.paged_kv import PagedKVCache, append_kv, rollback_kv
from .cache import PagePool
from .decode import decode_attn_step, verify_attn_step
from .model import ToyModel
from .prefill import prefill_request
from .scheduler import Scheduler, ServeRequest

__all__ = ["ServeConfig", "ServeEngine", "ServeRequest"]


@dataclass(frozen=True)
class ServeConfig:
    """Static-shape envelope of one engine instance. Everything here fixes
    an array shape or a traversal schedule, so two engines with equal
    configs replay each other exactly."""

    page_size: int = 16
    num_pages: int = 64
    max_slots: int = 4
    max_pages_per_seq: int = 16
    prefill_chunk: int = 64
    softmax_scale: float | None = None
    kv_dtype: str = "float32"  # 'float32' | 'int8'
    spec_tokens: int = 1  # draft tokens verified per tick
    decode_shards: int = 1  # kv-head mesh width for the decode kernel
    pool_shards: int = 1  # page-pool partitions (scheduler routing)

    @classmethod
    def from_env(cls) -> "ServeConfig":
        num_pages = env_serve.serve_num_pages()
        return cls(
            page_size=env_serve.serve_page_size(),
            num_pages=num_pages,
            max_slots=env_serve.serve_max_slots(),
            max_pages_per_seq=num_pages,
            prefill_chunk=env_serve.serve_prefill_chunk(),
            kv_dtype=env_serve.serve_kv_dtype(),
            spec_tokens=env_serve.serve_spec_tokens(),
            decode_shards=env_serve.serve_shards(),
            pool_shards=env_serve.serve_pool_shards(),
        )


# draft_fn(model, request, current_input, draft_index) -> next draft input
DraftFn = Callable[[ToyModel, ServeRequest, jnp.ndarray, int], jnp.ndarray]


def _greedy_draft(
    model: ToyModel, req: ServeRequest, x: jnp.ndarray, j: int
) -> jnp.ndarray:
    return model.draft_next(x)


class ServeEngine:
    """Drives a :class:`ToyModel`-shaped model over a shared paged cache."""

    def __init__(
        self,
        model: ToyModel,
        config: ServeConfig,
        draft_fn: DraftFn | None = None,
    ) -> None:
        if config.kv_dtype not in ("float32", "int8"):
            raise ValueError(
                f"kv_dtype={config.kv_dtype!r} not in ('float32', 'int8')"
            )
        if config.spec_tokens < 1:
            raise ValueError(f"spec_tokens={config.spec_tokens} must be >= 1")
        self.model = model
        self.config = config
        self.draft_fn = draft_fn or _greedy_draft
        self.cache = PagedKVCache.create(
            num_pages=config.num_pages,
            page_size=config.page_size,
            n_kv_heads=model.n_kv_heads,
            head_dim=model.head_dim,
            max_seqs=config.max_slots,
            max_pages_per_seq=config.max_pages_per_seq,
            dtype=jnp.int8 if config.kv_dtype == "int8" else jnp.float32,
        )
        self.scheduler = Scheduler(
            PagePool(config.num_pages, config.pool_shards),
            config.max_slots,
            config.page_size,
        )
        self.step_count = 0
        self.finished: list[ServeRequest] = []

    # -- request intake ---------------------------------------------------
    def submit(self, req: ServeRequest) -> None:
        if req.prompt_len < 1:
            raise ValueError(f"request {req.req_id}: empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError(f"request {req.req_id}: max_new_tokens < 1")
        req.submit_time = time.perf_counter()
        self.scheduler.submit_request(req)

    # -- one tick ---------------------------------------------------------
    def step(self) -> dict:
        """Admit, prefill, decode (1 token or a spec_tokens draft window)
        per active slot, retire. Returns the tick's stats dict (mirrors
        the telemetry record)."""
        t0 = time.perf_counter()
        cfg = self.config
        sched = self.scheduler
        spec_k = cfg.spec_tokens
        admitted = evicted = completed = 0
        prefill_tokens = decode_tokens = 0
        draft_attempted = draft_accepted = 0

        # 1. admission + prefill
        self.cache, newly = sched.admit(self.cache)
        for req in newly:
            self.cache, last_hidden = prefill_request(
                self.model, self.cache, req.slot, req.prompt,
                cfg.prefill_chunk, cfg.softmax_scale,
            )
            req.length = req.prompt_len
            req.pending_x = self.model.next_input(last_hidden)
            prefill_tokens += req.prompt_len
            admitted += 1

        # 2. page growth for this tick's tokens (may evict — including a
        # request admitted above, whose prefill is then discarded and
        # deterministically redone after re-admission)
        for slot in range(cfg.max_slots):
            req = sched.slots[slot]
            if req is None or req.pending_x is None:
                continue
            self.cache, n_evicted = sched.ensure_capacity(
                self.cache, req, req.length + spec_k
            )
            evicted += n_evicted

        # 3. decode: one token (spec_k == 1) or draft+verify (spec_k > 1)
        # per surviving slot
        if spec_k == 1:
            q_rows: dict[int, jnp.ndarray] = {}
            for slot in range(cfg.max_slots):
                req = sched.slots[slot]
                if req is None or req.pending_x is None:
                    continue
                q, k, v = self.model.qkv(req.pending_x[None])
                self.cache = append_kv(self.cache, slot, k, v)
                req.length += 1
                q_rows[slot] = q[0]
                decode_tokens += 1
                draft_attempted += 1
                draft_accepted += 1

            if q_rows:
                hq, d = self.model.n_heads, self.model.head_dim
                zero_row = jnp.zeros((hq, d), jnp.float32)
                q_batch = jnp.stack(
                    [q_rows.get(s, zero_row) for s in range(cfg.max_slots)]
                )
                host_lengths = tuple(
                    sched.slots[s].length if s in q_rows else 0
                    for s in range(cfg.max_slots)
                )
                out, _ = decode_attn_step(
                    q_batch, self.cache, host_lengths, cfg.softmax_scale,
                    shards=cfg.decode_shards,
                )
                for slot in sorted(q_rows):
                    req = sched.slots[slot]
                    hidden = self.model.project(out[slot : slot + 1])[0]
                    req.generated.append(np.asarray(hidden))
                    if req.first_token_time is None:
                        req.first_token_time = time.perf_counter()
                    req.pending_x = self.model.next_input(hidden)
        else:
            q_tiles: dict[int, jnp.ndarray] = {}
            draft_xs: dict[int, list] = {}
            for slot in range(cfg.max_slots):
                req = sched.slots[slot]
                if req is None or req.pending_x is None:
                    continue
                xs = [req.pending_x]
                for j in range(1, spec_k):
                    xs.append(self.draft_fn(self.model, req, xs[-1], j))
                x_block = jnp.stack(xs)  # (spec_k, d_model)
                q, k, v = self.model.qkv(x_block)
                self.cache = append_kv(self.cache, slot, k, v)
                req.length += spec_k
                q_tiles[slot] = q  # (spec_k, hq, d)
                draft_xs[slot] = xs
                draft_attempted += spec_k

            if q_tiles:
                hq, d = self.model.n_heads, self.model.head_dim
                zero_tile = jnp.zeros((spec_k, hq, d), jnp.float32)
                q_batch = jnp.stack(
                    [q_tiles.get(s, zero_tile) for s in range(cfg.max_slots)]
                )
                host_lengths = tuple(
                    sched.slots[s].length if s in q_tiles else 0
                    for s in range(cfg.max_slots)
                )
                out, _ = verify_attn_step(
                    q_batch, self.cache, host_lengths, cfg.softmax_scale
                )
                for slot in sorted(q_tiles):
                    req = sched.slots[slot]
                    # (spec_k, d_model) — row j is correct iff draft inputs
                    # 0..j were (causal rows never see later garbage)
                    hiddens = self.model.project(out[slot])
                    xs = draft_xs[slot]
                    # longest accepted prefix: draft 0 is the true
                    # pending_x, so row 0 is always right; row j commits
                    # iff its input equals what row j-1's output implies
                    accept = 1
                    while accept < spec_k and np.array_equal(
                        np.asarray(xs[accept]),
                        np.asarray(self.model.next_input(hiddens[accept - 1])),
                    ):
                        accept += 1
                    remaining = req.max_new_tokens - len(req.generated)
                    commit = min(accept, remaining)
                    for j in range(commit):
                        req.generated.append(np.asarray(hiddens[j]))
                    if req.first_token_time is None:
                        req.first_token_time = time.perf_counter()
                    req.pending_x = self.model.next_input(hiddens[commit - 1])
                    decode_tokens += commit
                    draft_accepted += commit
                    if commit < spec_k:  # rollback rejected rows + pages
                        req.length -= spec_k - commit
                        self.cache = rollback_kv(
                            self.cache, slot, req.length
                        )
                        self.cache = sched.shrink_to_length(self.cache, req)

        # 4. retirement
        for slot in range(cfg.max_slots):
            req = sched.slots[slot]
            if req is not None and req.done:
                req.finish_time = time.perf_counter()
                self.cache = sched.finish(self.cache, req)
                self.finished.append(req)
                completed += 1

        self.step_count += 1
        stats = dict(
            step=self.step_count,
            wall_ms=(time.perf_counter() - t0) * 1e3,
            occupancy=len(sched.active) / cfg.max_slots,
            pages_in_use=sched.pool.used_count,
            waiting=len(sched.waiting),
            admitted=admitted,
            evicted=evicted,
            completed=completed,
            prefill_tokens=prefill_tokens,
            decode_tokens=decode_tokens,
            kv_dtype=cfg.kv_dtype,
            shards=cfg.decode_shards,
            spec_k=spec_k,
            draft_attempted=draft_attempted,
            draft_accepted=draft_accepted,
            accept_rate=(
                draft_accepted / draft_attempted if draft_attempted else 0.0
            ),
        )
        if telemetry.enabled():
            telemetry.record_event("serve_step", **stats)
            telemetry.inc("serve.steps")
        return stats

    # -- full drain -------------------------------------------------------
    def run(
        self, requests: list[ServeRequest], max_steps: int = 100_000
    ) -> list[ServeRequest]:
        """Submit ``requests`` and tick until every one completes."""
        for req in requests:
            self.submit(req)
        while self.scheduler.has_work():
            self.step()
            if self.step_count > max_steps:
                raise RuntimeError(
                    f"serving loop exceeded {max_steps} steps "
                    f"({len(self.finished)}/{len(requests)} done)"
                )
        return self.finished
