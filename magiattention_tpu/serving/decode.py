"""Batched decode attention with the serving fallback ladder.

Six registry rungs, descending rank order (docs/serving.md,
docs/serving_scale.md):

1. **paged_decode_sharded** — the Pallas kernel under a ``shard_map`` over
   the kv-head axis: one launch per mesh shard. Feasible only when the
   engine asks for >1 shard, the head count splits evenly, enough devices
   exist, and the cache is unquantized. Bitwise-equal to the single-device
   kernel (per-(head, seq) accumulation is untouched by the split).
2. **paged_decode_spec** — multi-token speculative verify
   (:func:`verify_attn_step` only; never feasible for the 1-row step).
3. **paged_decode_int8** — the dequant-in-kernel variant; feasible only on
   quantized caches.
4. **paged_decode** — the PR 8 kernel (unquantized caches).
5. **gather_ffa** — per-slot gather+FFA (:func:`~..kernels.paged_kv.paged_attn`);
   host-static lengths. ``gather_kv`` dequantizes on the way out, so this
   rung (and dense below) serves every cache dtype — it is the recovery
   floor beneath all three new kernels.
6. **dense** — masked jnp softmax over the gathered pages, no Pallas.

Each Pallas rung arms the ``serve_decode`` injection site (NOT the FFA
``kernel_lowering`` site, which prefill's FFA calls also arm — faulting
that would crash prefill, whose calls have no ladder around them).

Descent follows the resilience contract of ``ffa.ffa_bwd_pallas_dispatch``:
recoverable failure types from :func:`kernel_failure_types`, descent only
under ``MAGI_ATTENTION_FALLBACK=1`` (otherwise failures propagate), one
``resilience`` telemetry record per hop. Infeasible rungs are filtered out
BEFORE descent — a pin on an infeasible rung starts from the first
feasible rung at or below it, the same "pin subject to feasibility guards"
rule as the ffa_bwd decision.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from ..env import backend as env_backend
from ..env import resilience as env_resilience
from ..kernels import registry as _registry
from ..kernels.paged_decode import (
    paged_decode_attn,
    paged_decode_attn_int8,
    paged_decode_attn_sharded,
    paged_decode_attn_spec,
)
from ..kernels.paged_kv import PagedKVCache, gather_kv, paged_attn
from ..resilience import fallback as _fallback
from ..resilience.inject import maybe_inject

NEG_INF = float("-inf")


def _feasibility(
    cache: PagedKVCache, hk: int, shards: int, multi_row: bool
) -> Callable[[str], bool]:
    quantized = cache.quantized

    def feasible(rung: str) -> bool:
        if rung == "paged_decode_sharded":
            return (
                not multi_row
                and not quantized
                and shards > 1
                and hk % shards == 0
                and len(jax.devices()) >= shards
            )
        if rung == "paged_decode_spec":
            # quantized verify descends to gather_ffa's dequantized path
            return multi_row and not quantized
        if rung == "paged_decode_int8":
            return not multi_row and quantized
        if rung == "paged_decode":
            return not multi_row and not quantized
        return True  # gather_ffa / dense serve every shape and dtype

    return feasible


def _rungs(
    cache: PagedKVCache,
    key: tuple,
    default: str,
    hk: int,
    shards: int,
    multi_row: bool,
) -> list[str]:
    start = _registry.resolve(
        "serve_decode", key, lambda: default,
        pin=env_backend.serve_decode_pin(),
    ).name
    feasible = _feasibility(cache, hk, shards, multi_row)
    rungs = [r for r in _registry.ladder("serve_decode", start) if feasible(r)]
    if not rungs:  # pinned below every feasible rung: full feasible ladder
        rungs = [r for r in _registry.ladder("serve_decode") if feasible(r)]
    return rungs


def decode_attn_step(
    q_batch: jax.Array,
    cache: PagedKVCache,
    host_lengths: tuple[int, ...],
    softmax_scale: float | None = None,
    shards: int = 1,
) -> tuple[jax.Array, jax.Array]:
    """One decode step for every active slot.

    Args:
        q_batch: ``(max_seqs, hq, d)`` — one query row per slot (zeros on
            inactive slots).
        cache: the paged cache AFTER this step's k/v rows were appended.
        host_lengths: per-slot token counts as host ints (0 = inactive);
            must match ``cache.lengths`` — the gather/dense rungs need them
            static, the kernel rungs ignore them.
        shards: kv-head mesh width the engine wants; 1 disables the
            sharded rung.

    Returns (out ``(max_seqs, hq, dv)``, lse ``(max_seqs, hq)``).
    """
    S, hq, d = q_batch.shape
    hk = cache.k_pages.shape[2]
    dv = cache.v_pages.shape[-1]
    quantized = cache.quantized
    key = (S, hq, hk, d, dv, str(q_batch.dtype), quantized, shards)
    if quantized:
        default = "paged_decode_int8"
    elif shards > 1:
        default = "paged_decode_sharded"
    else:
        default = "paged_decode"
    rungs = _rungs(cache, key, default, hk, shards, multi_row=False)
    failures = _fallback.kernel_failure_types()
    for i, rung in enumerate(rungs):
        try:
            if rung == "paged_decode_sharded":
                maybe_inject("serve_decode")
                return paged_decode_attn_sharded(
                    q_batch, cache, shards, softmax_scale=softmax_scale
                )
            if rung == "paged_decode_int8":
                maybe_inject("serve_decode")
                return paged_decode_attn_int8(
                    q_batch, cache, softmax_scale=softmax_scale
                )
            if rung == "paged_decode":
                maybe_inject("serve_decode")
                return paged_decode_attn(
                    q_batch, cache, softmax_scale=softmax_scale
                )
            if rung == "gather_ffa":
                return _gather_ffa_decode(
                    q_batch, cache, host_lengths, softmax_scale
                )
            return _dense_decode(q_batch, cache, host_lengths, softmax_scale)
        except failures as e:
            if i + 1 >= len(rungs) or not env_resilience.is_fallback_enable():
                raise
            _fallback.record_resilience_event(
                "fallback", "serve_decode",
                action_detail=f"{rung}_to_{rungs[i + 1]}",
                error=type(e).__name__,
            )
    raise AssertionError("serve_decode ladder is empty")  # pragma: no cover


def verify_attn_step(
    q_spec: jax.Array,
    cache: PagedKVCache,
    host_lengths: tuple[int, ...],
    softmax_scale: float | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Speculative verify: ``spec_k`` draft-token query rows per slot in
    one launch, each row attending its own causal prefix.

    Args:
        q_spec: ``(max_seqs, spec_k, hq, d)`` — draft token ``t`` of a slot
            sits at absolute position ``lengths - spec_k + t`` (the draft
            rows are already appended, so lengths include them).
        host_lengths: per-slot counts AFTER the append (0 = inactive).

    Returns (out ``(max_seqs, spec_k, hq, dv)``,
    lse ``(max_seqs, spec_k, hq)``).
    """
    S, spec_k, hq, d = q_spec.shape
    hk = cache.k_pages.shape[2]
    dv = cache.v_pages.shape[-1]
    key = (
        S, hq, hk, d, dv, str(q_spec.dtype), cache.quantized,
        "spec", spec_k,
    )
    rungs = _rungs(
        cache, key, "paged_decode_spec", hk, shards=1, multi_row=True
    )
    failures = _fallback.kernel_failure_types()
    for i, rung in enumerate(rungs):
        try:
            if rung == "paged_decode_spec":
                maybe_inject("serve_decode")
                return paged_decode_attn_spec(
                    q_spec, cache, softmax_scale=softmax_scale
                )
            if rung == "gather_ffa":
                return _gather_ffa_verify(
                    q_spec, cache, host_lengths, softmax_scale
                )
            return _dense_verify(q_spec, cache, host_lengths, softmax_scale)
        except failures as e:
            if i + 1 >= len(rungs) or not env_resilience.is_fallback_enable():
                raise
            _fallback.record_resilience_event(
                "fallback", "serve_decode",
                action_detail=f"{rung}_to_{rungs[i + 1]}",
                error=type(e).__name__,
            )
    raise AssertionError("serve_decode ladder is empty")  # pragma: no cover


def _gather_ffa_decode(q_batch, cache, host_lengths, softmax_scale):
    """Per-slot gather+FFA decode: the reference rung. The new token sits
    at position ``length - 1`` (appended before attending), so the causal
    band covers exactly the stored rows."""
    S, hq, d = q_batch.shape
    dv = cache.v_pages.shape[-1]
    max_pages = cache.page_table.shape[1]
    outs, lses = [], []
    for s, length in enumerate(host_lengths):
        if length <= 0:
            outs.append(jnp.zeros((hq, dv), q_batch.dtype))
            lses.append(jnp.full((hq,), NEG_INF, jnp.float32))
            continue
        out, lse = paged_attn(
            q_batch[s : s + 1], cache, s,
            q_start=int(length) - 1,
            max_pages=max_pages,
            softmax_scale=softmax_scale,
        )
        outs.append(out[0])
        lses.append(lse[0])
    return jnp.stack(outs), jnp.stack(lses)


def _gather_ffa_verify(q_spec, cache, host_lengths, softmax_scale):
    """Per-slot gather+FFA over the ``spec_k`` draft rows at once: row 0
    sits at ``length - spec_k``, the causal band puts row ``t`` at
    ``length - spec_k + t`` — identical geometry to the spec kernel, and
    (per-row FFA online-softmax invariance, reference.py) bitwise-equal to
    issuing the rows as sequential single-token calls."""
    S, spec_k, hq, d = q_spec.shape
    dv = cache.v_pages.shape[-1]
    max_pages = cache.page_table.shape[1]
    outs, lses = [], []
    for s, length in enumerate(host_lengths):
        if length <= 0:
            outs.append(jnp.zeros((spec_k, hq, dv), q_spec.dtype))
            lses.append(jnp.full((spec_k, hq), NEG_INF, jnp.float32))
            continue
        out, lse = paged_attn(
            q_spec[s], cache, s,
            q_start=int(length) - spec_k,
            max_pages=max_pages,
            softmax_scale=softmax_scale,
        )
        outs.append(out)
        lses.append(lse)
    return jnp.stack(outs), jnp.stack(lses)


def _dense_decode(q_batch, cache, host_lengths, softmax_scale):
    """Masked dense softmax over the gathered pages — no Pallas anywhere."""
    S, hq, d = q_batch.shape
    dv = cache.v_pages.shape[-1]
    hk = cache.k_pages.shape[2]
    g = hq // hk
    if softmax_scale is None:
        softmax_scale = float(d) ** -0.5
    outs, lses = [], []
    for s, length in enumerate(host_lengths):
        if length <= 0:
            outs.append(jnp.zeros((hq, dv), q_batch.dtype))
            lses.append(jnp.full((hq,), NEG_INF, jnp.float32))
            continue
        k, v = gather_kv(cache, s)  # (cap, hk, d)
        k = k[:length].astype(jnp.float32)
        v = v[:length].astype(jnp.float32)
        q = q_batch[s].astype(jnp.float32)  # (hq, d)
        kh = jnp.repeat(k, g, axis=1)  # (length, hq, d)
        scores = jnp.einsum("hd,lhd->hl", q, kh) * softmax_scale
        m = jnp.max(scores, axis=1, keepdims=True)
        p = jnp.exp(scores - m)
        l = jnp.sum(p, axis=1, keepdims=True)
        vh = jnp.repeat(v, g, axis=1)
        out = jnp.einsum("hl,lhd->hd", p / l, vh)
        outs.append(out.astype(q_batch.dtype))
        lses.append((m[:, 0] + jnp.log(l[:, 0])).astype(jnp.float32))
    return jnp.stack(outs), jnp.stack(lses)


def _dense_verify(q_spec, cache, host_lengths, softmax_scale):
    """Dense softmax over the draft rows, one per-row causal horizon."""
    S, spec_k, hq, d = q_spec.shape
    dv = cache.v_pages.shape[-1]
    outs, lses = [], []
    for t in range(spec_k):
        # row t of every slot is a plain decode step over the prefix that
        # ends at its own position
        t_lengths = tuple(
            max(0, length - (spec_k - 1 - t)) if length > 0 else 0
            for length in host_lengths
        )
        out, lse = _dense_decode(
            q_spec[:, t], cache, t_lengths, softmax_scale
        )
        outs.append(out)
        lses.append(lse)
    return jnp.stack(outs, axis=1), jnp.stack(lses, axis=1)
