"""Batched decode attention with the serving fallback ladder.

Three rungs, descending (docs/serving.md):

1. **Pallas paged-decode kernel** (:func:`~..kernels.paged_decode.paged_decode_attn`)
   — one batched call, page-table prefetch, traced lengths (no retrace per
   step). Armed with its own ``serve_decode`` injection site (NOT the FFA
   ``kernel_lowering`` site, which prefill's FFA calls also arm — faulting
   that would crash prefill, whose calls have no ladder around them).
2. **gather+FFA reference** (:func:`~..kernels.paged_kv.paged_attn` per
   active slot) — the pre-existing path; host-static lengths, so each new
   length traces a fresh plan. This is the serve-smoke bitwise-equality
   target (``MAGI_ATTENTION_SERVE_DECODE_KERNEL=0`` pins it).
3. **dense jnp softmax** over the gathered pages — the sdpa_online-style
   last resort with no Pallas in the loop.

Descent follows the resilience contract of ``ffa.ffa_bwd_pallas_dispatch``:
recoverable failure types from :func:`kernel_failure_types`, descent only
under ``MAGI_ATTENTION_FALLBACK=1`` (otherwise failures propagate), one
``resilience`` telemetry record per hop.

Rung selection flows through the backend registry's ``serve_decode``
decision (kernels/registry.py): a pin
(MAGI_ATTENTION_BACKEND_SERVE_DECODE, or the legacy
MAGI_ATTENTION_SERVE_DECODE_KERNEL mapped 1->paged_decode,
0->gather_ffa) sets the starting rung; unpinned steps resolve against the
policy cache / measured serve_step history, defaulting to the kernel
rung. The ladder itself — which rungs exist and their descent order — is
the registry's rank ordering, shared with the resilience module.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..env import backend as env_backend
from ..env import resilience as env_resilience
from ..kernels import registry as _registry
from ..kernels.paged_decode import paged_decode_attn
from ..kernels.paged_kv import PagedKVCache, gather_kv, paged_attn
from ..resilience import fallback as _fallback
from ..resilience.inject import maybe_inject

NEG_INF = float("-inf")


def decode_attn_step(
    q_batch: jax.Array,
    cache: PagedKVCache,
    host_lengths: tuple[int, ...],
    softmax_scale: float | None = None,
) -> tuple[jax.Array, jax.Array]:
    """One decode step for every active slot.

    Args:
        q_batch: ``(max_seqs, hq, d)`` — one query row per slot (zeros on
            inactive slots).
        cache: the paged cache AFTER this step's k/v rows were appended.
        host_lengths: per-slot token counts as host ints (0 = inactive);
            must match ``cache.lengths`` — the gather/dense rungs need them
            static, the kernel rung ignores them.

    Returns (out ``(max_seqs, hq, dv)``, lse ``(max_seqs, hq)``).
    """
    S, hq, d = q_batch.shape
    hk = cache.k_pages.shape[2]
    dv = cache.v_pages.shape[-1]
    key = (S, hq, hk, d, dv, str(q_batch.dtype))
    start = _registry.resolve(
        "serve_decode", key, lambda: "paged_decode",
        pin=env_backend.serve_decode_pin(),
    ).name
    rungs = _registry.ladder("serve_decode", start)
    failures = _fallback.kernel_failure_types()
    for i, rung in enumerate(rungs):
        try:
            if rung == "paged_decode":
                maybe_inject("serve_decode")
                return paged_decode_attn(
                    q_batch, cache, softmax_scale=softmax_scale
                )
            if rung == "gather_ffa":
                return _gather_ffa_decode(
                    q_batch, cache, host_lengths, softmax_scale
                )
            return _dense_decode(q_batch, cache, host_lengths, softmax_scale)
        except failures as e:
            if i + 1 >= len(rungs) or not env_resilience.is_fallback_enable():
                raise
            _fallback.record_resilience_event(
                "fallback", "serve_decode",
                action_detail=f"{rung}_to_{rungs[i + 1]}",
                error=type(e).__name__,
            )
    raise AssertionError("serve_decode ladder is empty")  # pragma: no cover


def _gather_ffa_decode(q_batch, cache, host_lengths, softmax_scale):
    """Per-slot gather+FFA decode: the reference rung. The new token sits
    at position ``length - 1`` (appended before attending), so the causal
    band covers exactly the stored rows."""
    S, hq, d = q_batch.shape
    dv = cache.v_pages.shape[-1]
    max_pages = cache.page_table.shape[1]
    outs, lses = [], []
    for s, length in enumerate(host_lengths):
        if length <= 0:
            outs.append(jnp.zeros((hq, dv), q_batch.dtype))
            lses.append(jnp.full((hq,), NEG_INF, jnp.float32))
            continue
        out, lse = paged_attn(
            q_batch[s : s + 1], cache, s,
            q_start=int(length) - 1,
            max_pages=max_pages,
            softmax_scale=softmax_scale,
        )
        outs.append(out[0])
        lses.append(lse[0])
    return jnp.stack(outs), jnp.stack(lses)


def _dense_decode(q_batch, cache, host_lengths, softmax_scale):
    """Masked dense softmax over the gathered pages — no Pallas anywhere."""
    S, hq, d = q_batch.shape
    dv = cache.v_pages.shape[-1]
    hk = cache.k_pages.shape[2]
    g = hq // hk
    if softmax_scale is None:
        softmax_scale = float(d) ** -0.5
    outs, lses = [], []
    for s, length in enumerate(host_lengths):
        if length <= 0:
            outs.append(jnp.zeros((hq, dv), q_batch.dtype))
            lses.append(jnp.full((hq,), NEG_INF, jnp.float32))
            continue
        k, v = gather_kv(cache, s)  # (cap, hk, d)
        k = k[:length].astype(jnp.float32)
        v = v[:length].astype(jnp.float32)
        q = q_batch[s].astype(jnp.float32)  # (hq, d)
        kh = jnp.repeat(k, g, axis=1)  # (length, hq, d)
        scores = jnp.einsum("hd,lhd->hl", q, kh) * softmax_scale
        m = jnp.max(scores, axis=1, keepdims=True)
        p = jnp.exp(scores - m)
        l = jnp.sum(p, axis=1, keepdims=True)
        vh = jnp.repeat(v, g, axis=1)
        out = jnp.einsum("hl,lhd->hd", p / l, vh)
        outs.append(out.astype(q_batch.dtype))
        lses.append((m[:, 0] + jnp.log(l[:, 0])).astype(jnp.float32))
    return jnp.stack(outs), jnp.stack(lses)
