"""Host-side KV page pool + cache slot lifecycle (serving layer).

The device cache is :class:`~..kernels.paged_kv.PagedKVCache` (functional,
jit-safe); this module owns the HOST bookkeeping around it: which pages are
free, how many a request needs, and resetting a slot's table row when a
request finishes or is evicted. Allocation order is deterministic (FIFO
free list), which is what makes slot reuse and eviction replayable in
tests.
"""

from __future__ import annotations

from collections import deque

from ..kernels.paged_kv import PagedKVCache
from ..resilience.errors import PageExhaustedError


def pages_needed(tokens: int, page_size: int) -> int:
    """Pages covering ``tokens`` rows (at least one: a slot's first token
    always needs a page)."""
    return max(1, -(-tokens // page_size))


class PagePool:
    """Deterministic FIFO free-list over the cache's page ids."""

    def __init__(self, num_pages: int) -> None:
        self._num_pages = num_pages
        self._free: deque[int] = deque(range(num_pages))

    @property
    def num_pages(self) -> int:
        return self._num_pages

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self._num_pages - len(self._free)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> list[int]:
        """Pop ``n`` page ids; raises :class:`PageExhaustedError` when the
        pool cannot cover them (callers decide whether to evict first)."""
        if n > len(self._free):
            raise PageExhaustedError(requested=n, free=len(self._free))
        return [self._free.popleft() for _ in range(n)]

    def release(self, page_ids: list[int]) -> None:
        self._free.extend(page_ids)


def release_slot(cache: PagedKVCache, slot: int) -> PagedKVCache:
    """Reset a slot on the device cache: table row back to -1 sentinels,
    length to 0 — so a reused slot can never read a predecessor's pages
    (the decode kernel masks on length; gather clamps -1 to page 0 whose
    rows the mask also kills)."""
    return PagedKVCache(
        cache.k_pages,
        cache.v_pages,
        cache.page_table.at[slot].set(-1),
        cache.lengths.at[slot].set(0),
    )
