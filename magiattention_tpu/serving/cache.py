"""Host-side KV page pool + cache slot lifecycle (serving layer).

The device cache is :class:`~..kernels.paged_kv.PagedKVCache` (functional,
jit-safe); this module owns the HOST bookkeeping around it: which pages are
free, how many a request needs, and resetting a slot's table row when a
request finishes or is evicted. Allocation order is deterministic (FIFO
free list), which is what makes slot reuse and eviction replayable in
tests.

Sharding: when the decode kernel is mesh-sharded over kv heads
(``paged_decode_attn_sharded``), every shard walks the same page ids — the
head axis, not the page axis, is split. The pool still partitions its page
ids into ``num_shards`` contiguous ranges with independent FIFO free lists
so the scheduler can route each slot to the shard with the most headroom
and keep per-shard HBM (each device materializes only its head slice of
the pages it touches) balanced. ``num_shards=1`` is the exact PR 8 pool.
"""

from __future__ import annotations

from collections import deque

import jax.numpy as jnp

from ..kernels.paged_kv import PagedKVCache
from ..resilience.errors import PageExhaustedError


def pages_needed(tokens: int, page_size: int) -> int:
    """Pages covering ``tokens`` rows (at least one: a slot's first token
    always needs a page)."""
    return max(1, -(-tokens // page_size))


def kv_page_bytes(
    page_size: int,
    n_kv_heads: int,
    head_dim: int,
    head_dim_v: int | None = None,
    kv_dtype: str = "float32",
) -> int:
    """HBM bytes one KV page costs under ``kv_dtype``, including the
    per-(page, head) f32 scales a quantized cache carries. This is the
    page-pool accounting behind the int8 residency claim: int8 pages cost
    ~1/2 of bf16 (~1/4 of f32), so the same HBM budget holds >=2x the
    slots."""
    dv = head_dim if head_dim_v is None else head_dim_v
    itemsize = {"int8": 1, "bfloat16": 2, "float16": 2, "float32": 4}[
        str(kv_dtype)
    ]
    nbytes = page_size * n_kv_heads * (head_dim + dv) * itemsize
    if kv_dtype == "int8":
        nbytes += 2 * n_kv_heads * 4  # k_scales + v_scales rows
    return nbytes


def slot_residency(
    hbm_budget_bytes: int, page_bytes: int, pages_per_slot: int
) -> int:
    """How many full slots (``pages_per_slot`` pages each) fit in an HBM
    budget — the denominator of the tokens/sec/chip lever int8 pulls."""
    return hbm_budget_bytes // (page_bytes * pages_per_slot)


class PagePool:
    """Deterministic FIFO free-list over the cache's page ids, partitioned
    into ``num_shards`` contiguous ranges (``num_shards=1`` = one list)."""

    def __init__(self, num_pages: int, num_shards: int = 1) -> None:
        if num_shards < 1 or num_pages % num_shards:
            raise ValueError(
                f"num_pages={num_pages} must split evenly over "
                f"num_shards={num_shards}"
            )
        self._num_pages = num_pages
        self._num_shards = num_shards
        self._per_shard = num_pages // num_shards
        self._free: list[deque[int]] = [
            deque(range(s * self._per_shard, (s + 1) * self._per_shard))
            for s in range(num_shards)
        ]

    @property
    def num_pages(self) -> int:
        return self._num_pages

    @property
    def num_shards(self) -> int:
        return self._num_shards

    @property
    def free_count(self) -> int:
        return sum(len(f) for f in self._free)

    @property
    def used_count(self) -> int:
        return self._num_pages - self.free_count

    def shard_of(self, page_id: int) -> int:
        """Which shard range a page id belongs to (release routing)."""
        return page_id // self._per_shard

    def free_count_shard(self, shard: int) -> int:
        return len(self._free[shard])

    def best_shard(self, n: int) -> int | None:
        """Shard with the most free pages that can cover ``n`` (ties go to
        the lowest id — deterministic routing); None if no single shard
        can. A slot's pages all live on one shard, so admission is
        per-shard even though the aggregate pool might cover ``n``."""
        best, best_free = None, -1
        for s in range(self._num_shards):
            free = len(self._free[s])
            if free >= n and free > best_free:
                best, best_free = s, free
        return best

    def can_alloc(self, n: int, shard: int = 0) -> bool:
        return n <= len(self._free[shard])

    def alloc(self, n: int, shard: int = 0) -> list[int]:
        """Pop ``n`` page ids from one shard's range; raises
        :class:`PageExhaustedError` when that shard cannot cover them
        (callers decide whether to evict first)."""
        free = self._free[shard]
        if n > len(free):
            raise PageExhaustedError(requested=n, free=len(free))
        return [free.popleft() for _ in range(n)]

    def release(self, page_ids: list[int]) -> None:
        for pid in page_ids:
            self._free[self.shard_of(pid)].append(pid)


def reset_page_scales(
    cache: PagedKVCache, page_ids: list[int]
) -> PagedKVCache:
    """Zero the quantization scales of released pages so a reused page
    quantizes exactly like a fresh one (scale growth is monotone within a
    page's lifetime; without the reset, a predecessor's larger scale would
    leak into the successor's codes and break the bitwise replay oracle).
    No-op on float caches."""
    if not cache.quantized or not page_ids:
        return cache
    idx = jnp.asarray(page_ids, jnp.int32)
    return PagedKVCache(
        cache.k_pages,
        cache.v_pages,
        cache.page_table,
        cache.lengths,
        cache.k_scales.at[idx].set(0.0),
        cache.v_scales.at[idx].set(0.0),
    )


def release_slot(cache: PagedKVCache, slot: int) -> PagedKVCache:
    """Reset a slot on the device cache: table row back to -1 sentinels,
    length to 0 — so a reused slot can never read a predecessor's pages
    (the decode kernel masks on length; gather clamps -1 to page 0 whose
    rows the mask also kills)."""
    return PagedKVCache(
        cache.k_pages,
        cache.v_pages,
        cache.page_table.at[slot].set(-1),
        cache.lengths.at[slot].set(0),
        cache.k_scales,
        cache.v_scales,
    )
