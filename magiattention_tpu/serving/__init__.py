"""Serving runtime: ragged paged-decode FFA + continuous batching.

Layers (docs/serving.md, docs/serving_scale.md):

- :mod:`.model` — the minimal deterministic model interface the engine
  drives (q/k/v projection, output projection, autoregressive closure,
  greedy self-draft for speculative decode);
- :mod:`.cache` — host page pool (optionally partitioned into shards) +
  slot lifecycle + residency accounting over the device-side
  :class:`~..kernels.paged_kv.PagedKVCache` (f32 or int8+scales);
- :mod:`.prefill` — chunked prompt ingestion through the existing FFA;
- :mod:`.decode` — batched decode/verify attention with the registry
  fallback ladder (sharded / speculative / int8 / base Pallas kernels →
  gather+FFA → dense softmax);
- :mod:`.scheduler` — FIFO admission with shard routing, lazy page
  growth, LIFO eviction with restart semantics under the page budget,
  page-level rollback shrink;
- :mod:`.engine` — the continuous-batching tick loop (one token or a
  spec_tokens draft window per tick) + telemetry;
- :mod:`.reference` — sequential replay oracle for bitwise equality.
"""

from .cache import (  # noqa: F401
    PagePool,
    kv_page_bytes,
    pages_needed,
    release_slot,
    reset_page_scales,
    slot_residency,
)
from .decode import decode_attn_step, verify_attn_step  # noqa: F401
from .engine import ServeConfig, ServeEngine  # noqa: F401
from .model import ToyModel  # noqa: F401
from .prefill import prefill_request, prefill_schedule  # noqa: F401
from .reference import (  # noqa: F401
    generate_reference,
    oracle_draft_fn,
    run_reference,
)
from .scheduler import Scheduler, ServeRequest  # noqa: F401

__all__ = [
    "PagePool",
    "Scheduler",
    "ServeConfig",
    "ServeEngine",
    "ServeRequest",
    "ToyModel",
    "decode_attn_step",
    "generate_reference",
    "kv_page_bytes",
    "oracle_draft_fn",
    "pages_needed",
    "prefill_request",
    "prefill_schedule",
    "release_slot",
    "reset_page_scales",
    "run_reference",
    "slot_residency",
    "verify_attn_step",
]
