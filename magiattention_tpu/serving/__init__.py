"""Serving runtime: ragged paged-decode FFA + continuous batching.

Layers (docs/serving.md):

- :mod:`.model` — the minimal deterministic model interface the engine
  drives (q/k/v projection, output projection, autoregressive closure);
- :mod:`.cache` — host page pool + slot lifecycle over the device-side
  :class:`~..kernels.paged_kv.PagedKVCache`;
- :mod:`.prefill` — chunked prompt ingestion through the existing FFA;
- :mod:`.decode` — batched decode attention with the three-rung fallback
  ladder (Pallas paged-decode kernel → gather+FFA → dense softmax);
- :mod:`.scheduler` — FIFO admission, lazy page growth, LIFO eviction
  with restart semantics under the page budget;
- :mod:`.engine` — the continuous-batching tick loop + telemetry;
- :mod:`.reference` — sequential replay oracle for bitwise equality.
"""

from .cache import PagePool, pages_needed, release_slot  # noqa: F401
from .decode import decode_attn_step  # noqa: F401
from .engine import ServeConfig, ServeEngine  # noqa: F401
from .model import ToyModel  # noqa: F401
from .prefill import prefill_request, prefill_schedule  # noqa: F401
from .reference import generate_reference, run_reference  # noqa: F401
from .scheduler import Scheduler, ServeRequest  # noqa: F401

__all__ = [
    "PagePool",
    "Scheduler",
    "ServeConfig",
    "ServeEngine",
    "ServeRequest",
    "ToyModel",
    "decode_attn_step",
    "generate_reference",
    "pages_needed",
    "prefill_request",
    "prefill_schedule",
    "release_slot",
    "run_reference",
]
