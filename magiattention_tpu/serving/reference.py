"""Sequential per-request replay — the engine's equality oracle.

Each request runs alone against a fresh single-sequence cache with every
page pre-assigned, using the SAME :func:`~.prefill.prefill_request` chunk
schedule and the SAME gather+FFA decode call the engine's reference rung
makes. Per-row FFA results depend only on the unmasked rows (masked scores
are the MASK_VALUE constant regardless of what garbage the gathered pages
hold, and their exp2 contributions underflow to exactly 0.0), so with an
identical chunk schedule, ``max_pages`` and env snapshot, the engine under
``MAGI_ATTENTION_SERVE_DECODE_KERNEL=0`` must reproduce this replay
BITWISE — the serve-smoke acceptance gate.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..kernels.paged_kv import PagedKVCache, append_kv, assign_pages, paged_attn
from .engine import ServeConfig
from .model import ToyModel
from .prefill import prefill_request
from .scheduler import ServeRequest


def generate_reference(
    model: ToyModel, req: ServeRequest, config: ServeConfig
) -> list[np.ndarray]:
    """Generate ``req``'s tokens in isolation; returns the per-step hidden
    rows (same objects the engine stores in ``req.generated``)."""
    P = config.max_pages_per_seq
    cache = PagedKVCache.create(
        num_pages=P,
        page_size=config.page_size,
        n_kv_heads=model.n_kv_heads,
        head_dim=model.head_dim,
        max_seqs=1,
        max_pages_per_seq=P,
        dtype=jnp.float32,
    )
    cache = assign_pages(cache, 0, np.arange(P, dtype=np.int32))

    cache, last_hidden = prefill_request(
        model, cache, 0, req.prompt, config.prefill_chunk,
        config.softmax_scale,
    )
    length = req.prompt_len
    x = model.next_input(last_hidden)

    outs: list[np.ndarray] = []
    for _ in range(req.max_new_tokens):
        q, k, v = model.qkv(x[None])
        cache = append_kv(cache, 0, k, v)
        length += 1
        out, _ = paged_attn(
            q, cache, 0,
            q_start=length - 1,
            max_pages=P,
            softmax_scale=config.softmax_scale,
        )
        hidden = model.project(out)[0]
        outs.append(np.asarray(hidden))
        x = model.next_input(hidden)
    return outs


def run_reference(
    model: ToyModel, requests: list[ServeRequest], config: ServeConfig
) -> dict[int, list[np.ndarray]]:
    """Replay every request sequentially; keyed by ``req_id``."""
    return {
        req.req_id: generate_reference(model, req, config)
        for req in requests
    }
