"""Sequential per-request replay — the engine's equality oracle.

Each request runs alone against a fresh single-sequence cache with every
page pre-assigned, using the SAME :func:`~.prefill.prefill_request` chunk
schedule and the SAME gather+FFA decode call the engine's reference rung
makes. Per-row FFA results depend only on the unmasked rows (masked scores
are the MASK_VALUE constant regardless of what garbage the gathered pages
hold, and their exp2 contributions underflow to exactly 0.0), so with an
identical chunk schedule, ``max_pages`` and env snapshot, the engine under
``MAGI_ATTENTION_SERVE_DECODE_KERNEL=0`` must reproduce this replay
BITWISE — the serve-smoke acceptance gate.

This one-token-per-tick replay is ALSO the oracle for the speculative
engine (``spec_tokens`` k > 1): a verify row attends its own causal
prefix, so whenever a row's draft input chain is correct its output is the
exact sequential output — the same masked-row invariance as above makes
the multi-row gather+FFA call bitwise-equal to issuing its rows
sequentially. Commits (the longest accepted prefix) are therefore bitwise
prefixes of this replay regardless of where rejection lands, and rollback
only ever discards rows the oracle never produced.

The int8 story is the same with one extra ingredient: quantized append is
a pure function of a page's append history (monotone per-page scales,
reset on release), so an int8 engine pinned to the gather rung is bitwise
vs an int8 oracle (``config.kv_dtype='int8'`` here), while int8-vs-f32 is
a tolerance comparison (the quantization error itself).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..kernels.paged_kv import PagedKVCache, append_kv, assign_pages, paged_attn
from .engine import DraftFn, ServeConfig
from .model import ToyModel
from .prefill import prefill_request
from .scheduler import ServeRequest


def generate_reference(
    model: ToyModel, req: ServeRequest, config: ServeConfig
) -> list[np.ndarray]:
    """Generate ``req``'s tokens in isolation; returns the per-step hidden
    rows (same objects the engine stores in ``req.generated``)."""
    P = config.max_pages_per_seq
    cache = PagedKVCache.create(
        num_pages=P,
        page_size=config.page_size,
        n_kv_heads=model.n_kv_heads,
        head_dim=model.head_dim,
        max_seqs=1,
        max_pages_per_seq=P,
        dtype=jnp.int8 if config.kv_dtype == "int8" else jnp.float32,
    )
    cache = assign_pages(cache, 0, np.arange(P, dtype=np.int32))

    cache, last_hidden = prefill_request(
        model, cache, 0, req.prompt, config.prefill_chunk,
        config.softmax_scale,
    )
    length = req.prompt_len
    x = model.next_input(last_hidden)

    outs: list[np.ndarray] = []
    for _ in range(req.max_new_tokens):
        q, k, v = model.qkv(x[None])
        cache = append_kv(cache, 0, k, v)
        length += 1
        out, _ = paged_attn(
            q, cache, 0,
            q_start=length - 1,
            max_pages=P,
            softmax_scale=config.softmax_scale,
        )
        hidden = model.project(out)[0]
        outs.append(np.asarray(hidden))
        x = model.next_input(hidden)
    return outs


def run_reference(
    model: ToyModel, requests: list[ServeRequest], config: ServeConfig
) -> dict[int, list[np.ndarray]]:
    """Replay every request sequentially; keyed by ``req_id``."""
    return {
        req.req_id: generate_reference(model, req, config)
        for req in requests
    }


def oracle_draft_fn(
    ref_outputs: dict[int, list[np.ndarray]]
) -> DraftFn:
    """A draft function that drafts the TRUE next inputs (from a completed
    :func:`run_reference` replay), so the speculative engine's verify
    accepts every row — the full-accept end of the accept/rollback
    spectrum, used by tests and serve-smoke to pin accept_rate == 1.
    Positions past the replay fall back to the model's greedy draft."""

    def draft(model: ToyModel, req: ServeRequest, x, j: int):
        # draft j's input is next_input(hidden_{n+j-1}) where n tokens are
        # committed so far (draft 0 == pending_x == next_input(hidden_{n-1}))
        idx = len(req.generated) + j - 1
        hiddens = ref_outputs.get(req.req_id, [])
        if 0 <= idx < len(hiddens):
            return model.next_input(jnp.asarray(hiddens[idx]))
        return model.draft_next(x)

    return draft
