"""Forward-pass side outputs (ref: magi_attention/common/forward_meta.py:21)."""

from dataclasses import dataclass
from typing import Any


@dataclass
class AttnForwardMeta:
    """Side outputs returned by every attention call.

    Attributes:
        lse: log-sum-exp of attention logits, shape ``[seqlen_q, num_heads]``
            (fp32), or None when not requested.
        max_logits: per-head max attention logit (fp32), or None when not
            requested.
    """

    lse: Any = None
    max_logits: Any = None
