"""AttnRectangle(s) — (q_range, k_range, d_range) geometry.

Ref: magi_attention/common/rectangle.py:28-564, rectangles.py:29-309 — the
planning unit of the *dynamic* (qo-comm) solver. A rectangle is a q x k box
with a diagonal band ``d_range = [d_lo, d_hi]`` (closed, in ``j - i``
coordinates); identical to the band-slice encoding the kernels use
(kernels/mask_utils), so converting between the two is free.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..kernels.mask_utils import BAND_INF
from .enum import AttnMaskType
from .range import AttnRange


@dataclass
class AttnRectangle:
    q_range: AttnRange
    k_range: AttnRange
    d_lo: int = -BAND_INF
    d_hi: int = BAND_INF

    @classmethod
    def from_mask_type(
        cls, q_range: AttnRange, k_range: AttnRange, mask_type: AttnMaskType
    ) -> "AttnRectangle":
        d_hi = (
            k_range.end - q_range.end
            if mask_type in (AttnMaskType.CAUSAL, AttnMaskType.BICAUSAL)
            else BAND_INF
        )
        d_lo = (
            k_range.start - q_range.start
            if mask_type in (AttnMaskType.INVCAUSAL, AttnMaskType.BICAUSAL)
            else -BAND_INF
        )
        return cls(q_range, k_range, d_lo, d_hi).shrink()

    # -- geometry ----------------------------------------------------------

    def shrink(self) -> "AttnRectangle":
        """Tighten q/k ranges and d bounds to the actual footprint
        (ref rectangle.py shrink_d/q/k_range)."""
        qs, qe = self.q_range.start, self.q_range.end
        ks, ke = self.k_range.start, self.k_range.end
        if qs >= qe or ks >= ke or self.d_lo > self.d_hi:
            return AttnRectangle(AttnRange(qs, qs), AttnRange(ks, ks), 0, -1)
        # d range implied by the box corners
        lo = max(self.d_lo, ks - (qe - 1))
        hi = min(self.d_hi, (ke - 1) - qs)
        if lo > hi:
            return AttnRectangle(AttnRange(qs, qs), AttnRange(ks, ks), 0, -1)
        # k bounds implied by band over q rows
        k_min = max(ks, qs + lo)
        k_max = min(ke, (qe - 1) + hi + 1)
        # q bounds implied by band over k cols
        q_min = max(qs, k_min - hi)
        q_max = min(qe, (k_max - 1) - lo + 1)
        return AttnRectangle(
            AttnRange(q_min, q_max), AttnRange(k_min, k_max), lo, hi
        )

    def is_empty(self) -> bool:
        r = self.shrink()
        return r.q_range.is_empty() or r.k_range.is_empty() or r.d_lo > r.d_hi

    def area(self) -> int:
        from ..meta.container.slice import band_area

        return band_area(
            self.q_range.start, self.q_range.end,
            self.k_range.start, self.k_range.end,
            self.d_lo, self.d_hi,
        )

    def cut_q(self, pos: int) -> tuple["AttnRectangle", "AttnRectangle"]:
        """Split at q == pos into (top, bottom), both shrunk (ref cut_q)."""
        top = AttnRectangle(
            self.q_range.truncate(end=pos), self.k_range, self.d_lo, self.d_hi
        ).shrink()
        bot = AttnRectangle(
            self.q_range.truncate(start=pos), self.k_range, self.d_lo, self.d_hi
        ).shrink()
        return top, bot

    def cut_k(self, pos: int) -> tuple["AttnRectangle", "AttnRectangle"]:
        """Split at k == pos into (left, right), both shrunk (ref cut_k)."""
        left = AttnRectangle(
            self.q_range, self.k_range.truncate(end=pos), self.d_lo, self.d_hi
        ).shrink()
        right = AttnRectangle(
            self.q_range, self.k_range.truncate(start=pos), self.d_lo, self.d_hi
        ).shrink()
        return left, right


@dataclass
class AttnRectangles:
    """A list of rectangles with bulk geometry ops (ref rectangles.py)."""

    rects: list[AttnRectangle] = field(default_factory=list)

    @classmethod
    def from_ranges(cls, q_ranges, k_ranges, attn_mask_type) -> "AttnRectangles":
        out = cls()
        for qr, kr, mt in zip(q_ranges, k_ranges, attn_mask_type):
            r = AttnRectangle.from_mask_type(qr, kr, AttnMaskType.normalize(mt))
            if not r.is_empty():
                out.rects.append(r)
        return out

    def append(self, r: AttnRectangle) -> None:
        self.rects.append(r)

    def extend(self, other: "AttnRectangles") -> None:
        self.rects.extend(other.rects)

    def area(self) -> int:
        return sum(r.area() for r in self.rects)

    def count(self) -> int:
        return len(self.rects)

    def cut_q(self, pos: int) -> tuple["AttnRectangles", "AttnRectangles"]:
        top, bot = AttnRectangles(), AttnRectangles()
        for r in self.rects:
            t, b = r.cut_q(pos)
            if not t.is_empty():
                top.append(t)
            if not b.is_empty():
                bot.append(b)
        return top, bot

    def cut_k(self, pos: int) -> tuple["AttnRectangles", "AttnRectangles"]:
        left, right = AttnRectangles(), AttnRectangles()
        for r in self.rects:
            lft, rgt = r.cut_k(pos)
            if not lft.is_empty():
                left.append(lft)
            if not rgt.is_empty():
                right.append(rgt)
        return left, right

    def __iter__(self):
        return iter(self.rects)

    def __len__(self) -> int:
        return len(self.rects)
