"""Materializable 2-D attention mask built from slice metadata.

Testing / solver aid (ref: magi_attention/common/mask.py:29-472). Materializes
the boolean mask implied by ``(q_ranges, k_ranges, attn_mask_type)`` on the
host with numpy; never used on the device path.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from .enum import AttnMaskType
from .range import AttnRange
from .ranges import AttnRanges


def make_causal_mask(
    seqlen_q: int, seqlen_k: int, align: str = "bottom-right", dtype=np.bool_
) -> np.ndarray:
    """Tril mask aligned to the requested corner of the (seqlen_q, seqlen_k) box."""
    m = max(seqlen_q, seqlen_k)
    tril = np.tril(np.ones((m, m), dtype=dtype))
    if align == "bottom-right":
        return tril[m - seqlen_q :, m - seqlen_k :]
    elif align == "top-left":
        return tril[:seqlen_q, :seqlen_k]
    raise ValueError(f"invalid alignment mode: {align}")


def slice_mask_block(
    q_range: AttnRange, k_range: AttnRange, mask_type: AttnMaskType
) -> np.ndarray:
    """The (q_range.seqlen, k_range.seqlen) boolean mask of one slice.

    Geometry (d = j - i in global coords):
      CAUSAL:    j - i <= k_range.end - q_range.end     (bottom-right aligned)
      INVCAUSAL: j - i >= k_range.start - q_range.start (top-left aligned)
      BICAUSAL:  both
    """
    sq, sk = q_range.seqlen, k_range.seqlen
    i = np.arange(q_range.start, q_range.end)[:, None]
    j = np.arange(k_range.start, k_range.end)[None, :]
    d = j - i
    if mask_type == AttnMaskType.FULL:
        return np.ones((sq, sk), dtype=np.bool_)
    if mask_type == AttnMaskType.CAUSAL:
        return d <= (k_range.end - q_range.end)
    if mask_type == AttnMaskType.INVCAUSAL:
        return d >= (k_range.start - q_range.start)
    if mask_type == AttnMaskType.BICAUSAL:
        return (d <= (k_range.end - q_range.end)) & (
            d >= (k_range.start - q_range.start)
        )
    raise ValueError(f"invalid mask type: {mask_type}")


def slice_area(q_range: AttnRange, k_range: AttnRange, mask_type: AttnMaskType) -> int:
    """Number of unmasked (q, k) pairs of one slice, in closed form."""
    sq, sk = q_range.seqlen, k_range.seqlen
    if sq == 0 or sk == 0:
        return 0
    if mask_type == AttnMaskType.FULL:
        return sq * sk

    def tri_causal(sq: int, sk: int) -> int:
        # bottom-right aligned causal area
        if sk >= sq:
            return sq * sk - sq * (sq - 1) // 2
        # top rows of the box are fully masked
        return sk * (sk + 1) // 2

    if mask_type == AttnMaskType.CAUSAL:
        return tri_causal(sq, sk)
    if mask_type == AttnMaskType.INVCAUSAL:
        # top-left aligned inv-causal == transpose-symmetric of causal
        return tri_causal(sq, sk)
    if mask_type == AttnMaskType.BICAUSAL:
        # band: rows each see [row_lo, row_hi] where width = sk - sq + 1 if sk>=sq
        if sk >= sq:
            return sq * (sk - sq + 1)
        return 0  # d_range empty: no valid band
    raise ValueError(f"invalid mask type: {mask_type}")


class AttnMask:
    """A materialized attention mask with slice metadata attached."""

    def __init__(
        self,
        mask_array: np.ndarray,
        q_ranges: AttnRanges,
        k_ranges: AttnRanges,
        attn_mask_type: list[AttnMaskType],
        total_seqlen_q: int,
        total_seqlen_k: int,
    ) -> None:
        self.mask_array = mask_array
        self.q_ranges = q_ranges
        self.k_ranges = k_ranges
        self.attn_mask_type = attn_mask_type
        self.total_seqlen_q = total_seqlen_q
        self.total_seqlen_k = total_seqlen_k

    @classmethod
    def from_ranges(
        cls,
        q_ranges: AttnRanges,
        k_ranges: AttnRanges,
        attn_mask_type: Sequence[AttnMaskType | str | int],
        total_seqlen_q: int | None = None,
        total_seqlen_k: int | None = None,
    ) -> "AttnMask":
        if not (len(q_ranges) == len(k_ranges) == len(attn_mask_type)):
            raise ValueError(
                f"length mismatch: {len(q_ranges)=} {len(k_ranges)=} "
                f"{len(attn_mask_type)=}"
            )
        mask_types = [AttnMaskType.normalize(t) for t in attn_mask_type]
        tq = total_seqlen_q if total_seqlen_q is not None else q_ranges.end
        tk = total_seqlen_k if total_seqlen_k is not None else k_ranges.end
        mask = np.zeros((tq, tk), dtype=np.bool_)
        for qr, kr, mt in zip(q_ranges, k_ranges, mask_types):
            mask[qr.start : qr.end, kr.start : kr.end] |= slice_mask_block(qr, kr, mt)
        return cls(mask, q_ranges, k_ranges, mask_types, tq, tk)

    @property
    def area(self) -> int:
        return int(self.mask_array.sum())

    def make_sub_mask(self, q_range: AttnRange, k_range: AttnRange) -> np.ndarray:
        return self.mask_array[q_range.start : q_range.end, k_range.start : k_range.end]

    def is_pure_causal(self) -> bool:
        expected = make_causal_mask(self.total_seqlen_q, self.total_seqlen_k)
        return bool((self.mask_array == expected).all())

    def is_empty(self) -> bool:
        return not self.mask_array.any()

    def __eq__(self, other) -> bool:
        if isinstance(other, AttnMask):
            return (
                self.mask_array.shape == other.mask_array.shape
                and bool((self.mask_array == other.mask_array).all())
            )
        return NotImplemented

    def __repr__(self) -> str:
        return (
            f"AttnMask(q={self.total_seqlen_q}, k={self.total_seqlen_k}, "
            f"area={self.area}, n_slices={len(self.q_ranges)})"
        )

    def visualize(
        self,
        path: str | None = None,
        max_cells: int = 64,
        rank_of_row: np.ndarray | None = None,
    ) -> str:
        """Render the mask (ref common/mask.py:430 AttnMask.visualize).

        Returns an ASCII rendering downsampled to at most ``max_cells`` per
        side; when ``path`` is given, additionally writes a PNG (matplotlib,
        best-effort). ``rank_of_row`` (optional, (total_seqlen_q,) int) tints
        rows by owning CP rank — the dispatch-assignment view (ref
        dynamic_solver_vis.py).
        """
        m = self.mask_array
        sq, sk = m.shape
        fq = max(1, -(-sq // max_cells))
        fk = max(1, -(-sk // max_cells))
        nq, nk = -(-sq // fq), -(-sk // fk)
        pad = np.zeros((nq * fq, nk * fk), dtype=np.float32)
        pad[:sq, :sk] = m
        cells = pad.reshape(nq, fq, nk, fk).mean(axis=(1, 3))
        shades = " .:#"
        lines = []
        for i in range(nq):
            row = "".join(
                shades[min(int(c * (len(shades) - 1) + 0.999), len(shades) - 1)]
                for c in cells[i]
            )
            if rank_of_row is not None:
                r = int(rank_of_row[min(i * fq, sq - 1)])
                row += f"  r{r}"
            lines.append(row)
        text = "\n".join(lines)
        if path is not None:
            try:  # pragma: no cover - depends on matplotlib backend
                import matplotlib

                matplotlib.use("Agg")
                import matplotlib.pyplot as plt

                fig, ax = plt.subplots(figsize=(6, 6))
                if rank_of_row is not None:
                    img = np.where(
                        m,
                        rank_of_row[:, None].astype(np.float32) + 1.0,
                        np.nan,
                    )
                    ax.imshow(img, aspect="auto", interpolation="nearest",
                              cmap="tab20")
                else:
                    ax.imshow(m, aspect="auto", interpolation="nearest",
                              cmap="Greys")
                ax.set_xlabel("k")
                ax.set_ylabel("q")
                ax.set_title(repr(self))
                fig.savefig(path, dpi=120, bbox_inches="tight")
                plt.close(fig)
            except Exception:
                pass
        return text
