"""Common host-side data structures.

When the C++ host backend is built and ``MAGI_ATTENTION_CPP_BACKEND=1``
(default), ``AttnRange``/``AttnRanges`` resolve to the native implementations
(ref: magi_attention/common/__init__.py:17-34); otherwise the pure-Python
implementations are used. Both conform to ``common.protocols``.
"""

from .enum import AttnMaskType, AttnRole, AttnType  # noqa: F401
from .forward_meta import AttnForwardMeta  # noqa: F401
from .mask import AttnMask  # noqa: F401

from .range import AttnRange as _PyAttnRange
from .ranges import AttnRanges as _PyAttnRanges

AttnRange = _PyAttnRange
AttnRanges = _PyAttnRanges

from .. import env as _env  # noqa: E402

if _env.general.is_cpp_backend_enable():  # pragma: no branch
    try:
        from ..csrc_backend import CppAttnRange, CppAttnRanges  # noqa: F401

        AttnRange = CppAttnRange  # type: ignore[misc]
        AttnRanges = CppAttnRanges  # type: ignore[misc]
    except ImportError:
        pass

__all__ = [
    "AttnForwardMeta",
    "AttnMask",
    "AttnMaskType",
    "AttnRange",
    "AttnRanges",
    "AttnRole",
    "AttnType",
]
