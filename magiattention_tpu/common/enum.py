"""Core enums for magiattention_tpu.

TPU-native re-design of the reference's enum surface
(ref: magi_attention/common/enum.py:42-176). Integer codes for
``AttnMaskType`` match the reference kernel contract
(0=FULL, 1=CAUSAL, 2=INVCAUSAL, 3=BICAUSAL) so slice metadata arrays are
interchangeable.
"""

from enum import Enum
from typing import Literal, TypeAlias

GroupReduceOp: TypeAlias = Literal["sum", "avg", "lse"]

AttnSinkLayout: TypeAlias = Literal["sh", "shd", "ssh"]


class AttnType(Enum):
    """Type of attention computation."""

    SELF_ATTN = "self_attn"
    CROSS_ATTN = "cross_attn"


class AttnRole(Enum):
    """Tensor role in attention."""

    QUERY = "query"
    KEY = "key"
    VALUE = "value"


class AttnMaskType(Enum):
    """Unit mask type of an attention slice.

    Semantics over a slice ``(q_range=[qs,qe), k_range=[ks,ke))`` for global
    coordinates ``(i, j)``:

    - ``FULL``:      all pairs in the rectangle are unmasked.
    - ``CAUSAL``:    bottom-right aligned lower-triangle: ``j - i <= ke - qe``.
    - ``INVCAUSAL``: top-left aligned upper-triangle:     ``j - i >= ks - qs``.
    - ``BICAUSAL``:  both constraints (a diagonal band).
    """

    FULL = "full"
    CAUSAL = "causal"
    BICAUSAL = "bi_causal"
    INVCAUSAL = "inv_causal"

    @classmethod
    def from_int_type(cls, int_type: int) -> "AttnMaskType":
        return _INT_TO_MASK_TYPE[int_type]

    def to_int_type(self) -> int:
        return _MASK_TYPE_TO_INT[self]

    @classmethod
    def normalize(
        cls, value: "AttnMaskType | str | int"
    ) -> "AttnMaskType":
        """Accept enum / str / int forms uniformly (incl. numpy integer
        scalars — mask metadata routinely arrives as np.int32 arrays)."""
        if isinstance(value, cls):
            return value
        if isinstance(value, int) or (
            hasattr(value, "__index__") and not isinstance(value, str)
        ):
            return cls.from_int_type(int(value))
        return cls(value)


_INT_TO_MASK_TYPE = {
    0: AttnMaskType.FULL,
    1: AttnMaskType.CAUSAL,
    2: AttnMaskType.INVCAUSAL,
    3: AttnMaskType.BICAUSAL,
}
_MASK_TYPE_TO_INT = {v: k for k, v in _INT_TO_MASK_TYPE.items()}


class AttnOverlapMode(Enum):
    """Overlap mode for multi-stage compute/comm overlapping."""

    STATIC = "static"
    DYNAMIC = "dynamic"


class DispatchAlgType(Enum):
    """Algorithm for load-balanced chunk->rank dispatching."""

    # AUTO is this build's addition (no reference analogue): solve with a
    # small candidate set and pick by a modeled compute/comm trade-off —
    # see meta/_make_dispatch_meta.py:_auto_select_partitions
    AUTO = "auto"
    LOWER_BOUND = "lower_bound"
    DYNAMIC_PROGRAMMING = "dynamic_programming"
    BINARY_SEARCH = "binary_search"
    MIN_HEAP = "min_heap"
    TOPP_HEAP = "topp_heap"
    BACKTRACKING_PRUNING = "backtracing_pruning"
    RANDOM_SELECT = "random_select"
    SEQUENTIAL_SELECT = "sequential_select"
    BATCH_TOPP_HEAP = "batch_topp_heap"
    SORTED_SEQUENTIAL_SELECT = "sorted_sequential_select"


class OverlapAlgType(Enum):
    """Algorithm for multi-stage overlap planning."""

    UNIFORM = "uniform"
    GREEDY = "greedy"


class DynamicAttnAlgType(Enum):
    """Algorithm for the dynamic (qo-comm) attention solver."""

    NON_COMMUNICATION_QO = "ncq"
    GREEDY_RANDOM_GRID = "grg"
    SIMPLEX_NETWORK_FLOW = "snf"
    FAST_SNF = "fast_snf"
    BINARY_GREEDY = "binary_greedy"
    BINARY_GREEDY_PARALLEL = "binary_greedy_parallel"


class AttnKernelBackend(Enum):
    """Which attention kernel backend executes an AttnArg.

    - ``FFA``: the Pallas-TPU flex-flash-attention kernel (production path).
    - ``SDPA``: dense jnp reference backend, fp32/fp64 (testing path).
    - ``SDPA_ONLINE``: blockwise-online jnp backend (low-memory testing path).
    """

    FFA = "ffa"
    SDPA = "sdpa"
    SDPA_ONLINE = "sdpa_online"


class AttnPrecision(Enum):
    """Precision override for attention compute."""

    DEFAULT = "default"
    FP32 = "fp32"
    BF16 = "bf16"
