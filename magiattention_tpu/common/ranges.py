"""Ordered list-of-ranges algebra for attention planning.

Host-side planning primitive (ref: magi_attention/common/ranges.py:101-924).
``AttnRanges`` is the workhorse of the dispatch / dist-attn solvers: a mutable
sequence of :class:`AttnRange` with sort / merge / chunk / coordinate-remap
operations. Pure Python, no JAX dependency.
"""

from __future__ import annotations

import bisect
from typing import Any, Iterable, Iterator, Sequence

import numpy as np

from .range import AttnRange, RangeError


class AttnRanges:
    """A list of half-open ranges with planning algebra."""

    def __init__(self, ranges: Iterable[AttnRange] | None = None) -> None:
        self._ranges: list[AttnRange] = list(ranges) if ranges is not None else []

    # -- constructors ------------------------------------------------------

    @classmethod
    def from_ranges(
        cls, ranges: Sequence[Sequence[int]] | Sequence[AttnRange], check: bool = False
    ) -> "AttnRanges":
        out = cls()
        for r in ranges:
            if isinstance(r, AttnRange):
                out.append(AttnRange.from_range(r))
            else:
                out.append(AttnRange(r[0], r[1]))
        if check and not out.is_valid():
            raise RangeError(f"invalid ranges: {out}")
        return out

    @classmethod
    def from_cu_seqlens(cls, cu_seqlens: Sequence[int], seq_len: int | None = None) -> "AttnRanges":
        """Build contiguous ranges from a cumulative-seqlen array."""
        if len(cu_seqlens) == 0:
            return cls()
        if cu_seqlens[0] != 0:
            raise RangeError(f"cu_seqlens must start at 0, got {cu_seqlens[0]}")
        if seq_len is not None and cu_seqlens[-1] != seq_len:
            raise RangeError(
                f"cu_seqlens must end at seq_len={seq_len}, got {cu_seqlens[-1]}"
            )
        return cls.from_ranges(
            [(cu_seqlens[i], cu_seqlens[i + 1]) for i in range(len(cu_seqlens) - 1)]
        )

    # -- container protocol ------------------------------------------------

    def append(self, r: AttnRange, check: bool = False) -> None:
        if check and not r.is_valid():
            raise RangeError(f"invalid range {r}")
        self._ranges.append(r)

    def extend(self, other: "AttnRanges", check: bool = False) -> None:
        for r in other:
            self.append(r, check=check)

    def insert(self, idx: int, r: AttnRange) -> None:
        self._ranges.insert(idx, r)

    def pop(self, idx: int = -1) -> AttnRange:
        return self._ranges.pop(idx)

    def clear(self) -> None:
        self._ranges.clear()

    def __len__(self) -> int:
        return len(self._ranges)

    def __iter__(self) -> Iterator[AttnRange]:
        return iter(self._ranges)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return AttnRanges(self._ranges[idx])
        return self._ranges[idx]

    def __setitem__(self, idx: int, value: AttnRange) -> None:
        self._ranges[idx] = value

    def __eq__(self, other: Any) -> bool:
        if isinstance(other, AttnRanges):
            return self._ranges == other._ranges
        return NotImplemented

    def __hash__(self) -> int:
        return hash(tuple(self._ranges))

    def __repr__(self) -> str:
        return f"AttnRanges({self._ranges})"

    # -- properties --------------------------------------------------------

    @property
    def start(self) -> int:
        """Min start over all non-empty ranges."""
        starts = [r.start for r in self._ranges if not r.is_empty()]
        if not starts:
            return 0
        return min(starts)

    @property
    def end(self) -> int:
        """Max end over all ranges."""
        if not self._ranges:
            return 0
        return max(r.end for r in self._ranges)

    @property
    def total_seqlen(self) -> int:
        """Sum of range lengths (NOT deduplicated)."""
        return sum(r.seqlen for r in self._ranges)

    @property
    def max_seqlen(self) -> int:
        if not self._ranges:
            return 0
        return max(r.seqlen for r in self._ranges)

    def is_empty(self) -> bool:
        return all(r.is_empty() for r in self._ranges)

    def is_valid(self) -> bool:
        return all(r.is_valid() for r in self._ranges)

    def is_sorted(self) -> bool:
        return all(
            self._ranges[i].start <= self._ranges[i + 1].start
            for i in range(len(self._ranges) - 1)
        )

    def is_merged(self) -> bool:
        """True iff sorted, non-empty, pairwise disjoint and non-adjacent."""
        m = self.merge()
        return self._ranges == m._ranges

    def is_non_overlap(self) -> bool:
        rs = sorted(r for r in self._ranges if not r.is_empty())
        return all(rs[i].end <= rs[i + 1].start for i in range(len(rs) - 1))

    def is_cu_seqlens(self, seq_len: int | None = None) -> bool:
        """True iff ranges are contiguous from 0 (optionally covering seq_len)."""
        if not self._ranges:
            return seq_len in (None, 0)
        if self._ranges[0].start != 0:
            return False
        for i in range(len(self._ranges) - 1):
            if self._ranges[i].end != self._ranges[i + 1].start:
                return False
        return seq_len is None or self._ranges[-1].end == seq_len

    # -- algebra -----------------------------------------------------------

    def sort(self) -> "AttnRanges":
        return AttnRanges(sorted(self._ranges, key=lambda r: (r.start, r.end)))

    def merge(self) -> "AttnRanges":
        """Sort, drop empties, coalesce overlapping/adjacent ranges."""
        rs = sorted((r for r in self._ranges if not r.is_empty()), key=lambda r: r.start)
        out: list[AttnRange] = []
        for r in rs:
            if out and r.start <= out[-1].end:
                if r.end > out[-1].end:
                    out[-1] = AttnRange(out[-1].start, r.end)
            else:
                out.append(AttnRange.from_range(r))
        return AttnRanges(out)

    def intersect_size(self) -> int:
        """Total (deduplicated) covered length."""
        return self.merge().total_seqlen

    def intersect_size_with(self, other: "AttnRanges") -> int:
        """Covered length of the intersection of the two (merged) coverages."""
        a, b = self.merge(), other.merge()
        i = j = 0
        total = 0
        while i < len(a) and j < len(b):
            total += a[i].intersect_size(b[j])
            if a[i].end < b[j].end:
                i += 1
            else:
                j += 1
        return total

    def union_size_with(self, other: "AttnRanges") -> int:
        combined = AttnRanges(list(self._ranges) + list(other._ranges))
        return combined.intersect_size()

    def find_hole_ranges(
        self, other: "AttnRanges", is_self_merged: bool = False
    ) -> "AttnRanges":
        """Coverage of ``self`` not covered by ``other`` (set difference)."""
        mine = self if is_self_merged else self.merge()
        theirs = other.merge()
        out = AttnRanges()
        j = 0
        for r in mine:
            cur = r.start
            while j < len(theirs) and theirs[j].end <= cur:
                j += 1
            k = j
            while k < len(theirs) and theirs[k].start < r.end:
                if theirs[k].start > cur:
                    out.append(AttnRange(cur, theirs[k].start))
                cur = max(cur, theirs[k].end)
                if cur >= r.end:
                    break
                k += 1
            if cur < r.end:
                out.append(AttnRange(cur, r.end))
        return out

    def find_overlap_ranges(self, other: "AttnRanges") -> "AttnRanges":
        """Coverage intersection of the two (merged) range sets."""
        a, b = self.merge(), other.merge()
        out = AttnRanges()
        i = j = 0
        while i < len(a) and j < len(b):
            inter = a[i].intersect(b[j])
            if not inter.is_empty():
                out.append(inter)
            if a[i].end < b[j].end:
                i += 1
            else:
                j += 1
        return out

    def chunk(self, chunk_size: int, check: bool = False) -> list["AttnRanges"]:
        """Split the (merged) coverage into consecutive chunks of ``chunk_size``
        *in coverage coordinates*: chunk i covers covered positions
        ``[i*chunk_size, (i+1)*chunk_size)``. Each chunk is an AttnRanges of the
        global sub-ranges it maps to.
        """
        merged = self.merge()
        if check and merged.total_seqlen % chunk_size != 0:
            raise RangeError(
                f"total covered seqlen {merged.total_seqlen} is not divisible by "
                f"chunk_size {chunk_size}"
            )
        chunks: list[AttnRanges] = []
        cur = AttnRanges()
        budget = chunk_size
        for r in merged:
            start = r.start
            while start < r.end:
                take = min(budget, r.end - start)
                cur.append(AttnRange(start, start + take))
                start += take
                budget -= take
                if budget == 0:
                    chunks.append(cur)
                    cur = AttnRanges()
                    budget = chunk_size
        if len(cur) > 0:
            chunks.append(cur)
        return chunks

    def locator(self) -> "RangeLocator":
        """Bisect-backed global<->local mapper over the merged ranges.

        Build once per (stable) range list and reuse: every query is
        O(log n + pieces) instead of make_ranges_local's O(n) scan with a
        fresh merge — the 1M-token planning hot path (the reference solves
        the same problem by moving these loops into the C++ backend,
        csrc/extensions/attn_ranges.hpp).
        """
        return RangeLocator(self)

    def make_range_local(self, r: AttnRange, is_self_merged: bool = False) -> AttnRange:
        """Map a global sub-range into the local (concatenated) coordinate system
        defined by this range list. ``r`` must be fully inside one range."""
        offset = 0
        host = self if is_self_merged else self.merge()
        for own in host:
            if r.is_subrange_of(own):
                return AttnRange(
                    offset + (r.start - own.start), offset + (r.end - own.start)
                )
            offset += own.seqlen
        raise RangeError(f"range {r} is not contained in any single range of {host}")

    def make_ranges_local(
        self, ranges: "AttnRanges", is_self_merged: bool = False
    ) -> "AttnRanges":
        """Map global sub-ranges into local coordinates, splitting at boundaries."""
        host = self if is_self_merged else self.merge()
        # prefix offsets of each host range in local coords
        offsets = []
        off = 0
        for own in host:
            offsets.append(off)
            off += own.seqlen
        out = AttnRanges()
        for r in ranges:
            if r.is_empty():
                continue
            remaining = AttnRange.from_range(r)
            matched = 0
            for own, own_off in zip(host, offsets):
                inter = remaining.intersect(own)
                if inter.is_empty():
                    continue
                out.append(
                    AttnRange(
                        own_off + (inter.start - own.start),
                        own_off + (inter.end - own.start),
                    )
                )
                matched += inter.seqlen
            if matched != r.seqlen:
                raise RangeError(f"range {r} is not fully covered by {host}")
        return out

    def find_overlap_ranges_with_self(self) -> "AttnRanges":
        """Positions covered by >= 2 ranges of self."""
        events: list[tuple[int, int]] = []
        for r in self._ranges:
            if not r.is_empty():
                events.append((r.start, 1))
                events.append((r.end, -1))
        events.sort()
        out = AttnRanges()
        depth = 0
        seg_start = None
        for pos, delta in events:
            new_depth = depth + delta
            if depth < 2 and new_depth >= 2:
                seg_start = pos
            elif depth >= 2 and new_depth < 2 and seg_start is not None:
                if pos > seg_start:
                    out.append(AttnRange(seg_start, pos))
                seg_start = None
            depth = new_depth
        return out.merge()

    # -- conversions -------------------------------------------------------

    def to_cu_seqlens(self, seq_len: int | None = None) -> list[int]:
        if not self.is_cu_seqlens(seq_len):
            raise RangeError(f"{self} is not in cu_seqlens (contiguous) form")
        if not self._ranges:
            return [0]
        return [0] + [r.end for r in self._ranges]

    def to_naive_ranges(self) -> list[tuple[int, int]]:
        return [r.to_tuple() for r in self._ranges]

    def to_array(self) -> np.ndarray:
        """``(n, 2)`` int32 array — the device-metadata form."""
        if not self._ranges:
            return np.zeros((0, 2), dtype=np.int32)
        return np.asarray(self.to_naive_ranges(), dtype=np.int32)

    def points(self) -> list[int]:
        out: list[int] = []
        for r in self._ranges:
            out.extend(range(r.start, r.end))
        return out


class RangeLocator:
    """Bisect-backed global->local mapper for a merged range list.

    Precomputes (starts, ends, local offsets) of the merged host ranges so
    repeated single-range queries avoid make_ranges_local's per-call merge +
    linear scan (the 1M-token planning hot loop; the reference keeps these
    loops in C++, csrc/extensions/attn_ranges.hpp).
    """

    __slots__ = ("starts", "ends", "offsets")

    def __init__(self, host: "AttnRanges") -> None:
        merged = host.merge()
        self.starts = [r.start for r in merged]
        self.ends = [r.end for r in merged]
        self.offsets = []
        off = 0
        for r in merged:
            self.offsets.append(off)
            off += r.seqlen

    def segments(
        self, start: int, end: int
    ) -> list[tuple[int, int, int | None]]:
        """Decompose global [start, end) into maximal pieces.

        Returns (gs, ge, local_start) per piece in global order;
        ``local_start`` is None for pieces not covered by the host ranges
        (holes). Empty input yields [].
        """
        out: list[tuple[int, int, int | None]] = []
        if start >= end:
            return out
        pos = start
        # first host range whose end > pos
        i = bisect.bisect_right(self.ends, pos)
        n = len(self.starts)
        while pos < end:
            if i >= n or self.starts[i] >= end:
                out.append((pos, end, None))
                break
            hs, he = self.starts[i], self.ends[i]
            if pos < hs:
                out.append((pos, hs, None))
                pos = hs
            ge = min(end, he)
            out.append((pos, ge, self.offsets[i] + (pos - hs)))
            pos = ge
            i += 1
        return out

    def to_local(self, start: int, end: int) -> list[tuple[int, int]]:
        """Local (ls, le) pieces covering global [start, end); raises
        RangeError on any uncovered position (make_ranges_local contract)."""
        out = []
        for gs, ge, ls in self.segments(start, end):
            if ls is None:
                raise RangeError(
                    f"range [{start}, {end}) not fully covered by host"
                )
            out.append((ls, ls + (ge - gs)))
        return out
