"""range_op — range-indexed fill / gather / reduce device ops.

Ref: magi_attention/common/range_op/ (Triton kernels ``range_fill_`` :65,
``range_gather`` :127, ``range_reduce`` with sum / avg / lse-weighted and a
deterministic ordered variant, _range_reduce.py:80,360) — the post-processing
stage of every group collective.

TPU-native re-design: ranges are host metadata, so each op precomputes flat
gather/scatter indices once (numpy) and lowers to a single fused XLA
gather / scatter-add — no custom kernel needed, and XLA scatter-add is
deterministic on TPU, so the "deterministic" ordered variant and the default
coincide for sum/avg. The lse-weighted reduce merges range-pairs in list
order (safe log-add-exp), which is the reference's ordered semantics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..functional.utils import correct_attn_out_lse
from .range import RangeError


def _ranges_to_indices(ranges) -> np.ndarray:
    """(N, 2) host ranges -> concatenated row indices."""
    r = np.asarray(ranges, dtype=np.int64).reshape(-1, 2)
    chunks = [np.arange(s, e, dtype=np.int32) for s, e in r if s < e]
    if not chunks:
        return np.zeros(0, dtype=np.int32)
    return np.concatenate(chunks)


def range_fill(x: jax.Array, ranges, value) -> jax.Array:
    """Set rows covered by ``ranges`` to ``value`` (ref range_fill_ :65)."""
    idx = _ranges_to_indices(ranges)
    if len(idx) == 0:
        return x
    return x.at[jnp.asarray(idx)].set(value)


def range_gather(x: jax.Array, ranges) -> jax.Array:
    """Concatenate rows covered by ``ranges`` (ref range_gather :127)."""
    idx = _ranges_to_indices(ranges)
    return jnp.take(x, jnp.asarray(idx), axis=0)


def range_scatter(x: jax.Array, ranges, rows: jax.Array) -> jax.Array:
    """Inverse of range_gather: write ``rows`` into the covered positions."""
    idx = _ranges_to_indices(ranges)
    return x.at[jnp.asarray(idx)].set(rows[: len(idx)])


def range_reduce(
    out: jax.Array,
    inp: jax.Array,
    out_ranges,
    inp_ranges,
    op: str = "sum",
    deterministic: bool = False,
) -> jax.Array:
    """Reduce ``inp`` range-blocks into ``out`` range-blocks.

    Each pair ``(inp_ranges[i] -> out_ranges[i])`` (equal lengths) adds its
    rows into the destination; overlapping destinations accumulate.
    op: "sum" | "avg" (mean over contributions per destination row).
    The ``deterministic`` flag is accepted for parity (ref
    _range_reduce.py:80); XLA scatter-add is already deterministic on TPU.
    """
    del deterministic
    oi = _ranges_to_indices(out_ranges)
    ii = _ranges_to_indices(inp_ranges)
    if len(oi) != len(ii):
        raise RangeError(
            f"range length mismatch: out_ranges {out_ranges} cover "
            f"{len(oi)} rows vs inp_ranges {inp_ranges} {len(ii)} rows"
        )
    if len(oi) == 0:
        return out
    oj = jnp.asarray(oi)
    rows = jnp.take(inp, jnp.asarray(ii), axis=0)
    if op == "sum":
        return out.at[oj].add(rows)
    if op == "avg":
        # average over ALL partials of a destination row: the pre-existing
        # out row counts as one contribution (ref avg_reduce_output)
        counts = np.zeros(out.shape[0], dtype=np.int64)
        np.add.at(counts, oi, 1)
        acc = out.at[oj].add(rows)
        denom = jnp.asarray((counts + 1).astype(np.float32))
        shape = (-1,) + (1,) * (out.ndim - 1)
        scale = jnp.where(
            jnp.asarray(counts) > 0, 1.0 / denom, 1.0
        ).reshape(shape)
        return (acc.astype(jnp.float32) * scale).astype(out.dtype)
    raise ValueError(f"unknown op: {op}")


def range_lse_reduce(
    out: jax.Array,
    lse: jax.Array,
    inp_out: jax.Array,
    inp_lse: jax.Array,
    out_ranges,
    inp_ranges,
) -> tuple[jax.Array, jax.Array]:
    """LSE-weighted partial-attention reduce (ref range_lse_reduce_kernel
    :239): for each range pair, merge the incoming partial (out, lse) rows
    into the destination rows with the safe log-sum-exp identity. Pairs
    merge in list order — the deterministic ordered semantics.
    """
    ro = np.asarray(out_ranges, dtype=np.int64)
    ri = np.asarray(inp_ranges, dtype=np.int64)
    for (os_, oe), (is_, ie) in zip(ro, ri):
        if oe <= os_:
            continue
        o_rows = jax.lax.dynamic_slice_in_dim(out, os_, oe - os_, 0)
        l_rows = jax.lax.dynamic_slice_in_dim(lse, os_, oe - os_, 0)
        po = jax.lax.dynamic_slice_in_dim(inp_out, is_, ie - is_, 0)
        pl = jax.lax.dynamic_slice_in_dim(inp_lse, is_, ie - is_, 0)
        merged_o, merged_l = correct_attn_out_lse(o_rows, l_rows, po, pl)
        out = jax.lax.dynamic_update_slice_in_dim(out, merged_o, os_, 0)
        lse = jax.lax.dynamic_update_slice_in_dim(lse, merged_l, os_, 0)
    return out, lse
