"""Half-open interval ``[start, end)`` algebra.

Host-side planning primitive (ref: magi_attention/common/range.py:24-294).
Pure Python — no JAX dependency; everything here runs at plan/trace time.
"""

from __future__ import annotations

from typing import Any, Iterator


class RangeError(ValueError):
    pass


class AttnRange:
    """A half-open integer interval ``[start, end)``."""

    __slots__ = ("_start", "_end")

    def __init__(self, start: int, end: int) -> None:
        self.check_valid(start, end)
        self._start = int(start)
        self._end = int(end)

    # -- constructors ------------------------------------------------------

    @staticmethod
    def check_valid(start: int, end: int) -> None:
        if start < 0 or end < 0:
            raise RangeError(f"range must be non-negative, got [{start}, {end})")
        if start > end:
            raise RangeError(f"range start must be <= end, got [{start}, {end})")

    @classmethod
    def from_range(cls, other: "AttnRange") -> "AttnRange":
        return cls(other.start, other.end)

    @classmethod
    def from_tuple(cls, t: tuple[int, int]) -> "AttnRange":
        return cls(t[0], t[1])

    # -- properties --------------------------------------------------------

    @property
    def start(self) -> int:
        return self._start

    @start.setter
    def start(self, value: int) -> None:
        self.check_valid(value, self._end)
        self._start = int(value)

    @property
    def end(self) -> int:
        return self._end

    @end.setter
    def end(self, value: int) -> None:
        self.check_valid(self._start, value)
        self._end = int(value)

    @property
    def seqlen(self) -> int:
        return self._end - self._start

    def is_empty(self) -> bool:
        return self._start == self._end

    def is_valid(self) -> bool:
        return 0 <= self._start <= self._end

    # -- algebra -----------------------------------------------------------

    def is_subrange_of(self, other: "AttnRange") -> bool:
        if self.is_empty():
            return True
        return other.start <= self.start and self.end <= other.end

    def is_overlap_with(self, other: "AttnRange") -> bool:
        return max(self.start, other.start) < min(self.end, other.end)

    def intersect(self, other: "AttnRange") -> "AttnRange":
        """The overlap of the two ranges (empty range at the boundary if disjoint)."""
        start = max(self.start, other.start)
        end = min(self.end, other.end)
        if start >= end:  # disjoint -> canonical empty range
            return AttnRange(start, start)
        return AttnRange(start, end)

    def union(self, other: "AttnRange") -> "AttnRange":
        """The union, valid only if the ranges touch or overlap."""
        if not (self.is_overlap_with(other) or self.is_adjacent_to(other)):
            raise RangeError(f"cannot union disjoint ranges {self} and {other}")
        return AttnRange(min(self.start, other.start), max(self.end, other.end))

    def is_adjacent_to(self, other: "AttnRange") -> bool:
        return self.end == other.start or other.end == self.start

    def diff_by(self, other: "AttnRange") -> list["AttnRange"]:
        """``self - other`` as a list of 0-2 non-empty ranges."""
        out: list[AttnRange] = []
        if not self.is_overlap_with(other):
            if not self.is_empty():
                out.append(AttnRange.from_range(self))
            return out
        if self.start < other.start:
            out.append(AttnRange(self.start, other.start))
        if other.end < self.end:
            out.append(AttnRange(other.end, self.end))
        return out

    def truncate(self, start: int | None = None, end: int | None = None) -> "AttnRange":
        """Clamp this range into ``[start, end)``."""
        lo = self.start if start is None else max(self.start, start)
        hi = self.end if end is None else min(self.end, end)
        if lo >= hi:
            lo = hi = max(lo if end is None else min(lo, end), 0)
        return AttnRange(lo, hi)

    def offset(self, off: int) -> "AttnRange":
        return AttnRange(self.start + off, self.end + off)

    def intersect_size(self, other: "AttnRange") -> int:
        return max(0, min(self.end, other.end) - max(self.start, other.start))

    # -- dunder ------------------------------------------------------------

    def to_tuple(self) -> tuple[int, int]:
        return (self._start, self._end)

    def __contains__(self, pos: int) -> bool:
        return self._start <= pos < self._end

    def __iter__(self) -> Iterator[int]:
        return iter(range(self._start, self._end))

    def __len__(self) -> int:
        return self.seqlen

    def __eq__(self, other: Any) -> bool:
        if isinstance(other, AttnRange):
            return self._start == other._start and self._end == other._end
        return NotImplemented

    def __lt__(self, other: "AttnRange") -> bool:
        return (self._start, self._end) < (other._start, other._end)

    def __hash__(self) -> int:
        return hash((self._start, self._end))

    def __repr__(self) -> str:
        return f"[{self._start}, {self._end})"
