"""Persistent XLA/Mosaic compilation cache.

The tunnel TPU comes and goes in short windows; first-compile of each kernel
variant costs 20-40s, which can eat an entire window. Enabling JAX's
persistent compilation cache (keyed by backend + HLO + flags) makes every
process after the first reuse the compiled executable — across the smoke
script, the block sweep, bench.py, and the driver's round-end bench run.

Reference analogue: the JIT build cache (magi_attention/common/jit/core.py,
keyed by env snapshot env/ffa.py:125) — same role, compiler-level.
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache",
)


def enable_persistent_cache(cache_dir: str | None = None) -> str:
    """Turn on the JAX persistent compilation cache (idempotent).

    Call before the first jit/pallas compilation. Honors
    ``JAX_COMPILATION_CACHE_DIR`` if already set; otherwise uses
    ``<repo>/.jax_cache``.
    """
    import jax

    from ..env.general import jax_compilation_cache_dir

    path = cache_dir or jax_compilation_cache_dir() or _DEFAULT_DIR
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # Small nonzero floor: the 20-40s Mosaic kernels this cache exists for
    # are far above it, while trivial sub-second compiles stay out of the
    # cache dir (which has no eviction).
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    return path
