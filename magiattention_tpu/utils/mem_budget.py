"""Memory budget calculators (ref: magi_attention/utils/mem_budget.py:126-215).

The reference budgets FFA workspace HBM; on TPU the scarce resource is VMEM
(~16 MB/core): the fwd kernel keeps one q tile, one k tile, one v tile, the
out tile, and the fp32 accumulators resident. These helpers size tiles and
bound the maximum merged-buffer seqlen for a given budget.
"""

from __future__ import annotations

# Per-core VMEM on current TPU generations (v4/v5e/v5p: 16 MiB), and the
# margin left for Mosaic's own spills/semaphores/metadata. Every layer that
# bounds kernel residency — the tile policy's candidate filter, the packed-
# kernel dispatch guards in kernels/ffa.py, verifier rule R5 and the static
# kernel checker's K1 — derives its limit from THESE constants, so the
# budget model cannot diverge between plan-time and kernel-time checks.
VMEM_LIMIT_BYTES = 16 * 1024 * 1024
VMEM_HEADROOM_BYTES = 2 * 1024 * 1024
VMEM_ALLOWED_BYTES = VMEM_LIMIT_BYTES - VMEM_HEADROOM_BYTES


def ffa_vmem_budget(
    block_q: int,
    block_k: int,
    head_dim: int,
    head_dim_v: int | None = None,
    dtype_bytes: int = 2,
) -> int:
    """Approximate fwd-kernel VMEM residency in bytes (per grid step, double
    buffered by the pipeline)."""
    dv = head_dim_v or head_dim
    q = block_q * head_dim * dtype_bytes
    k = block_k * head_dim * dtype_bytes
    v = block_k * dv * dtype_bytes
    out = block_q * dv * dtype_bytes
    acc = block_q * dv * 4
    ml = 2 * block_q * 128 * 4
    s = block_q * block_k * 4  # logits tile (fp32)
    return 2 * (q + k + v + out) + acc + ml + s


def ffa_bwd_vmem_budget(
    kind: str,
    block_q: int,
    block_k: int,
    head_dim: int,
    head_dim_v: int | None = None,
    dtype_bytes: int = 2,
) -> int:
    """Approximate bwd-kernel VMEM residency in bytes for one grid step:
    the fwd residency plus the pass's fp32 accumulator scratch and the
    recomputed score tile ((bq, bk) for dq, transposed — same size — for
    dkv). ``kind`` is "dq" or "dkv"."""
    if kind not in ("dq", "dkv"):
        raise ValueError(f"kind must be 'dq' or 'dkv', got {kind!r}")
    dv = head_dim_v or head_dim
    scratch = block_q * head_dim if kind == "dq" else block_k * (head_dim + dv)
    return (
        ffa_vmem_budget(block_q, block_k, head_dim, dv, dtype_bytes)
        + 4 * (scratch + block_q * block_k)
    )


def ffa_kernel_residency(
    kind: str,
    block_q: int,
    block_k: int,
    head_dim: int,
    head_dim_v: int | None = None,
    dtype_bytes: int = 2,
    group: int = 1,
    packed: bool = False,
    emit_ml: bool = False,
    include_intermediates: bool = True,
) -> int:
    """EXACT declared VMEM residency of one FFA kernel grid step, in bytes.

    Mirrors the BlockSpec/scratch shapes in ``kernels/ffa.py`` closed-form:
    every in/out block is double-buffered by the Pallas pipeline, scratch is
    single-buffered, and (when ``include_intermediates``) the fp32 score-
    sized value tiles Mosaic must materialize are added (one (rows, bk) tile
    for fwd — p reuses s's storage — and two for the bwd passes: s + dp).
    The static kernel checker (analysis/kernel_check, rule K1) asserts this
    function matches the captured pallas_call contracts bit-for-bit, so the
    dispatch guards below it cannot drift from the real kernels.

    ``packed`` selects the GQA-packed variant (query rows x ``group``);
    unpacked kernels are per-q-head, so ``group`` is ignored for them
    except dkv's lse/delta sublane layout which is group-independent.
    """
    if kind not in (
        "fwd", "dq", "dkv", "fused", "delta", "decode", "decode_spec",
        "decode_int8", "bsp_fwd", "bsp_bwd",
    ):
        raise ValueError(
            f"kind must be 'fwd'|'dq'|'dkv'|'fused'|'delta'|'decode'|"
            f"'decode_spec'|'decode_int8'|'bsp_fwd'|'bsp_bwd', got {kind!r}"
        )
    dv = head_dim_v or head_dim
    g = group if packed else 1
    bq, bk, d = block_q, block_k, head_dim
    f32 = 4

    k_in = bk * d * dtype_bytes
    v_in = bk * dv * dtype_bytes
    q_in = g * bq * d * dtype_bytes
    if kind == "fwd":
        blocks = q_in + k_in + v_in
        blocks += g * bq * dv * dtype_bytes  # out
        blocks += g * bq * 128 * f32  # lse (lanes-broadcast)
        if emit_ml and not packed:
            blocks += bq * 128 * f32  # max-logits (fwd unpacked only)
        scratch = (2 * g * bq * 128 + g * bq * dv) * f32  # m, l, acc
        inter = g * bq * bk * f32  # s (p reuses its storage)
    elif kind == "dq":
        blocks = q_in + k_in + v_in
        blocks += g * bq * dv * dtype_bytes  # do
        blocks += 2 * (g if packed else 1) * bq * f32  # lse + delta rows
        blocks += g * bq * d * f32  # dq out (fp32)
        scratch = g * bq * d * f32
        inter = 2 * g * bq * bk * f32  # s + dp
    elif kind == "dkv":
        blocks = q_in + k_in + v_in
        blocks += g * bq * dv * dtype_bytes  # do
        # lse/delta: packed rides (1, g*bq) rows; unpacked an (8, bq) slab
        blocks += 2 * (g * bq if packed else 8 * bq) * f32
        blocks += (bk * d + bk * dv) * f32  # dk + dv outs (fp32)
        scratch = (bk * d + bk * dv) * f32
        inter = 2 * g * bq * bk * f32  # s_t + dp_t
    elif kind == "fused":
        # one-pass backward: the dkv residency PLUS the revisited dq
        # output window and its aliased zero-background input block (both
        # fp32, both declared BlockSpecs so both pipeline-double-buffered)
        blocks = q_in + k_in + v_in
        blocks += g * bq * dv * dtype_bytes  # do
        blocks += 2 * (g * bq if packed else 8 * bq) * f32  # lse + delta
        blocks += (bk * d + bk * dv) * f32  # dk + dv outs (fp32)
        blocks += 2 * g * bq * d * f32  # dq out + aliased dqz in (fp32)
        scratch = (bk * d + bk * dv) * f32
        inter = 2 * g * bq * bk * f32  # s_t + dp_t
    elif kind == "delta":
        # stateless rowsum(dO ⊙ O) map kernel: o + do blocks in, one
        # lanes-broadcast fp32 block out, no scratch; group-independent
        blocks = 2 * bq * dv * dtype_bytes  # o + do
        blocks += bq * 128 * f32  # delta (lanes-broadcast)
        scratch = 0
        inter = bq * dv * f32  # fp32 elementwise product
    elif kind in ("decode", "decode_spec", "bsp_fwd"):
        # decode (kernels/paged_decode.py): bq = GQA group rows of one kv
        # head, bk = page_size. decode_spec (the speculative-verify
        # variant): identical shape with bq = spec_k * group rows — the
        # draft window rides the q tile. bsp_fwd (kernels/block_sparse.py):
        # bq = block_size_q * group rows of one q block, bk = d_stride
        # chunk rows. Identical residency shape: q tile, one streamed k/v
        # chunk, out + lanes-broadcast lse, m/l/acc scratch
        # (group/packed/emit_ml are ignored).
        blocks = bq * d * dtype_bytes  # q group tile
        blocks += bk * d * dtype_bytes + bk * dv * dtype_bytes  # one k/v page
        blocks += bq * dv * dtype_bytes  # out
        blocks += bq * 128 * f32  # lse (lanes-broadcast)
        scratch = (2 * bq * 128 + bq * dv) * f32  # m, l, acc
        inter = bq * bk * f32  # s (p reuses its storage)
    elif kind == "decode_int8":
        # int8-KV decode (kernels/paged_decode.py): k/v pages are int8
        # codes (1 byte/elem regardless of the compute dtype), each riding
        # a (1, 1) f32 per-(page, head) scale block on the same page-table
        # prefetch; q/out stay at the compute dtype. Dequant is in-kernel,
        # so scratch/intermediates match the base decode shape.
        blocks = bq * d * dtype_bytes  # q group tile
        blocks += bk * d + bk * dv  # one int8 k/v page (1 byte/elem)
        blocks += 2 * f32  # k + v per-page scale blocks
        blocks += bq * dv * dtype_bytes  # out
        blocks += bq * 128 * f32  # lse (lanes-broadcast)
        scratch = (2 * bq * 128 + bq * dv) * f32  # m, l, acc
        inter = bq * bk * f32  # s (p reuses its storage)
    else:  # bsp_bwd (kernels/block_sparse.py fused backward): q/do tiles,
        # one streamed k/v chunk, lanes-broadcast lse + delta, fp32 dq out
        # plus revisit-accumulated dk/dv output windows with their aliased
        # zero-background input blocks, dq fp32 scratch
        blocks = bq * d * dtype_bytes  # q tile
        blocks += bk * d * dtype_bytes + bk * dv * dtype_bytes  # k/v chunk
        blocks += bq * dv * dtype_bytes  # do
        blocks += 2 * bq * 128 * f32  # lse + delta (lanes-broadcast)
        blocks += bq * d * f32  # dq out (fp32)
        blocks += 2 * (bk * d + bk * dv) * f32  # dk/dv outs + dkz/dvz ins
        scratch = bq * d * f32  # dq accumulator
        inter = 2 * bq * bk * f32  # s + dp
    total = 2 * blocks + scratch
    if include_intermediates:
        total += inter
    return total


def ffa_max_total_seqlen(
    vmem_bytes: int,
    block_q: int,
    block_k: int,
    head_dim: int,
    dtype_bytes: int = 2,
) -> int:
    """Upper bound on the merged kv length whose *index metadata* fits the
    scalar-prefetch budget (the payload streams from HBM, so the real bound
    is plan size, not seqlen)."""
    per_item = 15 * 4 + 2 * 4  # meta row (9 band + 4 extent + 2 q-visit cols) + two work indices
    max_items = max(1, vmem_bytes // (8 * per_item))
    return max_items * block_k
