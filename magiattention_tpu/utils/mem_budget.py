"""Memory budget calculators (ref: magi_attention/utils/mem_budget.py:126-215).

The reference budgets FFA workspace HBM; on TPU the scarce resource is VMEM
(~16 MB/core): the fwd kernel keeps one q tile, one k tile, one v tile, the
out tile, and the fp32 accumulators resident. These helpers size tiles and
bound the maximum merged-buffer seqlen for a given budget.
"""

from __future__ import annotations


def ffa_vmem_budget(
    block_q: int,
    block_k: int,
    head_dim: int,
    head_dim_v: int | None = None,
    dtype_bytes: int = 2,
) -> int:
    """Approximate fwd-kernel VMEM residency in bytes (per grid step, double
    buffered by the pipeline)."""
    dv = head_dim_v or head_dim
    q = block_q * head_dim * dtype_bytes
    k = block_k * head_dim * dtype_bytes
    v = block_k * dv * dtype_bytes
    out = block_q * dv * dtype_bytes
    acc = block_q * dv * 4
    ml = 2 * block_q * 128 * 4
    s = block_q * block_k * 4  # logits tile (fp32)
    return 2 * (q + k + v + out) + acc + ml + s


def ffa_max_total_seqlen(
    vmem_bytes: int,
    block_q: int,
    block_k: int,
    head_dim: int,
    dtype_bytes: int = 2,
) -> int:
    """Upper bound on the merged kv length whose *index metadata* fits the
    scalar-prefetch budget (the payload streams from HBM, so the real bound
    is plan size, not seqlen)."""
    per_item = 9 * 4 + 2 * 4  # meta row + two work indices
    max_items = max(1, vmem_bytes // (8 * per_item))
    return max_items * block_k
