"""Block/index-sparse mask utilities (ref: magi_attention/utils/sparse_utils.py).

Converts sparse attention patterns into the slice metadata the FFA kernel
consumes (the reference's block-mask -> ranges conversion :371-407 and
topk -> ranges :262-304). Covers the Magi-1 spatiotemporal video mask
(BASELINE config 4): a per-block boolean mask over (q_blocks, k_blocks).
"""

from __future__ import annotations

import numpy as np

from ..common.ranges import AttnRanges


def block_mask_to_ranges(
    block_mask: np.ndarray,
    block_size_q: int,
    block_size_k: int,
) -> tuple[AttnRanges, AttnRanges, list]:
    """Block-boolean mask -> (q_ranges, k_ranges, FULL types).

    One slice per maximal contiguous run of attended k blocks in each q-block
    row (runs collapse many blocks into one wide slice — the kernel's plan
    stays small for structured video masks).
    """
    from ..common.enum import AttnMaskType

    nqb, nkb = block_mask.shape
    q_out, k_out, t_out = AttnRanges(), AttnRanges(), []
    from ..common.range import AttnRange

    for qb in range(nqb):
        row = block_mask[qb]
        j = 0
        while j < nkb:
            if not row[j]:
                j += 1
                continue
            j0 = j
            while j < nkb and row[j]:
                j += 1
            q_out.append(AttnRange(qb * block_size_q, (qb + 1) * block_size_q))
            k_out.append(AttnRange(j0 * block_size_k, j * block_size_k))
            t_out.append(AttnMaskType.FULL)
    return q_out, k_out, t_out


def topk_indices_to_block_mask(
    topk_idx: np.ndarray, num_k_blocks: int
) -> np.ndarray:
    """(nqb, topk) block indices (pad -1) -> (nqb, nkb) boolean block mask
    (the index-sparse -> block-sparse preprocessing, ref :262-304)."""
    nqb = topk_idx.shape[0]
    mask = np.zeros((nqb, num_k_blocks), dtype=bool)
    for qb in range(nqb):
        for idx in topk_idx[qb]:
            if idx >= 0:
                mask[qb, int(idx)] = True
    return mask


def make_video_block_mask(
    num_frames: int,
    tokens_per_frame_blocks: int,
    window_frames: int = 2,
    causal_frames: bool = True,
) -> np.ndarray:
    """Magi-1 style spatiotemporal pattern at block granularity: each frame's
    blocks attend to all blocks of the last ``window_frames`` frames
    (optionally causal over frames). Returns (nqb, nkb) boolean."""
    nb = num_frames * tokens_per_frame_blocks
    mask = np.zeros((nb, nb), dtype=bool)
    for f in range(num_frames):
        f_lo = max(0, f - window_frames + 1)
        f_hi = f + 1 if causal_frames else min(num_frames, f + window_frames)
        q0, q1 = f * tokens_per_frame_blocks, (f + 1) * tokens_per_frame_blocks
        k0, k1 = f_lo * tokens_per_frame_blocks, f_hi * tokens_per_frame_blocks
        mask[q0:q1, k0:k1] = True
    return mask
