"""Block/index-sparse mask utilities (ref: magi_attention/utils/sparse_utils.py).

Converts sparse attention patterns into the slice metadata the FFA kernel
consumes (the reference's block-mask -> ranges conversion :371-407 and
topk -> ranges :262-304). Covers the Magi-1 spatiotemporal video mask
(BASELINE config 4): a per-block boolean mask over (q_blocks, k_blocks).
"""

from __future__ import annotations

import numpy as np

from ..common.ranges import AttnRanges


def block_mask_to_ranges(
    block_mask: np.ndarray,
    block_size_q: int,
    block_size_k: int,
) -> tuple[AttnRanges, AttnRanges, list]:
    """Block-boolean mask -> (q_ranges, k_ranges, FULL types).

    One slice per maximal contiguous run of attended k blocks in each q-block
    row (runs collapse many blocks into one wide slice — the kernel's plan
    stays small for structured video masks).
    """
    from ..common.enum import AttnMaskType

    nqb, nkb = block_mask.shape
    q_out, k_out, t_out = AttnRanges(), AttnRanges(), []
    from ..common.range import AttnRange

    for qb in range(nqb):
        row = block_mask[qb]
        j = 0
        while j < nkb:
            if not row[j]:
                j += 1
                continue
            j0 = j
            while j < nkb and row[j]:
                j += 1
            q_out.append(AttnRange(qb * block_size_q, (qb + 1) * block_size_q))
            k_out.append(AttnRange(j0 * block_size_k, j * block_size_k))
            t_out.append(AttnMaskType.FULL)
    return q_out, k_out, t_out


def topk_indices_to_block_mask(
    topk_idx: np.ndarray, num_k_blocks: int
) -> np.ndarray:
    """(nqb, topk) block indices (pad -1) -> (nqb, nkb) boolean block mask
    (the index-sparse -> block-sparse preprocessing, ref :262-304)."""
    nqb = topk_idx.shape[0]
    mask = np.zeros((nqb, num_k_blocks), dtype=bool)
    for qb in range(nqb):
        for idx in topk_idx[qb]:
            if idx >= 0:
                mask[qb, int(idx)] = True
    return mask


def make_video_block_mask(
    num_frames: int,
    tokens_per_frame_blocks: int,
    window_frames: int = 2,
    causal_frames: bool = True,
) -> np.ndarray:
    """Magi-1 style spatiotemporal pattern at block granularity: each frame's
    blocks attend to all blocks of the last ``window_frames`` frames
    (optionally causal over frames). Returns (nqb, nkb) boolean."""
    nb = num_frames * tokens_per_frame_blocks
    mask = np.zeros((nb, nb), dtype=bool)
    for f in range(num_frames):
        f_lo = max(0, f - window_frames + 1)
        f_hi = f + 1 if causal_frames else min(num_frames, f + window_frames)
        q0, q1 = f * tokens_per_frame_blocks, (f + 1) * tokens_per_frame_blocks
        k0, k1 = f_lo * tokens_per_frame_blocks, f_hi * tokens_per_frame_blocks
        mask[q0:q1, k0:k1] = True
    return mask


def block_mask_to_dense_mask(
    block_mask: np.ndarray, block_size_q: int, block_size_k: int
) -> np.ndarray:
    """Token-level dense boolean oracle for a block mask (the reference's
    SDPA-mask test oracles, sparse_utils.py:500-699)."""
    return np.kron(
        block_mask, np.ones((block_size_q, block_size_k), dtype=bool)
    )


def ranges_to_block_mask(
    q_ranges, k_ranges, num_q_blocks: int, num_k_blocks: int,
    block_size_q: int, block_size_k: int,
) -> np.ndarray:
    """Inverse of :func:`block_mask_to_ranges` for block-aligned FULL slices
    (round-trip testing aid)."""
    mask = np.zeros((num_q_blocks, num_k_blocks), dtype=bool)
    for qr, kr in zip(q_ranges, k_ranges):
        qb0, qb1 = qr.start // block_size_q, -(-qr.end // block_size_q)
        kb0, kb1 = kr.start // block_size_k, -(-kr.end // block_size_k)
        mask[qb0:qb1, kb0:kb1] = True
    return mask


def varlen_block_mask_to_ranges(
    block_mask: np.ndarray,
    q_block_bounds: np.ndarray,
    k_block_bounds: np.ndarray,
) -> tuple[AttnRanges, AttnRanges, list]:
    """Variable-size blocks: ``*_block_bounds`` are (nb+1,) token offsets per
    block (the reference's variable block patterns, sparse_utils.py:749).
    Returns (q_ranges, k_ranges, FULL types), one slice per maximal run."""
    from ..common.enum import AttnMaskType
    from ..common.range import AttnRange

    nqb, nkb = block_mask.shape
    assert len(q_block_bounds) == nqb + 1 and len(k_block_bounds) == nkb + 1
    q_out, k_out, t_out = AttnRanges(), AttnRanges(), []
    for qb in range(nqb):
        row = block_mask[qb]
        j = 0
        while j < nkb:
            if not row[j]:
                j += 1
                continue
            j0 = j
            while j < nkb and row[j]:
                j += 1
            q_out.append(
                AttnRange(int(q_block_bounds[qb]), int(q_block_bounds[qb + 1]))
            )
            k_out.append(
                AttnRange(int(k_block_bounds[j0]), int(k_block_bounds[j]))
            )
            t_out.append(AttnMaskType.FULL)
    return q_out, k_out, t_out


def topk_indices_to_ranges(
    topk_idx: np.ndarray, block_size_q: int, block_size_k: int,
    num_k_blocks: int,
) -> tuple[AttnRanges, AttnRanges, list]:
    """Index-sparse (per-q-block top-k k-blocks) directly to slice metadata
    (ref topk -> ranges, sparse_utils.py:262-304)."""
    mask = topk_indices_to_block_mask(topk_idx, num_k_blocks)
    return block_mask_to_ranges(mask, block_size_q, block_size_k)
