"""Cross-version JAX compatibility shims.

The package targets current JAX, where ``shard_map`` lives in the
top-level namespace and takes ``check_vma``; older releases ship it under
``jax.experimental.shard_map`` with the ``check_rep`` spelling. Importing
from here instead of ``jax`` keeps the whole functional/parallel stack
importable on both (the same pattern as ffa.py's ``_CompilerParams``
alias for the TPUCompilerParams rename).
"""

from __future__ import annotations

try:  # JAX >= 0.6: promoted to the top-level namespace
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # older JAX: experimental location, check_rep kwarg
    import functools

    from jax.experimental.shard_map import (  # type: ignore[import]
        shard_map as _shard_map,
    )

    @functools.wraps(_shard_map)
    def shard_map(f, *args, check_vma=None, **kwargs):
        if check_vma is not None:
            kwargs["check_rep"] = check_vma
        return _shard_map(f, *args, **kwargs)
