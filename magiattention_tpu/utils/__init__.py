"""Support utilities (ref: magi_attention/utils/)."""

from .profiling import add_profile_event, instrument_scope, switch_profile  # noqa: F401
from .mem_budget import ffa_vmem_budget, ffa_max_total_seqlen  # noqa: F401
