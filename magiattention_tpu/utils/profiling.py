"""Tracing / profiling helpers (ref: magi_attention/utils/nvtx.py).

The reference instruments every hot-path function with NVTX ranges and opens
torch.profiler windows; the TPU equivalents are ``jax.named_scope`` (shows up
in XLA HLO + xprof traces) and ``jax.profiler`` trace windows.
"""

from __future__ import annotations

import functools
from contextlib import contextmanager
from typing import Callable

import jax


def instrument_scope(fn: Callable | None = None, *, name: str | None = None):
    """Decorator wrapping a function in a ``jax.named_scope`` (the
    ``instrument_nvtx`` equivalent, ref nvtx.py:81). Scope names appear in
    HLO metadata and profiler traces."""

    def wrap(f):
        scope = name or f.__qualname__

        @functools.wraps(f)
        def inner(*args, **kwargs):
            with jax.named_scope(scope):
                return f(*args, **kwargs)

        return inner

    return wrap(fn) if fn is not None else wrap


@contextmanager
def add_profile_event(name: str):
    """Annotate a host-side region in the profiler trace (ref add_nvtx_event)."""
    with jax.profiler.TraceAnnotation(name):
        yield


class switch_profile:
    """Start/stop a jax profiler window (ref nvtx.py:110 switch_profile).

    Usage::

        prof = switch_profile(log_dir="/tmp/trace")
        prof.start(); ...steps...; prof.stop()
    """

    def __init__(self, log_dir: str = "/tmp/magiattention_tpu_trace") -> None:
        self.log_dir = log_dir
        self._running = False

    def start(self) -> None:
        if not self._running:
            jax.profiler.start_trace(self.log_dir)
            self._running = True

    def stop(self) -> None:
        if self._running:
            jax.profiler.stop_trace()
            self._running = False
