"""Tracing / profiling helpers (ref: magi_attention/utils/nvtx.py).

The reference instruments every hot-path function with NVTX ranges and opens
torch.profiler windows; the TPU equivalents are ``jax.named_scope`` (shows up
in XLA HLO + xprof traces) and ``jax.profiler`` trace windows.
"""

from __future__ import annotations

import functools
from contextlib import contextmanager
from typing import Callable

import jax

from ..env import general as env_general


def instrument_scope(fn: Callable | None = None, *, name: str | None = None):
    """Decorator wrapping a function in a ``jax.named_scope`` (the
    ``instrument_nvtx`` equivalent, ref nvtx.py:81). Scope names appear in
    HLO metadata and profiler traces.

    Gated on ``MAGI_ATTENTION_PROFILE_MODE`` (read per call, i.e. per
    trace): off by default, zero overhead in production programs — the
    reference gates its nvtx instrumentation the same way
    (env/general.py:191)."""

    def wrap(f):
        scope = name or f.__qualname__

        @functools.wraps(f)
        def inner(*args, **kwargs):
            if not env_general.is_profile_mode_enable():
                return f(*args, **kwargs)
            with jax.named_scope(scope):
                return f(*args, **kwargs)

        return inner

    return wrap(fn) if fn is not None else wrap


@contextmanager
def profile_scope(name: str):
    """Inline ``jax.named_scope`` gated on MAGI_ATTENTION_PROFILE_MODE —
    for loop bodies (per-stage kernels / casts) where a decorator can't
    reach."""
    if not env_general.is_profile_mode_enable():
        yield
    else:
        with jax.named_scope(name):
            yield


def instrument_host(fn: Callable | None = None, *, name: str | None = None):
    """Host-side profiler annotation (``jax.profiler.TraceAnnotation``) for
    UN-traced hot paths — solvers, plan builders, runtime init. These run in
    Python, so named_scope (an HLO-metadata construct) cannot see them; the
    TraceAnnotation puts them on the profiler timeline instead (the ref
    add_nvtx_event analogue). Gated on MAGI_ATTENTION_PROFILE_MODE."""

    def wrap(f):
        scope = name or f.__qualname__

        @functools.wraps(f)
        def inner(*args, **kwargs):
            if not env_general.is_profile_mode_enable():
                return f(*args, **kwargs)
            with jax.profiler.TraceAnnotation(scope):
                return f(*args, **kwargs)

        return inner

    return wrap(fn) if fn is not None else wrap


@contextmanager
def add_profile_event(name: str):
    """Annotate a host-side region in the profiler trace (ref
    add_nvtx_event). Gated on MAGI_ATTENTION_PROFILE_MODE like every other
    annotation helper here — off means no TraceAnnotation is constructed."""
    if not env_general.is_profile_mode_enable():
        yield
    else:
        with jax.profiler.TraceAnnotation(name):
            yield


class switch_profile:
    """Start/stop a jax profiler window (ref nvtx.py:110 switch_profile).

    Usable explicitly or as a context manager (exception-safe: the trace
    window is closed even when the body raises)::

        prof = switch_profile(log_dir="/tmp/trace")
        prof.start(); ...steps...; prof.stop()

        with switch_profile(log_dir="/tmp/trace"):
            ...steps...
    """

    def __init__(self, log_dir: str = "/tmp/magiattention_tpu_trace") -> None:
        self.log_dir = log_dir
        self._running = False

    def start(self) -> None:
        if not self._running:
            jax.profiler.start_trace(self.log_dir)
            self._running = True

    def stop(self) -> None:
        if self._running:
            jax.profiler.stop_trace()
            self._running = False

    def __enter__(self) -> "switch_profile":
        self.start()
        return self

    def __exit__(self, *exc_info) -> None:
        self.stop()
