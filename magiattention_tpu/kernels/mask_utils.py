"""Traced mask construction from AttnSlice metadata arrays.

The device-side counterpart of ``common.mask`` (ref kernel contract:
magi_attention/functional/flex_flash_attn.py:1454-1466): slice metadata is
``q_ranges (N,2) int32``, ``k_ranges (N,2) int32``, ``attn_type_map (N,)
int32`` with 0=FULL, 1=CAUSAL, 2=INVCAUSAL, 3=BICAUSAL. Empty slices
(``q_start >= q_end``) are padding and contribute nothing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def slice_block_mask(
    q_start,
    q_end,
    k_start,
    k_end,
    mask_type,
    q_index,
    k_index,
):
    """Boolean mask contribution of one slice on a (len(q_index), len(k_index))
    tile of global coordinates.

    Geometry (d = j - i): CAUSAL: d <= k_end - q_end (bottom-right aligned);
    INVCAUSAL: d >= k_start - q_start (top-left aligned); BICAUSAL: both.
    """
    i = q_index[:, None]
    j = k_index[None, :]
    in_rect = (i >= q_start) & (i < q_end) & (j >= k_start) & (j < k_end)
    d = j - i
    causal_ok = d <= (k_end - q_end)
    inv_ok = d >= (k_start - q_start)
    ok = jnp.where(
        mask_type == 0,
        True,
        jnp.where(
            mask_type == 1,
            causal_ok,
            jnp.where(mask_type == 2, inv_ok, causal_ok & inv_ok),
        ),
    )
    return in_rect & ok


def build_dense_mask(
    q_ranges: jax.Array,
    k_ranges: jax.Array,
    attn_type_map: jax.Array,
    seqlen_q: int,
    seqlen_k: int,
    q_offset: int = 0,
    k_offset: int = 0,
) -> jax.Array:
    """Materialize the (seqlen_q, seqlen_k) boolean mask from slice metadata.

    ``q_offset``/``k_offset`` shift the local tile into global coordinates
    (used by the blockwise backends). O(N * sq * sk) work via scan — testing /
    fallback path only; the Pallas kernel never materializes this.
    """
    q_index = q_offset + jnp.arange(seqlen_q, dtype=jnp.int32)
    k_index = k_offset + jnp.arange(seqlen_k, dtype=jnp.int32)

    def body(mask, slice_meta):
        qr, kr, mt = slice_meta
        contrib = slice_block_mask(qr[0], qr[1], kr[0], kr[1], mt, q_index, k_index)
        return mask | contrib, None

    init = jnp.zeros((seqlen_q, seqlen_k), dtype=jnp.bool_)
    mask, _ = jax.lax.scan(body, init, (q_ranges, k_ranges, attn_type_map))
    return mask
