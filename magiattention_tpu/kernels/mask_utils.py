"""Traced mask construction from AttnSlice metadata arrays.

Device-side counterpart of ``common.mask`` (ref kernel contract:
magi_attention/functional/flex_flash_attn.py:1454-1466). Public metadata is
``q_ranges (N,2) int32``, ``k_ranges (N,2) int32``, ``attn_type_map (N,)
int32`` with 0=FULL, 1=CAUSAL, 2=INVCAUSAL, 3=BICAUSAL.

Internally every slice is normalized to an explicit diagonal band
``d_lo <= j - i <= d_hi`` (the reference's AttnRectangle d_range geometry,
common/rectangle.py:60-82): types only bound the band at construction time,
after which clipping slices in q or k — which the CP planner does constantly —
never changes the band. Empty slices (``q_start >= q_end``) are padding and
contribute nothing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# sentinel band bound: wide enough to be unbounded for any real seqlen,
# small enough that int32 arithmetic with coordinates cannot overflow
BAND_INF = 1 << 30


def types_to_bands(q_ranges, k_ranges, attn_type_map):
    """Convert (q_range, k_range, mask_type) to diagonal band bounds.

    Works on numpy or jnp arrays. Geometry (d = j - i, global coords):
      CAUSAL:    d <= k_end - q_end      (bottom-right aligned)
      INVCAUSAL: d >= k_start - q_start  (top-left aligned)
      BICAUSAL:  both;  FULL: unbounded.

    Returns:
        (d_lo, d_hi) int32 arrays of shape (N,).
    """
    import numpy as np

    t = attn_type_map
    is_causal = (t == 1) | (t == 3)
    is_inv = (t == 2) | (t == 3)
    hi_bound = k_ranges[:, 1] - q_ranges[:, 1]
    lo_bound = k_ranges[:, 0] - q_ranges[:, 0]
    if isinstance(t, np.ndarray):
        d_hi = np.where(is_causal, hi_bound, BAND_INF).astype(np.int32)
        d_lo = np.where(is_inv, lo_bound, -BAND_INF).astype(np.int32)
    else:
        d_hi = jnp.where(is_causal, hi_bound, BAND_INF).astype(jnp.int32)
        d_lo = jnp.where(is_inv, lo_bound, -BAND_INF).astype(jnp.int32)
    return d_lo, d_hi


def merge_band_slices(
    q_ranges,
    k_ranges,
    d_lo,
    d_hi,
):
    """Merge band-compatible adjacent slices (host numpy, exact).

    The TPU counterpart of the reference's kernel-entry range merge
    (magi_attention/functional/flex_flash_attn.py:87 merge_ranges, backed by
    csrc/extensions/unique_consecutive_pairs.cu). Because bands are encoded
    in GLOBAL coordinates (``d_lo <= j - i <= d_hi`` — see
    :func:`types_to_bands`), two rectangles with the SAME band whose k
    ranges are adjacent (or whose q ranges are adjacent, at equal k) union
    to one rectangle with that band — the merged slice covers exactly the
    same (i, j) pairs, so the kernel's output is mathematically identical
    while fragmented masks (e.g. per-block ranges from block-sparse /
    video masks) collapse into far fewer work items.

    Empty slices (``q_start >= q_end`` or ``k_start >= k_end``) are dropped
    (they are padding by contract). Returns ``(q_ranges, k_ranges, d_lo,
    d_hi)`` int32 arrays with at least one row: if every input slice was
    empty (or the input had zero rows), a single all-zero empty slice is
    synthesized so downstream plan builders never index into nothing.
    """
    import numpy as np

    qr = np.asarray(q_ranges, dtype=np.int64).reshape(-1, 2)
    kr = np.asarray(k_ranges, dtype=np.int64).reshape(-1, 2)
    lo = np.asarray(d_lo, dtype=np.int64).reshape(-1)
    hi = np.asarray(d_hi, dtype=np.int64).reshape(-1)

    keep = (qr[:, 0] < qr[:, 1]) & (kr[:, 0] < kr[:, 1])
    if not keep.any():
        empty = np.zeros((1, 2), np.int32)
        return (
            empty, empty.copy(),
            np.zeros(1, np.int32), np.zeros(1, np.int32),
        )
    rows = np.concatenate(
        [qr[keep], kr[keep], lo[keep, None], hi[keep, None]], axis=1
    )  # (n, 6): q0 q1 k0 k1 lo hi

    def sweep(rows, key_cols, adj_lo, adj_hi):
        """Sort by key_cols then merge maximal chains where all key_cols
        match and each row's [adj_lo] equals its predecessor's [adj_hi];
        the merged row spans [first.adj_lo, last.adj_hi). Fully vectorized
        — this sits in front of the native plan builder on fragmented
        masks with tens of thousands of slices, so no Python row loop."""
        order = np.lexsort(
            tuple(rows[:, c] for c in reversed(key_cols + [adj_lo]))
        )
        r = rows[order]
        n = len(r)
        start = np.ones(n, dtype=bool)
        if n > 1:
            same_key = np.ones(n - 1, dtype=bool)
            for c in key_cols:
                same_key &= r[1:, c] == r[:-1, c]
            start[1:] = ~(same_key & (r[1:, adj_lo] == r[:-1, adj_hi]))
        out = r[start].copy()
        starts = np.nonzero(start)[0]
        last = np.append(starts[1:] - 1, n - 1)
        out[:, adj_hi] = r[last, adj_hi]
        return out

    prev_n = -1
    while rows.shape[0] != prev_n:
        prev_n = rows.shape[0]
        # k-direction: same (q range, band), k-adjacent
        rows = sweep(rows, [0, 1, 4, 5], adj_lo=2, adj_hi=3)
        # q-direction: same (k range, band), q-adjacent
        rows = sweep(rows, [2, 3, 4, 5], adj_lo=0, adj_hi=1)
    return (
        rows[:, 0:2].astype(np.int32),
        rows[:, 2:4].astype(np.int32),
        rows[:, 4].astype(np.int32),
        rows[:, 5].astype(np.int32),
    )


def slice_block_mask_band(
    q_start, q_end, k_start, k_end, d_lo, d_hi, q_index, k_index
):
    """Boolean mask contribution of one band slice on a coordinate tile."""
    i = q_index[:, None]
    j = k_index[None, :]
    in_rect = (i >= q_start) & (i < q_end) & (j >= k_start) & (j < k_end)
    d = j - i
    return in_rect & (d >= d_lo) & (d <= d_hi)


def build_dense_mask_band(
    q_ranges: jax.Array,
    k_ranges: jax.Array,
    d_lo: jax.Array,
    d_hi: jax.Array,
    seqlen_q: int,
    seqlen_k: int,
    q_offset: int = 0,
    k_offset: int = 0,
) -> jax.Array:
    """Materialize the (seqlen_q, seqlen_k) boolean mask from band slices.

    O(N * sq * sk) via scan — testing / fallback path only; the Pallas kernel
    never materializes this.
    """
    q_index = q_offset + jnp.arange(seqlen_q, dtype=jnp.int32)
    k_index = k_offset + jnp.arange(seqlen_k, dtype=jnp.int32)

    def body(mask, slice_meta):
        qr, kr, lo, hi = slice_meta
        contrib = slice_block_mask_band(
            qr[0], qr[1], kr[0], kr[1], lo, hi, q_index, k_index
        )
        return mask | contrib, None

    init = jnp.zeros((seqlen_q, seqlen_k), dtype=jnp.bool_)
    mask, _ = jax.lax.scan(body, init, (q_ranges, k_ranges, d_lo, d_hi))
    return mask


def build_dense_mask(
    q_ranges: jax.Array,
    k_ranges: jax.Array,
    attn_type_map: jax.Array,
    seqlen_q: int,
    seqlen_k: int,
    q_offset: int = 0,
    k_offset: int = 0,
) -> jax.Array:
    """Type-based convenience wrapper over :func:`build_dense_mask_band`."""
    d_lo, d_hi = types_to_bands(q_ranges, k_ranges, attn_type_map)
    return build_dense_mask_band(
        q_ranges, k_ranges, d_lo, d_hi, seqlen_q, seqlen_k, q_offset, k_offset
    )
