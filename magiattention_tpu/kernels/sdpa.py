"""Dense jnp SDPA backend replaying AttnSlice metadata.

The numerical fake-backend substitute for the Pallas kernel (mirrors the
reference's sdpa backend strategy, magi_attention/functional/sdpa.py): same
``AttnArg`` contract, fp32/fp64 dense compute, differentiable via jax AD.
Testing / small-seqlen only — O(sq*sk) memory.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .mask_utils import build_dense_mask_band, types_to_bands

NEG_INF = float("-inf")


def sdpa_attn(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_ranges: jax.Array,
    k_ranges: jax.Array,
    attn_type_map: jax.Array | None = None,
    softmax_scale: float | None = None,
    softcap: float = 0.0,
    d_lo: jax.Array | None = None,
    d_hi: jax.Array | None = None,
    compute_dtype=jnp.float32,
) -> tuple[jax.Array, jax.Array]:
    """Compute flex attention densely.

    Args:
        q: ``[sq, hq, d]`` queries (varlen packed layout, no batch dim).
        k: ``[sk, hk, d]`` keys; ``hq % hk == 0`` (GQA).
        v: ``[sk, hk, dv]`` values.
        q_ranges/k_ranges/attn_type_map: slice metadata arrays (N,2)/(N,2)/(N,).

    Returns:
        out ``[sq, hq, dv]`` in q.dtype, lse ``[sq, hq]`` fp32 (natural log;
        ``-inf`` on fully-masked rows, whose out is 0).
    """
    sq, hq, d = q.shape
    sk, hk, dv = v.shape
    g = hq // hk
    if softmax_scale is None:
        softmax_scale = d ** -0.5

    if d_lo is None or d_hi is None:
        if attn_type_map is None:
            attn_type_map = jnp.zeros((q_ranges.shape[0],), dtype=jnp.int32)
        d_lo, d_hi = types_to_bands(q_ranges, k_ranges, attn_type_map)
    mask = build_dense_mask_band(q_ranges, k_ranges, d_lo, d_hi, sq, sk)

    qc = q.astype(compute_dtype)
    kc = jnp.repeat(k.astype(compute_dtype), g, axis=1)  # [sk, hq, d]
    vc = jnp.repeat(v.astype(compute_dtype), g, axis=1)  # [sk, hq, dv]

    # [hq, sq, sk]
    logits = jnp.einsum("qhd,khd->hqk", qc, kc) * softmax_scale
    if softcap > 0.0:
        logits = softcap * jnp.tanh(logits / softcap)
    logits = jnp.where(mask[None, :, :], logits, NEG_INF)

    lse = jax.scipy.special.logsumexp(logits, axis=-1)  # [hq, sq]
    # fully-masked rows: lse = -inf; make softmax output exact zeros
    p = jnp.exp(logits - jnp.where(jnp.isfinite(lse), lse, 0.0)[..., None])
    p = jnp.where(mask[None, :, :], p, 0.0)
    out = jnp.einsum("hqk,khd->qhd", p, vc)

    return out.astype(q.dtype), lse.T.astype(jnp.float32)


def dense_max_logits(
    q: jax.Array,
    k: jax.Array,
    q_ranges: jax.Array,
    k_ranges: jax.Array,
    attn_type_map: jax.Array | None = None,
    softmax_scale: float | None = None,
    softcap: float = 0.0,
    d_lo: jax.Array | None = None,
    d_hi: jax.Array | None = None,
    compute_dtype=jnp.float32,
) -> jax.Array:
    """Per-head max of the (scaled, softcapped) masked logits: ``[hq]`` fp32,
    -inf for heads with no attended entries. The dense oracle for the FFA
    kernel's max_logits output (ref common/forward_meta.py:21)."""
    sq, hq, d = q.shape
    sk, hk, _ = k.shape
    g = hq // hk
    if softmax_scale is None:
        softmax_scale = d ** -0.5
    if d_lo is None or d_hi is None:
        if attn_type_map is None:
            attn_type_map = jnp.zeros((q_ranges.shape[0],), dtype=jnp.int32)
        d_lo, d_hi = types_to_bands(q_ranges, k_ranges, attn_type_map)
    mask = build_dense_mask_band(q_ranges, k_ranges, d_lo, d_hi, sq, sk)
    qc = q.astype(compute_dtype)
    kc = jnp.repeat(k.astype(compute_dtype), g, axis=1)
    logits = jnp.einsum("qhd,khd->hqk", qc, kc) * softmax_scale
    if softcap > 0.0:
        logits = softcap * jnp.tanh(logits / softcap)
    logits = jnp.where(mask[None, :, :], logits, NEG_INF)
    return jnp.max(logits, axis=(1, 2)).astype(jnp.float32)
