"""Automatic FFA tile-size selection (the TPU analogue of the reference's
per-arch tile tables, ref magi_attention/functional/_flex_flash_attn_jit.py:41-57
and csrc/flexible_flash_attention/tile_size.h).

The reference hard-codes (head_dim, arch) -> tile tables tuned offline; on
TPU the equivalent decision is (block_q, block_k), and the right choice
depends on the *mask geometry*: wide dense masks amortize per-step
bookkeeping best with big tiles, narrow bands waste padded MXU work unless
tiles shrink. Because the host-side plan builder is cheap (native C path,
LRU-cached), the policy can *measure* each candidate's true padded work for
the actual slice set instead of guessing from mask type:

    score(bq, bk) = W * bq * bk            # padded elements actually run
                  + W * OVERHEAD_ELEMS     # per-grid-step fixed cost,
                                           # expressed in element units

``OVERHEAD_ELEMS`` is the one free constant (per-step softmax bookkeeping +
pipeline bubble, in score-matrix-element equivalents). It is deliberately
conservative pending silicon calibration from ``benchmarks/history``
sweeps; at 0 the policy reduces to pure padded-area minimization.

Selection is gated by ``MAGI_ATTENTION_FFA_AUTO_TILE=1`` and only applies
when the caller didn't pin blocks (env or argument) — explicit settings
always win, mirroring the reference's env-override contract.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import telemetry
from ..env.general import _get_int
from ..resilience.inject import maybe_inject

NUM_LANES = 128
# per-grid-step fixed cost in score-element equivalents: ~the VPU work of
# one (8, 128) bookkeeping pass per lane group. Refine from silicon sweeps
# (benchmarks/history/true_rate.csv A/Bs) — see docs/performance.md.
OVERHEAD_ELEMS = 8 * 1024
# candidate tilings: bq multiples of 8 (fp32) / MXU-friendly, bk multiples
# of 128 (lane tiling); spans the sweep grid the silicon harnesses measure.
# The small-bk rows exist for thin bands (sliding-window, varlen tails):
# a 128-wide band inside a 512-wide k tile runs 4x the padded MXU work,
# and the exact per-slice work counting below is what detects that.
CANDIDATES: tuple[tuple[int, int], ...] = (
    (128, 128),
    (256, 128),
    (512, 128),
    (128, 256),
    (256, 256),
    (512, 256),
    (128, 512),
    (256, 512),
    (256, 1024),
    (512, 512),
    (512, 1024),
    (1024, 512),
    (1024, 1024),
)
# VMEM budget for one grid step's resident blocks (bytes), double-buffered;
# ~16 MB/core on v5e minus headroom
VMEM_BUDGET = 10 * 1024 * 1024


def auto_tile_enabled() -> bool:
    return _get_int("MAGI_ATTENTION_FFA_AUTO_TILE", 0) == 1


def _overhead_elems() -> float:
    """The per-grid-step fixed cost the scorers charge: the built-in
    :data:`OVERHEAD_ELEMS` constant, or the store-fitted value when the
    performance observatory's calibration loop is on
    (telemetry/drift.fit_constants writes it; store.calibrated gates on
    telemetry + MAGI_ATTENTION_CALIBRATION, so with the observatory off
    this is exactly the constant and scores are bit-identical)."""
    from ..env import backend as env_backend

    if not env_backend.calibration_enabled():
        return OVERHEAD_ELEMS
    from ..telemetry import store as _tstore

    return _tstore.calibrated("overhead_elems", OVERHEAD_ELEMS)


def count_ffa_work(
    qr: np.ndarray,
    kr: np.ndarray,
    d_lo: np.ndarray,
    d_hi: np.ndarray,
    sq: int,
    sk: int,
    bq: int,
    bk: int,
) -> int:
    """Exact work-item count of :func:`ffa_plan.build_ffa_plan` for this
    tiling WITHOUT building (or LRU-caching) the plan arrays — candidate
    scoring must not evict live plans from the shared plan cache.

    One work item per (slice, q_tile, k_tile) whose diagonal band
    intersects the clipped tile rect (per q tile the intersecting k tiles
    form one contiguous run, so that part is closed-form per (slice,
    q_tile)) — plus the builder's one dummy item for every q tile whose
    bucket stays empty (those tiles still need a grid step to write their
    zeros/-inf outputs). Parity with the builder is pinned by test.
    """
    total = 0
    num_q_tiles = max(1, -(-sq // bq))
    num_k_tiles = max(1, -(-sk // bk))
    covered = np.zeros(num_q_tiles, dtype=bool)
    for s in range(len(qr)):
        qs, qe = int(qr[s, 0]), int(qr[s, 1])
        ks, ke = int(kr[s, 0]), int(kr[s, 1])
        lo, hi = int(d_lo[s]), int(d_hi[s])
        if qs >= qe or ks >= ke or lo > hi:
            continue
        t = np.arange(qs // bq, (qe - 1) // bq + 1, dtype=np.int64)
        i0 = np.maximum(qs, t * bq)  # clipped row span per q tile
        i1 = np.minimum(qe, (t + 1) * bq)
        # attended column window of the clipped rows, clipped to [ks, ke)
        j0 = np.maximum(ks, i0 + lo)
        j1 = np.minimum(ke - 1, (i1 - 1) + hi)
        nonempty = j0 <= j1  # empty window ⟺ band misses the clipped rect
        kt0 = np.clip(j0 // bk, 0, num_k_tiles - 1)
        kt1 = np.clip(j1 // bk, 0, num_k_tiles - 1)
        total += int(np.sum((kt1 - kt0 + 1)[nonempty]))
        covered[t[nonempty]] = True
    return total + int(num_q_tiles - covered.sum())


def count_ffa_work_t(
    qr: np.ndarray,
    kr: np.ndarray,
    d_lo: np.ndarray,
    d_hi: np.ndarray,
    sq: int,
    sk: int,
    bq: int,
    bk: int,
) -> int:
    """Exact K-MAJOR work-item count (the dkv pass's grid length) for this
    tiling, mirroring :func:`count_ffa_work`'s closed form with the roles
    of q and k swapped: one item per (slice, k_tile, q_tile) whose band
    intersects the clipped tile rect (per k tile the attended row span is
    one interval, so the intersecting q tiles form a contiguous run), plus
    the builder's one dummy item per never-covered k tile (those still
    need a grid step to write their zero dk/dv). Parity with the builder's
    ``num_work_t`` is pinned by test.
    """
    total = 0
    num_q_tiles = max(1, -(-sq // bq))
    num_k_tiles = max(1, -(-sk // bk))
    covered = np.zeros(num_k_tiles, dtype=bool)
    for s in range(len(qr)):
        qs, qe = int(qr[s, 0]), int(qr[s, 1])
        ks, ke = int(kr[s, 0]), int(kr[s, 1])
        lo, hi = int(d_lo[s]), int(d_hi[s])
        if qs >= qe or ks >= ke or lo > hi:
            continue
        t = np.arange(ks // bk, (ke - 1) // bk + 1, dtype=np.int64)
        j0 = np.maximum(ks, t * bk)  # clipped col span per k tile
        j1 = np.minimum(ke, (t + 1) * bk)
        # attended row window of the clipped cols (lo <= j - i <= hi  ⟺
        # j - hi <= i <= j - lo), clipped to [qs, qe)
        i0 = np.maximum(qs, j0 - hi)
        i1 = np.minimum(qe - 1, (j1 - 1) - lo)
        nonempty = i0 <= i1
        qt0 = np.clip(i0 // bq, 0, num_q_tiles - 1)
        qt1 = np.clip(i1 // bq, 0, num_q_tiles - 1)
        total += int(np.sum((qt1 - qt0 + 1)[nonempty]))
        covered[t[nonempty]] = True
    return total + int(num_k_tiles - covered.sum())


def _vmem_bytes(bq: int, bk: int, d: int, dv: int, itemsize: int) -> int:
    """Per-step fwd-kernel VMEM residency — ONE estimator for the whole
    package (utils/mem_budget.ffa_vmem_budget)."""
    from ..utils.mem_budget import ffa_vmem_budget

    return ffa_vmem_budget(bq, bk, d, head_dim_v=dv, dtype_bytes=itemsize)


def choose_blocks_multi(
    rank_geoms: list[tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]],
    sq: int,
    sk: int,
    d: int = 128,
    dv: int = 128,
    itemsize: int = 2,
) -> tuple[int, int]:
    """Pick (block_q, block_k) minimizing modeled kernel time over a group
    of per-rank slice sets that share one padded grid (the CP runtime
    stacks per-rank plans padded to the max work count, so every rank runs
    max-W grid steps): score = max_rank(W) * (bq*bk + OVERHEAD_ELEMS),
    VMEM-guarded. Falls back to the clamped default if every candidate is
    excluded."""
    maybe_inject("vmem_check")
    seen: set[tuple[int, int]] = set()
    best = None
    best_score = None
    ov = _overhead_elems()
    for bq, bk in CANDIDATES:
        # clamp to the problem (same rule as default_blocks), then dedupe
        bq = min(bq, _round_up(sq, 16))
        bk = min(bk, _round_up(sk, NUM_LANES))
        if (bq, bk) in seen:
            continue
        seen.add((bq, bk))
        if _vmem_bytes(bq, bk, d, dv, itemsize) > VMEM_BUDGET:
            continue
        w = max(
            count_ffa_work(qr, kr, lo, hi, sq, sk, bq, bk)
            for qr, kr, lo, hi in rank_geoms
        )
        score = w * (bq * bk + ov)
        if best_score is None or score < best_score:
            best, best_score = (bq, bk), score
    chosen = best or (
        min(256, _round_up(sq, 16)), min(512, _round_up(sk, NUM_LANES))
    )
    if telemetry.enabled():
        telemetry.record_event(
            "tile_policy",
            mode="fwd_only",
            sq=sq, sk=sk, d=d, dv=dv, itemsize=itemsize,
            num_geoms=len(rank_geoms),
            candidates_scored=len(seen),
            fwd_blocks=list(chosen),
            fallback=best is None,
        )
    return chosen


def choose_blocks(
    qr: np.ndarray,
    kr: np.ndarray,
    d_lo: np.ndarray,
    d_hi: np.ndarray,
    sq: int,
    sk: int,
    d: int,
    dv: int,
    itemsize: int = 2,
) -> tuple[int, int]:
    """Single-slice-set entry of :func:`choose_blocks_multi`."""
    return choose_blocks_multi(
        [(qr, kr, d_lo, d_hi)], sq, sk, d, dv, itemsize
    )


def _bwd_vmem_bytes(
    kind: str, bq: int, bk: int, d: int, dv: int, itemsize: int
) -> int:
    """Per-step VMEM residency of the bwd kernels — ONE estimator for the
    whole package (utils/mem_budget.ffa_bwd_vmem_budget), shared with the
    static kernel checker (analysis/kernel_check K1) and verifier R5."""
    from ..utils.mem_budget import ffa_bwd_vmem_budget

    return ffa_bwd_vmem_budget(kind, bq, bk, d, head_dim_v=dv, dtype_bytes=itemsize)


def _band_candidates(
    rank_geoms: list, sq: int, sk: int
) -> tuple[tuple[int, int], ...]:
    """CANDIDATES extended with a block_k derived from the narrowest
    band in the slice set: thin bands (sliding window, varlen tails)
    waste padded MXU columns in any k tile wider than the band, so the
    band width itself (rounded up to the lane quantum) is always worth
    scoring alongside the fixed grid."""
    widths = []
    for qr, kr, lo, hi in rank_geoms:
        for s in range(len(qr)):
            if qr[s, 0] >= qr[s, 1] or kr[s, 0] >= kr[s, 1]:
                continue
            band = int(hi[s]) - int(lo[s]) + 1
            rect = int(kr[s, 1]) - int(kr[s, 0])
            widths.append(min(max(band, 0), rect))
    if not widths:
        return CANDIDATES
    bk_band = min(max(_round_up(min(widths), NUM_LANES), NUM_LANES), 1024)
    extra = tuple((bq, bk_band) for bq in (128, 256, 512))
    return CANDIDATES + extra


def choose_blocks_per_pass_multi(
    rank_geoms: list[tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]],
    sq: int,
    sk: int,
    d: int = 128,
    dv: int = 128,
    itemsize: int = 2,
) -> tuple[
    tuple[int, int], tuple[int, int] | None, tuple[int, int] | None
]:
    """Per-PASS tile choice: ``(fwd_blocks, dq_blocks, dkv_blocks)``.

    The three passes score differently over the same slice set: fwd and
    dq run the q-major plan, dkv the k-major plan (its work count — and
    so its padded-area profile — differs whenever bands are thin or
    ragged), and each pass has its own VMEM residency (the dkv kernel
    holds (bk, d+dv) fp32 scratch). A bwd entry is None when the fwd
    choice is already optimal for that pass (inherit — the plan tuple
    stays at 6 arrays). Bwd candidates are constrained to divide the
    fwd-padded geometry, the same gate :func:`ffa.resolve_bwd_overrides`
    applies to env overrides.
    """
    maybe_inject("vmem_check")
    cands = _band_candidates(rank_geoms, sq, sk)
    ov = _overhead_elems()

    def score_pass(kind: str, allowed=None):
        seen: set[tuple[int, int]] = set()
        best = None
        best_score = None
        counter = count_ffa_work_t if kind == "dkv" else count_ffa_work
        for bq, bk in cands:
            bq = min(bq, _round_up(sq, 16))
            bk = min(bk, _round_up(sk, NUM_LANES))
            if (bq, bk) in seen:
                continue
            seen.add((bq, bk))
            if allowed is not None and not allowed(bq, bk):
                continue
            if kind == "fwd":
                vmem = _vmem_bytes(bq, bk, d, dv, itemsize)
            else:
                vmem = _bwd_vmem_bytes(kind, bq, bk, d, dv, itemsize)
            if vmem > VMEM_BUDGET:
                continue
            w = max(
                counter(qr, kr, lo, hi, sq, sk, bq, bk)
                for qr, kr, lo, hi in rank_geoms
            )
            score = w * (bq * bk + ov)
            if best_score is None or score < best_score:
                best, best_score = (bq, bk), score
        return best

    fwd = score_pass("fwd") or (
        min(256, _round_up(sq, 16)), min(512, _round_up(sk, NUM_LANES))
    )
    sqp = _round_up(sq, fwd[0])
    skp = _round_up(sk, fwd[1])

    def divides(bq: int, bk: int) -> bool:
        return sqp % bq == 0 and skp % bk == 0

    dq = score_pass("dq", allowed=divides)
    dkv = score_pass("dkv", allowed=divides)
    if dq == fwd:
        dq = None
    if dkv == fwd:
        dkv = None
    if telemetry.enabled():
        telemetry.record_event(
            "tile_policy",
            mode="per_pass",
            sq=sq, sk=sk, d=d, dv=dv, itemsize=itemsize,
            num_geoms=len(rank_geoms),
            candidates_scored=len(cands),
            fwd_blocks=list(fwd),
            # None = inherit fwd (the plan tuple stays at 6 arrays)
            dq_blocks=list(dq) if dq else None,
            dkv_blocks=list(dkv) if dkv else None,
        )
    return fwd, dq, dkv


def choose_blocks_per_pass(
    qr: np.ndarray,
    kr: np.ndarray,
    d_lo: np.ndarray,
    d_hi: np.ndarray,
    sq: int,
    sk: int,
    d: int,
    dv: int,
    itemsize: int = 2,
) -> tuple[
    tuple[int, int], tuple[int, int] | None, tuple[int, int] | None
]:
    """Single-slice-set entry of :func:`choose_blocks_per_pass_multi`."""
    return choose_blocks_per_pass_multi(
        [(qr, kr, d_lo, d_hi)], sq, sk, d, dv, itemsize
    )


def reachable_block_space(
    sq: int,
    sk: int,
    kind: str = "fwd",
    d: int = 128,
    dv: int = 128,
    itemsize: int = 2,
) -> list[tuple[int, int]]:
    """Every ``(block_q, block_k)`` this policy can emit for a pass of the
    given ``kind`` ("fwd" | "dq" | "dkv") at problem size (sq, sk) —
    the closure the static kernel checker (analysis/kernel_check) proves
    K1/K3 over, so a tiling the policy can choose is by construction a
    tiling the checker has audited.

    The space is the union of:

    - the clamped default (``ffa.default_blocks`` fallback, also the
      score-loop fallback when every candidate busts VMEM),
    - every VMEM-feasible clamped :data:`CANDIDATES` entry,
    - the band-derived grid ``{128, 256, 512} x {128, 256, ..., 1024}``
      (:func:`_band_candidates` emits ``bk_band`` = the narrowest band
      width rounded to the lane quantum and clamped to [128, 1024] —
      data-dependent, so the whole reachable range is enumerated).

    Env overrides (MAGI_ATTENTION_FFA_BLOCK_*) are intentionally NOT
    bounded here: they pass through ``resolve_bwd_overrides``'s
    divisibility/quantum gate and the kernels' own VMEM dispatch guards,
    and the audit CLI checks the documented defaults explicitly.
    """
    if kind not in ("fwd", "dq", "dkv"):
        raise ValueError(f"kind must be 'fwd'|'dq'|'dkv', got {kind!r}")
    cands = set(CANDIDATES)
    cands.update(
        (bq, bk_band)
        for bq in (128, 256, 512)
        for bk_band in range(NUM_LANES, 1024 + 1, NUM_LANES)
    )
    space: set[tuple[int, int]] = set()
    for bq, bk in cands:
        bq = min(bq, _round_up(sq, 16))
        bk = min(bk, _round_up(sk, NUM_LANES))
        if kind == "fwd":
            vmem = _vmem_bytes(bq, bk, d, dv, itemsize)
        else:
            vmem = _bwd_vmem_bytes(kind, bq, bk, d, dv, itemsize)
        if vmem > VMEM_BUDGET:
            continue
        space.add((bq, bk))
    # the clamped default is reachable regardless of the VMEM filter
    # (score-loop fallback + ffa.default_blocks)
    space.add(
        (min(256, _round_up(sq, 16)), min(512, _round_up(sk, NUM_LANES)))
    )
    return sorted(space)


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


# ---------------------------------------------------------------------------
# Mixed-granularity dispatch: per-slice fragmentation + two-pass plan split.
#
# A single (block_q, block_k) choice is a compromise: dense slices amortize
# per-step overhead best under big tiles, while fragmented slices (block-
# sparse, video windows) waste most of each big tile on padding. When the
# gap is large enough, splitting the slice set into a coarse-block dense
# pass and a fine-block fragmented pass — merged through the standard LSE
# merge — beats any single tiling. The split is judged by the same exact
# work counters the tile scorer uses, so the decision cannot drift from
# what the plans actually cost.
# ---------------------------------------------------------------------------

# a slice is "fragmented" when its tile cover runs >= 2x its band area
FRAG_THRESHOLD = 2.0
# LSE-merge overhead in score-element equivalents: one extra read+combine
# pass over out/lse rows (VPU) plus the second pass's outputs round-tripping
# HBM — charged per merged q row at lane granularity
MERGE_OVERHEAD_PER_ROW = 2 * NUM_LANES


def slice_cover_tiles(
    qr: np.ndarray,
    kr: np.ndarray,
    d_lo: np.ndarray,
    d_hi: np.ndarray,
    block_q: int,
    block_k: int,
) -> np.ndarray:
    """Per-slice count of (q_tile, k_tile) pairs the slice's band touches.

    Per q tile of a slice the intersecting k tiles form one contiguous run
    (the band's column window of the clipped rows is a single interval),
    so the cover is closed-form per (slice, q_tile) — same counting core
    as :func:`count_ffa_work`, kept per-slice instead of summed, and
    without the one-dummy-per-empty-q-tile floor the grid needs.
    """
    n = len(qr)
    tiles = np.zeros(n, dtype=np.int64)
    for s in range(n):
        qs, qe = int(qr[s, 0]), int(qr[s, 1])
        ks, ke = int(kr[s, 0]), int(kr[s, 1])
        lo, hi = int(d_lo[s]), int(d_hi[s])
        if qs >= qe or ks >= ke or lo > hi:
            continue
        t = np.arange(qs // block_q, (qe - 1) // block_q + 1, dtype=np.int64)
        i0 = np.maximum(qs, t * block_q)
        i1 = np.minimum(qe, (t + 1) * block_q)
        j0 = np.maximum(ks, i0 + lo)
        j1 = np.minimum(ke - 1, (i1 - 1) + hi)
        nonempty = j0 <= j1
        tiles[s] = int(np.sum((j1 // block_k - j0 // block_k + 1)[nonempty]))
    return tiles


def slice_cover_ratios(
    qr: np.ndarray,
    kr: np.ndarray,
    d_lo: np.ndarray,
    d_hi: np.ndarray,
    block_q: int,
    block_k: int,
) -> np.ndarray:
    """Per-slice fragmentation ratio: padded tile-cover elements / band
    elements under this tiling. 1.0 = the tiles fit the band exactly;
    large values flag slices whose tiles are mostly padding. Empty or
    degenerate slices get ratio 1.0 (nothing to rescue).
    """
    from .. import telemetry as _telemetry

    n = len(qr)
    tiles = slice_cover_tiles(qr, kr, d_lo, d_hi, block_q, block_k)
    ratios = np.ones(n, dtype=np.float64)
    for s in range(n):
        if tiles[s] <= 0:
            continue
        band = _telemetry.band_area(
            qr[s : s + 1], kr[s : s + 1], d_lo[s : s + 1], d_hi[s : s + 1]
        )
        if band <= 0:
            continue
        ratios[s] = int(tiles[s]) * block_q * block_k / band
    return ratios


@dataclass(frozen=True)
class MixedDispatch:
    """A profitable two-pass split of one slice set."""

    dense_idx: np.ndarray  # slice indices for the coarse-block pass
    frag_idx: np.ndarray  # slice indices for the fine-block pass
    coarse_blocks: tuple[int, int]
    fine_blocks: tuple[int, int]
    single_score: int  # modeled cost of coarse blocks over ALL slices
    split_score: int  # modeled cost of the split incl. merge overhead


def choose_mixed_dispatch(
    qr: np.ndarray,
    kr: np.ndarray,
    d_lo: np.ndarray,
    d_hi: np.ndarray,
    sq: int,
    sk: int,
    d: int = 128,
    dv: int = 128,
    itemsize: int = 2,
    coarse_blocks: tuple[int, int] | None = None,
) -> MixedDispatch | None:
    """Decide whether to split the slice set into a coarse-block dense pass
    plus a fine-block fragmented pass (merged via LSE merge), or run one
    plan as usual (None).

    Selection flows through the backend registry's ``ffa_dispatch``
    decision (kernels/registry.py): a 'single'/'mixed' pin
    (MAGI_ATTENTION_BACKEND_MIXED_BLOCKS, or the legacy
    MAGI_ATTENTION_FFA_MIXED_BLOCKS mapped 0/1) wins — 'mixed' still
    degrades to None when the mask yields no non-trivial partition with
    distinct tilings; unpinned geometries resolve against the policy cache
    / measured history, falling back to the cost model: split wins when
    score(coarse on dense) + score(fine on fragmented) + merge overhead <
    score(coarse on everything), with score the same padded-work +
    per-step-overhead model the tile scorer minimizes.
    """
    from ..env import backend as env_backend
    from . import registry as _registry

    pin = env_backend.mixed_blocks_pin()
    if pin == "single" or len(qr) < 2:
        return None
    coarse = coarse_blocks or (
        min(256, _round_up(sq, 16)), min(512, _round_up(sk, NUM_LANES))
    )
    ratios = slice_cover_ratios(qr, kr, d_lo, d_hi, coarse[0], coarse[1])
    frag = ratios >= FRAG_THRESHOLD
    frag_idx = np.nonzero(frag)[0]
    dense_idx = np.nonzero(~frag)[0]
    if len(frag_idx) == 0 or len(dense_idx) == 0:
        return None
    fi = frag_idx
    fine = choose_blocks(
        qr[fi], kr[fi], d_lo[fi], d_hi[fi], sq, sk, d, dv, itemsize
    )
    if fine == coarse:
        return None

    ov = _overhead_elems()

    def score(idx: np.ndarray, blocks: tuple[int, int]) -> int:
        # grid steps (incl. one dummy per empty q tile) pay fixed overhead;
        # only band-touching tiles pay compute — with extent clamping on,
        # dummy items skip their dots entirely, so charging them a full
        # bq*bk tile would bias auto mode against fine-block passes
        w = count_ffa_work(
            qr[idx], kr[idx], d_lo[idx], d_hi[idx],
            sq, sk, blocks[0], blocks[1],
        )
        tiles = int(
            slice_cover_tiles(
                qr[idx], kr[idx], d_lo[idx], d_hi[idx], blocks[0], blocks[1]
            ).sum()
        )
        return tiles * blocks[0] * blocks[1] + w * ov

    all_idx = np.arange(len(qr))
    single = score(all_idx, coarse)
    split = (
        score(dense_idx, coarse)
        + score(frag_idx, fine)
        + sq * MERGE_OVERHEAD_PER_ROW
    )
    profitable = split < single
    if pin == "mixed":
        choice = "mixed"
    else:
        key = _mixed_dispatch_key(
            qr, kr, d_lo, d_hi, sq, sk, d, dv, itemsize, coarse
        )
        choice = _registry.resolve(
            "ffa_dispatch", key,
            lambda: "mixed" if profitable else "single",
        ).name
    if choice != "mixed":
        return None
    result = MixedDispatch(
        dense_idx=dense_idx,
        frag_idx=frag_idx,
        coarse_blocks=coarse,
        fine_blocks=fine,
        single_score=single,
        split_score=split,
    )
    if telemetry.enabled():
        telemetry.record_event(
            "mixed_dispatch",
            num_slices=len(qr),
            num_dense=len(dense_idx),
            num_frag=len(frag_idx),
            coarse_blocks=list(coarse),
            fine_blocks=list(fine),
            single_score=single,
            split_score=split,
            forced=not profitable,
        )
    return result


def _mixed_dispatch_key(
    qr, kr, d_lo, d_hi, sq, sk, d, dv, itemsize, coarse
) -> tuple:
    """Registry/store key of one mixed-dispatch decision: a digest of the
    slice geometry (the mask-class signature) plus the static dims the
    cost model consumes."""
    import hashlib

    h = hashlib.md5()
    for arr in (qr, kr, d_lo, d_hi):
        h.update(np.ascontiguousarray(arr).tobytes())
    return (h.hexdigest()[:16], sq, sk, d, dv, itemsize, coarse[0], coarse[1])


# ---------------------------------------------------------------------------
# Backward execution mode: fused one-pass vs split dq + dkv.
#
# Per work item the split backward spends 7 tile matmuls (dq pass: s, dp,
# dq; dkv pass: s_t, dp_t, dk, dv) where the fused kernel spends 5 (s_t,
# dp_t, dk, dv, dq) — the FlashAttention-2 work-partitioning count — and
# the fused pass streams q/k/v/do from HBM once instead of twice, at the
# price of a per-step fp32 read-modify-write of the revisited dq window.
# The chooser models both terms from the STATIC plan counts (work items,
# blocks, dims) so the decision is trace-time stable.
# ---------------------------------------------------------------------------

# tile matmuls per work item (asserted 7 -> 5 by unit test)
BWD_TILE_MATMULS_SPLIT_DQ = 3  # s, dp, dq
BWD_TILE_MATMULS_SPLIT_DKV = 4  # s_t, dp_t, dk, dv
BWD_TILE_MATMULS_SPLIT = BWD_TILE_MATMULS_SPLIT_DQ + BWD_TILE_MATMULS_SPLIT_DKV
BWD_TILE_MATMULS_FUSED = 5  # s_t, dp_t, dk, dv, dq
# MXU MAC-elements per HBM byte at which compute and memory time balance
# (~v5e: 197 TF/s bf16 against 819 GB/s ≈ 240); converts the HBM term into
# the same element units the MXU term is counted in
BWD_MXU_ELEMS_PER_HBM_BYTE = 240


def bwd_mxu_elems(
    mode: str,
    w_dq: int,
    bq_dq: int,
    bk_dq: int,
    wt: int,
    bq_dkv: int,
    bk_dkv: int,
    d: int,
) -> int:
    """MXU MAC-element count of one backward under ``mode`` ("split" |
    "fused"): tile matmuls per work item x the item's (bq, bk, d) MAC
    volume. Under equal blocks and equal work counts the split/fused
    ratio is exactly 7/5 — the fusion's recompute saving."""
    if mode == "split":
        return (
            BWD_TILE_MATMULS_SPLIT_DQ * w_dq * bq_dq * bk_dq * d
            + BWD_TILE_MATMULS_SPLIT_DKV * wt * bq_dkv * bk_dkv * d
        )
    return BWD_TILE_MATMULS_FUSED * wt * bq_dkv * bk_dkv * d


def bwd_hbm_bytes(
    mode: str,
    w_dq: int,
    bq_dq: int,
    bk_dq: int,
    wt: int,
    bq_dkv: int,
    bk_dkv: int,
    d: int,
    dv: int,
    itemsize: int = 2,
    group: int = 1,
) -> int:
    """Modeled HBM bytes streamed by one backward under ``mode``: per grid
    item, the operand blocks fetched plus the output blocks written. The
    fused mode drops the dq pass's whole stream but adds the revisited dq
    window's fp32 read-modify-write every step."""
    g = group
    dq_stream = (
        (bq_dq * d + bk_dq * d + bk_dq * dv + bq_dq * dv) * itemsize
        + bq_dq * d * 4  # fp32 dq out
    )
    dkv_stream = (
        (g * bq_dkv * d + bk_dkv * d + bk_dkv * dv + g * bq_dkv * dv)
        * itemsize
        + (bk_dkv * d + bk_dkv * dv) * 4  # fp32 dk/dv outs
    )
    if mode == "split":
        return w_dq * dq_stream + wt * dkv_stream
    # fused: one pass, plus 2x the fp32 dq window (read + write) per step
    return wt * (dkv_stream + 2 * g * bq_dkv * d * 4)


def choose_bwd_mode(
    w_dq: int,
    bq_dq: int,
    bk_dq: int,
    wt: int,
    bq_dkv: int,
    bk_dkv: int,
    d: int,
    dv: int,
    itemsize: int = 2,
    group: int = 1,
) -> str:
    """"fused" or "split" by modeled cost (MXU elems + balanced HBM term).

    Fused wins whenever the two plans are comparably sized (the common
    case: 5/7 the recompute and half the operand streams); split wins when
    the q-major dq plan is much cheaper than the k-major plan — e.g. a
    mask whose k-major tiling fragments far worse than its q-major one,
    where rerunning the cheap dq pass beats dragging dq through every
    k-major step's fp32 window RMW. Feasibility (VMEM, plan meta columns)
    is the caller's job (kernels/ffa.ffa_bwd_mode)."""
    args = (w_dq, bq_dq, bk_dq, wt, bq_dkv, bk_dkv, d)
    hbm = (dv, itemsize, group)
    split_cost = bwd_mxu_elems("split", *args) + (
        BWD_MXU_ELEMS_PER_HBM_BYTE * bwd_hbm_bytes("split", *args, *hbm)
    )
    fused_cost = bwd_mxu_elems("fused", *args) + (
        BWD_MXU_ELEMS_PER_HBM_BYTE * bwd_hbm_bytes("fused", *args, *hbm)
    )
    return "fused" if fused_cost <= split_cost else "split"
