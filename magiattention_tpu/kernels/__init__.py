"""Device compute kernels: Pallas FFA + jnp reference backends."""

from .paged_decode import paged_decode_attn  # noqa: F401
from .paged_kv import (  # noqa: F401
    PagedKVCache,
    append_kv,
    assign_pages,
    gather_kv,
    paged_attn,
)

__all__ = [
    "PagedKVCache",
    "append_kv",
    "assign_pages",
    "gather_kv",
    "paged_attn",
    "paged_decode_attn",
]
