"""Device compute kernels: Pallas FFA + jnp reference backends."""
