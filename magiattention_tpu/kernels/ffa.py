"""Flex-flash-attention Pallas TPU kernels (fwd + bwd).

TPU-native counterpart of the reference FFA CUDA kernel
(magi_attention/csrc/flexible_flash_attention/ — fwd/bwd mainloops, tile
schedulers, mask.h). Design differences, deliberate and TPU-first:

- The device-side persistent tile scheduler is replaced by a host-side plan
  (:mod:`ffa_plan`) + ``PrefetchScalarGridSpec``: the grid is exactly the list
  of non-empty (q_tile, k_tile, slice) work items, so fully-masked tiles cost
  nothing and no dynamic control flow reaches the MXU. Plan *contents* may be
  traced arrays (per-CP-rank metadata under shard_map); only the work counts
  and tile geometry are static.
- The atomic-reduce epilogues (epilogue_fwd.hpp / epilogue_bwd.hpp) are
  replaced by run-ordering: all work items of one output tile are consecutive
  grid steps accumulating into VMEM scratch; the tile is written once at the
  end of its run. dq uses the q-major plan, dk/dv the k-major plan — no
  atomics exist on TPU and none are needed.
- Slices are diagonal bands (d_lo <= j - i <= d_hi): the mask is two compares.
- Online-softmax merge math matches functional/utils.py (lse in natural log,
  -inf on fully-masked rows).

Layouts inside the kernels are head-major ``[h, s, d]`` so each block is a
contiguous ``(s_tile, d)`` matrix on the MXU.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..env import general as env_general
from ..env import kernel as env_kernel
from .ffa_plan import (  # noqa: F401
    IS_FULL,
    DHI,
    DLO,
    IS_FIRST,
    IS_LAST,
    KE,
    KS,
    QE,
    QS,
    FFAPlan,
    get_ffa_plan,
)
from .mask_utils import types_to_bands

NEG_INF = float("-inf")


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


@dataclass(frozen=True, eq=False)
class FFAParams:
    """Static kernel parameters (hashable by identity for custom_vjp)."""

    num_work: int
    num_work_t: int
    num_q_tiles: int
    num_k_tiles: int
    block_q: int
    block_k: int
    softmax_scale: float
    softcap: float
    group: int  # hq // hk
    interpret: bool


def plan_arrays(plan: FFAPlan) -> tuple[jax.Array, ...]:
    """The 6 device arrays of a plan (q-major triple + k-major triple)."""
    return (
        jnp.asarray(plan.work_qt),
        jnp.asarray(plan.work_kt),
        jnp.asarray(plan.meta),
        jnp.asarray(plan.work_qt_t),
        jnp.asarray(plan.work_kt_t),
        jnp.asarray(plan.meta_t),
    )


def _item_mask(
    meta_ref, w, q_base, k_base, bq: int, bk: int, transposed: bool = False
):
    """Boolean mask of work item w on the tile at (q_base, k_base).

    Shape (bq, bk) with q rows, or (bk, bq) when ``transposed`` (k rows) —
    built directly with swapped iota since Mosaic cannot transpose i1 vectors.
    """
    qs, qe = meta_ref[w, QS], meta_ref[w, QE]
    ks, ke = meta_ref[w, KS], meta_ref[w, KE]
    lo, hi = meta_ref[w, DLO], meta_ref[w, DHI]
    if transposed:
        rows = q_base + jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 1)
        cols = k_base + jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 0)
    else:
        rows = q_base + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = k_base + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    in_rect = (rows >= qs) & (rows < qe) & (cols >= ks) & (cols < ke)
    d = cols - rows
    return in_rect & (d >= lo) & (d <= hi)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    work_qt_ref,
    work_kt_ref,
    meta_ref,
    q_ref,
    k_ref,
    v_ref,
    out_ref,
    lse_ref,
    m_scr,
    l_scr,
    acc_scr,
    *,
    scale: float,
    softcap: float,
    bq: int,
    bk: int,
):
    w = pl.program_id(1)
    is_first = meta_ref[w, IS_FIRST]
    is_last = meta_ref[w, IS_LAST]
    q_base = work_qt_ref[w] * bq
    k_base = work_kt_ref[w] * bk

    @pl.when(is_first == 1)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0]
    k = k_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if softcap > 0.0:
        s = softcap * jnp.tanh(s / softcap)
    # interior (fully-unmasked) tiles skip the mask build + select entirely
    # — the TPU analogue of the reference schedulers' full-tile fast path
    s = jax.lax.cond(
        meta_ref[w, IS_FULL] == 1,
        lambda s: s,
        lambda s: jnp.where(
            _item_mask(meta_ref, w, q_base, k_base, bq, bk), s, NEG_INF
        ),
        s,
    )

    m_prev = m_scr[:, :1]  # (bq, 1)
    m_blk = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_blk)
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - m_safe)  # exp(-inf - finite) == 0: no re-masking needed
    alpha = jnp.exp(m_prev - m_safe)  # 0 when m_prev = -inf, m_safe finite
    alpha = jnp.where(jnp.isneginf(m_prev) & jnp.isneginf(m_new), 0.0, alpha)

    l_new = l_scr[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
    pv = jax.lax.dot_general(
        p.astype(v_ref.dtype),
        v_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_scr[:] = acc_scr[:] * alpha + pv
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(is_last == 1)
    def _():
        l = l_scr[:, :1]
        empty = l == 0.0
        l_safe = jnp.where(empty, 1.0, l)
        out_ref[0] = (acc_scr[:] / l_safe).astype(out_ref.dtype)
        lse = jnp.where(
            empty[:, 0], NEG_INF, m_scr[:, 0] + jnp.log(l_safe[:, 0])
        )
        lse_ref[...] = lse.astype(jnp.float32)[:, None]


def _ffa_fwd_pallas(params: FFAParams, work_qt, work_kt, meta, q_t, k_t, v_t):
    """q_t/k_t/v_t are head-major padded: [hq,sqp,d], [hk,skp,d], [hk,skp,dv]."""
    bq, bk = params.block_q, params.block_k
    hq, sqp, d = q_t.shape
    hk, skp, dv = v_t.shape
    g = params.group
    W = params.num_work

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(hq, W),
        in_specs=[
            pl.BlockSpec(
                (1, bq, d), lambda h, w, qt, kt, mt: (h, qt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, bk, d), lambda h, w, qt, kt, mt: (h // g, kt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, bk, dv), lambda h, w, qt, kt, mt: (h // g, kt[w], 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, bq, dv), lambda h, w, qt, kt, mt: (h, qt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (None, bq, 1), lambda h, w, qt, kt, mt: (h, qt[w], 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, dv), jnp.float32),
        ],
    )

    kernel = partial(
        _fwd_kernel,
        scale=params.softmax_scale,
        softcap=params.softcap,
        bq=bq,
        bk=bk,
    )
    out_t, lse_t = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((hq, sqp, dv), q_t.dtype),
            jax.ShapeDtypeStruct((hq, sqp, 1), jnp.float32),
        ],
        interpret=params.interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * W * bq * bk * d * hq,
            bytes_accessed=(q_t.size + k_t.size + v_t.size) * q_t.dtype.itemsize,
            transcendentals=W * bq * bk * hq,
        ),
    )(work_qt, work_kt, meta, q_t, k_t, v_t)
    return out_t, lse_t[..., 0]


# ---------------------------------------------------------------------------
# backward: dq (q-major plan)
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    work_qt_ref,
    work_kt_ref,
    meta_ref,
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    dq_ref,
    dq_scr,
    *,
    scale: float,
    softcap: float,
    bq: int,
    bk: int,
):
    w = pl.program_id(1)
    is_first = meta_ref[w, IS_FIRST]
    is_last = meta_ref[w, IS_LAST]
    q_base = work_qt_ref[w] * bq
    k_base = work_kt_ref[w] * bk

    @pl.when(is_first == 1)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q = q_ref[0]
    k = k_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if softcap > 0.0:
        sc = softcap * jnp.tanh(s / softcap)
        dcap = 1.0 - (sc / softcap) ** 2
    else:
        sc = s
        dcap = None
    sm = jax.lax.cond(
        meta_ref[w, IS_FULL] == 1,
        lambda s: s,
        lambda s: jnp.where(
            _item_mask(meta_ref, w, q_base, k_base, bq, bk), s, NEG_INF
        ),
        sc,
    )

    lse = lse_ref[:, 0]  # (bq,) f32
    neg = jnp.isneginf(lse)
    lse_safe = jnp.where(neg, 0.0, lse)
    p = jnp.exp(sm - lse_safe[:, None])
    p = jnp.where(neg[:, None], 0.0, p)  # uncovered rows contribute nothing

    dp = jax.lax.dot_general(
        do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta_ref[:, :1])
    if dcap is not None:
        ds = ds * dcap
    ds = ds * scale
    dq_scr[:] += jax.lax.dot_general(
        ds.astype(q_ref.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(is_last == 1)
    def _():
        dq_ref[0] = dq_scr[:]


def _ffa_bwd_dq_pallas(
    params: FFAParams, work_qt, work_kt, meta, q_t, k_t, v_t, do_t, lse_t, delta_t
):
    bq, bk = params.block_q, params.block_k
    hq, sqp, d = q_t.shape
    _, _, dv = v_t.shape
    g = params.group
    W = params.num_work

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(hq, W),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda h, w, qt, kt, mt: (h, qt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda h, w, qt, kt, mt: (h // g, kt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, dv), lambda h, w, qt, kt, mt: (h // g, kt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, dv), lambda h, w, qt, kt, mt: (h, qt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, bq, 1), lambda h, w, qt, kt, mt: (h, qt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, bq, 1), lambda h, w, qt, kt, mt: (h, qt[w], 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda h, w, qt, kt, mt: (h, qt[w], 0),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
    )
    kernel = partial(
        _bwd_dq_kernel, scale=params.softmax_scale, softcap=params.softcap,
        bq=bq, bk=bk,
    )
    (dq_t,) = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((hq, sqp, d), jnp.float32)],
        interpret=params.interpret,
    )(work_qt, work_kt, meta, q_t, k_t, v_t, do_t,
      lse_t[..., None], delta_t[..., None])
    return dq_t


# ---------------------------------------------------------------------------
# backward: dk/dv (k-major plan)
# ---------------------------------------------------------------------------


def _bwd_dkv_kernel(
    work_qt_ref,
    work_kt_ref,
    meta_ref,
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    dk_ref,
    dv_ref,
    dk_scr,
    dv_scr,
    *,
    scale: float,
    softcap: float,
    bq: int,
    bk: int,
):
    w = pl.program_id(1)
    is_first = meta_ref[w, IS_FIRST]
    is_last = meta_ref[w, IS_LAST]
    q_base = work_qt_ref[w] * bq
    k_base = work_kt_ref[w] * bk

    @pl.when(is_first == 1)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    # s_t: (bk, bq) — k rows, q cols
    s_t = jax.lax.dot_general(
        k, q, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if softcap > 0.0:
        sc_t = softcap * jnp.tanh(s_t / softcap)
        dcap_t = 1.0 - (sc_t / softcap) ** 2
    else:
        sc_t = s_t
        dcap_t = None
    sm_t = jax.lax.cond(
        meta_ref[w, IS_FULL] == 1,
        lambda s: s,
        lambda s: jnp.where(
            _item_mask(meta_ref, w, q_base, k_base, bq, bk, transposed=True),
            s, NEG_INF,
        ),
        sc_t,
    )

    lse = lse_ref[:, 0]  # (bq,)
    neg = jnp.isneginf(lse)
    lse_safe = jnp.where(neg, 0.0, lse)
    p_t = jnp.exp(sm_t - lse_safe[None, :])
    p_t = jnp.where(neg[None, :], 0.0, p_t)

    dv_scr[:] += jax.lax.dot_general(
        p_t.astype(do.dtype), do, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp_t = jax.lax.dot_general(
        v, do, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds_t = p_t * (dp_t - delta_ref[:, 0][None, :])
    if dcap_t is not None:
        ds_t = ds_t * dcap_t
    ds_t = ds_t * scale
    dk_scr[:] += jax.lax.dot_general(
        ds_t.astype(q.dtype), q, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(is_last == 1)
    def _():
        dk_ref[0] = dk_scr[:]
        dv_ref[0] = dv_scr[:]


def _ffa_bwd_dkv_pallas(
    params: FFAParams, work_qt_t, work_kt_t, meta_t,
    q_t, k_t, v_t, do_t, lse_t, delta_t,
):
    bq, bk = params.block_q, params.block_k
    hq, sqp, d = q_t.shape
    hk, skp, dv = v_t.shape
    g = params.group
    WT = params.num_work_t

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(hq, WT),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda h, w, qt, kt, mt: (h, qt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda h, w, qt, kt, mt: (h // g, kt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, dv), lambda h, w, qt, kt, mt: (h // g, kt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, dv), lambda h, w, qt, kt, mt: (h, qt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, bq, 1), lambda h, w, qt, kt, mt: (h, qt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, bq, 1), lambda h, w, qt, kt, mt: (h, qt[w], 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda h, w, qt, kt, mt: (h, kt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, dv), lambda h, w, qt, kt, mt: (h, kt[w], 0),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, dv), jnp.float32),
        ],
    )
    kernel = partial(
        _bwd_dkv_kernel, scale=params.softmax_scale, softcap=params.softcap,
        bq=bq, bk=bk,
    )
    dk_t, dv_t = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((hq, skp, d), jnp.float32),
            jax.ShapeDtypeStruct((hq, skp, dv), jnp.float32),
        ],
        interpret=params.interpret,
    )(work_qt_t, work_kt_t, meta_t, q_t, k_t, v_t, do_t,
      lse_t[..., None], delta_t[..., None])
    return dk_t, dv_t


# ---------------------------------------------------------------------------
# public entry (custom VJP)
# ---------------------------------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(9,))
def _ffa_core(
    q_t, k_t, v_t, work_qt, work_kt, meta, work_qt_t, work_kt_t, meta_t,
    params: FFAParams,
):
    return _ffa_fwd_pallas(params, work_qt, work_kt, meta, q_t, k_t, v_t)


def _ffa_core_fwd(
    q_t, k_t, v_t, work_qt, work_kt, meta, work_qt_t, work_kt_t, meta_t,
    params: FFAParams,
):
    out_t, lse_t = _ffa_fwd_pallas(params, work_qt, work_kt, meta, q_t, k_t, v_t)
    res = (q_t, k_t, v_t, out_t, lse_t, work_qt, work_kt, meta,
           work_qt_t, work_kt_t, meta_t)
    return (out_t, lse_t), res


def _ffa_core_bwd(params: FFAParams, res, cts):
    # lse is an auxiliary output: its cotangent is ignored (the CP runtime
    # differentiates the lse-merge manually, matching the reference).
    do_t, _ = cts
    (q_t, k_t, v_t, out_t, lse_t, work_qt, work_kt, meta,
     work_qt_t, work_kt_t, meta_t) = res
    delta_t = jnp.sum(
        do_t.astype(jnp.float32) * out_t.astype(jnp.float32), axis=-1
    )  # (hq, sqp)
    dq_t = _ffa_bwd_dq_pallas(
        params, work_qt, work_kt, meta, q_t, k_t, v_t, do_t, lse_t, delta_t
    )
    dk_t, dv_t = _ffa_bwd_dkv_pallas(
        params, work_qt_t, work_kt_t, meta_t,
        q_t, k_t, v_t, do_t, lse_t, delta_t,
    )
    g = params.group
    if g > 1:
        hq, skp, d = dk_t.shape
        dk_t = dk_t.reshape(hq // g, g, skp, d).sum(axis=1)
        dv_t = dv_t.reshape(hq // g, g, skp, dv_t.shape[-1]).sum(axis=1)
    return (
        dq_t.astype(q_t.dtype),
        dk_t.astype(k_t.dtype),
        dv_t.astype(v_t.dtype),
        None, None, None, None, None, None,
    )


_ffa_core.defvjp(_ffa_core_fwd, _ffa_core_bwd)


def _should_interpret() -> bool:
    return (
        env_general.is_interpret_mode_enable()
        or jax.default_backend() == "cpu"
    )


def ffa_attn_with_plan(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    arrays: tuple[jax.Array, ...],
    params: FFAParams,
) -> tuple[jax.Array, jax.Array]:
    """FFA over an explicit plan — the CP-runtime entry point.

    Args:
        q/k/v: ``[sq,hq,d] / [sk,hk,d] / [sk,hk,dv]``, seq-major.
        arrays: the 6 plan arrays (:func:`plan_arrays`), possibly traced
            (per-rank metadata under shard_map), padded to params.num_work /
            params.num_work_t.
        params: static dims + scalars; sq/sk must fit the tile counts.

    Returns:
        (out ``[sq,hq,dv]``, lse ``[sq,hq]`` fp32).
    """
    sq, hq, d = q.shape
    sk, hk, dv = v.shape
    sqp = params.num_q_tiles * params.block_q
    skp = params.num_k_tiles * params.block_k
    q_t = jnp.pad(q, ((0, sqp - sq), (0, 0), (0, 0))).transpose(1, 0, 2)
    k_t = jnp.pad(k, ((0, skp - sk), (0, 0), (0, 0))).transpose(1, 0, 2)
    v_t = jnp.pad(v, ((0, skp - sk), (0, 0), (0, 0))).transpose(1, 0, 2)
    out_t, lse_t = _ffa_core(q_t, k_t, v_t, *arrays, params)
    return out_t.transpose(1, 0, 2)[:sq], lse_t.T[:sq]


def default_blocks(sq: int, sk: int, block_q=None, block_k=None) -> tuple[int, int]:
    bq = block_q or env_kernel.ffa_block_q()
    bk = block_k or env_kernel.ffa_block_k()
    return min(bq, _round_up(sq, 16)), min(bk, _round_up(sk, 128))


def ffa_attn(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_ranges,
    k_ranges,
    attn_type_map=None,
    softmax_scale: float | None = None,
    softcap: float = 0.0,
    block_q: int | None = None,
    block_k: int | None = None,
    d_lo=None,
    d_hi=None,
) -> tuple[jax.Array, jax.Array]:
    """Pallas FFA over slice metadata. Same contract as sdpa_attn.

    Slices may be given as mask types (``attn_type_map``) or directly as
    diagonal bands (``d_lo``/``d_hi``). The metadata must be *concrete*
    (host) values — it parameterizes the kernel grid. Inside jit-traced code,
    close over it (the runtime manager caches traced plans per mask,
    mirroring the reference's runtime LRU), or use :func:`ffa_attn_with_plan`.
    """
    try:
        qr = np.asarray(q_ranges, dtype=np.int32)
        kr = np.asarray(k_ranges, dtype=np.int32)
        if d_lo is None or d_hi is None:
            tm = (
                np.zeros(len(qr), dtype=np.int32)
                if attn_type_map is None
                else np.asarray(attn_type_map, dtype=np.int32)
            )
            d_lo, d_hi = types_to_bands(qr, kr, tm)
        else:
            d_lo = np.asarray(d_lo, dtype=np.int32)
            d_hi = np.asarray(d_hi, dtype=np.int32)
    except Exception as e:  # pragma: no cover
        raise ValueError(
            "ffa_attn requires concrete (host) slice metadata; inside jit, "
            "close over the metadata or use ffa_attn_with_plan"
        ) from e

    sq, hq, d = q.shape
    sk, hk, dv = v.shape
    if softmax_scale is None:
        softmax_scale = float(d) ** -0.5
    bq, bk = default_blocks(sq, sk, block_q, block_k)

    plan = get_ffa_plan(qr, kr, d_lo, d_hi, sq, sk, bq, bk)
    params = FFAParams(
        num_work=plan.num_work,
        num_work_t=plan.num_work_t,
        num_q_tiles=plan.num_q_tiles,
        num_k_tiles=plan.num_k_tiles,
        block_q=bq,
        block_k=bk,
        softmax_scale=float(softmax_scale),
        softcap=float(softcap),
        group=hq // hk,
        interpret=_should_interpret(),
    )
    return ffa_attn_with_plan(q, k, v, plan_arrays(plan), params)
