"""Flex-flash-attention Pallas TPU kernels (fwd + bwd).

TPU-native counterpart of the reference FFA CUDA kernel
(magi_attention/csrc/flexible_flash_attention/ — fwd/bwd mainloops, tile
schedulers, mask.h). Design differences, deliberate and TPU-first:

- The device-side persistent tile scheduler is replaced by a host-side plan
  (:mod:`ffa_plan`) + ``PrefetchScalarGridSpec``: the grid is exactly the list
  of non-empty (q_tile, k_tile, slice) work items, so fully-masked tiles cost
  nothing and no dynamic control flow reaches the MXU. Plan *contents* may be
  traced arrays (per-CP-rank metadata under shard_map); only the work counts
  and tile geometry are static.
- The atomic-reduce epilogues (epilogue_fwd.hpp / epilogue_bwd.hpp) are
  replaced by run-ordering: all work items of one output tile are consecutive
  grid steps accumulating into VMEM scratch; the tile is written once at the
  end of its run. dq uses the q-major plan, dk/dv the k-major plan — no
  atomics exist on TPU and none are needed.
- Slices are diagonal bands (d_lo <= j - i <= d_hi): the mask is two compares.
- Online-softmax merge math matches functional/utils.py (lse in natural log,
  -inf on fully-masked rows).

Mosaic-compatibility notes (mirrors the bundled TPU kernels
jax/experimental/pallas/ops/tpu/{flash_attention,splash_attention}):

- No ``-inf`` arithmetic inside kernels: masking uses a large finite
  ``MASK_VALUE`` (splash's DEFAULT_MASK_VALUE); fully-masked rows are detected
  by threshold at finalize and converted to (out=0, lse=-inf) on the host.
- No ``lax.cond`` over tiles: the full-tile fast path ORs the band mask with a
  scalar ``is_full`` flag (splash's ``should_not_mask`` idiom).
- lse is emitted broadcast across ``NUM_LANES`` (out block ``(bq, 128)``,
  like splash's logsumexp) and sliced on the host; the backward kernels read
  lse/delta from a lanes-major layout ``(hq, sublanes, sqp)`` with q in the
  lane dimension (splash's backward logsumexp layout).
- m/l scratch are ``(bq, NUM_LANES)`` fp32; softmax rescale uses
  ``jnp.tile`` over 128-lane groups (both bundled kernels' idiom) which
  requires ``block_k % 128 == 0`` — guaranteed by :func:`default_blocks`.

max_logits: the fwd kernel additionally emits the per-(head, q-tile) running
max of the (scaled, softcapped) logits — the TPU equivalent of the CUDA
softmax max tracking (ref csrc/flexible_flash_attention/softmax.h, surfaced
via common/forward_meta.py:21) — reduced to per-head [hq] on the host.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..env import general as env_general
from ..env import kernel as env_kernel
from ..resilience.inject import maybe_inject
from ..utils.mem_budget import VMEM_ALLOWED_BYTES, ffa_kernel_residency
from .ffa_plan import (  # noqa: F401
    EK0,
    EK1,
    EQ0,
    EQ1,
    IS_FULL,
    DHI,
    DLO,
    IS_FIRST,
    IS_LAST,
    KE,
    KS,
    QE,
    QS,
    QVF,
    QVL,
    FFAPlan,
    get_ffa_plan,
)
from .mask_utils import types_to_bands

NEG_INF = float("-inf")


def _registry_mod():
    """Lazy handle on the backend registry (kernels/registry.py) — every
    kernel-choice read in this file flows through it, not raw env flags."""
    from . import registry as _registry

    return _registry
NUM_LANES = 128
NUM_SUBLANES = 8
# jax < 0.5 exposes the TPU compiler params as TPUCompilerParams; newer
# versions renamed it. Resolve once so the kernel layer imports (and the
# CPU/interpret parity suite runs) on either.
_CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)
# exp2-domain softmax (softcap-free path): folding log2(e) into the q
# pre-scale turns every exp(x) into a bare exp2, deleting the per-element
# multiply Mosaic otherwise emits inside exp (flash_attention's idiom)
LOG2E = float(np.log2(np.e))
LN2 = float(np.log(2.0))
# splash's DEFAULT_MASK_VALUE: large but finite so no inf arithmetic reaches
# Mosaic; exp(MASK_VALUE - anything_sane) underflows to exactly 0.
MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)
# anything at or below this is "never attended" (real logits are O(1e2))
EMPTY_THRESH = 0.5 * MASK_VALUE


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


@dataclass(frozen=True, eq=False)
class FFAParams:
    """Static kernel parameters (hashable by identity for custom_vjp)."""

    num_work: int
    num_work_t: int
    num_q_tiles: int
    num_k_tiles: int
    block_q: int
    block_k: int
    softmax_scale: float
    softcap: float
    group: int  # hq // hk
    interpret: bool
    # emit the per-head max-logits output (ref forward_meta.py:21). Costs an
    # extra (hq, sqp, 128) fp32 HBM write, so it is opt-in; when off, the
    # returned max_logits is a constant -inf placeholder.
    emit_max_logits: bool = False
    # Backward-specific tile overrides (TPU analogue of the reference's FFA
    # BWD tuning flags, docs/source/user_guide/env_variables.md:111): the dq
    # and dkv kernels have different VMEM/compute profiles than fwd (dkv
    # holds (bk, d)+(bk, dv) fp32 scratch and loops the GQA group innermost),
    # so they may want their own block sizes. None = inherit fwd blocks.
    # When set, the plan tuple carries 12 arrays (fwd6 + dq3 + dkv3) and
    # num_work_dq / num_work_dkv are the respective work counts.
    block_q_dq: int | None = None
    block_k_dq: int | None = None
    block_q_dkv: int | None = None
    block_k_dkv: int | None = None
    num_work_dq: int | None = None
    num_work_dkv: int | None = None

    def dq_blocks(self) -> tuple[int, int]:
        return (self.block_q_dq or self.block_q,
                self.block_k_dq or self.block_k)

    def dkv_blocks(self) -> tuple[int, int]:
        return (self.block_q_dkv or self.block_q,
                self.block_k_dkv or self.block_k)


def plan_arrays(plan: FFAPlan) -> tuple[jax.Array, ...]:
    """The 6 device arrays of a plan (q-major triple + k-major triple)."""
    return (
        jnp.asarray(plan.work_qt),
        jnp.asarray(plan.work_kt),
        jnp.asarray(plan.meta),
        jnp.asarray(plan.work_qt_t),
        jnp.asarray(plan.work_kt_t),
        jnp.asarray(plan.meta_t),
    )


def _item_mask(
    meta_ref, w, q_base, k_base, bq: int, bk: int, transposed: bool = False,
    repeat: int = 1,
):
    """Boolean mask of work item w on the tile at (q_base, k_base).

    Shape (bq, bk) with q rows, or (bk, bq) when ``transposed`` (k rows) —
    built directly with swapped iota since Mosaic cannot transpose i1 vectors.
    The scalar is_full flag is OR-ed in (splash's should_not_mask idiom), so
    interior tiles need no separate code path.

    ``repeat`` > 1 emits the same q tile stacked for ``repeat`` packed
    heads — ``(repeat*bq, bk)`` (q rows) or ``(bk, repeat*bq)``
    (transposed; packed heads along lanes) — via iota-mod rather than an
    i1 tile (which Mosaic cannot relayout).
    """
    qs, qe = meta_ref[w, QS], meta_ref[w, QE]
    ks, ke = meta_ref[w, KS], meta_ref[w, KE]
    lo, hi = meta_ref[w, DLO], meta_ref[w, DHI]
    full = meta_ref[w, IS_FULL] == 1
    if transposed:
        shape = (bk, repeat * bq)
        rows = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
        if repeat > 1:
            rows = jax.lax.rem(rows, jnp.int32(bq))
        rows = q_base + rows
        cols = k_base + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    else:
        shape = (repeat * bq, bk)
        rows = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
        if repeat > 1:
            rows = jax.lax.rem(rows, jnp.int32(bq))
        rows = q_base + rows
        cols = k_base + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    in_rect = (rows >= qs) & (rows < qe) & (cols >= ks) & (cols < ke)
    d = cols - rows
    band = in_rect & (d >= lo) & (d <= hi)
    return band | jnp.broadcast_to(full, band.shape)


def _lane_tile(col, width: int):
    """(r, NUM_LANES) fp32 -> (r, width) by lane-group tiling (flash_attention
    idiom; width % NUM_LANES == 0) or slicing (width < NUM_LANES)."""
    if width <= NUM_LANES:
        return col[:, :width]
    assert width % NUM_LANES == 0, f"{width=} not a multiple of {NUM_LANES}"
    return jnp.tile(col, (1, width // NUM_LANES))


# extent-clamp chunking: at most this many lane-dim chunks per tile — more
# chunks skip finer-grained dead work but each live chunk re-pays the MXU
# ramp and mask arithmetic, and past ~8 the chunk dots drop under the MXU's
# efficient minimum anyway
_MAX_CLAMP_CHUNKS = 8


def _clamp_chunks(width: int) -> int:
    """Number of lane-dimension chunks the extent-clamped kernel bodies
    split a ``width``-wide tile into; 0 = clamping off (the legacy
    single-dot bodies lower unchanged). Chunk width must stay a lane-quantum
    multiple (``_lane_tile``/Mosaic layout rule), so the count is the
    largest divisor of ``width // NUM_LANES`` within the chunk cap."""
    from . import registry as _registry

    if not _registry.extent_clamp_enabled() or width % NUM_LANES:
        return 0
    m = width // NUM_LANES
    return max(c for c in range(1, min(_MAX_CLAMP_CHUNKS, m) + 1) if m % c == 0)


def _item_extents(meta_ref, w):
    """(eq0, eq1, ek0, ek1, live) scalars of work item w: the tile-local
    live sub-rectangle the plan builder derived from the band geometry
    (ffa_plan._extend_meta_extents). ``live`` is False exactly for dummy /
    pad_plan filler items (all-zero extent)."""
    eq0, eq1 = meta_ref[w, EQ0], meta_ref[w, EQ1]
    ek0, ek1 = meta_ref[w, EK0], meta_ref[w, EK1]
    return eq0, eq1, ek0, ek1, (eq1 > eq0) & (ek1 > ek0)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    work_qt_ref,
    work_kt_ref,
    meta_ref,
    q_ref,
    k_ref,
    v_ref,
    *rest,
    softcap: float,
    bq: int,
    bk: int,
    emit_ml: bool,
    nc: int,
):
    if emit_ml:
        out_ref, lse_ref, ml_ref, m_scr, l_scr, acc_scr = rest
    else:
        out_ref, lse_ref, m_scr, l_scr, acc_scr = rest
        ml_ref = None
    w = pl.program_id(1)
    is_first = meta_ref[w, IS_FIRST]
    is_last = meta_ref[w, IS_LAST]
    is_full = meta_ref[w, IS_FULL]
    # softcap-free path runs the online softmax in the log2 domain (q was
    # pre-scaled by softmax_scale * log2(e) on the host)
    use_exp2 = softcap == 0.0
    exp_fn = jnp.exp2 if use_exp2 else jnp.exp

    @pl.when(is_first == 1)
    def _():
        m_scr[:] = jnp.full_like(m_scr, MASK_VALUE)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0]  # pre-scaled by softmax_scale (* log2e when softcap-free)
    k = k_ref[0]

    def update(s, v_blk, width: int):
        m_prev = m_scr[...]  # (bq, NUM_LANES)
        m_blk = jnp.max(s, axis=1)[:, None]  # (bq, 1)
        m_new = jnp.maximum(m_prev, m_blk)  # (bq, NUM_LANES)
        p = exp_fn(s - _lane_tile(m_new, width))
        alpha = exp_fn(m_prev - m_new)  # (bq, NUM_LANES); ==1 while empty

        l_new = l_scr[...] * alpha + jnp.sum(p, axis=1)[:, None]
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype),
            v_blk,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[:] = acc_scr[:] * _lane_tile(alpha, acc_scr.shape[-1]) + pv
        m_scr[:] = m_new
        l_scr[:] = l_new

    def score(k_blk):
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if softcap > 0.0:
            s = softcap * jnp.tanh(s / softcap)
        return s

    if nc == 0:
        s_raw = score(k)

        # interior tiles skip the band-mask arithmetic entirely (VPU is the
        # bottleneck with bf16 MXUs; splash's should-not-mask split)
        @pl.when(is_full == 1)
        def _():
            update(s_raw, v_ref[0], bk)

        @pl.when(is_full == 0)
        def _():
            q_base = work_qt_ref[w] * bq
            k_base = work_kt_ref[w] * bk
            update(
                jnp.where(
                    _item_mask(meta_ref, w, q_base, k_base, bq, bk),
                    s_raw,
                    MASK_VALUE,
                ),
                v_ref[0],
                bk,
            )
    else:
        # extent-clamped body: partial tiles run only the k chunks the live
        # extent touches — skipped chunks lie fully outside the band, so
        # their legacy contribution was exactly 0 (masked p underflows to
        # 0.0; never-live rows are discarded by finalize's empty threshold)
        ck = bk // nc
        _, _, ek0, ek1, live = _item_extents(meta_ref, w)

        @pl.when(is_full == 1)
        def _():
            update(score(k), v_ref[0], bk)

        for c in range(nc):
            c0 = c * ck

            @pl.when((is_full == 0) & live & (ek0 < c0 + ck) & (ek1 > c0))
            def _(c0=c0):
                q_base = work_qt_ref[w] * bq
                k_base = work_kt_ref[w] * bk
                update(
                    jnp.where(
                        _item_mask(
                            meta_ref, w, q_base, k_base + c0, bq, ck
                        ),
                        score(k[c0 : c0 + ck]),
                        MASK_VALUE,
                    ),
                    v_ref[0][c0 : c0 + ck],
                    ck,
                )

    @pl.when(is_last == 1)
    def _():
        m = m_scr[...]
        l = l_scr[...]
        # rows never covered by any slice: m stayed at MASK_VALUE (l holds
        # exp(0)-garbage from masked-only tiles) -> out 0, lse MASK-flagged
        # (converted to -inf on the host)
        empty = m <= EMPTY_THRESH
        l_safe = jnp.where(empty | (l == 0.0), 1.0, l)
        o = acc_scr[:] / _lane_tile(l_safe, acc_scr.shape[-1])
        o = jnp.where(_lane_tile(empty, o.shape[-1]), 0.0, o)
        out_ref[0] = o.astype(out_ref.dtype)
        if use_exp2:
            # convert back to the natural-log contract
            lse_nat = (m + jnp.log2(l_safe)) * LN2
            m_nat = m * LN2
        else:
            lse_nat = m + jnp.log(l_safe)
            m_nat = m
        lse_ref[...] = jnp.where(empty, MASK_VALUE, lse_nat).astype(
            jnp.float32
        )
        if ml_ref is not None:
            # per-row running max of scaled/softcapped logits (lanes equal);
            # host reduces rows -> per-head. Empty rows forced to MASK_VALUE
            # (m * ln2 would otherwise shift the sentinel).
            ml_ref[...] = jnp.where(empty, MASK_VALUE, m_nat).astype(
                jnp.float32
            )


def _ffa_fwd_pallas(params: FFAParams, work_qt, work_kt, meta, q_t, k_t, v_t):
    """q_t/k_t/v_t are head-major padded: [hq,sqp,d], [hk,skp,d], [hk,skp,dv].

    Returns (out_t [hq,sqp,dv], lse_t [hq,sqp] fp32 with -inf on uncovered
    rows, ml [hq] fp32 per-head max logit with -inf for never-covered heads).
    """
    bq, bk = params.block_q, params.block_k
    hq, sqp, d = q_t.shape
    hk, skp, dv = v_t.shape
    g = params.group
    W = params.num_work
    emit_ml = params.emit_max_logits

    # fold softmax_scale into q (saves a (bq,bk) VPU multiply per grid
    # step); the softcap-free path also folds log2(e) to run the softmax in
    # the exp2 domain
    q_scale = params.softmax_scale * (LOG2E if params.softcap == 0.0 else 1.0)
    q_t = (q_t.astype(jnp.float32) * q_scale).astype(q_t.dtype)

    lse_spec = pl.BlockSpec(
        (None, bq, NUM_LANES), lambda h, w, qt, kt, mt: (h, qt[w], 0),
        memory_space=pltpu.VMEM,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(hq, W),
        in_specs=[
            pl.BlockSpec(
                (1, bq, d), lambda h, w, qt, kt, mt: (h, qt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, bk, d), lambda h, w, qt, kt, mt: (h // g, kt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, bk, dv), lambda h, w, qt, kt, mt: (h // g, kt[w], 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, bq, dv), lambda h, w, qt, kt, mt: (h, qt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            lse_spec,
        ] + ([lse_spec] if emit_ml else []),
        scratch_shapes=[
            pltpu.VMEM((bq, NUM_LANES), jnp.float32),
            pltpu.VMEM((bq, NUM_LANES), jnp.float32),
            pltpu.VMEM((bq, dv), jnp.float32),
        ],
    )

    kernel = partial(
        _fwd_kernel,
        softcap=params.softcap,
        bq=bq,
        bk=bk,
        emit_ml=emit_ml,
        nc=_clamp_chunks(bk),
    )
    lse_shape = jax.ShapeDtypeStruct((hq, sqp, NUM_LANES), jnp.float32)
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((hq, sqp, dv), q_t.dtype),
            lse_shape,
        ] + ([lse_shape] if emit_ml else []),
        interpret=params.interpret,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * W * bq * bk * d * hq,
            bytes_accessed=(q_t.size + k_t.size + v_t.size) * q_t.dtype.itemsize,
            transcendentals=W * bq * bk * hq,
        ),
    )(work_qt, work_kt, meta, q_t, k_t, v_t)
    out_t, lse_b = outs[0], outs[1]
    lse_raw = lse_b[..., 0]  # (hq, sqp)
    lse_t = jnp.where(lse_raw <= EMPTY_THRESH, NEG_INF, lse_raw)
    if emit_ml:
        ml_raw = jnp.max(outs[2], axis=(1, 2))  # (hq,)
        ml = jnp.where(ml_raw <= EMPTY_THRESH, NEG_INF, ml_raw)
    else:
        ml = jnp.full((hq,), NEG_INF, dtype=jnp.float32)
    return out_t, lse_t, ml


def _fwd_kernel_gqa(
    work_qt_ref,
    work_kt_ref,
    meta_ref,
    q_ref,
    k_ref,
    v_ref,
    out_ref,
    lse_ref,
    m_scr,
    l_scr,
    acc_scr,
    *,
    softcap: float,
    bq: int,
    bk: int,
    g: int,
    nc: int,
):
    """GQA-packed forward: the whole query group of one kv head per grid
    step. vs :func:`_fwd_kernel`: grid (hk, W) instead of (hq, W), so each
    k/v tile is fetched ONCE per work item instead of ``g`` times (k/v HBM
    traffic /g) and per-step bookkeeping amortizes over a g x taller MXU
    op. Same online-softmax math on ``g*bq`` packed rows; rows of different
    heads never interact (the mask repeats per head; softmax is row-wise).
    """
    w = pl.program_id(1)
    is_first = meta_ref[w, IS_FIRST]
    is_last = meta_ref[w, IS_LAST]
    is_full = meta_ref[w, IS_FULL]
    use_exp2 = softcap == 0.0
    exp_fn = jnp.exp2 if use_exp2 else jnp.exp

    @pl.when(is_first == 1)
    def _():
        m_scr[:] = jnp.full_like(m_scr, MASK_VALUE)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    d = q_ref.shape[-1]
    dv = v_ref.shape[-1]
    # (g, bq, d) block -> (g*bq, d) packed rows: contiguous sublane merge
    q = q_ref[0].reshape(g * bq, d)
    k = k_ref[0]

    def update(s, v_blk, width: int):
        m_prev = m_scr[...]  # (g*bq, NUM_LANES)
        m_blk = jnp.max(s, axis=1)[:, None]
        m_new = jnp.maximum(m_prev, m_blk)
        p = exp_fn(s - _lane_tile(m_new, width))
        alpha = exp_fn(m_prev - m_new)
        l_new = l_scr[...] * alpha + jnp.sum(p, axis=1)[:, None]
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype),
            v_blk,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[:] = acc_scr[:] * _lane_tile(alpha, dv) + pv
        m_scr[:] = m_new
        l_scr[:] = l_new

    def score(k_blk):
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if softcap > 0.0:
            s = softcap * jnp.tanh(s / softcap)
        return s

    if nc == 0:
        s_raw = score(k)

        @pl.when(is_full == 1)
        def _():
            update(s_raw, v_ref[0], bk)

        @pl.when(is_full == 0)
        def _():
            q_base = work_qt_ref[w] * bq
            k_base = work_kt_ref[w] * bk
            update(
                jnp.where(
                    _item_mask(
                        meta_ref, w, q_base, k_base, bq, bk, repeat=g
                    ),
                    s_raw,
                    MASK_VALUE,
                ),
                v_ref[0],
                bk,
            )
    else:
        # extent-clamped body (see _fwd_kernel): the live k extent is
        # head-independent — the packed heads share the work item's band —
        # so chunk skipping is uniform across the packed rows
        ck = bk // nc
        _, _, ek0, ek1, live = _item_extents(meta_ref, w)

        @pl.when(is_full == 1)
        def _():
            update(score(k), v_ref[0], bk)

        for c in range(nc):
            c0 = c * ck

            @pl.when((is_full == 0) & live & (ek0 < c0 + ck) & (ek1 > c0))
            def _(c0=c0):
                q_base = work_qt_ref[w] * bq
                k_base = work_kt_ref[w] * bk
                update(
                    jnp.where(
                        _item_mask(
                            meta_ref, w, q_base, k_base + c0, bq, ck,
                            repeat=g,
                        ),
                        score(k[c0 : c0 + ck]),
                        MASK_VALUE,
                    ),
                    v_ref[0][c0 : c0 + ck],
                    ck,
                )

    @pl.when(is_last == 1)
    def _():
        m = m_scr[...]
        l = l_scr[...]
        empty = m <= EMPTY_THRESH
        l_safe = jnp.where(empty | (l == 0.0), 1.0, l)
        o = acc_scr[:] / _lane_tile(l_safe, dv)
        o = jnp.where(_lane_tile(empty, dv), 0.0, o)
        out_ref[0] = o.reshape(g, bq, dv).astype(out_ref.dtype)
        if use_exp2:
            lse_nat = (m + jnp.log2(l_safe)) * LN2
        else:
            lse_nat = m + jnp.log(l_safe)
        lse_ref[0] = (
            jnp.where(empty, MASK_VALUE, lse_nat)
            .reshape(g, bq, NUM_LANES)
            .astype(jnp.float32)
        )


def _ffa_fwd_pallas_gqa(
    params: FFAParams, work_qt, work_kt, meta, q_t, k_t, v_t
):
    """GQA-packed forward pallas call (see :func:`_fwd_kernel_gqa`).

    Preconditions (enforced by the caller's dispatch): group > 1,
    max_logits not requested. Heads of one group are adjacent in q_t
    (head h uses kv head h // g), so the (hq, sqp, d) -> (hk, g, sqp, d)
    reshape is free.
    """
    bq, bk = params.block_q, params.block_k
    hq, sqp, d = q_t.shape
    hk, skp, dv = v_t.shape
    g = params.group
    W = params.num_work

    q_scale = params.softmax_scale * (LOG2E if params.softcap == 0.0 else 1.0)
    q_t = (q_t.astype(jnp.float32) * q_scale).astype(q_t.dtype)
    q_g = q_t.reshape(hk, g, sqp, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(hk, W),
        in_specs=[
            pl.BlockSpec(
                (1, g, bq, d), lambda h, w, qt, kt, mt: (h, 0, qt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, bk, d), lambda h, w, qt, kt, mt: (h, kt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, bk, dv), lambda h, w, qt, kt, mt: (h, kt[w], 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, g, bq, dv), lambda h, w, qt, kt, mt: (h, 0, qt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, g, bq, NUM_LANES),
                lambda h, w, qt, kt, mt: (h, 0, qt[w], 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        scratch_shapes=[
            pltpu.VMEM((g * bq, NUM_LANES), jnp.float32),
            pltpu.VMEM((g * bq, NUM_LANES), jnp.float32),
            pltpu.VMEM((g * bq, dv), jnp.float32),
        ],
    )
    kernel = partial(
        _fwd_kernel_gqa, softcap=params.softcap, bq=bq, bk=bk, g=g,
        nc=_clamp_chunks(bk),
    )
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((hk, g, sqp, dv), q_t.dtype),
            jax.ShapeDtypeStruct((hk, g, sqp, NUM_LANES), jnp.float32),
        ],
        interpret=params.interpret,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * W * bq * bk * d * hq,
            bytes_accessed=(q_t.size + k_t.size + v_t.size)
            * q_t.dtype.itemsize,
            transcendentals=W * bq * bk * hq,
        ),
    )(work_qt, work_kt, meta, q_g, k_t, v_t)
    out_t = outs[0].reshape(hq, sqp, dv)
    lse_raw = outs[1].reshape(hq, sqp, NUM_LANES)[..., 0]
    lse_t = jnp.where(lse_raw <= EMPTY_THRESH, NEG_INF, lse_raw)
    ml = jnp.full((hq,), NEG_INF, dtype=jnp.float32)
    return out_t, lse_t, ml


def _use_gqa_pack(
    params: FFAParams, d: int, dv: int, itemsize: int = 2
) -> bool:
    """Trace-time dispatch to the packed fwd kernel: opt-in flag, real
    grouping, no max-logits (the packed kernel doesn't emit them), and a
    VMEM guard — the EXACT packed-step residency (blocks + scratch +
    score-tile intermediates, utils/mem_budget.ffa_kernel_residency — the
    same model the static kernel checker proves K1 with) must fit the
    per-core budget with headroom."""
    from . import registry as _registry

    return (
        _registry.gqa_pack_variant("fwd") == "gqa_packed"
        and params.group > 1
        and not params.emit_max_logits
        and ffa_kernel_residency(
            "fwd", params.block_q, params.block_k, d, head_dim_v=dv,
            dtype_bytes=itemsize, group=params.group, packed=True,
        )
        <= VMEM_ALLOWED_BYTES
    )


# ---------------------------------------------------------------------------
# backward: dq (q-major plan)
# ---------------------------------------------------------------------------


def _lanes_layout(x: jax.Array, sublanes: int) -> jax.Array:
    """(hq, sqp) fp32 -> (hq, sublanes, sqp): q in the lane dim, broadcast
    over sublanes (splash's backward logsumexp/di layout)."""
    return jnp.broadcast_to(x[:, None, :], (x.shape[0], sublanes, x.shape[1]))


def _bwd_dq_kernel(
    work_qt_ref,
    work_kt_ref,
    meta_ref,
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    dq_ref,
    dq_scr,
    *,
    softcap: float,
    scale: float,
    bq: int,
    bk: int,
    nc: int,
):
    w = pl.program_id(1)
    is_first = meta_ref[w, IS_FIRST]
    is_last = meta_ref[w, IS_LAST]
    is_full = meta_ref[w, IS_FULL]
    use_exp2 = softcap == 0.0
    exp_fn = jnp.exp2 if use_exp2 else jnp.exp

    @pl.when(is_first == 1)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q = q_ref[0]  # pre-scaled by softmax_scale (* log2e when softcap-free)
    k = k_ref[0]

    # lse/delta live q-in-lanes: ref block (1, bq); column views via
    # expand_dims (splash dq idiom). lse arrives in natural log; the exp2
    # path converts the (bq,1) column, never the (bq,bk) tile.
    lse = jnp.expand_dims(lse_ref[0], -1)  # (bq, 1)
    delta = jnp.expand_dims(delta_ref[0], -1)  # (bq, 1)

    def score(k_blk):
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if softcap > 0.0:
            sc = softcap * jnp.tanh(s / softcap)
            return sc, 1.0 - (sc / softcap) ** 2
        return s, None

    def accum(sm, dcap, dp, k_blk, masked: bool):
        if masked:
            neg = lse <= EMPTY_THRESH  # uncovered rows (host clamps -inf)
            lse_safe = jnp.where(neg, 0.0, lse)
            if use_exp2:
                lse_safe = lse_safe * LOG2E
            p = exp_fn(sm - lse_safe)  # exp(MASK_VALUE - O(1)) == 0
            p = jnp.where(neg, 0.0, p)
        else:
            # a full tile's rows are covered by definition -> lse finite
            p = exp_fn(sm - (lse * LOG2E if use_exp2 else lse))
        ds = p * (dp - delta)
        if dcap is not None:
            ds = ds * dcap
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    def dp_of(v_blk):
        return jax.lax.dot_general(
            do_ref[0], v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if nc == 0:
        sc, dcap = score(k)
        dp = dp_of(v_ref[0])

        @pl.when(is_full == 1)
        def _():
            accum(sc, dcap, dp, k, masked=False)

        @pl.when(is_full == 0)
        def _():
            q_base = work_qt_ref[w] * bq
            k_base = work_kt_ref[w] * bk
            accum(
                jnp.where(
                    _item_mask(meta_ref, w, q_base, k_base, bq, bk),
                    sc, MASK_VALUE,
                ),
                dcap, dp, k,
                masked=True,
            )
    else:
        # extent-clamped body: skipped k chunks are fully masked, and the
        # masked path's p is exactly 0 there (exp underflow / neg-row
        # forcing), so dropping them does not change dq
        ck = bk // nc
        _, _, ek0, ek1, live = _item_extents(meta_ref, w)

        @pl.when(is_full == 1)
        def _():
            sc, dcap = score(k)
            accum(sc, dcap, dp_of(v_ref[0]), k, masked=False)

        for c in range(nc):
            c0 = c * ck

            @pl.when((is_full == 0) & live & (ek0 < c0 + ck) & (ek1 > c0))
            def _(c0=c0):
                q_base = work_qt_ref[w] * bq
                k_base = work_kt_ref[w] * bk
                k_c = k[c0 : c0 + ck]
                sc, dcap = score(k_c)
                accum(
                    jnp.where(
                        _item_mask(
                            meta_ref, w, q_base, k_base + c0, bq, ck
                        ),
                        sc, MASK_VALUE,
                    ),
                    dcap, dp_of(v_ref[0][c0 : c0 + ck]), k_c,
                    masked=True,
                )

    @pl.when(is_last == 1)
    def _():
        # softmax_scale folds into the flush (ds carries no scale): one VPU
        # multiply on the resident tile instead of an XLA full-array pass
        dq_ref[0] = dq_scr[:] * scale


def _clamp_lse(lse_t: jax.Array) -> jax.Array:
    """Replace -inf (uncovered-row lse) with MASK_VALUE so no inf enters the
    kernels; threshold compares recover the flag."""
    return jnp.maximum(lse_t, MASK_VALUE)


def _ffa_bwd_dq_pallas(
    params: FFAParams, work_qt, work_kt, meta, q_t, k_t, v_t, do_t, lse_t, delta_t
):
    bq, bk = params.dq_blocks()
    hq, sqp, d = q_t.shape
    _, _, dv = v_t.shape
    g = params.group
    W = params.num_work_dq if params.num_work_dq is not None else params.num_work

    # pre-scale q (exp2 domain when softcap-free); the missing scale factor
    # on ds is applied to dq on return
    q_scale = params.softmax_scale * (LOG2E if params.softcap == 0.0 else 1.0)
    q_t = (q_t.astype(jnp.float32) * q_scale).astype(q_t.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(hq, W),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda h, w, qt, kt, mt: (h, qt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda h, w, qt, kt, mt: (h // g, kt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, dv), lambda h, w, qt, kt, mt: (h // g, kt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, dv), lambda h, w, qt, kt, mt: (h, qt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, 1, bq), lambda h, w, qt, kt, mt: (h, 0, qt[w]),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, 1, bq), lambda h, w, qt, kt, mt: (h, 0, qt[w]),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda h, w, qt, kt, mt: (h, qt[w], 0),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
    )
    kernel = partial(
        _bwd_dq_kernel, softcap=params.softcap,
        scale=params.softmax_scale, bq=bq, bk=bk, nc=_clamp_chunks(bk),
    )
    (dq_t,) = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((hq, sqp, d), jnp.float32)],
        interpret=params.interpret,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
    )(work_qt, work_kt, meta, q_t, k_t, v_t, do_t,
      _lanes_layout(_clamp_lse(lse_t), 1), _lanes_layout(delta_t, 1))
    return dq_t  # softmax_scale already folded into the kernel flush


def _bwd_dq_kernel_gqa(
    work_qt_ref,
    work_kt_ref,
    meta_ref,
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    dq_ref,
    dq_scr,
    *,
    softcap: float,
    scale: float,
    bq: int,
    bk: int,
    g: int,
    nc: int,
):
    """GQA-packed dq: grid (hk, W) — the whole query group of one kv head
    per grid step (vs :func:`_bwd_dq_kernel`'s (hq, W)). k/v are fetched
    ONCE per work item instead of ``g`` times and the per-step s/dp matmuls
    run ``g``x taller. lse/delta arrive TILE-PACKED from the host:
    ``(hk, num_q_tiles, g*bq)`` with packed row ``gi*bq + r`` = head
    ``h*g+gi``, row ``qt*bq + r`` — so the kernel's column view is the same
    lanes->sublanes expand the unpacked kernel uses, just ``g``x taller.
    """
    w = pl.program_id(1)
    is_first = meta_ref[w, IS_FIRST]
    is_last = meta_ref[w, IS_LAST]
    is_full = meta_ref[w, IS_FULL]
    use_exp2 = softcap == 0.0
    exp_fn = jnp.exp2 if use_exp2 else jnp.exp

    @pl.when(is_first == 1)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    d = q_ref.shape[-1]
    q = q_ref[0].reshape(g * bq, d)  # pre-scaled on host
    k = k_ref[0]

    lse = jnp.expand_dims(lse_ref[0], -1)  # (g*bq, 1), tile-packed rows
    delta = jnp.expand_dims(delta_ref[0], -1)
    dv = v_ref.shape[-1]
    do = do_ref[0].reshape(g * bq, dv)

    def score(k_blk):
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if softcap > 0.0:
            sc = softcap * jnp.tanh(s / softcap)
            return sc, 1.0 - (sc / softcap) ** 2
        return s, None

    def accum(sm, dcap, dp, k_blk, masked: bool):
        if masked:
            neg = lse <= EMPTY_THRESH
            lse_safe = jnp.where(neg, 0.0, lse)
            if use_exp2:
                lse_safe = lse_safe * LOG2E
            p = exp_fn(sm - lse_safe)
            p = jnp.where(neg, 0.0, p)
        else:
            p = exp_fn(sm - (lse * LOG2E if use_exp2 else lse))
        ds = p * (dp - delta)
        if dcap is not None:
            ds = ds * dcap
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    def dp_of(v_blk):
        return jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if nc == 0:
        sc, dcap = score(k)
        dp = dp_of(v_ref[0])

        @pl.when(is_full == 1)
        def _():
            accum(sc, dcap, dp, k, masked=False)

        @pl.when(is_full == 0)
        def _():
            q_base = work_qt_ref[w] * bq
            k_base = work_kt_ref[w] * bk
            accum(
                jnp.where(
                    _item_mask(
                        meta_ref, w, q_base, k_base, bq, bk, repeat=g
                    ),
                    sc, MASK_VALUE,
                ),
                dcap, dp, k,
                masked=True,
            )
    else:
        # extent-clamped body (see _bwd_dq_kernel); the live k extent is
        # shared by the packed heads
        ck = bk // nc
        _, _, ek0, ek1, live = _item_extents(meta_ref, w)

        @pl.when(is_full == 1)
        def _():
            sc, dcap = score(k)
            accum(sc, dcap, dp_of(v_ref[0]), k, masked=False)

        for c in range(nc):
            c0 = c * ck

            @pl.when((is_full == 0) & live & (ek0 < c0 + ck) & (ek1 > c0))
            def _(c0=c0):
                q_base = work_qt_ref[w] * bq
                k_base = work_kt_ref[w] * bk
                k_c = k[c0 : c0 + ck]
                sc, dcap = score(k_c)
                accum(
                    jnp.where(
                        _item_mask(
                            meta_ref, w, q_base, k_base + c0, bq, ck,
                            repeat=g,
                        ),
                        sc, MASK_VALUE,
                    ),
                    dcap, dp_of(v_ref[0][c0 : c0 + ck]), k_c,
                    masked=True,
                )

    @pl.when(is_last == 1)
    def _():
        # softmax_scale folded into the flush (see _bwd_dq_kernel)
        dq_ref[0] = (dq_scr[:] * scale).reshape(g, bq, d)


def _tile_pack_rows(x_t: jax.Array, hk: int, g: int, bq: int) -> jax.Array:
    """(hq, sqp) fp32 -> (hk, num_q_tiles, 1, g*bq) tile-packed rows for
    the packed dq kernel (host-side; one transpose of a small fp32 array).
    The unit sublane axis keeps the BlockSpec's trailing-two dims equal to
    the array dims (the Pallas TPU (8, 128) divisibility rule)."""
    hq, sqp = x_t.shape
    nqt = sqp // bq
    return (
        x_t.reshape(hk, g, nqt, bq).transpose(0, 2, 1, 3).reshape(
            hk, nqt, 1, g * bq
        )
    )


def _ffa_bwd_dq_pallas_gqa(
    params: FFAParams, work_qt, work_kt, meta, q_t, k_t, v_t, do_t, lse_t,
    delta_t,
):
    """GQA-packed dq pallas call (see :func:`_bwd_dq_kernel_gqa`)."""
    bq, bk = params.dq_blocks()
    hq, sqp, d = q_t.shape
    hk, skp, dv = v_t.shape
    g = params.group
    W = params.num_work_dq if params.num_work_dq is not None else params.num_work

    use_exp2 = params.softcap == 0.0
    q_scale = params.softmax_scale * (LOG2E if use_exp2 else 1.0)
    q_t = (q_t.astype(jnp.float32) * q_scale).astype(q_t.dtype)
    q_g = q_t.reshape(hk, g, sqp, d)
    do_g = do_t.reshape(hk, g, sqp, dv)
    lse_p = _tile_pack_rows(_clamp_lse(lse_t), hk, g, bq)
    delta_p = _tile_pack_rows(delta_t, hk, g, bq)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(hk, W),
        in_specs=[
            pl.BlockSpec((1, g, bq, d), lambda h, w, qt, kt, mt: (h, 0, qt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda h, w, qt, kt, mt: (h, kt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, dv), lambda h, w, qt, kt, mt: (h, kt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, g, bq, dv),
                         lambda h, w, qt, kt, mt: (h, 0, qt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, None, 1, g * bq),
                         lambda h, w, qt, kt, mt: (h, qt[w], 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, None, 1, g * bq),
                         lambda h, w, qt, kt, mt: (h, qt[w], 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, g, bq, d),
                         lambda h, w, qt, kt, mt: (h, 0, qt[w], 0),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[pltpu.VMEM((g * bq, d), jnp.float32)],
    )
    kernel = partial(
        _bwd_dq_kernel_gqa, softcap=params.softcap,
        scale=params.softmax_scale, bq=bq, bk=bk, g=g,
        nc=_clamp_chunks(bk),
    )
    (dq_g,) = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((hk, g, sqp, d), jnp.float32)],
        interpret=params.interpret,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
    )(work_qt, work_kt, meta, q_g, k_t, v_t, do_g, lse_p, delta_p)
    return dq_g.reshape(hq, sqp, d)  # scale folded into the kernel flush


def _use_gqa_pack_dq(
    params: FFAParams, d: int, dv: int | None = None, itemsize: int = 2
) -> bool:
    """Trace-time dispatch to the packed dq kernel: opt-in flag, real
    grouping, and a VMEM guard on the EXACT packed-step residency with the
    REAL head dims (utils/mem_budget.ffa_kernel_residency — shared with
    the static kernel checker's K1; an earlier score-tile-only formula
    under-counted blocks + scratch at large head_dim)."""
    from . import registry as _registry

    bq, bk = params.dq_blocks()
    return (
        _registry.gqa_pack_variant("dq") == "gqa_packed"
        and params.group > 1
        and ffa_kernel_residency(
            "dq", bq, bk, d, head_dim_v=dv, dtype_bytes=itemsize,
            group=params.group, packed=True,
        )
        <= VMEM_ALLOWED_BYTES
    )


def ffa_bwd_dq_pallas_dispatch(
    params: FFAParams, work_qt, work_kt, meta, q_t, k_t, v_t, do_t, lse_t,
    delta_t,
):
    """dq backward with the GQA-packing dispatch applied — the ONE entry
    every backward path (custom-vjp core, CP multi-stage, sink, dynamic)
    uses so the packed dq kernel is reachable from all of them (mirrors
    :func:`ffa_fwd_pallas_dispatch`)."""
    fn = (
        _ffa_bwd_dq_pallas_gqa
        if _use_gqa_pack_dq(params, q_t.shape[2], v_t.shape[2],
                            q_t.dtype.itemsize)
        else _ffa_bwd_dq_pallas
    )
    return fn(params, work_qt, work_kt, meta, q_t, k_t, v_t, do_t, lse_t,
              delta_t)


# ---------------------------------------------------------------------------
# backward: dk/dv (k-major plan)
# ---------------------------------------------------------------------------


def _bwd_dkv_kernel(
    work_qt_ref,
    work_kt_ref,
    meta_ref,
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    dk_ref,
    dv_ref,
    dk_scr,
    dv_scr,
    *,
    softcap: float,
    bq: int,
    bk: int,
    group: int,
    nc: int,
):
    # grid (hk, W, gi): the GQA group dim is innermost so dk/dv accumulate
    # over the g q-heads of a kv head in VMEM scratch — the kv-head output
    # is written once (vs per-q-head partials + a host reshape-sum, which
    # costs g x the HBM writes; the CUDA kernel accumulates in-epilogue the
    # same way). k/v blocks stay resident across the g inner steps.
    w = pl.program_id(1)
    gi = pl.program_id(2)
    is_first = meta_ref[w, IS_FIRST]
    is_last = meta_ref[w, IS_LAST]
    is_full = meta_ref[w, IS_FULL]
    use_exp2 = softcap == 0.0
    exp_fn = jnp.exp2 if use_exp2 else jnp.exp

    @pl.when((is_first == 1) & (gi == 0))
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q = q_ref[0]  # pre-scaled by softmax_scale on the host: dk = ds_t @ q'
    # (exp2 path: q' also carries log2e; the host divides dk by log2e)
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]

    def score(q_blk):
        # s_t: (bk, rows(q_blk)) — k rows, q cols
        s_t = jax.lax.dot_general(
            k, q_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if softcap > 0.0:
            sc_t = softcap * jnp.tanh(s_t / softcap)
            return sc_t, 1.0 - (sc_t / softcap) ** 2
        return s_t, None

    def accum(sm_t, dcap_t, lse_c, delta_c, do_blk, q_blk, masked: bool):
        dp_t = jax.lax.dot_general(
            v, do_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if masked:
            neg = lse_c <= EMPTY_THRESH
            lse_safe = jnp.where(neg, 0.0, lse_c)
            if use_exp2:
                lse_safe = lse_safe * LOG2E
            p_t = exp_fn(sm_t - lse_safe)
            p_t = jnp.where(neg, 0.0, p_t)
        else:
            p_t = exp_fn(sm_t - (lse_c * LOG2E if use_exp2 else lse_c))
        dv_scr[:] += jax.lax.dot_general(
            p_t.astype(do.dtype), do_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds_t = p_t * (dp_t - delta_c)
        if dcap_t is not None:
            ds_t = ds_t * dcap_t
        # q is pre-scaled, so ds_t @ q' == (ds_t * scale) @ q == dk exactly
        dk_scr[:] += jax.lax.dot_general(
            ds_t.astype(q.dtype), q_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    # lse/delta q-in-lanes rows: ref block (sublanes, bq) -> (1, bq) views
    lse = lse_ref[:1, :]  # (1, bq)
    delta = delta_ref[:1, :]  # (1, bq)

    if nc == 0:
        sc_t, dcap_t = score(q)

        @pl.when(is_full == 1)
        def _():
            accum(sc_t, dcap_t, lse, delta, do, q, masked=False)

        @pl.when(is_full == 0)
        def _():
            q_base = work_qt_ref[w] * bq
            k_base = work_kt_ref[w] * bk
            accum(
                jnp.where(
                    _item_mask(meta_ref, w, q_base, k_base, bq, bk,
                               transposed=True),
                    sc_t, MASK_VALUE,
                ),
                dcap_t, lse, delta, do, q,
                masked=True,
            )
    else:
        # extent-clamped body: q is the LANE dim of s_t here, so partial
        # tiles chunk the q extent (eq0/eq1) instead of the k extent;
        # skipped chunks are fully masked -> p_t exactly 0 in the legacy
        # path, so dropping them does not change dk/dv
        cq = bq // nc
        eq0, eq1, _, _, live = _item_extents(meta_ref, w)

        @pl.when(is_full == 1)
        def _():
            sc_t, dcap_t = score(q)
            accum(sc_t, dcap_t, lse, delta, do, q, masked=False)

        for c in range(nc):
            c0 = c * cq

            @pl.when((is_full == 0) & live & (eq0 < c0 + cq) & (eq1 > c0))
            def _(c0=c0):
                q_base = work_qt_ref[w] * bq
                k_base = work_kt_ref[w] * bk
                q_c = q[c0 : c0 + cq]
                sc_t, dcap_t = score(q_c)
                accum(
                    jnp.where(
                        _item_mask(meta_ref, w, q_base + c0, k_base, cq,
                                   bk, transposed=True),
                        sc_t, MASK_VALUE,
                    ),
                    dcap_t,
                    lse_ref[:1, c0 : c0 + cq],
                    delta_ref[:1, c0 : c0 + cq],
                    do[c0 : c0 + cq],
                    q_c,
                    masked=True,
                )

    @pl.when((is_last == 1) & (gi == group - 1))
    def _():
        dk_ref[0] = dk_scr[:]
        dv_ref[0] = dv_scr[:]


def _ffa_bwd_dkv_pallas(
    params: FFAParams, work_qt_t, work_kt_t, meta_t,
    q_t, k_t, v_t, do_t, lse_t, delta_t,
):
    bq, bk = params.dkv_blocks()
    hq, sqp, d = q_t.shape
    hk, skp, dv = v_t.shape
    g = params.group
    WT = (
        params.num_work_dkv
        if params.num_work_dkv is not None
        else params.num_work_t
    )

    # pre-scale q: dk = ds_t @ q' carries the scale factor exactly; the
    # exp2-path log2e factor is divided back out of dk on return
    use_exp2 = params.softcap == 0.0
    q_scale = params.softmax_scale * (LOG2E if use_exp2 else 1.0)
    q_t = (q_t.astype(jnp.float32) * q_scale).astype(q_t.dtype)

    # grid (hk, WT, g): group innermost so the kv-head dk/dv accumulate in
    # scratch over the g q-heads (outputs and k/v fetches are per kv head —
    # 1/g the HBM traffic of per-q-head partials)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(hk, WT, g),
        in_specs=[
            pl.BlockSpec(
                (1, bq, d),
                lambda h, w, gi, qt, kt, mt: (h * g + gi, qt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, bk, d), lambda h, w, gi, qt, kt, mt: (h, kt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, bk, dv), lambda h, w, gi, qt, kt, mt: (h, kt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, bq, dv),
                lambda h, w, gi, qt, kt, mt: (h * g + gi, qt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (None, NUM_SUBLANES, bq),
                lambda h, w, gi, qt, kt, mt: (h * g + gi, 0, qt[w]),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (None, NUM_SUBLANES, bq),
                lambda h, w, gi, qt, kt, mt: (h * g + gi, 0, qt[w]),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, bk, d), lambda h, w, gi, qt, kt, mt: (h, kt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, bk, dv), lambda h, w, gi, qt, kt, mt: (h, kt[w], 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, dv), jnp.float32),
        ],
    )
    kernel = partial(
        _bwd_dkv_kernel, softcap=params.softcap,
        bq=bq, bk=bk, group=g, nc=_clamp_chunks(bq),
    )
    dk_t, dv_t = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((hk, skp, d), jnp.float32),
            jax.ShapeDtypeStruct((hk, skp, dv), jnp.float32),
        ],
        interpret=params.interpret,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
    )(work_qt_t, work_kt_t, meta_t, q_t, k_t, v_t, do_t,
      _lanes_layout(_clamp_lse(lse_t), NUM_SUBLANES),
      _lanes_layout(delta_t, NUM_SUBLANES))
    if use_exp2:
        dk_t = dk_t * LN2  # divide the folded log2e back out
    return dk_t, dv_t


def _bwd_dkv_kernel_gqa(
    work_qt_ref,
    work_kt_ref,
    meta_ref,
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    dk_ref,
    dv_ref,
    dk_scr,
    dv_scr,
    *,
    softcap: float,
    bq: int,
    bk: int,
    g: int,
    clamp: bool,
):
    """GQA-packed dk/dv: grid (hk, WT) — the whole query group of one kv
    head per grid step (vs :func:`_bwd_dkv_kernel`'s (hk, WT, g) with the
    group innermost). q/do arrive as (g, bq, ·) blocks reshaped to packed
    (g*bq, ·) rows, so s_t/dp_t are ONE (bk, g*bq) MXU contraction and the
    dk/dv accumulations contract over all g heads at once — summing the
    packed columns IS the group sum, since each packed column belongs to
    exactly one (head, row) pair. q/do are fetched once per work item
    instead of per group member and the matmuls run ``g``x longer,
    feeding the MXU full tiles (FlashAttention-2's bwd work-partitioning
    lesson). lse/delta arrive TILE-PACKED (:func:`_tile_pack_rows`) and
    broadcast over the bk rows.
    """
    w = pl.program_id(1)
    is_first = meta_ref[w, IS_FIRST]
    is_last = meta_ref[w, IS_LAST]
    is_full = meta_ref[w, IS_FULL]
    use_exp2 = softcap == 0.0
    exp_fn = jnp.exp2 if use_exp2 else jnp.exp

    @pl.when(is_first == 1)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    d = q_ref.shape[-1]
    dv = v_ref.shape[-1]
    q = q_ref[0].reshape(g * bq, d)  # pre-scaled on host
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0].reshape(g * bq, dv)

    lse = lse_ref[...]  # (1, g*bq), tile-packed cols; broadcasts over bk rows
    delta = delta_ref[...]

    def score():
        # s_t: (bk, g*bq) — k rows, packed (head, q-row) cols
        s_t = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if softcap > 0.0:
            sc_t = softcap * jnp.tanh(s_t / softcap)
            return sc_t, 1.0 - (sc_t / softcap) ** 2
        return s_t, None

    def accum(sm_t, dcap_t, masked: bool):
        dp_t = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if masked:
            neg = lse <= EMPTY_THRESH
            lse_safe = jnp.where(neg, 0.0, lse)
            if use_exp2:
                lse_safe = lse_safe * LOG2E
            p_t = exp_fn(sm_t - lse_safe)
            p_t = jnp.where(neg, 0.0, p_t)
        else:
            p_t = exp_fn(sm_t - (lse * LOG2E if use_exp2 else lse))
        # contraction over the g*bq packed cols == the per-group sum the
        # unpacked kernel does across its g inner grid steps
        dv_scr[:] += jax.lax.dot_general(
            p_t.astype(do.dtype), do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds_t = p_t * (dp_t - delta)
        if dcap_t is not None:
            ds_t = ds_t * dcap_t
        dk_scr[:] += jax.lax.dot_general(
            ds_t.astype(q.dtype), q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if not clamp:
        sc_t, dcap_t = score()

        @pl.when(is_full == 1)
        def _():
            accum(sc_t, dcap_t, masked=False)

        @pl.when(is_full == 0)
        def _():
            q_base = work_qt_ref[w] * bq
            k_base = work_kt_ref[w] * bk
            accum(
                jnp.where(
                    _item_mask(meta_ref, w, q_base, k_base, bq, bk,
                               transposed=True, repeat=g),
                    sc_t, MASK_VALUE,
                ),
                dcap_t,
                masked=True,
            )
    else:
        # the packed lane dim interleaves the g heads' q rows, so it cannot
        # be chunked by a single q extent; clamping here is the whole-item
        # guard — dummy/pad items (empty extent) skip both MXU passes
        # (their legacy contribution was exactly 0: masked p_t underflows)
        _, _, _, _, live = _item_extents(meta_ref, w)

        @pl.when((is_full == 1) & live)
        def _():
            sc_t, dcap_t = score()
            accum(sc_t, dcap_t, masked=False)

        @pl.when((is_full == 0) & live)
        def _():
            q_base = work_qt_ref[w] * bq
            k_base = work_kt_ref[w] * bk
            sc_t, dcap_t = score()
            accum(
                jnp.where(
                    _item_mask(meta_ref, w, q_base, k_base, bq, bk,
                               transposed=True, repeat=g),
                    sc_t, MASK_VALUE,
                ),
                dcap_t,
                masked=True,
            )

    @pl.when(is_last == 1)
    def _():
        dk_ref[0] = dk_scr[:]
        dv_ref[0] = dv_scr[:]


def _ffa_bwd_dkv_pallas_gqa(
    params: FFAParams, work_qt_t, work_kt_t, meta_t,
    q_t, k_t, v_t, do_t, lse_t, delta_t,
):
    """GQA-packed dk/dv pallas call (see :func:`_bwd_dkv_kernel_gqa`)."""
    bq, bk = params.dkv_blocks()
    hq, sqp, d = q_t.shape
    hk, skp, dv = v_t.shape
    g = params.group
    WT = (
        params.num_work_dkv
        if params.num_work_dkv is not None
        else params.num_work_t
    )

    use_exp2 = params.softcap == 0.0
    q_scale = params.softmax_scale * (LOG2E if use_exp2 else 1.0)
    q_t = (q_t.astype(jnp.float32) * q_scale).astype(q_t.dtype)
    q_g = q_t.reshape(hk, g, sqp, d)
    do_g = do_t.reshape(hk, g, sqp, dv)
    lse_p = _tile_pack_rows(_clamp_lse(lse_t), hk, g, bq)
    delta_p = _tile_pack_rows(delta_t, hk, g, bq)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(hk, WT),
        in_specs=[
            pl.BlockSpec((1, g, bq, d),
                         lambda h, w, qt, kt, mt: (h, 0, qt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda h, w, qt, kt, mt: (h, kt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, dv), lambda h, w, qt, kt, mt: (h, kt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, g, bq, dv),
                         lambda h, w, qt, kt, mt: (h, 0, qt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, None, 1, g * bq),
                         lambda h, w, qt, kt, mt: (h, qt[w], 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, None, 1, g * bq),
                         lambda h, w, qt, kt, mt: (h, qt[w], 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda h, w, qt, kt, mt: (h, kt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, dv), lambda h, w, qt, kt, mt: (h, kt[w], 0),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, dv), jnp.float32),
        ],
    )
    kernel = partial(
        _bwd_dkv_kernel_gqa, softcap=params.softcap, bq=bq, bk=bk, g=g,
        clamp=_registry_mod().extent_clamp_enabled(),
    )
    dk_t, dv_t = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((hk, skp, d), jnp.float32),
            jax.ShapeDtypeStruct((hk, skp, dv), jnp.float32),
        ],
        interpret=params.interpret,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
    )(work_qt_t, work_kt_t, meta_t, q_g, k_t, v_t, do_g, lse_p, delta_p)
    if use_exp2:
        dk_t = dk_t * LN2  # divide the folded log2e back out
    return dk_t, dv_t


def _use_gqa_pack_dkv(
    params: FFAParams, sqp: int, d: int, dv: int, itemsize: int = 2
) -> bool:
    """Trace-time dispatch to the packed dkv kernel. ON by default when
    there is real grouping (env flag ``ffa_gqa_pack_dkv``) and shapes
    divide (the dkv q tile must tile the padded seqlen for the host-side
    lse/delta tile-pack). VMEM guard: the EXACT packed-step residency —
    blocks + (bk, d+dv) fp32 scratch + the (bk, g*bq) fp32 s_t/dp_t tiles
    (utils/mem_budget.ffa_kernel_residency, shared with the static kernel
    checker's K1) — must fit the per-core budget with headroom."""
    from . import registry as _registry

    bq, bk = params.dkv_blocks()
    return (
        _registry.gqa_pack_variant("dkv") == "gqa_packed"
        and params.group > 1
        and sqp % bq == 0
        and ffa_kernel_residency(
            "dkv", bq, bk, d, head_dim_v=dv, dtype_bytes=itemsize,
            group=params.group, packed=True,
        )
        <= VMEM_ALLOWED_BYTES
    )


def ffa_bwd_dkv_pallas_dispatch(
    params: FFAParams, work_qt_t, work_kt_t, meta_t, q_t, k_t, v_t, do_t,
    lse_t, delta_t,
):
    """dk/dv backward with the GQA-packing dispatch applied — the ONE
    entry every backward path (custom-vjp core, CP multi-stage, sink,
    dynamic) uses so the packed dkv kernel is reachable from all of them
    (mirrors :func:`ffa_bwd_dq_pallas_dispatch`)."""
    fn = (
        _ffa_bwd_dkv_pallas_gqa
        if _use_gqa_pack_dkv(params, q_t.shape[1], q_t.shape[2],
                             v_t.shape[2], q_t.dtype.itemsize)
        else _ffa_bwd_dkv_pallas
    )
    return fn(params, work_qt_t, work_kt_t, meta_t, q_t, k_t, v_t, do_t,
              lse_t, delta_t)


# ---------------------------------------------------------------------------
# backward: delta preprocessing (rowsum of dO ⊙ O)
# ---------------------------------------------------------------------------


def _delta_kernel(o_ref, do_ref, delta_ref, *, bq: int):
    """delta = rowsum(dO ⊙ O) in fp32 for one (head, q-tile) block.

    Shared preprocessing of every backward pass (split dq, split dkv, and
    the fused one-pass kernel all consume delta); running it as a Pallas
    kernel removes the XLA full-array pass over o and do the old
    ``jnp.sum`` epilogue cost. The result is emitted lanes-broadcast
    ``(bq, NUM_LANES)`` — the proven lse output layout — and sliced to a
    column on the host; no accumulator, every grid step is independent.
    """
    prod = o_ref[0].astype(jnp.float32) * do_ref[0].astype(jnp.float32)
    col = jnp.sum(prod, axis=-1)[:, None]  # (bq, 1)
    delta_ref[0] = jnp.broadcast_to(col, (bq, NUM_LANES))


def _ffa_delta_pallas(out_t, do_t, block_q: int, interpret: bool):
    """Tiled delta kernel over head-major padded (hq, sqp, dv) arrays.

    ``block_q`` must divide sqp (always true for the fwd padded geometry:
    sqp = num_q_tiles * block_q). Returns (hq, sqp) fp32.
    """
    hq, sqp, dv = out_t.shape
    bq = min(block_q, sqp)
    nqt = sqp // bq
    (delta_b,) = pl.pallas_call(
        partial(_delta_kernel, bq=bq),
        grid=(hq, nqt),
        in_specs=[
            pl.BlockSpec((1, bq, dv), lambda h, i: (h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, dv), lambda h, i: (h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, NUM_LANES), lambda h, i: (h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[jax.ShapeDtypeStruct((hq, sqp, NUM_LANES), jnp.float32)],
        interpret=interpret,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel"),
        ),
    )(out_t, do_t)
    return delta_b[..., 0]


def ffa_delta_pallas_dispatch(params: FFAParams, out_t, do_t):
    """delta preprocessing entry used by every backward path (mirrors the
    fwd/dq/dkv dispatch naming so the static kernel checker drives it the
    same way)."""
    return _ffa_delta_pallas(out_t, do_t, params.block_q, params.interpret)


# ---------------------------------------------------------------------------
# backward: fused one-pass (k-major plan, revisit-accumulated dq)
# ---------------------------------------------------------------------------


def _bwd_fused_kernel(
    work_qt_ref,
    work_kt_ref,
    meta_ref,
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    dqz_ref,
    dq_ref,
    dk_ref,
    dv_ref,
    dk_scr,
    dv_scr,
    *,
    softcap: float,
    scale: float,
    bq: int,
    bk: int,
    group: int,
    nc: int,
):
    """Fused one-pass backward: dk, dv AND dq from ONE score recompute.

    Same grid and dk/dv discipline as :func:`_bwd_dkv_kernel` (k-major
    plan, grid (hk, WT, g), group innermost, VMEM scratch flushed on the
    k tile's last visit). The fused extra: each work item's dq
    contribution ``ds @ k`` is accumulated directly into the REVISITED dq
    output window — the k-major traversal visits one q tile many times,
    non-consecutively, so there is no scratch run to accumulate in;
    instead the output block itself is read-modify-written across visits:
    zero-initialized when the plan's first-q-visit flag (QVF) is set,
    accumulated every visit, and flushed (folding softmax_scale) on the
    last-q-visit flag (QVL). Never-visited q tiles (fully masked rows)
    keep the aliased zero background the wrapper passes as ``dqz_ref``.
    This shares the s_t/p_t recompute between dq and dk/dv — 5 tile
    matmuls per work item where the split passes spend 7 — and halves the
    backward HBM reads of q/k/v/do.
    """
    w = pl.program_id(1)
    gi = pl.program_id(2)
    is_first = meta_ref[w, IS_FIRST]
    is_last = meta_ref[w, IS_LAST]
    is_full = meta_ref[w, IS_FULL]
    qvf = meta_ref[w, QVF]
    qvl = meta_ref[w, QVL]
    use_exp2 = softcap == 0.0
    exp_fn = jnp.exp2 if use_exp2 else jnp.exp
    del dqz_ref  # aliased zero background only; never read in-kernel

    @pl.when((is_first == 1) & (gi == 0))
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    d = q_ref.shape[-1]

    # revisit-accumulation init: this (head, q tile) dq window is seen for
    # the first time in the k-major traversal — start it from zero
    @pl.when(qvf == 1)
    def _():
        dq_ref[0] = jnp.zeros((bq, d), jnp.float32)

    q = q_ref[0]  # pre-scaled by softmax_scale (* log2e when softcap-free)
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]

    def score(q_blk):
        s_t = jax.lax.dot_general(
            k, q_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if softcap > 0.0:
            sc_t = softcap * jnp.tanh(s_t / softcap)
            return sc_t, 1.0 - (sc_t / softcap) ** 2
        return s_t, None

    def accum(sm_t, dcap_t, lse_c, delta_c, do_blk, q_blk, c0: int,
              rows: int, masked: bool):
        dp_t = jax.lax.dot_general(
            v, do_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if masked:
            neg = lse_c <= EMPTY_THRESH
            lse_safe = jnp.where(neg, 0.0, lse_c)
            if use_exp2:
                lse_safe = lse_safe * LOG2E
            p_t = exp_fn(sm_t - lse_safe)
            p_t = jnp.where(neg, 0.0, p_t)
        else:
            p_t = exp_fn(sm_t - (lse_c * LOG2E if use_exp2 else lse_c))
        dv_scr[:] += jax.lax.dot_general(
            p_t.astype(do.dtype), do_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds_t = p_t * (dp_t - delta_c)
        if dcap_t is not None:
            ds_t = ds_t * dcap_t
        # q is pre-scaled, so ds_t @ q' == (ds_t * scale) @ q == dk exactly
        dk_scr[:] += jax.lax.dot_general(
            ds_t.astype(q.dtype), q_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # the fused extra product: ds^T-contraction with k gives this
        # item's (rows, d) dq contribution, read-modify-written into the
        # revisited output window (k carries NO scale; applied at flush)
        dq_ref[0, c0:c0 + rows] += jax.lax.dot_general(
            ds_t.astype(k.dtype), k, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    # lse/delta q-in-lanes rows: ref block (sublanes, bq) -> (1, bq) views
    lse = lse_ref[:1, :]
    delta = delta_ref[:1, :]

    if nc == 0:
        sc_t, dcap_t = score(q)

        @pl.when(is_full == 1)
        def _():
            accum(sc_t, dcap_t, lse, delta, do, q, 0, bq, masked=False)

        @pl.when(is_full == 0)
        def _():
            q_base = work_qt_ref[w] * bq
            k_base = work_kt_ref[w] * bk
            accum(
                jnp.where(
                    _item_mask(meta_ref, w, q_base, k_base, bq, bk,
                               transposed=True),
                    sc_t, MASK_VALUE,
                ),
                dcap_t, lse, delta, do, q, 0, bq,
                masked=True,
            )
    else:
        # extent-clamped body (see _bwd_dkv_kernel): q is the lane dim of
        # s_t, so partial tiles chunk the q extent; a skipped chunk's p_t
        # was exactly 0 in the unclamped path, so its dq/dk/dv terms all
        # vanish and dropping it changes nothing
        cq = bq // nc
        eq0, eq1, _, _, live = _item_extents(meta_ref, w)

        @pl.when(is_full == 1)
        def _():
            sc_t, dcap_t = score(q)
            accum(sc_t, dcap_t, lse, delta, do, q, 0, bq, masked=False)

        for c in range(nc):
            c0 = c * cq

            @pl.when((is_full == 0) & live & (eq0 < c0 + cq) & (eq1 > c0))
            def _(c0=c0):
                q_base = work_qt_ref[w] * bq
                k_base = work_kt_ref[w] * bk
                q_c = q[c0 : c0 + cq]
                sc_t, dcap_t = score(q_c)
                accum(
                    jnp.where(
                        _item_mask(meta_ref, w, q_base + c0, k_base, cq,
                                   bk, transposed=True),
                        sc_t, MASK_VALUE,
                    ),
                    dcap_t,
                    lse_ref[:1, c0 : c0 + cq],
                    delta_ref[:1, c0 : c0 + cq],
                    do[c0 : c0 + cq],
                    q_c,
                    c0, cq,
                    masked=True,
                )

    @pl.when((is_last == 1) & (gi == group - 1))
    def _():
        dk_ref[0] = dk_scr[:]
        dv_ref[0] = dv_scr[:]

    # revisit-accumulation flush: last visit of this q tile — fold
    # softmax_scale into the resident window (both exp2 and softcap paths
    # accumulate the UNSCALED ds @ k above)
    @pl.when(qvl == 1)
    def _():
        dq_ref[0] = dq_ref[0] * scale


def _ffa_bwd_fused_pallas(
    params: FFAParams, work_qt_t, work_kt_t, meta_t,
    q_t, k_t, v_t, do_t, lse_t, delta_t,
):
    """Fused one-pass backward pallas call (see :func:`_bwd_fused_kernel`).

    Returns (dq_t, dk_t, dv_t), all fp32. The dq output is aliased to a
    zero input (``input_output_aliases``) whose CONSTANT index map fetches
    one window exactly once: q tiles the k-major work list never visits
    (fully masked rows) keep that zero background, so no dummy work items
    are needed and the plan's work counts are untouched.
    """
    bq, bk = params.dkv_blocks()
    hq, sqp, d = q_t.shape
    hk, skp, dv = v_t.shape
    g = params.group
    WT = (
        params.num_work_dkv
        if params.num_work_dkv is not None
        else params.num_work_t
    )

    use_exp2 = params.softcap == 0.0
    q_scale = params.softmax_scale * (LOG2E if use_exp2 else 1.0)
    q_t = (q_t.astype(jnp.float32) * q_scale).astype(q_t.dtype)
    dqz = jnp.zeros((hq, sqp, d), jnp.float32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(hk, WT, g),
        in_specs=[
            pl.BlockSpec(
                (1, bq, d),
                lambda h, w, gi, qt, kt, mt: (h * g + gi, qt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, bk, d), lambda h, w, gi, qt, kt, mt: (h, kt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, bk, dv), lambda h, w, gi, qt, kt, mt: (h, kt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, bq, dv),
                lambda h, w, gi, qt, kt, mt: (h * g + gi, qt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (None, NUM_SUBLANES, bq),
                lambda h, w, gi, qt, kt, mt: (h * g + gi, 0, qt[w]),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (None, NUM_SUBLANES, bq),
                lambda h, w, gi, qt, kt, mt: (h * g + gi, 0, qt[w]),
                memory_space=pltpu.VMEM,
            ),
            # aliased zero background for dq: constant index map — the
            # window is fetched once, never streamed per step, never read
            pl.BlockSpec(
                (1, bq, d), lambda h, w, gi, qt, kt, mt: (0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, bq, d),
                lambda h, w, gi, qt, kt, mt: (h * g + gi, qt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, bk, d), lambda h, w, gi, qt, kt, mt: (h, kt[w], 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, bk, dv), lambda h, w, gi, qt, kt, mt: (h, kt[w], 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, dv), jnp.float32),
        ],
    )
    kernel = partial(
        _bwd_fused_kernel, softcap=params.softcap,
        scale=params.softmax_scale, bq=bq, bk=bk, group=g,
        nc=_clamp_chunks(bq),
    )
    dq_t, dk_t, dv_t = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((hq, sqp, d), jnp.float32),
            jax.ShapeDtypeStruct((hk, skp, d), jnp.float32),
            jax.ShapeDtypeStruct((hk, skp, dv), jnp.float32),
        ],
        # operand 9 (dqz, counting the 3 scalar-prefetch args) -> output 0
        input_output_aliases={9: 0},
        interpret=params.interpret,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
    )(work_qt_t, work_kt_t, meta_t, q_t, k_t, v_t, do_t,
      _lanes_layout(_clamp_lse(lse_t), NUM_SUBLANES),
      _lanes_layout(delta_t, NUM_SUBLANES), dqz)
    if use_exp2:
        dk_t = dk_t * LN2  # divide the folded log2e back out
    return dq_t, dk_t, dv_t


def _bwd_fused_kernel_gqa(
    work_qt_ref,
    work_kt_ref,
    meta_ref,
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    dqz_ref,
    dq_ref,
    dk_ref,
    dv_ref,
    dk_scr,
    dv_scr,
    *,
    softcap: float,
    scale: float,
    bq: int,
    bk: int,
    g: int,
    clamp: bool,
):
    """GQA-packed fused one-pass backward: grid (hk, WT), the whole query
    group of one kv head per step (see :func:`_bwd_dkv_kernel_gqa` for the
    packing scheme). The dq window is the full (g, bq, d) group block of
    the work item's q tile, revisit-accumulated under the same QVF/QVL
    discipline as :func:`_bwd_fused_kernel` — one init and one flush per
    tile visit run covers all g heads at once. Clamping is the whole-item
    live guard (the packed lane dim interleaves the g heads' q rows, so
    it cannot be chunked by a single q extent); init/flush stay OUTSIDE
    the guard so dead items still honor their visit flags.
    """
    w = pl.program_id(1)
    is_first = meta_ref[w, IS_FIRST]
    is_last = meta_ref[w, IS_LAST]
    is_full = meta_ref[w, IS_FULL]
    qvf = meta_ref[w, QVF]
    qvl = meta_ref[w, QVL]
    use_exp2 = softcap == 0.0
    exp_fn = jnp.exp2 if use_exp2 else jnp.exp
    del dqz_ref  # aliased zero background only; never read in-kernel

    @pl.when(is_first == 1)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    d = q_ref.shape[-1]
    dv = v_ref.shape[-1]

    @pl.when(qvf == 1)
    def _():
        dq_ref[0] = jnp.zeros((g, bq, d), jnp.float32)

    q = q_ref[0].reshape(g * bq, d)  # pre-scaled on host
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0].reshape(g * bq, dv)

    lse = lse_ref[...]  # (1, g*bq), tile-packed cols
    delta = delta_ref[...]

    def score():
        s_t = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if softcap > 0.0:
            sc_t = softcap * jnp.tanh(s_t / softcap)
            return sc_t, 1.0 - (sc_t / softcap) ** 2
        return s_t, None

    def accum(sm_t, dcap_t, masked: bool):
        dp_t = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if masked:
            neg = lse <= EMPTY_THRESH
            lse_safe = jnp.where(neg, 0.0, lse)
            if use_exp2:
                lse_safe = lse_safe * LOG2E
            p_t = exp_fn(sm_t - lse_safe)
            p_t = jnp.where(neg, 0.0, p_t)
        else:
            p_t = exp_fn(sm_t - (lse * LOG2E if use_exp2 else lse))
        dv_scr[:] += jax.lax.dot_general(
            p_t.astype(do.dtype), do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds_t = p_t * (dp_t - delta)
        if dcap_t is not None:
            ds_t = ds_t * dcap_t
        dk_scr[:] += jax.lax.dot_general(
            ds_t.astype(q.dtype), q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # fused dq contribution for ALL g heads at once: (g*bq, d) packed
        # rows unpacked back into the (g, bq, d) revisited window
        dq_ref[0] += jax.lax.dot_general(
            ds_t.astype(k.dtype), k, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).reshape(g, bq, d)

    if not clamp:
        sc_t, dcap_t = score()

        @pl.when(is_full == 1)
        def _():
            accum(sc_t, dcap_t, masked=False)

        @pl.when(is_full == 0)
        def _():
            q_base = work_qt_ref[w] * bq
            k_base = work_kt_ref[w] * bk
            accum(
                jnp.where(
                    _item_mask(meta_ref, w, q_base, k_base, bq, bk,
                               transposed=True, repeat=g),
                    sc_t, MASK_VALUE,
                ),
                dcap_t,
                masked=True,
            )
    else:
        # whole-item live guard (see _bwd_dkv_kernel_gqa); dead items'
        # contribution was exactly 0, so skipping their MXU passes is free
        _, _, _, _, live = _item_extents(meta_ref, w)

        @pl.when((is_full == 1) & live)
        def _():
            sc_t, dcap_t = score()
            accum(sc_t, dcap_t, masked=False)

        @pl.when((is_full == 0) & live)
        def _():
            q_base = work_qt_ref[w] * bq
            k_base = work_kt_ref[w] * bk
            sc_t, dcap_t = score()
            accum(
                jnp.where(
                    _item_mask(meta_ref, w, q_base, k_base, bq, bk,
                               transposed=True, repeat=g),
                    sc_t, MASK_VALUE,
                ),
                dcap_t,
                masked=True,
            )

    @pl.when(is_last == 1)
    def _():
        dk_ref[0] = dk_scr[:]
        dv_ref[0] = dv_scr[:]

    @pl.when(qvl == 1)
    def _():
        dq_ref[0] = dq_ref[0] * scale


def _ffa_bwd_fused_pallas_gqa(
    params: FFAParams, work_qt_t, work_kt_t, meta_t,
    q_t, k_t, v_t, do_t, lse_t, delta_t,
):
    """GQA-packed fused one-pass backward pallas call (see
    :func:`_bwd_fused_kernel_gqa`)."""
    bq, bk = params.dkv_blocks()
    hq, sqp, d = q_t.shape
    hk, skp, dv = v_t.shape
    g = params.group
    WT = (
        params.num_work_dkv
        if params.num_work_dkv is not None
        else params.num_work_t
    )

    use_exp2 = params.softcap == 0.0
    q_scale = params.softmax_scale * (LOG2E if use_exp2 else 1.0)
    q_t = (q_t.astype(jnp.float32) * q_scale).astype(q_t.dtype)
    q_g = q_t.reshape(hk, g, sqp, d)
    do_g = do_t.reshape(hk, g, sqp, dv)
    lse_p = _tile_pack_rows(_clamp_lse(lse_t), hk, g, bq)
    delta_p = _tile_pack_rows(delta_t, hk, g, bq)
    dqz = jnp.zeros((hk, g, sqp, d), jnp.float32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(hk, WT),
        in_specs=[
            pl.BlockSpec((1, g, bq, d),
                         lambda h, w, qt, kt, mt: (h, 0, qt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda h, w, qt, kt, mt: (h, kt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, dv), lambda h, w, qt, kt, mt: (h, kt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, g, bq, dv),
                         lambda h, w, qt, kt, mt: (h, 0, qt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, None, 1, g * bq),
                         lambda h, w, qt, kt, mt: (h, qt[w], 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, None, 1, g * bq),
                         lambda h, w, qt, kt, mt: (h, qt[w], 0, 0),
                         memory_space=pltpu.VMEM),
            # aliased zero background for dq (constant index map)
            pl.BlockSpec((1, g, bq, d),
                         lambda h, w, qt, kt, mt: (0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, g, bq, d),
                         lambda h, w, qt, kt, mt: (h, 0, qt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda h, w, qt, kt, mt: (h, kt[w], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, dv), lambda h, w, qt, kt, mt: (h, kt[w], 0),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, dv), jnp.float32),
        ],
    )
    kernel = partial(
        _bwd_fused_kernel_gqa, softcap=params.softcap,
        scale=params.softmax_scale, bq=bq, bk=bk, g=g,
        clamp=_registry_mod().extent_clamp_enabled(),
    )
    dq_g, dk_t, dv_t = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((hk, g, sqp, d), jnp.float32),
            jax.ShapeDtypeStruct((hk, skp, d), jnp.float32),
            jax.ShapeDtypeStruct((hk, skp, dv), jnp.float32),
        ],
        # operand 9 (dqz, counting the 3 scalar-prefetch args) -> output 0
        input_output_aliases={9: 0},
        interpret=params.interpret,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
    )(work_qt_t, work_kt_t, meta_t, q_g, k_t, v_t, do_g, lse_p, delta_p,
      dqz)
    if use_exp2:
        dk_t = dk_t * LN2  # divide the folded log2e back out
    return dq_g.reshape(hq, sqp, d), dk_t, dv_t


def _use_gqa_pack_fused(
    params: FFAParams, sqp: int, d: int, dv: int, itemsize: int = 2
) -> bool:
    """Trace-time dispatch to the packed fused kernel: same conditions as
    the packed dkv kernel (shared env flag — the packing trade-off is
    identical) with the LARGER fused residency — dkv's plus the revisited
    dq window and its aliased zero background (utils/mem_budget
    ``ffa_kernel_residency("fused", ...)``, one source of truth with K1)."""
    from . import registry as _registry

    bq, bk = params.dkv_blocks()
    return (
        _registry.gqa_pack_variant("dkv") == "gqa_packed"
        and params.group > 1
        and sqp % bq == 0
        and ffa_kernel_residency(
            "fused", bq, bk, d, head_dim_v=dv, dtype_bytes=itemsize,
            group=params.group, packed=True,
        )
        <= VMEM_ALLOWED_BYTES
    )


def fused_bwd_feasible(
    params: FFAParams, sqp: int, d: int, dv: int, itemsize: int = 2
) -> bool:
    """True when at least one fused-kernel variant's per-step VMEM
    residency fits the budget — the guard that forces split mode even
    under MAGI_ATTENTION_FFA_FUSED_BWD=1."""
    if _use_gqa_pack_fused(params, sqp, d, dv, itemsize):
        return True
    bq, bk = params.dkv_blocks()
    return (
        ffa_kernel_residency(
            "fused", bq, bk, d, head_dim_v=dv, dtype_bytes=itemsize,
            group=params.group, packed=False,
        )
        <= VMEM_ALLOWED_BYTES
    )


def ffa_bwd_fused_pallas_dispatch(
    params: FFAParams, work_qt_t, work_kt_t, meta_t, q_t, k_t, v_t, do_t,
    lse_t, delta_t,
):
    """Fused one-pass backward with the GQA-packing dispatch applied
    (mirrors :func:`ffa_bwd_dkv_pallas_dispatch`)."""
    fn = (
        _ffa_bwd_fused_pallas_gqa
        if _use_gqa_pack_fused(params, q_t.shape[1], q_t.shape[2],
                               v_t.shape[2], q_t.dtype.itemsize)
        else _ffa_bwd_fused_pallas
    )
    return fn(params, work_qt_t, work_kt_t, meta_t, q_t, k_t, v_t, do_t,
              lse_t, delta_t)


def ffa_bwd_mode(
    params: FFAParams, sqp: int, d: int, dv: int, itemsize: int,
    meta_cols: int,
) -> str:
    """Resolved backward execution mode — "fused" or "split" — decidable
    at trace time (static work counts / blocks / dims only; no plan
    contents, which may be traced arrays under shard_map).

    Selection flows through the backend registry (kernels/registry.py):
    a 'split'/'fused' pin (MAGI_ATTENTION_BACKEND_FFA_BWD, or the legacy
    MAGI_ATTENTION_FFA_FUSED_BWD mapped 0/1) wins outright — 'fused' still
    subject to the feasibility guards below — and unpinned geometries
    resolve against the policy cache / measured history, falling back to
    the tile_policy cost model.
    """
    from ..env import backend as env_backend
    from . import registry as _registry

    pin = env_backend.ffa_bwd_pin()
    if pin == "split":
        return "split"
    if meta_cols <= QVL:
        # plan meta predates the QVF/QVL visit-flag columns (hand-built
        # 13-col metas in older tests): the fused kernel cannot run
        return "split"
    if not fused_bwd_feasible(params, sqp, d, dv, itemsize):
        return "split"
    if pin == "fused":
        return "fused"
    from .tile_policy import choose_bwd_mode

    key = bwd_mode_key(params, d, dv, itemsize)
    return _registry.resolve(
        "ffa_bwd",
        key,
        lambda: choose_bwd_mode(
            *key[:7], dv, itemsize=itemsize, group=params.group
        ),
    ).name


def bwd_mode_key(
    params: FFAParams, d: int, dv: int, itemsize: int
) -> tuple[int, ...]:
    """The registry/store key of one backward-mode decision: the exact
    static quantities choose_bwd_mode consumes — (w_dq, bq_dq, bk_dq, wt,
    bq_dkv, bk_dkv, d, dv, itemsize, group). Shared by ffa_bwd_mode and
    the telemetry layer so measured history joins against resolutions."""
    bq_dq, bk_dq = params.dq_blocks()
    bq_dkv, bk_dkv = params.dkv_blocks()
    w_dq = (
        params.num_work_dq
        if params.num_work_dq is not None
        else params.num_work
    )
    wt = (
        params.num_work_dkv
        if params.num_work_dkv is not None
        else params.num_work_t
    )
    return (
        w_dq, bq_dq, bk_dq, wt, bq_dkv, bk_dkv, d, dv, itemsize,
        params.group,
    )


def bwd_modeled_cost(
    params: FFAParams, d: int, dv: int, itemsize: int, mode: str
) -> int:
    """choose_bwd_mode's modeled cost (MXU elems + balanced HBM term) of
    running the backward under ``mode`` — what the drift layer compares
    against measured wall time."""
    from .tile_policy import (
        BWD_MXU_ELEMS_PER_HBM_BYTE,
        bwd_hbm_bytes,
        bwd_mxu_elems,
    )

    key = bwd_mode_key(params, d, dv, itemsize)
    args = key[:7]
    return bwd_mxu_elems(mode, *args) + BWD_MXU_ELEMS_PER_HBM_BYTE * (
        bwd_hbm_bytes(
            mode, *args, dv, itemsize=itemsize, group=params.group
        )
    )


def resolved_bwd_mode(
    params: FFAParams, sqp: int, d: int, dv: int, itemsize: int = 2
) -> str:
    """The mode :func:`ffa_bwd_pallas_dispatch` will pick for a
    current-layout (META_DIM-column) plan — the telemetry layer stamps
    ``attn_step`` records' ``bwd_mode`` with this."""
    from .ffa_plan import META_DIM

    return ffa_bwd_mode(params, sqp, d, dv, itemsize, META_DIM)


def ffa_bwd_pallas_dispatch(
    params: FFAParams, dq_arrays, dkv_arrays, q_t, k_t, v_t, do_t, lse_t,
    delta_t,
):
    """ONE backward entry for every path (custom-vjp core, mixed branches,
    CP multi-stage, sink, dynamic): returns (dq_t, dk_t, dv_t).

    Picks the fused one-pass kernel (:func:`ffa_bwd_mode`) when the env
    flag / cost model / VMEM guard allow it, else the split dq + dkv
    passes. A fused-kernel failure is one resilience rung ABOVE the split
    path: with MAGI_ATTENTION_FALLBACK=1 it degrades to split (recorded as
    a resilience event) before the calc_attn tile ladder ever engages.
    """
    hq, sqp, d = q_t.shape
    dv = v_t.shape[2]
    meta_t = dkv_arrays[2]
    meta_cols = meta_t.shape[1] if meta_t.ndim == 2 else 0
    mode = ffa_bwd_mode(params, sqp, d, dv, q_t.dtype.itemsize, meta_cols)
    if mode == "fused":
        from ..resilience import fallback as _fallback

        try:
            maybe_inject("kernel_lowering")
            return ffa_bwd_fused_pallas_dispatch(
                params, *dkv_arrays, q_t, k_t, v_t, do_t, lse_t, delta_t
            )
        except _fallback.kernel_failure_types() as e:
            from ..env import resilience as env_resilience

            if not env_resilience.is_fallback_enable():
                raise
            _fallback.record_resilience_event(
                "fallback", "kernel_lowering",
                action_detail="fused_bwd_to_split",
                error=type(e).__name__,
            )
    dq_t = ffa_bwd_dq_pallas_dispatch(
        params, *dq_arrays, q_t, k_t, v_t, do_t, lse_t, delta_t
    )
    dk_t, dv_t = ffa_bwd_dkv_pallas_dispatch(
        params, *dkv_arrays, q_t, k_t, v_t, do_t, lse_t, delta_t
    )
    return dq_t, dk_t, dv_t


# ---------------------------------------------------------------------------
# static kernel contracts (consumed by analysis/kernel_check.py)
# ---------------------------------------------------------------------------

# One entry per Pallas kernel body in this file; the static checker's K2
# (accumulator discipline) and K4 (precision) passes read these as ground
# truth and verify the kernel SOURCE against them, so a drive-by edit that
# drops an init or moves a flush out of its guard fails `make kernel-audit`.
# Names refer to ref parameters / unpacked locals inside the kernel body.
# ``group_inner`` marks kernels whose grid revisits the same output tile
# across an inner grid dimension: init/flush must then additionally be
# qualified on that dimension's first/last position — the dkv-GQA-pack bug
# class K2 exists for. ``out_dtypes`` pairs positionally with the
# pallas_call out_shape ("input" = operand dtype passthrough, "f32" =
# must be float32); trailing optional outputs may be absent at capture.
PALLAS_CONTRACTS: dict[str, dict] = {
    "_fwd_kernel": dict(
        wrapper="_ffa_fwd_pallas",
        scratch=("m_scr", "l_scr", "acc_scr"),
        outputs=("out_ref", "lse_ref", "ml_ref"),
        out_dtypes=("input", "f32", "f32"),
        init_guard="is_first",
        flush_guard="is_last",
        group_inner=None,
    ),
    "_fwd_kernel_gqa": dict(
        wrapper="_ffa_fwd_pallas_gqa",
        scratch=("m_scr", "l_scr", "acc_scr"),
        outputs=("out_ref", "lse_ref"),
        out_dtypes=("input", "f32"),
        init_guard="is_first",
        flush_guard="is_last",
        group_inner=None,
    ),
    "_bwd_dq_kernel": dict(
        wrapper="_ffa_bwd_dq_pallas",
        scratch=("dq_scr",),
        outputs=("dq_ref",),
        out_dtypes=("f32",),
        init_guard="is_first",
        flush_guard="is_last",
        group_inner=None,
    ),
    "_bwd_dq_kernel_gqa": dict(
        wrapper="_ffa_bwd_dq_pallas_gqa",
        scratch=("dq_scr",),
        outputs=("dq_ref",),
        out_dtypes=("f32",),
        init_guard="is_first",
        flush_guard="is_last",
        group_inner=None,
    ),
    "_bwd_dkv_kernel": dict(
        wrapper="_ffa_bwd_dkv_pallas",
        scratch=("dk_scr", "dv_scr"),
        outputs=("dk_ref", "dv_ref"),
        out_dtypes=("f32", "f32"),
        init_guard="is_first",
        flush_guard="is_last",
        group_inner=dict(var="gi", count="group"),
    ),
    "_bwd_dkv_kernel_gqa": dict(
        wrapper="_ffa_bwd_dkv_pallas_gqa",
        scratch=("dk_scr", "dv_scr"),
        outputs=("dk_ref", "dv_ref"),
        out_dtypes=("f32", "f32"),
        init_guard="is_first",
        flush_guard="is_last",
        group_inner=None,
    ),
    # Fused one-pass backward kernels: dk/dv follow the standard scratch
    # discipline; dq is a REVISIT-accumulated output — no scratch run
    # exists, the output window itself is zero-initialized under the
    # first-q-visit guard and scale-flushed under the last-q-visit guard
    # (K2's revisit rule). ``revisit`` names that output and its guards.
    "_bwd_fused_kernel": dict(
        wrapper="_ffa_bwd_fused_pallas",
        scratch=("dk_scr", "dv_scr"),
        outputs=("dq_ref", "dk_ref", "dv_ref"),
        out_dtypes=("f32", "f32", "f32"),
        init_guard="is_first",
        flush_guard="is_last",
        group_inner=dict(var="gi", count="group"),
        revisit=dict(out="dq_ref", init_guard="qvf", flush_guard="qvl"),
    ),
    "_bwd_fused_kernel_gqa": dict(
        wrapper="_ffa_bwd_fused_pallas_gqa",
        scratch=("dk_scr", "dv_scr"),
        outputs=("dq_ref", "dk_ref", "dv_ref"),
        out_dtypes=("f32", "f32", "f32"),
        init_guard="is_first",
        flush_guard="is_last",
        group_inner=None,
        revisit=dict(out="dq_ref", init_guard="qvf", flush_guard="qvl"),
    ),
    # Delta preprocessing: stateless map kernel — every grid step writes
    # its own block once, so there is no accumulator discipline to prove.
    "_delta_kernel": dict(
        wrapper="_ffa_delta_pallas",
        scratch=(),
        outputs=("delta_ref",),
        out_dtypes=("f32",),
        init_guard=None,
        flush_guard=None,
        group_inner=None,
    ),
}


# ---------------------------------------------------------------------------
# public entry (custom VJP)
# ---------------------------------------------------------------------------


def _bwd_plan_slices(arrays: tuple):
    """(dq_triple, dkv_triple) of a 6- or 12-array plan tuple.

    6 arrays: dq shares the fwd q-major triple, dkv the k-major triple.
    12 arrays: fwd6 + dq-specific q-major triple + dkv-specific k-major
    triple (built with the bwd block overrides, see FFAParams).
    """
    if len(arrays) == 12:
        return arrays[6:9], arrays[9:12]
    return arrays[0:3], arrays[3:6]


def ffa_fwd_pallas_dispatch(params: FFAParams, work_qt, work_kt, meta,
                            q_t, k_t, v_t):
    """Forward pallas call with the GQA-packing dispatch applied — the ONE
    entry every forward path (custom-vjp core, CP multi-stage, sink) uses
    so the packed kernel is reachable from all of them."""
    maybe_inject("kernel_lowering")
    fwd = (
        _ffa_fwd_pallas_gqa
        if _use_gqa_pack(params, q_t.shape[2], v_t.shape[2],
                         q_t.dtype.itemsize)
        else _ffa_fwd_pallas
    )
    return fwd(params, work_qt, work_kt, meta, q_t, k_t, v_t)


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def _ffa_core(q_t, k_t, v_t, arrays, params: FFAParams):
    # dtype-polymorphic: compute always runs in q's dtype; k/v may arrive
    # fp32 (the high-precision wire-reduce path upcasts receive buffers so
    # their COTANGENTS legally stay fp32 through the group-reduce — ref
    # _reduce_partial_dkv, dist_attn.py:2123) and are cast down here.
    kc, vc = k_t.astype(q_t.dtype), v_t.astype(q_t.dtype)
    return ffa_fwd_pallas_dispatch(params, *arrays[0:3], q_t, kc, vc)


def _ffa_core_fwd(q_t, k_t, v_t, arrays, params: FFAParams):
    out_t, lse_t, ml = ffa_fwd_pallas_dispatch(
        params, *arrays[0:3], q_t,
        k_t.astype(q_t.dtype), v_t.astype(q_t.dtype),
    )
    # residuals keep the PRIMAL-dtype k/v: under HP reduce that is fp32
    # (2x residual HBM — the documented cost of the flag); the cotangents
    # below then legally leave in fp32 for the wire reduce
    res = (q_t, k_t, v_t, out_t, lse_t, arrays)
    return (out_t, lse_t, ml), res


def _ffa_core_bwd(params: FFAParams, res, cts):
    # lse/max_logits are auxiliary outputs: their cotangents are ignored (the
    # CP runtime differentiates the lse-merge manually, matching the
    # reference).
    do_t, _, _ = cts
    q_t, k_t, v_t, out_t, lse_t, arrays = res
    kc, vc = k_t.astype(q_t.dtype), v_t.astype(q_t.dtype)
    dq_arrays, dkv_arrays = _bwd_plan_slices(arrays)
    # delta = rowsum(dO ⊙ O) via the shared Pallas delta kernel — no XLA
    # full-array pass over o/do
    delta_t = ffa_delta_pallas_dispatch(params, out_t, do_t)  # (hq, sqp)
    dq_t, dk_t, dv_t = ffa_bwd_pallas_dispatch(
        params, dq_arrays, dkv_arrays, q_t, kc, vc, do_t, lse_t, delta_t,
    )
    # dk/dv already come back per kv head: the dkv kernel accumulates the
    # GQA group in-kernel (no host reshape-sum). The kernels emit fp32; the
    # casts below are identity when the primal k/v were fp32 (HP reduce).
    return (
        dq_t.astype(q_t.dtype),
        dk_t.astype(k_t.dtype),
        dv_t.astype(v_t.dtype),
        tuple(None for _ in arrays),
    )


_ffa_core.defvjp(_ffa_core_fwd, _ffa_core_bwd)


def _should_interpret() -> bool:
    return (
        env_general.is_interpret_mode_enable()
        or jax.default_backend() == "cpu"
    )


def ffa_attn_with_plan(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    arrays: tuple[jax.Array, ...],
    params: FFAParams,
    return_max_logits: bool = False,
):
    """FFA over an explicit plan — the CP-runtime entry point.

    Args:
        q/k/v: ``[sq,hq,d] / [sk,hk,d] / [sk,hk,dv]``, seq-major.
        arrays: the 6 plan arrays (:func:`plan_arrays`) — or 12 when
            bwd-specific block overrides are active (fwd6 + dq3 + dkv3, see
            FFAParams) — possibly traced (per-rank metadata under
            shard_map), padded to params.num_work / params.num_work_t.
        params: static dims + scalars; sq/sk must fit the tile counts.

    Returns:
        (out ``[sq,hq,dv]``, lse ``[sq,hq]`` fp32), plus per-head max_logits
        ``[hq]`` fp32 when ``return_max_logits``.
    """
    sq, hq, d = q.shape
    sk, hk, dv = v.shape
    sqp = params.num_q_tiles * params.block_q
    skp = params.num_k_tiles * params.block_k
    q_t = jnp.pad(q, ((0, sqp - sq), (0, 0), (0, 0))).transpose(1, 0, 2)
    k_t = jnp.pad(k, ((0, skp - sk), (0, 0), (0, 0))).transpose(1, 0, 2)
    v_t = jnp.pad(v, ((0, skp - sk), (0, 0), (0, 0))).transpose(1, 0, 2)
    out_t, lse_t, ml = _ffa_core(q_t, k_t, v_t, tuple(arrays), params)
    out = out_t.transpose(1, 0, 2)[:sq]
    lse = lse_t.T[:sq]
    if return_max_logits:
        return out, lse, ml
    return out, lse


def resolve_bwd_overrides(
    bq: int, bk: int, sqp: int, skp: int,
    policy_dq: tuple[int, int] | None = None,
    policy_dkv: tuple[int, int] | None = None,
) -> tuple[tuple[int, int] | None, tuple[int, int] | None]:
    """Bwd-tile overrides resolved against a padded geometry.

    Returns ``(dq_blocks, dkv_blocks)``; an entry is None when unset or
    incompatible (the bwd kernels index the same padded q/k/v and lse
    buffers as fwd, so the override must divide the fwd-padded geometry and
    satisfy TPU alignment — incompatible values silently inherit fwd's).
    ``policy_dq``/``policy_dkv`` are the auto-tile policy's per-pass picks
    (:func:`tile_policy.choose_blocks_per_pass`); explicit env settings
    always take precedence over them, component-wise.
    """

    def gate(env_bq: int, env_bk: int,
             policy: tuple[int, int] | None) -> tuple[int, int] | None:
        pol_bq, pol_bk = policy or (0, 0)
        obq = env_bq or pol_bq or bq
        obk = env_bk or pol_bk or bk
        obq, obk = min(obq, sqp), min(obk, skp)
        if (
            (obq, obk) == (bq, bk)
            or sqp % obq or skp % obk
            or obq % 8 or obk % 128
        ):
            return None
        return obq, obk

    return (
        gate(env_kernel.ffa_block_q_dq(), env_kernel.ffa_block_k_dq(),
             policy_dq),
        gate(env_kernel.ffa_block_q_dkv(), env_kernel.ffa_block_k_dkv(),
             policy_dkv),
    )


def assemble_bwd_overrides(
    arrays: tuple, bq: int, bk: int, num_q_tiles: int, num_k_tiles: int,
    build_triple,
    policy_dq: tuple[int, int] | None = None,
    policy_dkv: tuple[int, int] | None = None,
) -> tuple[tuple, dict]:
    """Shared override assembly for single-device and stacked (CP) plans —
    ONE place defines the 12-array layout and FFAParams override fields.

    Args:
        arrays: the 6 fwd plan arrays (possibly rank-stacked).
        build_triple: ``(blocks, kind) -> (triple, work_count)`` — kind
            "dq" returns a q-major triple + its num_work cap; "dkv" a
            k-major triple + its num_work_t cap.

    Returns ``(arrays, FFAParams-field overrides)`` — arrays extended to 12
    when an override is active.
    """
    dq_blocks, dkv_blocks = resolve_bwd_overrides(
        bq, bk, num_q_tiles * bq, num_k_tiles * bk,
        policy_dq=policy_dq, policy_dkv=policy_dkv,
    )
    overrides: dict = {}
    if not (dq_blocks or dkv_blocks):
        return tuple(arrays), overrides
    dq_triple = tuple(arrays[0:3])
    dkv_triple = tuple(arrays[3:6])
    if dq_blocks:
        dq_triple, w_dq = build_triple(dq_blocks, "dq")
        overrides.update(
            block_q_dq=dq_blocks[0], block_k_dq=dq_blocks[1],
            num_work_dq=w_dq,
        )
    if dkv_blocks:
        dkv_triple, wt_dkv = build_triple(dkv_blocks, "dkv")
        overrides.update(
            block_q_dkv=dkv_blocks[0], block_k_dkv=dkv_blocks[1],
            num_work_dkv=wt_dkv,
        )
    return tuple(arrays) + tuple(dq_triple) + tuple(dkv_triple), overrides


def apply_bwd_overrides(
    arrays: tuple, qr, kr, d_lo, d_hi, sq: int, sk: int, bq: int, bk: int,
    num_q_tiles: int, num_k_tiles: int,
    policy_dq: tuple[int, int] | None = None,
    policy_dkv: tuple[int, int] | None = None,
) -> tuple[tuple, dict]:
    """Single-plan wrapper of :func:`assemble_bwd_overrides`."""

    def build_triple(blocks, kind):
        p = get_ffa_plan(qr, kr, d_lo, d_hi, sq, sk, *blocks)
        if kind == "dq":
            return plan_arrays(p)[0:3], p.num_work
        return plan_arrays(p)[3:6], p.num_work_t

    return assemble_bwd_overrides(
        arrays, bq, bk, num_q_tiles, num_k_tiles, build_triple,
        policy_dq=policy_dq, policy_dkv=policy_dkv,
    )


def default_blocks(sq: int, sk: int, block_q=None, block_k=None) -> tuple[int, int]:
    bq = block_q or env_kernel.ffa_block_q()
    bk = block_k or env_kernel.ffa_block_k()
    return min(bq, _round_up(sq, 16)), min(bk, _round_up(sk, 128))


# ---------------------------------------------------------------------------
# mixed-granularity dispatch: coarse-block pass over dense slices + fine-
# block pass over fragmented slices, merged through the LSE-merge math
# (tile_policy.choose_mixed_dispatch decides when the split is profitable)
# ---------------------------------------------------------------------------


def _merge_out_lse(o1, l1, o2, l2):
    """Exact two-way online-softmax merge of (out, lse) pairs, seq-major.

    Same math as functional/utils.py's lse merge (reimplemented locally:
    functional imports this module, so importing it here would cycle). lse
    is natural-log with -inf on uncovered rows. Because the two passes
    partition the slice set, merged out == sum_i exp(lse_i - lse) * out_i
    and merged lse == log(sum_i exp(lse_i)) — the single-pass results up
    to fp roundoff."""
    m = jnp.maximum(l1, l2)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    w1 = jnp.where(jnp.isneginf(l1), 0.0, jnp.exp(l1 - m_safe))
    w2 = jnp.where(jnp.isneginf(l2), 0.0, jnp.exp(l2 - m_safe))
    s = w1 + w2
    covered = s > 0.0
    lse = jnp.where(
        covered, m_safe + jnp.log(jnp.where(covered, s, 1.0)), NEG_INF
    )
    s_safe = jnp.where(covered, s, 1.0)[..., None]
    out = (
        o1.astype(jnp.float32) * w1[..., None]
        + o2.astype(jnp.float32) * w2[..., None]
    ) / s_safe
    return out.astype(o1.dtype), lse


def _mixed_branch_fwd(q, k, v, arrays, params: FFAParams):
    """One forward pass of the mixed dispatch: pad/transpose to the branch's
    padded geometry, run the fwd kernel, slice back to seq-major."""
    sq = q.shape[0]
    sk = k.shape[0]
    sqp = params.num_q_tiles * params.block_q
    skp = params.num_k_tiles * params.block_k
    q_t = jnp.pad(q, ((0, sqp - sq), (0, 0), (0, 0))).transpose(1, 0, 2)
    k_t = jnp.pad(k, ((0, skp - sk), (0, 0), (0, 0))).transpose(1, 0, 2)
    v_t = jnp.pad(v, ((0, skp - sk), (0, 0), (0, 0))).transpose(1, 0, 2)
    out_t, lse_t, _ = ffa_fwd_pallas_dispatch(
        params, *arrays[0:3], q_t,
        k_t.astype(q_t.dtype), v_t.astype(q_t.dtype),
    )
    return out_t.transpose(1, 0, 2)[:sq], lse_t.T[:sq]


@partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _ffa_mixed(q, k, v, arrays_a, arrays_b, params_a: FFAParams,
               params_b: FFAParams):
    # A dedicated custom_vjp at the merged level is mandatory: the branch
    # cores ignore their lse cotangents (see _ffa_core_bwd), so naive
    # autodiff THROUGH the lse merge would drop the coupling between the
    # branches' softmax normalizers and return wrong branch gradients.
    o1, l1 = _mixed_branch_fwd(q, k, v, arrays_a, params_a)
    o2, l2 = _mixed_branch_fwd(q, k, v, arrays_b, params_b)
    return _merge_out_lse(o1, l1, o2, l2)


def _ffa_mixed_fwd(q, k, v, arrays_a, arrays_b, params_a, params_b):
    out, lse = _ffa_mixed(q, k, v, arrays_a, arrays_b, params_a, params_b)
    return (out, lse), (q, k, v, out, lse, arrays_a, arrays_b)


def _ffa_mixed_bwd(params_a: FFAParams, params_b: FFAParams, res, cts):
    # Each branch kernel receives the MERGED lse/delta: p = exp(s - lse)
    # then is the GLOBAL softmax probability of every entry the branch's
    # slices cover, and since the branches partition the mask the summed
    # branch gradients equal the single-pass gradients exactly. The lse
    # cotangent is ignored (same contract as _ffa_core_bwd).
    do, _ = cts
    q, k, v, out, lse, arrays_a, arrays_b = res
    sq, sk = q.shape[0], k.shape[0]
    do = do.astype(q.dtype)
    # delta via the shared Pallas delta kernel, computed ONCE on branch
    # a's padded geometry and sliced back to seq-major — both branches
    # consume the same merged delta, and padded do rows are zero so their
    # delta is exactly 0 (matching the old zero padding per branch)
    sqp_a = params_a.num_q_tiles * params_a.block_q
    out_h = jnp.pad(out, ((0, sqp_a - sq), (0, 0), (0, 0))).transpose(1, 0, 2)
    do_h = jnp.pad(do, ((0, sqp_a - sq), (0, 0), (0, 0))).transpose(1, 0, 2)
    delta = ffa_delta_pallas_dispatch(params_a, out_h, do_h).T[:sq]  # (sq, hq)

    def branch(arrays, params: FFAParams):
        sqp = params.num_q_tiles * params.block_q
        skp = params.num_k_tiles * params.block_k
        q_t = jnp.pad(q, ((0, sqp - sq), (0, 0), (0, 0))).transpose(1, 0, 2)
        k_t = jnp.pad(k, ((0, skp - sk), (0, 0), (0, 0))).transpose(1, 0, 2)
        v_t = jnp.pad(v, ((0, skp - sk), (0, 0), (0, 0))).transpose(1, 0, 2)
        kc, vc = k_t.astype(q_t.dtype), v_t.astype(q_t.dtype)
        do_t = jnp.pad(do, ((0, sqp - sq), (0, 0), (0, 0))).transpose(1, 0, 2)
        # padded q rows are uncovered: pad the merged lse with -inf (the
        # dispatch clamps it to MASK_VALUE, making p exactly 0 there) —
        # padding with 0 would fabricate probabilities exp(s - 0)
        lse_t = jnp.pad(
            lse, ((0, sqp - sq), (0, 0)), constant_values=NEG_INF
        ).T
        delta_t = jnp.pad(delta, ((0, sqp - sq), (0, 0))).T
        dq_arrays, dkv_arrays = _bwd_plan_slices(arrays)
        dq_t, dk_t, dv_t = ffa_bwd_pallas_dispatch(
            params, dq_arrays, dkv_arrays, q_t, kc, vc, do_t, lse_t,
            delta_t,
        )
        return (
            dq_t.transpose(1, 0, 2)[:sq],
            dk_t.transpose(1, 0, 2)[:sk],
            dv_t.transpose(1, 0, 2)[:sk],
        )

    dq1, dk1, dv1 = branch(arrays_a, params_a)
    dq2, dk2, dv2 = branch(arrays_b, params_b)
    return (
        (dq1 + dq2).astype(q.dtype),
        (dk1 + dk2).astype(k.dtype),
        (dv1 + dv2).astype(v.dtype),
        tuple(None for _ in arrays_a),
        tuple(None for _ in arrays_b),
    )


_ffa_mixed.defvjp(_ffa_mixed_fwd, _ffa_mixed_bwd)


def _mixed_params(
    plan: FFAPlan, softmax_scale: float, softcap: float, group: int
) -> FFAParams:
    """Branch params for the mixed dispatch: plain 6-array plans, no bwd
    overrides, no max-logits (the dispatch gate excludes that path)."""
    return FFAParams(
        num_work=plan.num_work,
        num_work_t=plan.num_work_t,
        num_q_tiles=plan.num_q_tiles,
        num_k_tiles=plan.num_k_tiles,
        block_q=plan.block_q,
        block_k=plan.block_k,
        softmax_scale=softmax_scale,
        softcap=softcap,
        group=group,
        interpret=_should_interpret(),
    )


def ffa_attn(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_ranges,
    k_ranges,
    attn_type_map=None,
    softmax_scale: float | None = None,
    softcap: float = 0.0,
    block_q: int | None = None,
    block_k: int | None = None,
    d_lo=None,
    d_hi=None,
    return_max_logits: bool = False,
):
    """Pallas FFA over slice metadata. Same contract as sdpa_attn.

    Slices may be given as mask types (``attn_type_map``) or directly as
    diagonal bands (``d_lo``/``d_hi``). The metadata must be *concrete*
    (host) values — it parameterizes the kernel grid. Inside jit-traced code,
    close over it (the runtime manager caches traced plans per mask,
    mirroring the reference's runtime LRU), or use :func:`ffa_attn_with_plan`.
    """
    try:
        qr = np.asarray(q_ranges, dtype=np.int32)
        kr = np.asarray(k_ranges, dtype=np.int32)
        if d_lo is None or d_hi is None:
            tm = (
                np.zeros(len(qr), dtype=np.int32)
                if attn_type_map is None
                else np.asarray(attn_type_map, dtype=np.int32)
            )
            d_lo, d_hi = types_to_bands(qr, kr, tm)
        else:
            d_lo = np.asarray(d_lo, dtype=np.int32)
            d_hi = np.asarray(d_hi, dtype=np.int32)
    except Exception as e:  # pragma: no cover
        raise ValueError(
            "ffa_attn requires concrete (host) slice metadata; inside jit, "
            "close over the metadata or use ffa_attn_with_plan"
        ) from e

    sq, hq, d = q.shape
    sk, hk, dv = v.shape
    if softmax_scale is None:
        softmax_scale = float(d) ** -0.5
    if (
        not return_max_logits
        and block_q is None
        and block_k is None
        and not _registry_mod().tiles_pinned()
    ):
        # mixed-granularity dispatch: when the cost model (or an explicit
        # MAGI_ATTENTION_FFA_MIXED_BLOCKS=1) says a coarse/fine split wins,
        # run two plans and merge — only reachable when blocks are not
        # pinned (explicit settings always win) and max-logits is off (the
        # merge does not combine per-head maxima)
        from .tile_policy import choose_mixed_dispatch

        mix = choose_mixed_dispatch(
            qr, kr, d_lo, d_hi, sq, sk, d, dv,
            itemsize=q.dtype.itemsize,
            coarse_blocks=default_blocks(sq, sk),
        )
        if mix is not None:
            di, fi = mix.dense_idx, mix.frag_idx
            plan_a = get_ffa_plan(
                qr[di], kr[di], d_lo[di], d_hi[di], sq, sk,
                *mix.coarse_blocks,
            )
            plan_b = get_ffa_plan(
                qr[fi], kr[fi], d_lo[fi], d_hi[fi], sq, sk,
                *mix.fine_blocks,
            )
            return _ffa_mixed(
                q, k, v, plan_arrays(plan_a), plan_arrays(plan_b),
                _mixed_params(
                    plan_a, float(softmax_scale), float(softcap), hq // hk
                ),
                _mixed_params(
                    plan_b, float(softmax_scale), float(softcap), hq // hk
                ),
            )
    policy_dq = policy_dkv = None
    if block_q is None and block_k is None and not _registry_mod().tiles_pinned():
        from .tile_policy import auto_tile_enabled, choose_blocks_per_pass

        if auto_tile_enabled():
            # plan-geometry-driven, per-PASS tile choice (ref tile tables
            # analogue): fwd/dq score the q-major plan, dkv the k-major one,
            # and thin bands get their own block_k candidates; explicit
            # env/arg settings always take precedence
            (block_q, block_k), policy_dq, policy_dkv = (
                choose_blocks_per_pass(
                    qr, kr, d_lo, d_hi, sq, sk, d, dv,
                    itemsize=q.dtype.itemsize,
                )
            )
    bq, bk = default_blocks(sq, sk, block_q, block_k)

    plan = get_ffa_plan(qr, kr, d_lo, d_hi, sq, sk, bq, bk)
    arrays = plan_arrays(plan)
    arrays, overrides = apply_bwd_overrides(
        arrays, qr, kr, d_lo, d_hi, sq, sk, bq, bk,
        plan.num_q_tiles, plan.num_k_tiles,
        policy_dq=policy_dq, policy_dkv=policy_dkv,
    )

    params = FFAParams(
        num_work=plan.num_work,
        num_work_t=plan.num_work_t,
        num_q_tiles=plan.num_q_tiles,
        num_k_tiles=plan.num_k_tiles,
        block_q=bq,
        block_k=bk,
        softmax_scale=float(softmax_scale),
        softcap=float(softcap),
        group=hq // hk,
        interpret=_should_interpret(),
        emit_max_logits=return_max_logits,
        **overrides,
    )
    return ffa_attn_with_plan(
        q, k, v, arrays, params, return_max_logits=return_max_logits
    )
