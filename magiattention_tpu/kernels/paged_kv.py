"""Paged KV cache + paged attention (ref: magi_attention/kernel/cutedsl/paged_kv.py).

Inference-oriented: K/V live in fixed-size pages indexed by a per-sequence
page table, so cache memory is allocated in page granularity instead of
max-seqlen rectangles. TPU-native design decisions (vs the reference's
CuTe-DSL gather-in-kernel):

- pages are gathered with ONE ``jnp.take`` over the page axis (a single
  large HBM gather XLA lays out well) into the contiguous ``[sk, hk, d]``
  layout the FFA kernel already consumes — no separate paged kernel to
  maintain, and every mask type / GQA / softcap feature works unchanged;
- the cache is a pytree of arrays updated functionally (``.at[].set``), so
  it jits and shards like any other state (e.g. pages sharded over a mesh
  axis for long-context serving).

Static-shape contract: ``max_pages_per_seq`` bounds the gather; rows beyond
``length`` are masked via the slice metadata (an INVCAUSAL-free band with
``ke = length``), which the plan encodes exactly.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_dataclass
@dataclass
class PagedKVCache:
    """Paged KV storage for one attention layer.

    Attributes:
        k_pages / v_pages: ``(num_pages, page_size, hk, d)``.
        page_table: ``(max_seqs, max_pages_per_seq)`` int32 page ids
            (-1 = unallocated).
        lengths: ``(max_seqs,)`` int32 tokens currently stored per sequence.
    """

    k_pages: jax.Array
    v_pages: jax.Array
    page_table: jax.Array
    lengths: jax.Array
    # quantized-cache extension: per-(page, kv head) symmetric scales for
    # int8 pages (None on float caches; value = code * scale)
    k_scales: jax.Array | None = None
    v_scales: jax.Array | None = None

    @property
    def page_size(self) -> int:
        return self.k_pages.shape[1]

    @property
    def quantized(self) -> bool:
        return self.k_scales is not None

    @classmethod
    def create(
        cls,
        num_pages: int,
        page_size: int,
        n_kv_heads: int,
        head_dim: int,
        max_seqs: int,
        max_pages_per_seq: int,
        dtype=jnp.bfloat16,
    ) -> "PagedKVCache":
        quantized = jnp.dtype(dtype) == jnp.int8
        scales = (
            jnp.zeros((num_pages, n_kv_heads), jnp.float32)
            if quantized
            else None
        )
        return cls(
            k_pages=jnp.zeros(
                (num_pages, page_size, n_kv_heads, head_dim), dtype
            ),
            v_pages=jnp.zeros(
                (num_pages, page_size, n_kv_heads, head_dim), dtype
            ),
            page_table=jnp.full(
                (max_seqs, max_pages_per_seq), -1, jnp.int32
            ),
            lengths=jnp.zeros((max_seqs,), jnp.int32),
            k_scales=scales,
            v_scales=None if scales is None else jnp.zeros_like(scales),
        )


def assign_pages(
    cache: PagedKVCache, seq_id: int, page_ids: np.ndarray
) -> PagedKVCache:
    """Host-side page allocation: install ``page_ids`` as seq's table."""
    table = cache.page_table.at[seq_id, : len(page_ids)].set(
        jnp.asarray(page_ids, jnp.int32)
    )
    return PagedKVCache(
        cache.k_pages, cache.v_pages, table, cache.lengths,
        cache.k_scales, cache.v_scales,
    )


def rollback_kv(cache: PagedKVCache, seq_id, new_length) -> PagedKVCache:
    """Discard a sequence's rows past ``new_length`` (speculative-verify
    rollback). Pure length bookkeeping: rejected rows stay as garbage in
    their pages and are dead under the length mask; the next append
    overwrites them in place (quantized pages keep their scale — the
    rescale-on-append algebra already handles overwritten rows)."""
    lengths = cache.lengths.at[seq_id].set(
        jnp.asarray(new_length, jnp.int32)
    )
    return PagedKVCache(
        cache.k_pages, cache.v_pages, cache.page_table, lengths,
        cache.k_scales, cache.v_scales,
    )


def _quantize_append(pages, scales, page_idx, row, x_new):
    """Append f32 rows into int8 pages with monotone per-(page, head)
    symmetric scales.

    Row i may raise its page's scale (new_scale = max(old, |x|_max / 127));
    existing codes of that page are rescaled by old/new (codes only ever
    shrink, so no clipping error) before the new row is quantized. Scale
    growth is monotone within a page's lifetime, which makes the stored
    values a pure function of the append history — the property the
    bitwise engine-vs-oracle comparisons rely on (reset on release).
    """
    t = x_new.shape[0]
    for i in range(t):
        p = page_idx[i]
        xi = x_new[i].astype(jnp.float32)  # (hk, d)
        cand = jnp.max(jnp.abs(xi), axis=-1) / 127.0  # (hk,)
        old = scales[p]
        new = jnp.maximum(old, cand)
        safe = jnp.where(new > 0.0, new, 1.0)
        ratio = old / safe  # 0 where the page was fresh
        page = jnp.round(pages[p].astype(jnp.float32) * ratio[None, :, None])
        page = jnp.clip(page, -127, 127)
        row_q = jnp.clip(jnp.round(xi / safe[:, None]), -127, 127)
        page = page.at[row[i]].set(row_q)
        pages = pages.at[p].set(page.astype(jnp.int8))
        scales = scales.at[p].set(new)
    return pages, scales


def append_kv(
    cache: PagedKVCache, seq_id, k_new: jax.Array, v_new: jax.Array
) -> PagedKVCache:
    """Append ``(t, hk, d)`` new rows to a sequence (pages pre-assigned).

    ``t`` is static (typically 1 for decode, chunk for prefill); positions
    are ``lengths[seq_id] .. +t``. Functional update — jit-safe. Quantized
    caches quantize rows on the way in (per-page symmetric int8 scales).
    """
    t = k_new.shape[0]
    start = cache.lengths[seq_id]
    ps = cache.page_size
    pos = start + jnp.arange(t, dtype=jnp.int32)
    page_idx = cache.page_table[seq_id, pos // ps]  # (t,)
    row = pos % ps

    if cache.quantized:
        k_pages, k_scales = _quantize_append(
            cache.k_pages, cache.k_scales, page_idx, row, k_new
        )
        v_pages, v_scales = _quantize_append(
            cache.v_pages, cache.v_scales, page_idx, row, v_new
        )
    else:
        k_pages = cache.k_pages.at[page_idx, row].set(k_new)
        v_pages = cache.v_pages.at[page_idx, row].set(v_new)
        k_scales, v_scales = cache.k_scales, cache.v_scales
    lengths = cache.lengths.at[seq_id].set(start + t)
    return PagedKVCache(
        k_pages, v_pages, cache.page_table, lengths, k_scales, v_scales
    )


def gather_kv(
    cache: PagedKVCache, seq_id, max_pages: int | None = None
) -> tuple[jax.Array, jax.Array]:
    """Gather a sequence's pages into contiguous ``(cap, hk, d)`` K/V
    (cap = max_pages * page_size; rows beyond ``lengths[seq_id]`` are
    whatever the unwritten pages hold and must be masked by the caller)."""
    table = cache.page_table[seq_id]
    if max_pages is not None:
        table = table[:max_pages]
    safe = jnp.maximum(table, 0)
    k = jnp.take(cache.k_pages, safe, axis=0)  # (P, ps, hk, d)
    v = jnp.take(cache.v_pages, safe, axis=0)
    if cache.quantized:
        # dequant on gather so every downstream consumer (FFA prefill,
        # gather/dense decode rungs, the replay oracle) sees f32 values
        ks = jnp.take(cache.k_scales, safe, axis=0)  # (P, hk)
        vs = jnp.take(cache.v_scales, safe, axis=0)
        k = k.astype(jnp.float32) * ks[:, None, :, None]
        v = v.astype(jnp.float32) * vs[:, None, :, None]
    ps = cache.page_size
    p = k.shape[0]
    return (
        k.reshape(p * ps, *k.shape[2:]),
        v.reshape(p * ps, *v.shape[2:]),
    )


def paged_attn(
    q: jax.Array,
    cache: PagedKVCache,
    seq_id: int,
    q_start: int,
    max_pages: int,
    softmax_scale: float | None = None,
    softcap: float = 0.0,
    causal: bool = True,
):
    """Attention of ``q`` (``(t, hq, d)`` at positions ``q_start..+t``)
    against a sequence's paged KV.

    The valid-length mask is expressed as FFA slice metadata (band with
    ``ke = kv_len``), so the Pallas kernel computes only real rows. The
    kv length must be host-static per call (the plan parameterizes the
    kernel grid) — standard for serving where lengths bucket into steps.

    Returns (out ``(t, hq, dv)``, lse ``(t, hq)``).
    """
    from .ffa import ffa_attn

    t = q.shape[0]
    kv_len = int(q_start) + t  # tokens stored so far incl. this chunk
    k, v = gather_kv(cache, seq_id, max_pages)
    # one slice: q rows [0,t) at global positions [q_start, q_start+t)
    # attending k rows [0, kv_len) with an optional causal band. In local
    # coords the causal diagonal sits at offset q_start.
    if causal:
        d_lo, d_hi = -(1 << 30), int(q_start)
    else:
        d_lo, d_hi = -(1 << 30), 1 << 30
    return ffa_attn(
        q, k, v,
        q_ranges=[[0, t]],
        k_ranges=[[0, kv_len]],
        softmax_scale=softmax_scale,
        softcap=softcap,
        d_lo=np.array([d_lo], np.int32),
        d_hi=np.array([d_hi], np.int32),
    )
