"""Paged KV cache + paged attention (ref: magi_attention/kernel/cutedsl/paged_kv.py).

Inference-oriented: K/V live in fixed-size pages indexed by a per-sequence
page table, so cache memory is allocated in page granularity instead of
max-seqlen rectangles. TPU-native design decisions (vs the reference's
CuTe-DSL gather-in-kernel):

- pages are gathered with ONE ``jnp.take`` over the page axis (a single
  large HBM gather XLA lays out well) into the contiguous ``[sk, hk, d]``
  layout the FFA kernel already consumes — no separate paged kernel to
  maintain, and every mask type / GQA / softcap feature works unchanged;
- the cache is a pytree of arrays updated functionally (``.at[].set``), so
  it jits and shards like any other state (e.g. pages sharded over a mesh
  axis for long-context serving).

Static-shape contract: ``max_pages_per_seq`` bounds the gather; rows beyond
``length`` are masked via the slice metadata (an INVCAUSAL-free band with
``ke = length``), which the plan encodes exactly.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_dataclass
@dataclass
class PagedKVCache:
    """Paged KV storage for one attention layer.

    Attributes:
        k_pages / v_pages: ``(num_pages, page_size, hk, d)``.
        page_table: ``(max_seqs, max_pages_per_seq)`` int32 page ids
            (-1 = unallocated).
        lengths: ``(max_seqs,)`` int32 tokens currently stored per sequence.
    """

    k_pages: jax.Array
    v_pages: jax.Array
    page_table: jax.Array
    lengths: jax.Array

    @property
    def page_size(self) -> int:
        return self.k_pages.shape[1]

    @classmethod
    def create(
        cls,
        num_pages: int,
        page_size: int,
        n_kv_heads: int,
        head_dim: int,
        max_seqs: int,
        max_pages_per_seq: int,
        dtype=jnp.bfloat16,
    ) -> "PagedKVCache":
        return cls(
            k_pages=jnp.zeros(
                (num_pages, page_size, n_kv_heads, head_dim), dtype
            ),
            v_pages=jnp.zeros(
                (num_pages, page_size, n_kv_heads, head_dim), dtype
            ),
            page_table=jnp.full(
                (max_seqs, max_pages_per_seq), -1, jnp.int32
            ),
            lengths=jnp.zeros((max_seqs,), jnp.int32),
        )


def assign_pages(
    cache: PagedKVCache, seq_id: int, page_ids: np.ndarray
) -> PagedKVCache:
    """Host-side page allocation: install ``page_ids`` as seq's table."""
    table = cache.page_table.at[seq_id, : len(page_ids)].set(
        jnp.asarray(page_ids, jnp.int32)
    )
    return PagedKVCache(cache.k_pages, cache.v_pages, table, cache.lengths)


def append_kv(
    cache: PagedKVCache, seq_id, k_new: jax.Array, v_new: jax.Array
) -> PagedKVCache:
    """Append ``(t, hk, d)`` new rows to a sequence (pages pre-assigned).

    ``t`` is static (typically 1 for decode, chunk for prefill); positions
    are ``lengths[seq_id] .. +t``. Functional update — jit-safe.
    """
    t = k_new.shape[0]
    start = cache.lengths[seq_id]
    ps = cache.page_size
    pos = start + jnp.arange(t, dtype=jnp.int32)
    page_idx = cache.page_table[seq_id, pos // ps]  # (t,)
    row = pos % ps

    k_pages = cache.k_pages.at[page_idx, row].set(k_new)
    v_pages = cache.v_pages.at[page_idx, row].set(v_new)
    lengths = cache.lengths.at[seq_id].set(start + t)
    return PagedKVCache(k_pages, v_pages, cache.page_table, lengths)


def gather_kv(
    cache: PagedKVCache, seq_id, max_pages: int | None = None
) -> tuple[jax.Array, jax.Array]:
    """Gather a sequence's pages into contiguous ``(cap, hk, d)`` K/V
    (cap = max_pages * page_size; rows beyond ``lengths[seq_id]`` are
    whatever the unwritten pages hold and must be masked by the caller)."""
    table = cache.page_table[seq_id]
    if max_pages is not None:
        table = table[:max_pages]
    safe = jnp.maximum(table, 0)
    k = jnp.take(cache.k_pages, safe, axis=0)  # (P, ps, hk, d)
    v = jnp.take(cache.v_pages, safe, axis=0)
    ps = cache.page_size
    p = k.shape[0]
    return (
        k.reshape(p * ps, *k.shape[2:]),
        v.reshape(p * ps, *v.shape[2:]),
    )


def paged_attn(
    q: jax.Array,
    cache: PagedKVCache,
    seq_id: int,
    q_start: int,
    max_pages: int,
    softmax_scale: float | None = None,
    softcap: float = 0.0,
    causal: bool = True,
):
    """Attention of ``q`` (``(t, hq, d)`` at positions ``q_start..+t``)
    against a sequence's paged KV.

    The valid-length mask is expressed as FFA slice metadata (band with
    ``ke = kv_len``), so the Pallas kernel computes only real rows. The
    kv length must be host-static per call (the plan parameterizes the
    kernel grid) — standard for serving where lengths bucket into steps.

    Returns (out ``(t, hq, dv)``, lse ``(t, hq)``).
    """
    from .ffa import ffa_attn

    t = q.shape[0]
    kv_len = int(q_start) + t  # tokens stored so far incl. this chunk
    k, v = gather_kv(cache, seq_id, max_pages)
    # one slice: q rows [0,t) at global positions [q_start, q_start+t)
    # attending k rows [0, kv_len) with an optional causal band. In local
    # coords the causal diagonal sits at offset q_start.
    if causal:
        d_lo, d_hi = -(1 << 30), int(q_start)
    else:
        d_lo, d_hi = -(1 << 30), 1 << 30
    return ffa_attn(
        q, k, v,
        q_ranges=[[0, t]],
        k_ranges=[[0, kv_len]],
        softmax_scale=softmax_scale,
        softcap=softcap,
        d_lo=np.array([d_lo], np.int32),
        d_hi=np.array([d_hi], np.int32),
    )
