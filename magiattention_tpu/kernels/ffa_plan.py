"""Host-side tiling plan for the Pallas FFA kernel.

The TPU replacement for the reference's range-aware persistent tile schedulers
(csrc/flexible_flash_attention/fwd_tile_scheduler.hpp, bwd_tile_scheduler.hpp):
instead of a device-side scheduler walking (q_range, k_range, mask_type) lists,
we precompute — on the host, from concrete slice metadata — the exact list of
(q_tile, k_tile, slice) work items the kernel grid will visit. Fully-masked
tiles are never visited; fully-unmasked tiles can skip mask evaluation. This is
the idiomatic TPU trade: static grids + scalar prefetch instead of dynamic
scheduling + atomics.

Slices are encoded as diagonal bands (q_range, k_range, d_lo <= j-i <= d_hi) —
see kernels/mask_utils.types_to_bands.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import numpy as np

from .mask_utils import BAND_INF
from .. import telemetry
from ..utils.profiling import instrument_host

# meta columns per work item
QS, QE, KS, KE, DLO, DHI, IS_FIRST, IS_LAST, IS_FULL = range(9)
META_DIM = 9


@dataclass(frozen=True, eq=False)
class FFAPlan:
    """A flat, q-tile-major work list plus its k-tile-major transpose."""

    # q-major (forward + dq): runs of items grouped by q tile
    work_qt: np.ndarray  # (W,) int32 — q tile index per item
    work_kt: np.ndarray  # (W,) int32 — k tile index per item
    meta: np.ndarray  # (W, META_DIM) int32
    # k-major (dkv): runs of items grouped by k tile
    work_qt_t: np.ndarray
    work_kt_t: np.ndarray
    meta_t: np.ndarray
    num_q_tiles: int
    num_k_tiles: int
    block_q: int
    block_k: int

    @property
    def num_work(self) -> int:
        return len(self.work_qt)

    @property
    def num_work_t(self) -> int:
        return len(self.work_qt_t)


def _record_plan_telemetry(
    plan: FFAPlan,
    qr: np.ndarray,
    kr: np.ndarray,
    d_lo: np.ndarray,
    d_hi: np.ndarray,
) -> FFAPlan:
    """Gated per-build record: the padded grid work the kernel will execute
    vs the true band area it needed — the estimated-vs-executed FLOP ratio
    at plan time (multiply elems by 4 * head_dim * num_heads_q for fwd
    FLOPs; the step record does, once dims are known)."""
    if telemetry.enabled():
        padded = plan.num_work * plan.block_q * plan.block_k
        band = telemetry.band_area(qr, kr, d_lo, d_hi)
        telemetry.record_event(
            "ffa_plan",
            num_slices=len(qr),
            block_q=plan.block_q,
            block_k=plan.block_k,
            num_q_tiles=plan.num_q_tiles,
            num_k_tiles=plan.num_k_tiles,
            num_work=plan.num_work,
            num_work_t=plan.num_work_t,
            padded_elems=padded,
            band_elems=band,
            padding_ratio=padded / band if band else 1.0,
        )
    return plan


def _band_tile_interaction(
    i0: int, i1: int, j0: int, j1: int, lo: int, hi: int
) -> tuple[bool, bool]:
    """(nonempty, fully_unmasked) of band [lo, hi] on rect [i0,i1) x [j0,j1)."""
    if i0 >= i1 or j0 >= j1:
        return False, False
    d_min = j0 - (i1 - 1)
    d_max = (j1 - 1) - i0
    nonempty = d_min <= hi and d_max >= lo
    full = nonempty and d_max <= hi and d_min >= lo
    return nonempty, full


@instrument_host
def build_ffa_plan(
    q_ranges: np.ndarray,
    k_ranges: np.ndarray,
    d_lo: np.ndarray,
    d_hi: np.ndarray,
    seqlen_q: int,
    seqlen_k: int,
    block_q: int,
    block_k: int,
) -> FFAPlan:
    """Build the work-item lists for the given band-slice metadata.

    When ``MAGI_ATTENTION_RANGE_MERGE`` is on (default), band-compatible
    adjacent slices are merged first (mask_utils.merge_band_slices — the ref
    merges at its kernel entry, functional/flex_flash_attn.py:87). Exact:
    bands are global-coordinate, so the merged cover is identical; fragmented
    masks (block-sparse, video) collapse into fewer work items. This is the
    one choke point every planning path flows through (single-device
    ffa_attn, CP _stack_plans, dynamic runtime), so all of them benefit.
    """
    from ..env.general import is_range_merge_enable

    if is_range_merge_enable():
        from .mask_utils import merge_band_slices

        q_ranges, k_ranges, d_lo, d_hi = merge_band_slices(
            q_ranges, k_ranges, d_lo, d_hi
        )
    num_q_tiles = max(1, -(-seqlen_q // block_q))
    num_k_tiles = max(1, -(-seqlen_k // block_k))

    from ..env.kernel import ffa_native_plan

    mode = ffa_native_plan()
    if mode != "0":
        try:
            from ..csrc_backend.ops import ffa_plan_native

            arrays = ffa_plan_native(
                q_ranges, k_ranges, d_lo, d_hi,
                num_q_tiles, num_k_tiles, block_q, block_k, BAND_INF,
            )
            return _record_plan_telemetry(
                FFAPlan(
                    work_qt=arrays[0], work_kt=arrays[1], meta=arrays[2],
                    work_qt_t=arrays[3], work_kt_t=arrays[4],
                    meta_t=arrays[5],
                    num_q_tiles=num_q_tiles, num_k_tiles=num_k_tiles,
                    block_q=block_q, block_k=block_k,
                ),
                q_ranges, k_ranges, d_lo, d_hi,
            )
        except ImportError:
            if mode == "1":
                raise
            # auto: native lib unavailable — pure-Python builder below

    n = len(q_ranges)
    q_items: list[list[tuple[int, ...]]] = [[] for _ in range(num_q_tiles)]
    k_items: list[list[tuple[int, ...]]] = [[] for _ in range(num_k_tiles)]

    for s in range(n):
        qs, qe = int(q_ranges[s, 0]), int(q_ranges[s, 1])
        ks, ke = int(k_ranges[s, 0]), int(k_ranges[s, 1])
        lo, hi = int(d_lo[s]), int(d_hi[s])
        if qs >= qe or ks >= ke or lo > hi:
            continue
        # same bounds validation as the native builder (csrc/magi_host.cpp:251
        # returns -1 -> ops.py raises): without it, negative starts would
        # silently wrap via Python negative indexing and corrupt the plan
        if (
            qs < 0
            or ks < 0
            or -(-qe // block_q) > num_q_tiles
            or -(-ke // block_k) > num_k_tiles
        ):
            raise ValueError(
                f"ffa plan slice {s} out of bounds: q[{qs},{qe}) "
                f"k[{ks},{ke}) vs grid {num_q_tiles}x{num_k_tiles} tiles"
            )
        qt_lo, qt_hi = qs // block_q, -(-qe // block_q)
        kt_lo, kt_hi = ks // block_k, -(-ke // block_k)
        for qt in range(qt_lo, qt_hi):
            i0, i1 = max(qs, qt * block_q), min(qe, (qt + 1) * block_q)
            for kt in range(kt_lo, kt_hi):
                j0, j1 = max(ks, kt * block_k), min(ke, (kt + 1) * block_k)
                nonempty, full = _band_tile_interaction(i0, i1, j0, j1, lo, hi)
                if not nonempty:
                    continue
                tile_full = (
                    full
                    and i0 == qt * block_q
                    and i1 == (qt + 1) * block_q
                    and j0 == kt * block_k
                    and j1 == (kt + 1) * block_k
                )
                item = (qt, kt, qs, qe, ks, ke, lo, hi, int(tile_full))
                q_items[qt].append(item)
                k_items[kt].append(item)

    def flatten(buckets, major_is_q: bool):
        work_a, work_b, metas = [], [], []
        for tile_idx, items in enumerate(buckets):
            if not items:
                # dummy item: empty k range -> all-masked -> finalize writes
                # zeros/-inf (fwd) or zero grads (bwd) for this tile
                items = [
                    (
                        tile_idx if major_is_q else 0,
                        0 if major_is_q else tile_idx,
                        0, 0, 0, 0, -BAND_INF, BAND_INF, 0,
                    )
                ]
            for pos, (qt, kt, qs, qe, ks, ke, lo, hi, full) in enumerate(items):
                m = np.zeros(META_DIM, dtype=np.int32)
                m[QS], m[QE], m[KS], m[KE] = qs, qe, ks, ke
                m[DLO], m[DHI] = lo, hi
                m[IS_FIRST] = 1 if pos == 0 else 0
                m[IS_LAST] = 1 if pos == len(items) - 1 else 0
                m[IS_FULL] = full
                work_a.append(qt)
                work_b.append(kt)
                metas.append(m)
        return (
            np.asarray(work_a, dtype=np.int32),
            np.asarray(work_b, dtype=np.int32),
            np.stack(metas).astype(np.int32),
        )

    work_qt, work_kt, meta = flatten(q_items, major_is_q=True)
    work_qt_t, work_kt_t, meta_t = flatten(k_items, major_is_q=False)

    return _record_plan_telemetry(
        FFAPlan(
            work_qt=work_qt,
            work_kt=work_kt,
            meta=meta,
            work_qt_t=work_qt_t,
            work_kt_t=work_kt_t,
            meta_t=meta_t,
            num_q_tiles=num_q_tiles,
            num_k_tiles=num_k_tiles,
            block_q=block_q,
            block_k=block_k,
        ),
        q_ranges, k_ranges, d_lo, d_hi,
    )


def pad_plan(plan: FFAPlan, num_work: int, num_work_t: int) -> FFAPlan:
    """Pad work lists with no-op items (same tile as the last real item,
    is_first=is_last=0, empty ranges) so plans from different CP ranks share
    one static shape and can be fed to the kernel as traced arrays."""

    def pad(work_a, work_b, meta, target, tile_col_is_q: bool):
        w = len(work_a)
        if w > target:
            raise ValueError(f"plan has {w} items > target {target}")
        if w == target:
            return work_a, work_b, meta
        pad_n = target - w
        pa = np.full(pad_n, work_a[-1], dtype=np.int32)
        pb = np.full(pad_n, work_b[-1], dtype=np.int32)
        pm = np.zeros((pad_n, META_DIM), dtype=np.int32)
        pm[:, DLO], pm[:, DHI] = -BAND_INF, BAND_INF
        return (
            np.concatenate([work_a, pa]),
            np.concatenate([work_b, pb]),
            np.concatenate([meta, pm]),
        )

    wq, wk, m = pad(plan.work_qt, plan.work_kt, plan.meta, num_work, True)
    wqt, wkt, mt = pad(
        plan.work_qt_t, plan.work_kt_t, plan.meta_t, num_work_t, False
    )
    return FFAPlan(
        work_qt=wq, work_kt=wk, meta=m,
        work_qt_t=wqt, work_kt_t=wkt, meta_t=mt,
        num_q_tiles=plan.num_q_tiles, num_k_tiles=plan.num_k_tiles,
        block_q=plan.block_q, block_k=plan.block_k,
    )


@lru_cache(maxsize=256)
def _cached_plan(
    qr_bytes: bytes,
    kr_bytes: bytes,
    lo_bytes: bytes,
    hi_bytes: bytes,
    n: int,
    seqlen_q: int,
    seqlen_k: int,
    block_q: int,
    block_k: int,
    range_merge: bool,  # cache-key only: build reads the env flag itself
) -> FFAPlan:
    qr = np.frombuffer(qr_bytes, dtype=np.int32).reshape(n, 2)
    kr = np.frombuffer(kr_bytes, dtype=np.int32).reshape(n, 2)
    lo = np.frombuffer(lo_bytes, dtype=np.int32)
    hi = np.frombuffer(hi_bytes, dtype=np.int32)
    return build_ffa_plan(qr, kr, lo, hi, seqlen_q, seqlen_k, block_q, block_k)


def get_ffa_plan(
    q_ranges: np.ndarray,
    k_ranges: np.ndarray,
    d_lo: np.ndarray,
    d_hi: np.ndarray,
    seqlen_q: int,
    seqlen_k: int,
    block_q: int,
    block_k: int,
) -> FFAPlan:
    """LRU-cached plan lookup keyed by the full metadata contents."""
    qr = np.ascontiguousarray(q_ranges, dtype=np.int32)
    kr = np.ascontiguousarray(k_ranges, dtype=np.int32)
    lo = np.ascontiguousarray(d_lo, dtype=np.int32)
    hi = np.ascontiguousarray(d_hi, dtype=np.int32)
    from ..env.general import is_range_merge_enable

    return _cached_plan(
        qr.tobytes(), kr.tobytes(), lo.tobytes(), hi.tobytes(), len(qr),
        seqlen_q, seqlen_k, block_q, block_k, is_range_merge_enable(),
    )
