"""Host-side tiling plan for the Pallas FFA kernel.

The TPU replacement for the reference's range-aware persistent tile schedulers
(csrc/flexible_flash_attention/fwd_tile_scheduler.hpp, bwd_tile_scheduler.hpp):
instead of a device-side scheduler walking (q_range, k_range, mask_type) lists,
we precompute — on the host, from concrete slice metadata — the exact list of
(q_tile, k_tile, slice) work items the kernel grid will visit. Fully-masked
tiles are never visited; fully-unmasked tiles can skip mask evaluation. This is
the idiomatic TPU trade: static grids + scalar prefetch instead of dynamic
scheduling + atomics.

Slices are encoded as diagonal bands (q_range, k_range, d_lo <= j-i <= d_hi) —
see kernels/mask_utils.types_to_bands.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import numpy as np

from .mask_utils import BAND_INF
from .. import telemetry
from ..utils.profiling import instrument_host

# meta columns per work item. The first 9 are the band/run columns the
# native (C) builder also fills; EQ0..EK1 are the tile-LOCAL live-extent
# columns appended host-side by :func:`_extend_meta_extents` — the exact
# sub-rectangle of the tile the band touches, rounded out to the hardware
# quanta, consumed by the extent-clamped kernel bodies (kernels/ffa.py).
# QVF/QVL mark the first/last occurrence of the item's q tile across the
# WHOLE list (appended by :func:`_extend_meta_visits`): on the k-major list
# a q tile's visits are non-consecutive, and the fused one-pass backward
# zero-initializes its revisited dq output block on QVF and flushes
# (applies softmax_scale) on QVL. On the q-major list a q tile's items form
# one contiguous run, so there QVF/QVL coincide with IS_FIRST/IS_LAST.
QS, QE, KS, KE, DLO, DHI, IS_FIRST, IS_LAST, IS_FULL = range(9)
EQ0, EQ1, EK0, EK1 = 9, 10, 11, 12
QVF, QVL = 13, 14
META_DIM = 15
# rounding quanta for the live extents: q rows land in the sublane dim
# (fp32 register tiling), k cols in the lane dim
SUBLANE_QUANTUM = 8
LANE_QUANTUM = 128


@dataclass(frozen=True, eq=False)
class FFAPlan:
    """A flat, q-tile-major work list plus its k-tile-major transpose."""

    # q-major (forward + dq): runs of items grouped by q tile
    work_qt: np.ndarray  # (W,) int32 — q tile index per item
    work_kt: np.ndarray  # (W,) int32 — k tile index per item
    meta: np.ndarray  # (W, META_DIM) int32
    # k-major (dkv): runs of items grouped by k tile
    work_qt_t: np.ndarray
    work_kt_t: np.ndarray
    meta_t: np.ndarray
    num_q_tiles: int
    num_k_tiles: int
    block_q: int
    block_k: int

    @property
    def num_work(self) -> int:
        return len(self.work_qt)

    @property
    def num_work_t(self) -> int:
        return len(self.work_qt_t)


def _extend_meta_extents(
    meta9: np.ndarray,
    work_qt: np.ndarray,
    work_kt: np.ndarray,
    block_q: int,
    block_k: int,
) -> np.ndarray:
    """Append the tile-local live-extent columns EQ0..EK1 to 9-col meta rows.

    For each work item the band ``d_lo <= j - i <= d_hi`` restricted to the
    slice rectangle intersected with the tile gives a live sub-rectangle;
    its q rows are floored/ceiled to SUBLANE_QUANTUM, its k cols to
    LANE_QUANTUM (the granularities a kernel chunk can actually skip at).
    Items with an empty intersection — dummy items for empty tiles, and
    ``pad_plan`` filler — get the all-zero extent (0, 0, 0, 0), which the
    clamp path reads as "no live work". Full tiles come out as
    (0, block_q, 0, block_k) by construction. int64 internally: DLO/DHI
    carry ±BAND_INF and the un-clamped interval arithmetic must not wrap.
    """
    m = meta9.astype(np.int64)
    qb = work_qt.astype(np.int64) * block_q
    kb = work_kt.astype(np.int64) * block_k
    i0 = np.maximum(m[:, QS], qb)
    i1 = np.minimum(m[:, QE], qb + block_q)
    j0 = np.maximum(m[:, KS], kb)
    j1 = np.minimum(m[:, KE], kb + block_k)
    lo, hi = m[:, DLO], m[:, DHI]
    # band-live rows/cols inside the clipped rectangle: row i is live iff
    # some col j in [j0, j1) has lo <= j - i <= hi, and vice versa
    q0 = np.maximum(i0, j0 - hi)
    q1 = np.minimum(i1, j1 - lo)
    k0 = np.maximum(j0, i0 + lo)
    k1 = np.minimum(j1, i1 + hi)
    eq0 = (q0 - qb) // SUBLANE_QUANTUM * SUBLANE_QUANTUM
    eq1 = -(-(q1 - qb) // SUBLANE_QUANTUM) * SUBLANE_QUANTUM
    ek0 = (k0 - kb) // LANE_QUANTUM * LANE_QUANTUM
    ek1 = -(-(k1 - kb) // LANE_QUANTUM) * LANE_QUANTUM
    ext = np.stack(
        [
            np.clip(eq0, 0, block_q),
            np.clip(eq1, 0, block_q),
            np.clip(ek0, 0, block_k),
            np.clip(ek1, 0, block_k),
        ],
        axis=1,
    )
    empty = (i0 >= i1) | (j0 >= j1) | (q1 <= q0) | (k1 <= k0)
    ext[empty] = 0
    return np.concatenate([meta9, ext.astype(np.int32)], axis=1)


def _extend_meta_visits(meta13: np.ndarray, work_qt: np.ndarray) -> np.ndarray:
    """Append the q-visit flag columns QVF/QVL to 13-col meta rows.

    QVF (resp. QVL) is 1 on the row where the item's q tile appears for the
    first (resp. last) time in this list — across the WHOLE list, not per
    run, which is what makes them usable from the k-major traversal where a
    q tile's visits are interleaved with other q tiles. Dummy items count
    as visits (their contribution is zero, so an init or flush landing on
    one is benign); ``pad_plan`` filler is appended after the fact with
    QVF = QVL = 0 so the real flush row keeps the flag.
    """
    w = np.asarray(work_qt)
    n = len(w)
    qvf = np.zeros(n, dtype=np.int32)
    qvl = np.zeros(n, dtype=np.int32)
    if n:
        first_idx: dict[int, int] = {}
        last_idx: dict[int, int] = {}
        for i, qt in enumerate(w.tolist()):
            if qt not in first_idx:
                first_idx[qt] = i
            last_idx[qt] = i
        qvf[list(first_idx.values())] = 1
        qvl[list(last_idx.values())] = 1
    return np.concatenate(
        [meta13, np.stack([qvf, qvl], axis=1)], axis=1
    ).astype(np.int32)


def plan_extent_stats(plan: FFAPlan) -> dict:
    """Executed-vs-padded element accounting from the extent columns.

    Real items are rows with a non-empty q range (QE > QS) — dummy items
    for empty tiles and ``pad_plan`` filler carry QS == QE == 0 and are
    excluded from both counts (CP-stacking filler is not real work)."""
    meta = plan.meta.astype(np.int64)
    real = meta[:, QE] > meta[:, QS]
    n_real = int(real.sum())
    executed = int(
        (
            (meta[real, EQ1] - meta[real, EQ0])
            * (meta[real, EK1] - meta[real, EK0])
        ).sum()
    )
    return {
        "num_real_work": n_real,
        "padded_elems": n_real * plan.block_q * plan.block_k,
        "executed_elems": executed,
    }


# per-slice padded/band cover-ratio buckets for the fragmentation histogram
FRAG_BUCKETS: tuple[tuple[str, float], ...] = (
    ("lt_1.2", 1.2),
    ("lt_2", 2.0),
    ("lt_4", 4.0),
    ("lt_8", 8.0),
    ("ge_8", float("inf")),
)


def fragmentation_histogram(ratios: np.ndarray) -> dict[str, int]:
    """Bucket per-slice cover ratios (tile-cover elems / band elems) into
    the FRAG_BUCKETS histogram the telemetry record and the mixed-dispatch
    cost model share."""
    hist = {name: 0 for name, _ in FRAG_BUCKETS}
    for r in np.asarray(ratios, dtype=np.float64).ravel():
        for name, ub in FRAG_BUCKETS:
            if r < ub:
                hist[name] += 1
                break
    return hist


def _record_plan_telemetry(
    plan: FFAPlan,
    qr: np.ndarray,
    kr: np.ndarray,
    d_lo: np.ndarray,
    d_hi: np.ndarray,
) -> FFAPlan:
    """Gated per-build record: the padded grid work the kernel would execute
    un-clamped, the post-clamp executed elements (live extents), and the
    true band area it needed — the estimated-vs-executed FLOP ratio at plan
    time (multiply elems by 4 * head_dim * num_heads_q for fwd FLOPs; the
    step record does, once dims are known)."""
    if telemetry.enabled():
        from ..env.kernel import ffa_extent_clamp
        from .tile_policy import slice_cover_ratios

        stats = plan_extent_stats(plan)
        padded = stats["padded_elems"]
        executed = stats["executed_elems"]
        band = telemetry.band_area(qr, kr, d_lo, d_hi)
        ratios = slice_cover_ratios(
            qr, kr, d_lo, d_hi, plan.block_q, plan.block_k
        )
        telemetry.record_event(
            "ffa_plan",
            num_slices=len(qr),
            block_q=plan.block_q,
            block_k=plan.block_k,
            num_q_tiles=plan.num_q_tiles,
            num_k_tiles=plan.num_k_tiles,
            num_work=plan.num_work,
            num_work_t=plan.num_work_t,
            padded_elems=padded,
            band_elems=band,
            executed_elems=executed,
            padding_ratio=padded / band if band else 1.0,
            executed_ratio=executed / band if band else 1.0,
            extent_clamp=ffa_extent_clamp(),
            frag_histogram=fragmentation_histogram(ratios),
        )
    return plan


def _band_tile_interaction(
    i0: int, i1: int, j0: int, j1: int, lo: int, hi: int
) -> tuple[bool, bool]:
    """(nonempty, fully_unmasked) of band [lo, hi] on rect [i0,i1) x [j0,j1)."""
    if i0 >= i1 or j0 >= j1:
        return False, False
    d_min = j0 - (i1 - 1)
    d_max = (j1 - 1) - i0
    nonempty = d_min <= hi and d_max >= lo
    full = nonempty and d_max <= hi and d_min >= lo
    return nonempty, full


@instrument_host
def build_ffa_plan(
    q_ranges: np.ndarray,
    k_ranges: np.ndarray,
    d_lo: np.ndarray,
    d_hi: np.ndarray,
    seqlen_q: int,
    seqlen_k: int,
    block_q: int,
    block_k: int,
) -> FFAPlan:
    """Build the work-item lists for the given band-slice metadata.

    When ``MAGI_ATTENTION_RANGE_MERGE`` is on (default), band-compatible
    adjacent slices are merged first (mask_utils.merge_band_slices — the ref
    merges at its kernel entry, functional/flex_flash_attn.py:87). Exact:
    bands are global-coordinate, so the merged cover is identical; fragmented
    masks (block-sparse, video) collapse into fewer work items. This is the
    one choke point every planning path flows through (single-device
    ffa_attn, CP _stack_plans, dynamic runtime), so all of them benefit.
    """
    from ..env.general import is_range_merge_enable

    if is_range_merge_enable():
        from .mask_utils import merge_band_slices

        q_ranges, k_ranges, d_lo, d_hi = merge_band_slices(
            q_ranges, k_ranges, d_lo, d_hi
        )
    num_q_tiles = max(1, -(-seqlen_q // block_q))
    num_k_tiles = max(1, -(-seqlen_k // block_k))

    from ..env.kernel import ffa_native_plan

    mode = ffa_native_plan()
    if mode != "0":
        try:
            from ..csrc_backend.ops import ffa_plan_native

            arrays = ffa_plan_native(
                q_ranges, k_ranges, d_lo, d_hi,
                num_q_tiles, num_k_tiles, block_q, block_k, BAND_INF,
            )
            # the C fill writes 9-col rows (fixed stride, csrc/magi_host.cpp);
            # the extent and q-visit columns are appended here so native
            # and Python plans stay bit-identical
            return _record_plan_telemetry(
                FFAPlan(
                    work_qt=arrays[0], work_kt=arrays[1],
                    meta=_extend_meta_visits(
                        _extend_meta_extents(
                            arrays[2], arrays[0], arrays[1], block_q, block_k
                        ),
                        arrays[0],
                    ),
                    work_qt_t=arrays[3], work_kt_t=arrays[4],
                    meta_t=_extend_meta_visits(
                        _extend_meta_extents(
                            arrays[5], arrays[3], arrays[4], block_q, block_k
                        ),
                        arrays[3],
                    ),
                    num_q_tiles=num_q_tiles, num_k_tiles=num_k_tiles,
                    block_q=block_q, block_k=block_k,
                ),
                q_ranges, k_ranges, d_lo, d_hi,
            )
        except ImportError:
            if mode == "1":
                raise
            # auto: native lib unavailable — pure-Python builder below

    n = len(q_ranges)
    q_items: list[list[tuple[int, ...]]] = [[] for _ in range(num_q_tiles)]
    k_items: list[list[tuple[int, ...]]] = [[] for _ in range(num_k_tiles)]

    for s in range(n):
        qs, qe = int(q_ranges[s, 0]), int(q_ranges[s, 1])
        ks, ke = int(k_ranges[s, 0]), int(k_ranges[s, 1])
        lo, hi = int(d_lo[s]), int(d_hi[s])
        if qs >= qe or ks >= ke or lo > hi:
            continue
        # same bounds validation as the native builder (csrc/magi_host.cpp:251
        # returns -1 -> ops.py raises): without it, negative starts would
        # silently wrap via Python negative indexing and corrupt the plan
        if (
            qs < 0
            or ks < 0
            or -(-qe // block_q) > num_q_tiles
            or -(-ke // block_k) > num_k_tiles
        ):
            raise ValueError(
                f"ffa plan slice {s} out of bounds: q[{qs},{qe}) "
                f"k[{ks},{ke}) vs grid {num_q_tiles}x{num_k_tiles} tiles"
            )
        qt_lo, qt_hi = qs // block_q, -(-qe // block_q)
        kt_lo, kt_hi = ks // block_k, -(-ke // block_k)
        for qt in range(qt_lo, qt_hi):
            i0, i1 = max(qs, qt * block_q), min(qe, (qt + 1) * block_q)
            for kt in range(kt_lo, kt_hi):
                j0, j1 = max(ks, kt * block_k), min(ke, (kt + 1) * block_k)
                nonempty, full = _band_tile_interaction(i0, i1, j0, j1, lo, hi)
                if not nonempty:
                    continue
                tile_full = (
                    full
                    and i0 == qt * block_q
                    and i1 == (qt + 1) * block_q
                    and j0 == kt * block_k
                    and j1 == (kt + 1) * block_k
                )
                item = (qt, kt, qs, qe, ks, ke, lo, hi, int(tile_full))
                q_items[qt].append(item)
                k_items[kt].append(item)

    def flatten(buckets, major_is_q: bool):
        work_a, work_b, metas = [], [], []
        for tile_idx, items in enumerate(buckets):
            if not items:
                # dummy item: empty k range -> all-masked -> finalize writes
                # zeros/-inf (fwd) or zero grads (bwd) for this tile
                items = [
                    (
                        tile_idx if major_is_q else 0,
                        0 if major_is_q else tile_idx,
                        0, 0, 0, 0, -BAND_INF, BAND_INF, 0,
                    )
                ]
            for pos, (qt, kt, qs, qe, ks, ke, lo, hi, full) in enumerate(items):
                m = np.zeros(9, dtype=np.int32)
                m[QS], m[QE], m[KS], m[KE] = qs, qe, ks, ke
                m[DLO], m[DHI] = lo, hi
                m[IS_FIRST] = 1 if pos == 0 else 0
                m[IS_LAST] = 1 if pos == len(items) - 1 else 0
                m[IS_FULL] = full
                work_a.append(qt)
                work_b.append(kt)
                metas.append(m)
        work_a = np.asarray(work_a, dtype=np.int32)
        work_b = np.asarray(work_b, dtype=np.int32)
        meta9 = np.stack(metas).astype(np.int32)
        return (
            work_a,
            work_b,
            _extend_meta_visits(
                _extend_meta_extents(meta9, work_a, work_b, block_q, block_k),
                work_a,
            ),
        )

    work_qt, work_kt, meta = flatten(q_items, major_is_q=True)
    work_qt_t, work_kt_t, meta_t = flatten(k_items, major_is_q=False)

    return _record_plan_telemetry(
        FFAPlan(
            work_qt=work_qt,
            work_kt=work_kt,
            meta=meta,
            work_qt_t=work_qt_t,
            work_kt_t=work_kt_t,
            meta_t=meta_t,
            num_q_tiles=num_q_tiles,
            num_k_tiles=num_k_tiles,
            block_q=block_q,
            block_k=block_k,
        ),
        q_ranges, k_ranges, d_lo, d_hi,
    )


def pad_plan(plan: FFAPlan, num_work: int, num_work_t: int) -> FFAPlan:
    """Pad work lists with no-op items (same tile as the last real item,
    is_first=is_last=0, empty ranges) so plans from different CP ranks share
    one static shape and can be fed to the kernel as traced arrays."""

    def pad(work_a, work_b, meta, target, tile_col_is_q: bool):
        w = len(work_a)
        if w > target:
            raise ValueError(f"plan has {w} items > target {target}")
        if w == target:
            return work_a, work_b, meta
        pad_n = target - w
        pa = np.full(pad_n, work_a[-1], dtype=np.int32)
        pb = np.full(pad_n, work_b[-1], dtype=np.int32)
        # filler rows keep the all-zero live extent (EQ0..EK1 == 0): the
        # clamp path skips them and plan_extent_stats excludes them from
        # the padded/executed accounting (QS == QE flags them as non-real).
        # QVF/QVL stay 0 too — filler revisits the last real tile's dq
        # window with a zero contribution, after its real flush row
        pm = np.zeros((pad_n, META_DIM), dtype=np.int32)
        pm[:, DLO], pm[:, DHI] = -BAND_INF, BAND_INF
        return (
            np.concatenate([work_a, pa]),
            np.concatenate([work_b, pb]),
            np.concatenate([meta, pm]),
        )

    wq, wk, m = pad(plan.work_qt, plan.work_kt, plan.meta, num_work, True)
    wqt, wkt, mt = pad(
        plan.work_qt_t, plan.work_kt_t, plan.meta_t, num_work_t, False
    )
    return FFAPlan(
        work_qt=wq, work_kt=wk, meta=m,
        work_qt_t=wqt, work_kt_t=wkt, meta_t=mt,
        num_q_tiles=plan.num_q_tiles, num_k_tiles=plan.num_k_tiles,
        block_q=plan.block_q, block_k=plan.block_k,
    )


@lru_cache(maxsize=256)
def _cached_plan(
    qr_bytes: bytes,
    kr_bytes: bytes,
    lo_bytes: bytes,
    hi_bytes: bytes,
    n: int,
    seqlen_q: int,
    seqlen_k: int,
    block_q: int,
    block_k: int,
    range_merge: bool,  # cache-key only: build reads the env flag itself
) -> FFAPlan:
    qr = np.frombuffer(qr_bytes, dtype=np.int32).reshape(n, 2)
    kr = np.frombuffer(kr_bytes, dtype=np.int32).reshape(n, 2)
    lo = np.frombuffer(lo_bytes, dtype=np.int32)
    hi = np.frombuffer(hi_bytes, dtype=np.int32)
    return build_ffa_plan(qr, kr, lo, hi, seqlen_q, seqlen_k, block_q, block_k)


def get_ffa_plan(
    q_ranges: np.ndarray,
    k_ranges: np.ndarray,
    d_lo: np.ndarray,
    d_hi: np.ndarray,
    seqlen_q: int,
    seqlen_k: int,
    block_q: int,
    block_k: int,
) -> FFAPlan:
    """LRU-cached plan lookup keyed by the full metadata contents."""
    qr = np.ascontiguousarray(q_ranges, dtype=np.int32)
    kr = np.ascontiguousarray(k_ranges, dtype=np.int32)
    lo = np.ascontiguousarray(d_lo, dtype=np.int32)
    hi = np.ascontiguousarray(d_hi, dtype=np.int32)
    from ..env.general import is_range_merge_enable

    return _cached_plan(
        qr.tobytes(), kr.tobytes(), lo.tobytes(), hi.tobytes(), len(qr),
        seqlen_q, seqlen_k, block_q, block_k, is_range_merge_enable(),
    )
