"""Ragged paged-decode attention Pallas kernel (serving decode step).

The gather+FFA path in :mod:`paged_kv` materializes ``max_pages * page_size``
contiguous rows per sequence before attending — fine for prefill chunks, but
a decode step reads ONE query row per sequence, so the gather dominates. This
kernel attends straight out of the paged cache instead, in the Ragged Paged
Attention shape (PAPERS.md): a single query tile per sequence (the GQA group
rows of one kv head), a KV-page-major grid, and the per-sequence page table
as scalar prefetch so each grid step DMAs exactly one page.

Design notes (shared idiom with ``ffa.py`` — same online-softmax algebra,
same Mosaic compatibility rules):

- grid ``(hk, max_seqs, pages_per_seq)`` with the page axis innermost and
  ``arbitrary``: all pages of one (head, seq) are consecutive grid steps
  accumulating into VMEM scratch; the output tile is written once at the end
  of the run (the FFA run-ordering contract, rule K2).
- the page-table row is prefetch state consumed by the k/v index maps;
  unallocated entries (-1) clamp to page 0 and the length mask turns the
  whole page into exact no-op contributions (masked ``p`` underflows to 0.0,
  never-live rows are discarded by the finalize empty threshold), so dead
  pages need no control flow — matching ``gather_kv``'s clamp semantics.
- lengths are traced values (NOT host constants): one lowered kernel serves
  every step of a serving loop, which is the whole point vs ``paged_attn``'s
  host-static ``kv_len`` plan parameterization.
- q is pre-scaled by ``softmax_scale * log2(e)`` on the host and the softmax
  runs in the exp2 domain (the softcap-free fwd-kernel fast path; decode has
  no softcap rung today).
- no ``-inf`` arithmetic in-kernel: masking uses ``MASK_VALUE``; fully-empty
  slots (length 0) are flagged at ``EMPTY_THRESH`` and converted to
  (out=0, lse=-inf) on the host, exactly like ``_fwd_kernel``.

This module is deliberately env-free (rule K5): routing decisions (decode
kernel vs gather+FFA vs dense) live in ``serving/decode.py``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec

from .ffa import (
    _CompilerParams,
    _lane_tile,
    _should_interpret,
    EMPTY_THRESH,
    LN2,
    LOG2E,
    MASK_VALUE,
    NEG_INF,
    NUM_LANES,
)
from .paged_kv import PagedKVCache

__all__ = [
    "paged_decode_attn",
    "paged_decode_attn_int8",
    "paged_decode_attn_sharded",
    "paged_decode_attn_spec",
    "PALLAS_CONTRACTS",
]


def _paged_decode_kernel(
    table_ref,
    lengths_ref,
    q_ref,
    k_ref,
    v_ref,
    out_ref,
    lse_ref,
    m_scr,
    l_scr,
    acc_scr,
    *,
    ps: int,
):
    s_idx = pl.program_id(1)
    p_idx = pl.program_id(2)
    num_pages_grid = pl.num_programs(2)
    is_first = jnp.int32(p_idx == 0)
    is_last = jnp.int32(p_idx == num_pages_grid - 1)

    @pl.when(is_first == 1)
    def _():
        m_scr[:] = jnp.full_like(m_scr, MASK_VALUE)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0, 0]  # (g, d), pre-scaled by softmax_scale * log2e
    k = k_ref[0, :, 0, :]  # (ps, d)
    v = v_ref[0, :, 0, :]  # (ps, dv)

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (g, ps)
    # ragged length mask: page p covers rows [p*ps, (p+1)*ps) of the
    # sequence; rows at or past lengths[s] are dead (incl. every row of a
    # clamped -1 page, whose coverage lies entirely past the length)
    cols = p_idx * ps + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(cols < lengths_ref[s_idx], s, MASK_VALUE)

    m_prev = m_scr[...]  # (g, NUM_LANES)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1)[:, None])
    p = jnp.exp2(s - _lane_tile(m_new, ps))
    alpha = jnp.exp2(m_prev - m_new)  # == 1 while empty
    l_scr[:] = l_scr[...] * alpha + jnp.sum(p, axis=1)[:, None]
    pv = jax.lax.dot_general(
        p.astype(v_ref.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_scr[:] = acc_scr[:] * _lane_tile(alpha, acc_scr.shape[-1]) + pv
    m_scr[:] = m_new

    @pl.when(is_last == 1)
    def _():
        m = m_scr[...]
        l = l_scr[...]
        empty = m <= EMPTY_THRESH
        l_safe = jnp.where(empty | (l == 0.0), 1.0, l)
        o = acc_scr[:] / _lane_tile(l_safe, acc_scr.shape[-1])
        o = jnp.where(_lane_tile(empty, o.shape[-1]), 0.0, o)
        out_ref[0, 0] = o.astype(out_ref.dtype)
        lse_ref[0, 0] = jnp.where(
            empty, MASK_VALUE, (m + jnp.log2(l_safe)) * LN2
        ).astype(jnp.float32)


def _paged_decode_pallas(page_table, lengths, q_hds, k_pages, v_pages,
                         interpret: bool):
    """q_hds: ``(hk, S, g, d)`` pre-scaled; k/v_pages ``(num_pages, ps, hk, *)``.

    Returns (out ``(hk, S, g, dv)`` q dtype, lse ``(hk, S, g, NUM_LANES)``
    fp32 with MASK_VALUE flags on empty slots).
    """
    hk, S, g, d = q_hds.shape
    num_pages, ps, _, dv = v_pages.shape
    P = page_table.shape[1]

    lse_spec = pl.BlockSpec(
        (1, 1, g, NUM_LANES),
        lambda h, s, p, table, lens: (h, s, 0, 0),
        memory_space=pltpu.VMEM,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(hk, S, P),
        in_specs=[
            pl.BlockSpec(
                (1, 1, g, d),
                lambda h, s, p, table, lens: (h, s, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ps, 1, d),
                lambda h, s, p, table, lens: (
                    jnp.maximum(table[s, p], 0), 0, h, 0
                ),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ps, 1, dv),
                lambda h, s, p, table, lens: (
                    jnp.maximum(table[s, p], 0), 0, h, 0
                ),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, g, dv),
                lambda h, s, p, table, lens: (h, s, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            lse_spec,
        ],
        scratch_shapes=[
            pltpu.VMEM((g, NUM_LANES), jnp.float32),
            pltpu.VMEM((g, NUM_LANES), jnp.float32),
            pltpu.VMEM((g, dv), jnp.float32),
        ],
    )
    kernel = partial(_paged_decode_kernel, ps=ps)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((hk, S, g, dv), q_hds.dtype),
            jax.ShapeDtypeStruct((hk, S, g, NUM_LANES), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * hk * S * P * g * ps * d,
            bytes_accessed=(
                q_hds.size * q_hds.dtype.itemsize
                + S * P * ps * (d + dv) * k_pages.dtype.itemsize
            ),
            transcendentals=hk * S * P * g * ps,
        ),
    )(page_table, lengths, q_hds, k_pages, v_pages)
    return out, lse


def paged_decode_attn(
    q: jax.Array,
    cache: PagedKVCache,
    softmax_scale: float | None = None,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """One batched decode step: each sequence slot's single query token
    attends over its own paged KV rows ``[0, lengths[slot])``.

    Args:
        q: ``(max_seqs, hq, d)`` — one query row per slot. Slots with
            ``lengths == 0`` are inactive and yield (out=0, lse=-inf).
        cache: the paged cache; ``page_table``/``lengths`` ride as scalar
            prefetch, so they may be traced (jit-safe serving loop).
        softmax_scale: defaults to ``d ** -0.5``.
        interpret: force/deny Pallas interpret mode (defaults to the env/
            backend heuristic shared with FFA).

    Returns:
        (out ``(max_seqs, hq, dv)`` in q's dtype, lse ``(max_seqs, hq)``
        fp32, ``-inf`` on inactive slots).
    """
    S, hq, d = q.shape
    num_pages, ps, hk, dv = cache.v_pages.shape
    if hq % hk:
        raise ValueError(f"hq={hq} not a multiple of kv heads hk={hk}")
    if not (ps <= NUM_LANES or ps % NUM_LANES == 0):
        raise ValueError(
            f"page_size={ps} must be <= {NUM_LANES} or a multiple of it "
            f"(lane-tiling rule shared with ffa.default_blocks)"
        )
    g = hq // hk
    if softmax_scale is None:
        softmax_scale = float(d) ** -0.5
    if interpret is None:
        interpret = _should_interpret()

    q_scale = softmax_scale * LOG2E
    q = (q.astype(jnp.float32) * q_scale).astype(q.dtype)
    # (S, hq, d) -> (hk, S, g, d): q heads [h*g, (h+1)*g) share kv head h,
    # the same grouping as ffa's `h // g` k index map
    q_hds = q.reshape(S, hk, g, d).transpose(1, 0, 2, 3)

    out_hds, lse_hds = _paged_decode_pallas(
        cache.page_table, cache.lengths, q_hds,
        cache.k_pages, cache.v_pages, interpret,
    )
    out = out_hds.transpose(1, 0, 2, 3).reshape(S, hq, dv)
    lse_raw = lse_hds[..., 0].transpose(1, 0, 2).reshape(S, hq)
    lse = jnp.where(lse_raw <= EMPTY_THRESH, NEG_INF, lse_raw)
    return out, lse


def paged_decode_attn_sharded(
    q: jax.Array,
    cache: PagedKVCache,
    num_shards: int,
    softmax_scale: float | None = None,
    interpret: bool | None = None,
    devices=None,
) -> tuple[jax.Array, jax.Array]:
    """Mesh-sharded decode step: ``shard_map`` over the kv-head axis, one
    kernel launch per shard (the SNIPPETS ``sharded_paged_attention``
    pattern). Each shard runs the *same* ``_paged_decode_pallas`` body over
    its ``hk // num_shards`` heads — per-(head, seq) accumulation is
    untouched, so shard output is bitwise-equal to the single-device run.

    page_table/lengths are replicated (every shard walks the same pages);
    k/v pages are split on their head axis, q on its leading kv-head axis.
    No new ``pallas_call`` site: the audited single-device contract covers
    the sharded path exactly.
    """
    S, hq, d = q.shape
    num_pages, ps, hk, dv = cache.v_pages.shape
    if hq % hk:
        raise ValueError(f"hq={hq} not a multiple of kv heads hk={hk}")
    if hk % num_shards:
        raise ValueError(
            f"hk={hk} not divisible by num_shards={num_shards}; the kv-head "
            f"axis is the shard axis"
        )
    if not (ps <= NUM_LANES or ps % NUM_LANES == 0):
        raise ValueError(
            f"page_size={ps} must be <= {NUM_LANES} or a multiple of it "
            f"(lane-tiling rule shared with ffa.default_blocks)"
        )
    if devices is None:
        devices = jax.devices()[:num_shards]
    if len(devices) < num_shards:
        raise ValueError(
            f"need {num_shards} devices for the kv mesh, have {len(devices)}"
        )
    g = hq // hk
    if softmax_scale is None:
        softmax_scale = float(d) ** -0.5
    if interpret is None:
        interpret = _should_interpret()

    q_scale = softmax_scale * LOG2E
    q = (q.astype(jnp.float32) * q_scale).astype(q.dtype)
    q_hds = q.reshape(S, hk, g, d).transpose(1, 0, 2, 3)

    mesh = Mesh(np.asarray(devices), ("kv",))
    spec_kv_heads = PartitionSpec(None, None, "kv")
    sharded = shard_map(
        lambda table, lens, qh, kp, vp: _paged_decode_pallas(
            table, lens, qh, kp, vp, interpret
        ),
        mesh=mesh,
        in_specs=(
            PartitionSpec(),  # page_table: replicated
            PartitionSpec(),  # lengths: replicated
            PartitionSpec("kv"),  # q_hds (hk, S, g, d)
            spec_kv_heads,  # k_pages (num_pages, ps, hk, d)
            spec_kv_heads,  # v_pages (num_pages, ps, hk, dv)
        ),
        out_specs=(PartitionSpec("kv"), PartitionSpec("kv")),
        check_rep=False,
    )
    out_hds, lse_hds = sharded(
        cache.page_table, cache.lengths, q_hds, cache.k_pages, cache.v_pages
    )
    # Re-materialize as uncommitted single-device arrays: the shard_map
    # outputs are laid out across the mesh, and downstream eager ops (the
    # model's projections) on sharded operands would pick partitioned
    # reduction orders that drift ~1e-7 from the single-device run.
    # Gathering here keeps the whole serving loop bitwise-equal to the
    # unsharded rung; uncommitted (vs device_put to a mesh device) so the
    # next tick's inputs can feed the mesh again.
    out_hds = jnp.asarray(jax.device_get(out_hds))
    lse_hds = jnp.asarray(jax.device_get(lse_hds))
    out = out_hds.transpose(1, 0, 2, 3).reshape(S, hq, dv)
    lse_raw = lse_hds[..., 0].transpose(1, 0, 2).reshape(S, hq)
    lse = jnp.where(lse_raw <= EMPTY_THRESH, NEG_INF, lse_raw)
    return out, lse


def _paged_decode_spec_kernel(
    table_ref,
    lengths_ref,
    q_ref,
    k_ref,
    v_ref,
    out_ref,
    lse_ref,
    m_scr,
    l_scr,
    acc_scr,
    *,
    ps: int,
    spec_k: int,
    g: int,
):
    """Multi-token speculative-verify variant: the q tile holds the GQA
    group rows of ``spec_k`` consecutive draft tokens (``spec_k * g`` rows),
    already appended to the cache, with a per-row causal horizon — row
    ``r`` verifies draft token ``t = r // g`` sitting at absolute position
    ``lengths - spec_k + t``, so it may attend columns ``< lengths -
    (spec_k - 1 - t)``. Everything else (page walk, online softmax,
    init/flush discipline) is the base decode kernel."""
    s_idx = pl.program_id(1)
    p_idx = pl.program_id(2)
    num_pages_grid = pl.num_programs(2)
    is_first = jnp.int32(p_idx == 0)
    is_last = jnp.int32(p_idx == num_pages_grid - 1)

    @pl.when(is_first == 1)
    def _():
        m_scr[:] = jnp.full_like(m_scr, MASK_VALUE)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0, 0]  # (spec_k * g, d), pre-scaled by softmax_scale * log2e
    k = k_ref[0, :, 0, :]  # (ps, d)
    v = v_ref[0, :, 0, :]  # (ps, dv)

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (spec_k * g, ps)
    cols = p_idx * ps + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    # per-row ragged causal horizon: draft token t = row // g ends at
    # absolute position lengths - spec_k + t (inclusive)
    limit = lengths_ref[s_idx] - (spec_k - 1 - rows // g)
    s = jnp.where(cols < limit, s, MASK_VALUE)

    m_prev = m_scr[...]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1)[:, None])
    p = jnp.exp2(s - _lane_tile(m_new, ps))
    alpha = jnp.exp2(m_prev - m_new)
    l_scr[:] = l_scr[...] * alpha + jnp.sum(p, axis=1)[:, None]
    pv = jax.lax.dot_general(
        p.astype(v_ref.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_scr[:] = acc_scr[:] * _lane_tile(alpha, acc_scr.shape[-1]) + pv
    m_scr[:] = m_new

    @pl.when(is_last == 1)
    def _():
        m = m_scr[...]
        l = l_scr[...]
        empty = m <= EMPTY_THRESH
        l_safe = jnp.where(empty | (l == 0.0), 1.0, l)
        o = acc_scr[:] / _lane_tile(l_safe, acc_scr.shape[-1])
        o = jnp.where(_lane_tile(empty, o.shape[-1]), 0.0, o)
        out_ref[0, 0] = o.astype(out_ref.dtype)
        lse_ref[0, 0] = jnp.where(
            empty, MASK_VALUE, (m + jnp.log2(l_safe)) * LN2
        ).astype(jnp.float32)


def _paged_decode_spec_pallas(page_table, lengths, q_hds, k_pages, v_pages,
                              spec_k: int, g: int, interpret: bool):
    """q_hds: ``(hk, S, spec_k * g, d)`` pre-scaled; same page walk as the
    base decode pallas wrapper, taller q/out/scratch tiles."""
    hk, S, kg, d = q_hds.shape
    num_pages, ps, _, dv = v_pages.shape
    P = page_table.shape[1]

    lse_spec = pl.BlockSpec(
        (1, 1, kg, NUM_LANES),
        lambda h, s, p, table, lens: (h, s, 0, 0),
        memory_space=pltpu.VMEM,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(hk, S, P),
        in_specs=[
            pl.BlockSpec(
                (1, 1, kg, d),
                lambda h, s, p, table, lens: (h, s, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ps, 1, d),
                lambda h, s, p, table, lens: (
                    jnp.maximum(table[s, p], 0), 0, h, 0
                ),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ps, 1, dv),
                lambda h, s, p, table, lens: (
                    jnp.maximum(table[s, p], 0), 0, h, 0
                ),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, kg, dv),
                lambda h, s, p, table, lens: (h, s, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            lse_spec,
        ],
        scratch_shapes=[
            pltpu.VMEM((kg, NUM_LANES), jnp.float32),
            pltpu.VMEM((kg, NUM_LANES), jnp.float32),
            pltpu.VMEM((kg, dv), jnp.float32),
        ],
    )
    kernel = partial(_paged_decode_spec_kernel, ps=ps, spec_k=spec_k, g=g)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((hk, S, kg, dv), q_hds.dtype),
            jax.ShapeDtypeStruct((hk, S, kg, NUM_LANES), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * hk * S * P * kg * ps * d,
            bytes_accessed=(
                q_hds.size * q_hds.dtype.itemsize
                + S * P * ps * (d + dv) * k_pages.dtype.itemsize
            ),
            transcendentals=hk * S * P * kg * ps,
        ),
    )(page_table, lengths, q_hds, k_pages, v_pages)
    return out, lse


def paged_decode_attn_spec(
    q: jax.Array,
    cache: PagedKVCache,
    softmax_scale: float | None = None,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Speculative verify step: each slot's ``spec_k`` draft-token query
    rows (already appended to the cache, so ``lengths`` includes them)
    attend their own causal prefixes in one launch.

    Args:
        q: ``(max_seqs, spec_k, hq, d)`` — draft token ``t`` of a slot sits
            at absolute position ``lengths[slot] - spec_k + t``. Slots with
            ``lengths == 0`` are inactive and yield (out=0, lse=-inf).

    Returns:
        (out ``(max_seqs, spec_k, hq, dv)`` in q's dtype,
        lse ``(max_seqs, spec_k, hq)`` fp32, ``-inf`` on inactive slots).
    """
    S, spec_k, hq, d = q.shape
    num_pages, ps, hk, dv = cache.v_pages.shape
    if hq % hk:
        raise ValueError(f"hq={hq} not a multiple of kv heads hk={hk}")
    if spec_k < 1:
        raise ValueError(f"spec_k={spec_k} must be >= 1")
    if not (ps <= NUM_LANES or ps % NUM_LANES == 0):
        raise ValueError(
            f"page_size={ps} must be <= {NUM_LANES} or a multiple of it "
            f"(lane-tiling rule shared with ffa.default_blocks)"
        )
    g = hq // hk
    if softmax_scale is None:
        softmax_scale = float(d) ** -0.5
    if interpret is None:
        interpret = _should_interpret()

    q_scale = softmax_scale * LOG2E
    q = (q.astype(jnp.float32) * q_scale).astype(q.dtype)
    # (S, spec_k, hq, d) -> (hk, S, spec_k * g, d): token-major rows within
    # a kv head, so kernel row r = t * g + group_row
    q_hds = (
        q.reshape(S, spec_k, hk, g, d)
        .transpose(2, 0, 1, 3, 4)
        .reshape(hk, S, spec_k * g, d)
    )

    out_hds, lse_hds = _paged_decode_spec_pallas(
        cache.page_table, cache.lengths, q_hds,
        cache.k_pages, cache.v_pages, spec_k, g, interpret,
    )
    out = (
        out_hds.reshape(hk, S, spec_k, g, dv)
        .transpose(1, 2, 0, 3, 4)
        .reshape(S, spec_k, hq, dv)
    )
    lse_raw = (
        lse_hds[..., 0]
        .reshape(hk, S, spec_k, g)
        .transpose(1, 2, 0, 3)
        .reshape(S, spec_k, hq)
    )
    lse = jnp.where(lse_raw <= EMPTY_THRESH, NEG_INF, lse_raw)
    return out, lse


def _paged_decode_int8_kernel(
    table_ref,
    lengths_ref,
    q_ref,
    k_ref,
    v_ref,
    ks_ref,
    vs_ref,
    out_ref,
    lse_ref,
    m_scr,
    l_scr,
    acc_scr,
    *,
    ps: int,
):
    """int8-KV variant: k/v pages arrive as int8 codes plus one f32 scale
    per (page, kv head), routed by the same page-table prefetch as the page
    itself (a (1, 1) block of the ``(num_pages, hk)`` scale arrays).
    Dequant happens in-kernel right after the DMA; all accumulation stays
    f32 (rule K4), so the only precision loss is the storage quantization."""
    s_idx = pl.program_id(1)
    p_idx = pl.program_id(2)
    num_pages_grid = pl.num_programs(2)
    is_first = jnp.int32(p_idx == 0)
    is_last = jnp.int32(p_idx == num_pages_grid - 1)

    @pl.when(is_first == 1)
    def _():
        m_scr[:] = jnp.full_like(m_scr, MASK_VALUE)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0, 0].astype(jnp.float32)  # (g, d), pre-scaled
    # dequant: codes are symmetric int8, scale is per (page, kv head)
    k = k_ref[0, :, 0, :].astype(jnp.float32) * ks_ref[0, 0]  # (ps, d)
    v = v_ref[0, :, 0, :].astype(jnp.float32) * vs_ref[0, 0]  # (ps, dv)

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (g, ps)
    cols = p_idx * ps + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(cols < lengths_ref[s_idx], s, MASK_VALUE)

    m_prev = m_scr[...]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1)[:, None])
    p = jnp.exp2(s - _lane_tile(m_new, ps))
    alpha = jnp.exp2(m_prev - m_new)
    l_scr[:] = l_scr[...] * alpha + jnp.sum(p, axis=1)[:, None]
    pv = jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_scr[:] = acc_scr[:] * _lane_tile(alpha, acc_scr.shape[-1]) + pv
    m_scr[:] = m_new

    @pl.when(is_last == 1)
    def _():
        m = m_scr[...]
        l = l_scr[...]
        empty = m <= EMPTY_THRESH
        l_safe = jnp.where(empty | (l == 0.0), 1.0, l)
        o = acc_scr[:] / _lane_tile(l_safe, acc_scr.shape[-1])
        o = jnp.where(_lane_tile(empty, o.shape[-1]), 0.0, o)
        out_ref[0, 0] = o.astype(out_ref.dtype)
        lse_ref[0, 0] = jnp.where(
            empty, MASK_VALUE, (m + jnp.log2(l_safe)) * LN2
        ).astype(jnp.float32)


def _paged_decode_int8_pallas(page_table, lengths, q_hds, k_pages, v_pages,
                              k_scales, v_scales, interpret: bool):
    """q_hds ``(hk, S, g, d)`` pre-scaled; k/v_pages int8
    ``(num_pages, ps, hk, *)``; k/v_scales f32 ``(num_pages, hk)`` — the
    scale blocks ride the same page-table index map as their pages."""
    hk, S, g, d = q_hds.shape
    num_pages, ps, _, dv = v_pages.shape
    P = page_table.shape[1]

    lse_spec = pl.BlockSpec(
        (1, 1, g, NUM_LANES),
        lambda h, s, p, table, lens: (h, s, 0, 0),
        memory_space=pltpu.VMEM,
    )
    scale_spec = pl.BlockSpec(
        (1, 1),
        lambda h, s, p, table, lens: (jnp.maximum(table[s, p], 0), h),
        memory_space=pltpu.VMEM,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(hk, S, P),
        in_specs=[
            pl.BlockSpec(
                (1, 1, g, d),
                lambda h, s, p, table, lens: (h, s, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ps, 1, d),
                lambda h, s, p, table, lens: (
                    jnp.maximum(table[s, p], 0), 0, h, 0
                ),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ps, 1, dv),
                lambda h, s, p, table, lens: (
                    jnp.maximum(table[s, p], 0), 0, h, 0
                ),
                memory_space=pltpu.VMEM,
            ),
            scale_spec,
            scale_spec,
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, g, dv),
                lambda h, s, p, table, lens: (h, s, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            lse_spec,
        ],
        scratch_shapes=[
            pltpu.VMEM((g, NUM_LANES), jnp.float32),
            pltpu.VMEM((g, NUM_LANES), jnp.float32),
            pltpu.VMEM((g, dv), jnp.float32),
        ],
    )
    kernel = partial(_paged_decode_int8_kernel, ps=ps)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((hk, S, g, dv), q_hds.dtype),
            jax.ShapeDtypeStruct((hk, S, g, NUM_LANES), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * hk * S * P * g * ps * d,
            bytes_accessed=(
                q_hds.size * q_hds.dtype.itemsize
                + S * P * ps * (d + dv)  # int8: 1 byte/elem
                + S * P * 2 * 4  # per-page scales
            ),
            transcendentals=hk * S * P * g * ps,
        ),
    )(page_table, lengths, q_hds, k_pages, v_pages, k_scales, v_scales)
    return out, lse


def paged_decode_attn_int8(
    q: jax.Array,
    cache: PagedKVCache,
    softmax_scale: float | None = None,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """One batched decode step over a quantized (int8 + per-page-scale)
    cache. Same contract as :func:`paged_decode_attn`; requires
    ``cache.k_scales``/``cache.v_scales`` (see ``PagedKVCache.create`` with
    ``dtype=jnp.int8``)."""
    if cache.k_scales is None or cache.v_scales is None:
        raise ValueError(
            "paged_decode_attn_int8 needs a quantized cache "
            "(PagedKVCache.create(..., dtype=jnp.int8))"
        )
    S, hq, d = q.shape
    num_pages, ps, hk, dv = cache.v_pages.shape
    if hq % hk:
        raise ValueError(f"hq={hq} not a multiple of kv heads hk={hk}")
    if not (ps <= NUM_LANES or ps % NUM_LANES == 0):
        raise ValueError(
            f"page_size={ps} must be <= {NUM_LANES} or a multiple of it "
            f"(lane-tiling rule shared with ffa.default_blocks)"
        )
    g = hq // hk
    if softmax_scale is None:
        softmax_scale = float(d) ** -0.5
    if interpret is None:
        interpret = _should_interpret()

    q_scale = softmax_scale * LOG2E
    q = (q.astype(jnp.float32) * q_scale).astype(q.dtype)
    q_hds = q.reshape(S, hk, g, d).transpose(1, 0, 2, 3)

    out_hds, lse_hds = _paged_decode_int8_pallas(
        cache.page_table, cache.lengths, q_hds,
        cache.k_pages, cache.v_pages,
        cache.k_scales, cache.v_scales, interpret,
    )
    out = out_hds.transpose(1, 0, 2, 3).reshape(S, hq, dv)
    lse_raw = lse_hds[..., 0].transpose(1, 0, 2).reshape(S, hq)
    lse = jnp.where(lse_raw <= EMPTY_THRESH, NEG_INF, lse_raw)
    return out, lse


# Static kernel-contract declarations consumed by analysis/kernel_check
# (K2/K4 source rules + K1/K3/K4 capture checks). The page-axis guards bind
# from pl.program_id instead of plan meta columns — init_binding /
# flush_binding carry the expected binding substrings.
PALLAS_CONTRACTS: dict = {
    "_paged_decode_kernel": dict(
        wrapper="_paged_decode_pallas",
        scratch=("m_scr", "l_scr", "acc_scr"),
        outputs=("out_ref", "lse_ref"),
        out_dtypes=("input", "f32"),
        init_guard="is_first",
        flush_guard="is_last",
        init_binding="p_idx == 0",
        flush_binding="num_pages_grid - 1",
        group_inner=None,
    ),
    "_paged_decode_spec_kernel": dict(
        wrapper="_paged_decode_spec_pallas",
        scratch=("m_scr", "l_scr", "acc_scr"),
        outputs=("out_ref", "lse_ref"),
        out_dtypes=("input", "f32"),
        init_guard="is_first",
        flush_guard="is_last",
        init_binding="p_idx == 0",
        flush_binding="num_pages_grid - 1",
        group_inner=None,
    ),
    "_paged_decode_int8_kernel": dict(
        wrapper="_paged_decode_int8_pallas",
        scratch=("m_scr", "l_scr", "acc_scr"),
        outputs=("out_ref", "lse_ref"),
        out_dtypes=("input", "f32"),
        init_guard="is_first",
        flush_guard="is_last",
        init_binding="p_idx == 0",
        flush_binding="num_pages_grid - 1",
        group_inner=None,
    ),
}
