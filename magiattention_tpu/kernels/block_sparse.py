"""Gather-free block-sparse FFA kernel for the NSA selected branch.

The NSA baseline (:mod:`..parallel.nsa`) picks ``slc_top_k`` KV blocks per
(kv-head, q-block) and then *materializes* them with ``jnp.take_along_axis``
followed by a dense, non-online softmax — full HBM gather traffic plus O(L)
logits memory. This kernel attends straight out of the resident K/V instead:
the per-(kv-head, q-block) block index table rides as scalar prefetch and the
K/V ``BlockSpec`` index maps read it directly, so each grid step DMAs exactly
one selected chunk in place (the ``paged_decode.py`` page-table idiom — FSA's
"selected branch as a first-class sparse kernel", PAPERS.md arXiv:2508.18224).

Design notes (shared idiom with ``ffa.py`` / ``paged_decode.py`` — same
online-softmax algebra, same Mosaic compatibility rules):

- the selected-block space is re-tiled into **chunks** of ``d_stride`` rows:
  NSA blocks overlap when ``d_stride < block_len`` (stride-``d`` sliding
  windows), but their *starts* are stride-aligned, so every selected block is
  exactly ``block_len // d_stride`` consecutive chunks. Chunking makes the
  streamed unit uniform; duplicate chunks in a row's list reproduce the
  gathered reference's duplicated softmax mass term for term.
- grid ``(hk, n_qb, n_chunk_steps)`` with the chunk axis innermost and
  ``arbitrary``: all chunks of one (head, q-block) are consecutive grid steps
  accumulating into f32 m/l/acc VMEM scratch; the output tile is written once
  at the end of the run (the FFA run-ordering contract, rule K2).
- blocks produced by ``nsa._block_layout`` lie fully inside their segment, so
  no length mask is needed in-kernel and no row can be empty (every q row
  attends ``top_k * block_len`` live keys). The LSE output merges with the
  cmp/win branches via the existing host-side LSE-merge.
- backward is a fused one-pass custom_vjp: **dq** accumulates in VMEM scratch
  over the same chunk table and flushes once per (head, q-block); **dk/dv**
  use revisit-accumulation into *indexed* output windows — the PR 7 fused
  backward first-visit/last-visit discipline, except the first-visit flags
  come from a second scalar-prefetch array (a chunk may be selected by many
  q-blocks; its first visitor zero-inits the window, later visitors ``+=``)
  and no last-visit flush is needed (dv is unscaled; dk's ``ln2`` correction
  is a host-side multiply). The zero background rides as aliased inputs.
- q is pre-scaled by ``softmax_scale * log2(e)`` on the host and the softmax
  runs in the exp2 domain (the softcap-free fwd-kernel fast path).

This module is deliberately env-free (rule K5): the gather-free vs gathered
choice is a registry decision (``nsa_slc``) resolved in ``parallel/nsa.py``.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .ffa import (
    _CompilerParams,
    _lane_tile,
    _should_interpret,
    EMPTY_THRESH,
    LN2,
    LOG2E,
    MASK_VALUE,
    NEG_INF,
    NUM_LANES,
)

__all__ = [
    "block_sparse_attn",
    "first_visit_flags",
    "modeled_slc_bytes",
    "validate_block_table",
    "PALLAS_CONTRACTS",
]


def _bsp_fwd_kernel(
    tbl_ref,
    q_ref,
    k_ref,
    v_ref,
    out_ref,
    lse_ref,
    m_scr,
    l_scr,
    acc_scr,
    *,
    ds: int,
):
    c_idx = pl.program_id(2)
    num_chunks_grid = pl.num_programs(2)
    is_first = jnp.int32(c_idx == 0)
    is_last = jnp.int32(c_idx == num_chunks_grid - 1)

    @pl.when(is_first == 1)
    def _():
        m_scr[:] = jnp.full_like(m_scr, MASK_VALUE)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0, 0]  # (r, d), pre-scaled by softmax_scale * log2e
    k = k_ref[0, :, 0, :]  # (ds, d)
    v = v_ref[0, :, 0, :]  # (ds, dv)

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (r, ds) — every chunk row is live (blocks lie inside their segment)

    m_prev = m_scr[...]  # (r, NUM_LANES)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1)[:, None])
    p = jnp.exp2(s - _lane_tile(m_new, ds))
    alpha = jnp.exp2(m_prev - m_new)
    l_scr[:] = l_scr[...] * alpha + jnp.sum(p, axis=1)[:, None]
    pv = jax.lax.dot_general(
        p.astype(v_ref.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_scr[:] = acc_scr[:] * _lane_tile(alpha, acc_scr.shape[-1]) + pv
    m_scr[:] = m_new

    @pl.when(is_last == 1)
    def _():
        m = m_scr[...]
        l = l_scr[...]
        empty = m <= EMPTY_THRESH
        l_safe = jnp.where(empty | (l == 0.0), 1.0, l)
        o = acc_scr[:] / _lane_tile(l_safe, acc_scr.shape[-1])
        o = jnp.where(_lane_tile(empty, o.shape[-1]), 0.0, o)
        out_ref[0, 0] = o.astype(out_ref.dtype)
        lse_ref[0, 0] = jnp.where(
            empty, MASK_VALUE, (m + jnp.log2(l_safe)) * LN2
        ).astype(jnp.float32)


def _bsp_fwd_pallas(chunk_tbl, q_r, k_c, v_c, scale: float, interpret: bool):
    """q_r: ``(hk, n_qb, r, d)`` UNscaled; k/v_c ``(n_chunks, ds, hk, *)``;
    chunk_tbl ``(hk, n_qb, C)`` int32 chunk indices, every entry in-range.

    Returns (out ``(hk, n_qb, r, dv)`` q dtype, lse ``(hk, n_qb, r,
    NUM_LANES)`` fp32 natural-log, MASK_VALUE flags on empty rows).
    """
    hk, n_qb, r, d = q_r.shape
    n_chunks, ds, _, dv = v_c.shape
    C = chunk_tbl.shape[2]
    q_r = (q_r.astype(jnp.float32) * (scale * LOG2E)).astype(q_r.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(hk, n_qb, C),
        in_specs=[
            pl.BlockSpec(
                (1, 1, r, d),
                lambda h, b, c, tbl: (h, b, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ds, 1, d),
                lambda h, b, c, tbl: (tbl[h, b, c], 0, h, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ds, 1, dv),
                lambda h, b, c, tbl: (tbl[h, b, c], 0, h, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, r, dv),
                lambda h, b, c, tbl: (h, b, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, r, NUM_LANES),
                lambda h, b, c, tbl: (h, b, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        scratch_shapes=[
            pltpu.VMEM((r, NUM_LANES), jnp.float32),
            pltpu.VMEM((r, NUM_LANES), jnp.float32),
            pltpu.VMEM((r, dv), jnp.float32),
        ],
    )
    kernel = partial(_bsp_fwd_kernel, ds=ds)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((hk, n_qb, r, dv), q_r.dtype),
            jax.ShapeDtypeStruct((hk, n_qb, r, NUM_LANES), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * hk * n_qb * C * r * ds * (d + dv),
            bytes_accessed=(
                q_r.size * q_r.dtype.itemsize
                + hk * n_qb * C * ds * (d + dv) * k_c.dtype.itemsize
                + hk * n_qb * r * dv * q_r.dtype.itemsize
            ),
            transcendentals=hk * n_qb * C * r * ds,
        ),
    )(chunk_tbl, q_r, k_c, v_c)
    return out, lse


def _bsp_bwd_kernel(
    tbl_ref,
    fvis_ref,
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    dkz_ref,
    dvz_ref,
    dq_ref,
    dk_ref,
    dv_ref,
    dq_scr,
    *,
    scale: float,
):
    h_idx = pl.program_id(0)
    b_idx = pl.program_id(1)
    c_idx = pl.program_id(2)
    num_chunks_grid = pl.num_programs(2)
    is_first = jnp.int32(c_idx == 0)
    is_last = jnp.int32(c_idx == num_chunks_grid - 1)
    del dkz_ref, dvz_ref  # aliased zero background only; never read in-kernel

    # first-visit flag for the (head, chunk) window this step accumulates
    # into: 1 exactly on the earliest grid step (in b-major, c-minor visit
    # order) that maps onto this chunk for this head
    fvis = fvis_ref[h_idx, b_idx, c_idx]

    @pl.when(is_first == 1)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(fvis == 1)
    def _():
        dk_ref[0, :, 0] = jnp.zeros(dk_ref.shape[1:2] + dk_ref.shape[3:],
                                    jnp.float32)
        dv_ref[0, :, 0] = jnp.zeros(dv_ref.shape[1:2] + dv_ref.shape[3:],
                                    jnp.float32)

    q = q_ref[0, 0]  # (r, d), pre-scaled by softmax_scale * log2e
    k = k_ref[0, :, 0, :]  # (ds, d)
    v = v_ref[0, :, 0, :]  # (ds, dv)
    do_blk = do_ref[0, 0]  # (r, dv)
    # lse is stored in natural log; the recompute runs in the exp2 domain
    lse2 = lse_ref[0, 0][:, :1] * LOG2E  # (r, 1)
    delta_c = delta_ref[0, 0][:, :1]  # (r, 1)

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (r, ds) exp2-domain logits
    p = jnp.exp2(s - lse2)  # exact softmax weights (no running max needed)

    dv_ref[0, :, 0] += jax.lax.dot_general(
        p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (ds, dv)

    dp = jax.lax.dot_general(
        do_blk, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (r, ds)
    ds_mat = p * (dp - delta_c)

    # dk accumulates against the PRE-scaled q: the extra scale*log2e factor
    # is corrected on the host by a single * ln2 (ln2 * log2e == 1, leaving
    # exactly the softmax_scale the math wants) — the ffa fused-bwd algebra
    dk_ref[0, :, 0] += jax.lax.dot_general(
        ds_mat.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (ds, d)

    dq_scr[:] += jax.lax.dot_general(
        ds_mat.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (r, d) against UNscaled k; the flush applies softmax_scale

    @pl.when(is_last == 1)
    def _():
        dq_ref[0, 0] = (dq_scr[:] * scale).astype(jnp.float32)


def _bsp_bwd_pallas(chunk_tbl, q_r, k_c, v_c, do_r, lse_r, delta_r,
                    scale: float, interpret: bool):
    """Fused one-pass backward over the same chunk table as the forward.

    q_r UNscaled ``(hk, n_qb, r, d)``; do_r ``(hk, n_qb, r, dv)``; lse_r /
    delta_r ``(hk, n_qb, r, NUM_LANES)`` fp32 (lane-broadcast). Returns
    (dq ``(hk, n_qb, r, d)``, dk ``(n_chunks, ds, hk, d)``, dv
    ``(n_chunks, ds, hk, dv)``), all fp32.
    """
    hk, n_qb, r, d = q_r.shape
    n_chunks, ds, _, dv = v_c.shape
    C = chunk_tbl.shape[2]
    q_r = (q_r.astype(jnp.float32) * (scale * LOG2E)).astype(q_r.dtype)
    fvis = first_visit_flags(chunk_tbl, n_chunks)

    # zero background for the revisit-accumulated dk/dv windows: donated to
    # the outputs via input_output_aliases, fetched by a CONSTANT index map
    # (never streamed per step, never read in-kernel)
    dkz = jnp.zeros((n_chunks, ds, hk, d), jnp.float32)
    dvz = jnp.zeros((n_chunks, ds, hk, dv), jnp.float32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(hk, n_qb, C),
        in_specs=[
            pl.BlockSpec(
                (1, 1, r, d),
                lambda h, b, c, tbl, fv: (h, b, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ds, 1, d),
                lambda h, b, c, tbl, fv: (tbl[h, b, c], 0, h, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ds, 1, dv),
                lambda h, b, c, tbl, fv: (tbl[h, b, c], 0, h, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, r, dv),
                lambda h, b, c, tbl, fv: (h, b, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, r, NUM_LANES),
                lambda h, b, c, tbl, fv: (h, b, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, r, NUM_LANES),
                lambda h, b, c, tbl, fv: (h, b, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ds, 1, d),
                lambda h, b, c, tbl, fv: (0, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ds, 1, dv),
                lambda h, b, c, tbl, fv: (0, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, r, d),
                lambda h, b, c, tbl, fv: (h, b, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ds, 1, d),
                lambda h, b, c, tbl, fv: (tbl[h, b, c], 0, h, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ds, 1, dv),
                lambda h, b, c, tbl, fv: (tbl[h, b, c], 0, h, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        scratch_shapes=[
            pltpu.VMEM((r, d), jnp.float32),
        ],
    )
    kernel = partial(_bsp_bwd_kernel, scale=scale)
    dq, dk, dv_out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((hk, n_qb, r, d), jnp.float32),
            jax.ShapeDtypeStruct((n_chunks, ds, hk, d), jnp.float32),
            jax.ShapeDtypeStruct((n_chunks, ds, hk, dv), jnp.float32),
        ],
        interpret=interpret,
        # operands 8/9 (dkz/dvz, counting the 2 scalar-prefetch args) donate
        # their zeroed buffers to outputs 1/2 (dk/dv)
        input_output_aliases={8: 1, 9: 2},
        compiler_params=_CompilerParams(
            # the chunk axis must be sequential (scratch accumulation) AND
            # the q-block axis too: dk/dv windows are revisited across
            # q-blocks of the same head, in b-major grid order
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=8 * hk * n_qb * C * r * ds * (d + dv) // 2,
            bytes_accessed=(
                2 * q_r.size * q_r.dtype.itemsize
                + 3 * hk * n_qb * C * ds * (d + dv) * k_c.dtype.itemsize
            ),
            transcendentals=hk * n_qb * C * r * ds,
        ),
    )(chunk_tbl, fvis, q_r, k_c, v_c, do_r, lse_r, delta_r, dkz, dvz)
    # the kernel accumulated ds^T @ (q * scale * log2e); * ln2 leaves scale
    dk = dk * LN2
    return dq, dk, dv_out


def first_visit_flags(chunk_tbl: jax.Array, n_chunks: int) -> jax.Array:
    """Per-head first-visit flags for the backward's revisit windows.

    For each kv head, grid steps visit chunk-table entries in row-major
    ``(q_block, slot)`` order; entry (b, c) is flagged 1 iff it is the FIRST
    step whose index map lands on its chunk. Works on traced tables (the
    table may come from an in-graph top-k); shape ``(hk, n_qb, C)`` int32.
    """
    hk, n_qb, C = chunk_tbl.shape

    def per_head(tbl_h):
        e = tbl_h.reshape(-1).astype(jnp.int32)  # (n_qb * C,)
        pos = jnp.arange(e.shape[0], dtype=jnp.int32)
        big = jnp.int32(e.shape[0])
        first = jnp.full((n_chunks,), big, jnp.int32).at[e].min(pos)
        return (first[e] == pos).astype(jnp.int32).reshape(n_qb, C)

    return jax.vmap(per_head)(chunk_tbl)


def validate_block_table(block_idx: np.ndarray, n_blocks: int) -> None:
    """R5-style index-table audit (host, concrete tables only): every
    prefetched block index must be in-range and each (kv-head, q-block)
    row's top-k picks must be pairwise distinct — a duplicate would double
    that block's softmax mass silently."""
    tbl = np.asarray(block_idx)
    if tbl.size == 0:
        raise ValueError("block_idx is empty")
    if tbl.min() < 0 or tbl.max() >= n_blocks:
        raise ValueError(
            f"block_idx out of range: min={tbl.min()} max={tbl.max()} "
            f"valid=[0, {n_blocks})"
        )
    srt = np.sort(tbl, axis=-1)
    if (srt[..., 1:] == srt[..., :-1]).any():
        raise ValueError(
            "block_idx has duplicate block picks within a "
            "(kv-head, q-block) row"
        )


def modeled_slc_bytes(
    *,
    hk: int,
    n_qb: int,
    top_k: int,
    block_len: int,
    d_stride: int,
    block_size_q: int,
    g: int,
    d: int,
    dv: int,
    itemsize: int,
) -> dict:
    """Modeled HBM bytes for the slc branch: gather-free streaming vs the
    gathered-dense reference. The gathered path pays the streamed traffic
    PLUS a write+read round trip of the materialized ``take_along_axis``
    K/V selections (``top_k * block_len`` rows per (head, q-block))."""
    r = block_size_q * g
    C = top_k * (block_len // d_stride)
    q_bytes = hk * n_qb * r * d * itemsize
    out_bytes = hk * n_qb * r * dv * itemsize
    streamed_kv = hk * n_qb * C * d_stride * (d + dv) * itemsize
    streamed = q_bytes + out_bytes + streamed_kv
    gathered = streamed + 2 * hk * n_qb * top_k * block_len * (d + dv) * itemsize
    return {"streamed_bytes": streamed, "gathered_bytes": gathered}


@dataclass(frozen=True, eq=False)
class BSPParams:
    """Static kernel parameters (hashable by identity for custom_vjp)."""

    softmax_scale: float
    interpret: bool


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def _bsp_core(q_r, k_c, v_c, chunk_tbl, params: BSPParams):
    out, lse = _bsp_fwd_pallas(
        chunk_tbl, q_r, k_c, v_c, params.softmax_scale, params.interpret
    )
    return out, lse


def _bsp_core_fwd(q_r, k_c, v_c, chunk_tbl, params: BSPParams):
    out, lse = _bsp_fwd_pallas(
        chunk_tbl, q_r, k_c, v_c, params.softmax_scale, params.interpret
    )
    return (out, lse), (q_r, k_c, v_c, chunk_tbl, out, lse)


def _bsp_core_bwd(params: BSPParams, res, cts):
    do, _ = cts  # lse cotangent discarded (lse feeds merges, not losses)
    q_r, k_c, v_c, chunk_tbl, out, lse = res
    delta = jnp.sum(
        out.astype(jnp.float32) * do.astype(jnp.float32), axis=-1
    )  # (hk, n_qb, r)
    delta_r = jnp.broadcast_to(delta[..., None], lse.shape).astype(jnp.float32)
    dq, dk, dv = _bsp_bwd_pallas(
        chunk_tbl, q_r, k_c, v_c, do.astype(q_r.dtype), lse, delta_r,
        params.softmax_scale, params.interpret,
    )
    return (
        dq.astype(q_r.dtype),
        dk.astype(k_c.dtype),
        dv.astype(v_c.dtype),
        None,  # int chunk table: no cotangent
    )


_bsp_core.defvjp(_bsp_core_fwd, _bsp_core_bwd)


def block_sparse_attn(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_idx: jax.Array,
    block_starts,
    *,
    block_len: int,
    block_size_q: int,
    d_stride: int | None = None,
    softmax_scale: float | None = None,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Gather-free block-sparse attention over a top-k block index table.

    Each q-block of ``block_size_q`` rows attends, per kv head, exactly the
    KV blocks named by its ``block_idx`` row — streamed from HBM in place
    through the prefetched chunk table, never gathered.

    Args:
        q: ``(S, hq, d)``; k: ``(S, hk, d)``; v: ``(S, hk, dv)``.
        block_idx: ``(hk, n_qb, top_k)`` int — selected block ids per
            (kv-head, q-block). May be traced (in-graph top-k); concrete
            tables are audited (in-range + per-row deduplicated).
        block_starts: ``(n_blocks,)`` int row offsets of each selectable
            block (``nsa._block_layout`` starts); every start must be
            ``d_stride``-aligned and every block fully inside its segment.
        block_len: rows per selectable block; ``d_stride`` (default
            ``block_len``) is the block-start stride — blocks overlap when
            it is smaller, and it is the streamed-chunk granularity.
        block_size_q: q rows per table row; must divide ``S``.
        softmax_scale: defaults to ``d ** -0.5``.
        interpret: force/deny Pallas interpret mode (defaults to the shared
            env/backend heuristic).

    Returns:
        (out ``(S, hq, dv)`` in q's dtype, lse ``(S, hq)`` fp32 natural-log,
        ``-inf`` on never-attending rows — none exist for valid tables).
    """
    S, hq, d = q.shape
    _, hk, dv = v.shape
    if hq % hk:
        raise ValueError(f"hq={hq} not a multiple of kv heads hk={hk}")
    if d_stride is None:
        d_stride = block_len
    ds = int(d_stride)
    if block_len % ds:
        raise ValueError(f"block_len={block_len} not a multiple of {ds=}")
    if S % ds:
        raise ValueError(f"S={S} not a multiple of d_stride={ds}")
    if S % block_size_q:
        raise ValueError(f"S={S} not a multiple of {block_size_q=}")
    if not (ds <= NUM_LANES or ds % NUM_LANES == 0):
        raise ValueError(
            f"d_stride={ds} must be <= {NUM_LANES} or a multiple of it "
            f"(lane-tiling rule shared with ffa.default_blocks)"
        )
    g = hq // hk
    n_qb = S // block_size_q
    n_chunks = S // ds
    alpha = block_len // ds
    if softmax_scale is None:
        softmax_scale = float(d) ** -0.5
    if interpret is None:
        interpret = _should_interpret()

    starts_arr = block_starts
    if not isinstance(block_idx, jax.core.Tracer):
        n_blocks = int(np.asarray(starts_arr).shape[0])
        validate_block_table(np.asarray(block_idx), n_blocks)
    if not isinstance(starts_arr, jax.core.Tracer):
        starts_np = np.asarray(starts_arr)
        if (starts_np % ds).any():
            raise ValueError(
                f"block_starts must be d_stride={ds} aligned"
            )
        if starts_np.size and int(starts_np.max()) + block_len > S:
            raise ValueError("a block extends past the sequence end")

    starts = jnp.asarray(starts_arr, jnp.int32)
    ctbl = (
        (starts // ds)[block_idx][..., None]
        + jnp.arange(alpha, dtype=jnp.int32)
    ).reshape(hk, n_qb, -1).astype(jnp.int32)

    # (S, hq, d) -> (hk, n_qb, bq*g, d): q heads [h*g, (h+1)*g) share kv
    # head h (nsa's `reshape(S, hk, g, dh)` grouping); within a tile, row
    # q_row * g + gi
    q_r = (
        q.reshape(n_qb, block_size_q, hk, g, d)
        .transpose(2, 0, 1, 3, 4)
        .reshape(hk, n_qb, block_size_q * g, d)
    )
    k_c = k.reshape(n_chunks, ds, hk, d)
    v_c = v.reshape(n_chunks, ds, hk, dv)

    params = BSPParams(softmax_scale=float(softmax_scale),
                       interpret=bool(interpret))
    out_r, lse_r = _bsp_core(q_r, k_c, v_c, ctbl, params)

    out = (
        out_r.reshape(hk, n_qb, block_size_q, g, dv)
        .transpose(1, 2, 0, 3, 4)
        .reshape(S, hq, dv)
    )
    lse_raw = (
        lse_r[..., 0]
        .reshape(hk, n_qb, block_size_q, g)
        .transpose(1, 2, 0, 3)
        .reshape(S, hq)
    )
    lse = jnp.where(lse_raw <= EMPTY_THRESH, NEG_INF, lse_raw)
    return out, lse


# Static kernel-contract declarations consumed by analysis/kernel_check
# (K2/K4 source rules + K1/K3/K4 capture checks). The chunk-axis guards bind
# from pl.program_id; the backward's dk/dv windows are revisit-accumulated
# (scatter targets indexed by the chunk table) with first-visit init bound
# from the fvis scalar-prefetch array and NO flush (dv is exact as
# accumulated; dk's ln2 correction is a host-side multiply).
PALLAS_CONTRACTS: dict = {
    "_bsp_fwd_kernel": dict(
        wrapper="_bsp_fwd_pallas",
        scratch=("m_scr", "l_scr", "acc_scr"),
        outputs=("out_ref", "lse_ref"),
        out_dtypes=("input", "f32"),
        init_guard="is_first",
        flush_guard="is_last",
        init_binding="c_idx == 0",
        flush_binding="num_chunks_grid - 1",
        group_inner=None,
    ),
    "_bsp_bwd_kernel": dict(
        wrapper="_bsp_bwd_pallas",
        scratch=("dq_scr",),
        outputs=("dq_ref", "dk_ref", "dv_ref"),
        out_dtypes=("f32", "f32", "f32"),
        init_guard="is_first",
        flush_guard="is_last",
        init_binding="c_idx == 0",
        flush_binding="num_chunks_grid - 1",
        group_inner=None,
        revisit=[
            dict(out="dk_ref", init_guard="fvis", init_binding="fvis_ref",
                 flush_guard=None),
            dict(out="dv_ref", init_guard="fvis", init_binding="fvis_ref",
                 flush_guard=None),
        ],
    ),
}
