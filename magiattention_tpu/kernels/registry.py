"""Unified attention-backend registry: ONE selection point for every
kernel-choice decision in the package.

Call sites that used to read env flags directly (``ffa_bwd_mode``'s
``MAGI_ATTENTION_FFA_FUSED_BWD``, ``choose_mixed_dispatch``'s
``MAGI_ATTENTION_FFA_MIXED_BLOCKS``, ``decode_attn_step``'s
``MAGI_ATTENTION_SERVE_DECODE_KERNEL``, ``DistAttnRuntime.backend``'s
``MAGI_ATTENTION_KERNEL_BACKEND``) now resolve through
:func:`resolve`, with precedence:

1. **pin** — an explicit env-derived choice (env/backend.py getters map
   both the new ``MAGI_ATTENTION_BACKEND_*`` keys and the legacy flags to
   pins). A pin bypasses every cache, is re-read per call (tests flip env
   vars mid-process), and is subject only to the call site's *feasibility*
   guards (VMEM, plan meta layout) — exactly the legacy flag semantics.
2. **cached decision** — the in-process memo, then the persistent policy
   store (telemetry/store.py): a prior resolution persisted across
   restarts, or the fastest backend with enough ``ok`` measurements in
   history (``measured``). Both are gated on ``store_active()`` at *use*
   time, so flipping telemetry off mid-process also stops store-sourced
   decisions from applying — with the observatory off, resolution is
   bit-identical to the legacy heuristics.
3. **heuristic** — the call site's legacy default (cost model or constant),
   run at most once per key (memoized + persisted when the store is on).
   Each heuristic run counts as one *tuning decision*
   (``stats()["heuristic_calls"]``); a warm policy cache makes zero.

Rank-ordered backend registrations double as the resilience ladders:
``ladder("serve_decode")`` is the decode fallback order and
``ladder("calc_attn")[-1]`` is the reference rung the resilience module
descends to (resilience/fallback.py).

MAGI-L002: no clocks here — measurements enter via the telemetry store,
never from this module. MAGI-L001: env access only through typed getters.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable

from .. import telemetry
from ..env import backend as env_backend
from ..env import kernel as env_kernel

# sources a resolution can come from; STORE_SOURCES only apply while the
# store is active (checked on every memo hit, so a stale store-sourced memo
# can never leak into a telemetry-off run)
STORE_SOURCES = ("policy", "measured")


@dataclass(frozen=True)
class BackendChoice:
    name: str
    source: str  # "pin" | "policy" | "measured" | "heuristic"


def _memo_key(key: Any) -> Any:
    """Hashable form of a decision key. Dict keys (the calc_attn policy
    key) canonicalize to their sorted-JSON string; the ORIGINAL key is
    still what store lookups join on, so the on-disk form matches what
    ingest_event writes."""
    try:
        hash(key)
        return key
    except TypeError:
        from ..telemetry.store import canonical_key

        return canonical_key(key)


# decision -> [(rank, name, description)], rank order = ladder order
_BACKENDS: dict[str, list[tuple[int, str, str]]] = {}


def register_backend(
    decision: str, name: str, rank: int, description: str = ""
) -> None:
    """Register a backend for a decision. Rank orders the fallback ladder
    (0 = preferred / fastest, last = most conservative reference)."""
    entries = _BACKENDS.setdefault(decision, [])
    entries[:] = [e for e in entries if e[1] != name]
    entries.append((rank, name, description))
    entries.sort()


def decisions() -> tuple[str, ...]:
    return tuple(sorted(_BACKENDS))


def backends_for(decision: str) -> tuple[str, ...]:
    return tuple(name for _, name, _ in _BACKENDS.get(decision, ()))


def ladder(decision: str, start: str | None = None) -> tuple[str, ...]:
    """The rank-ordered fallback ladder for a decision, optionally starting
    at ``start`` (an unknown start returns the full ladder)."""
    names = backends_for(decision)
    if start in names:
        return names[names.index(start):]
    return names


class BackendRegistry:
    """In-process resolution cache + tuning stats (one global instance)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._memo: dict[tuple[str, Any], BackendChoice] = {}
        self._last: dict[str, tuple[Any, str]] = {}
        self._announced: set[tuple[str, Any, str]] = set()
        self.stats: dict[str, int] = {
            "resolves": 0,
            "pins": 0,
            "memo_hits": 0,
            "store_hits": 0,
            "heuristic_calls": 0,
        }

    def _announce(self, decision: str, key: Any, choice: BackendChoice) -> None:
        """One ``backend_select`` telemetry record per (decision, key,
        choice) — selection provenance without per-step record spam."""
        if not telemetry.enabled():
            return
        tag = (decision, _memo_key(key), choice.name)
        with self._lock:
            if tag in self._announced:
                return
            self._announced.add(tag)
        telemetry.record_event(
            "backend_select",
            decision=decision,
            key=list(key) if isinstance(key, tuple) else key,
            choice=choice.name,
            source=choice.source,
        )

    def resolve(
        self,
        decision: str,
        key: Any,
        heuristic: Callable[[], str],
        pin: str | None = None,
    ) -> BackendChoice:
        with self._lock:
            self.stats["resolves"] += 1
        if pin is not None:
            choice = BackendChoice(pin, "pin")
            with self._lock:
                self.stats["pins"] += 1
                self._last[decision] = (key, pin)
            self._announce(decision, key, choice)
            return choice

        ck = (decision, _memo_key(key))
        with self._lock:
            hit = self._memo.get(ck)
        if hit is not None:
            usable = hit.source not in STORE_SOURCES or _store_gate()
            if usable:
                with self._lock:
                    self.stats["memo_hits"] += 1
                    self._last[decision] = (key, hit.name)
                return hit

        choice: BackendChoice | None = None
        if _store_gate():
            from ..telemetry import store as _tstore

            persisted = _tstore.policy_lookup(decision, key)
            if persisted is not None and (
                not backends_for(decision)
                or persisted["choice"] in backends_for(decision)
            ):
                choice = BackendChoice(persisted["choice"], "policy")
            else:
                best = _tstore.measured_best(decision, key)
                if best is not None and (
                    not backends_for(decision)
                    or best in backends_for(decision)
                ):
                    choice = BackendChoice(best, "measured")
                    _tstore.policy_record(decision, key, best, "measured")
            if choice is not None:
                with self._lock:
                    self.stats["store_hits"] += 1

        if choice is None:
            name = heuristic()
            choice = BackendChoice(name, "heuristic")
            with self._lock:
                self.stats["heuristic_calls"] += 1
            if _store_gate():
                from ..telemetry import store as _tstore

                _tstore.policy_record(decision, key, name, "heuristic")

        with self._lock:
            self._memo[ck] = choice
            self._last[decision] = (key, choice.name)
        self._announce(decision, key, choice)
        return choice

    def last(self, decision: str) -> tuple[Any, str] | None:
        with self._lock:
            return self._last.get(decision)


def _store_gate() -> bool:
    """Is the persistent policy store allowed to influence resolution
    *right now*? Lazy import keeps telemetry fully out of the picture for
    processes that never enable it."""
    from ..telemetry import store as _tstore

    return _tstore.store_active()


_registry: BackendRegistry | None = None
_registry_lock = threading.Lock()


def get_registry() -> BackendRegistry:
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = BackendRegistry()
        return _registry


def reset_registry() -> None:
    """Drop the in-process resolution cache + stats (tests)."""
    global _registry
    with _registry_lock:
        _registry = None


def resolve(
    decision: str,
    key: Any,
    heuristic: Callable[[], str],
    pin: str | None = None,
) -> BackendChoice:
    return get_registry().resolve(decision, key, heuristic, pin=pin)


def stats() -> dict[str, int]:
    return dict(get_registry().stats)


def last_choice(decision: str) -> str | None:
    last = get_registry().last(decision)
    return None if last is None else last[1]


def last_key(decision: str) -> Any | None:
    last = get_registry().last(decision)
    return None if last is None else last[0]


# -- call-site conveniences (the env reads kernel code used to do) ----------


def calc_attn_backend(key: Any = ()) -> str:
    """The attention backend for a runtime/step: explicit
    MAGI_ATTENTION_KERNEL_BACKEND pins it; otherwise the policy cache /
    measured history / the 'ffa' default decide."""
    return resolve(
        "calc_attn", key, lambda: "ffa",
        pin=env_backend.kernel_backend_pin(),
    ).name


def nsa_slc_backend(key: Any = ()) -> str:
    """The NSA selected-block branch for a shape: explicit
    MAGI_ATTENTION_BACKEND_NSA_SLC pins it; otherwise the policy cache /
    measured history / the gather-free kernel default decide."""
    return resolve(
        "nsa_slc", key, lambda: "block_sparse_pallas",
        pin=env_backend.nsa_slc_pin(),
    ).name


def tiles_pinned() -> bool:
    """Explicit FFA block settings present (env FFA_BLOCK_Q/K): auto-tile
    and mixed dispatch must stand down — explicit settings always win."""
    return env_kernel.ffa_blocks_pinned()


def gqa_pack_variant(kind: str) -> str:
    """'gqa_packed' | 'plain' for the fwd / bwd-dq / bwd-dkv kernels. The
    pack flags are explicit opt-ins, so these decisions are always pinned;
    the call site's VMEM-residency guard still applies on top."""
    if kind == "fwd":
        flag = env_kernel.ffa_gqa_pack()
        decision = "ffa_fwd"
    elif kind == "dq":
        flag = env_kernel.ffa_gqa_pack_dq()
        decision = "ffa_bwd_dq"
    elif kind == "dkv":
        flag = env_kernel.ffa_gqa_pack_dkv()
        decision = "ffa_bwd_dkv"
    else:
        raise ValueError(f"unknown gqa pack kind: {kind!r}")
    return resolve(
        decision, (), lambda: "plain",
        pin="gqa_packed" if flag else "plain",
    ).name


def extent_clamp_enabled() -> bool:
    """Lowering variant of the FFA kernel bodies: extent-clamped chunked
    dots vs the legacy single-dot bodies."""
    return (
        resolve(
            "ffa_lowering", (), lambda: "clamped",
            pin="clamped" if env_kernel.ffa_extent_clamp() else "single_dot",
        ).name
        == "clamped"
    )


# -- backend registrations --------------------------------------------------

register_backend(
    "calc_attn", "ffa", 0, "Pallas flex-flash-attention (default)")
register_backend(
    "calc_attn", "sdpa", 1, "XLA dense reference")
register_backend(
    "calc_attn", "sdpa_online", 2,
    "streamed dense reference — resilience ladder's last rung")
register_backend("ffa_fwd", "plain", 0, "per-head fwd kernel")
register_backend(
    "ffa_fwd", "gqa_packed", 1, "grouped-head packed fwd kernel")
register_backend("ffa_bwd", "fused", 0, "one-pass fused dq/dk/dv")
register_backend(
    "ffa_bwd", "split", 1, "split dq + dkv passes — fused's fallback rung")
register_backend("ffa_bwd_dq", "plain", 0, "per-head dq kernel")
register_backend("ffa_bwd_dq", "gqa_packed", 1, "packed dq kernel")
register_backend("ffa_bwd_dkv", "gqa_packed", 0, "packed dkv (default on)")
register_backend("ffa_bwd_dkv", "plain", 1, "per-head dkv kernel")
register_backend(
    "ffa_dispatch", "mixed", 0, "coarse+fine two-pass LSE-merged dispatch")
register_backend("ffa_dispatch", "single", 1, "one plan, one tiling")
register_backend(
    "ffa_lowering", "clamped", 0, "extent-clamped chunked-dot bodies")
register_backend(
    "ffa_lowering", "single_dot", 1, "legacy full-tile dot bodies")
register_backend(
    "serve_decode", "paged_decode_sharded", 0,
    "paged-decode kernel shard_mapped over kv heads (one launch per shard)")
register_backend(
    "serve_decode", "paged_decode_spec", 1,
    "multi-token speculative-verify kernel (spec_k draft rows per q tile)")
register_backend(
    "serve_decode", "paged_decode_int8", 2,
    "int8-KV paged-decode kernel (per-page scales, dequant in-kernel)")
register_backend(
    "serve_decode", "paged_decode", 3, "Pallas ragged paged-decode kernel")
register_backend(
    "serve_decode", "gather_ffa", 4, "per-slot gather+FFA reference")
register_backend(
    "serve_decode", "dense", 5, "dense jnp softmax — last resort")
register_backend(
    "nsa_slc", "block_sparse_pallas", 0,
    "gather-free Pallas block-sparse slc kernel")
register_backend(
    "nsa_slc", "gathered_dense", 1,
    "take_along_axis + dense softmax reference")

# which env keys pin each decision (new BACKEND_* key first, legacy key
# second) — provenance for reports and docs/env_variables.md
PIN_KEYS: dict[str, tuple[str, ...]] = {
    "calc_attn": ("MAGI_ATTENTION_KERNEL_BACKEND",),
    "ffa_bwd": (
        "MAGI_ATTENTION_BACKEND_FFA_BWD", "MAGI_ATTENTION_FFA_FUSED_BWD"),
    "ffa_dispatch": (
        "MAGI_ATTENTION_BACKEND_MIXED_BLOCKS",
        "MAGI_ATTENTION_FFA_MIXED_BLOCKS"),
    "serve_decode": (
        "MAGI_ATTENTION_BACKEND_SERVE_DECODE",
        "MAGI_ATTENTION_SERVE_DECODE_KERNEL"),
    "ffa_fwd": ("MAGI_ATTENTION_FFA_GQA_PACK",),
    "ffa_bwd_dq": ("MAGI_ATTENTION_FFA_GQA_PACK_DQ",),
    "ffa_bwd_dkv": ("MAGI_ATTENTION_FFA_GQA_PACK_DKV",),
    "ffa_lowering": ("MAGI_ATTENTION_FFA_EXTENT_CLAMP",),
    "nsa_slc": ("MAGI_ATTENTION_BACKEND_NSA_SLC",),
}
