"""Blockwise-online jnp SDPA backend (low-memory testing path).

Ref: magi_attention/functional/sdpa_online.py — replays the same AttnArg
contract with an online-softmax scan over key blocks; exercises exactly the
merge math the Pallas kernel and the CP lse-reduce use.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .mask_utils import build_dense_mask_band, types_to_bands

NEG_INF = float("-inf")


def sdpa_online_attn(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_ranges: jax.Array,
    k_ranges: jax.Array,
    attn_type_map: jax.Array | None = None,
    softmax_scale: float | None = None,
    softcap: float = 0.0,
    d_lo: jax.Array | None = None,
    d_hi: jax.Array | None = None,
    block_k: int = 512,
    compute_dtype=jnp.float32,
) -> tuple[jax.Array, jax.Array]:
    """Same contract as :func:`kernels.sdpa.sdpa_attn`, O(sq*block_k) memory."""
    sq, hq, d = q.shape
    sk, hk, dv = v.shape
    g = hq // hk
    if softmax_scale is None:
        softmax_scale = d ** -0.5
    if d_lo is None or d_hi is None:
        if attn_type_map is None:
            attn_type_map = jnp.zeros((q_ranges.shape[0],), dtype=jnp.int32)
        d_lo, d_hi = types_to_bands(q_ranges, k_ranges, attn_type_map)

    num_blocks = -(-sk // block_k)
    sk_pad = num_blocks * block_k

    qc = q.astype(compute_dtype)
    kc = jnp.repeat(k.astype(compute_dtype), g, axis=1)
    vc = jnp.repeat(v.astype(compute_dtype), g, axis=1)
    kc = jnp.pad(kc, ((0, sk_pad - sk), (0, 0), (0, 0)))
    vc = jnp.pad(vc, ((0, sk_pad - sk), (0, 0), (0, 0)))
    kc = kc.reshape(num_blocks, block_k, hq, d)
    vc = vc.reshape(num_blocks, block_k, hq, dv)

    def body(carry, blk):
        m, l, acc = carry  # [hq,sq], [hq,sq], [sq,hq,dv]
        kb, vb, blk_idx = blk
        logits = jnp.einsum("qhd,khd->hqk", qc, kb) * softmax_scale  # [hq,sq,bk]
        if softcap > 0.0:
            logits = softcap * jnp.tanh(logits / softcap)
        k_off = blk_idx * block_k
        mask = build_dense_mask_band(
            q_ranges, k_ranges, d_lo, d_hi, sq, block_k, k_offset=k_off
        )
        # padding cols beyond sk are masked automatically (k >= every k_range end)
        logits = jnp.where(mask[None], logits, NEG_INF)

        m_blk = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        alpha = jnp.exp(m - m_safe)  # 0 where m was -inf
        p = jnp.exp(logits - m_safe[..., None])
        p = jnp.where(mask[None], p, 0.0)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha.T[..., None] + jnp.einsum("hqk,khd->qhd", p, vb)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((hq, sq), NEG_INF, dtype=compute_dtype)
    l0 = jnp.zeros((hq, sq), dtype=compute_dtype)
    acc0 = jnp.zeros((sq, hq, dv), dtype=compute_dtype)
    (m, l, acc), _ = jax.lax.scan(
        body, (m0, l0, acc0), (kc, vc, jnp.arange(num_blocks, dtype=jnp.int32))
    )

    empty = l == 0.0
    lse = jnp.where(empty, NEG_INF, m + jnp.log(jnp.where(empty, 1.0, l)))
    out = acc / jnp.where(empty, 1.0, l).T[..., None]
    out = jnp.where(empty.T[..., None], 0.0, out)
    return out.astype(q.dtype), lse.T.astype(jnp.float32)
