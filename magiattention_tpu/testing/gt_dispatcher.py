"""Brute-force ground-truth dispatcher (ref: magi_attention/testing/gt_dispatcher.py:27).

Computes per-chunk self-attention areas by materializing the full mask —
O(S^2) memory, testing only — to validate the solver's closed-form areas.
"""

from __future__ import annotations

import numpy as np

from ..common.enum import AttnMaskType
from ..common.mask import AttnMask
from ..common.ranges import AttnRanges


class GroundTruthDispatcher:
    def __init__(
        self,
        q_ranges: AttnRanges,
        k_ranges: AttnRanges,
        attn_mask_type: list[AttnMaskType],
        total_seqlen: int,
    ) -> None:
        self.mask = AttnMask.from_ranges(
            q_ranges, k_ranges, attn_mask_type,
            total_seqlen_q=total_seqlen, total_seqlen_k=total_seqlen,
        ).mask_array
        self.total_seqlen = total_seqlen

    def chunk_areas(self, chunk_size: int) -> np.ndarray:
        n = -(-self.total_seqlen // chunk_size)
        return np.array(
            [
                int(self.mask[c * chunk_size : (c + 1) * chunk_size].sum())
                for c in range(n)
            ],
            dtype=np.int64,
        )

    def rank_areas(self, partitions: list[list[int]], chunk_size: int) -> list[int]:
        per_chunk = self.chunk_areas(chunk_size)
        return [int(sum(per_chunk[c] for c in p)) for p in partitions]
