"""Plan/kernel pre-warming (ref: magi_attention/testing/precompile.py).

The reference pre-JITs CUDA kernels before spawning distributed test
processes. The TPU analogue warms the two host caches that dominate first
-call latency — the FFA tile-plan LRU and jax's jit cache — for a list of
(mask, shape) configurations, so timed or distributed test bodies hit warm
caches.
"""

from __future__ import annotations

import numpy as np


def precompile_ffa(
    configs: list[dict],
    dtype=None,
) -> int:
    """Warm plan + jit caches for each config.

    Each config: ``{"q_ranges", "k_ranges", "attn_type_map", "seqlen_q",
    "seqlen_k", "num_heads_q", "num_heads_kv", "head_dim"}`` (ranges as
    (N, 2) arrays).

    Returns the number of configs warmed.
    """
    import jax.numpy as jnp

    from ..kernels.ffa import ffa_attn

    if dtype is None:
        dtype = jnp.float32
    n = 0
    for cfg in configs:
        sq, sk = cfg["seqlen_q"], cfg["seqlen_k"]
        hq = cfg.get("num_heads_q", 2)
        hk = cfg.get("num_heads_kv", 1)
        d = cfg.get("head_dim", 64)
        q = jnp.zeros((sq, hq, d), dtype)
        k = jnp.zeros((sk, hk, d), dtype)
        v = jnp.zeros((sk, hk, d), dtype)
        out, _ = ffa_attn(
            q, k, v,
            np.asarray(cfg["q_ranges"], np.int32),
            np.asarray(cfg["k_ranges"], np.int32),
            np.asarray(cfg.get("attn_type_map"), np.int32)
            if cfg.get("attn_type_map") is not None else None,
        )
        out.block_until_ready()
        n += 1
    return n
