"""Env-flag combination generator (ref: magi_attention/testing/flag_generator.py:25-330).

Iterates valid combinations of behavior-affecting env flags so CI covers the
flag matrix without exhaustive blowup. Strategies: constant (defaults only),
sequential (one flag varied at a time), random (seeded sampling), heuristic
(hand-picked high-risk combos).
"""

from __future__ import annotations

import random
from contextlib import contextmanager
from typing import Iterator

from ..env.general import scoped_env

# flag -> candidate values (None = unset)
FLAG_SPACE: dict[str, list[str | None]] = {
    "MAGI_ATTENTION_KERNEL_BACKEND": [None, "sdpa", "sdpa_online", "ffa"],
    "MAGI_ATTENTION_RANGE_MERGE": [None, "0", "1"],
    "MAGI_ATTENTION_FWD_HIGH_PRECISION_REDUCE": [None, "0", "1"],
    "MAGI_ATTENTION_BWD_HIGH_PRECISION_REDUCE": [None, "0", "1"],
    "MAGI_ATTENTION_CPP_BACKEND": [None, "0", "1"],
    "MAGI_ATTENTION_NATIVE_FFA_PLAN": [None, "0", "1"],
    "MAGI_ATTENTION_FFA_GQA_PACK": [None, "0", "1"],
    "MAGI_ATTENTION_FFA_GQA_PACK_DQ": [None, "0", "1"],
    "MAGI_ATTENTION_FFA_AUTO_TILE": [None, "0", "1"],
}

HEURISTIC_COMBOS: list[dict[str, str]] = [
    {"MAGI_ATTENTION_KERNEL_BACKEND": "sdpa",
     "MAGI_ATTENTION_CPP_BACKEND": "0"},
    {"MAGI_ATTENTION_KERNEL_BACKEND": "ffa",
     "MAGI_ATTENTION_FWD_HIGH_PRECISION_REDUCE": "0"},
    {"MAGI_ATTENTION_KERNEL_BACKEND": "ffa",
     "MAGI_ATTENTION_BWD_HIGH_PRECISION_REDUCE": "1"},
    {"MAGI_ATTENTION_KERNEL_BACKEND": "ffa",
     "MAGI_ATTENTION_NATIVE_FFA_PLAN": "0"},
    # both GQA packs + auto-tile through the full pipeline at once
    {"MAGI_ATTENTION_KERNEL_BACKEND": "ffa",
     "MAGI_ATTENTION_FFA_GQA_PACK": "1",
     "MAGI_ATTENTION_FFA_GQA_PACK_DQ": "1",
     "MAGI_ATTENTION_FFA_AUTO_TILE": "1"},
]


class FlagCombGenerator:
    """Yields flag dicts; apply with :func:`with_flags`."""

    def __init__(self, strategy: str = "heuristic", seed: int = 0,
                 max_combos: int = 8) -> None:
        self.strategy = strategy
        self.seed = seed
        self.max_combos = max_combos

    def __iter__(self) -> Iterator[dict[str, str | None]]:
        if self.strategy == "constant":
            yield {}
        elif self.strategy == "sequential":
            yield {}
            for flag, values in FLAG_SPACE.items():
                for v in values:
                    if v is not None:
                        yield {flag: v}
        elif self.strategy == "random":
            rng = random.Random(self.seed)
            for _ in range(self.max_combos):
                combo = {}
                for flag, values in FLAG_SPACE.items():
                    v = rng.choice(values)
                    if v is not None:
                        combo[flag] = v
                yield combo
        elif self.strategy == "heuristic":
            yield {}
            yield from HEURISTIC_COMBOS
        else:
            raise ValueError(f"unknown strategy {self.strategy}")


@contextmanager
def with_flags(combo: dict[str, str | None]):
    """Temporarily apply a flag combination via env.general.scoped_env
    (the one sanctioned environment mutation point)."""
    with scoped_env(combo):
        yield
