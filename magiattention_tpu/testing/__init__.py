"""Testing support: golden reference attention + precision asserts."""

from .precision import assert_close  # noqa: F401
from .precompile import precompile_ffa  # noqa: F401
from .ref_attn import ref_attn, ref_max_logits  # noqa: F401
from .template import assert_overlap_safe  # noqa: F401
