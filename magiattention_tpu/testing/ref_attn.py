"""Ground-truth attention (ref: magi_attention/testing/ref_attn.py:41-638).

A dense fp64 (fp32 on TPU) masked-SDPA over an *explicit* boolean mask —
independent of the slice-metadata machinery, so it cross-checks both the mask
construction and the kernels. Differentiable with jax AD.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = float("-inf")


def ref_attn(
    q,
    k,
    v,
    mask: np.ndarray,
    softmax_scale: float | None = None,
    softcap: float = 0.0,
    compute_dtype=None,
) -> tuple[jax.Array, jax.Array]:
    """Dense reference attention.

    Args:
        q/k/v: ``[sq,hq,d] / [sk,hk,d] / [sk,hk,dv]`` (varlen packed layout).
        mask: ``[sq, sk]`` boolean numpy array (True = attend).

    Returns:
        (out ``[sq,hq,dv]`` in q.dtype, lse ``[sq,hq]`` fp32).
    """
    if compute_dtype is None:
        compute_dtype = (
            jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        )
    sq, hq, d = q.shape
    sk, hk, dv = v.shape
    g = hq // hk
    if softmax_scale is None:
        softmax_scale = d ** -0.5

    qc = jnp.asarray(q, dtype=compute_dtype)
    kc = jnp.repeat(jnp.asarray(k, dtype=compute_dtype), g, axis=1)
    vc = jnp.repeat(jnp.asarray(v, dtype=compute_dtype), g, axis=1)
    maskj = jnp.asarray(np.asarray(mask))

    logits = jnp.einsum("qhd,khd->hqk", qc, kc) * softmax_scale
    if softcap > 0.0:
        logits = softcap * jnp.tanh(logits / softcap)
    logits = jnp.where(maskj[None], logits, NEG_INF)

    lse = jax.scipy.special.logsumexp(logits, axis=-1)  # [hq, sq]
    p = jnp.exp(logits - jnp.where(jnp.isfinite(lse), lse, 0.0)[..., None])
    p = jnp.where(maskj[None], p, 0.0)
    out = jnp.einsum("hqk,khd->qhd", p, vc)
    return out.astype(q.dtype), lse.T.astype(jnp.float32)
