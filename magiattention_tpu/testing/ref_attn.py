"""Ground-truth attention (ref: magi_attention/testing/ref_attn.py:41-638).

A dense fp64 (fp32 on TPU) masked-SDPA over an *explicit* boolean mask —
independent of the slice-metadata machinery, so it cross-checks both the mask
construction and the kernels. Differentiable with jax AD.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = float("-inf")


def ref_attn(
    q,
    k,
    v,
    mask: np.ndarray,
    softmax_scale: float | None = None,
    softcap: float = 0.0,
    sink=None,
    compute_dtype=None,
) -> tuple[jax.Array, jax.Array]:
    """Dense reference attention.

    Args:
        q/k/v: ``[sq,hq,d] / [sk,hk,d] / [sk,hk,dv]`` (varlen packed layout).
        mask: ``[sq, sk]`` boolean numpy array (True = attend).
        sink: optional ``(s_sink, hq)`` learnable sink logits — extra softmax
            columns with no value contribution.

    Returns:
        (out ``[sq,hq,dv]`` in q.dtype, lse ``[sq,hq]`` fp32).
    """
    if compute_dtype is None:
        compute_dtype = (
            jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        )
    sq, hq, d = q.shape
    sk, hk, dv = v.shape
    g = hq // hk
    if softmax_scale is None:
        softmax_scale = d ** -0.5

    qc = jnp.asarray(q, dtype=compute_dtype)
    kc = jnp.repeat(jnp.asarray(k, dtype=compute_dtype), g, axis=1)
    vc = jnp.repeat(jnp.asarray(v, dtype=compute_dtype), g, axis=1)
    maskj = jnp.asarray(np.asarray(mask))

    logits = jnp.einsum("qhd,khd->hqk", qc, kc) * softmax_scale
    if softcap > 0.0:
        logits = softcap * jnp.tanh(logits / softcap)
    logits = jnp.where(maskj[None], logits, NEG_INF)
    maskj_h = jnp.broadcast_to(maskj[None], logits.shape)
    if sink is not None:
        # append sink columns: participate in softmax, contribute no value
        s_sink = sink.shape[0]
        sink_cols = jnp.broadcast_to(
            jnp.asarray(sink, dtype=compute_dtype).T[:, None, :],
            (hq, sq, s_sink),
        )
        logits = jnp.concatenate([logits, sink_cols], axis=-1)
        maskj_h = jnp.concatenate(
            [maskj_h, jnp.ones((hq, sq, s_sink), dtype=bool)], axis=-1
        )
        vc = jnp.concatenate(
            [vc, jnp.zeros((s_sink, hq, dv), dtype=compute_dtype)], axis=0
        )

    lse = jax.scipy.special.logsumexp(logits, axis=-1)  # [hq, sq]
    p = jnp.exp(logits - jnp.where(jnp.isfinite(lse), lse, 0.0)[..., None])
    p = jnp.where(maskj_h, p, 0.0)
    out = jnp.einsum("hqk,khd->qhd", p, vc)
    return out.astype(q.dtype), lse.T.astype(jnp.float32)


def ref_max_logits(
    q,
    k,
    mask: np.ndarray,
    softmax_scale: float | None = None,
    softcap: float = 0.0,
    compute_dtype=None,
) -> jax.Array:
    """Per-head max of the (scaled, softcapped) masked logits ``[hq]`` fp32 —
    golden model for AttnForwardMeta.max_logits (ref forward_meta.py:21)."""
    if compute_dtype is None:
        compute_dtype = (
            jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        )
    sq, hq, d = q.shape
    sk, hk, _ = k.shape
    g = hq // hk
    if softmax_scale is None:
        softmax_scale = d ** -0.5
    qc = jnp.asarray(q, dtype=compute_dtype)
    kc = jnp.repeat(jnp.asarray(k, dtype=compute_dtype), g, axis=1)
    logits = jnp.einsum("qhd,khd->hqk", qc, kc) * softmax_scale
    if softcap > 0.0:
        logits = softcap * jnp.tanh(logits / softcap)
    logits = jnp.where(jnp.asarray(np.asarray(mask))[None], logits, NEG_INF)
    return jnp.max(logits, axis=(1, 2)).astype(jnp.float32)
