"""Dual-threshold closeness asserts (ref: magi_attention/testing/precision.py:57-304).

``assert_close`` passes iff BOTH the relative-norm error is under
``norm_rtol`` AND the elementwise mismatch ratio (beyond atol/rtol) is under
``mismatch_thres`` — robust for low-precision kernels where a tiny fraction of
elements may exceed tight elementwise bounds.
"""

from __future__ import annotations

import numpy as np


def rel_norm_err(a: np.ndarray, b: np.ndarray) -> float:
    denom = np.linalg.norm(b.astype(np.float64).ravel())
    if denom == 0.0:
        return float(np.linalg.norm(a.astype(np.float64).ravel()))
    return float(
        np.linalg.norm((a.astype(np.float64) - b.astype(np.float64)).ravel()) / denom
    )


def mismatch_ratio(
    a: np.ndarray, b: np.ndarray, atol: float, rtol: float
) -> float:
    a64, b64 = a.astype(np.float64), b.astype(np.float64)
    finite_mismatch = ~np.isclose(a64, b64, atol=atol, rtol=rtol, equal_nan=True)
    # -inf == -inf counts as a match (fully-masked lse rows)
    both_neginf = np.isneginf(a64) & np.isneginf(b64)
    mismatch = finite_mismatch & ~both_neginf
    return float(mismatch.mean()) if mismatch.size else 0.0


def assert_close(
    actual,
    expected,
    atol: float = 1e-5,
    rtol: float = 1e-5,
    norm_rtol: float = 1e-4,
    mismatch_thres: float = 0.0,
    msg: str = "",
) -> None:
    a = np.asarray(actual)
    b = np.asarray(expected)
    assert a.shape == b.shape, f"{msg}: shape {a.shape} != {b.shape}"

    finite = np.isfinite(b)
    if finite.any():
        nerr = rel_norm_err(
            np.where(finite, a, 0.0), np.where(finite, b, 0.0)
        )
    else:
        nerr = 0.0
    mratio = mismatch_ratio(a, b, atol, rtol)

    assert nerr <= norm_rtol and mratio <= mismatch_thres, (
        f"{msg}: rel-norm-err {nerr:.3e} (thres {norm_rtol:.1e}), "
        f"mismatch-ratio {mratio:.3e} (thres {mismatch_thres:.1e}, "
        f"atol={atol:.1e} rtol={rtol:.1e})"
    )
