"""Overlap-safety assertion (ref: magi_attention/testing/template.py:77).

The reference stress-runs a kernel against a concurrent NCCL overlay stream
to catch compute/comm data races. On TPU there are no user-visible streams
— XLA owns the schedule — so the corresponding hazard is a *plan* bug: the
multi-stage overlapped program reading a receive buffer before its
collective completes would manifest as a numerical mismatch between the
overlapped and the blocking (no-overlap, single merged kernel) executions
of the same mask. ``assert_overlap_safe`` runs both and demands agreement,
which also exercises XLA's async-collective scheduling on the overlapped
program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..functional.dist_attn import DistAttnRuntime
from .precision import assert_close


def assert_overlap_safe(
    comm_meta,
    calc_meta,
    mesh,
    cp_axis,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    atol: float = 1e-5,
    rtol: float = 1e-5,
    iters: int = 3,
) -> None:
    """Assert the overlapped CP program matches the blocking one.

    Args:
        comm_meta/calc_meta: a solved plan with >= 1 remote stage.
        q/k/v: dispatched tensors sharded over the cp axis.
        iters: repetitions (the reference stress-loops; XLA is
            deterministic, so this guards against nondeterministic
            scheduling regressions rather than races).
    """
    overlapped = DistAttnRuntime(
        comm_meta=comm_meta, calc_meta=calc_meta, mesh=mesh, cp_axis=cp_axis,
        use_overlap=True,
    )
    blocking = DistAttnRuntime(
        comm_meta=comm_meta, calc_meta=calc_meta, mesh=mesh, cp_axis=cp_axis,
        use_overlap=False,
    )
    f_o = jax.jit(overlapped.calc_attn)
    f_b = jax.jit(blocking.calc_attn)
    out_ref, lse_ref = f_b(q, k, v)
    for i in range(iters):
        out, lse = f_o(q, k, v)
        assert_close(
            out, out_ref, atol=atol, rtol=rtol, norm_rtol=rtol,
            msg=f"overlap-safety iter {i}: out mismatch",
        )
        assert_close(
            jnp.where(jnp.isneginf(lse), 0.0, lse),
            jnp.where(jnp.isneginf(lse_ref), 0.0, lse_ref),
            atol=atol, rtol=rtol, norm_rtol=rtol,
            msg=f"overlap-safety iter {i}: lse mismatch",
        )
