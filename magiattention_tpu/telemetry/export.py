"""JSONL sink: one line per record, flushed per write, crash-safe.

Records are plain JSON objects; numpy scalars/arrays are converted on the
way out so call sites can pass solver/planner arrays without ceremony.
The file opens lazily on the first record, so merely enabling telemetry
does not create files in processes that never plan or step.
"""

from __future__ import annotations

import json
import os
from typing import Any, IO


def _jsonable(x: Any) -> Any:
    """Best-effort conversion to JSON-serializable builtins."""
    import numpy as np

    if isinstance(x, dict):
        return {str(k): _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if isinstance(x, np.ndarray):
        return x.tolist()
    if isinstance(x, np.generic):
        return x.item()
    if isinstance(x, (str, int, float, bool)) or x is None:
        return x
    return str(x)


class JsonlSink:
    def __init__(self, path: str) -> None:
        self.path = path
        self._f: IO[str] | None = None

    def write(self, record: dict[str, Any]) -> None:
        if self._f is None:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            self._f = open(self.path, "a")
        self._f.write(json.dumps(_jsonable(record)) + "\n")
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
