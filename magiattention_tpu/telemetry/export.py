"""JSONL sink: one line-atomic append per record, crash-safe.

Records are plain JSON objects; numpy scalars/arrays are converted on the
way out so call sites can pass solver/planner arrays without ceremony.
The file opens lazily on the first record, so merely enabling telemetry
does not create files in processes that never plan or step.

Multi-writer safety: the file is opened with ``O_APPEND`` and each record
is emitted as a single ``os.write`` of one ``\\n``-terminated line, so
concurrent writers to the same file (threads, or processes that happen to
share a path on a network filesystem) never interleave partial records.
On top of that, :func:`process_unique_path` gives each writer its own
file — ``<prefix>-<host>-<pid>-<token>.jsonl`` — so two hosts of a
multi-slice job with colliding pids still never share a file.
"""

from __future__ import annotations

import json
import os
import socket
import uuid
from typing import Any


def _jsonable(x: Any) -> Any:
    """Best-effort conversion to JSON-serializable builtins."""
    import numpy as np

    if isinstance(x, dict):
        return {str(k): _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if isinstance(x, np.ndarray):
        return x.tolist()
    if isinstance(x, np.generic):
        return x.item()
    if isinstance(x, (str, int, float, bool)) or x is None:
        return x
    return str(x)


def process_unique_path(
    directory: str, prefix: str, suffix: str = ".jsonl"
) -> str:
    """A writer-unique path under ``directory``: host short-name + pid +
    a random token. Pid alone is not unique across the hosts of a
    multi-slice job, and pids get recycled within one host — the token
    covers both."""
    host = socket.gethostname().split(".")[0] or "host"
    token = uuid.uuid4().hex[:8]
    return os.path.join(directory, f"{prefix}-{host}-{os.getpid()}-{token}{suffix}")


class JsonlSink:
    def __init__(self, path: str) -> None:
        self.path = path
        self._fd: int | None = None

    def write(self, record: dict[str, Any]) -> None:
        if self._fd is None:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            self._fd = os.open(
                self.path, os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644
            )
        data = (json.dumps(_jsonable(record)) + "\n").encode("utf-8")
        # single write syscall per line: O_APPEND makes it atomic with
        # respect to other appenders, and there is no userspace buffer to
        # lose on crash (the old sink buffered then flushed)
        os.write(self._fd, data)

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None
