"""Per-rank health tracking → capacity vector (docs/degraded_ranks.md).

Folds per-rank step wall times (the ``rank_wall_ms`` field of ``attn_step``
records, or direct :func:`observe_step` calls) into an EWMA per rank and
derives a per-rank *capacity* in (0, 1] with hysteresis:

- a rank **enters** degraded state only after ``STRAGGLER_MIN_STEPS``
  observations, when its normalized EWMA exceeds ``STRAGGLER_ENTER`` times
  the healthy median, and only once per ``STRAGGLER_COOLDOWN`` steps;
- while degraded its capacity is **frozen** (one noisy step never re-flips
  the plan) until its normalized EWMA drops under ``STRAGGLER_EXIT``;
- slowness is always judged per *unit of work*: a degraded rank runs a
  capacity-proportional share of the weighted plan, so its raw wall time
  converges back to the healthy median even on still-slow hardware —
  dividing the EWMA by the rank's capacity removes that feedback loop.

The derived vector feeds ``DistAttnRuntimeKey.capacities`` (api layer), so
a changed vector is a changed plan key: the runtime re-solves exactly when
the vector changes and the PR 13 cache/store/broadcast tiers handle
weighted plans with zero new plumbing. An all-ones vector normalizes to
``None`` — plan signatures stay byte-identical to a build without this
module.

Everything is gated on ``MAGI_ATTENTION_STRAGGLER_DETECT``; the
``rank_health_read`` chaos site covers the read path (fault + fallback →
uniform all-ones vector).
"""

from __future__ import annotations

import statistics
import threading
from dataclasses import dataclass, field as _field

from ..env import health as env_health
from . import registry as _registry

# capacity quantization grid: coarse steps keep float jitter out of the
# plan key (a vector change means a re-solve, so changes must be rare)
_CAP_GRID = 8
_CAP_MIN = 1.0 / _CAP_GRID


def _quantize_capacity(x: float) -> float:
    return max(_CAP_MIN, min(1.0, round(x * _CAP_GRID) / _CAP_GRID))


@dataclass
class _RankState:
    ewma_ms: float | None = None
    count: int = 0
    capacity: float = 1.0
    # large initial value: the first transition is never cooldown-blocked
    since_change: int = 1 << 30


@dataclass
class RankHealthMonitor:
    """EWMA + hysteresis straggler detector. Thread-safe; step-count based
    (no wall clock of its own — the observed wall_ms IS the signal)."""

    _ranks: dict[int, _RankState] = _field(default_factory=dict)
    _lock: threading.Lock = _field(default_factory=threading.Lock)

    def observe_step(self, rank: int, wall_ms: float) -> str | None:
        """Fold one step wall time for ``rank``; returns "degraded" /
        "recovered" on a capacity transition, else None. Emits a
        ``rank_health`` telemetry record (store row) per observation."""
        if not env_health.is_straggler_detect_enable():
            return None
        alpha = env_health.straggler_ewma_alpha()
        with self._lock:
            st = self._ranks.setdefault(int(rank), _RankState())
            st.count += 1
            st.since_change = min(st.since_change + 1, 1 << 30)
            st.ewma_ms = (
                float(wall_ms)
                if st.ewma_ms is None
                else alpha * float(wall_ms) + (1.0 - alpha) * st.ewma_ms
            )
            transition = self._evaluate(st)
            ewma, cap = st.ewma_ms, st.capacity
        _registry.record_event(
            "rank_health",
            rank=int(rank),
            wall_ms=float(wall_ms),
            ewma_ms=ewma,
            capacity=cap,
            degraded=cap < 1.0,
            **({"transition": transition} if transition else {}),
        )
        return transition

    def _evaluate(self, st: _RankState) -> str | None:
        """Hysteresis state machine for one rank (lock held)."""
        if st.count < env_health.straggler_min_steps():
            return None
        # per-unit-work EWMA: a degraded rank only runs a capacity share
        # of the plan, so divide by capacity before comparing
        norm = [
            s.ewma_ms / s.capacity
            for s in self._ranks.values()
            if s.ewma_ms is not None and s.capacity >= 1.0
        ]
        if not norm:
            norm = [
                s.ewma_ms / s.capacity
                for s in self._ranks.values()
                if s.ewma_ms is not None
            ]
        ref = statistics.median(norm) if norm else 0.0
        if ref <= 0.0 or st.ewma_ms is None:
            return None
        slowness = (st.ewma_ms / st.capacity) / ref
        if st.since_change < env_health.straggler_cooldown_steps():
            return None
        if st.capacity >= 1.0:
            if slowness >= env_health.straggler_enter_ratio():
                st.capacity = _quantize_capacity(1.0 / slowness)
                st.since_change = 0
                return "degraded"
        elif slowness <= env_health.straggler_exit_ratio():
            # recovery is the only exit; capacity stays frozen otherwise
            st.capacity = 1.0
            st.since_change = 0
            return "recovered"
        return None

    def capacities(self, cp_size: int) -> tuple[float, ...] | None:
        """Active capacity vector, or None when uniform (all healthy)."""
        with self._lock:
            caps = tuple(
                self._ranks[r].capacity if r in self._ranks else 1.0
                for r in range(cp_size)
            )
        if all(c == caps[0] for c in caps):
            return None
        return caps

    def reset(self) -> None:
        with self._lock:
            self._ranks.clear()


_monitor: RankHealthMonitor | None = None
_monitor_lock = threading.Lock()


def get_monitor() -> RankHealthMonitor:
    global _monitor
    with _monitor_lock:
        if _monitor is None:
            _monitor = RankHealthMonitor()
        return _monitor


def reset() -> None:
    global _monitor
    with _monitor_lock:
        _monitor = None


def observe_step(rank: int, wall_ms: float) -> str | None:
    return get_monitor().observe_step(rank, wall_ms)


def observe_attn_step(payload: dict) -> None:
    """Collector hook: fold an ``attn_step`` record's per-rank wall times
    (optional ``rank_wall_ms`` list) into the monitor. Cheap no-op unless
    straggler detection is on and the record carries the field."""
    if not env_health.is_straggler_detect_enable():
        return
    rank_wall = payload.get("rank_wall_ms")
    if not rank_wall:
        return
    mon = get_monitor()
    for rank, wall_ms in enumerate(rank_wall):
        if wall_ms is not None:
            mon.observe_step(rank, float(wall_ms))


def active_capacities(cp_size: int) -> tuple[float, ...] | None:
    """The capacity vector plan keys should carry right now — None when
    detection is off or every rank is healthy (uniform ⇒ byte-identical
    plan signatures). The ``rank_health_read`` chaos site covers this
    read: an injected fault degrades to the uniform all-ones vector when
    fallback is enabled, else propagates typed."""
    if not env_health.is_straggler_detect_enable():
        return None
    from ..resilience.inject import maybe_inject

    try:
        maybe_inject("rank_health_read")
    except Exception as e:
        from ..resilience.errors import InjectedFault

        if not isinstance(e, InjectedFault):
            raise
        from ..env import resilience as env_resilience

        if not env_resilience.is_fallback_enable():
            raise
        from ..resilience.fallback import record_resilience_event

        record_resilience_event(
            "fallback", "rank_health_read",
            action_detail="uniform_capacities", error=type(e).__name__,
        )
        return None
    return get_monitor().capacities(cp_size)
