"""Measured-vs-modeled drift: close the loop on the open-loop cost models.

Three cost models steer planning with hard-coded constants and no feedback:

- ``tile_policy``'s block score ``w * (bq*bk + OVERHEAD_ELEMS)`` (fwd tile
  choice and the mixed-dispatch split decision),
- ``choose_bwd_mode``'s arithmetic-intensity model (split vs fused bwd),
- the overlap solver's ``two_level_makespan`` (ICI x DCN stage packing,
  ``dcn_per_row = 8.0``).

The store (telemetry/store.py) accumulates ``obs`` rows pairing each
model's *predicted* cost (model units) with the *measured* wall ms.
:func:`scan` fits a single global scale per model (least squares through
the origin — model units to ms), flags observations whose relative error
after scaling exceeds ``MAGI_ATTENTION_DRIFT_THRESHOLD``, and emits them
as ``model_drift`` telemetry records (which the collector ingests back
into the store, so drift findings persist across runs and show up in
``scripts/telemetry_report.py``).

:func:`fit_constants` goes one step further: it refits the models' free
constants from history — ``overhead_elems`` from the (tile area, work
count) components of the tile score, ``dcn_per_row`` from (ici rows, dcn
rows) makespan observations — and writes them as ``calib`` rows that
``tile_policy`` / ``overlap_solver`` consume via ``store.calibrated()``
when ``MAGI_ATTENTION_CALIBRATION`` is on.
"""

from __future__ import annotations

from typing import Any, Iterable

from ..env import backend as env_backend
from . import registry as telemetry
from . import store as tstore
from .store import StoreState

# a model needs at least this many observations before scale fitting /
# drift flagging is meaningful
MIN_SAMPLES = 3


def fit_scale(pairs: Iterable[tuple[float, float]]) -> float:
    """Least-squares scale a (through the origin) for measured ≈ a*predicted."""
    num = 0.0
    den = 0.0
    for pred, meas in pairs:
        num += pred * meas
        den += pred * pred
    return num / den if den > 0 else 0.0


def _fit2(
    xs: list[float], ys: list[float], ms: list[float]
) -> tuple[float, float] | None:
    """Least squares for ms ≈ a*x + b*y (2x2 normal equations)."""
    sxx = sum(x * x for x in xs)
    syy = sum(y * y for y in ys)
    sxy = sum(x * y for x, y in zip(xs, ys))
    sxm = sum(x * m for x, m in zip(xs, ms))
    sym = sum(y * m for y, m in zip(ys, ms))
    det = sxx * syy - sxy * sxy
    if abs(det) < 1e-12 * max(sxx, syy, 1.0):
        return None  # degenerate: the two regressors are collinear
    a = (sxm * syy - sym * sxy) / det
    b = (sym * sxx - sxm * sxy) / det
    return a, b


def scan(
    state: StoreState | None = None,
    threshold: float | None = None,
    emit: bool = True,
) -> list[dict[str, Any]]:
    """Flag observations whose scaled prediction misses the measurement.

    Returns the findings; with ``emit`` also records each as a
    ``model_drift`` telemetry event (no-op when telemetry is off), which
    the collector's store ingest persists as a ``drift`` row."""
    if state is None:
        st = tstore.get_store()
        if st is None:
            return []
        state = st.load()
    thr = env_backend.drift_threshold() if threshold is None else threshold
    findings: list[dict[str, Any]] = []
    for model, obs in sorted(state.observations.items()):
        if len(obs) < MIN_SAMPLES:
            continue
        alpha = fit_scale(
            (o["predicted"], o["measured_ms"]) for o in obs
        )
        if alpha <= 0:
            continue
        for o in obs:
            pred_ms = alpha * o["predicted"]
            rel = abs(pred_ms - o["measured_ms"]) / max(o["measured_ms"], 1e-9)
            if rel <= thr:
                continue
            finding = {
                "model": model,
                "alpha": alpha,
                "rel_err": rel,
                "predicted": o["predicted"],
                "predicted_ms": pred_ms,
                "measured_ms": o["measured_ms"],
                "extras": o.get("extras") or {},
            }
            findings.append(finding)
            if emit:
                telemetry.record_event("model_drift", **finding)
    return findings


def fit_constants(
    state: StoreState | None = None, persist: bool = True
) -> dict[str, float]:
    """Refit model constants from observation history.

    - ``overhead_elems``: tile score is ``area + works*OVERHEAD``; fitting
      ms ≈ a*area + b*works gives OVERHEAD = b/a in element units.
    - ``dcn_per_row``: makespan costs ICI rows at 1.0 and DCN rows at
      ``dcn_per_row``; fitting ms ≈ a*ici_rows + b*dcn_rows gives b/a.

    Returns the fitted values (only keys with a sane positive fit) and,
    with ``persist`` and an active store, writes ``calib`` rows."""
    if state is None:
        st = tstore.get_store()
        if st is None:
            return {}
        state = st.load()
    fitted: dict[str, float] = {}

    def fit_ratio(model: str, xf: str, yf: str) -> tuple[float, int] | None:
        obs = [
            o
            for o in state.observations.get(model, [])
            if o.get("extras", {}).get(xf) is not None
            and o.get("extras", {}).get(yf) is not None
        ]
        if len(obs) < MIN_SAMPLES:
            return None
        ab = _fit2(
            [float(o["extras"][xf]) for o in obs],
            [float(o["extras"][yf]) for o in obs],
            [o["measured_ms"] for o in obs],
        )
        if ab is None or ab[0] <= 0 or ab[1] <= 0:
            return None
        return ab[1] / ab[0], len(obs)

    r = fit_ratio("tile_score", "area", "works")
    if r is not None:
        fitted["overhead_elems"] = r[0]
    r2 = fit_ratio("two_level_makespan", "ici_rows", "dcn_rows")
    if r2 is not None:
        fitted["dcn_per_row"] = r2[0]
    if persist and fitted:
        st = tstore.get_store()
        if st is not None:
            if "overhead_elems" in fitted:
                st.record_calibration(
                    "overhead_elems", fitted["overhead_elems"], r[1]
                )
            if "dcn_per_row" in fitted:
                st.record_calibration("dcn_per_row", fitted["dcn_per_row"], r2[1])
    return fitted
