"""Process-global metrics registry (counters, gauges, timers, events).

One :class:`TelemetryCollector` per process, created lazily on the first
gated call. Every record flows straight through the JSONL sink
(telemetry/export.py) — append-only, flushed per record, so a crashed run
still leaves parseable history — while counters/gauges/last-events stay in
memory for :func:`summary` / :func:`flat_summary` (the hook
``benchmarking/perf_report.append_row`` uses to stamp bench rows).
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Any

from ..env import general as env_general
from .export import JsonlSink, process_unique_path

SCHEMA_VERSION = 1


def enabled() -> bool:
    """The ONE gate every telemetry entry point checks first."""
    return env_general.is_telemetry_enable()


class TelemetryCollector:
    """Counters + gauges + per-kind last-event cache over a JSONL sink."""

    def __init__(self, directory: str) -> None:
        self.directory = directory
        self._lock = threading.Lock()
        self._seq = 0
        self.counters: dict[str, int] = {}
        self.gauges: dict[str, float] = {}
        self.last_event: dict[str, dict[str, Any]] = {}
        # host+pid+token unique name: concurrent hosts of a multi-slice
        # job never share a file (export.py also makes each line atomic)
        self._sink = JsonlSink(process_unique_path(directory, "magiattention"))

    @property
    def path(self) -> str:
        return self._sink.path

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = float(value)

    def record_event(self, kind: str, payload: dict[str, Any]) -> None:
        with self._lock:
            self._seq += 1
            record = {
                "schema_version": SCHEMA_VERSION,
                "ts": time.time(),
                "pid": os.getpid(),
                "seq": self._seq,
                "kind": kind,
                **payload,
            }
            self.counters[f"events.{kind}"] = (
                self.counters.get(f"events.{kind}", 0) + 1
            )
            self.last_event[kind] = record
            self._sink.write(record)
        # feed the persistent cross-run store (outside the collector lock;
        # the store has its own). No-op unless the store is active and the
        # kind is one it aggregates.
        from . import store as _store

        _store.ingest_event(record)

    def close(self) -> None:
        self._sink.close()


_collector: TelemetryCollector | None = None
_collector_lock = threading.Lock()


def get_collector() -> TelemetryCollector:
    """The process-global collector (created on first use; recreated when
    ``MAGI_ATTENTION_TELEMETRY_DIR`` changes, so tests can redirect it)."""
    global _collector
    directory = env_general.telemetry_dir()
    with _collector_lock:
        if _collector is None or _collector.directory != directory:
            if _collector is not None:
                _collector.close()
            _collector = TelemetryCollector(directory)
        return _collector


def reset() -> None:
    """Drop the global collector (tests; a new one is created on demand)."""
    global _collector
    with _collector_lock:
        if _collector is not None:
            _collector.close()
        _collector = None


# -- module-level gated entry points (what call sites use) -----------------


def record_event(kind: str, **payload: Any) -> None:
    if not enabled():
        return
    get_collector().record_event(kind, payload)
    if kind == "attn_step":
        # straggler detection: fold per-rank wall times into the health
        # monitor (no-op unless MAGI_ATTENTION_STRAGGLER_DETECT is on and
        # the record carries rank_wall_ms)
        from . import health as _health

        _health.observe_attn_step(payload)


def inc(name: str, n: int = 1) -> None:
    if not enabled():
        return
    get_collector().inc(name, n)


def set_gauge(name: str, value: float) -> None:
    if not enabled():
        return
    get_collector().set_gauge(name, value)


@contextmanager
def stage_timer(name: str, record_kind: str | None = None, **payload: Any):
    """Gated host wall-timer. Off: identity (no ``perf_counter`` read).

    On: times the block, bumps ``time.<name>.calls`` / ``.total_ms``
    counters and the ``time.<name>.last_ms`` gauge; with ``record_kind``
    also emits a JSONL record carrying ``xprof_scope=name`` so the record
    links to the identically-named ``profile_scope`` span in an xprof trace
    when MAGI_ATTENTION_PROFILE_MODE is also on.
    """
    if not enabled():
        yield None
        return
    c = get_collector()
    t0 = time.perf_counter()
    try:
        yield c
    finally:
        ms = (time.perf_counter() - t0) * 1e3
        c.inc(f"time.{name}.calls")
        with c._lock:
            c.counters[f"time.{name}.total_ms"] = int(
                c.counters.get(f"time.{name}.total_ms", 0) + ms
            )
        c.set_gauge(f"time.{name}.last_ms", ms)
        if record_kind is not None:
            c.record_event(
                record_kind, {"xprof_scope": name, "wall_ms": ms, **payload}
            )


def summary() -> dict[str, Any]:
    """Structured in-memory snapshot: counters, gauges, last event per kind."""
    if not enabled():
        return {}
    c = get_collector()
    with c._lock:
        return {
            "counters": dict(c.counters),
            "gauges": dict(c.gauges),
            "last": {k: dict(v) for k, v in c.last_event.items()},
        }


# last-event fields worth carrying onto bench-history rows: comm/balance
# context for a perf number (kind, field, column suffix)
_FLAT_FIELDS = (
    ("dispatch_meta", "balance_ratio", "balance_ratio"),
    ("dispatch_meta", "alg", "dispatch_alg"),
    ("attn_step", "overlap_degree", "overlap_degree"),
    ("attn_step", "wire_bytes_total", "wire_bytes"),
    ("attn_step", "payload_bytes_total", "payload_bytes"),
    ("attn_step", "wall_ms", "step_wall_ms"),
)


def flat_summary(prefix: str = "tel_") -> dict[str, Any]:
    """Flat scalar summary for tabular sinks (bench history CSV rows)."""
    if not enabled():
        return {}
    s = summary()
    out: dict[str, Any] = {}
    for kind, field, col in _FLAT_FIELDS:
        ev = s["last"].get(kind)
        if ev is not None and field in ev:
            out[prefix + col] = ev[field]
    for name in ("runtime_cache.hit", "runtime_cache.miss",
                 "runtime_cache.evict", "events.attn_step",
                 "events.plan_build"):
        if name in s["counters"]:
            out[prefix + name.replace(".", "_")] = s["counters"][name]
    return out
