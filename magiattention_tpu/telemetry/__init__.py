"""Runtime telemetry: per-plan / per-step metrics registry with JSONL export.

The observability counterpart of ``utils/profiling.py``: where profiling
puts *names* on the xprof timeline, telemetry records *numbers* — the
dispatch solver's balance ratio, every GroupCast stage's payload/wire/padding
rows and bytes, the FFA planner's padded-vs-true work, per-step host wall
times, and the runtime LRU's hit/miss/evict counts — as schema-versioned
JSONL records a CI job or ``scripts/telemetry_report.py`` can read back.

Gated on ``MAGI_ATTENTION_TELEMETRY`` (env/general.py typed getter, same
pattern as ``MAGI_ATTENTION_PROFILE_MODE``): with the flag off every entry
point here is a cheap early return — no file I/O, no timer reads, nothing
allocated (pinned by tests/test_support/test_telemetry.py).

Stage records carry the SAME scope names (``group_cast_stage0``,
``ffa_fwd_stage0``, ...) that ``utils/profiling.profile_scope`` annotates on
the xprof timeline, so a JSONL record links directly to its trace span when
both flags are on.
"""

from .registry import (  # noqa: F401
    SCHEMA_VERSION,
    TelemetryCollector,
    enabled,
    flat_summary,
    get_collector,
    inc,
    record_event,
    reset,
    set_gauge,
    stage_timer,
    summary,
)
from . import health  # noqa: F401
from .stats import band_area  # noqa: F401
from .store import (  # noqa: F401
    TelemetryStore,
    StoreState,
    store_active,
)
from .drift import fit_constants, fit_scale, scan  # noqa: F401
