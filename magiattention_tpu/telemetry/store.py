"""Persistent cross-run telemetry store — the observatory's memory.

Where telemetry/registry.py streams write-only per-process JSONL, this
module keeps a small *readable* history that survives restarts and is
shared by every process pointing at the same directory:

- ``history-<host>-<pid>-<token>.jsonl`` — append-only rows, one writer
  per file, each line written atomically (O_APPEND, single write; see
  telemetry/export.py). Safe for any number of concurrent writers.
- ``store.json`` — compacted snapshot, replaced atomically via a temp
  file + ``os.replace``. :meth:`TelemetryStore.compact` folds all history
  files into it; run compaction when no writers are active (end of run,
  CI, or the report tool) — a writer whose open file is deleted under it
  loses subsequent rows.

Row kinds (``rk`` field):

- ``measure`` — one timed execution of a backend for a registry decision
  key; aggregated into per-(decision, key, backend) count/sum/min so the
  policy layer can pick the fastest *measured* backend.
- ``policy``  — a resolved registry decision, persisted so a warm restart
  re-uses it with zero re-tuning (kernels/registry.py reads these back).
- ``hist``    — aggregated ``attn_step`` / ``serve_step`` / ``plan_solve``
  run history keyed by (mask-class signature, shape, dtype, mesh, env
  snapshot signature), fed by :func:`ingest_event` from the collector.
- ``obs``     — a (predicted cost, measured ms) pair for one of the
  open-loop cost models; consumed by telemetry/drift.py.
- ``calib``   — a fitted model constant (e.g. ``overhead_elems``,
  ``dcn_per_row``) solvers may consume via :func:`calibration_value`.
- ``drift``   — a measured-vs-modeled drift finding past threshold.

Everything here is gated on :func:`store_active` — with
``MAGI_ATTENTION_TELEMETRY`` off (or ``MAGI_ATTENTION_BACKEND_STORE=0``)
every entry point is a cheap early return: no file I/O, no state, and the
backend registry falls back to its legacy heuristics bit-identically.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any

from ..env import backend as env_backend
from ..env import general as env_general
from .export import JsonlSink, _jsonable, process_unique_path

STORE_SCHEMA_VERSION = 1
SNAPSHOT_NAME = "store.json"
HISTORY_PREFIX = "history"

# measurements needed before a backend is considered "verified fastest"
MIN_MEASUREMENTS = 2
# bounded in-memory/snapshot tails (aggregates are unbounded-safe; raw
# observation/drift rows are not)
OBS_CAP = 512
DRIFT_CAP = 256

# collector kinds ingest_event aggregates into run history
_HISTORY_KINDS = ("attn_step", "serve_step", "plan_solve", "step_retry")
# collector kinds with dedicated fold logic besides run history
_SPECIAL_KINDS = ("model_drift", "rank_health")
# attn_step fields forming the run-history key (ISSUE: mask-class
# signature, shape, dtype, mesh, env snapshot)
_ATTN_KEY_FIELDS = (
    "mask_sig", "q_shape", "kv_shape", "dtype", "mesh_sig", "env_sig",
    "cp_size",
)


def store_active() -> bool:
    """The ONE gate every store entry point checks first."""
    return (
        env_general.is_telemetry_enable()
        and env_backend.backend_store_mode() != "0"
    )


def canonical_key(key: Any) -> str:
    """Stable string form of a decision/history key (dict keys sorted,
    tuples as lists) — the join key across processes and restarts."""
    return json.dumps(_jsonable(key), sort_keys=True, separators=(",", ":"))


@dataclass
class StoreState:
    """In-memory aggregate view of the store (snapshot + replayed rows)."""

    entries: dict[str, dict[str, Any]] = field(default_factory=dict)
    history: dict[str, dict[str, Any]] = field(default_factory=dict)
    policy: dict[str, dict[str, Any]] = field(default_factory=dict)
    calibration: dict[str, dict[str, Any]] = field(default_factory=dict)
    observations: dict[str, list[dict[str, Any]]] = field(default_factory=dict)
    drift: list[dict[str, Any]] = field(default_factory=list)
    rank_health: dict[str, dict[str, Any]] = field(default_factory=dict)
    quarantine: dict[str, dict[str, Any]] = field(default_factory=dict)


def _apply(state: StoreState, row: dict[str, Any]) -> None:
    """Fold one history row into the aggregate state."""
    rk = row.get("rk")
    if rk == "measure":
        ekey = f"{row['decision']}|{row['key']}"
        entry = state.entries.setdefault(ekey, {"count": 0, "by_backend": {}})
        entry["count"] += 1
        b = entry["by_backend"].setdefault(
            row["backend"],
            {"count": 0, "ok": 0, "wall_ms_sum": 0.0, "wall_ms_min": None},
        )
        b["count"] += 1
        if row.get("ok", True):
            b["ok"] += 1
            ms = float(row["wall_ms"])
            b["wall_ms_sum"] += ms
            if b["wall_ms_min"] is None or ms < b["wall_ms_min"]:
                b["wall_ms_min"] = ms
    elif rk == "policy":
        state.policy[f"{row['decision']}|{row['key']}"] = {
            "choice": row["choice"],
            "source": row.get("source", "heuristic"),
            "ts": row.get("ts"),
        }
    elif rk == "hist":
        hkey = f"{row['kind']}|{row['key']}"
        h = state.history.setdefault(
            hkey,
            {
                "kind": row["kind"],
                "count": 0,
                "wall_ms_sum": 0.0,
                "wall_ms_min": None,
                "wall_ms_max": None,
            },
        )
        h["count"] += 1
        ms = row.get("wall_ms")
        if ms is not None:
            ms = float(ms)
            h["wall_ms_sum"] += ms
            if h["wall_ms_min"] is None or ms < h["wall_ms_min"]:
                h["wall_ms_min"] = ms
            if h["wall_ms_max"] is None or ms > h["wall_ms_max"]:
                h["wall_ms_max"] = ms
        h["last_ts"] = row.get("ts")
    elif rk == "obs":
        obs = state.observations.setdefault(row["model"], [])
        obs.append(
            {
                "predicted": float(row["predicted"]),
                "measured_ms": float(row["measured_ms"]),
                "extras": row.get("extras") or {},
            }
        )
        if len(obs) > OBS_CAP:
            del obs[: len(obs) - OBS_CAP]
    elif rk == "calib":
        state.calibration[row["name"]] = {
            "value": float(row["value"]),
            "n": int(row.get("n", 0)),
            "ts": row.get("ts"),
        }
    elif rk == "drift":
        state.drift.append(
            {k: v for k, v in row.items() if k not in ("rk", "v")}
        )
        if len(state.drift) > DRIFT_CAP:
            del state.drift[: len(state.drift) - DRIFT_CAP]
    elif rk == "rank_health":
        r = str(row.get("rank"))
        h = state.rank_health.setdefault(
            r,
            {
                "count": 0,
                "transitions": 0,
                "ewma_ms": None,
                "capacity": 1.0,
                "degraded": False,
            },
        )
        h["count"] += 1
        if row.get("ewma_ms") is not None:
            h["ewma_ms"] = float(row["ewma_ms"])
        if row.get("capacity") is not None:
            cap = float(row["capacity"])
            if cap != h["capacity"]:
                h["transitions"] += 1
            h["capacity"] = cap
        h["degraded"] = bool(row.get("degraded", False))
        h["last_ts"] = row.get("ts")
    elif rk == "quarantine":
        qkey = f"{row.get('decision')}|{row.get('key')}|{row.get('backend')}"
        if row.get("action") == "clear":
            state.quarantine.pop(qkey, None)
        else:
            q = state.quarantine.setdefault(
                qkey,
                {
                    "decision": row.get("decision"),
                    "key": row.get("key"),
                    "backend": row.get("backend"),
                    "trips": 0,
                },
            )
            q["trips"] = max(q["trips"], int(row.get("trips", 1)))
            q["last_ts"] = row.get("ts")
    # unknown rk: forward-compat skip


def _load_from_disk(directory: str) -> StoreState:
    state = StoreState()
    snap_path = os.path.join(directory, SNAPSHOT_NAME)
    try:
        with open(snap_path) as f:
            snap = json.load(f)
        if isinstance(snap, dict) and snap.get("v", 0) <= STORE_SCHEMA_VERSION:
            state.entries = snap.get("entries", {})
            state.history = snap.get("history", {})
            state.policy = snap.get("policy", {})
            state.calibration = snap.get("calibration", {})
            state.observations = snap.get("observations", {})
            state.drift = snap.get("drift", [])
            state.rank_health = snap.get("rank_health", {})
            state.quarantine = snap.get("quarantine", {})
    except (OSError, ValueError):
        pass  # no/garbled snapshot: rebuild from history alone
    for path in sorted(glob.glob(os.path.join(directory, f"{HISTORY_PREFIX}-*.jsonl"))):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue  # torn/foreign line: skip, keep reading
                    if row.get("v", 0) > STORE_SCHEMA_VERSION:
                        continue
                    _apply(state, row)
        except OSError:
            continue
    return state


class TelemetryStore:
    """One process's handle on a store directory: appends rows to its own
    history file (line-atomic) and keeps the aggregate state in memory."""

    def __init__(self, directory: str) -> None:
        self.directory = directory
        self._lock = threading.Lock()
        self._sink = JsonlSink(process_unique_path(directory, HISTORY_PREFIX))
        self._state: StoreState | None = None

    # -- persistence ------------------------------------------------------

    def _append(self, row: dict[str, Any]) -> None:
        """Write one row (caller holds the lock) and fold it into the
        in-memory state so this process sees its own writes immediately."""
        row.setdefault("v", STORE_SCHEMA_VERSION)
        row.setdefault("ts", time.time())
        self._sink.write(row)
        _apply(self._ensure_loaded(), row)

    def _ensure_loaded(self) -> StoreState:
        if self._state is None:
            self._state = _load_from_disk(self.directory)
        return self._state

    def load(self) -> StoreState:
        """(Re)load the aggregate state from disk: snapshot + every
        history file, including other writers'."""
        with self._lock:
            self._state = _load_from_disk(self.directory)
            return self._state

    def compact(self) -> str:
        """Fold all history files into ``store.json`` (atomic replace) and
        delete them. Call with no concurrent writers; this process's own
        file is rotated so it keeps appending safely afterwards."""
        with self._lock:
            self._sink.close()
            files = sorted(
                glob.glob(
                    os.path.join(self.directory, f"{HISTORY_PREFIX}-*.jsonl")
                )
            )
            state = _load_from_disk(self.directory)
            snap_path = os.path.join(self.directory, SNAPSHOT_NAME)
            tmp_path = snap_path + f".tmp-{os.getpid()}"
            os.makedirs(self.directory, exist_ok=True)
            with open(tmp_path, "w") as f:
                json.dump(
                    {
                        "v": STORE_SCHEMA_VERSION,
                        "entries": state.entries,
                        "history": state.history,
                        "policy": state.policy,
                        "calibration": state.calibration,
                        "observations": state.observations,
                        "drift": state.drift,
                        "rank_health": state.rank_health,
                        "quarantine": state.quarantine,
                    },
                    f,
                )
            os.replace(tmp_path, snap_path)
            for path in files:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            self._sink = JsonlSink(
                process_unique_path(self.directory, HISTORY_PREFIX)
            )
            self._state = state
            return snap_path

    def close(self) -> None:
        with self._lock:
            self._sink.close()

    # -- writers ----------------------------------------------------------

    def record_measurement(
        self,
        decision: str,
        key: Any,
        backend: str,
        wall_ms: float,
        ok: bool = True,
        **extra: Any,
    ) -> None:
        with self._lock:
            self._append(
                {
                    "rk": "measure",
                    "decision": decision,
                    "key": canonical_key(key),
                    "backend": backend,
                    "wall_ms": float(wall_ms),
                    "ok": bool(ok),
                    **({"ctx": _jsonable(extra)} if extra else {}),
                }
            )

    def record_policy(
        self, decision: str, key: Any, choice: str, source: str
    ) -> None:
        with self._lock:
            self._append(
                {
                    "rk": "policy",
                    "decision": decision,
                    "key": canonical_key(key),
                    "choice": choice,
                    "source": source,
                }
            )

    def record_history(
        self, kind: str, key: Any, wall_ms: float | None, **extra: Any
    ) -> None:
        with self._lock:
            row: dict[str, Any] = {
                "rk": "hist",
                "kind": kind,
                "key": canonical_key(key),
            }
            if wall_ms is not None:
                row["wall_ms"] = float(wall_ms)
            if extra:
                row["ctx"] = _jsonable(extra)
            self._append(row)

    def record_observation(
        self,
        model: str,
        predicted: float,
        measured_ms: float,
        **extras: Any,
    ) -> None:
        with self._lock:
            self._append(
                {
                    "rk": "obs",
                    "model": model,
                    "predicted": float(predicted),
                    "measured_ms": float(measured_ms),
                    **({"extras": _jsonable(extras)} if extras else {}),
                }
            )

    def record_calibration(self, name: str, value: float, n: int) -> None:
        with self._lock:
            self._append(
                {"rk": "calib", "name": name, "value": float(value), "n": n}
            )

    def record_drift(self, row: dict[str, Any]) -> None:
        with self._lock:
            self._append({"rk": "drift", **_jsonable(row)})

    def record_rank_health(
        self,
        rank: int,
        wall_ms: float | None,
        ewma_ms: float | None,
        capacity: float,
        degraded: bool,
        **extra: Any,
    ) -> None:
        with self._lock:
            row: dict[str, Any] = {
                "rk": "rank_health",
                "rank": int(rank),
                "capacity": float(capacity),
                "degraded": bool(degraded),
            }
            if wall_ms is not None:
                row["wall_ms"] = float(wall_ms)
            if ewma_ms is not None:
                row["ewma_ms"] = float(ewma_ms)
            if extra:
                row["ctx"] = _jsonable(extra)
            self._append(row)

    def record_quarantine(
        self,
        decision: str,
        key: Any,
        backend: str,
        trips: int,
        action: str = "add",
    ) -> None:
        with self._lock:
            self._append(
                {
                    "rk": "quarantine",
                    "decision": decision,
                    "key": canonical_key(key),
                    "backend": backend,
                    "trips": int(trips),
                    "action": action,
                }
            )

    # -- readers ----------------------------------------------------------

    def policy_for(self, decision: str, key: Any) -> dict[str, Any] | None:
        with self._lock:
            return self._ensure_loaded().policy.get(
                f"{decision}|{canonical_key(key)}"
            )

    def best_backend(
        self, decision: str, key: Any, min_count: int = MIN_MEASUREMENTS
    ) -> tuple[str, float] | None:
        """Fastest *verified* backend for a decision key: lowest mean
        wall_ms among backends with >= min_count ok measurements."""
        with self._lock:
            entry = self._ensure_loaded().entries.get(
                f"{decision}|{canonical_key(key)}"
            )
        if not entry:
            return None
        best: tuple[str, float] | None = None
        for name, b in entry["by_backend"].items():
            if b["ok"] < min_count:
                continue
            mean = b["wall_ms_sum"] / b["ok"]
            if best is None or mean < best[1]:
                best = (name, mean)
        return best

    def calibration_for(self, name: str) -> float | None:
        with self._lock:
            c = self._ensure_loaded().calibration.get(name)
        return None if c is None else float(c["value"])

    def rank_health_view(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            return {
                r: dict(h)
                for r, h in self._ensure_loaded().rank_health.items()
            }

    def quarantined(self, decision: str, key: Any) -> set[str]:
        """Backends quarantined for a decision key (restart-persistent)."""
        prefix = f"{decision}|{canonical_key(key)}|"
        with self._lock:
            return {
                q["backend"]
                for qkey, q in self._ensure_loaded().quarantine.items()
                if qkey.startswith(prefix)
            }


# -- module-level gated access (what the registry / solvers use) ------------

_store: TelemetryStore | None = None
_store_lock = threading.Lock()


def resolve_store_dir() -> str:
    d = env_backend.store_dir()
    return d or os.path.join(env_general.telemetry_dir(), "store")


def get_store() -> TelemetryStore | None:
    """The process-global store, or None when inactive. Recreated when the
    resolved directory changes (tests redirect via env)."""
    if not store_active():
        return None
    global _store
    directory = resolve_store_dir()
    with _store_lock:
        if _store is None or _store.directory != directory:
            if _store is not None:
                _store.close()
            _store = TelemetryStore(directory)
        return _store


def reset() -> None:
    """Drop the global store (tests; recreated on demand)."""
    global _store
    with _store_lock:
        if _store is not None:
            _store.close()
        _store = None


def policy_lookup(decision: str, key: Any) -> dict[str, Any] | None:
    st = get_store()
    return None if st is None else st.policy_for(decision, key)


def policy_record(decision: str, key: Any, choice: str, source: str) -> None:
    st = get_store()
    if st is not None:
        st.record_policy(decision, key, choice, source)


def measured_best(decision: str, key: Any) -> str | None:
    st = get_store()
    if st is None:
        return None
    best = st.best_backend(decision, key)
    return None if best is None else best[0]


def calibration_value(name: str) -> float | None:
    st = get_store()
    return None if st is None else st.calibration_for(name)


def calibrated(name: str, default: float) -> float:
    """A store-fitted model constant, or ``default`` when the store or
    MAGI_ATTENTION_CALIBRATION is off (or no sane fit exists). This is the
    one entry point solvers/cost models use — off-path it is two env dict
    reads and the built-in constant, bit-identical to pre-store behavior."""
    if not store_active() or not env_backend.calibration_enabled():
        return default
    v = calibration_value(name)
    if v is None or not (v > 0):
        return default
    return v


def record_measurement(
    decision: str, key: Any, backend: str, wall_ms: float, ok: bool = True
) -> None:
    st = get_store()
    if st is not None:
        st.record_measurement(decision, key, backend, wall_ms, ok=ok)


def record_observation(
    model: str, predicted: float, measured_ms: float, **extras: Any
) -> None:
    st = get_store()
    if st is not None:
        st.record_observation(model, predicted, measured_ms, **extras)


def quarantined_backends(decision: str, key: Any) -> set[str]:
    """Restart-persistent quarantine set for a decision key; empty when
    the store is inactive (quarantine still works in-process then)."""
    st = get_store()
    return set() if st is None else st.quarantined(decision, key)


def record_quarantine(
    decision: str, key: Any, backend: str, trips: int, action: str = "add"
) -> None:
    st = get_store()
    if st is not None:
        st.record_quarantine(decision, key, backend, trips, action=action)


# -- collector ingest -------------------------------------------------------


def _tile_score_prediction(
    record: dict[str, Any],
) -> tuple[float, float, float] | None:
    """Re-evaluate the tile-policy cost model on a recorded plan: the same
    ``w * (bq*bk + OVERHEAD_ELEMS)`` score choose_blocks minimized, summed
    over the plan's groups. Uses the built-in constant (not a calibrated
    one) — drift is measured against the open-loop model. Returns
    (score, tile_area_term, work_count_term) so drift.fit_constants can
    refit OVERHEAD_ELEMS from the two components."""
    groups = record.get("plan_groups")
    if not groups:
        return None
    from ..kernels.tile_policy import OVERHEAD_ELEMS

    area = 0.0
    works = 0.0
    for g in groups:
        try:
            area += g["num_work"] * g["block_q"] * g["block_k"]
            works += g["num_work"]
        except (KeyError, TypeError):
            return None
    if works <= 0:
        return None
    return (area + works * OVERHEAD_ELEMS, area, works)


def ingest_event(record: dict[str, Any]) -> None:
    """Collector hook: fold a telemetry record into the persistent store.
    Called for every record the collector writes; cheap kind/gate check
    first so non-store kinds cost one tuple membership test."""
    kind = record.get("kind")
    if kind not in _HISTORY_KINDS and kind not in _SPECIAL_KINDS:
        return
    if not store_active():
        return
    st = get_store()
    if st is None:
        return
    if kind == "model_drift":
        st.record_drift(
            {
                k: record[k]
                for k in ("model", "alpha", "rel_err", "predicted",
                          "measured_ms", "extras")
                if k in record
            }
        )
        return
    wall_ms = record.get("wall_ms")
    if kind == "attn_step":
        key = {f: record.get(f) for f in _ATTN_KEY_FIELDS}
        st.record_history("attn_step", key, wall_ms)
        if wall_ms is not None and record.get("backend"):
            # the step wall time is a calc_attn measurement; finer
            # decisions (ffa_bwd, serve_decode) are measured by their own
            # harnesses/tests and land as explicit measure rows
            bwd_key = record.get("bwd_key")
            # keyed exactly like DistAttnRuntime._policy_key so the
            # registry's measured lookup joins against these rows
            mkey = {
                "mask_sig": record.get("mask_sig"),
                "mesh_sig": record.get("mesh_sig"),
                "env_sig": record.get("env_sig"),
            }
            st.record_measurement(
                "calc_attn",
                mkey,
                str(record["backend"]),
                float(wall_ms),
                bwd_mode=record.get("bwd_mode"),
            )
            pred = _tile_score_prediction(record)
            if pred is not None:
                area, works = pred[1], pred[2]
                st.record_observation(
                    "tile_score", pred[0], float(wall_ms),
                    mask_sig=record.get("mask_sig"),
                    area=area, works=works,
                )
            if bwd_key is not None and record.get("bwd_cost") is not None:
                st.record_observation(
                    "bwd_cost", float(record["bwd_cost"]), float(wall_ms),
                    bwd_mode=record.get("bwd_mode"), bwd_key=bwd_key,
                )
    elif kind == "serve_step":
        key = {
            "occupancy": record.get("occupancy"),
            "pages_in_use": record.get("pages_in_use"),
        }
        st.record_history("serve_step", key, wall_ms)
        backend = record.get("decode_backend")
        if backend is None:
            from ..kernels import registry as _kreg

            backend = _kreg.last_choice("serve_decode")
        if wall_ms is not None and backend:
            st.record_measurement(
                "serve_decode",
                _kreg_last_key_or(key),
                str(backend),
                float(wall_ms),
            )
    elif kind == "plan_solve":
        key = {
            k: record.get(k)
            for k in ("signature", "cp_size", "num_slices", "planner")
            if k in record
        }
        st.record_history("plan_solve", key, wall_ms)
    elif kind == "rank_health":
        st.record_rank_health(
            rank=int(record.get("rank", -1)),
            wall_ms=record.get("wall_ms"),
            ewma_ms=record.get("ewma_ms"),
            capacity=float(record.get("capacity", 1.0)),
            degraded=bool(record.get("degraded", False)),
        )
    elif kind == "step_retry":
        key = {
            k: record.get(k)
            for k in ("stage", "from_backend", "to_backend", "error")
            if k in record
        }
        st.record_history("step_retry", key, wall_ms)


def _kreg_last_key_or(default: Any) -> Any:
    from ..kernels import registry as _kreg

    last = _kreg.last_key("serve_decode")
    return default if last is None else last
