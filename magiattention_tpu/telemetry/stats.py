"""Host-side work accounting shared by the telemetry call sites.

``band_area`` is the TRUE score-matrix element count of a band-slice set —
the numerator of every padding-efficiency figure (the FFA planner's padded
grid work is the denominator), and the base of estimated-FLOP numbers
(fwd flops = 4 * area * head_dim * num_heads_q, the FlashAttention-2
convention perf_report.py already uses).
"""

from __future__ import annotations

import numpy as np


def band_area(
    qr: np.ndarray, kr: np.ndarray, d_lo: np.ndarray, d_hi: np.ndarray
) -> int:
    """Exact (i, j) pair count of band slices: rows i in [qs, qe), cols j in
    [ks, ke) with lo <= j - i <= hi. Vectorized per slice over rows."""
    total = 0
    for s in range(len(qr)):
        qs, qe = int(qr[s, 0]), int(qr[s, 1])
        ks, ke = int(kr[s, 0]), int(kr[s, 1])
        lo, hi = int(d_lo[s]), int(d_hi[s])
        if qs >= qe or ks >= ke or lo > hi:
            continue
        i = np.arange(qs, qe, dtype=np.int64)
        j0 = np.maximum(ks, i + lo)
        j1 = np.minimum(ke - 1, i + hi)
        total += int(np.maximum(j1 - j0 + 1, 0).sum())
    return total
