"""Violation records + the named rule registry for the plan verifier.

Severity taxonomy: ``error`` marks an invariant whose violation produces
wrong results or a hang inside shard_map (the runtime hook raises on
these); ``warning`` marks a plan that is correct but off-contract on a
quality bound (load imbalance, dead overlap stage, non-minimal padding).
"""

from __future__ import annotations

from dataclasses import dataclass, field

ERROR = "error"
WARNING = "warning"

# The named rule set (one-line rationale each; full catalogue with
# examples in docs/plan_invariants.md).
RULES: dict[str, str] = {
    "R1": "slice well-formedness: non-negative, in-bounds, "
          "mask-type/band-consistent q/k ranges",
    "R2": "dispatch partition: chunks cover [0, total_seqlen) exactly once "
          "per rank-set; per-rank area within the declared balance bound",
    "R3": "zero-redundancy comms: per-stage cast rows disjoint + complete "
          "vs remote KV demand, reduce/gather indices mirror cast rows, "
          "wire rows exceed payload only via declared alignment padding",
    "R4": "overlap staging: stage partition covers all remote work; "
          "overlap degree consistent between CommMeta and CalcMeta",
    "R5": "tile legality: chosen blocks respect TPU alignment, divide the "
          "fwd-padded geometry (bwd overrides) and fit the VMEM budget",
    # Kernel contract rules (analysis/kernel_check.py; catalogue with
    # examples in docs/kernel_contracts.md).
    "K1": "kernel VMEM budget: sum of BlockSpec + scratch footprints fits "
          "the per-step budget with headroom, and the shared mem_budget "
          "estimator upper-bounds the exact residency",
    "K2": "accumulator discipline: every cross-step scratch accumulator is "
          "zero-initialized under the is-first guard (innermost-position "
          "qualified when the grid revisits tiles) and flushed exactly "
          "once under the is-last guard",
    "K3": "index-map bounds: every index_map output x block shape stays "
          "inside its operand for all grid points",
    "K4": "dtype/precision: f32 accumulator scratch, f32-preferred "
          "dot_generals, no implicit f32->bf16 truncation before the "
          "final guarded write",
    "K5": "cache-key soundness: every env key consumed under kernels/ "
          "appears in ENV_KEYS_AFFECTING_RUNTIME (or the audited "
          "no-lowering-effect allowlist)",
}

# Which verifier rule(s) cover each public dataclass in meta/collection.
# The AST linter (analysis/lint.py, rule MAGI-L004) fails when a public
# dataclass appears there without an entry here — adding a new plan
# object forces someone to decide how it is verified.
RULE_COVERAGE: dict[str, tuple[str, ...]] = {
    "DispatchMeta": ("R2",),
    "GroupCollectiveArg": ("R3",),
    "CommMeta": ("R3", "R4"),
    "AttnArg": ("R1",),
    "CalcMeta": ("R1", "R4"),
    "DynamicAttnPlan": ("R1", "R3", "R4"),
}


@dataclass(frozen=True)
class Violation:
    """One rule violation at one site.

    Attributes:
        rule_id: "R1".."R5" (see :data:`RULES`).
        severity: "error" | "warning".
        site: where in the plan (e.g. "kv_stage0 transfer_table[2][1]").
        detail: what exactly is wrong, with the offending values.
    """

    rule_id: str
    severity: str
    site: str
    detail: str

    def __str__(self) -> str:
        return f"[{self.rule_id}:{self.severity}] {self.site}: {self.detail}"


class PlanVerificationError(ValueError):
    """Raised by the runtime hook on error-severity violations."""

    def __init__(self, report: "VerifyReport") -> None:
        self.report = report
        errs = report.errors()
        lines = [f"plan verification failed ({len(errs)} error(s)):"]
        lines += [f"  {v}" for v in errs]
        super().__init__("\n".join(lines))


@dataclass
class VerifyReport:
    """Outcome of one verifier run: rules exercised + violations found."""

    violations: list[Violation] = field(default_factory=list)
    rules_run: list[str] = field(default_factory=list)

    def add(self, rule_id: str, severity: str, site: str, detail: str) -> None:
        self.violations.append(Violation(rule_id, severity, site, detail))

    def mark_run(self, rule_id: str) -> None:
        if rule_id not in self.rules_run:
            self.rules_run.append(rule_id)

    def errors(self) -> list[Violation]:
        return [v for v in self.violations if v.severity == ERROR]

    def warnings(self) -> list[Violation]:
        return [v for v in self.violations if v.severity == WARNING]

    def fired_rules(self) -> set[str]:
        return {v.rule_id for v in self.violations}

    def ok(self) -> bool:
        return not self.errors()

    def raise_if_errors(self) -> None:
        if not self.ok():
            raise PlanVerificationError(self)

    def summary(self) -> str:
        head = (
            f"plan verify: rules={','.join(self.rules_run) or '-'} "
            f"errors={len(self.errors())} warnings={len(self.warnings())}"
        )
        return "\n".join([head] + [f"  {v}" for v in self.violations])
