"""Static analysis layer: plan verifier + repo-rule linter.

Two complementary passes turn the codebase's implicit contracts into
machine-checked ones (see docs/plan_invariants.md):

- :mod:`verifier` proves (or reports violations of) the named rule set
  R1-R5 over already-constructed plan metadata — slices, ``DispatchMeta``,
  ``CommMeta``/``GroupCollectiveArg``, ``CalcMeta``, ``DynamicAttnPlan``
  and tile choices — before any collective runs.
- :mod:`lint` is an AST-based linter enforcing codebase rules (no raw
  ``os.environ`` outside ``env/``, no host clocks in kernels/functional,
  no ``print`` in library code, every public ``meta/collection`` dataclass
  covered by a verifier rule).

Entry points: ``make analysis``, ``scripts/verify_plans.py`` (golden
corpus), and the opt-in runtime hook ``MAGI_ATTENTION_VERIFY_PLANS=1``
(``dist_attn_runtime_mgr`` -> :func:`maybe_verify_runtime`).
"""

from .violation import (  # noqa: F401
    PlanVerificationError,
    RULES,
    RULE_COVERAGE,
    VerifyReport,
    Violation,
)
from .verifier import (  # noqa: F401
    maybe_verify_runtime,
    verify_dynamic_plan,
    verify_plan,
)
