"""Static analysis layer: plan verifier + kernel contract checker + linter.

Three complementary passes turn the codebase's implicit contracts into
machine-checked ones (see docs/plan_invariants.md and
docs/kernel_contracts.md):

- :mod:`verifier` proves (or reports violations of) the named rule set
  R1-R5 over already-constructed plan metadata — slices, ``DispatchMeta``,
  ``CommMeta``/``GroupCollectiveArg``, ``CalcMeta``, ``DynamicAttnPlan``
  and tile choices — before any collective runs.
- :mod:`kernel_check` proves the named rule set K1-K5 over every
  ``pl.pallas_call`` site in ``kernels/``: VMEM residency, accumulator
  init/flush discipline, index-map bounds, dtype/precision, and
  cache-key soundness — abstractly, without executing kernel bodies.
- :mod:`lint` is an AST-based linter enforcing codebase rules (no raw
  ``os.environ`` outside ``env/``, no host clocks in kernels/functional,
  no ``print`` in library code, every public ``meta/collection`` dataclass
  covered by a verifier rule, every env key documented).

Entry points: ``make analysis``, ``scripts/verify_plans.py`` and
``scripts/kernel_audit.py`` (golden corpora), and the opt-in runtime hook
``MAGI_ATTENTION_VERIFY_PLANS=1`` (``dist_attn_runtime_mgr`` ->
:func:`maybe_verify_runtime`).

:mod:`kernel_check` is re-exported lazily (PEP 562 ``__getattr__``): it
imports ``kernels.tile_policy`` at module scope and jax inside functions,
and eagerly importing it here would tax every jax-free consumer of the
violation registry.
"""

from .violation import (  # noqa: F401
    PlanVerificationError,
    RULES,
    RULE_COVERAGE,
    VerifyReport,
    Violation,
)
from .verifier import (  # noqa: F401
    maybe_verify_runtime,
    verify_dynamic_plan,
    verify_plan,
)

_KERNEL_CHECK_EXPORTS = frozenset(
    {
        "capture_ffa_contracts",
        "check_contract",
        "check_env_keys",
        "check_kernel_sources",
        "check_reachable_space",
        "discover_pallas_sites",
        "golden_corpus",
        "run_kernel_audit",
        "run_seeded_mutations",
    }
)


def __getattr__(name: str):
    if name in _KERNEL_CHECK_EXPORTS:
        from . import kernel_check

        return getattr(kernel_check, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
