"""Static plan verifier: prove R1-R5 over constructed plan metadata.

Every rule takes the *already-built* host metadata objects — nothing here
touches devices or re-runs solvers — and appends structured
:class:`~.violation.Violation` records instead of asserting, so one pass
reports every problem at once (CI) and the runtime hook can decide what is
fatal (error severity) vs. advisory (warning).

The rule bodies deliberately re-derive expectations from first principles
(coverage algebra over ``AttnRanges``, closed-form band areas) rather than
replaying solver code paths: a bug shared by solver and verifier would
otherwise verify itself.
"""

from __future__ import annotations

import numpy as np

from ..common.range import AttnRange
from ..common.ranges import AttnRanges
from .violation import ERROR, WARNING, VerifyReport

# Tile alignment quanta (TPU MXU/VPU lane geometry; see kernels/tile_policy
# NUM_LANES and kernels/ffa.resolve_bwd_overrides' env-override gate).
_BQ_QUANTUM = 8
_BK_QUANTUM = 128


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


# ---------------------------------------------------------------------------
# R1 — slice well-formedness
# ---------------------------------------------------------------------------


def check_attn_arg(report: VerifyReport, arg, site: str) -> None:
    """R1 over one AttnArg's (N, 2) range arrays in local coordinates."""
    report.mark_run("R1")
    n = arg.num_slices
    if n == 0:
        return
    qr, kr = arg.q_ranges, arg.k_ranges
    d_lo, d_hi = arg.d_lo, arg.d_hi
    if qr.min() < 0 or kr.min() < 0:
        report.add("R1", ERROR, site, "negative range endpoint in slice set")
    bad = np.nonzero((qr[:, 0] > qr[:, 1]) | (kr[:, 0] > kr[:, 1]))[0]
    for i in bad[:4]:
        report.add(
            "R1", ERROR, f"{site} slice {int(i)}",
            f"inverted range q={qr[i].tolist()} k={kr[i].tolist()}",
        )
    if arg.total_seqlen_q and qr.max() > arg.total_seqlen_q:
        report.add(
            "R1", ERROR, site,
            f"q slice reaches {int(qr.max())} > extent {arg.total_seqlen_q}",
        )
    if arg.total_seqlen_k and kr.max() > arg.total_seqlen_k:
        report.add(
            "R1", ERROR, site,
            f"k slice reaches {int(kr.max())} > extent {arg.total_seqlen_k}",
        )
    # an inverted band on a non-empty rectangle attends nothing — a slice
    # that should have been dropped at construction
    nonempty = (qr[:, 0] < qr[:, 1]) & (kr[:, 0] < kr[:, 1])
    inv_band = np.nonzero(nonempty & (d_lo > d_hi))[0]
    for i in inv_band[:4]:
        report.add(
            "R1", WARNING, f"{site} slice {int(i)}",
            f"empty band [{int(d_lo[i])}, {int(d_hi[i])}] on non-empty "
            "rectangle (dead work item)",
        )


def check_global_slices(
    report: VerifyReport,
    q_ranges: AttnRanges,
    k_ranges: AttnRanges,
    mask_types,
    total_seqlen_q: int,
    total_seqlen_k: int,
) -> None:
    """R1 over the user-level (q_range, k_range, mask_type) triples."""
    from ..common.enum import AttnMaskType

    report.mark_run("R1")
    if not (len(q_ranges) == len(k_ranges) == len(mask_types)):
        report.add(
            "R1", ERROR, "global slices",
            f"count mismatch: {len(q_ranges)} q vs {len(k_ranges)} k vs "
            f"{len(mask_types)} mask types",
        )
        return
    for i, (qr, kr, mt) in enumerate(zip(q_ranges, k_ranges, mask_types)):
        site = f"global slice {i}"
        if not qr.is_valid() or not kr.is_valid():
            report.add("R1", ERROR, site, f"invalid range q={qr} k={kr}")
            continue
        if qr.end > total_seqlen_q:
            report.add(
                "R1", ERROR, site,
                f"q range {qr} exceeds total_seqlen_q {total_seqlen_q}",
            )
        if kr.end > total_seqlen_k:
            report.add(
                "R1", ERROR, site,
                f"k range {kr} exceeds total_seqlen_k {total_seqlen_k}",
            )
        try:
            AttnMaskType.normalize(mt)
        except (KeyError, ValueError):
            report.add("R1", ERROR, site, f"unknown mask type {mt!r}")


def check_bucket(report: VerifyReport, bucket) -> None:
    """R1 over the chunked global bucket's AttnSlices."""
    report.mark_run("R1")
    for chunk in bucket.q_chunks:
        for j, s in enumerate(chunk.attn_slices):
            site = f"chunk {chunk.chunk_id} slice {j}"
            if not s.q_range.is_valid() or not s.k_range.is_valid():
                report.add(
                    "R1", ERROR, site,
                    f"invalid range q={s.q_range} k={s.k_range}",
                )
                continue
            if not s.q_range.is_subrange_of(chunk.q_range):
                report.add(
                    "R1", ERROR, site,
                    f"slice q {s.q_range} escapes chunk q {chunk.q_range}",
                )
            if not s.q_range.is_empty() and s.d_lo > s.d_hi:
                report.add(
                    "R1", WARNING, site,
                    f"empty band [{s.d_lo}, {s.d_hi}] survived chunking",
                )


# ---------------------------------------------------------------------------
# R2 — dispatch partition
# ---------------------------------------------------------------------------


def check_dispatch(
    report: VerifyReport,
    dispatch_meta,
    bucket=None,
    balance_bound: float = 2.0,
    capacities=None,
) -> None:
    """R2: the chunk->rank assignment partitions the sequence exactly once.

    ``balance_bound`` is the declared per-rank area bound relative to the
    balance lower bound ``max(ceil(total/cp), max_chunk_area)`` — exceeding
    it is a warning (the AUTO dispatcher may trade balance for comm volume
    on purpose), never an error.

    ``capacities`` (per-rank weights, see dispatch_solver.solve) switches
    the balance sub-check to its weighted form: per-rank completion time is
    ``area_r / w_r`` and the lower bound is
    ``max(total/sum(w_active), max_chunk/max_w)``. A rank with zero weight
    owning any chunk is an ERROR — a drained rank must receive no work.
    """
    report.mark_run("R2")
    meta = dispatch_meta
    site = "dispatch partitions"
    if meta.total_seqlen % meta.chunk_size:
        report.add(
            "R2", ERROR, site,
            f"total_seqlen {meta.total_seqlen} not divisible by chunk_size "
            f"{meta.chunk_size}",
        )
        return
    num_chunks = meta.total_seqlen // meta.chunk_size
    if len(meta.partitions) != meta.cp_size:
        report.add(
            "R2", ERROR, site,
            f"{len(meta.partitions)} rank partitions != cp_size "
            f"{meta.cp_size}",
        )
    seen: dict[int, int] = {}
    for r, part in enumerate(meta.partitions):
        if list(part) != sorted(part):
            report.add(
                "R2", ERROR, f"rank {r}",
                f"chunk list not ascending: {list(part)}",
            )
        for c in part:
            if not (0 <= c < num_chunks):
                report.add(
                    "R2", ERROR, f"rank {r}",
                    f"chunk id {c} outside [0, {num_chunks})",
                )
            elif c in seen:
                report.add(
                    "R2", ERROR, f"rank {r}",
                    f"chunk {c} already owned by rank {seen[c]} "
                    "(double-dispatched rows)",
                )
            else:
                seen[c] = r
    dropped = [c for c in range(num_chunks) if c not in seen]
    if dropped:
        report.add(
            "R2", ERROR, site,
            f"chunks never dispatched (rows fall out of the attention): "
            f"{dropped[:8]}{'...' if len(dropped) > 8 else ''}",
        )
    caps = None
    if capacities is not None and len(meta.partitions) == meta.cp_size:
        caps = [float(w) for w in capacities]
        if len(caps) != meta.cp_size:
            report.add(
                "R2", ERROR, site,
                f"{len(caps)} capacity weights != cp_size {meta.cp_size}",
            )
            caps = None
        else:
            for r, part in enumerate(meta.partitions):
                if caps[r] <= 0.0 and len(part) > 0:
                    report.add(
                        "R2", ERROR, f"rank {r}",
                        f"drained rank (capacity {caps[r]}) owns "
                        f"{len(part)} chunks — zero-weight ranks must "
                        "receive no work",
                    )
    if bucket is not None and not dropped and meta.cp_size > 0:
        areas = {c.chunk_id: c.area for c in bucket.q_chunks}
        if len(areas) == num_chunks and sum(areas.values()) > 0:
            per_rank = [
                sum(areas[c] for c in part) for part in meta.partitions
            ]
            if caps is not None and any(w > 0 for w in caps):
                active = [w for w in caps if w > 0]
                lb = max(
                    sum(areas.values()) / sum(active),
                    max(areas.values()) / max(active),
                )
                times = [
                    per_rank[r] / caps[r]
                    for r in range(meta.cp_size)
                    if caps[r] > 0
                ]
                if lb and times and max(times) > balance_bound * lb:
                    report.add(
                        "R2", WARNING, site,
                        f"weighted per-rank time {max(times):.1f} exceeds "
                        f"balance bound {balance_bound} x weighted lower "
                        f"bound {lb:.1f} (per_rank={per_rank}, "
                        f"capacities={caps})",
                    )
            else:
                lb = max(
                    -(-sum(areas.values()) // meta.cp_size),
                    max(areas.values()),
                )
                if lb and max(per_rank) > balance_bound * lb:
                    report.add(
                        "R2", WARNING, site,
                        f"per-rank area {max(per_rank)} exceeds balance "
                        f"bound {balance_bound} x lower bound {lb} "
                        f"(per_rank={per_rank})",
                    )


# ---------------------------------------------------------------------------
# R3 — zero-redundancy comms
# ---------------------------------------------------------------------------


def check_group_collective_arg(
    report: VerifyReport,
    arg,
    site: str,
    split_alignment: int = 128,
    src_shard_len: int | None = None,
    src_host_ranges: list[AttnRanges] | None = None,
) -> None:
    """R3 structural checks on one GroupCollectiveArg (any cast stream).

    Verifies the transpose-consistency of the wire program: send counts
    mirror the transfer table, receive selections mirror send positions
    exactly once (the same index algebra whose jax-AD transpose is
    GroupReduce — a double-selected row would double-count in the reduce),
    and every padded capacity is the minimal aligned cover of the true
    payload.
    """
    report.mark_run("R3")
    cp = arg.send_counts.shape[0]
    counts = arg.send_counts

    for dst in range(cp):
        for src in range(cp):
            rows = arg.transfer_table[dst][src].total_seqlen
            if rows != int(counts[src, dst]):
                report.add(
                    "R3", ERROR, f"{site} transfer_table[{dst}][{src}]",
                    f"{rows} table rows != send_counts {int(counts[src, dst])}",
                )
        recv = int(counts[:, dst].sum())
        if recv != int(arg.recv_len[dst]):
            report.add(
                "R3", ERROR, f"{site} dst {dst}",
                f"recv_len {int(arg.recv_len[dst])} != summed send counts "
                f"{recv}",
            )
        if int(arg.recv_len[dst]) > arg.r_max:
            report.add(
                "R3", ERROR, f"{site} dst {dst}",
                f"recv_len {int(arg.recv_len[dst])} overflows r_max "
                f"{arg.r_max}",
            )

    # wire rows may exceed payload rows only via declared alignment padding:
    # every capacity must be the minimal aligned cover of its max pair
    max_pair = int(counts.max()) if counts.size else 0
    want_cap = _round_up(max(max_pair, 1), split_alignment)
    if arg.a_cap != want_cap:
        report.add(
            "R3", ERROR if arg.a_cap < max_pair else WARNING, site,
            f"a_cap {arg.a_cap} is not the minimal aligned capacity "
            f"{want_cap} for max pair {max_pair} (alignment "
            f"{split_alignment}): undeclared wire padding",
        )
    want_rmax = _round_up(
        max(int(arg.recv_len.max()) if cp else 0, 1), split_alignment
    )
    if arg.r_max < int(arg.recv_len.max() if cp else 0):
        pass  # already reported as overflow above
    elif arg.r_max > want_rmax:
        report.add(
            "R3", WARNING, site,
            f"r_max {arg.r_max} exceeds minimal aligned receive length "
            f"{want_rmax}: undeclared buffer padding",
        )

    if arg.pp_caps:
        pp_align = min(split_alignment, 8)
        for delta, cap in zip(arg.pp_deltas, arg.pp_caps):
            mx = max(
                int(counts[s, (s + delta) % cp]) for s in range(cp)
            )
            if cap != _round_up(max(mx, 1), pp_align):
                report.add(
                    "R3", ERROR if cap < mx else WARNING,
                    f"{site} ppermute delta {delta}",
                    f"cap {cap} not minimal aligned cover of max pair {mx}",
                )

    # recv_sel: every selected flat slot must point at a filled send
    # position of the (src, dst) pair, each exactly once
    for dst in range(cp):
        n = int(arg.recv_len[dst])
        sel = np.asarray(arg.recv_sel[dst, :n], dtype=np.int64)
        if n == 0:
            continue
        if sel.min() < 0 or sel.max() >= cp * arg.a_cap:
            report.add(
                "R3", ERROR, f"{site} dst {dst}",
                "recv_sel index outside the (cp * a_cap) receive buffer",
            )
            continue
        if len(np.unique(sel)) != n:
            report.add(
                "R3", ERROR, f"{site} dst {dst}",
                "recv_sel selects a wire slot more than once "
                "(rows would double-count in the transpose reduce)",
            )
        srcs, pos = sel // arg.a_cap, sel % arg.a_cap
        over = pos >= counts[srcs, dst]
        if over.any():
            report.add(
                "R3", ERROR, f"{site} dst {dst}",
                f"recv_sel selects {int(over.sum())} padding slot(s) "
                "beyond the pair's send count",
            )

    # send_idx: gathered local rows must be in-bounds and mirror the
    # transfer table exactly (same rows, same order)
    for src in range(cp):
        for dst in range(cp):
            n = int(counts[src, dst])
            if n == 0:
                continue
            idx = np.asarray(arg.send_idx[src, dst, :n], dtype=np.int64)
            if idx.min() < 0 or (
                src_shard_len is not None and idx.max() >= src_shard_len
            ):
                report.add(
                    "R3", ERROR, f"{site} send_idx[{src}][{dst}]",
                    f"local row index outside [0, {src_shard_len})",
                )
                continue
            if src_host_ranges is not None:
                loc = src_host_ranges[src].locator()
                try:
                    want = np.concatenate(
                        [
                            np.arange(ls, le, dtype=np.int64)
                            for g in arg.transfer_table[dst][src]
                            for ls, le in loc.to_local(g.start, g.end)
                        ]
                    )
                except Exception as e:  # RangeError: rows not owned by src
                    report.add(
                        "R3", ERROR, f"{site} transfer_table[{dst}][{src}]",
                        f"cast rows not owned by source rank {src}: {e}",
                    )
                    continue
                if len(want) != n or (idx != want).any():
                    report.add(
                        "R3", ERROR, f"{site} send_idx[{src}][{dst}]",
                        "gathered local rows do not mirror the transfer "
                        "table's cast rows",
                    )


def check_hier_plan(report, plan, arg, host_ranges, site: str) -> None:
    """R3 fabric-split sub-check for a two-level (DCN x ICI) stage plan.

    First-principles simulation with global row ids: run the phase-A
    gather/exchange over the dcn axis, then the phase-B forwarding over the
    ici axis, and require the final receive buffer to reconstruct the flat
    plan's receive buffer row-for-row (the phase-A + phase-B row multisets
    are exactly the flat sends — zero-redundancy preserved across fabrics).
    Additionally, every cross-node (dst node, src) row must cross the DCN
    exactly once, and intra-node rows must never touch it.
    """
    report.mark_run("R3")
    n_outer, n_inner = plan.n_outer, plan.n_inner
    cp = plan.cp_size
    if cp != arg.send_counts.shape[0] or n_outer * n_inner != cp:
        report.add(
            "R3", ERROR, site,
            f"hier plan geometry ({n_outer}x{n_inner}) inconsistent with "
            f"the stage's cp {arg.send_counts.shape[0]}",
        )
        return

    # per-rank global row ids of the kv shard (locator order), -1 padded
    shard_ids = np.full((cp, plan.shard_len), -1, dtype=np.int64)
    for r in range(cp):
        chunks = [
            np.arange(g.start, g.end, dtype=np.int64) for g in host_ranges[r]
        ]
        flat = (
            np.concatenate(chunks) if chunks else np.zeros(0, dtype=np.int64)
        )
        if flat.size > plan.shard_len:
            report.add(
                "R3", ERROR, site,
                f"rank {r} owns {flat.size} rows > hier shard_len "
                f"{plan.shard_len}",
            )
            return
        shard_ids[r, : flat.size] = flat

    # phase A over the dcn axis: rank (o_s, i) -> aligned peer (o_d, i)
    a_cap = plan.a_send_idx.shape[2]
    ra = plan.a_recv_sel.shape[1]
    recv_a = np.full((cp, ra), -1, dtype=np.int64)
    crossed: dict[tuple[int, int], np.ndarray] = {}  # (dst_node, src) -> gids
    for r in range(cp):
        o_d, i = divmod(r, n_inner)
        n = int(plan.a_recv_len[r])
        if n == 0:
            continue
        sel = np.asarray(plan.a_recv_sel[r, :n], dtype=np.int64)
        if sel.min() < 0 or sel.max() >= n_outer * a_cap:
            report.add(
                "R3", ERROR, f"{site} hier phase A dst {r}",
                "a_recv_sel index outside the (n_outer * a_cap) buffer",
            )
            return
        o_s, pos = sel // a_cap, sel % a_cap
        src = o_s * n_inner + i
        local = np.asarray(plan.a_send_idx, dtype=np.int64)[src, o_d, pos]
        gids = shard_ids[src, local]
        recv_a[r, :n] = gids
        for s in np.unique(src):
            got = gids[src == s]
            key = (o_d, int(s))
            crossed[key] = (
                np.concatenate([crossed[key], got]) if key in crossed else got
            )

    # exactly-once DCN crossing per (dst node, src): the phase-A rows must
    # be the dedup-merged union of the node's flat requests from that src
    total_dcn = 0
    for o_d in range(n_outer):
        for src in range(cp):
            expect_ranges = AttnRanges()
            for d in range(o_d * n_inner, (o_d + 1) * n_inner):
                for g in arg.transfer_table[d][src]:
                    expect_ranges.append(g)
            expect = (
                np.concatenate(
                    [
                        np.arange(g.start, g.end, dtype=np.int64)
                        for g in expect_ranges.merge()
                    ]
                )
                if len(expect_ranges)
                else np.zeros(0, dtype=np.int64)
            )
            got = np.sort(crossed.get((o_d, src), np.zeros(0, np.int64)))
            if src // n_inner == o_d:
                if got.size:
                    report.add(
                        "R3", ERROR, f"{site} hier node {o_d} src {src}",
                        f"{got.size} intra-node rows crossed the DCN",
                    )
                continue
            total_dcn += got.size
            if got.size != expect.size or (
                got.size and (got != np.sort(expect)).any()
            ):
                report.add(
                    "R3", ERROR, f"{site} hier node {o_d} src {src}",
                    f"phase-A rows ({got.size}) are not the exactly-once "
                    f"dedup of the node's flat requests ({expect.size})",
                )
    if total_dcn != plan.dcn_rows():
        report.add(
            "R3", ERROR, site,
            f"dcn_rows() {plan.dcn_rows()} != simulated DCN crossings "
            f"{total_dcn}",
        )

    # phase B over the ici axis from [shard | recv_a], then byte-identity
    # of the final buffer with the flat plan's receive buffer
    buf_ids = np.concatenate([shard_ids, recv_a], axis=1)
    b_cap = plan.b_send_idx.shape[2]
    b_send = np.asarray(plan.b_send_idx, dtype=np.int64)
    for dst in range(cp):
        o, i_d = divmod(dst, n_inner)
        n = int(arg.recv_len[dst])
        if n == 0:
            continue
        sel = np.asarray(plan.b_recv_sel[dst, :n], dtype=np.int64)
        if sel.min() < 0 or sel.max() >= n_inner * b_cap:
            report.add(
                "R3", ERROR, f"{site} hier phase B dst {dst}",
                "b_recv_sel index outside the (n_inner * b_cap) buffer",
            )
            return
        i_s, pos = sel // b_cap, sel % b_cap
        src = o * n_inner + i_s
        local = b_send[src, i_d, pos]
        if local.size and local.max() >= buf_ids.shape[1]:
            report.add(
                "R3", ERROR, f"{site} hier phase B dst {dst}",
                "b_send_idx beyond the [shard | phase-A recv] buffer",
            )
            return
        final = buf_ids[src, local]
        fsel = np.asarray(arg.recv_sel[dst, :n], dtype=np.int64)
        fsrc, fpos = fsel // arg.a_cap, fsel % arg.a_cap
        flat = shard_ids[
            fsrc, np.asarray(arg.send_idx, dtype=np.int64)[fsrc, dst, fpos]
        ]
        if (final != flat).any():
            report.add(
                "R3", ERROR, f"{site} hier dst {dst}",
                f"{int((final != flat).sum())} rows of the two-phase "
                "receive buffer diverge from the flat plan's buffer",
            )


def _remote_demand(bucket, dispatch_meta, kv_own: AttnRanges, rank: int):
    """Global kv rows rank's slices need but the rank does not own."""
    chunks_by_id = {c.chunk_id: c for c in bucket.q_chunks}
    need = AttnRanges()
    for cid in dispatch_meta.partitions[rank]:
        chunk = chunks_by_id.get(cid)
        if chunk is None:
            continue
        for s in chunk.attn_slices:
            nk = s.shrink().needed_k_range()
            if not nk.is_empty():
                need.append(nk)
    return need.merge().find_hole_ranges(kv_own, is_self_merged=True)


def check_comm_demand(
    report: VerifyReport,
    comm_meta,
    dispatch_meta,
    bucket,
    dispatch_meta_kv=None,
) -> None:
    """R3 coverage: per rank, cast rows across all stages are pairwise
    disjoint (each remote row fetched exactly once — the zero-redundancy
    claim) and exactly equal to the remote KV demand derived independently
    from the slice set."""
    report.mark_run("R3")
    cp = dispatch_meta.cp_size
    kv_meta = dispatch_meta_kv or dispatch_meta
    kv_ranges = comm_meta.kv_host_ranges or kv_meta.host_ranges_per_rank
    for dst in range(cp):
        cast = AttnRanges()
        for st, s in enumerate(comm_meta.kv_stages):
            for src in range(cp):
                if src == dst and s.transfer_table[dst][src].total_seqlen:
                    report.add(
                        "R3", ERROR, f"kv_stage{st} dst {dst}",
                        "self-transfer in the kv cast (locally owned rows "
                        "must not cross the wire)",
                    )
                cast.extend(s.transfer_table[dst][src])
        dup = cast.find_overlap_ranges_with_self()
        if not dup.is_empty():
            report.add(
                "R3", ERROR, f"dst {dst}",
                f"cast rows requested more than once across stages: {dup} "
                "(redundant transfer, double-counted in GroupReduce)",
            )
        demand = _remote_demand(bucket, dispatch_meta, kv_ranges[dst], dst)
        missing = demand.find_hole_ranges(cast)
        extra = cast.find_hole_ranges(demand)
        if not missing.is_empty():
            report.add(
                "R3", ERROR, f"dst {dst}",
                f"remote KV demand not covered by any cast stage: {missing}",
            )
        if not extra.is_empty():
            report.add(
                "R3", ERROR, f"dst {dst}",
                f"cast rows no slice needs: {extra} (redundant transfer)",
            )


# ---------------------------------------------------------------------------
# R4 — overlap staging
# ---------------------------------------------------------------------------


def _arg_areas(arg) -> int:
    from ..meta.container.slice import band_area_batch

    if arg.num_slices == 0:
        return 0
    return int(
        band_area_batch(
            arg.q_ranges[:, 0], arg.q_ranges[:, 1],
            arg.k_ranges[:, 0], arg.k_ranges[:, 1],
            arg.d_lo, arg.d_hi,
        ).sum()
    )


def check_overlap(report: VerifyReport, comm_meta, calc_meta) -> None:
    """R4: the stage partition covers all remote work and CommMeta /
    CalcMeta agree on the overlap degree and per-stage buffer lengths."""
    report.mark_run("R4")
    degree = comm_meta.overlap_degree
    n_remote = len(calc_meta.remote_args_per_stage)
    n_lens = len(calc_meta.recv_len_per_stage)
    if not (degree == n_remote == n_lens):
        report.add(
            "R4", ERROR, "overlap degree",
            f"CommMeta has {degree} stages but CalcMeta has {n_remote} "
            f"remote-arg stages and {n_lens} recv lengths",
        )
    for st in range(min(degree, n_remote, n_lens)):
        s = comm_meta.kv_stages[st]
        if s.r_max != calc_meta.recv_len_per_stage[st]:
            report.add(
                "R4", ERROR, f"stage {st}",
                f"comm r_max {s.r_max} != calc recv_len_per_stage "
                f"{calc_meta.recv_len_per_stage[st]}",
            )
        for r, arg in enumerate(calc_meta.remote_args_per_stage[st]):
            if arg.total_seqlen_k != calc_meta.recv_len_per_stage[st]:
                report.add(
                    "R4", ERROR, f"stage {st} rank {r}",
                    f"remote arg extent {arg.total_seqlen_k} != stage "
                    f"recv length {calc_meta.recv_len_per_stage[st]}",
                )
        if int(np.asarray(s.recv_len).max(initial=0)) == 0:
            report.add(
                "R4", WARNING, f"stage {st}",
                "stage receives zero rows on every rank (dead stage)",
            )
    # merged extent and the area identity: merged == host + sum(remote) —
    # remote work dropped from (or invented by) the staging shows up here
    total_recv = sum(calc_meta.recv_len_per_stage)
    for r in range(len(calc_meta.host_args)):
        merged = calc_meta.merged_args[r]
        want_k = (calc_meta.kv_shard_len or 0) + total_recv
        if merged.total_seqlen_k != want_k:
            report.add(
                "R4", ERROR, f"rank {r}",
                f"merged arg k extent {merged.total_seqlen_k} != kv shard "
                f"+ stage buffers {want_k}",
            )
        host_a = _arg_areas(calc_meta.host_args[r])
        remote_a = sum(
            _arg_areas(stage_args[r])
            for stage_args in calc_meta.remote_args_per_stage
        )
        merged_a = _arg_areas(merged)
        if merged_a != host_a + remote_a:
            report.add(
                "R4", ERROR, f"rank {r}",
                f"stage partition loses work: merged area {merged_a} != "
                f"host {host_a} + remote {remote_a}",
            )


# ---------------------------------------------------------------------------
# R5 — tile legality
# ---------------------------------------------------------------------------


def check_tiles(
    report: VerifyReport,
    fwd_blocks: tuple[int, int],
    sq: int,
    sk: int,
    dq_blocks: tuple[int, int] | None = None,
    dkv_blocks: tuple[int, int] | None = None,
    head_dim: int = 128,
    head_dim_v: int = 128,
    itemsize: int = 2,
) -> None:
    """R5: chosen (block_q, block_k) respect the TPU lane quanta, bwd
    overrides divide the fwd-padded geometry, and every pass's resident
    blocks fit the VMEM budget. The byte model is the kernel checker's
    (analysis/kernel_check, rule K1) — the same arithmetic that is proven
    against the captured pallas_call contracts, so R5 and K1 cannot
    disagree about what fits."""
    from .kernel_check import (
        POLICY_VMEM_BUDGET as VMEM_BUDGET,
        bwd_vmem_bytes as _bwd_vmem_bytes,
        fwd_vmem_bytes as _vmem_bytes,
    )

    report.mark_run("R5")
    bq, bk = fwd_blocks

    def _check_quanta(name: str, b_q: int, b_k: int) -> bool:
        ok = True
        if b_q <= 0 or b_q % _BQ_QUANTUM:
            report.add(
                "R5", ERROR, name,
                f"block_q {b_q} not a positive multiple of {_BQ_QUANTUM}",
            )
            ok = False
        if b_k <= 0 or b_k % _BK_QUANTUM:
            report.add(
                "R5", ERROR, name,
                f"block_k {b_k} not a positive multiple of {_BK_QUANTUM} "
                "(TPU lane width)",
            )
            ok = False
        return ok

    if not _check_quanta("fwd blocks", bq, bk):
        return
    if _vmem_bytes(bq, bk, head_dim, head_dim_v, itemsize) > VMEM_BUDGET:
        report.add(
            "R5", ERROR, "fwd blocks",
            f"({bq}, {bk}) at d={head_dim}/dv={head_dim_v} exceeds the "
            f"VMEM budget {VMEM_BUDGET} bytes",
        )
    sqp, skp = _round_up(max(sq, 1), bq), _round_up(max(sk, 1), bk)
    for kind, blocks in (("dq", dq_blocks), ("dkv", dkv_blocks)):
        if blocks is None:
            continue
        ob_q, ob_k = blocks
        if not _check_quanta(f"{kind} blocks", ob_q, ob_k):
            continue
        if sqp % ob_q or skp % ob_k:
            report.add(
                "R5", ERROR, f"{kind} blocks",
                f"({ob_q}, {ob_k}) does not divide the fwd-padded geometry "
                f"({sqp}, {skp}) — the bwd kernel would index past the "
                "padded q/k/v and lse buffers",
            )
        if _bwd_vmem_bytes(
            kind, ob_q, ob_k, head_dim, head_dim_v, itemsize
        ) > VMEM_BUDGET:
            report.add(
                "R5", ERROR, f"{kind} blocks",
                f"({ob_q}, {ob_k}) exceeds the VMEM budget with the "
                f"{kind} pass's fp32 scratch",
            )


def check_plan_extents(report: VerifyReport, plan) -> None:
    """R5, extent half: an FFA plan's live-extent meta columns (EQ0..EK1)
    AND its q-visit flag columns (QVF/QVL — the fused backward's dq
    revisit init/flush guards) must equal the host recomputation from its
    own 9-col band geometry, for BOTH triples (q-major and k-major), and
    the executed-element count they imply must not exceed the padded tile
    work. The kernels skip
    dot_general chunks on these columns (kernels/ffa.py clamp path), so a
    stale or truncated row silently drops attention mass — the same
    invariant rule K3's extent half proves on captured contracts, applied
    here to the plan object before it ever reaches a kernel."""
    import numpy as np

    from ..kernels.ffa_plan import (
        EQ0,
        META_DIM,
        _extend_meta_extents,
        _extend_meta_visits,
        plan_extent_stats,
    )

    report.mark_run("R5")
    triples = (
        ("meta", plan.meta, plan.work_qt, plan.work_kt),
        ("meta_t", plan.meta_t, plan.work_qt_t, plan.work_kt_t),
    )
    for which, meta, wq, wk in triples:
        meta = np.asarray(meta)
        if meta.ndim != 2 or meta.shape[1] != META_DIM:
            report.add(
                "R5", ERROR, which,
                f"plan meta has {meta.shape} columns, expected {META_DIM} "
                "(9 band cols + 4 live-extent cols + 2 q-visit cols)",
            )
            continue
        want = _extend_meta_visits(
            _extend_meta_extents(
                meta[:, :EQ0].astype(np.int32), np.asarray(wq),
                np.asarray(wk), plan.block_q, plan.block_k,
            ),
            np.asarray(wq),
        )
        bad = np.nonzero((meta != want).any(axis=1))[0]
        for w in bad[:8]:
            report.add(
                "R5", ERROR, f"{which}[{int(w)}]",
                f"extent columns {meta[w, EQ0:].tolist()} != host "
                f"recomputation {want[w, EQ0:].tolist()} from the row's "
                "band geometry",
            )
        if len(bad) > 8:
            report.add(
                "R5", ERROR, which,
                f"... and {len(bad) - 8} more extent rows disagree",
            )
    stats = plan_extent_stats(plan)
    if stats["executed_elems"] > stats["padded_elems"]:
        report.add(
            "R5", ERROR, "extent_stats",
            f"executed elements {stats['executed_elems']} exceed the "
            f"padded tile work {stats['padded_elems']} — extents escape "
            "their tiles",
        )


# ---------------------------------------------------------------------------
# orchestrators
# ---------------------------------------------------------------------------


def verify_plan(
    *,
    dispatch_meta=None,
    bucket=None,
    comm_meta=None,
    calc_meta=None,
    dispatch_meta_kv=None,
    global_slices=None,
    tile_blocks=None,
    tile_geom=None,
    split_alignment: int = 128,
    balance_bound: float = 2.0,
    capacities=None,
) -> VerifyReport:
    """Run every rule the supplied metadata allows; returns a VerifyReport.

    Args:
        dispatch_meta / bucket: enable R2 (+ R1 over bucket slices).
        comm_meta / calc_meta: enable R3 / R4 (+ R1 over AttnArgs).
        dispatch_meta_kv: kv ownership for cross-attention plans.
        global_slices: (q_ranges, k_ranges, mask_types, seq_q, seq_k) for
            user-level R1.
        tile_blocks: (fwd, dq | None, dkv | None) block choices for R5.
        tile_geom: (sq, sk, head_dim, head_dim_v, itemsize) for R5; the
            seqlens default to the calc_meta merged geometry.
        split_alignment: the declared wire alignment (GrpCollConfig).
        balance_bound: declared R2 per-rank area bound (x lower bound).
        capacities: per-rank weight vector; switches the R2 balance
            sub-check to its weighted form (see check_dispatch).
    """
    report = VerifyReport()
    if global_slices is not None:
        check_global_slices(report, *global_slices)
    if bucket is not None:
        check_bucket(report, bucket)
    if dispatch_meta is not None:
        check_dispatch(
            report, dispatch_meta, bucket=bucket,
            balance_bound=balance_bound, capacities=capacities,
        )
    if calc_meta is not None:
        for r, arg in enumerate(calc_meta.host_args):
            check_attn_arg(report, arg, f"host_args[{r}]")
        for st, stage_args in enumerate(calc_meta.remote_args_per_stage):
            for r, arg in enumerate(stage_args):
                check_attn_arg(report, arg, f"remote_args[{st}][{r}]")
        for r, arg in enumerate(calc_meta.merged_args):
            check_attn_arg(report, arg, f"merged_args[{r}]")
    if comm_meta is not None:
        kv_meta = dispatch_meta_kv or dispatch_meta
        kv_ranges = comm_meta.kv_host_ranges or (
            kv_meta.host_ranges_per_rank if kv_meta is not None else None
        )
        for st, s in enumerate(comm_meta.kv_stages):
            check_group_collective_arg(
                report, s, f"kv_stage{st}",
                split_alignment=split_alignment,
                src_shard_len=(
                    calc_meta.kv_shard_len if calc_meta is not None else None
                ),
                src_host_ranges=kv_ranges,
            )
            if getattr(s, "hier_plan", None) is not None and (
                kv_ranges is not None
            ):
                check_hier_plan(
                    report, s.hier_plan, s, kv_ranges, f"kv_stage{st}"
                )
        if dispatch_meta is not None and bucket is not None:
            check_comm_demand(
                report, comm_meta, dispatch_meta, bucket,
                dispatch_meta_kv=dispatch_meta_kv,
            )
        if calc_meta is not None:
            check_overlap(report, comm_meta, calc_meta)
    if tile_blocks is not None:
        fwd, dq, dkv = tile_blocks
        if tile_geom is not None:
            sq, sk, d, dv, itemsize = tile_geom
        elif calc_meta is not None:
            sq = calc_meta.shard_len
            sk = (calc_meta.kv_shard_len or 0) + sum(
                calc_meta.recv_len_per_stage
            )
            d, dv, itemsize = 128, 128, 2
        else:
            raise ValueError("tile_blocks needs tile_geom or calc_meta")
        check_tiles(
            report, fwd, sq, sk, dq_blocks=dq, dkv_blocks=dkv,
            head_dim=d, head_dim_v=dv, itemsize=itemsize,
        )
    return report


def verify_dynamic_plan(
    plan, split_alignment: int = 128
) -> VerifyReport:
    """Verify a DynamicAttnPlan: R1 over its per-rank AttnArgs, R3
    structural checks over the three casts, R4 buffer-length consistency
    between the casts and the execution contract."""
    report = VerifyReport()
    for r, arg in enumerate(plan.attn_args):
        check_attn_arg(report, arg, f"dyn attn_args[{r}]")
    for name, cast in (
        ("q_cast", plan.q_cast), ("kv_cast", plan.kv_cast), ("ret", plan.ret)
    ):
        check_group_collective_arg(
            report, cast, name, split_alignment=split_alignment
        )
    report.mark_run("R4")
    relations = (
        ("q_buf_len", plan.q_buf_len, plan.shard_len + plan.q_cast.r_max),
        ("k_buf_len", plan.k_buf_len,
         plan.kv_shard_len + plan.kv_cast.r_max),
        ("ret_len", plan.ret_len, plan.ret.r_max),
    )
    for name, got, want in relations:
        if got != want:
            report.add(
                "R4", ERROR, f"dynamic plan {name}",
                f"{name} {got} inconsistent with cast buffers ({want})",
            )
    mi = np.asarray(plan.merge_idx)
    if mi.size and (mi.min() < 0 or mi.max() > plan.dummy_index):
        report.add(
            "R4", ERROR, "dynamic plan merge_idx",
            f"merge index outside [0, dummy={plan.dummy_index}]",
        )
    return report


def verify_runtime_mgr(mgr, balance_bound: float = 2.0) -> VerifyReport:
    """Verify everything a DistAttnRuntimeMgr planned (static or dynamic),
    including the tile choice the kernels will resolve for its geometry."""
    align = mgr.key.config.grpcoll_config.split_alignment
    if mgr.dynamic_plan is not None:
        return verify_dynamic_plan(mgr.dynamic_plan, split_alignment=align)
    report = verify_plan(
        dispatch_meta=mgr.dispatch_meta_q,
        bucket=mgr.bucket,
        comm_meta=mgr.comm_meta,
        calc_meta=mgr.calc_meta,
        dispatch_meta_kv=(
            mgr.dispatch_meta_kv
            if mgr.dispatch_meta_kv is not mgr.dispatch_meta_q
            else None
        ),
        split_alignment=align,
        balance_bound=balance_bound,
        capacities=getattr(mgr.key, "capacities", None),
    )
    # R5 over the blocks the kernels will resolve for the merged geometry
    from ..kernels.ffa import default_blocks, resolve_bwd_overrides

    sq = mgr.calc_meta.shard_len
    sk = (mgr.calc_meta.kv_shard_len or 0) + sum(
        mgr.calc_meta.recv_len_per_stage
    )
    bq, bk = default_blocks(sq, sk)
    dq, dkv = resolve_bwd_overrides(
        bq, bk, _round_up(max(sq, 1), bq), _round_up(max(sk, 1), bk)
    )
    check_tiles(report, (bq, bk), sq, sk, dq_blocks=dq, dkv_blocks=dkv)
    return report


def maybe_verify_runtime(mgr) -> VerifyReport | None:
    """The opt-in plan-build hook (MAGI_ATTENTION_VERIFY_PLANS=1): verify
    at plan time, emit a ``plan_verify`` telemetry record, raise
    :class:`PlanVerificationError` on error-severity violations."""
    from .. import telemetry
    from ..env import general as env_general

    if not env_general.is_verify_plans_enable():
        return None
    import time

    t0 = time.perf_counter()
    report = verify_runtime_mgr(mgr)
    wall_ms = (time.perf_counter() - t0) * 1e3
    if telemetry.enabled():
        telemetry.record_event(
            "plan_verify",
            planner="dynamic" if mgr.dynamic_plan is not None else "static",
            cp_size=mgr.key.cp_size,
            rules_run=list(report.rules_run),
            violations=len(report.violations),
            errors=len(report.errors()),
            warnings=len(report.warnings()),
            fired_rules=sorted(report.fired_rules()),
            wall_ms=wall_ms,
        )
    report.raise_if_errors()
    return report
