"""Static Pallas kernel contract checker (rules K1-K5).

PR 3's verifier proves plan-level invariants (R1-R5); this module proves
the KERNEL level: every ``pl.pallas_call`` site under ``kernels/`` is
discovered by AST, its contract (grid, BlockSpec shapes + index_maps,
scratch, dtypes) is reconstructed by interception — the wrapper functions
are driven with real plans and dummy operands while ``pallas_call`` is
replaced by a recorder, so the kernel bodies never execute — and the
contract is checked against five rule families:

- **K1** VMEM budget: the exact per-step residency (double-buffered
  in/out blocks + scratch + score-tile intermediates) fits the per-core
  budget with headroom. ONE model backs every layer:
  ``utils/mem_budget.ffa_kernel_residency`` is asserted here to match the
  captured contracts bit-for-bit, the packed-kernel dispatch guards in
  ``kernels/ffa.py`` call it, and the tile policy's candidate filter
  (guarded by the ``vmem_check`` fault-injection site) is asserted equal
  to ``mem_budget.ffa_vmem_budget``/``ffa_bwd_vmem_budget``. The
  abstract sweep (:func:`check_reachable_space`) closes the proof over
  the FULL config space ``tile_policy.reachable_block_space`` can emit —
  not just the sampled corpus.
- **K2** accumulator discipline (source-level, driven by
  ``kernels/ffa.py:PALLAS_CONTRACTS``): every cross-step scratch
  accumulator is zero-initialized under the is-first guard — qualified
  on the innermost grid position when the grid revisits tiles — and
  every output ref is stored exactly once, under the is-last guard (the
  dkv-GQA-pack bug class).
- **K3** index-map bounds: every index_map output x block shape stays
  inside its operand for ALL grid points (vectorized numpy evaluation of
  the captured index_map lambdas over the whole grid). The extent half
  (:func:`check_k3_extents`) proves the EQ0..EK1 live-extent prefetch
  columns — the state the clamp path skips dot chunks on — match a host
  recomputation from the band geometry and respect tile bounds and the
  sublane/lane chunking quanta.
- **K4** dtype/precision: fp32 accumulator scratch, fp32-preferred
  ``dot_general``s, declared out dtypes honored (no implicit f32->bf16
  truncation before the final guarded write).
- **K5** cache-key soundness: every env key consumed under ``kernels/``
  appears in ``ENV_KEYS_AFFECTING_RUNTIME`` or the audited allowlist of
  keys proven not to change lowering.

Violations reuse the :mod:`violation` registry; ``scripts/kernel_audit.py``
sweeps the golden corpus and ``make kernel-audit`` gates ``make test`` on
a clean run. See docs/kernel_contracts.md.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field, replace  # noqa: F401 (replace: test API)
from pathlib import Path

import numpy as np

from ..kernels.ffa_plan import (
    EK1,
    EQ0,
    LANE_QUANTUM,
    META_DIM,
    QE,
    QS,
    SUBLANE_QUANTUM,
    _extend_meta_extents,
)
from ..kernels.tile_policy import VMEM_BUDGET as POLICY_VMEM_BUDGET
from ..utils.mem_budget import (
    VMEM_ALLOWED_BYTES,
    VMEM_HEADROOM_BYTES,
    VMEM_LIMIT_BYTES,
    ffa_bwd_vmem_budget,
    ffa_kernel_residency,
    ffa_vmem_budget,
)
from .violation import ERROR, VerifyReport

__all__ = [
    "AuditSpec",
    "KernelContract",
    "PallasSite",
    "POLICY_VMEM_BUDGET",
    "VMEM_ALLOWED_BYTES",
    "VMEM_HEADROOM_BYTES",
    "VMEM_LIMIT_BYTES",
    "K5_ALLOWLIST",
    "bwd_vmem_bytes",
    "capture_ffa_contracts",
    "check_contract",
    "check_env_keys",
    "check_k3_extents",
    "check_kernel_sources",
    "check_reachable_space",
    "discover_pallas_sites",
    "fwd_vmem_bytes",
    "golden_corpus",
    "padding_stats",
    "run_kernel_audit",
    "run_seeded_mutations",
]

# env keys consumed under kernels/ that are PROVEN not to change kernel
# lowering and are therefore exempt from ENV_KEYS_AFFECTING_RUNTIME
# membership (K5). Every entry carries its proof obligation.
K5_ALLOWLIST: dict[str, str] = {
    "MAGI_ATTENTION_NATIVE_FFA_PLAN": (
        "selects the native-C vs pure-Python FFA plan builder; both emit "
        "identical work-item arrays (parity pinned by the plan tests), so "
        "the traced kernel program cannot differ"
    ),
}


# ---------------------------------------------------------------------------
# the shared VMEM model (verifier R5 delegates here — satellite 3)
# ---------------------------------------------------------------------------


def fwd_vmem_bytes(
    bq: int, bk: int, d: int, dv: int | None = None, itemsize: int = 2
) -> int:
    """Estimated fwd per-step residency — the tile policy's filter model."""
    return ffa_vmem_budget(bq, bk, d, head_dim_v=dv, dtype_bytes=itemsize)


def bwd_vmem_bytes(
    kind: str, bq: int, bk: int, d: int, dv: int | None = None,
    itemsize: int = 2,
) -> int:
    """Estimated bwd per-step residency — the tile policy's filter model."""
    return ffa_bwd_vmem_budget(
        kind, bq, bk, d, head_dim_v=dv, dtype_bytes=itemsize
    )


# ---------------------------------------------------------------------------
# discovery (AST)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PallasSite:
    """One ``pl.pallas_call`` site in the kernels package."""

    relpath: str
    line: int
    wrapper: str  # enclosing function
    kernel_name: str  # the kernel body passed (resolved through partial)


def _kernels_dir() -> Path:
    return Path(__file__).resolve().parents[1] / "kernels"


def discover_pallas_sites(kernels_dir: str | Path | None = None) -> list[PallasSite]:
    """Every ``*.pallas_call`` call site under ``kernels/``, with the kernel
    body name resolved through local ``kernel = partial(<fn>, ...)``
    assignments inside the enclosing wrapper."""
    root = Path(kernels_dir) if kernels_dir else _kernels_dir()
    sites: list[PallasSite] = []
    for path in sorted(root.glob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            partials: dict[str, str] = {}
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and _callee_name(node.value.func) == "partial"
                    and node.value.args
                    and isinstance(node.value.args[0], ast.Name)
                ):
                    partials[node.targets[0].id] = node.value.args[0].id
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "pallas_call"
                ):
                    kernel = "<unknown>"
                    if node.args:
                        arg = node.args[0]
                        if isinstance(arg, ast.Name):
                            kernel = partials.get(arg.id, arg.id)
                        elif (
                            isinstance(arg, ast.Call)
                            and _callee_name(arg.func) == "partial"
                            and arg.args
                            and isinstance(arg.args[0], ast.Name)
                        ):
                            kernel = arg.args[0].id
                    sites.append(
                        PallasSite(
                            relpath=f"kernels/{path.name}",
                            line=node.lineno,
                            wrapper=fn.name,
                            kernel_name=kernel,
                        )
                    )
    return sites


def _callee_name(func: ast.expr) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


# ---------------------------------------------------------------------------
# contract capture (pallas_call interception)
# ---------------------------------------------------------------------------


@dataclass
class KernelContract:
    """The reconstructed contract of one pallas_call at one config."""

    kernel_name: str
    grid: tuple[int, ...]
    num_scalar_prefetch: int
    in_specs: tuple  # of pl.BlockSpec (block_shape + index_map introspected)
    out_specs: tuple
    scratch: tuple[tuple[tuple[int, ...], str], ...]  # (shape, dtype)
    out_shape: tuple[tuple[tuple[int, ...], str], ...]
    prefetch: tuple[np.ndarray, ...]  # concrete scalar-prefetch operands
    operands: tuple[tuple[tuple[int, ...], str], ...]  # tensor (shape, dtype)


class _Captured(Exception):
    pass


class _capture_pallas:
    """Context manager replacing ``pallas.pallas_call`` with a recorder:
    the returned callable snapshots the full contract at call time and
    raises, so no kernel is ever lowered or executed."""

    def __init__(self) -> None:
        self.contracts: list[KernelContract] = []

    def __enter__(self) -> "_capture_pallas":
        from jax.experimental import pallas as pl_mod

        self._mod = pl_mod
        self._real = pl_mod.pallas_call
        contracts = self.contracts

        def recorder(kernel, *, grid_spec=None, out_shape=None, **_kw):
            def runner(*args):
                gs = grid_spec
                if gs is None:
                    # plain-grid pallas_call (grid=/in_specs=/out_specs=
                    # kwargs, no scalar prefetch or scratch) — the delta
                    # kernel's shape
                    from types import SimpleNamespace

                    gs = SimpleNamespace(
                        grid=tuple(_kw.get("grid", ())),
                        in_specs=tuple(_kw.get("in_specs", ())),
                        out_specs=tuple(_kw.get("out_specs", ())),
                        scratch_shapes=tuple(_kw.get("scratch_shapes", ())),
                        num_scalar_prefetch=0,
                    )
                nsp = int(getattr(gs, "num_scalar_prefetch", 0))
                kname = getattr(
                    getattr(kernel, "func", kernel), "__name__", str(kernel)
                )
                oshape = (
                    list(out_shape)
                    if isinstance(out_shape, (list, tuple))
                    else [out_shape]
                )
                out_specs = gs.out_specs
                if not isinstance(out_specs, (list, tuple)):
                    out_specs = (out_specs,)
                contracts.append(
                    KernelContract(
                        kernel_name=kname,
                        grid=tuple(int(dim) for dim in gs.grid),
                        num_scalar_prefetch=nsp,
                        in_specs=tuple(gs.in_specs),
                        out_specs=tuple(out_specs),
                        scratch=tuple(
                            (tuple(s.shape), np.dtype(s.dtype).name)
                            for s in gs.scratch_shapes
                        ),
                        out_shape=tuple(
                            (tuple(o.shape), np.dtype(o.dtype).name)
                            for o in oshape
                        ),
                        prefetch=tuple(np.asarray(a) for a in args[:nsp]),
                        operands=tuple(
                            (tuple(a.shape), np.dtype(a.dtype).name)
                            for a in args[nsp:]
                        ),
                    )
                )
                raise _Captured(kname)

            return runner

        pl_mod.pallas_call = recorder
        return self

    def __exit__(self, *exc) -> None:
        self._mod.pallas_call = self._real


# ---------------------------------------------------------------------------
# audit specs + capture drivers
# ---------------------------------------------------------------------------


@dataclass(frozen=True, eq=False)
class AuditSpec:
    """One golden-corpus configuration to capture contracts at."""

    name: str
    q_ranges: np.ndarray
    k_ranges: np.ndarray
    d_lo: np.ndarray
    d_hi: np.ndarray
    sq: int
    sk: int
    hq: int
    hk: int
    blocks: tuple[int, int]
    d: int = 128
    dv: int = 128
    dtype: str = "bfloat16"
    dq_blocks: tuple[int, int] | None = None
    dkv_blocks: tuple[int, int] | None = None
    emit_ml: bool = False


def capture_ffa_contracts(spec: AuditSpec) -> list[KernelContract]:
    """Drive every FFA wrapper applicable at ``spec`` under capture.

    Applicability mirrors the runtime dispatch predicates in
    ``kernels/ffa.py`` minus their env flags (the audit proves every
    kernel a flag COULD route to), so a config the packed guards refuse
    is audited on the unpacked path only — exactly like the runtime.
    """
    import jax
    import jax.numpy as jnp

    from ..kernels import ffa
    from ..kernels.ffa_plan import get_ffa_plan

    bq, bk = spec.blocks
    plan = get_ffa_plan(
        spec.q_ranges, spec.k_ranges, spec.d_lo, spec.d_hi,
        spec.sq, spec.sk, bq, bk,
    )
    sqp = plan.num_q_tiles * bq
    skp = plan.num_k_tiles * bk
    g = spec.hq // spec.hk
    itemsize = jnp.dtype(spec.dtype).itemsize

    arrays = ffa.plan_arrays(plan)
    dq_triple, dkv_triple = arrays[0:3], arrays[3:6]
    overrides: dict = {}
    if spec.dq_blocks:
        plan_dq = get_ffa_plan(
            spec.q_ranges, spec.k_ranges, spec.d_lo, spec.d_hi,
            spec.sq, spec.sk, *spec.dq_blocks,
        )
        dq_triple = ffa.plan_arrays(plan_dq)[0:3]
        overrides.update(
            block_q_dq=spec.dq_blocks[0], block_k_dq=spec.dq_blocks[1],
            num_work_dq=plan_dq.num_work,
        )
    if spec.dkv_blocks:
        plan_dkv = get_ffa_plan(
            spec.q_ranges, spec.k_ranges, spec.d_lo, spec.d_hi,
            spec.sq, spec.sk, *spec.dkv_blocks,
        )
        dkv_triple = ffa.plan_arrays(plan_dkv)[3:6]
        overrides.update(
            block_q_dkv=spec.dkv_blocks[0], block_k_dkv=spec.dkv_blocks[1],
            num_work_dkv=plan_dkv.num_work_t,
        )

    params = ffa.FFAParams(
        num_work=plan.num_work,
        num_work_t=plan.num_work_t,
        num_q_tiles=plan.num_q_tiles,
        num_k_tiles=plan.num_k_tiles,
        block_q=bq,
        block_k=bk,
        softmax_scale=float(spec.d) ** -0.5,
        softcap=0.0,
        group=g,
        interpret=True,
        emit_max_logits=spec.emit_ml,
        **overrides,
    )
    dtype = jnp.dtype(spec.dtype)
    q_t = jnp.zeros((spec.hq, sqp, spec.d), dtype)
    k_t = jnp.zeros((spec.hk, skp, spec.d), dtype)
    v_t = jnp.zeros((spec.hk, skp, spec.dv), dtype)
    do_t = jnp.zeros((spec.hq, sqp, spec.dv), dtype)
    out_t = jnp.zeros((spec.hq, sqp, spec.dv), dtype)
    lse_t = jnp.zeros((spec.hq, sqp), jnp.float32)
    delta_t = jnp.zeros((spec.hq, sqp), jnp.float32)

    def pack_ok(kind: str, kbq: int, kbk: int) -> bool:
        return (
            g > 1
            and sqp % kbq == 0
            and ffa_kernel_residency(
                kind, kbq, kbk, spec.d, head_dim_v=spec.dv,
                dtype_bytes=itemsize, group=g, packed=True,
            )
            <= VMEM_ALLOWED_BYTES
        )

    def fused_ok(packed_flag: bool) -> bool:
        # mirrors ffa.fused_bwd_feasible: the runtime never routes an
        # over-budget config to the fused kernel, so the audit doesn't
        # drive one either
        kbq, kbk = params.dkv_blocks()
        if packed_flag and (g == 1 or sqp % kbq != 0):
            return False
        return (
            ffa_kernel_residency(
                "fused", kbq, kbk, spec.d, head_dim_v=spec.dv,
                dtype_bytes=itemsize, group=g, packed=packed_flag,
            )
            <= VMEM_ALLOWED_BYTES
        )

    runs: list[tuple] = [
        (ffa._ffa_fwd_pallas, (params, *arrays[0:3], q_t, k_t, v_t)),
        (ffa._ffa_bwd_dq_pallas,
         (params, *dq_triple, q_t, k_t, v_t, do_t, lse_t, delta_t)),
        (ffa._ffa_bwd_dkv_pallas,
         (params, *dkv_triple, q_t, k_t, v_t, do_t, lse_t, delta_t)),
        (ffa._ffa_delta_pallas, (out_t, do_t, bq, True)),
    ]
    if fused_ok(False):
        runs.append(
            (ffa._ffa_bwd_fused_pallas,
             (params, *dkv_triple, q_t, k_t, v_t, do_t, lse_t, delta_t))
        )
    if fused_ok(True):
        runs.append(
            (ffa._ffa_bwd_fused_pallas_gqa,
             (params, *dkv_triple, q_t, k_t, v_t, do_t, lse_t, delta_t))
        )
    if g > 1 and not spec.emit_ml and pack_ok("fwd", bq, bk):
        runs.append(
            (ffa._ffa_fwd_pallas_gqa, (params, *arrays[0:3], q_t, k_t, v_t))
        )
    if pack_ok("dq", *params.dq_blocks()):
        runs.append(
            (ffa._ffa_bwd_dq_pallas_gqa,
             (params, *dq_triple, q_t, k_t, v_t, do_t, lse_t, delta_t))
        )
    if pack_ok("dkv", *params.dkv_blocks()):
        runs.append(
            (ffa._ffa_bwd_dkv_pallas_gqa,
             (params, *dkv_triple, q_t, k_t, v_t, do_t, lse_t, delta_t))
        )

    contracts: list[KernelContract] = []
    with jax.default_device(jax.devices("cpu")[0]):
        for fn, args in runs:
            cap = _capture_pallas()
            with cap:
                try:
                    fn(*args)
                except _Captured:
                    pass
            contracts.extend(cap.contracts)
    return contracts


@dataclass(frozen=True, eq=False)
class DecodeAuditSpec:
    """One paged-decode corpus configuration (kernels/paged_decode.py).
    ``variant`` picks the wrapper driven: "base" (one row per slot),
    "spec" (``spec_k`` draft rows per slot, the speculative-verify
    kernel) or "int8" (quantized pages + per-page scale prefetch)."""

    name: str
    max_seqs: int = 4
    pages_per_seq: int = 8
    num_pages: int = 32
    page_size: int = 128
    hq: int = 4
    hk: int = 2
    d: int = 128
    dv: int = 128
    dtype: str = "bfloat16"
    lengths: tuple[int, ...] | None = None
    variant: str = "base"
    spec_k: int = 2


def decode_corpus() -> list[DecodeAuditSpec]:
    """Configs the decode kernels are captured at: the serving default, a
    wide-page fp32 variant, a ragged batch with dead slots + partially
    allocated page-table rows (-1 entries exercise the clamp index map),
    plus spec-verify (multi-row q tiles, both group widths) and int8
    (scale-prefetch index maps, fp32 compute dtype — the engine's) riders."""
    return [
        DecodeAuditSpec(name="decode/bfloat16/g2/ps128"),
        DecodeAuditSpec(
            name="decode/float32/g1/ps256", dtype="float32",
            hq=2, page_size=256, num_pages=16, pages_per_seq=4,
        ),
        DecodeAuditSpec(
            name="decode/bfloat16/g4/ragged", hq=8,
            lengths=(5, 0, 259, 128),
        ),
        DecodeAuditSpec(
            name="decode_spec/bfloat16/g2/k2/ps128", variant="spec",
        ),
        DecodeAuditSpec(
            name="decode_spec/float32/g4/k4/ragged", variant="spec",
            dtype="float32", hq=8, spec_k=4, lengths=(5, 0, 259, 128),
        ),
        DecodeAuditSpec(
            name="decode_int8/float32/g2/ps128", variant="int8",
            dtype="float32",
        ),
        DecodeAuditSpec(
            name="decode_int8/float32/g1/ps256", variant="int8",
            dtype="float32", hq=2, page_size=256, num_pages=16,
            pages_per_seq=4,
        ),
    ]


def capture_decode_contracts(spec: DecodeAuditSpec) -> list[KernelContract]:
    """Drive the paged-decode wrapper under capture at ``spec``: a cache
    whose page table is allocated exactly as the serving allocator would
    (pages in order per slot, -1 beyond each slot's allocation)."""
    import jax
    import jax.numpy as jnp

    from ..kernels import paged_decode
    from ..kernels.paged_kv import PagedKVCache

    ps = spec.page_size
    lengths = spec.lengths
    if lengths is None:
        lengths = tuple(
            min((i + 1) * ps, spec.pages_per_seq * ps)
            for i in range(spec.max_seqs)
        )
    table = np.full((spec.max_seqs, spec.pages_per_seq), -1, np.int32)
    nxt = 0
    for s, ln in enumerate(lengths):
        for j in range(-(-ln // ps)):
            table[s, j] = nxt % spec.num_pages
            nxt += 1
    dtype = jnp.dtype(spec.dtype)
    kv_dtype = jnp.int8 if spec.variant == "int8" else dtype
    scales = (
        jnp.zeros((spec.num_pages, spec.hk), jnp.float32)
        if spec.variant == "int8"
        else None
    )
    cache = PagedKVCache(
        k_pages=jnp.zeros(
            (spec.num_pages, ps, spec.hk, spec.d), kv_dtype
        ),
        v_pages=jnp.zeros(
            (spec.num_pages, ps, spec.hk, spec.dv), kv_dtype
        ),
        page_table=jnp.asarray(table),
        lengths=jnp.asarray(np.asarray(lengths, np.int32)),
        k_scales=scales,
        v_scales=scales,
    )
    if spec.variant == "spec":
        q = jnp.zeros((spec.max_seqs, spec.spec_k, spec.hq, spec.d), dtype)
        drive = lambda: paged_decode.paged_decode_attn_spec(q, cache)  # noqa: E731
    elif spec.variant == "int8":
        q = jnp.zeros((spec.max_seqs, spec.hq, spec.d), dtype)
        drive = lambda: paged_decode.paged_decode_attn_int8(q, cache)  # noqa: E731
    else:
        q = jnp.zeros((spec.max_seqs, spec.hq, spec.d), dtype)
        drive = lambda: paged_decode.paged_decode_attn(q, cache)  # noqa: E731
    cap = _capture_pallas()
    with jax.default_device(jax.devices("cpu")[0]):
        with cap:
            try:
                drive()
            except _Captured:
                pass
    return cap.contracts


@dataclass(frozen=True, eq=False)
class BlockSparseAuditSpec:
    """One block-sparse NSA-slc corpus config (kernels/block_sparse.py)."""

    name: str
    seq: int = 512
    hq: int = 4
    hk: int = 2
    d: int = 128
    dv: int = 128
    block_len: int = 64
    d_stride: int = 32
    block_size_q: int = 16
    top_k: int = 2
    dtype: str = "bfloat16"


def bsp_corpus() -> list[BlockSparseAuditSpec]:
    """Configs the block-sparse kernels are captured at: the NSA default
    (overlapping stride-32 blocks, GQA g=2), a non-overlapping fp32 g=1
    variant, and a wider-group bf16 config whose deterministic table picks
    adjacent blocks (maximal chunk duplication across the revisit axis)."""
    return [
        BlockSparseAuditSpec(name="bsp/bfloat16/g2/overlap"),
        BlockSparseAuditSpec(
            name="bsp/float32/g1/aligned", dtype="float32", hq=2,
            block_len=64, d_stride=64, top_k=3,
        ),
        BlockSparseAuditSpec(
            name="bsp/bfloat16/g4/adjacent", hq=8, seq=256, top_k=4,
        ),
    ]


def capture_bsp_contracts(spec: BlockSparseAuditSpec) -> list[KernelContract]:
    """Drive BOTH block-sparse wrappers (fwd + fused bwd) under capture at
    ``spec`` with a deterministic adjacent-block index table — the shape the
    NSA top-k emits, including overlapping picks when d_stride < block_len."""
    import jax
    import jax.numpy as jnp

    from ..kernels import block_sparse

    S, ds = spec.seq, spec.d_stride
    n_blocks = (S - spec.block_len) // ds + 1
    n_qb = S // spec.block_size_q
    n_chunks = S // ds
    alpha = spec.block_len // ds
    g = spec.hq // spec.hk
    r = spec.block_size_q * g
    dtype = jnp.dtype(spec.dtype)

    # adjacent distinct block ids per (head, q-block), wrapped in range
    idx = (
        np.arange(spec.top_k)[None, None, :]
        + np.arange(n_qb)[None, :, None]
        + np.arange(spec.hk)[:, None, None]
    ) % n_blocks
    starts = np.arange(n_blocks, dtype=np.int32) * ds
    ctbl = jnp.asarray(
        ((starts // ds)[idx][..., None] + np.arange(alpha))
        .reshape(spec.hk, n_qb, -1),
        jnp.int32,
    )
    C = spec.top_k * alpha

    q_r = jnp.zeros((spec.hk, n_qb, r, spec.d), dtype)
    k_c = jnp.zeros((n_chunks, ds, spec.hk, spec.d), dtype)
    v_c = jnp.zeros((n_chunks, ds, spec.hk, spec.dv), dtype)
    do_r = jnp.zeros((spec.hk, n_qb, r, spec.dv), dtype)
    lse_r = jnp.zeros((spec.hk, n_qb, r, 128), jnp.float32)
    delta_r = jnp.zeros((spec.hk, n_qb, r, 128), jnp.float32)
    scale = float(spec.d) ** -0.5

    contracts: list[KernelContract] = []
    with jax.default_device(jax.devices("cpu")[0]):
        for drive in (
            lambda: block_sparse._bsp_fwd_pallas(
                ctbl, q_r, k_c, v_c, scale, True
            ),
            lambda: block_sparse._bsp_bwd_pallas(
                ctbl, q_r, k_c, v_c, do_r, lse_r, delta_r, scale, True
            ),
        ):
            cap = _capture_pallas()
            with cap:
                try:
                    drive()
                except _Captured:
                    pass
            contracts.extend(cap.contracts)
    assert all(c.grid == (spec.hk, n_qb, C) for c in contracts)
    return contracts


# ---------------------------------------------------------------------------
# contract geometry helpers
# ---------------------------------------------------------------------------


def _contract_shape_info(contract: KernelContract) -> dict:
    """(kind, packed, g, bq, bk, d, dv, itemsize, emit_ml) derived from the
    captured blocks — no reliance on the driver's inputs, so the checks
    also apply to synthetic/mutated contracts in tests."""
    name = contract.kernel_name
    packed = name.endswith("_gqa")
    if "delta" in name:
        # stateless map kernel: in_specs are (o, do), both (1, bq, dv)
        o_block = contract.in_specs[0].block_shape
        return dict(
            kind="delta", packed=False, g=1,
            bq=int(o_block[1]), bk=0,
            d=int(o_block[2]), dv=int(o_block[2]),
            itemsize=np.dtype(contract.operands[0][1]).itemsize,
            emit_ml=False,
        )
    if "decode" in name:
        # paged-decode kernels: q block (1, 1, rows, d), k/v blocks
        # (1, page_size, 1, d|dv); bq = q-tile rows (GQA group rows, or
        # spec_k * group rows for the verify variant), bk = page size.
        # int8/spec substrings dispatch to their own residency kinds and
        # MUST be tested before the generic branch — their names also
        # contain "decode". itemsize is always q's dtype; the int8 kind
        # bakes the 1-byte k/v payload + f32 scale blocks into its formula.
        q_block = contract.in_specs[0].block_shape
        k_block = contract.in_specs[1].block_shape
        v_block = contract.in_specs[2].block_shape
        kind = (
            "decode_int8" if "int8" in name
            else "decode_spec" if "spec" in name
            else "decode"
        )
        return dict(
            kind=kind, packed=False, g=1,
            bq=int(q_block[2]), bk=int(k_block[1]),
            d=int(q_block[3]), dv=int(v_block[3]),
            itemsize=np.dtype(contract.operands[0][1]).itemsize,
            emit_ml=False,
        )
    if "bsp" in name:
        # block-sparse kernels (kernels/block_sparse.py): q block
        # (1, 1, r, d) with r = block_size_q * group rows, k/v blocks
        # (1, d_stride, 1, d|dv); bq = r, bk = chunk rows. Checked BEFORE
        # the generic branch — "_bsp_fwd_kernel" also contains "fwd".
        q_block = contract.in_specs[0].block_shape
        k_block = contract.in_specs[1].block_shape
        v_block = contract.in_specs[2].block_shape
        return dict(
            kind="bsp_bwd" if "bwd" in name else "bsp_fwd",
            packed=False, g=1,
            bq=int(q_block[2]), bk=int(k_block[1]),
            d=int(q_block[3]), dv=int(v_block[3]),
            itemsize=np.dtype(contract.operands[0][1]).itemsize,
            emit_ml=False,
        )
    kind = (
        "fused" if "fused" in name
        else "fwd" if "fwd" in name
        else "dq" if "dq" in name
        else "dkv"
    )
    q_block = contract.in_specs[0].block_shape
    k_block = contract.in_specs[1].block_shape
    v_block = contract.in_specs[2].block_shape
    if packed:
        g, bq, d = int(q_block[1]), int(q_block[2]), int(q_block[3])
    else:
        g, bq, d = 1, int(q_block[1]), int(q_block[2])
    bk = int(k_block[1])
    dv = int(v_block[2])
    itemsize = np.dtype(contract.operands[0][1]).itemsize
    emit_ml = kind == "fwd" and not packed and len(contract.out_shape) == 3
    return dict(
        kind=kind, packed=packed, g=g, bq=bq, bk=bk, d=d, dv=dv,
        itemsize=itemsize, emit_ml=emit_ml,
    )


def _block_bytes(block_shape, dtype_name: str) -> int:
    n = 1
    for dim in block_shape:
        if dim is not None:
            n *= int(dim)
    return n * np.dtype(dtype_name).itemsize


def _declared_bytes(contract: KernelContract) -> int:
    """Exact declared residency from the captured contract: in/out blocks
    double-buffered + scratch. Scratch is counted at 4 bytes/elem by
    decree — its DTYPE is K4's rule, so a bf16-scratch mutation fires K4
    alone, not K1 as a side effect."""
    total = 0
    for spec, (_, dtype_name) in zip(
        contract.in_specs, contract.operands
    ):
        total += 2 * _block_bytes(spec.block_shape, dtype_name)
    for spec, (_, dtype_name) in zip(contract.out_specs, contract.out_shape):
        total += 2 * _block_bytes(spec.block_shape, dtype_name)
    for shape, _dtype in contract.scratch:
        total += int(np.prod(shape)) * 4
    return total


# ---------------------------------------------------------------------------
# K1 — VMEM budget
# ---------------------------------------------------------------------------


def check_k1_vmem(
    report: VerifyReport, contract: KernelContract, site: str
) -> None:
    report.mark_run("K1")
    info = _contract_shape_info(contract)
    declared = _declared_bytes(contract)
    model_declared = ffa_kernel_residency(
        info["kind"], info["bq"], info["bk"], info["d"],
        head_dim_v=info["dv"], dtype_bytes=info["itemsize"],
        group=info["g"], packed=info["packed"], emit_ml=info["emit_ml"],
        include_intermediates=False,
    )
    model_total = ffa_kernel_residency(
        info["kind"], info["bq"], info["bk"], info["d"],
        head_dim_v=info["dv"], dtype_bytes=info["itemsize"],
        group=info["g"], packed=info["packed"], emit_ml=info["emit_ml"],
    )
    intermediates = model_total - model_declared
    if declared != model_declared:
        report.add(
            "K1", ERROR, site,
            f"residency model drift: mem_budget.ffa_kernel_residency "
            f"predicts {model_declared} declared bytes but the captured "
            f"contract holds {declared} — the shared VMEM model no longer "
            f"matches the real kernel",
        )
    total = declared + intermediates
    if total > VMEM_ALLOWED_BYTES:
        report.add(
            "K1", ERROR, site,
            f"VMEM budget: {total} bytes/step (declared {declared} + "
            f"intermediates {intermediates}) exceeds the allowed "
            f"{VMEM_ALLOWED_BYTES} ({VMEM_LIMIT_BYTES} limit - "
            f"{VMEM_HEADROOM_BYTES} headroom)",
        )
    if not info["packed"] and info["kind"] in ("fwd", "dq", "dkv"):
        # cross-check against the vmem_check-guarded tile-policy model:
        # the policy filter and mem_budget must be the SAME arithmetic
        # (fused/delta have no tile_policy block filter — the fused path
        # reuses the dkv block space and gates on ffa_kernel_residency
        # directly, so there is no second model to diverge from)
        from ..kernels import tile_policy

        est_policy = (
            tile_policy._vmem_bytes(
                info["bq"], info["bk"], info["d"], info["dv"],
                info["itemsize"],
            )
            if info["kind"] == "fwd"
            else tile_policy._bwd_vmem_bytes(
                info["kind"], info["bq"], info["bk"], info["d"],
                info["dv"], info["itemsize"],
            )
        )
        est_budget = (
            fwd_vmem_bytes(
                info["bq"], info["bk"], info["d"], info["dv"],
                info["itemsize"],
            )
            if info["kind"] == "fwd"
            else bwd_vmem_bytes(
                info["kind"], info["bq"], info["bk"], info["d"],
                info["dv"], info["itemsize"],
            )
        )
        if est_policy != est_budget:
            report.add(
                "K1", ERROR, site,
                f"policy/runtime VMEM models diverge: tile_policy "
                f"estimates {est_policy} but mem_budget {est_budget} for "
                f"the same blocks — the vmem_check site no longer guards "
                f"the model this checker proves",
            )


def check_reachable_space(
    report: VerifyReport,
    sq: int,
    sk: int,
    d: int = 128,
    dv: int = 128,
    itemsizes: tuple[int, ...] = (2, 4),
    groups: tuple[int, ...] = (1, 2, 4, 8),
) -> dict:
    """Abstract K1 over the FULL reachable config space: every tiling
    ``tile_policy`` can emit for any pass must keep the UNPACKED kernel
    residency within budget (unpacked kernels launch unconditionally — no
    dispatch-time guard protects them), and the packed dispatch guards
    share :func:`ffa_kernel_residency`, so packed admission is safe by
    construction (asserted per captured contract in :func:`check_k1_vmem`).
    Returns sweep stats for the audit report."""
    from ..kernels import tile_policy

    report.mark_run("K1")
    checked = 0
    worst = (0, None)
    for kind in ("fwd", "dq", "dkv"):
        for itemsize in itemsizes:
            space = tile_policy.reachable_block_space(
                sq, sk, kind, d, dv, itemsize
            )
            for bq, bk in space:
                checked += 1
                total = ffa_kernel_residency(
                    kind, bq, bk, d, head_dim_v=dv, dtype_bytes=itemsize,
                    emit_ml=(kind == "fwd"),
                )
                if total > worst[0]:
                    worst = (total, (kind, bq, bk, itemsize))
                if total > VMEM_ALLOWED_BYTES:
                    report.add(
                        "K1", ERROR,
                        f"reachable_block_space(sq={sq}, sk={sk}, "
                        f"{kind}, itemsize={itemsize})",
                        f"policy-reachable tiling ({bq}, {bk}) puts the "
                        f"unpacked {kind} kernel at {total} bytes/step > "
                        f"allowed {VMEM_ALLOWED_BYTES}",
                    )
                # packed admission is the guard's decision; prove the
                # guard's model here so a guard bypass cannot hide
                for g in groups:
                    if g == 1:
                        continue
                    packed_total = ffa_kernel_residency(
                        kind, bq, bk, d, head_dim_v=dv,
                        dtype_bytes=itemsize, group=g, packed=True,
                    )
                    admitted = packed_total <= VMEM_ALLOWED_BYTES
                    if admitted and packed_total > VMEM_ALLOWED_BYTES:
                        report.add(  # pragma: no cover - tautology guard
                            "K1", ERROR, "packed dispatch guard",
                            f"guard admits ({kind}, g={g}, {bq}x{bk}) at "
                            f"{packed_total} bytes",
                        )
    return {
        "configs_checked": checked,
        "worst_bytes": worst[0],
        "worst_config": worst[1],
        "allowed_bytes": VMEM_ALLOWED_BYTES,
    }


# ---------------------------------------------------------------------------
# K3 — index-map bounds
# ---------------------------------------------------------------------------


def _grid_mesh(grid: tuple[int, ...]) -> list[np.ndarray]:
    axes = [np.arange(n, dtype=np.int64) for n in grid]
    return list(np.meshgrid(*axes, indexing="ij")) if axes else []


def _eval_index_map(spec, mesh, prefetch):
    out = spec.index_map(*mesh, *prefetch)
    if not isinstance(out, tuple):
        out = (out,)
    shape = mesh[0].shape if mesh else ()
    return [np.broadcast_to(np.asarray(o), shape) for o in out]


def check_k3_bounds(
    report: VerifyReport, contract: KernelContract, site: str
) -> None:
    report.mark_run("K3")
    mesh = _grid_mesh(contract.grid)
    pairs = [
        (f"in[{i}]", spec, shape)
        for i, (spec, (shape, _)) in enumerate(
            zip(contract.in_specs, contract.operands)
        )
    ] + [
        (f"out[{i}]", spec, shape)
        for i, (spec, (shape, _)) in enumerate(
            zip(contract.out_specs, contract.out_shape)
        )
    ]
    for label, spec, op_shape in pairs:
        block = spec.block_shape
        if len(block) != len(op_shape):
            report.add(
                "K3", ERROR, f"{site} {label}",
                f"block rank {len(block)} != operand rank {len(op_shape)}",
            )
            continue
        idx = _eval_index_map(spec, mesh, contract.prefetch)
        if len(idx) != len(block):
            report.add(
                "K3", ERROR, f"{site} {label}",
                f"index_map returns {len(idx)} indices for a rank-"
                f"{len(block)} block",
            )
            continue
        for axis, (bdim, dim) in enumerate(zip(block, op_shape)):
            ext = 1 if bdim is None else int(bdim)
            origin = idx[axis] * (1 if bdim is None else int(bdim))
            lo = int(origin.min()) if origin.size else 0
            hi = int(origin.max()) + ext if origin.size else ext
            if lo < 0 or hi > dim:
                report.add(
                    "K3", ERROR, f"{site} {label}",
                    f"axis {axis}: block [{lo}, {hi}) escapes operand "
                    f"dim {dim} (block {ext} x index range "
                    f"[{int(origin.min())}, {int(origin.max())}])",
                )


def check_k3_extents(
    report: VerifyReport, contract: KernelContract, site: str
) -> None:
    """K3, extent half: the EQ0..EK1 live-extent meta columns are prefetch
    state the clamp path uses to SKIP dot_general chunks, so a wrong row
    silently drops (or re-adds) attention mass instead of faulting. Prove
    every captured row equals the host-side recomputation from the 9-col
    band geometry (``ffa_plan._extend_meta_extents``) and sits inside the
    tile at the sublane/lane quanta the kernels chunk at."""
    if contract.num_scalar_prefetch < 3:
        return
    meta = np.asarray(contract.prefetch[2])
    if meta.ndim != 2 or meta.shape[1] < META_DIM:
        return  # pre-extent 9-col meta: nothing to prove
    report.mark_run("K3")
    info = _contract_shape_info(contract)
    bq, bk = info["bq"], info["bk"]
    work_qt = np.asarray(contract.prefetch[0])
    work_kt = np.asarray(contract.prefetch[1])
    ext = meta[:, EQ0 : EK1 + 1].astype(np.int64)
    want = _extend_meta_extents(
        meta[:, :EQ0].astype(np.int32), work_qt, work_kt, bq, bk
    )[:, EQ0 : EK1 + 1].astype(np.int64)
    bad = np.nonzero((ext != want).any(axis=1))[0]
    for w in bad[:8]:
        report.add(
            "K3", ERROR, f"{site} meta[{int(w)}]",
            f"extent columns {ext[w].tolist()} != host recomputation "
            f"{want[w].tolist()} from the band geometry — the clamp "
            f"path would skip live chunks or execute dead ones",
        )
    if len(bad) > 8:
        report.add(
            "K3", ERROR, site,
            f"... and {len(bad) - 8} more extent rows disagree",
        )
    eq0, eq1, ek0, ek1 = ext[:, 0], ext[:, 1], ext[:, 2], ext[:, 3]
    oob = (
        (eq0 < 0) | (eq1 > bq) | (eq0 > eq1)
        | (ek0 < 0) | (ek1 > bk) | (ek0 > ek1)
    )
    for w in np.nonzero(oob)[0][:8]:
        report.add(
            "K3", ERROR, f"{site} meta[{int(w)}]",
            f"extent {ext[w].tolist()} escapes tile ({bq}, {bk}) or is "
            f"inverted",
        )
    misaligned = (
        (eq0 % SUBLANE_QUANTUM != 0) | (eq1 % SUBLANE_QUANTUM != 0)
        | (ek0 % LANE_QUANTUM != 0) | (ek1 % LANE_QUANTUM != 0)
    )
    for w in np.nonzero(misaligned & ~oob)[0][:8]:
        report.add(
            "K3", ERROR, f"{site} meta[{int(w)}]",
            f"extent {ext[w].tolist()} not aligned to "
            f"({SUBLANE_QUANTUM}, {LANE_QUANTUM}) quanta — chunk "
            f"liveness tests would straddle a partially-live chunk",
        )


def padding_stats(
    contract: KernelContract, sq: int, sk: int
) -> dict:
    """Statically counted padded-tile work for the audit report (feeds
    roadmap item 3's block-skip dispatch): grid steps whose q or k tile
    sticks out past the true seqlen."""
    info = _contract_shape_info(contract)
    if contract.num_scalar_prefetch < 2:
        return {}
    work_qt = contract.prefetch[0].astype(np.int64)
    work_kt = contract.prefetch[1].astype(np.int64)
    q_pad = (work_qt + 1) * info["bq"] > sq
    k_pad = (work_kt + 1) * info["bk"] > sk
    steps = int(work_qt.size)
    return {
        "grid_steps": steps,
        "padded_q_steps": int(q_pad.sum()),
        "padded_k_steps": int(k_pad.sum()),
        "padded_steps": int((q_pad | k_pad).sum()),
        "padded_ratio": float((q_pad | k_pad).sum()) / steps if steps else 0.0,
    }


# ---------------------------------------------------------------------------
# K4 — dtype/precision contract (captured side)
# ---------------------------------------------------------------------------


def check_k4_dtypes(
    report: VerifyReport, contract: KernelContract, site: str,
    declared: dict | None = None,
) -> None:
    report.mark_run("K4")
    for i, (shape, dtype_name) in enumerate(contract.scratch):
        if dtype_name != "float32":
            report.add(
                "K4", ERROR, f"{site} scratch[{i}]",
                f"accumulator scratch {shape} is {dtype_name}, not "
                f"float32 — cross-step accumulation would truncate",
            )
    if declared is None:
        declared = _pallas_contracts().get(contract.kernel_name)
    if declared is None:
        return
    input_dtype = contract.operands[0][1] if contract.operands else None
    for i, want in enumerate(declared.get("out_dtypes", ())):
        if i >= len(contract.out_shape):
            break  # trailing optional output (ml) absent at this config
        got = contract.out_shape[i][1]
        if want == "f32" and got != "float32":
            report.add(
                "K4", ERROR, f"{site} out[{i}]",
                f"declared f32 output lowered as {got} — implicit "
                f"truncation before the final write",
            )
        elif want == "input" and input_dtype and got != input_dtype:
            report.add(
                "K4", ERROR, f"{site} out[{i}]",
                f"passthrough output dtype {got} != operand dtype "
                f"{input_dtype}",
            )


def _pallas_contracts() -> dict:
    from ..kernels.block_sparse import PALLAS_CONTRACTS as bsp_contracts
    from ..kernels.ffa import PALLAS_CONTRACTS as ffa_contracts
    from ..kernels.paged_decode import PALLAS_CONTRACTS as decode_contracts

    return {**ffa_contracts, **decode_contracts, **bsp_contracts}


def _contract_sources() -> list[tuple[str, str, dict]]:
    """(relpath, source, contracts) for every kernel module that declares
    PALLAS_CONTRACTS — the K2/K4 source-rule sweep iterates these."""
    from ..kernels.block_sparse import PALLAS_CONTRACTS as bsp_contracts
    from ..kernels.ffa import PALLAS_CONTRACTS as ffa_contracts
    from ..kernels.paged_decode import PALLAS_CONTRACTS as decode_contracts

    kdir = _kernels_dir()
    return [
        ("kernels/ffa.py", (kdir / "ffa.py").read_text(), ffa_contracts),
        (
            "kernels/paged_decode.py",
            (kdir / "paged_decode.py").read_text(),
            decode_contracts,
        ),
        (
            "kernels/block_sparse.py",
            (kdir / "block_sparse.py").read_text(),
            bsp_contracts,
        ),
    ]


def check_contract(
    report: VerifyReport, contract: KernelContract, site: str | None = None
) -> None:
    """K1 + K3 + K4 on one captured contract (K2/K5 are source/repo-level)."""
    site = site or contract.kernel_name
    check_k1_vmem(report, contract, site)
    check_k3_bounds(report, contract, site)
    check_k3_extents(report, contract, site)
    check_k4_dtypes(report, contract, site)


# ---------------------------------------------------------------------------
# K2 — accumulator discipline + K4 source rules (AST over kernel bodies)
# ---------------------------------------------------------------------------


def _guard_conds(expr: ast.expr) -> list[tuple[str, str]] | None:
    """Flatten a ``pl.when`` predicate into (name, rhs) equality pairs;
    None when the shape is unrecognized."""
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.BitAnd):
        left = _guard_conds(expr.left)
        right = _guard_conds(expr.right)
        if left is None or right is None:
            return None
        return left + right
    if (
        isinstance(expr, ast.Compare)
        and len(expr.ops) == 1
        and isinstance(expr.ops[0], ast.Eq)
        and isinstance(expr.left, ast.Name)
    ):
        return [(expr.left.id, ast.unparse(expr.comparators[0]))]
    return None


def _when_blocks(fn: ast.FunctionDef) -> list[tuple[list, ast.FunctionDef]]:
    blocks = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.FunctionDef) or node is fn:
            continue
        for dec in node.decorator_list:
            if (
                isinstance(dec, ast.Call)
                and isinstance(dec.func, ast.Attribute)
                and dec.func.attr == "when"
                and dec.args
            ):
                conds = _guard_conds(dec.args[0])
                if conds is not None:
                    blocks.append((conds, node))
    return blocks


def _subscript_stores(node: ast.AST, names: tuple[str, ...]) -> dict[str, list]:
    """name -> list of Assign/AugAssign nodes whose target subscripts it."""
    stores: dict[str, list] = {n: [] for n in names}
    for sub in ast.walk(node):
        targets = []
        if isinstance(sub, ast.Assign):
            targets = sub.targets
        elif isinstance(sub, ast.AugAssign):
            targets = [sub.target]
        for t in targets:
            if (
                isinstance(t, ast.Subscript)
                and isinstance(t.value, ast.Name)
                and t.value.id in stores
            ):
                stores[t.value.id].append(sub)
    return stores


def check_kernel_sources(
    report: VerifyReport,
    source: str | None = None,
    contracts: dict | None = None,
    relpath: str = "kernels/ffa.py",
) -> None:
    """K2 (+ the source half of K4) over the kernel bodies declared in
    ``PALLAS_CONTRACTS``. With no ``source``/``contracts`` the sweep covers
    every kernel module in :func:`_contract_sources`; tests pass mutated
    fixtures explicitly."""
    if source is None and contracts is None:
        for rel, src, decls in _contract_sources():
            _check_kernel_sources_one(report, src, decls, rel)
        return
    if contracts is None:
        contracts = _pallas_contracts()
    if source is None:
        source = (_kernels_dir() / "ffa.py").read_text()
    _check_kernel_sources_one(report, source, contracts, relpath)


def _check_kernel_sources_one(
    report: VerifyReport,
    source: str,
    contracts: dict,
    relpath: str,
) -> None:
    report.mark_run("K2")
    report.mark_run("K4")
    tree = ast.parse(source)
    fns = {
        node.name: node
        for node in ast.walk(tree)
        if isinstance(node, ast.FunctionDef)
    }
    for kname, decl in contracts.items():
        site = f"{relpath}:{kname}"
        fn = fns.get(kname)
        if fn is None:
            report.add(
                "K2", ERROR, site,
                "annotated kernel body not found in source — "
                "PALLAS_CONTRACTS out of date",
            )
            continue
        # K4 source half: every MXU contraction accumulates in f32
        # (runs for every contract, including stateless map kernels)
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Call)
                and _callee_name(node.func) == "dot_general"
            ):
                kw = {k.arg: k.value for k in node.keywords}
                pet = kw.get("preferred_element_type")
                if pet is None or not ast.unparse(pet).endswith("float32"):
                    report.add(
                        "K4", ERROR, f"{site}:{node.lineno}",
                        "dot_general without "
                        "preferred_element_type=jnp.float32 — MXU "
                        "accumulation falls back to the input dtype",
                    )

        init_guard = decl["init_guard"]
        flush_guard = decl["flush_guard"]
        group = decl.get("group_inner")
        # revisit: one dict or a list of dicts, one per revisit-accumulated
        # output. Each may override the guard-binding substrings
        # (init_binding / flush_binding, defaults QVF / QVL for the plan-
        # meta kernels) and may declare flush_guard=None for outputs whose
        # accumulated value is final as-is (host-side correction only)
        revisit = decl.get("revisit")
        revisits = (
            [revisit] if isinstance(revisit, dict) else list(revisit or [])
        )

        if init_guard is None and flush_guard is None:
            # stateless map kernel (e.g. the delta kernel): no cross-step
            # accumulator, so the only K2 obligation is that every
            # declared output is actually written
            for name in decl["outputs"]:
                if not _subscript_stores(fn, (name,))[name]:
                    report.add(
                        "K2", ERROR, site,
                        f"output '{name}' is never stored",
                    )
            continue

        # guard vars must be derived from the plan's IS_FIRST / IS_LAST
        # (and, for a revisit-accumulated output, QVF / QVL)
        bindings = {}
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                bindings[node.targets[0].id] = ast.unparse(node.value)
        # guard-binding provenance: plan-meta kernels bind from IS_FIRST /
        # IS_LAST columns; grid-axis kernels (the paged-decode page run)
        # declare their expected binding substrings explicitly
        guard_cols = [
            (init_guard, decl.get("init_binding", "IS_FIRST")),
            (flush_guard, decl.get("flush_binding", "IS_LAST")),
        ]
        for rv in revisits:
            guard_cols.append(
                (rv["init_guard"], rv.get("init_binding", "QVF"))
            )
            if rv.get("flush_guard") is not None:
                guard_cols.append(
                    (rv["flush_guard"], rv.get("flush_binding", "QVL"))
                )
        for var, col in guard_cols:
            if col not in bindings.get(var, ""):
                report.add(
                    "K2", ERROR, site,
                    f"guard variable '{var}' is not bound from the plan's "
                    f"{col} column",
                )

        blocks = _when_blocks(fn)
        init_blocks = [
            (conds, node) for conds, node in blocks
            if (init_guard, "1") in conds
        ]
        flush_blocks = [
            (conds, node) for conds, node in blocks
            if (flush_guard, "1") in conds
        ]

        if group:
            var, count = group["var"], group["count"]
            for conds, _node in init_blocks:
                if (var, "0") not in conds:
                    report.add(
                        "K2", ERROR, site,
                        f"init guard lacks the inner-revisit qualifier "
                        f"({var} == 0): the grid revisits this tile "
                        f"across '{var}', so a bare {init_guard} re-zeros "
                        f"a live accumulator",
                    )
            for conds, _node in flush_blocks:
                if (var, f"{count} - 1") not in conds:
                    report.add(
                        "K2", ERROR, site,
                        f"flush guard lacks the inner-revisit qualifier "
                        f"({var} == {count} - 1): the output would be "
                        f"written {count} times per tile run",
                    )

        # every scratch accumulator zero-initialized inside an init block
        scratch = tuple(decl["scratch"])
        initialized: set[str] = set()
        init_fns = {"zeros_like", "full_like", "zeros", "full"}
        for _conds, node in init_blocks:
            for name, assigns in _subscript_stores(node, scratch).items():
                for a in assigns:
                    val = getattr(a, "value", None)
                    if (
                        isinstance(a, ast.Assign)
                        and isinstance(val, ast.Call)
                        and _callee_name(val.func) in init_fns
                    ):
                        initialized.add(name)
        for name in scratch:
            if name not in initialized:
                report.add(
                    "K2", ERROR, site,
                    f"scratch accumulator '{name}' is never zero-"
                    f"initialized under the {init_guard} guard — first "
                    f"grid step reads stale VMEM",
                )

        # outputs: stored exactly once, only under the flush guard
        # (a revisit-accumulated output follows its own discipline below)
        revisit_outs = {rv["out"] for rv in revisits}
        outputs = tuple(
            n for n in decl["outputs"] if n not in revisit_outs
        )
        flush_assigns: dict[str, int] = {n: 0 for n in outputs}
        flush_nodes: set[int] = set()
        for _conds, node in flush_blocks:
            for name, assigns in _subscript_stores(node, outputs).items():
                flush_assigns[name] += len(assigns)
                flush_nodes.update(id(a) for a in assigns)
        all_assigns = _subscript_stores(fn, outputs)
        for name in outputs:
            stray = [
                a for a in all_assigns[name] if id(a) not in flush_nodes
            ]
            if stray:
                report.add(
                    "K2", ERROR, site,
                    f"output '{name}' is stored outside the {flush_guard} "
                    f"flush guard (line {stray[0].lineno}) — partial "
                    f"accumulation would be written",
                )
            if flush_assigns[name] == 0:
                report.add(
                    "K2", ERROR, site,
                    f"output '{name}' is never flushed under the "
                    f"{flush_guard} guard",
                )
            elif flush_assigns[name] > 1:
                report.add(
                    "K2", ERROR, site,
                    f"output '{name}' is flushed {flush_assigns[name]} "
                    f"times — the contract requires exactly one flush",
                )

        # revisit-accumulated outputs: the traversal revisits the same
        # output block across work items, so the kernel must (a) zero it
        # on the FIRST visit — on hardware the window's initial VMEM
        # content is undefined; interpret mode hides this — (b) when a
        # last-visit correction is declared (flush_guard not None), flush
        # exactly once on the LAST visit, and (c) only ever accumulate
        # (+=) in between, never overwrite
        for rv in revisits:
            rout = rv["out"]
            rvf = rv["init_guard"]
            rvl = rv.get("flush_guard")
            r_init_ids: set[int] = set()
            has_init = False
            for conds, node in blocks:
                if (rvf, "1") not in conds:
                    continue
                for a in _subscript_stores(node, (rout,))[rout]:
                    r_init_ids.add(id(a))
                    val = getattr(a, "value", None)
                    if (
                        isinstance(a, ast.Assign)
                        and isinstance(val, ast.Call)
                        and _callee_name(val.func) in init_fns
                    ):
                        has_init = True
            if not has_init:
                report.add(
                    "K2", ERROR, site,
                    f"revisit-accumulated output '{rout}' is never zero-"
                    f"initialized under the {rvf} (first-visit) guard — "
                    f"on hardware the output window's first-visit VMEM "
                    f"content is undefined, so accumulation starts from "
                    f"garbage",
                )
            r_flush_ids: set[int] = set()
            if rvl is not None:
                n_flush = 0
                for conds, node in blocks:
                    if (rvl, "1") not in conds:
                        continue
                    assigns = _subscript_stores(node, (rout,))[rout]
                    n_flush += len(assigns)
                    r_flush_ids.update(id(a) for a in assigns)
                if n_flush == 0:
                    report.add(
                        "K2", ERROR, site,
                        f"revisit-accumulated output '{rout}' is never "
                        f"flushed under the {rvl} (last-visit) guard",
                    )
                elif n_flush > 1:
                    report.add(
                        "K2", ERROR, site,
                        f"revisit-accumulated output '{rout}' is flushed "
                        f"{n_flush} times — the contract requires exactly "
                        f"one last-visit flush",
                    )
            for a in _subscript_stores(fn, (rout,))[rout]:
                if id(a) in r_init_ids or id(a) in r_flush_ids:
                    continue
                if not isinstance(a, ast.AugAssign):
                    report.add(
                        "K2", ERROR, site,
                        f"revisit-accumulated output '{rout}' is plainly "
                        f"assigned outside the {rvf}/{rvl} guards (line "
                        f"{a.lineno}) — a revisit would overwrite, not "
                        f"accumulate, earlier work items' contributions",
                    )


# ---------------------------------------------------------------------------
# K5 — cache-key soundness
# ---------------------------------------------------------------------------

_ENV_KEY_RE = "MAGI_ATTENTION_"


def _env_getter_keys(env_dir: Path) -> dict[str, set[str]]:
    """getter function name -> env keys it reads, from env/*.py ASTs."""
    getters: dict[str, set[str]] = {}
    for path in sorted(env_dir.glob("*.py")):
        tree = ast.parse(path.read_text())
        for fn in ast.walk(tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            keys = {
                node.value
                for node in ast.walk(fn)
                if isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and node.value.startswith(_ENV_KEY_RE)
            }
            if keys:
                getters.setdefault(fn.name, set()).update(keys)
    return getters


def consumed_env_keys(
    kernels_dir: Path | None = None, env_dir: Path | None = None
) -> dict[str, set[str]]:
    """env key -> the kernels/ files consuming it (directly via a MAGI_*
    literal or through an env/ getter call)."""
    kroot = Path(kernels_dir) if kernels_dir else _kernels_dir()
    eroot = Path(env_dir) if env_dir else kroot.parent / "env"
    getters = _env_getter_keys(eroot)
    consumed: dict[str, set[str]] = {}
    for path in sorted(kroot.glob("*.py")):
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _callee_name(node.func)
            for key in getters.get(callee, ()):
                consumed.setdefault(key, set()).add(path.name)
            for arg in node.args[:1]:
                if (
                    isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and arg.value.startswith(_ENV_KEY_RE)
                ):
                    consumed.setdefault(arg.value, set()).add(path.name)
    return consumed


def check_env_keys(
    report: VerifyReport,
    consumed: dict[str, set[str]] | None = None,
    listed: tuple[str, ...] | None = None,
    allowlist: dict[str, str] | None = None,
) -> None:
    """K5: every env key that can change kernel lowering (= consumed under
    kernels/) must invalidate runtime caches via
    ENV_KEYS_AFFECTING_RUNTIME, unless allowlisted with a proof."""
    report.mark_run("K5")
    if consumed is None:
        consumed = consumed_env_keys()
    if listed is None:
        from ..env.general import ENV_KEYS_AFFECTING_RUNTIME

        listed = ENV_KEYS_AFFECTING_RUNTIME
    if allowlist is None:
        allowlist = K5_ALLOWLIST
    for key in sorted(consumed):
        if key in listed or key in allowlist:
            continue
        files = ", ".join(sorted(consumed[key]))
        report.add(
            "K5", ERROR, f"kernels/ ({files})",
            f"env key {key} changes kernel behavior but is missing from "
            f"ENV_KEYS_AFFECTING_RUNTIME — cached runtimes would be "
            f"shared across flag flips",
        )


# ---------------------------------------------------------------------------
# golden corpus + full audit
# ---------------------------------------------------------------------------

_SEQ = 1024


def _canonical_masks(seq: int = _SEQ) -> dict[str, tuple]:
    """Small self-contained mask set spanning the plan-shape classes the
    scripts/verify_plans.py corpus uses (dense, causal, varlen, sliding
    window, block-sparse). Returns name -> (qr, kr, d_lo, d_hi)."""
    from ..kernels.mask_utils import types_to_bands

    def bands(qr, kr, tm):
        qr = np.asarray(qr, dtype=np.int32)
        kr = np.asarray(kr, dtype=np.int32)
        tm = np.asarray(tm, dtype=np.int32)
        lo, hi = types_to_bands(qr, kr, tm)
        return qr, kr, lo, hi

    h = seq // 2
    quarter = seq // 4
    masks = {
        "full": bands([[0, seq]], [[0, seq]], [0]),
        "causal": bands([[0, seq]], [[0, seq]], [1]),
        "varlen_block_causal": bands(
            [[0, quarter], [quarter, h], [h, seq]],
            [[0, quarter], [quarter, h], [h, seq]],
            [1, 1, 1],
        ),
        "sliding_window": (
            np.asarray([[0, seq]], dtype=np.int32),
            np.asarray([[0, seq]], dtype=np.int32),
            np.asarray([-256], dtype=np.int32),
            np.asarray([0], dtype=np.int32),
        ),
        "block_sparse": bands(
            [[0, quarter], [h, h + quarter]],
            [[quarter, h], [0, quarter]],
            [0, 0],
        ),
    }
    return masks


def _fragmented_masks(seq: int = _SEQ) -> dict[str, tuple]:
    """Sparse masks whose tiles are mostly padding at the default blocks —
    the shapes the extent-clamp/mixed-dispatch rescue targets. Shared with
    the verify_plans and parity corpora (video-style windowed frames via
    utils/sparse_utils, plus a fine block-diagonal)."""
    from ..kernels.mask_utils import types_to_bands
    from ..utils.sparse_utils import block_mask_to_ranges, make_video_block_mask

    blk = 128
    frames = seq // blk
    bm = make_video_block_mask(frames, 1, window_frames=2)
    vq, vk, vt = block_mask_to_ranges(bm, blk, blk)
    vqr = np.asarray(vq.to_naive_ranges(), dtype=np.int32)
    vkr = np.asarray(vk.to_naive_ranges(), dtype=np.int32)
    vtm = np.asarray([t.to_int_type() for t in vt], dtype=np.int32)
    vlo, vhi = types_to_bands(vqr, vkr, vtm)

    n = seq // blk
    dqr = np.asarray([[i * blk, (i + 1) * blk] for i in range(n)], np.int32)
    dlo, dhi = types_to_bands(dqr, dqr, np.zeros(n, dtype=np.int32))
    return {
        "video_sparse": (vqr, vkr, vlo, vhi),
        "block_diag_sparse": (dqr, dqr.copy(), dlo, dhi),
    }


def _largest_reachable_blocks(seq: int, itemsize: int) -> tuple[int, int]:
    """Max-area tiling reachable for EVERY pass at this dtype — the fwd
    blocks serve dq/dkv whenever no override is active, so the audit's
    'largest' sample must sit in the intersection of the per-pass
    reachable spaces (e.g. (1024, 1024) fits the fwd budget at fp32 but
    busts the dkv kernel's VMEM, so the policy never emits it for dkv)."""
    from ..kernels import tile_policy

    spaces = [
        set(tile_policy.reachable_block_space(seq, seq, kind, 128, 128, itemsize))
        for kind in ("fwd", "dq", "dkv")
    ]
    common = set.intersection(*spaces)
    return max(common, key=lambda p: (p[0] * p[1], p))


def golden_corpus(seq: int = _SEQ) -> list[AuditSpec]:
    """mask kinds x block sizes x dtypes x GQA group — the sampled config
    corpus the audit captures real contracts at (the abstract
    :func:`check_reachable_space` sweep covers the rest of the space)."""
    specs: list[AuditSpec] = []
    masks = _canonical_masks(seq)
    for mask_name, (qr, kr, lo, hi) in masks.items():
        for dtype in ("bfloat16", "float32"):
            itemsize = 2 if dtype == "bfloat16" else 4
            block_choices = dict.fromkeys(
                ((256, 512), (128, 128),
                 _largest_reachable_blocks(seq, itemsize))
            )
            for g in (1, 2, 4):
                hk = 2
                hq = hk * g
                for blocks in block_choices:
                    specs.append(
                        AuditSpec(
                            name=(
                                f"{mask_name}/{dtype}/g{g}/"
                                f"b{blocks[0]}x{blocks[1]}"
                            ),
                            q_ranges=qr, k_ranges=kr, d_lo=lo, d_hi=hi,
                            sq=seq, sk=seq, hq=hq, hk=hk, blocks=blocks,
                            dtype=dtype,
                        )
                    )
    # coverage riders: max-logits output, and bwd block overrides
    qr, kr, lo, hi = masks["causal"]
    specs.append(
        AuditSpec(
            name="causal/bfloat16/g1/b256x512/emit_ml",
            q_ranges=qr, k_ranges=kr, d_lo=lo, d_hi=hi,
            sq=seq, sk=seq, hq=2, hk=2, blocks=(256, 512), emit_ml=True,
        )
    )
    specs.append(
        AuditSpec(
            name="causal/bfloat16/g4/b256x512/bwd_overrides",
            q_ranges=qr, k_ranges=kr, d_lo=lo, d_hi=hi,
            sq=seq, sk=seq, hq=8, hk=2, blocks=(256, 512),
            dq_blocks=(128, 512), dkv_blocks=(256, 256),
        )
    )
    # ragged seqlen: tiles overhang the true extent, so K3 must prove the
    # maps stay inside the PADDED operands and the padding columns of the
    # audit report are non-trivially exercised
    ragged = seq - seq // 8
    qr, kr, lo, hi = _canonical_masks(ragged)["causal"]
    specs.append(
        AuditSpec(
            name="causal_ragged/bfloat16/g2/b256x512",
            q_ranges=qr, k_ranges=kr, d_lo=lo, d_hi=hi,
            sq=ragged, sk=ragged, hq=4, hk=2, blocks=(256, 512),
        )
    )
    # fragmented-mask riders: partial tiles dominate, so the extent half
    # of K3 (check_k3_extents) is exercised on non-trivial live
    # sub-rectangles. The coarse-block variants are the extent-clamped
    # single-pass shape; the fine-block variants are what the mixed
    # dispatch's fragmented branch runs.
    for mask_name, (qr, kr, lo, hi) in _fragmented_masks(seq).items():
        for blocks, tag in (((256, 512), "coarse"), ((128, 128), "fine")):
            for g in (1, 4):
                specs.append(
                    AuditSpec(
                        name=(
                            f"{mask_name}/bfloat16/g{g}/"
                            f"b{blocks[0]}x{blocks[1]}/{tag}"
                        ),
                        q_ranges=qr, k_ranges=kr, d_lo=lo, d_hi=hi,
                        sq=seq, sk=seq, hq=2 * g, hk=2, blocks=blocks,
                    )
                )
    return specs


def run_kernel_audit(
    corpus: list[AuditSpec] | None = None,
    report: VerifyReport | None = None,
) -> tuple[VerifyReport, list[dict]]:
    """The full K1-K5 audit: discovery completeness, per-config contract
    capture + checks, source-level K2/K4, repo-level K5, and the abstract
    reachable-space K1 sweep. Returns (report, per-config rows)."""
    report = report or VerifyReport()
    corpus = corpus if corpus is not None else golden_corpus()

    sites = discover_pallas_sites()
    declared = _pallas_contracts()
    for site in sites:
        if site.kernel_name not in declared:
            report.add(
                "K2", ERROR, f"{site.relpath}:{site.line}",
                f"pallas_call site (kernel '{site.kernel_name}', wrapper "
                f"'{site.wrapper}') has no PALLAS_CONTRACTS entry — "
                f"annotate it so K2/K4 can check it",
            )

    check_kernel_sources(report)
    check_env_keys(report)

    rows: list[dict] = []
    captured_kernels: set[str] = set()
    for spec in corpus:
        for contract in capture_ffa_contracts(spec):
            captured_kernels.add(contract.kernel_name)
            site = f"{spec.name}:{contract.kernel_name}"
            check_contract(report, contract, site)
            info = _contract_shape_info(contract)
            row = {
                "config": spec.name,
                "kernel": contract.kernel_name,
                "grid": list(contract.grid),
                "vmem_bytes": _declared_bytes(contract),
                "vmem_total_bytes": ffa_kernel_residency(
                    info["kind"], info["bq"], info["bk"], info["d"],
                    head_dim_v=info["dv"], dtype_bytes=info["itemsize"],
                    group=info["g"], packed=info["packed"],
                    emit_ml=info["emit_ml"],
                ),
                "vmem_allowed_bytes": VMEM_ALLOWED_BYTES,
            }
            row.update(padding_stats(contract, spec.sq, spec.sk))
            rows.append(row)

    # paged-decode corpus: no plan metadata (padding_stats does not apply —
    # the page grid is dense by construction; dead pages are length-masked)
    for dspec in decode_corpus():
        for contract in capture_decode_contracts(dspec):
            captured_kernels.add(contract.kernel_name)
            site = f"{dspec.name}:{contract.kernel_name}"
            check_contract(report, contract, site)
            info = _contract_shape_info(contract)
            rows.append(
                {
                    "config": dspec.name,
                    "kernel": contract.kernel_name,
                    "grid": list(contract.grid),
                    "vmem_bytes": _declared_bytes(contract),
                    "vmem_total_bytes": ffa_kernel_residency(
                        info["kind"], info["bq"], info["bk"], info["d"],
                        head_dim_v=info["dv"], dtype_bytes=info["itemsize"],
                    ),
                    "vmem_allowed_bytes": VMEM_ALLOWED_BYTES,
                }
            )

    # block-sparse corpus: like decode, no plan metadata — the chunk grid
    # is exactly the top-k selection, dense by construction
    for bspec in bsp_corpus():
        for contract in capture_bsp_contracts(bspec):
            captured_kernels.add(contract.kernel_name)
            site = f"{bspec.name}:{contract.kernel_name}"
            check_contract(report, contract, site)
            info = _contract_shape_info(contract)
            rows.append(
                {
                    "config": bspec.name,
                    "kernel": contract.kernel_name,
                    "grid": list(contract.grid),
                    "vmem_bytes": _declared_bytes(contract),
                    "vmem_total_bytes": ffa_kernel_residency(
                        info["kind"], info["bq"], info["bk"], info["d"],
                        head_dim_v=info["dv"], dtype_bytes=info["itemsize"],
                    ),
                    "vmem_allowed_bytes": VMEM_ALLOWED_BYTES,
                }
            )

    site_kernels = {
        s.kernel_name for s in sites if s.kernel_name in declared
    }
    for missing in sorted(site_kernels - captured_kernels):
        report.add(
            "K1", ERROR, f"kernels/:{missing}",
            f"kernel '{missing}' has a pallas_call site but no corpus "
            f"config exercised it — the audit is not complete",
        )

    sweep = check_reachable_space(report, _SEQ, _SEQ)
    rows.append({"config": "reachable_space_sweep", **sweep})
    return report, rows


# ---------------------------------------------------------------------------
# seeded mutations — the checker's own regression proof
# ---------------------------------------------------------------------------

# a minimal clean kernel in the house style; the K2 mutation deletes its
# init block. Kept source-level so the mutation exercises the same AST
# path as the real kernels.
_TOY_KERNEL_SRC = '''
def _toy_kernel(qt_ref, kt_ref, meta_ref, x_ref, o_ref, acc_scr):
    w = pl.program_id(1)
    is_first = meta_ref[w, IS_FIRST]
    is_last = meta_ref[w, IS_LAST]

    @pl.when(is_first == 1)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    acc_scr[:] += jax.lax.dot_general(
        x_ref[:], x_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(is_last == 1)
    def _():
        o_ref[:] = acc_scr[:].astype(o_ref.dtype)
'''

_TOY_CONTRACTS = {
    "_toy_kernel": dict(
        wrapper="_toy",
        scratch=("acc_scr",),
        outputs=("o_ref",),
        out_dtypes=("input",),
        init_guard="is_first",
        flush_guard="is_last",
        group_inner=None,
    ),
}

# minimal fused-style kernel: a scratch accumulator (is_first/is_last)
# PLUS a revisit-accumulated output (qvf/qvl) — the shape the
# deleted_revisit_init mutation operates on
_TOY_FUSED_KERNEL_SRC = '''
def _toy_fused_kernel(qt_ref, kt_ref, meta_ref, x_ref, dq_ref, o_ref,
                      acc_scr):
    w = pl.program_id(1)
    is_first = meta_ref[w, IS_FIRST]
    is_last = meta_ref[w, IS_LAST]
    qvf = meta_ref[w, QVF]
    qvl = meta_ref[w, QVL]

    @pl.when(is_first == 1)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(qvf == 1)
    def _():
        dq_ref[0] = jnp.zeros((8, 8), jnp.float32)

    contrib = jax.lax.dot_general(
        x_ref[:], x_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_scr[:] += contrib
    dq_ref[0] += contrib

    @pl.when(is_last == 1)
    def _():
        o_ref[:] = acc_scr[:].astype(o_ref.dtype)

    @pl.when(qvl == 1)
    def _():
        dq_ref[0] = dq_ref[0] * 2.0
'''

_TOY_FUSED_CONTRACTS = {
    "_toy_fused_kernel": dict(
        wrapper="_toy_fused",
        scratch=("acc_scr",),
        outputs=("dq_ref", "o_ref"),
        out_dtypes=("f32", "input"),
        init_guard="is_first",
        flush_guard="is_last",
        group_inner=None,
        revisit=dict(out="dq_ref", init_guard="qvf", flush_guard="qvl"),
    ),
}


def _mutation_spec() -> AuditSpec:
    # hq (8) > num_q_tiles (4) so the swapped-axes mutation is provably
    # out of bounds on the q-tile axis
    qr, kr, lo, hi = _canonical_masks(512)["causal"]
    return AuditSpec(
        name="mutation/causal", q_ranges=qr, k_ranges=kr, d_lo=lo, d_hi=hi,
        sq=512, sk=512, hq=8, hk=8, blocks=(128, 128),
    )


def run_seeded_mutations() -> list[dict]:
    """Apply each seeded defect to a clean contract/source/key-set and
    report which rules fire. A healthy checker fires EXACTLY the expected
    rule per mutation — the test suite and ``kernel_audit --selftest``
    both assert on this."""
    from types import SimpleNamespace

    base = next(
        c for c in capture_ffa_contracts(_mutation_spec())
        if c.kernel_name == "_fwd_kernel"
    )
    results: list[dict] = []

    def run(name: str, expected: str, check) -> None:
        report = VerifyReport()
        check(report)
        fired = report.fired_rules()
        results.append(
            {
                "mutation": name,
                "expected_rule": expected,
                "fired_rules": sorted(fired),
                "ok": fired == {expected},
            }
        )

    def oversized(report: VerifyReport) -> None:
        mut = replace(
            base,
            scratch=tuple(
                ((shape[0] * 64,) + tuple(shape[1:]), dtype)
                for shape, dtype in base.scratch
            ),
        )
        check_contract(report, mut, "mutation:oversized_scratch")

    def swapped(report: VerifyReport) -> None:
        q_spec = base.in_specs[0]
        orig = q_spec.index_map
        shim = SimpleNamespace(
            block_shape=q_spec.block_shape,
            # swap the head and q-tile outputs of the real map
            index_map=lambda *a: (
                lambda o: (o[1], o[0]) + tuple(o[2:])
            )(orig(*a)),
        )
        mut = replace(base, in_specs=(shim,) + tuple(base.in_specs[1:]))
        check_contract(report, mut, "mutation:swapped_index_map")

    def no_init(report: VerifyReport) -> None:
        src = _TOY_KERNEL_SRC
        start = src.index("    @pl.when(is_first == 1)")
        end = src.index("    acc_scr[:] +=")
        check_kernel_sources(
            report, src[:start] + src[end:], _TOY_CONTRACTS, "mutation.py"
        )

    def bf16_scratch(report: VerifyReport) -> None:
        mut = replace(
            base,
            scratch=tuple(
                (shape, "bfloat16") for shape, _ in base.scratch
            ),
        )
        check_contract(report, mut, "mutation:bf16_scratch")

    def unlisted_key(report: VerifyReport) -> None:
        check_env_keys(
            report,
            consumed={"MAGI_ATTENTION_UNLISTED_KNOB": {"ffa.py"}},
        )

    def bad_extent(report: VerifyReport) -> None:
        # zero one real item's live k extent: stays aligned and in-bounds,
        # so ONLY the host-recomputation equality can catch the clamp path
        # silently skipping a live chunk
        meta = base.prefetch[2].copy()
        w = int(np.nonzero(meta[:, QE] > meta[:, QS])[0][0])
        meta[w, EK1] = meta[w, EK1] - LANE_QUANTUM
        mut = replace(
            base, prefetch=(base.prefetch[0], base.prefetch[1], meta)
        )
        check_contract(report, mut, "mutation:corrupted_extent_row")

    def no_revisit_init(report: VerifyReport) -> None:
        # delete the qvf first-visit zeroing of the revisit-accumulated
        # output — interpret mode still passes (the donated output buffer
        # happens to start zeroed) but hardware VMEM is undefined on the
        # first visit, so only K2's revisit rule can catch it
        src = _TOY_FUSED_KERNEL_SRC
        start = src.index("    @pl.when(qvf == 1)")
        end = src.index("    contrib = ")
        check_kernel_sources(
            report, src[:start] + src[end:], _TOY_FUSED_CONTRACTS,
            "mutation.py",
        )

    def oob_page_table(report: VerifyReport) -> None:
        # point one page-table entry one past the last page: gather_kv's
        # maximum(table, 0) clamp only rescues -1 sentinels, so an
        # oversized id escapes the k/v operands — only the K3 index-map
        # bounds eval over the real prefetch can catch it
        dbase = next(
            c for c in capture_decode_contracts(decode_corpus()[0])
            if c.kernel_name == "_paged_decode_kernel"
        )
        num_pages = dbase.operands[1][0][0]  # k_pages page axis
        table = dbase.prefetch[0].copy()
        table[0, 0] = num_pages
        mut = replace(
            dbase, prefetch=(table,) + tuple(dbase.prefetch[1:])
        )
        check_contract(report, mut, "mutation:oob_page_table")

    def misrouted_scale_prefetch(report: VerifyReport) -> None:
        # swap the (page, head) outputs of the int8 per-page scale index
        # map: the head coordinate (< hk) silently fits the page axis, but
        # real page ids land on the hk-wide head axis of the (num_pages,
        # hk) scale array — the decode output would mix WRONG pages'
        # scales without faulting, and only the K3 bounds eval over the
        # real page-table prefetch catches the escape
        ibase = next(
            c for c in capture_decode_contracts(
                next(s for s in decode_corpus() if s.variant == "int8")
            )
            if c.kernel_name == "_paged_decode_int8_kernel"
        )
        ks_spec = ibase.in_specs[3]
        orig = ks_spec.index_map
        shim = SimpleNamespace(
            block_shape=ks_spec.block_shape,
            index_map=lambda *a: (lambda o: (o[1], o[0]))(orig(*a)),
        )
        mut = replace(
            ibase,
            in_specs=tuple(ibase.in_specs[:3])
            + (shim,)
            + tuple(ibase.in_specs[4:]),
        )
        check_contract(report, mut, "mutation:misrouted_scale_prefetch")

    def oob_block_table(report: VerifyReport) -> None:
        # point one chunk-table entry one past the last chunk: the block-
        # sparse index maps consume the table UNclamped (the public wrapper
        # audits concrete tables, but a traced top-k bypasses that), so
        # only the K3 index-map bounds eval over the real prefetch catches
        # the out-of-range stream
        bbase = next(
            c for c in capture_bsp_contracts(bsp_corpus()[0])
            if c.kernel_name == "_bsp_fwd_kernel"
        )
        n_chunks = bbase.operands[1][0][0]  # k_c chunk axis
        table = bbase.prefetch[0].copy()
        table[0, 0, 0] = n_chunks
        mut = replace(bbase, prefetch=(table,) + tuple(bbase.prefetch[1:]))
        check_contract(report, mut, "mutation:oob_block_table")

    run("oversized_scratch", "K1", oversized)
    run("swapped_index_map_axes", "K3", swapped)
    run("missing_accumulator_init", "K2", no_init)
    run("deleted_revisit_init", "K2", no_revisit_init)
    run("bf16_accumulator", "K4", bf16_scratch)
    run("unlisted_env_key", "K5", unlisted_key)
    run("corrupted_extent_row", "K3", bad_extent)
    run("oob_page_table", "K3", oob_page_table)
    run("misrouted_scale_prefetch", "K3", misrouted_scale_prefetch)
    run("oob_block_table", "K3", oob_block_table)
    return results
