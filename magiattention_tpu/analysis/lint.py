"""AST-based repo linter: codebase rules the type system cannot express.

Rules (MAGI-L prefix; all stdlib ``ast``, no third-party linter deps):

- **MAGI-L001** — no raw ``os.environ`` / ``os.getenv`` outside
  ``magiattention_tpu/env/``: every behavior flag must go through a typed
  getter so ``ENV_KEYS_AFFECTING_RUNTIME`` can snapshot it into the
  runtime cache key (an unregistered flag read silently survives cache
  hits with stale behavior).
- **MAGI-L002** — no host clocks (``time.time``, ``perf_counter``,
  ``monotonic``, ``process_time``) inside ``kernels/`` or ``functional/``:
  those modules run under ``jit``/``shard_map`` tracing where a host clock
  reads trace time, not step time; timing belongs to the telemetry layer.
- **MAGI-L003** — no ``print`` in library code: the package logs through
  ``logging`` / telemetry so output is capturable and gated.
- **MAGI-L004** — every public dataclass in ``meta/collection`` has an
  entry in :data:`~.violation.RULE_COVERAGE`: adding a new plan object
  forces a decision about how the verifier checks it.
- **MAGI-L005** — every registered fault-injection site
  (``resilience.inject.INJECTION_SITES``) is exercised somewhere in
  ``tests/test_resilience/``: a site nobody injects is a recovery path
  nobody tests, which is how fallback code rots.
- **MAGI-L006** — every ``MAGI_*`` env key named under ``env/`` has a
  row in ``docs/env_variables.md``: an undocumented flag is invisible to
  operators, and the doc table doubles as the review surface for the
  "does this key belong in ENV_KEYS_AFFECTING_RUNTIME?" decision.

Known-legacy findings live in ``lint_baseline.txt`` (``<rule> <relpath>``
per line) so the linter lands green and only *new* violations fail CI.

CLI: ``python -m magiattention_tpu.analysis.lint [root] [--baseline FILE]``.
"""

from __future__ import annotations

import ast
import os
import sys
from dataclasses import dataclass

_CLOCK_NAMES = frozenset(
    {"time", "perf_counter", "monotonic", "process_time", "perf_counter_ns",
     "monotonic_ns", "time_ns"}
)
_ENV_ATTRS = frozenset({"environ", "getenv"})


@dataclass(frozen=True)
class LintFinding:
    rule: str
    path: str  # relative to the lint root
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    @property
    def baseline_key(self) -> str:
        return f"{self.rule} {self.path}"


class _FileLinter(ast.NodeVisitor):
    def __init__(self, relpath: str, check_env: bool, check_clocks: bool):
        self.relpath = relpath
        self.check_env = check_env
        self.check_clocks = check_clocks
        self.findings: list[LintFinding] = []
        self.os_aliases: set[str] = set()
        self.time_aliases: set[str] = set()
        self.env_names: set[str] = set()  # from os import environ/getenv
        self.clock_names: set[str] = set()  # from time import perf_counter...

    def _add(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(
            LintFinding(rule, self.relpath, getattr(node, "lineno", 0), message)
        )

    # -- alias collection --------------------------------------------------

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            if a.name == "os":
                self.os_aliases.add(a.asname or "os")
            elif a.name == "time":
                self.time_aliases.add(a.asname or "time")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "os":
            for a in node.names:
                if a.name in _ENV_ATTRS:
                    self.env_names.add(a.asname or a.name)
        elif node.module == "time":
            for a in node.names:
                if a.name in _CLOCK_NAMES:
                    self.clock_names.add(a.asname or a.name)
        self.generic_visit(node)

    # -- rule checks -------------------------------------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        base = node.value
        if isinstance(base, ast.Name):
            if (
                self.check_env
                and base.id in self.os_aliases
                and node.attr in _ENV_ATTRS
            ):
                self._add(
                    "MAGI-L001", node,
                    f"raw os.{node.attr} outside env/ — add a typed getter "
                    "in magiattention_tpu/env/ instead",
                )
            if (
                self.check_clocks
                and base.id in self.time_aliases
                and node.attr in _CLOCK_NAMES
            ):
                self._add(
                    "MAGI-L002", node,
                    f"host clock time.{node.attr} in traced/kernel code — "
                    "host clocks read trace time here; use the telemetry "
                    "layer",
                )
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if self.check_env and node.id in self.env_names:
            self._add(
                "MAGI-L001", node,
                f"raw {node.id} (from os) outside env/ — add a typed "
                "getter in magiattention_tpu/env/ instead",
            )
        if self.check_clocks and node.id in self.clock_names:
            self._add(
                "MAGI-L002", node,
                f"host clock {node.id} (from time) in traced/kernel code",
            )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Name) and node.func.id == "print":
            self._add(
                "MAGI-L003", node,
                "print() in library code — use logging or telemetry",
            )
        self.generic_visit(node)


def _iter_py_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _in_subdir(relpath: str, subdir: str) -> bool:
    return relpath.replace(os.sep, "/").startswith(subdir + "/")


def lint_file(path: str, relpath: str) -> list[LintFinding]:
    """Lint one python file; relpath decides which rules apply."""
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [LintFinding("MAGI-L000", relpath, e.lineno or 0,
                            f"syntax error: {e.msg}")]
    linter = _FileLinter(
        relpath,
        check_env=not _in_subdir(relpath, "env"),
        check_clocks=(
            _in_subdir(relpath, "kernels") or _in_subdir(relpath, "functional")
        ),
    )
    linter.visit(tree)
    return linter.findings


def check_rule_coverage(root: str) -> list[LintFinding]:
    """MAGI-L004: every public dataclass in meta/collection is covered by a
    verifier rule (declared in violation.RULE_COVERAGE)."""
    from .violation import RULE_COVERAGE

    findings: list[LintFinding] = []
    coll = os.path.join(root, "meta", "collection")
    if not os.path.isdir(coll):
        return findings
    for path in _iter_py_files(coll):
        relpath = os.path.relpath(path, root)
        with open(path, "r", encoding="utf-8") as f:
            try:
                tree = ast.parse(f.read(), filename=path)
            except SyntaxError:
                continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef) or node.name.startswith("_"):
                continue
            is_dataclass = any(
                (isinstance(d, ast.Name) and d.id == "dataclass")
                or (isinstance(d, ast.Attribute) and d.attr == "dataclass")
                or (
                    isinstance(d, ast.Call)
                    and (
                        (isinstance(d.func, ast.Name)
                         and d.func.id == "dataclass")
                        or (isinstance(d.func, ast.Attribute)
                            and d.func.attr == "dataclass")
                    )
                )
                for d in node.decorator_list
            )
            if is_dataclass and node.name not in RULE_COVERAGE:
                findings.append(
                    LintFinding(
                        "MAGI-L004", relpath, node.lineno,
                        f"public plan dataclass {node.name} has no entry in "
                        "analysis.violation.RULE_COVERAGE — declare which "
                        "verifier rule(s) check it",
                    )
                )
    return findings


def check_injection_site_coverage(root: str) -> list[LintFinding]:
    """MAGI-L005: every registered injection site name appears in the
    chaos suite (``tests/test_resilience/`` next to the package root)."""
    from ..resilience.inject import INJECTION_SITES

    findings: list[LintFinding] = []
    inject_rel = os.path.join("resilience", "inject.py")
    if not os.path.exists(os.path.join(root, inject_rel)):
        return findings  # linting a foreign tree; the registry isn't there
    tests_dir = os.path.join(os.path.dirname(root), "tests", "test_resilience")
    corpus = ""
    if os.path.isdir(tests_dir):
        for path in _iter_py_files(tests_dir):
            with open(path, "r", encoding="utf-8") as f:
                corpus += f.read()
    for site in INJECTION_SITES:
        if site not in corpus:
            findings.append(
                LintFinding(
                    "MAGI-L005", inject_rel, 0,
                    f"injection site '{site}' has no test in "
                    "tests/test_resilience/ — every registered site must "
                    "exercise its documented recover-or-raise path",
                )
            )
    return findings


_ENV_KEY_RE = None  # compiled lazily; keeps the module import light


def check_env_doc_coverage(
    root: str, docs_path: str | None = None
) -> list[LintFinding]:
    """MAGI-L006: every ``MAGI_*`` env key string constant under ``env/``
    appears in ``docs/env_variables.md``.

    Keys are discovered syntactically (string constants matching
    ``MAGI_[A-Z0-9_]+`` in ``env/*.py``) so getters, the
    ``ENV_KEYS_AFFECTING_RUNTIME`` registry, and scoped_env defaults all
    feed the same check. Non-``MAGI_`` keys (e.g. the upstream
    ``JAX_COMPILATION_CACHE_DIR`` passthrough) are deliberately exempt —
    they are not ours to catalogue.
    """
    global _ENV_KEY_RE
    if _ENV_KEY_RE is None:
        import re

        _ENV_KEY_RE = re.compile(r"^MAGI_[A-Z0-9_]+$")
    findings: list[LintFinding] = []
    env_dir = os.path.join(root, "env")
    if not os.path.isdir(env_dir):
        return findings
    if docs_path is None:
        docs_path = os.path.join(
            os.path.dirname(root), "docs", "env_variables.md"
        )
    doc_text = ""
    if os.path.exists(docs_path):
        with open(docs_path, "r", encoding="utf-8") as f:
            doc_text = f.read()
    for path in _iter_py_files(env_dir):
        relpath = os.path.relpath(path, root)
        with open(path, "r", encoding="utf-8") as f:
            try:
                tree = ast.parse(f.read(), filename=path)
            except SyntaxError:
                continue
        seen: set[str] = set()
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and _ENV_KEY_RE.match(node.value)
                and node.value not in seen
                and node.value not in doc_text
            ):
                seen.add(node.value)
                findings.append(
                    LintFinding(
                        "MAGI-L006", relpath, node.lineno,
                        f"env key {node.value} has no row in "
                        "docs/env_variables.md — document it (and decide "
                        "whether it belongs in ENV_KEYS_AFFECTING_RUNTIME)",
                    )
                )
    return findings


def lint_package(root: str) -> list[LintFinding]:
    """Run every rule over a package directory; findings in path order."""
    findings: list[LintFinding] = []
    for path in _iter_py_files(root):
        findings.extend(lint_file(path, os.path.relpath(path, root)))
    findings.extend(check_rule_coverage(root))
    findings.extend(check_injection_site_coverage(root))
    findings.extend(check_env_doc_coverage(root))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def load_baseline(path: str) -> set[str]:
    """``<rule> <relpath>`` per line; '#' comments and blanks ignored."""
    out: set[str] = set()
    if not os.path.exists(path):
        return out
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                out.add(line)
    return out


def run(root: str, baseline_path: str | None = None) -> int:
    """Lint ``root``; returns the number of non-baselined findings."""
    w = sys.stdout.write
    baseline = load_baseline(baseline_path) if baseline_path else set()
    findings = lint_package(root)
    fresh = [f for f in findings if f.baseline_key not in baseline]
    used = {f.baseline_key for f in findings} & baseline
    for f in fresh:
        w(f"{f}\n")
    stale = sorted(baseline - used)
    for key in stale:
        w(f"note: stale baseline entry (violation fixed — remove the "
          f"line): {key}\n")
    if baseline:
        w(
            f"warning: lint baseline is non-empty ({len(baseline)} "
            f"entr{'y' if len(baseline) == 1 else 'ies'}) — the legacy "
            f"debt was burned down; fix the site instead of baselining\n"
        )
    w(
        f"lint: {len(findings)} finding(s), {len(findings) - len(fresh)} "
        f"baselined, {len(fresh)} new\n"
    )
    return len(fresh)


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    baseline = None
    if "--baseline" in args:
        i = args.index("--baseline")
        baseline = args[i + 1]
        del args[i: i + 2]
    if args:
        root = args[0]
    else:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if baseline is None:
        default = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "lint_baseline.txt"
        )
        baseline = default if os.path.exists(default) else None
    return 1 if run(root, baseline_path=baseline) else 0


if __name__ == "__main__":
    sys.exit(main())
