"""Ring attention baseline (ppermute KV rotation).

Ref: exps/dist_attn/baselines/ring_attn.py — contiguous sequence sharding;
kv rotates around the ring one hop per step (``jax.lax.ppermute``), each rank
computes the partial attention of its q block against the visiting kv block,
and partials merge with the lse identity. Supports arbitrary band-slice masks
by clipping the global metadata to every (q_block, kv_block) pair on the host
(per-rank-per-step plans stacked as sharded arrays, like the CP runtime).

Backward reuses the multi-part merged VJP (functional/dist_attn._multi_ffa);
the ppermute chain transposes automatically under AD.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..functional.dist_attn import _multi_ffa
from ..kernels.ffa import FFAParams, _should_interpret, default_blocks
from ..kernels.ffa_plan import build_ffa_plan, pad_plan
from ..kernels.mask_utils import BAND_INF, types_to_bands


def _clip_to_blocks(
    q_ranges, k_ranges, d_lo, d_hi, q0, q1, k0, k1
) -> list[tuple[int, int, int, int, int, int]]:
    """Clip global band slices to q rows [q0,q1) x k cols [k0,k1), shifted to
    block-local coordinates."""
    out = []
    for i in range(len(q_ranges)):
        qs, qe = max(int(q_ranges[i, 0]), q0), min(int(q_ranges[i, 1]), q1)
        ks, ke = max(int(k_ranges[i, 0]), k0), min(int(k_ranges[i, 1]), k1)
        if qs >= qe or ks >= ke:
            continue
        lo, hi = int(d_lo[i]), int(d_hi[i])
        # local coords subtract block bases; shift band accordingly
        lo_l = lo if lo <= -BAND_INF else lo + q0 - k0
        hi_l = hi if hi >= BAND_INF else hi + q0 - k0
        out.append((qs - q0, qe - q0, ks - k0, ke - k0, lo_l, hi_l))
    return out


def ring_attn(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_ranges: np.ndarray,
    k_ranges: np.ndarray,
    attn_type_map: np.ndarray,
    mesh: Mesh,
    cp_axis: str = "cp",
    softmax_scale: float | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Sequence-sharded (contiguous blocks) in/out ring attention.

    Args:
        q/k/v: ``(S, h, d)`` natural order, sharded P(cp_axis) on dim 0
            (rank r owns rows [r*shard, (r+1)*shard)).

    Returns:
        (out ``(S, hq, dv)``, lse ``(S, hq)``), same sharding.
    """
    cp = mesh.shape[cp_axis]
    S, hq, dh = q.shape
    _, hk, dv = v.shape
    shard = S // cp
    scale = float(dh) ** -0.5 if softmax_scale is None else softmax_scale

    qr = np.asarray(q_ranges, dtype=np.int32)
    kr = np.asarray(k_ranges, dtype=np.int32)
    tm = np.asarray(attn_type_map, dtype=np.int32)
    lo, hi = types_to_bands(qr, kr, tm)

    bq, bk = default_blocks(shard, shard)
    # per (rank, step): kv block visiting rank r at step s came from rank
    # (r - s) mod cp
    plans = []
    for s in range(cp):
        per_rank = []
        for r in range(cp):
            src = (r - s) % cp
            slices = _clip_to_blocks(
                qr, kr, lo, hi,
                r * shard, (r + 1) * shard,
                src * shard, (src + 1) * shard,
            )
            arr = np.asarray(slices, dtype=np.int64).reshape(-1, 6)
            per_rank.append(
                build_ffa_plan(
                    arr[:, 0:2].astype(np.int32),
                    arr[:, 2:4].astype(np.int32),
                    arr[:, 4].astype(np.int32),
                    arr[:, 5].astype(np.int32),
                    shard, shard, bq, bk,
                )
            )
        plans.append(per_rank)

    w = max(p.num_work for ps in plans for p in ps)
    wt = max(p.num_work_t for ps in plans for p in ps)
    stacked = []  # per step: tuple of 6 arrays shaped (cp, ...)
    for s in range(cp):
        padded = [pad_plan(p, w, wt) for p in plans[s]]
        stacked.append(
            tuple(
                jnp.asarray(np.stack([getattr(p, f) for p in padded]))
                for f in ("work_qt", "work_kt", "meta",
                          "work_qt_t", "work_kt_t", "meta_t")
            )
        )
    params = FFAParams(
        num_work=w, num_work_t=wt,
        num_q_tiles=plans[0][0].num_q_tiles,
        num_k_tiles=plans[0][0].num_k_tiles,
        block_q=bq, block_k=bk,
        softmax_scale=scale, softcap=0.0, group=hq // hk,
        interpret=_should_interpret(),
    )
    params_list = tuple([params] * cp)
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def f(q, k, v, step_arrays):
        ks, vs = [k], [v]
        for s in range(1, cp):
            ks.append(jax.lax.ppermute(ks[-1], cp_axis, perm))
            vs.append(jax.lax.ppermute(vs[-1], cp_axis, perm))
        arrays_list = tuple(
            tuple(a[0] for a in step_arrays[s]) for s in range(cp)
        )
        return _multi_ffa(q, tuple(ks), tuple(vs), arrays_list, params_list)

    fn = shard_map(
        f, mesh=mesh,
        in_specs=(P(cp_axis), P(cp_axis), P(cp_axis),
                  [tuple(P(cp_axis) for _ in st) for st in stacked]),
        out_specs=(P(cp_axis), P(cp_axis)),
        check_vma=False,
    )
    return fn(q, k, v, stacked)
