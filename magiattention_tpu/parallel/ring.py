"""Ring attention baseline (ppermute KV rotation).

Ref: exps/dist_attn/baselines/ring_attn.py — contiguous sequence sharding;
kv rotates around the ring one hop per step (``jax.lax.ppermute``), each rank
computes the partial attention of its q block against the visiting kv block,
and partials merge with the lse identity. Supports arbitrary band-slice masks
by clipping the global metadata to every (q_block, kv_block) pair on the host
(per-rank-per-step plans stacked as sharded arrays, like the CP runtime).

Backward reuses the multi-part merged VJP (functional/dist_attn._multi_ffa);
the ppermute chain transposes automatically under AD.
"""

from __future__ import annotations

import jax
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..functional.dist_attn import _multi_ffa
from ..kernels.ffa import default_blocks
from ._utils import band_meta, baseline_params, ring_step_plans, stack_step_plans


def ring_attn(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_ranges: np.ndarray,
    k_ranges: np.ndarray,
    attn_type_map: np.ndarray,
    mesh: Mesh,
    cp_axis: str = "cp",
    softmax_scale: float | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Sequence-sharded (contiguous blocks) in/out ring attention.

    Args:
        q/k/v: ``(S, h, d)`` natural order, sharded P(cp_axis) on dim 0
            (rank r owns rows [r*shard, (r+1)*shard)).

    Returns:
        (out ``(S, hq, dv)``, lse ``(S, hq)``), same sharding.
    """
    cp = mesh.shape[cp_axis]
    S, hq, dh = q.shape
    _, hk, dv = v.shape
    shard = S // cp
    scale = float(dh) ** -0.5 if softmax_scale is None else softmax_scale

    qr, kr, lo, hi = band_meta(q_ranges, k_ranges, attn_type_map)

    bq, bk = default_blocks(shard, shard)
    plans = ring_step_plans(qr, kr, lo, hi, shard, cp, bq, bk)
    stacked, w, wt = stack_step_plans(plans)
    params = baseline_params(plans[0][0], w, wt, bq, bk, scale, hq, hk)
    params_list = tuple([params] * cp)
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def f(q, k, v, step_arrays):
        ks, vs = [k], [v]
        for s in range(1, cp):
            ks.append(jax.lax.ppermute(ks[-1], cp_axis, perm))
            vs.append(jax.lax.ppermute(vs[-1], cp_axis, perm))
        arrays_list = tuple(
            tuple(a[0] for a in step_arrays[s]) for s in range(cp)
        )
        return _multi_ffa(q, tuple(ks), tuple(vs), arrays_list, params_list)[:2]

    fn = shard_map(
        f, mesh=mesh,
        in_specs=(P(cp_axis), P(cp_axis), P(cp_axis),
                  [tuple(P(cp_axis) for _ in st) for st in stacked]),
        out_specs=(P(cp_axis), P(cp_axis)),
        check_vma=False,
    )
    return fn(q, k, v, stacked)
