"""Ring attention baseline family (P2P rotation + AllGather variants).

Ref: exps/dist_attn/baselines/ring_attn.py — the reference ships two
executors (RingAttnP2P :1668, RingAttnAllGather :1460), both over *zigzag*
sequence sharding (shard.py:486): the sequence splits into 2*cp chunks and
rank r owns chunks r and 2cp-1-r, so causal masks load-balance exactly.
TPU redesign:

- P2P: kv rotates one hop per step (``jax.lax.ppermute``); each rank
  computes its q block against the visiting kv block and partials merge
  with the lse identity (functional/dist_attn._multi_ffa). Arbitrary
  band-slice masks are supported by clipping the global metadata to every
  (q owner, kv owner) chunk pair on the host — the zigzag half-chunk
  causal skips (ref loongtrain.py "q, k0, v0" step specialization) fall
  out of the plan for free: empty pairs produce no work items.
- AllGather: KV is all-gathered up front (one collective instead of cp-1
  hops — the latency-bound regime the reference's AG variant targets),
  reordered zigzag->natural with a static gather, and each rank runs ONE
  merged-plan FFA of its q block against the full sequence. jax AD
  transposes the all_gather + take into scatter-add + reduce-scatter,
  which is exactly the reference's dkv reduce-scatter backward.

Backward everywhere reuses the multi-part merged VJP; the ppermute chain
transposes automatically under AD.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from ..utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..functional.dist_attn import _multi_ffa
from ..kernels.ffa import default_blocks
from ._utils import (
    band_meta,
    baseline_params,
    block_plan,
    check_zigzag_geometry,
    clip_to_segs,
    ring_step_plans,
    stack_step_plans,
    zigzag_inv_perm,
    zigzag_perm,
    zigzag_ring_step_plans,
    zigzag_segs,
)


def ring_dispatch(x: jax.Array, cp: int, sharding: str = "zigzag") -> jax.Array:
    """Natural global order -> the layout ``ring_attn`` shards (host-side
    permutation, ref shard.py zigzag_dispatch). Identity for contiguous."""
    if sharding == "contig":
        return x
    return jnp.take(x, jnp.asarray(zigzag_perm(x.shape[0], cp)), axis=0)


def ring_undispatch(x: jax.Array, cp: int, sharding: str = "zigzag") -> jax.Array:
    """Inverse of :func:`ring_dispatch` (ref shard.py zigzag_undispatch)."""
    if sharding == "contig":
        return x
    return jnp.take(x, jnp.asarray(zigzag_inv_perm(x.shape[0], cp)), axis=0)


def ring_attn(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_ranges: np.ndarray,
    k_ranges: np.ndarray,
    attn_type_map: np.ndarray,
    mesh: Mesh,
    cp_axis: str = "cp",
    softmax_scale: float | None = None,
    sharding: str = "zigzag",
) -> tuple[jax.Array, jax.Array]:
    """P2P ring attention (ref RingAttnP2P).

    Args:
        q/k/v: ``(S, h, d)`` in ``ring_dispatch(x, cp, sharding)`` layout,
            sharded P(cp_axis) on dim 0.
        sharding: ``zigzag`` (reference layout, causal load-balanced) or
            ``contig`` (naive contiguous blocks).

    Returns:
        (out ``(S, hq, dv)``, lse ``(S, hq)``), same layout/sharding —
        ``ring_undispatch`` restores natural order.
    """
    cp = mesh.shape[cp_axis]
    S, hq, dh = q.shape
    _, hk, dv = v.shape
    shard = S // cp
    scale = float(dh) ** -0.5 if softmax_scale is None else softmax_scale

    qr, kr, lo, hi = band_meta(q_ranges, k_ranges, attn_type_map)

    bq, bk = default_blocks(shard, shard)
    if sharding == "zigzag":
        plans = zigzag_ring_step_plans(qr, kr, lo, hi, shard, cp, bq, bk)
    elif sharding == "contig":
        plans = ring_step_plans(qr, kr, lo, hi, shard, cp, bq, bk)
    else:
        raise ValueError(f"unknown ring sharding: {sharding!r}")
    stacked, w, wt = stack_step_plans(plans)
    params = baseline_params(plans[0][0], w, wt, bq, bk, scale, hq, hk)
    params_list = tuple([params] * cp)
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def f(q, k, v, step_arrays):
        ks, vs = [k], [v]
        for s in range(1, cp):
            ks.append(jax.lax.ppermute(ks[-1], cp_axis, perm))
            vs.append(jax.lax.ppermute(vs[-1], cp_axis, perm))
        arrays_list = tuple(
            tuple(a[0] for a in step_arrays[s]) for s in range(cp)
        )
        return _multi_ffa(q, tuple(ks), tuple(vs), arrays_list, params_list)[:2]

    fn = shard_map(
        f, mesh=mesh,
        in_specs=(P(cp_axis), P(cp_axis), P(cp_axis),
                  [tuple(P(cp_axis) for _ in st) for st in stacked]),
        out_specs=(P(cp_axis), P(cp_axis)),
        check_vma=False,
    )
    return fn(q, k, v, stacked)


def ring_attn_allgather(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_ranges: np.ndarray,
    k_ranges: np.ndarray,
    attn_type_map: np.ndarray,
    mesh: Mesh,
    cp_axis: str = "cp",
    softmax_scale: float | None = None,
    sharding: str = "zigzag",
) -> tuple[jax.Array, jax.Array]:
    """AllGather ring attention (ref RingAttnAllGather): one up-front KV
    all_gather + a single merged-plan kernel per rank; dkv reduce-scatters
    through the AD transpose. Same layout contract as :func:`ring_attn`.
    """
    cp = mesh.shape[cp_axis]
    S, hq, dh = q.shape
    _, hk, dv = v.shape
    shard = S // cp
    scale = float(dh) ** -0.5 if softmax_scale is None else softmax_scale

    qr, kr, lo, hi = band_meta(q_ranges, k_ranges, attn_type_map)
    bq, bk = default_blocks(shard, S)

    # per-rank merged plan: q = this rank's segments, k = full natural seq
    per_rank = []
    for r in range(cp):
        if sharding == "zigzag":
            check_zigzag_geometry(shard, cp)
            q_segs = zigzag_segs(r, cp, shard // 2)
        elif sharding == "contig":
            q_segs = [(r * shard, (r + 1) * shard, 0)]
        else:
            raise ValueError(f"unknown ring sharding: {sharding!r}")
        slices = clip_to_segs(qr, kr, lo, hi, q_segs, [(0, S, 0)])
        per_rank.append(block_plan(slices, shard, S, bq, bk))
    stacked, w, wt = stack_step_plans([per_rank])
    params = baseline_params(per_rank[0], w, wt, bq, bk, scale, hq, hk)

    # gathered KV arrives in dispatch layout (rank-major shards); this
    # static gather restores natural order (ref
    # gather_with_reorder_before_attn, ring_attn.py:76)
    if sharding == "zigzag":
        reorder = jnp.asarray(zigzag_inv_perm(S, cp))
    else:
        reorder = None

    def f(q, k, v, arrays):
        k_all = jax.lax.all_gather(k, cp_axis, axis=0, tiled=True)
        v_all = jax.lax.all_gather(v, cp_axis, axis=0, tiled=True)
        if reorder is not None:
            k_all = jnp.take(k_all, reorder, axis=0)
            v_all = jnp.take(v_all, reorder, axis=0)
        local = tuple(a[0] for a in arrays[0])
        return _multi_ffa(
            q, (k_all,), (v_all,), (local,), (params,)
        )[:2]

    fn = shard_map(
        f, mesh=mesh,
        in_specs=(P(cp_axis), P(cp_axis), P(cp_axis),
                  [tuple(P(cp_axis) for _ in st) for st in stacked]),
        out_specs=(P(cp_axis), P(cp_axis)),
        check_vma=False,
    )
    return fn(q, k, v, stacked)
