"""Ulysses (head-sharded) sequence parallelism baseline.

Ref: exps/dist_attn/baselines/ulysess.py — DeepSpeed-SP style: all_to_all
converts sequence sharding into head sharding, every rank computes full-
sequence attention for its head subset with the *global* (static) slice
metadata, and an inverse all_to_all restores sequence sharding. Requires
``n_kv_heads % cp == 0``.
"""

from __future__ import annotations

import jax
import numpy as np
from ..utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..kernels.ffa import ffa_attn


def ulysses_attn(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_ranges: np.ndarray,
    k_ranges: np.ndarray,
    attn_type_map: np.ndarray,
    mesh: Mesh,
    cp_axis: str = "cp",
    softmax_scale: float | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Sequence-sharded in, sequence-sharded out.

    Args:
        q/k/v: ``(S, h, d)`` natural order, sharded P(cp_axis) on dim 0.
        q_ranges/k_ranges/attn_type_map: concrete global slice metadata.

    Returns:
        (out ``(S, hq, dv)``, lse ``(S, hq)``), same sharding.
    """
    cp = mesh.shape[cp_axis]
    S, hq, dh = q.shape
    _, hk, dv = v.shape
    if hq % cp or hk % cp:
        raise ValueError(f"ulysses requires heads divisible by cp ({hq},{hk},{cp})")

    def f(q, k, v):
        # (shard, h, d) -> (S, h/cp, d): split heads, gather sequence
        qg = jax.lax.all_to_all(q, cp_axis, split_axis=1, concat_axis=0, tiled=True)
        kg = jax.lax.all_to_all(k, cp_axis, split_axis=1, concat_axis=0, tiled=True)
        vg = jax.lax.all_to_all(v, cp_axis, split_axis=1, concat_axis=0, tiled=True)
        out_g, lse_g = ffa_attn(
            qg, kg, vg, q_ranges, k_ranges, attn_type_map,
            softmax_scale=softmax_scale,
        )
        out = jax.lax.all_to_all(
            out_g, cp_axis, split_axis=0, concat_axis=1, tiled=True
        )
        lse = jax.lax.all_to_all(
            lse_g[..., None], cp_axis, split_axis=0, concat_axis=1, tiled=True
        )[..., 0]
        return out, lse

    fn = shard_map(
        f, mesh=mesh,
        in_specs=(P(cp_axis), P(cp_axis), P(cp_axis)),
        out_specs=(P(cp_axis), P(cp_axis)),
        check_vma=False,
    )
    return fn(q, k, v)
