"""Parallelism strategies beyond the flagship CP engine.

Ref: exps/dist_attn/baselines/ — the reference ships Ulysses / Ring /
USP / LoongTrain context-parallel baselines for its distributed benchmark
comparison; these are the TPU-native equivalents built on the same FFA
kernel and XLA collectives.
"""

from .ulysses import ulysses_attn  # noqa: F401
from .ring import (  # noqa: F401
    ring_attn,
    ring_attn_allgather,
    ring_dispatch,
    ring_undispatch,
)
from .usp import usp_attn  # noqa: F401
from .loongtrain import loongtrain_attn, make_loongtrain_mesh  # noqa: F401
from .hybrid import allgather_attn, hybrid_cp_attn  # noqa: F401
