"""USP (Ulysses x Ring 2D) sequence-parallel baseline.

Ref: exps/dist_attn/baselines/usp.py — a 2D CP decomposition: the inner
``ulysses`` mesh axis converts sequence sharding to head sharding with an
all_to_all, and the outer ``ring`` axis rotates KV blocks ppermute-style.
Total context parallelism = ulysses_size * ring_size with the head-count
divisibility requirement reduced to the ulysses axis only.

Layout: q/k/v are sharded over BOTH axes on dim 0 via ``P((ring, ulysses))``
so that, after the in-shard_map all_to_all over the ulysses axis, each ring
rank holds the contiguous sequence block ``[r*S/R, (r+1)*S/R)`` for its head
subset — exactly the ring baseline's layout with ``1/U`` of the heads.
"""

from __future__ import annotations

import jax
import numpy as np
from ..utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..functional.dist_attn import _multi_ffa
from ..kernels.ffa import default_blocks
from ._utils import band_meta, baseline_params, ring_step_plans, stack_step_plans


def usp_attn(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_ranges: np.ndarray,
    k_ranges: np.ndarray,
    attn_type_map: np.ndarray,
    mesh: Mesh,
    ring_axis: str = "rp",
    ulysses_axis: str = "sp",
    softmax_scale: float | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Sequence-sharded in/out over ``P((ring_axis, ulysses_axis))``.

    Args:
        q/k/v: ``(S, h, d)`` natural order, dim 0 sharded over both axes.

    Returns:
        (out ``(S, hq, dv)``, lse ``(S, hq)`` fp32), same sharding.
    """
    R = mesh.shape[ring_axis]
    U = mesh.shape[ulysses_axis]
    S, hq, dh = q.shape
    _, hk, dv = v.shape
    if hq % U or hk % U:
        raise ValueError(
            f"usp requires heads divisible by ulysses size ({hq},{hk},{U})"
        )
    ring_shard = S // R
    scale = float(dh) ** -0.5 if softmax_scale is None else softmax_scale

    qr, kr, lo, hi = band_meta(q_ranges, k_ranges, attn_type_map)

    bq, bk = default_blocks(ring_shard, ring_shard)
    plans = ring_step_plans(qr, kr, lo, hi, ring_shard, R, bq, bk)
    stacked, w, wt = stack_step_plans(plans)

    params = baseline_params(plans[0][0], w, wt, bq, bk, scale, hq, hk)
    params_list = tuple([params] * R)
    perm = [(i, (i + 1) % R) for i in range(R)]

    def a2a(x, split_axis, concat_axis):
        return jax.lax.all_to_all(
            x, ulysses_axis, split_axis=split_axis,
            concat_axis=concat_axis, tiled=True,
        )

    def f(q, k, v, step_arrays):
        # ulysses phase: seq shard -> head shard within the ring block
        qg, kg, vg = (a2a(t, 1, 0) for t in (q, k, v))
        # ring phase over the ring axis
        ks, vs = [kg], [vg]
        for _ in range(1, R):
            ks.append(jax.lax.ppermute(ks[-1], ring_axis, perm))
            vs.append(jax.lax.ppermute(vs[-1], ring_axis, perm))
        arrays_list = tuple(
            tuple(a[0] for a in step_arrays[s]) for s in range(R)
        )
        out_g, lse_g, _ = _multi_ffa(
            qg, tuple(ks), tuple(vs), arrays_list, params_list
        )
        out = a2a(out_g, 0, 1)
        lse = a2a(lse_g, 0, 1)
        return out, lse

    spec = P((ring_axis, ulysses_axis))
    fn = shard_map(
        f, mesh=mesh,
        in_specs=(spec, spec, spec,
                  [tuple(P(ring_axis) for _ in st) for st in stacked]),
        out_specs=(spec, spec),
        check_vma=False,
    )
    return fn(q, k, v, stacked)
