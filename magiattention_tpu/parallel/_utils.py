"""Shared host-side planning helpers for the CP baselines.

Every baseline in this package walks the same recipe (ref
exps/dist_attn/baselines/shard.py, utils_cp.py): clip the *global*
band-slice metadata to a (q block, kv block) pair per (step, rank), build an
FFA plan for each, and stack the plans into rank-sharded arrays so one traced
SPMD program serves every rank.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..kernels.ffa import FFAParams, _should_interpret
from ..kernels.ffa_plan import build_ffa_plan, pad_plan
from ..kernels.mask_utils import BAND_INF, types_to_bands

PLAN_FIELDS = ("work_qt", "work_kt", "meta", "work_qt_t", "work_kt_t", "meta_t")


def band_meta(q_ranges, k_ranges, attn_type_map):
    """Normalize global slice metadata to (qr, kr, d_lo, d_hi) int32 arrays."""
    qr = np.asarray(q_ranges, dtype=np.int32)
    kr = np.asarray(k_ranges, dtype=np.int32)
    tm = np.asarray(attn_type_map, dtype=np.int32)
    lo, hi = types_to_bands(qr, kr, tm)
    return qr, kr, lo, hi


def ring_step_plans(qr, kr, lo, hi, shard: int, n: int, bq: int, bk: int):
    """``plans[step][rank]`` for an n-rank KV ring over contiguous blocks of
    ``shard`` rows: the kv block visiting rank r at step s came from rank
    ``(r - s) % n``."""
    plans = []
    for s in range(n):
        per_rank = []
        for r in range(n):
            src = (r - s) % n
            slices = clip_to_blocks(
                qr, kr, lo, hi,
                r * shard, (r + 1) * shard,
                src * shard, (src + 1) * shard,
            )
            per_rank.append(block_plan(slices, shard, shard, bq, bk))
        plans.append(per_rank)
    return plans


def zigzag_perm(S: int, cp: int) -> np.ndarray:
    """Global row permutation for zigzag sharding (ref
    exps/dist_attn/baselines/shard.py:486 generate_zigzag_dispatch_indices):
    the sequence splits into ``2*cp`` equal chunks and rank r owns chunks
    ``r`` and ``2*cp-1-r`` — the classic causal load-balance layout (every
    rank computes the same attention area). ``perm[i]`` is the natural-order
    row stored at zigzag position ``i``; sharding the permuted array with
    ``P(cp_axis)`` hands each rank its two chunks."""
    if S % (2 * cp):
        raise ValueError(f"zigzag needs seqlen % (2*cp) == 0, got {S} % {2*cp}")
    c = S // (2 * cp)
    order = []
    for r in range(cp):
        order += [r, 2 * cp - 1 - r]
    return np.concatenate(
        [np.arange(ch * c, (ch + 1) * c, dtype=np.int64) for ch in order]
    )


def zigzag_inv_perm(S: int, cp: int) -> np.ndarray:
    """Inverse of :func:`zigzag_perm` (zigzag position of each natural row)."""
    perm = zigzag_perm(S, cp)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm), dtype=np.int64)
    return inv


def check_zigzag_geometry(shard: int, n: int) -> None:
    """Plans assume each rank owns two equal chunks: shard must be even
    (seqlen % (2*n) == 0). Without this check an odd shard silently
    truncates (c = shard // 2) and the plans misalign to the mask."""
    if shard % 2:
        raise ValueError(
            f"zigzag sharding needs an even per-rank shard "
            f"(seqlen % {2 * n} == 0), got shard={shard}"
        )


def zigzag_segs(rank: int, cp: int, chunk: int) -> list[tuple[int, int, int]]:
    """The two global segments rank owns under zigzag sharding, as
    ``(gstart, gend, local_offset)`` rows-of-``chunk`` pairs."""
    return [
        (rank * chunk, (rank + 1) * chunk, 0),
        ((2 * cp - 1 - rank) * chunk, (2 * cp - rank) * chunk, chunk),
    ]


def clip_to_segs(
    q_ranges, k_ranges, d_lo, d_hi,
    q_segs: list[tuple[int, int, int]],
    k_segs: list[tuple[int, int, int]],
) -> np.ndarray:
    """Clip global band slices to every (q_seg, k_seg) pair of possibly
    non-contiguous ownership (zigzag), shifting to buffer-local coordinates
    via each segment's local offset. Returns ``(n, 6)`` int64 local slices."""
    out = []
    for q0, q1, qoff in q_segs:
        for k0, k1, koff in k_segs:
            # local ql = g - q0 + qoff, kl = g - k0 + koff; the band
            # j - i >= lo becomes kl - ql >= lo + (q0 - qoff) - (k0 - koff)
            shift = (q0 - qoff) - (k0 - koff)
            for i in range(len(q_ranges)):
                qs = max(int(q_ranges[i, 0]), q0)
                qe = min(int(q_ranges[i, 1]), q1)
                ks = max(int(k_ranges[i, 0]), k0)
                ke = min(int(k_ranges[i, 1]), k1)
                if qs >= qe or ks >= ke:
                    continue
                lo, hi = int(d_lo[i]), int(d_hi[i])
                lo_l = lo if lo <= -BAND_INF else lo + shift
                hi_l = hi if hi >= BAND_INF else hi + shift
                out.append((
                    qs - q0 + qoff, qe - q0 + qoff,
                    ks - k0 + koff, ke - k0 + koff,
                    lo_l, hi_l,
                ))
    return np.asarray(out, dtype=np.int64).reshape(-1, 6)


def zigzag_ring_step_plans(
    qr, kr, lo, hi, shard: int, n: int, bq: int, bk: int,
    ring_rank_of=None,
):
    """``plans[step][rank]`` for an n-rank KV ring under zigzag sharding:
    both q and the visiting kv buffer hold their owner's two zigzag chunks.
    ``ring_rank_of`` maps a flat rank to its ring rank (identity for the
    plain ring; the double-ring visiting order for LoongTrain)."""
    check_zigzag_geometry(shard, n)
    c = shard // 2
    plans = []
    for s in range(n):
        per_rank = []
        for r in range(n):
            src = ring_rank_of(r, s) if ring_rank_of else (r - s) % n
            slices = clip_to_segs(
                qr, kr, lo, hi,
                zigzag_segs(r, n, c), zigzag_segs(src, n, c),
            )
            per_rank.append(block_plan(slices, shard, shard, bq, bk))
        plans.append(per_rank)
    return plans


def clip_to_blocks(
    q_ranges, k_ranges, d_lo, d_hi, q0, q1, k0, k1
) -> np.ndarray:
    """Clip global band slices to q rows [q0,q1) x k cols [k0,k1), shifted to
    block-local coordinates. Returns an ``(n, 6)`` int64 array of
    ``(qs, qe, ks, ke, d_lo, d_hi)`` local slices."""
    out = []
    for i in range(len(q_ranges)):
        qs, qe = max(int(q_ranges[i, 0]), q0), min(int(q_ranges[i, 1]), q1)
        ks, ke = max(int(k_ranges[i, 0]), k0), min(int(k_ranges[i, 1]), k1)
        if qs >= qe or ks >= ke:
            continue
        lo, hi = int(d_lo[i]), int(d_hi[i])
        # local coords subtract block bases; shift band accordingly
        lo_l = lo if lo <= -BAND_INF else lo + q0 - k0
        hi_l = hi if hi >= BAND_INF else hi + q0 - k0
        out.append((qs - q0, qe - q0, ks - k0, ke - k0, lo_l, hi_l))
    return np.asarray(out, dtype=np.int64).reshape(-1, 6)


def block_plan(slices: np.ndarray, sq: int, sk: int, bq: int, bk: int):
    """FFA plan for one block pair from clipped ``(n, 6)`` slices."""
    return build_ffa_plan(
        slices[:, 0:2].astype(np.int32),
        slices[:, 2:4].astype(np.int32),
        slices[:, 4].astype(np.int32),
        slices[:, 5].astype(np.int32),
        sq, sk, bq, bk,
    )


def baseline_params(
    plan0, w: int, wt: int, bq: int, bk: int,
    scale: float, hq: int, hk: int,
) -> FFAParams:
    """The FFAParams every baseline shares (softcap-free, env interpret).

    The bwd-tile override flags (MAGI_ATTENTION_FFA_BLOCK_*_D{Q,KV}) are
    deliberately NOT honored here: baselines are fixed comparison targets,
    so their kernel configuration stays pinned to the fwd blocks.
    """
    return FFAParams(
        num_work=w, num_work_t=wt,
        num_q_tiles=plan0.num_q_tiles,
        num_k_tiles=plan0.num_k_tiles,
        block_q=bq, block_k=bk,
        softmax_scale=scale, softcap=0.0, group=hq // hk,
        interpret=_should_interpret(),
    )


def stack_step_plans(plans: list[list]):
    """``plans[step][rank]`` -> (per-step tuples of rank-stacked jnp arrays,
    shared (num_work, num_work_t) caps)."""
    w = max(p.num_work for ps in plans for p in ps)
    wt = max(p.num_work_t for ps in plans for p in ps)
    stacked = []
    for per_rank in plans:
        padded = [pad_plan(p, w, wt) for p in per_rank]
        stacked.append(
            tuple(
                jnp.asarray(np.stack([getattr(p, f) for p in padded]))
                for f in PLAN_FIELDS
            )
        )
    return stacked, w, wt
