"""LoongTrain (2D double-ring) context-parallel baseline.

Ref: exps/dist_attn/baselines/loongtrain.py — decomposes one big KV ring of
size ``O*I`` into a double ring: an inner ring over the ``inner`` (intra-node
on GPU; here first-ICI) axis and an outer ring over the ``outer`` axis. The
inner ring makes ``I-1`` cheap hops per outer round; the outer hop happens
once per round, so the expensive-axis traffic is ``O-1`` hops total instead
of interleaved through every step — the "context-first" placement of the
paper. On TPU both axes ride ICI collectives; the structure still reduces
cross-slice (DCN) hops when the outer axis is mapped onto DCN.

KV visiting rank ``(io, ii)`` at step ``(o, s)`` originates from global block
``((io-o) % O) * I + ((ii-s) % I)``.
"""

from __future__ import annotations

import jax
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..functional.dist_attn import _multi_ffa
from ..kernels.ffa import default_blocks
from ._utils import (
    band_meta,
    baseline_params,
    block_plan,
    clip_to_blocks,
    stack_step_plans,
)


def loongtrain_attn(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_ranges: np.ndarray,
    k_ranges: np.ndarray,
    attn_type_map: np.ndarray,
    mesh: Mesh,
    outer_axis: str = "rp_out",
    inner_axis: str = "rp_in",
    softmax_scale: float | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Sequence-sharded in/out over ``P((outer_axis, inner_axis))``.

    Args:
        q/k/v: ``(S, h, d)`` natural order, dim 0 sharded over both axes
            (rank ``(io, ii)`` owns contiguous block ``io*I + ii``).

    Returns:
        (out ``(S, hq, dv)``, lse ``(S, hq)`` fp32), same sharding.
    """
    O = mesh.shape[outer_axis]
    I = mesh.shape[inner_axis]
    cp = O * I
    S, hq, dh = q.shape
    _, hk, dv = v.shape
    shard = S // cp
    scale = float(dh) ** -0.5 if softmax_scale is None else softmax_scale

    qr, kr, lo, hi = band_meta(q_ranges, k_ranges, attn_type_map)

    bq, bk = default_blocks(shard, shard)
    # plans[o*I+s][global rank b = io*I+ii]
    plans = []
    for o in range(O):
        for s in range(I):
            per_rank = []
            for io in range(O):
                for ii in range(I):
                    src = ((io - o) % O) * I + ((ii - s) % I)
                    b = io * I + ii
                    slices = clip_to_blocks(
                        qr, kr, lo, hi,
                        b * shard, (b + 1) * shard,
                        src * shard, (src + 1) * shard,
                    )
                    per_rank.append(block_plan(slices, shard, shard, bq, bk))
            plans.append(per_rank)
    stacked, w, wt = stack_step_plans(plans)

    params = baseline_params(plans[0][0], w, wt, bq, bk, scale, hq, hk)
    params_list = tuple([params] * cp)
    perm_in = [(i, (i + 1) % I) for i in range(I)]
    perm_out = [(i, (i + 1) % O) for i in range(O)]

    def f(q, k, v, step_arrays):
        ks, vs = [], []
        k_base, v_base = k, v
        for o in range(O):
            if o > 0:
                k_base = jax.lax.ppermute(k_base, outer_axis, perm_out)
                v_base = jax.lax.ppermute(v_base, outer_axis, perm_out)
            k_cur, v_cur = k_base, v_base
            for s in range(I):
                if s > 0:
                    k_cur = jax.lax.ppermute(k_cur, inner_axis, perm_in)
                    v_cur = jax.lax.ppermute(v_cur, inner_axis, perm_in)
                ks.append(k_cur)
                vs.append(v_cur)
        arrays_list = tuple(
            tuple(a[0] for a in step_arrays[t]) for t in range(cp)
        )
        return _multi_ffa(q, tuple(ks), tuple(vs), arrays_list, params_list)[:2]

    spec = P((outer_axis, inner_axis))
    fn = shard_map(
        f, mesh=mesh,
        in_specs=(spec, spec, spec,
                  [tuple(spec for _ in st) for st in stacked]),
        out_specs=(spec, spec),
        check_vma=False,
    )
    return fn(q, k, v, stacked)
