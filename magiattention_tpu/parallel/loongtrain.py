"""LoongTrain (2D-attention + double-ring) context-parallel baseline.

Ref: exps/dist_attn/baselines/loongtrain.py — LoongTrain composes two
mechanisms on a flat world of ``U * O * I`` ranks:

- **2D attention** (head x context): a Ulysses process group of size ``U``
  converts sequence sharding to head sharding with an all_to_all
  (ParallelMode.ULYSESS, ref :1173), and the remaining ``R = O * I`` ranks
  form the context ring (ParallelMode.RING).
- **Double ring**: the context ring is decomposed into inner windows of
  size ``I`` (ParallelMode.INTRA_WINDOW — intra-node on GPU) and an outer
  ring of size ``O`` over windows (INTER_WINDOW): ``I-1`` cheap hops per
  outer round, one expensive hop per round. KV visiting ring rank
  ``(io, ii)`` at step ``(o, s)`` originates from ring rank
  ``((io-o) % O) * I + ((ii-s) % I)`` (ref :148 window_offset).
- **Zigzag sharding** on the ring dim (shard.py zigzag_dispatch): ring
  rank r owns chunks ``r`` and ``2R-1-r`` of ``2R``, so causal masks
  load-balance; the reference's per-step half-chunk specializations
  ("q, k0, v0" branches, ref :1216-1228) fall out of the band-slice plan
  clipping for free — empty chunk pairs produce no work items.

**Head-first vs context-first placement** (the paper's two process-group
constructions) is which logical role varies fastest over the flat device
order; on TPU that is the *mesh construction*, not the attention code —
use :func:`make_loongtrain_mesh`.

TPU redesign notes: process groups -> mesh axes; P2P send/recv ->
``jax.lax.ppermute``; the double-buffered comm/compute overlap ->
XLA async collective scheduling; backward -> AD through the multi-part
merged VJP (functional/dist_attn._multi_ffa).
"""

from __future__ import annotations

import jax
import numpy as np
from ..utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..functional.dist_attn import _multi_ffa
from ..kernels.ffa import default_blocks
from ._utils import (
    band_meta,
    baseline_params,
    block_plan,
    clip_to_blocks,
    stack_step_plans,
    zigzag_ring_step_plans,
)


def make_loongtrain_mesh(
    devices,
    ulysses: int,
    outer: int,
    inner: int,
    placement: str = "head_first",
) -> Mesh:
    """Build the LoongTrain mesh with the requested rank placement.

    head_first (ref default): the Ulysses group takes adjacent ranks
    (fastest-varying) — head a2a rides the cheapest links; the inner ring
    is next. context_first: the inner-window ring takes adjacent ranks —
    ring hops ride the cheapest links. Axis names are always
    ("rp_out", "rp_in", "sp") roles regardless of placement.
    """
    devs = np.asarray(devices).reshape(-1)[: ulysses * outer * inner]
    if placement == "head_first":
        arr = devs.reshape(outer, inner, ulysses)
        return Mesh(arr, axis_names=("rp_out", "rp_in", "sp"))
    if placement == "context_first":
        arr = devs.reshape(ulysses, outer, inner).transpose(1, 2, 0)
        return Mesh(arr, axis_names=("rp_out", "rp_in", "sp"))
    raise ValueError(f"unknown placement: {placement!r}")


def loongtrain_attn(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_ranges: np.ndarray,
    k_ranges: np.ndarray,
    attn_type_map: np.ndarray,
    mesh: Mesh,
    outer_axis: str = "rp_out",
    inner_axis: str = "rp_in",
    ulysses_axis: str | None = None,
    softmax_scale: float | None = None,
    sharding: str = "zigzag",
) -> tuple[jax.Array, jax.Array]:
    """Sequence-sharded in/out over the (outer, inner[, ulysses]) axes.

    Args:
        q/k/v: ``(S, h, d)``, dim 0 sharded over all given axes; in
            :func:`..ring.ring_dispatch` layout over the ``R = O*I`` ring
            ranks when ``sharding='zigzag'`` (ring rank ``io*I + ii`` owns
            zigzag chunks ``r`` and ``2R-1-r``).
        ulysses_axis: when set, 2D attention — heads split over this axis
            with an a2a, so only ``hq % U == 0`` is required (not the full
            world size).

    Returns:
        (out ``(S, hq, dv)``, lse ``(S, hq)`` fp32), same sharding.
    """
    O = mesh.shape[outer_axis]
    I = mesh.shape[inner_axis]
    U = mesh.shape[ulysses_axis] if ulysses_axis else 1
    R = O * I
    S, hq, dh = q.shape
    _, hk, dv = v.shape
    if ulysses_axis and (hq % U or hk % U):
        raise ValueError(
            f"loongtrain 2D attention needs heads divisible by the "
            f"ulysses size ({hq},{hk},{U})"
        )
    shard = S // R
    scale = float(dh) ** -0.5 if softmax_scale is None else softmax_scale

    qr, kr, lo, hi = band_meta(q_ranges, k_ranges, attn_type_map)

    bq, bk = default_blocks(shard, shard)

    def src_of(b: int, t: int) -> int:
        io, ii = divmod(b, I)
        o, s = divmod(t, I)
        return ((io - o) % O) * I + ((ii - s) % I)

    if sharding == "zigzag":
        plans = zigzag_ring_step_plans(
            qr, kr, lo, hi, shard, R, bq, bk, ring_rank_of=src_of
        )
    elif sharding == "contig":
        plans = []
        for t in range(R):
            per_rank = []
            for b in range(R):
                src = src_of(b, t)
                slices = clip_to_blocks(
                    qr, kr, lo, hi,
                    b * shard, (b + 1) * shard,
                    src * shard, (src + 1) * shard,
                )
                per_rank.append(block_plan(slices, shard, shard, bq, bk))
            plans.append(per_rank)
    else:
        raise ValueError(f"unknown loongtrain sharding: {sharding!r}")
    stacked, w, wt = stack_step_plans(plans)

    params = baseline_params(plans[0][0], w, wt, bq, bk, scale, hq, hk)
    params_list = tuple([params] * R)
    perm_in = [(i, (i + 1) % I) for i in range(I)]
    perm_out = [(i, (i + 1) % O) for i in range(O)]

    def a2a(x, split_axis, concat_axis):
        return jax.lax.all_to_all(
            x, ulysses_axis, split_axis=split_axis,
            concat_axis=concat_axis, tiled=True,
        )

    def f(q, k, v, step_arrays):
        if ulysses_axis:
            # 2D attention: seq shard -> head shard within the ring block
            q, k, v = (a2a(t, 1, 0) for t in (q, k, v))
        ks, vs = [], []
        k_base, v_base = k, v
        for o in range(O):
            if o > 0:
                k_base = jax.lax.ppermute(k_base, outer_axis, perm_out)
                v_base = jax.lax.ppermute(v_base, outer_axis, perm_out)
            k_cur, v_cur = k_base, v_base
            for s in range(I):
                if s > 0:
                    k_cur = jax.lax.ppermute(k_cur, inner_axis, perm_in)
                    v_cur = jax.lax.ppermute(v_cur, inner_axis, perm_in)
                ks.append(k_cur)
                vs.append(v_cur)
        arrays_list = tuple(
            tuple(a[0] for a in step_arrays[t]) for t in range(R)
        )
        out, lse, _ = _multi_ffa(q, tuple(ks), tuple(vs), arrays_list,
                                 params_list)
        if ulysses_axis:
            out = a2a(out, 0, 1)
            lse = a2a(lse[..., None], 0, 1)[..., 0]
        return out, lse

    data_axes = (
        (outer_axis, inner_axis, ulysses_axis)
        if ulysses_axis else (outer_axis, inner_axis)
    )
    spec = P(data_axes)
    ring_spec = P((outer_axis, inner_axis))
    fn = shard_map(
        f, mesh=mesh,
        in_specs=(spec, spec, spec,
                  [tuple(ring_spec for _ in st) for st in stacked]),
        out_specs=(spec, spec),
        check_vma=False,
    )
    return fn(q, k, v, stacked)
