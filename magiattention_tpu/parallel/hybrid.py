"""Megatron HybridCP and Ring-AllGather context-parallel baselines.

Ref: exps/dist_attn/baselines/hybrid_dcp.py (hybrid) and the allgather
variants in ring_attn.py — two KV-replication strategies:

- ``allgather_attn``: every rank all-gathers the full K/V over the cp axis
  and computes its q block against the global sequence with clipped global
  metadata. One collective, maximal memory — the "Ring AllGather" baseline.
- ``hybrid_cp_attn``: 2-level. K/V is all-gathered over the *intra* axis
  (cheap, high-bandwidth ICI), forming one super-block per intra group; the
  super-blocks then ring over the *inter* axis (ppermute), so the expensive
  axis carries ring traffic while the cheap axis pays one gather.
"""

from __future__ import annotations

import jax
import numpy as np
from ..utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..functional.dist_attn import _multi_ffa
from ..kernels.ffa import default_blocks
from ._utils import (
    band_meta,
    baseline_params,
    block_plan,
    clip_to_blocks,
    stack_step_plans,
)


def allgather_attn(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_ranges: np.ndarray,
    k_ranges: np.ndarray,
    attn_type_map: np.ndarray,
    mesh: Mesh,
    cp_axis: str = "cp",
    softmax_scale: float | None = None,
) -> tuple[jax.Array, jax.Array]:
    """All-gather-KV attention: seq-sharded in/out over ``P(cp_axis)``."""
    cp = mesh.shape[cp_axis]
    S, hq, dh = q.shape
    _, hk, dv = v.shape
    shard = S // cp
    scale = float(dh) ** -0.5 if softmax_scale is None else softmax_scale
    qr, kr, lo, hi = band_meta(q_ranges, k_ranges, attn_type_map)

    bq, bk = default_blocks(shard, S)
    per_rank = [
        block_plan(
            clip_to_blocks(qr, kr, lo, hi, r * shard, (r + 1) * shard, 0, S),
            shard, S, bq, bk,
        )
        for r in range(cp)
    ]
    stacked, w, wt = stack_step_plans([per_rank])

    params = baseline_params(per_rank[0], w, wt, bq, bk, scale, hq, hk)

    def f(q, k, v, arrays):
        k_all = jax.lax.all_gather(k, cp_axis, axis=0, tiled=True)
        v_all = jax.lax.all_gather(v, cp_axis, axis=0, tiled=True)
        local = tuple(a[0] for a in arrays[0])
        return _multi_ffa(q, (k_all,), (v_all,), (local,), (params,))[:2]

    spec = P(cp_axis)
    fn = shard_map(
        f, mesh=mesh,
        in_specs=(spec, spec, spec, [tuple(spec for _ in st) for st in stacked]),
        out_specs=(spec, spec),
        check_vma=False,
    )
    return fn(q, k, v, stacked)


def hybrid_cp_attn(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_ranges: np.ndarray,
    k_ranges: np.ndarray,
    attn_type_map: np.ndarray,
    mesh: Mesh,
    inter_axis: str = "cp_inter",
    intra_axis: str = "cp_intra",
    softmax_scale: float | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Hybrid 2-level CP: all-gather KV intra, ring inter.

    q/k/v: ``(S, h, d)``, dim 0 sharded ``P((inter_axis, intra_axis))`` —
    rank ``(io, ii)`` owns contiguous block ``io*I + ii``; the intra group of
    ``io`` jointly owns super-block ``[io*S/O, (io+1)*S/O)``.
    """
    O = mesh.shape[inter_axis]
    I = mesh.shape[intra_axis]
    cp = O * I
    S, hq, dh = q.shape
    _, hk, dv = v.shape
    shard = S // cp
    super_blk = S // O
    scale = float(dh) ** -0.5 if softmax_scale is None else softmax_scale
    qr, kr, lo, hi = band_meta(q_ranges, k_ranges, attn_type_map)

    bq, bk = default_blocks(shard, super_blk)
    # plans[o][global rank b]: q block b vs super-block of inter rank (io-o)%O
    plans = []
    for o in range(O):
        per_rank = []
        for io in range(O):
            for ii in range(I):
                b = io * I + ii
                src = (io - o) % O
                slices = clip_to_blocks(
                    qr, kr, lo, hi,
                    b * shard, (b + 1) * shard,
                    src * super_blk, (src + 1) * super_blk,
                )
                per_rank.append(block_plan(slices, shard, super_blk, bq, bk))
        plans.append(per_rank)
    stacked, w, wt = stack_step_plans(plans)

    params = baseline_params(plans[0][0], w, wt, bq, bk, scale, hq, hk)
    params_list = tuple([params] * O)
    perm_out = [(i, (i + 1) % O) for i in range(O)]

    def f(q, k, v, step_arrays):
        k_g = jax.lax.all_gather(k, intra_axis, axis=0, tiled=True)
        v_g = jax.lax.all_gather(v, intra_axis, axis=0, tiled=True)
        ks, vs = [k_g], [v_g]
        for _ in range(1, O):
            ks.append(jax.lax.ppermute(ks[-1], inter_axis, perm_out))
            vs.append(jax.lax.ppermute(vs[-1], inter_axis, perm_out))
        arrays_list = tuple(
            tuple(a[0] for a in step_arrays[o]) for o in range(O)
        )
        return _multi_ffa(q, tuple(ks), tuple(vs), arrays_list, params_list)[:2]

    spec = P((inter_axis, intra_axis))
    fn = shard_map(
        f, mesh=mesh,
        in_specs=(spec, spec, spec,
                  [tuple(spec for _ in st) for st in stacked]),
        out_specs=(spec, spec),
        check_vma=False,
    )
    return fn(q, k, v, stacked)
