"""NSA (native sparse attention) CP baselines.

Ref: exps/dist_attn/baselines/nsa.py (VarlenNSA) and usp_nsa.py
(USPAllGatherNSA). Three branches per query, mixed by a learned sigmoid
gate:

  cmp — attention over MLP-compressed KV blocks (length ``l_cmp``,
        stride ``d``), dense softmax per varlen segment;
  slc — attention over the ``slc_top_k`` *selected* KV blocks (length
        ``l_slc``), chosen per (kv-head, q-block) from the compressed
        scores (summed over GQA heads and q-block rows, ref
        compute_gqa_p_slc / compute_blockq_p_slc);
  win — sliding-window attention per segment.

TPU-first re-design: all block bookkeeping (block starts, segment masks,
the cmp->slc aggregation matrix) is static host metadata derived from
``cu_seqlens``, so the whole forward is one fused XLA program — top-k is
the only data-dependent op and its indices are block-granular (q-block x
kv-head), keeping gathers MXU-friendly. The distributed variant follows the
reference's all-gather design (usp_nsa.py:747 USPAllGatherNSA): ulysses
all_to_all head-shards, the ring axis all-gathers KV — a ring P2P loop
would fight XLA's static shapes for no bandwidth win on ICI.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from ..utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .. import telemetry
from ..kernels.block_sparse import block_sparse_attn, modeled_slc_bytes
from ..kernels.ffa import ffa_attn
from ..kernels.mask_utils import BAND_INF

NEG_INF = float("-inf")


def init_nsa_params(
    key: jax.Array, head_dim: int, l_cmp: int, dtype=jnp.float32
) -> dict:
    """Learned parameters: block compressors (ref cmp_linear_k/v) and the
    3-way branch gate (ref gate_proj)."""
    k1, k2, k3 = jax.random.split(key, 3)
    s = l_cmp ** -0.5
    return {
        "w_cmp_k": jax.random.uniform(k1, (l_cmp,), dtype, -s, s),
        "b_cmp_k": jnp.zeros((), dtype),
        "w_cmp_v": jax.random.uniform(k2, (l_cmp,), dtype, -s, s),
        "b_cmp_v": jnp.zeros((), dtype),
        "w_gate": jax.random.uniform(
            k3, (head_dim, 3), dtype, -(head_dim ** -0.5), head_dim ** -0.5
        ),
        "b_gate": jnp.zeros((3,), dtype),
    }


def _block_layout(cu_seqlens: list[int], l: int, d: int):
    """Per-segment stride-d window starts (host). Returns (starts (n,),
    seg_id (n,), counts per segment)."""
    starts, seg_ids, counts = [], [], []
    for s in range(len(cu_seqlens) - 1):
        a, b = cu_seqlens[s], cu_seqlens[s + 1]
        n = max(0, (b - a - l) // d + 1)
        counts.append(n)
        for j in range(n):
            starts.append(a + j * d)
            seg_ids.append(s)
    return (
        np.asarray(starts, dtype=np.int32),
        np.asarray(seg_ids, dtype=np.int32),
        counts,
    )


def _p_slc_matrix(
    counts_cmp: list[int], counts_slc: list[int], l_slc: int, l_cmp: int,
    d: int,
) -> np.ndarray:
    """(n_cmp_total, n_slc_total) aggregation weights: P_slc = P_cmp @ M.

    BOTH block families come from :func:`_block_layout`, i.e. both are
    anchored at stride ``d``: cmp block i covers d-chunks ``[i, i + beta)``
    and slc block j covers ``[j, j + alpha)`` (alpha = l_slc/d, beta =
    l_cmp/d). The weight is their chunk-overlap count — the number of
    stride-d chunks the two windows share:

        M[i, j] = max(0, min(i + beta, j + alpha) - max(i, j))

    a small-integer count, exact in f32. At alpha == beta == 1 this is the
    identity, matching the ``p_slc = p_cmp`` shortcut in :func:`nsa_attn`.

    (An earlier revision anchored slc blocks at stride ``l_slc`` — the
    non-overlapping layout of the reference ``compute_p_slc`` — while
    ``_block_layout`` emits stride-``d`` windows; for l_slc=2d, l_cmp=d
    that scored slc block j from cmp blocks {2j-1, 2j} instead of the
    overlapping {j, j+1}, so top-k selected windows that missed the very
    keys that scored them. The misaligned-stride parity test pins this.)
    """
    alpha, beta = l_slc // d, l_cmp // d
    n_cmp, n_slc = sum(counts_cmp), sum(counts_slc)
    M = np.zeros((n_cmp, n_slc), dtype=np.float32)
    co = so = 0
    for nc, ns in zip(counts_cmp, counts_slc):
        t = np.arange(nc)[:, None] - np.arange(ns)[None, :]  # i - j
        cnt = np.minimum(alpha, t + beta) - np.maximum(0, t)
        M[co:co + nc, so:so + ns] = np.maximum(cnt, 0).astype(np.float32)
        co += nc
        so += ns
    return M


def nsa_attn(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    params: dict,
    cu_seqlens: list[int],
    *,
    l_cmp: int = 32,
    l_slc: int = 64,
    d_stride: int = 32,
    block_size_q: int = 16,
    slc_top_k: int = 2,
    window: tuple[int, int] = (128, 0),
    causal: bool = True,
    softmax_scale: float | None = None,
) -> jax.Array:
    """Single-device NSA forward (``(S, h, dh)`` packed varlen layout).

    cu_seqlens / block geometry are static host metadata; every segment
    must satisfy ``len >= l_slc``, ``block_size_q | len``, ``d | start``,
    and hold at least ``slc_top_k`` selection blocks (ref asserts the same).
    """
    S, hq, dh = q.shape
    _, hk, _ = k.shape
    g = hq // hk
    scale = dh ** -0.5 if softmax_scale is None else softmax_scale
    cu = list(cu_seqlens)
    assert cu[0] == 0 and cu[-1] == S

    # ---- static layout ---------------------------------------------------
    cmp_starts, cmp_seg, cmp_counts = _block_layout(cu, l_cmp, d_stride)
    slc_starts, slc_seg, slc_counts = _block_layout(cu, l_slc, d_stride)
    n_cmp, n_slc = len(cmp_starts), len(slc_starts)
    assert min(slc_counts) >= slc_top_k, (
        f"every segment needs >= slc_top_k={slc_top_k} blocks"
    )
    row_seg = np.zeros(S, dtype=np.int32)
    for s in range(len(cu) - 1):
        row_seg[cu[s]: cu[s + 1]] = s
        assert (cu[s + 1] - cu[s]) % block_size_q == 0
    n_qb = S // block_size_q
    qb_seg = row_seg.reshape(n_qb, block_size_q)[:, 0]

    # ---- compressed KV ---------------------------------------------------
    def blocks_of(x, starts, l):  # (S, h, dh) -> (n, l, h, dh)
        idx = starts[:, None] + np.arange(l)[None, :]
        return jnp.take(x, jnp.asarray(idx.reshape(-1)), axis=0).reshape(
            len(starts), l, *x.shape[1:]
        )

    k_cmp_blk = blocks_of(k, cmp_starts, l_cmp)  # (n_cmp, l, hk, dh)
    v_cmp_blk = blocks_of(v, cmp_starts, l_cmp)
    k_cmp = (
        jnp.einsum("nlhd,l->nhd", k_cmp_blk, params["w_cmp_k"])
        + params["b_cmp_k"]
    )
    v_cmp = (
        jnp.einsum("nlhd,l->nhd", v_cmp_blk, params["w_cmp_v"])
        + params["b_cmp_v"]
    )

    # ---- cmp branch (dense per-segment softmax, ref :119-126) ------------
    seg_mask = jnp.asarray(row_seg[:, None] == cmp_seg[None, :])  # (S, n_cmp)
    # GQA: each q head attends its kv head's compressed blocks
    qg = q.reshape(S, hk, g, dh)
    logits = jnp.einsum("shgd,nhd->shgn", qg, k_cmp).astype(jnp.float32) * scale
    logits = jnp.where(seg_mask[:, None, None, :], logits, NEG_INF)
    p_cmp = jax.nn.softmax(logits, axis=-1)  # (S, hk, g, n_cmp)
    out_cmp = jnp.einsum(
        "shgn,nhd->shgd", p_cmp.astype(q.dtype), v_cmp
    ).reshape(S, hq, dh)

    # ---- selection scores (ref compute_p_slc/gqa/blockq) -----------------
    if l_slc == l_cmp == d_stride:
        p_slc = p_cmp  # (S, hk, g, n_slc)
    else:
        M = jnp.asarray(_p_slc_matrix(cmp_counts, slc_counts, l_slc, l_cmp,
                                      d_stride))
        p_slc = jnp.einsum("shgn,nm->shgm", p_cmp, M)
    # sum over GQA heads and q-block rows -> (hk, n_qb, n_slc)
    score = p_slc.sum(axis=2).reshape(n_qb, block_size_q, hk, n_slc).sum(1)
    score = score.transpose(1, 0, 2)  # (hk, n_qb, n_slc)
    qb_mask = jnp.asarray(qb_seg[:, None] == slc_seg[None, :])
    score = jnp.where(qb_mask[None], score, NEG_INF)
    _, idx = jax.lax.top_k(score, slc_top_k)  # (hk, n_qb, K)

    # ---- slc branch: registry decision — gather-free block-sparse kernel
    # (kernels/block_sparse.py streams the selected blocks through the
    # prefetched index table) vs the gathered-dense reference ---------------
    slc_feasible = (
        S % d_stride == 0
        and l_slc % d_stride == 0
        and (d_stride <= 128 or d_stride % 128 == 0)
        and not (slc_starts % d_stride).any()
    )
    if slc_feasible:
        from ..kernels import registry as _registry

        slc_backend = _registry.nsa_slc_backend(
            key=(hk, g, n_qb, slc_top_k, l_slc, d_stride)
        )
    else:
        slc_backend = "gathered_dense"
    if slc_backend == "block_sparse_pallas":
        out_slc, _ = block_sparse_attn(
            q, k, v, idx, slc_starts,
            block_len=l_slc, d_stride=d_stride,
            block_size_q=block_size_q, softmax_scale=scale,
        )
    else:
        # gathered-dense reference: materialize the top-k blocks, dense
        # softmax over the concatenated selection
        k_slc_blk = (
            k_cmp_blk if l_slc == l_cmp else blocks_of(k, slc_starts, l_slc)
        )  # (n_slc, l, hk, dh)
        v_slc_blk = (
            v_cmp_blk if l_slc == l_cmp else blocks_of(v, slc_starts, l_slc)
        )
        # (hk, n_qb, K, l, dh)
        k_sel = jnp.take_along_axis(
            k_slc_blk.transpose(2, 0, 1, 3)[:, None],  # (hk, 1, n_slc, l, dh)
            idx[..., None, None],
            axis=2,
        )
        v_sel = jnp.take_along_axis(
            v_slc_blk.transpose(2, 0, 1, 3)[:, None], idx[..., None, None],
            axis=2,
        )
        L = slc_top_k * k_sel.shape[-2]
        k_sel = k_sel.reshape(hk, n_qb, L, dh)
        v_sel = v_sel.reshape(hk, n_qb, L, dh)
        qb = q.reshape(n_qb, block_size_q, hk, g, dh)
        s_logits = (
            jnp.einsum("bqhgd,hbld->hbgql", qb, k_sel).astype(jnp.float32)
            * scale
        )
        p_s = jax.nn.softmax(s_logits, axis=-1)
        out_slc = (
            jnp.einsum("hbgql,hbld->bqhgd", p_s.astype(q.dtype), v_sel)
            .reshape(S, hq, dh)
        )
    if telemetry.enabled():
        slc_bytes = modeled_slc_bytes(
            hk=hk, n_qb=n_qb, top_k=slc_top_k, block_len=l_slc,
            d_stride=d_stride, block_size_q=block_size_q, g=g, d=dh,
            dv=dh, itemsize=q.dtype.itemsize,
        )
        telemetry.record_event(
            "nsa_step",
            slc_backend=slc_backend,
            top_k=slc_top_k,
            hk=hk,
            n_qb=n_qb,
            l_slc=l_slc,
            d_stride=d_stride,
            executed_bytes=slc_bytes["streamed_bytes"],
            gathered_bytes=slc_bytes["gathered_bytes"],
        )

    # ---- win branch: banded FFA per segment (ref flash varlen + window) --
    wl, wr = window
    d_hi = 0 if causal else (wr if wr >= 0 else BAND_INF)
    d_lo = -wl if wl >= 0 else -BAND_INF
    qr = np.array([[cu[s], cu[s + 1]] for s in range(len(cu) - 1)], np.int32)
    out_win, _ = ffa_attn(
        q, k, v, qr, qr.copy(), None,
        softmax_scale=scale,
        d_lo=np.full(len(qr), d_lo, np.int32),
        d_hi=np.full(len(qr), d_hi, np.int32),
    )

    # ---- gate mix (ref gate_proj + sigmoid) ------------------------------
    gate = jax.nn.sigmoid(
        jnp.einsum("shd,dc->shc", q.astype(jnp.float32),
                   params["w_gate"].astype(jnp.float32))
        + params["b_gate"]
    ).astype(q.dtype)
    out = (
        gate[..., 0:1] * out_cmp
        + gate[..., 1:2] * out_slc
        + gate[..., 2:3] * out_win
    )
    return out


def usp_nsa_attn(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    params: dict,
    cu_seqlens: list[int],
    mesh: Mesh,
    ring_axis: str = "rp",
    ulysses_axis: str = "sp",
    **nsa_kwargs,
) -> jax.Array:
    """USP-sharded NSA (ref usp_nsa.py:747 USPAllGatherNSA).

    q/k/v: ``(S, h, dh)`` natural order, dim 0 sharded P((ring, ulysses)).
    ulysses a2a -> head sharding; ring all-gather -> full sequence; each
    rank computes NSA for its head subset on its ring block's queries.
    """
    R = mesh.shape[ring_axis]
    U = mesh.shape[ulysses_axis]
    S, hq, dh = q.shape
    _, hk, _ = k.shape
    if hq % U or hk % U:
        raise ValueError(f"usp_nsa needs heads divisible by U ({hq},{hk},{U})")
    blk = S // R

    # head-subset params are identical on every rank; the gate/compressors
    # act per-head-dim so no parameter sharding is needed
    def f(q, k, v):
        # (S/(R*U), h) -> (S/R, h/U)
        qa = jax.lax.all_to_all(q, ulysses_axis, 1, 0, tiled=True)
        ka = jax.lax.all_to_all(k, ulysses_axis, 1, 0, tiled=True)
        va = jax.lax.all_to_all(v, ulysses_axis, 1, 0, tiled=True)
        # full sequence for the head subset
        qf = jax.lax.all_gather(qa, ring_axis, axis=0, tiled=True)
        kf = jax.lax.all_gather(ka, ring_axis, axis=0, tiled=True)
        vf = jax.lax.all_gather(va, ring_axis, axis=0, tiled=True)
        out_f = nsa_attn(qf, kf, vf, params, cu_seqlens, **nsa_kwargs)
        r = jax.lax.axis_index(ring_axis)
        out_blk = jax.lax.dynamic_slice_in_dim(out_f, r * blk, blk, axis=0)
        return jax.lax.all_to_all(out_blk, ulysses_axis, 0, 1, tiled=True)

    spec = P((ring_axis, ulysses_axis))
    return shard_map(
        f, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
