"""Model layer: reference integrations live in examples/ for the reference
(Llama-3 + FSDP/Megatron/Transformers, ref examples/); here the flagship
models are JAX-native with CP attention built in — a Llama decoder and a
Magi-1-style video diffusion transformer (the reference's headline
workload, ref README.md:54-56)."""

from .llama import LlamaConfig, forward, init_params, train_step  # noqa: F401
from . import video_dit  # noqa: F401
from .moe import (  # noqa: F401
    MoEConfig,
    init_moe_params,
    moe_forward,
    moe_train_step,
    shard_moe_params,
)
