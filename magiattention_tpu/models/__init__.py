"""Model layer: reference integrations live in examples/ for the reference
(Llama-3 + FSDP/Megatron/Transformers, ref examples/); here the flagship
model is a JAX-native Llama with CP attention built in."""

from .llama import LlamaConfig, forward, init_params, train_step  # noqa: F401
