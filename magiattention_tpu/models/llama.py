"""Llama-style transformer with context-parallel flex attention.

The TPU-native counterpart of the reference's examples/torch_native Llama-3
integration (ref examples/torch_native/README.md:75-90 — FSDP2 over a dp_cp
mesh): a packed-varlen (no batch dim) decoder where attention runs through
``magi_attn_flex_key -> dispatch -> calc_attn`` and every non-attention op is
row-wise or a matmul, so the whole network computes directly on the
dispatched (chunk-permuted, cp-sharded) layout. RoPE uses the dispatched
global position ids. Parameters are ZeRO-3-style sharded over the cp axis
(the FSDP equivalent), gathered on demand by XLA.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..api import calc_attn, dispatch, get_position_ids
from ..dist_attn_runtime_mgr import DistAttnRuntimeKey


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 4
    head_dim: int = 64
    ffn_hidden: int = 1408
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    # rematerialize each layer in backward (jax.checkpoint) — trades FLOPs
    # for activation memory, the standard long-context training setting
    remat: bool = False

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)


def init_params(cfg: LlamaConfig, key: jax.Array) -> dict:
    """Random-init parameter pytree (fp32 master weights)."""
    ks = jax.random.split(key, 2 + cfg.n_layers)
    dim, dh = cfg.dim, cfg.head_dim
    hq, hk = cfg.n_heads, cfg.n_kv_heads

    def dense(k, shape):
        return jax.random.normal(k, shape, dtype=jnp.float32) * (
            shape[0] ** -0.5
        )

    layers = []
    for i in range(cfg.n_layers):
        lk = jax.random.split(ks[2 + i], 7)
        layers.append(
            {
                "attn_norm": jnp.ones((dim,), jnp.float32),
                "wq": dense(lk[0], (dim, hq * dh)),
                "wk": dense(lk[1], (dim, hk * dh)),
                "wv": dense(lk[2], (dim, hk * dh)),
                "wo": dense(lk[3], (hq * dh, dim)),
                "mlp_norm": jnp.ones((dim,), jnp.float32),
                "w_gate": dense(lk[4], (dim, cfg.ffn_hidden)),
                "w_up": dense(lk[5], (dim, cfg.ffn_hidden)),
                "w_down": dense(lk[6], (cfg.ffn_hidden, dim)),
            }
        )
    return {
        "embed": dense(ks[0], (cfg.vocab_size, dim)),
        "final_norm": jnp.ones((dim,), jnp.float32),
        "lm_head": dense(ks[1], (dim, cfg.vocab_size)),
        "layers": layers,
    }


def shard_params(
    params: dict, mesh: Mesh, axis: str = "cp", tp_axis: str | None = None
) -> dict:
    """ZeRO-3-style first-dim sharding over the dp/cp axis; with ``tp_axis``
    the attention/MLP projections additionally Megatron-shard their
    column/row dims over TP (wq/wk/wv/w_gate/w_up column-parallel, wo/w_down
    row-parallel)."""
    tp = mesh.shape[tp_axis] if tp_axis else 1

    def s2(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    def s(path, x):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        dp_ok = x.ndim >= 2 and x.shape[0] % mesh.shape[axis] == 0
        d0 = axis if dp_ok else None
        if tp_axis and x.ndim == 2:
            if name in ("wq", "wk", "wv", "w_gate", "w_up") and x.shape[1] % tp == 0:
                return s2(x, P(d0, tp_axis))
            if name in ("wo", "w_down") and x.shape[0] % (mesh.shape[axis] * tp if dp_ok else tp) == 0:
                # row-parallel: input dim over tp (stacked with dp when legal)
                return s2(x, P((axis, tp_axis) if dp_ok else tp_axis, None))
        if dp_ok:
            return s2(x, P(axis, *([None] * (x.ndim - 1))))
        return s2(x, P())

    return jax.tree_util.tree_map_with_path(s, params)


def _rms_norm(x, w, eps):
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * scale).astype(x.dtype) * w.astype(x.dtype)


def _rope(x, pos, theta):
    """x: (S, h, dh); pos: (S,) global positions."""
    s, h, dh = x.shape
    half = dh // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = pos.astype(jnp.float32)[:, None] * freqs[None, :]  # (S, half)
    cos, sin = jnp.cos(ang)[:, None, :], jnp.sin(ang)[:, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    x32_1, x32_2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [x32_1 * cos - x32_2 * sin, x32_1 * sin + x32_2 * cos], axis=-1
    ).astype(x.dtype)


def attn_block(x, lyr, cfg, pos, attn_key):
    """Pre-norm attention sub-block on the dispatched layout (shared by the
    Llama and MoE families — ONE source of truth for qkv/rope/CP-attn/wo)."""
    dt = x.dtype
    h = _rms_norm(x, lyr["attn_norm"], cfg.norm_eps)
    q = (h @ lyr["wq"].astype(dt)).reshape(-1, cfg.n_heads, cfg.head_dim)
    k = (h @ lyr["wk"].astype(dt)).reshape(-1, cfg.n_kv_heads, cfg.head_dim)
    v = (h @ lyr["wv"].astype(dt)).reshape(-1, cfg.n_kv_heads, cfg.head_dim)
    q = _rope(q, pos, cfg.rope_theta)
    k = _rope(k, pos, cfg.rope_theta)
    attn_out, _ = calc_attn(q, k, v, attn_key)
    attn_out = attn_out.reshape(-1, cfg.n_heads * cfg.head_dim)
    return x + attn_out @ lyr["wo"].astype(dt)


def masked_ce(logits, labels):
    """Mean cross entropy over positions with ``labels >= 0`` (ignored
    positions clamped before the gather so no wrapped index is read)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(
        logp, jnp.maximum(labels, 0)[:, None], axis=-1
    )[:, 0]
    valid = labels >= 0
    return jnp.sum(jnp.where(valid, nll, 0.0)) / jnp.maximum(
        jnp.sum(valid), 1
    )


def forward(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,
    attn_key: DistAttnRuntimeKey,
) -> jax.Array:
    """Forward pass on the dispatched layout.

    Args:
        tokens: ``(total_seqlen,)`` int32, natural order.

    Returns:
        logits ``(total_seqlen, vocab)`` in DISPATCHED order (use
        ``undispatch`` for natural order; the training loss dispatches labels
        instead, which is cheaper).
    """
    dt = cfg.jdtype
    x = jnp.take(params["embed"], tokens, axis=0).astype(dt)  # (S, dim)
    x = dispatch(x, attn_key)
    pos = get_position_ids(attn_key)

    def layer(x, lyr):
        x = attn_block(x, lyr, cfg, pos, attn_key)
        h = _rms_norm(x, lyr["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(h @ lyr["w_gate"].astype(dt))
        up = h @ lyr["w_up"].astype(dt)
        return x + (gate * up) @ lyr["w_down"].astype(dt)

    if cfg.remat:
        layer = jax.checkpoint(layer)

    for lyr in params["layers"]:
        x = layer(x, lyr)

    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    return (x @ params["lm_head"].astype(dt)).astype(jnp.float32)


def loss_fn(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,
    labels: jax.Array,
    attn_key: DistAttnRuntimeKey,
) -> jax.Array:
    """Next-token cross entropy, computed on the dispatched layout (labels
    are dispatched with the same permutation — cheaper than undispatching
    the logits)."""
    logits = forward(params, cfg, tokens, attn_key)
    labels_d = dispatch(labels, attn_key)
    return masked_ce(logits, labels_d)


@partial(jax.jit, static_argnums=(1, 4), donate_argnums=(0,))
def train_step(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,
    labels: jax.Array,
    attn_key: DistAttnRuntimeKey,
    lr: float = 1e-4,
) -> tuple[dict, jax.Array]:
    """One SGD step (the examples pair this with optax in practice)."""
    loss, grads = jax.value_and_grad(loss_fn)(
        params, cfg, tokens, labels, attn_key
    )
    params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype), params, grads)
    return params, loss


# ---------------------------------------------------------------------------
# dense (non-CP) twin + optax integration — the convergence-parity artifact
# (ref examples/torch_native convergence evidence; VERDICT r1 item 10)
# ---------------------------------------------------------------------------


def forward_dense(
    params: dict, cfg: LlamaConfig, tokens: jax.Array, mask: jax.Array
) -> jax.Array:
    """Same network, replicated dense attention over an explicit boolean
    mask — the single-device twin used to check CP convergence parity."""
    dt = cfg.jdtype
    s = tokens.shape[0]
    x = jnp.take(params["embed"], tokens, axis=0).astype(dt)
    pos = jnp.arange(s, dtype=jnp.int32)

    for lyr in params["layers"]:
        h = _rms_norm(x, lyr["attn_norm"], cfg.norm_eps)
        q = (h @ lyr["wq"].astype(dt)).reshape(-1, cfg.n_heads, cfg.head_dim)
        k = (h @ lyr["wk"].astype(dt)).reshape(-1, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ lyr["wv"].astype(dt)).reshape(-1, cfg.n_kv_heads, cfg.head_dim)
        q = _rope(q, pos, cfg.rope_theta)
        k = _rope(k, pos, cfg.rope_theta)
        g = cfg.n_heads // cfg.n_kv_heads
        kf = jnp.repeat(k, g, axis=1)
        vf = jnp.repeat(v, g, axis=1)
        logits = jnp.einsum(
            "shd,thd->hst", q.astype(jnp.float32), kf.astype(jnp.float32)
        ) * (cfg.head_dim ** -0.5)
        logits = jnp.where(mask[None], logits, -jnp.inf)
        p = jax.nn.softmax(logits, axis=-1)
        attn_out = jnp.einsum("hst,thd->shd", p, vf.astype(jnp.float32))
        attn_out = attn_out.astype(dt).reshape(-1, cfg.n_heads * cfg.head_dim)
        x = x + attn_out @ lyr["wo"].astype(dt)

        h = _rms_norm(x, lyr["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(h @ lyr["w_gate"].astype(dt))
        up = h @ lyr["w_up"].astype(dt)
        x = x + (gate * up) @ lyr["w_down"].astype(dt)

    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    return (x @ params["lm_head"].astype(dt)).astype(jnp.float32)


def loss_fn_dense(params, cfg, tokens, labels, mask):
    logits = forward_dense(params, cfg, tokens, mask)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(
        logp, jnp.maximum(labels, 0)[:, None], axis=-1
    )[:, 0]
    valid = labels >= 0
    return jnp.sum(jnp.where(valid, nll, 0.0)) / jnp.maximum(
        jnp.sum(valid), 1
    )


def make_optax_train_step(cfg: LlamaConfig, attn_key, optimizer):
    """jitted optax train step on the CP model (ref examples/torch_native
    optimizer loop). ``optimizer`` is any optax GradientTransformation."""
    import optax

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens, labels):
        loss, grads = jax.value_and_grad(loss_fn)(
            params, cfg, tokens, labels, attn_key
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def make_optax_train_step_dense(cfg: LlamaConfig, mask, optimizer):
    """The dense twin of :func:`make_optax_train_step` (same optimizer)."""
    import optax

    mask = jnp.asarray(mask)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens, labels):
        loss, grads = jax.value_and_grad(loss_fn_dense)(
            params, cfg, tokens, labels, mask
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step
