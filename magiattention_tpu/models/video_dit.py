"""Video DiT: a diffusion-transformer model family on spatiotemporal CP.

The reference's flagship workload is Magi-1 — an autoregressive video
diffusion transformer trained with MagiAttention's varlen-block-causal mask
at 131k context (ref README.md:54-56; the Magi-1 mask is bench config 4 in
docs/source/blog/cp_benchmark.md:82-96). This module is the TPU-native
counterpart of that model family: a compact DiT (AdaLN conditioning on the
diffusion timestep, flow-matching objective) whose attention runs through
``magi_attn_flex_key -> dispatch -> calc_attn`` over the spatiotemporal
block mask (frames causal, each frame attending the last ``window_frames``
frames — utils/sparse_utils.make_video_block_mask).

Layout mirrors models/llama.py: packed tokens (no batch dim), every
non-attention op row-wise or a matmul so the whole network computes on the
dispatched (chunk-permuted, cp-sharded) layout; factorized (frame, spatial)
position embeddings are gathered with the dispatched global position ids.
Projection weights reuse llama's names so ``llama.shard_params`` (ZeRO-3 +
optional Megatron TP) applies unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..api import calc_attn, dispatch, get_position_ids, magi_attn_flex_key
from ..dist_attn_runtime_mgr import DistAttnRuntimeKey
from ..utils.sparse_utils import (
    block_mask_to_dense_mask,
    block_mask_to_ranges,
    make_video_block_mask,
)
from .llama import _rms_norm, shard_params  # noqa: F401  (re-exported)


@dataclass(frozen=True)
class VideoDiTConfig:
    num_frames: int = 8
    tokens_per_frame: int = 256
    in_dim: int = 16  # latent channels per token
    dim: int = 384
    n_layers: int = 4
    n_heads: int = 6
    n_kv_heads: int = 6
    head_dim: int = 64
    ffn_hidden: int = 1024
    window_frames: int = 2  # each frame sees this many trailing frames
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    remat: bool = False

    @property
    def seqlen(self) -> int:
        return self.num_frames * self.tokens_per_frame

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)


def video_mask_ranges(cfg: VideoDiTConfig):
    """(q_ranges, k_ranges, attn_type_map, block_mask) of the Magi-1-style
    spatiotemporal mask at frame-block granularity."""
    bm = make_video_block_mask(cfg.num_frames, 1, cfg.window_frames)
    qr, kr, tm = block_mask_to_ranges(
        bm, cfg.tokens_per_frame, cfg.tokens_per_frame
    )
    return (
        [[r.start, r.end] for r in qr],
        [[r.start, r.end] for r in kr],
        [t.to_int_type() for t in tm],
        bm,
    )


def make_video_attn_key(
    cfg: VideoDiTConfig,
    mesh,
    cp_axis: str = "cp",
    chunk_size: int | None = None,
    dist_attn_config=None,
) -> DistAttnRuntimeKey:
    qr, kr, tm, _ = video_mask_ranges(cfg)
    kwargs = {}
    if dist_attn_config is not None:
        kwargs["dist_attn_config"] = dist_attn_config
    return magi_attn_flex_key(
        qr, kr, tm, cfg.seqlen, cfg.seqlen,
        mesh=mesh, cp_axis=cp_axis,
        chunk_size=chunk_size or cfg.tokens_per_frame // 2,
        **kwargs,
    )


def dense_video_mask(cfg: VideoDiTConfig) -> np.ndarray:
    """Token-level boolean oracle for the dense twin."""
    _, _, _, bm = video_mask_ranges(cfg)
    return block_mask_to_dense_mask(
        bm, cfg.tokens_per_frame, cfg.tokens_per_frame
    )


def init_params(cfg: VideoDiTConfig, key: jax.Array) -> dict:
    ks = jax.random.split(key, 6 + cfg.n_layers)
    dim, dh = cfg.dim, cfg.head_dim
    hq, hk = cfg.n_heads, cfg.n_kv_heads

    def dense(k, shape, scale=None):
        return jax.random.normal(k, shape, dtype=jnp.float32) * (
            (scale if scale is not None else shape[0] ** -0.5)
        )

    layers = []
    for i in range(cfg.n_layers):
        lk = jax.random.split(ks[6 + i], 8)
        layers.append(
            {
                "attn_norm": jnp.ones((dim,), jnp.float32),
                "wq": dense(lk[0], (dim, hq * dh)),
                "wk": dense(lk[1], (dim, hk * dh)),
                "wv": dense(lk[2], (dim, hk * dh)),
                "wo": dense(lk[3], (hq * dh, dim)),
                "mlp_norm": jnp.ones((dim,), jnp.float32),
                "w_gate": dense(lk[4], (dim, cfg.ffn_hidden)),
                "w_up": dense(lk[5], (dim, cfg.ffn_hidden)),
                "w_down": dense(lk[6], (cfg.ffn_hidden, dim)),
                # AdaLN modulation: cond -> (shift,scale,gate) x (attn,mlp).
                # Small init keeps the network near-identity at t=0 while
                # still passing gradient everywhere (DiT's adaLN-Zero uses
                # exact zeros; small-random keeps parity tests meaningful).
                "w_mod": dense(lk[7], (dim, 6 * dim), scale=1e-3),
                "b_mod": jnp.zeros((6 * dim,), jnp.float32),
            }
        )
    return {
        "w_in": dense(ks[0], (cfg.in_dim, dim)),
        "frame_emb": dense(ks[1], (cfg.num_frames, dim), scale=0.02),
        "spatial_emb": dense(ks[2], (cfg.tokens_per_frame, dim), scale=0.02),
        # timestep conditioning MLP (sinusoidal -> dim -> dim)
        "w_t1": dense(ks[3], (dim, dim)),
        "w_t2": dense(ks[4], (dim, dim)),
        "final_norm": jnp.ones((dim,), jnp.float32),
        # small (not exactly zero, as DiT does) so gradients reach the body
        # from step 0 and the CP-vs-dense parity check is meaningful
        "w_out": dense(ks[5], (cfg.dim, cfg.in_dim), scale=1e-3),
        "layers": layers,
    }


def _timestep_embedding(t: jax.Array, dim: int) -> jax.Array:
    """Sinusoidal embedding of the diffusion time ``t`` in [0, 1]."""
    half = dim // 2
    freqs = jnp.exp(
        -np.log(10000.0) * jnp.arange(half, dtype=jnp.float32) / half
    )
    ang = t.astype(jnp.float32) * 1000.0 * freqs
    return jnp.concatenate([jnp.cos(ang), jnp.sin(ang)])


def _modulate(h, mod, dt):
    shift, scale, gate = mod
    return h * (1.0 + scale.astype(dt)) + shift.astype(dt), gate


def forward(
    params: dict,
    cfg: VideoDiTConfig,
    latents: jax.Array,
    t: jax.Array,
    attn_key: DistAttnRuntimeKey,
) -> jax.Array:
    """Velocity prediction on the dispatched layout.

    Args:
        latents: ``(seqlen, in_dim)`` noisy video latents, natural order.
        t: scalar diffusion time in [0, 1].

    Returns:
        ``(shard, in_dim)`` prediction in DISPATCHED order (dispatch the
        flow-matching target with the same key — cheaper than undispatch).
    """
    dt = cfg.jdtype
    x = (latents.astype(dt) @ params["w_in"].astype(dt))
    x = dispatch(x, attn_key)
    pos = get_position_ids(attn_key)
    frame = pos // cfg.tokens_per_frame
    sp = pos % cfg.tokens_per_frame
    x = x + (
        jnp.take(params["frame_emb"], frame, axis=0)
        + jnp.take(params["spatial_emb"], sp, axis=0)
    ).astype(dt)

    cond = _timestep_embedding(t, cfg.dim)
    cond = jax.nn.silu(cond @ params["w_t1"])
    cond = jax.nn.silu(cond @ params["w_t2"])  # (dim,) fp32

    def layer(x, lyr):
        mods = (cond @ lyr["w_mod"] + lyr["b_mod"]).reshape(6, cfg.dim)
        h = _rms_norm(x, lyr["attn_norm"], cfg.norm_eps)
        h, gate_a = _modulate(h, mods[0:3], dt)
        q = (h @ lyr["wq"].astype(dt)).reshape(-1, cfg.n_heads, cfg.head_dim)
        k = (h @ lyr["wk"].astype(dt)).reshape(
            -1, cfg.n_kv_heads, cfg.head_dim
        )
        v = (h @ lyr["wv"].astype(dt)).reshape(
            -1, cfg.n_kv_heads, cfg.head_dim
        )
        attn_out, _ = calc_attn(q, k, v, attn_key)
        attn_out = attn_out.reshape(-1, cfg.n_heads * cfg.head_dim)
        x = x + gate_a.astype(dt) * (attn_out @ lyr["wo"].astype(dt))

        h = _rms_norm(x, lyr["mlp_norm"], cfg.norm_eps)
        h, gate_m = _modulate(h, mods[3:6], dt)
        up = jax.nn.silu(h @ lyr["w_gate"].astype(dt)) * (
            h @ lyr["w_up"].astype(dt)
        )
        return x + gate_m.astype(dt) * (up @ lyr["w_down"].astype(dt))

    if cfg.remat:
        layer = jax.checkpoint(layer)

    for lyr in params["layers"]:
        x = layer(x, lyr)

    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    return (x.astype(jnp.float32) @ params["w_out"])


def loss_fn(
    params: dict,
    cfg: VideoDiTConfig,
    clean: jax.Array,
    noise: jax.Array,
    t: jax.Array,
    attn_key: DistAttnRuntimeKey,
) -> jax.Array:
    """Flow-matching MSE: x_t = (1-t)·x0 + t·eps, target v = eps - x0.

    The prediction comes back in dispatched order; the target is dispatched
    with the same permutation (mirrors llama.loss_fn's label handling).
    """
    xt = (1.0 - t) * clean + t * noise
    pred = forward(params, cfg, xt, t, attn_key)
    target = dispatch((noise - clean).astype(jnp.float32), attn_key)
    return jnp.mean((pred - target) ** 2)


# ---------------------------------------------------------------------------
# dense (non-CP) twin — convergence-parity artifact, mirrors llama.py
# ---------------------------------------------------------------------------


def forward_dense(
    params: dict,
    cfg: VideoDiTConfig,
    latents: jax.Array,
    t: jax.Array,
    mask: jax.Array,
) -> jax.Array:
    dt = cfg.jdtype
    s = latents.shape[0]
    x = latents.astype(dt) @ params["w_in"].astype(dt)
    pos = jnp.arange(s, dtype=jnp.int32)
    frame = pos // cfg.tokens_per_frame
    sp = pos % cfg.tokens_per_frame
    x = x + (
        jnp.take(params["frame_emb"], frame, axis=0)
        + jnp.take(params["spatial_emb"], sp, axis=0)
    ).astype(dt)

    cond = _timestep_embedding(t, cfg.dim)
    cond = jax.nn.silu(cond @ params["w_t1"])
    cond = jax.nn.silu(cond @ params["w_t2"])

    g = cfg.n_heads // cfg.n_kv_heads
    for lyr in params["layers"]:
        mods = (cond @ lyr["w_mod"] + lyr["b_mod"]).reshape(6, cfg.dim)
        h = _rms_norm(x, lyr["attn_norm"], cfg.norm_eps)
        h, gate_a = _modulate(h, mods[0:3], dt)
        q = (h @ lyr["wq"].astype(dt)).reshape(-1, cfg.n_heads, cfg.head_dim)
        k = (h @ lyr["wk"].astype(dt)).reshape(
            -1, cfg.n_kv_heads, cfg.head_dim
        )
        v = (h @ lyr["wv"].astype(dt)).reshape(
            -1, cfg.n_kv_heads, cfg.head_dim
        )
        kf = jnp.repeat(k, g, axis=1)
        vf = jnp.repeat(v, g, axis=1)
        logits = jnp.einsum(
            "shd,thd->hst", q.astype(jnp.float32), kf.astype(jnp.float32)
        ) * (cfg.head_dim ** -0.5)
        logits = jnp.where(mask[None], logits, -jnp.inf)
        p = jax.nn.softmax(logits, axis=-1)
        attn_out = jnp.einsum("hst,thd->shd", p, vf.astype(jnp.float32))
        attn_out = attn_out.astype(dt).reshape(
            -1, cfg.n_heads * cfg.head_dim
        )
        x = x + gate_a.astype(dt) * (attn_out @ lyr["wo"].astype(dt))

        h = _rms_norm(x, lyr["mlp_norm"], cfg.norm_eps)
        h, gate_m = _modulate(h, mods[3:6], dt)
        up = jax.nn.silu(h @ lyr["w_gate"].astype(dt)) * (
            h @ lyr["w_up"].astype(dt)
        )
        x = x + gate_m.astype(dt) * (up @ lyr["w_down"].astype(dt))

    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    return x.astype(jnp.float32) @ params["w_out"]


def loss_fn_dense(params, cfg, clean, noise, t, mask):
    xt = (1.0 - t) * clean + t * noise
    pred = forward_dense(params, cfg, xt, t, mask)
    return jnp.mean((pred - (noise - clean).astype(jnp.float32)) ** 2)


def make_optax_train_step(cfg: VideoDiTConfig, attn_key, optimizer):
    import optax

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, clean, noise, t):
        loss, grads = jax.value_and_grad(loss_fn)(
            params, cfg, clean, noise, t, attn_key
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def make_optax_train_step_dense(cfg: VideoDiTConfig, mask, optimizer):
    import optax

    mask = jnp.asarray(mask)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, clean, noise, t):
        loss, grads = jax.value_and_grad(loss_fn_dense)(
            params, cfg, clean, noise, t, mask
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step
