"""Mixture-of-Experts transformer with expert parallelism (EP) over the mesh.

The reference delegates MoE/expert parallelism to Megatron-LM (ref
examples/megatron/README.md — SURVEY §2.8 lists TP/PP/EP as "delegated to
Megatron; not implemented in-repo"); the TPU build provides it natively so
the CP attention engine composes with an in-framework MoE model family.

TPU-first design (GShard/Switch capacity routing, the canonical XLA MoE):

- **Static shapes everywhere.** Top-k routing lowers to one-hot matmuls and
  a cumsum-based position-in-expert assignment; each expert processes a
  fixed ``capacity`` of token slots per shard. Overflowing tokens are
  dropped (their combine weight is 0, the residual stream carries them
  unchanged) — no dynamic shapes reach XLA, so everything tiles onto the
  MXU.
- **EP = ``lax.all_to_all`` over a mesh axis.** Experts are sharded over the
  ``ep`` axis (which may be the same devices as the cp/dp axis — the
  DeepSpeed-MoE "expert-parallel group == data-parallel group" layout).
  Token slots travel shard -> expert shard and back with two all_to_alls
  riding ICI, exactly the comm pattern the reference's grpcoll a2av tier
  uses for KV (comm/primitives.py) — here it is the *token* payload.
- **Batched expert matmuls.** The per-shard expert FFN is a single
  ``(E_local, tokens, dim) x (E_local, dim, ffn)`` einsum — one batched MXU
  op, not a Python loop over experts.

Gating math follows Mixtral (softmax over selected top-k logits); auxiliary
load-balancing loss follows Switch Transformer (mean fraction x mean prob).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..api import dispatch, get_mesh, get_position_ids
from ..utils.compat import shard_map
from ..dist_attn_runtime_mgr import DistAttnRuntimeKey
from .llama import LlamaConfig, _rms_norm, attn_block, masked_ce


@dataclass(frozen=True)
class MoEConfig(LlamaConfig):
    """Llama backbone with MoE FFN layers (attention path unchanged)."""

    n_experts: int = 8
    top_k: int = 2
    # per-expert token slots per EP shard = ceil(top_k * S_shard / E) * cf
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01


def init_moe_params(cfg: MoEConfig, key: jax.Array) -> dict:
    """Parameter pytree: llama backbone, MoE FFN per layer.

    Expert weights are stacked on a leading ``n_experts`` dim so they shard
    over the ep axis with a plain ``P('ep', ...)`` annotation.
    """
    ks = jax.random.split(key, 2 + cfg.n_layers)
    dim, dh, ffn = cfg.dim, cfg.head_dim, cfg.ffn_hidden
    hq, hk = cfg.n_heads, cfg.n_kv_heads
    E = cfg.n_experts

    def dense(k, shape, fan_in):
        return jax.random.normal(k, shape, dtype=jnp.float32) * (
            fan_in ** -0.5
        )

    layers = []
    for i in range(cfg.n_layers):
        lk = jax.random.split(ks[2 + i], 9)
        layers.append(
            {
                "attn_norm": jnp.ones((dim,), jnp.float32),
                "wq": dense(lk[0], (dim, hq * dh), dim),
                "wk": dense(lk[1], (dim, hk * dh), dim),
                "wv": dense(lk[2], (dim, hk * dh), dim),
                "wo": dense(lk[3], (hq * dh, dim), hq * dh),
                "mlp_norm": jnp.ones((dim,), jnp.float32),
                "router": dense(lk[4], (dim, E), dim),
                "w_gate": dense(lk[5], (E, dim, ffn), dim),
                "w_up": dense(lk[6], (E, dim, ffn), dim),
                "w_down": dense(lk[7], (E, ffn, dim), ffn),
            }
        )
    return {
        "embed": dense(ks[0], (cfg.vocab_size, dim), dim),
        "final_norm": jnp.ones((dim,), jnp.float32),
        "lm_head": dense(ks[1], (dim, cfg.vocab_size), dim),
        "layers": layers,
    }


def _check_experts_divisible(n_experts: int, ep: int, ep_axis) -> None:
    if ep and n_experts % ep:
        raise ValueError(
            f"n_experts={n_experts} must be divisible by the ep axis size "
            f"{ep} (axis {ep_axis!r})"
        )


def shard_moe_params(
    params: dict, mesh: Mesh, dp_axis: str = "cp", ep_axis: str | None = None
) -> dict:
    """ZeRO-3 first-dim sharding over dp/cp + expert sharding over ep.

    Expert-stacked weights (leading dim ``n_experts``) shard their expert
    dim over ``ep_axis``; everything else follows llama's ZeRO-3 layout.
    ``ep_axis`` may equal ``dp_axis`` (expert-parallel group == data-
    parallel group).
    """
    ep = mesh.shape[ep_axis] if ep_axis else 1

    def s2(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    def s(path, x):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("w_gate", "w_up", "w_down") and x.ndim == 3:
            if ep_axis:
                _check_experts_divisible(x.shape[0], ep, ep_axis)
                return s2(x, P(ep_axis, None, None))
            # no EP: ZeRO-3 the expert dim over dp like every other
            # first-dim-shardable weight (experts are the dominant params)
            if x.shape[0] % mesh.shape[dp_axis] == 0:
                return s2(x, P(dp_axis, None, None))
            return s2(x, P())
        dp_ok = x.ndim >= 2 and x.shape[0] % mesh.shape[dp_axis] == 0
        if dp_ok:
            return s2(x, P(dp_axis, *([None] * (x.ndim - 1))))
        return s2(x, P())

    return jax.tree_util.tree_map_with_path(s, params)


# ---------------------------------------------------------------------------
# the MoE FFN layer
# ---------------------------------------------------------------------------


def _route(h32, router_w, cfg: MoEConfig):
    """Top-k routing tensors for one shard's tokens.

    Returns (dispatch ``(S, E, C)`` bool-as-dtype one-hot, combine
    ``(S, E, C)`` probs, aux load-balance loss scalar).
    """
    S = h32.shape[0]
    E, K = cfg.n_experts, cfg.top_k
    C = int(np.ceil(K * S / E * cfg.capacity_factor))
    logits = h32 @ router_w  # (S, E) fp32
    probs = jax.nn.softmax(logits, axis=-1)

    # Mixtral gating: softmax over the selected top-k logits
    topv, topi = jax.lax.top_k(logits, K)  # (S, K)
    gates = jax.nn.softmax(topv, axis=-1)  # (S, K)

    # Switch aux loss: E * mean_frac_per_expert . mean_prob_per_expert
    sel1 = jax.nn.one_hot(topi[:, 0], E, dtype=jnp.float32)
    aux = E * jnp.mean(sel1, axis=0) @ jnp.mean(probs, axis=0)

    # position-in-expert via cumsum over the flattened (K, S) priority
    # order: k=0 choices of all tokens beat k=1 choices (GShard's policy).
    # Top-k indices are distinct per token, so each (s, e) pair appears at
    # most once across K — sum over K *before* the one-hot over C, keeping
    # the big tensor at (S, E, C) instead of (K, S, E, C).
    onehot = jax.nn.one_hot(topi.T, E, dtype=jnp.float32)  # (K, S, E)
    flat = onehot.reshape(K * S, E)
    pos = jnp.cumsum(flat, axis=0) - flat  # slots before this entry
    pos = pos.reshape(K, S, E)
    keep = flat.reshape(K, S, E) * (pos < C)  # (K, S, E)
    sel = jnp.sum(keep, axis=0)  # (S, E) — 0/1
    pos_se = jnp.sum(pos * keep, axis=0)  # (S, E) — slot when sel
    gate_se = jnp.einsum("sk,kse->se", gates, keep)
    posc = jax.nn.one_hot(
        pos_se.astype(jnp.int32), C, dtype=jnp.float32
    )  # (S, E, C)
    dispatch_t = sel[..., None] * posc  # (S, E, C) — one slot per (s, e)
    combine = gate_se[..., None] * posc
    return dispatch_t, combine, aux


def _moe_ffn_local(
    h, router, w_gate, w_up, w_down, cfg: MoEConfig,
    ep_axis: str | None, ep: int,
):
    """MoE FFN on one shard's tokens ``h: (S_local, dim)``.

    Runs inside shard_map when ``ep_axis`` is set: expert weights arrive
    ep-sharded ``(E/ep, dim, ffn)``; token slots all_to_all to the expert
    shards and back. With ``ep_axis=None`` (single shard) the all_to_alls
    vanish and the full expert stack is local. Expert id convention:
    ``e = ep_rank * (E // ep) + e_local`` (shard p owns the p-th expert
    block).
    """
    dt = h.dtype
    h32 = h.astype(jnp.float32)
    dispatch_t, combine, aux = _route(h32, router, cfg)
    S, E, C = dispatch_t.shape

    # gather token slots: (E, C, dim)
    slots = jnp.einsum("seC,sd->eCd", dispatch_t.astype(dt), h)

    if ep_axis is not None and ep > 1:
        # send each peer its expert block's slots; receive my block's
        # slots from every peer. all_to_all(tiled=False, split 0, concat
        # 0) yields (ep=source_peer, E/ep, C, d); batch experts, stack
        # source peers into the slot axis: (E/ep, ep*C, d).
        recv = jax.lax.all_to_all(
            slots.reshape(ep, E // ep, C, -1), ep_axis,
            split_axis=0, concat_axis=0, tiled=False,
        )
        slots = recv.transpose(1, 0, 2, 3).reshape(E // ep, ep * C, -1)
        aux = jax.lax.pmean(aux, ep_axis)

    # batched expert FFN: one einsum per projection (E_local batched matmul)
    g = jax.nn.silu(jnp.einsum("ecd,edf->ecf", slots, w_gate.astype(dt)))
    u = jnp.einsum("ecd,edf->ecf", slots, w_up.astype(dt))
    out = jnp.einsum("ecf,efd->ecd", g * u, w_down.astype(dt))

    if ep_axis is not None and ep > 1:
        # inverse of the forward exchange: (E/ep, ep*C, d) -> split the
        # slot axis back by token-owner peer -> (ep, E/ep, C, d) -> a2a
        # -> (ep=expert_shard, E/ep, C, d) -> (E, C, d)
        send = out.reshape(E // ep, ep, C, -1).swapaxes(0, 1)
        out = jax.lax.all_to_all(
            send, ep_axis, split_axis=0, concat_axis=0, tiled=False,
        ).reshape(E, C, -1)

    y = jnp.einsum("seC,eCd->sd", combine.astype(dt), out)
    return y, aux


def moe_ffn(h, lyr, cfg: MoEConfig, mesh=None, ep_axis=None):
    """Public MoE FFN entry.

    - ``mesh`` given: wraps itself in a shard_map over ``ep_axis`` (tokens
      sharded over the same axis — the expert-parallel group == data/cp
      group layout).
    - ``mesh=None, ep_axis`` given: already inside a shard_map; uses the
      bound axis name directly.
    - both None: single-shard (no comm).
    """
    args = (lyr["router"], lyr["w_gate"], lyr["w_up"], lyr["w_down"])
    if mesh is None:
        ep = jax.lax.axis_size(ep_axis) if ep_axis is not None else 1
        _check_experts_divisible(cfg.n_experts, ep, ep_axis)
        return _moe_ffn_local(h, *args, cfg, ep_axis, ep)
    ep = mesh.shape[ep_axis]
    _check_experts_divisible(cfg.n_experts, ep, ep_axis)
    fn = shard_map(
        partial(_moe_ffn_local, cfg=cfg, ep_axis=ep_axis, ep=ep),
        mesh=mesh,
        in_specs=(
            P(ep_axis),  # tokens
            P(),  # router (replicated)
            P(ep_axis), P(ep_axis), P(ep_axis),  # expert-stacked weights
        ),
        out_specs=(P(ep_axis), P()),
    )
    return fn(h, *args)


# ---------------------------------------------------------------------------
# full model: llama backbone + MoE FFN
# ---------------------------------------------------------------------------


def moe_forward(
    params: dict,
    cfg: MoEConfig,
    tokens: jax.Array,
    attn_key: DistAttnRuntimeKey,
    ep_axis: str | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Forward on the dispatched CP layout; MoE FFN with optional EP.

    When ``ep_axis`` is given the caller must run this under pjit on a mesh
    carrying that axis; the MoE layer's shard_map boundary is established
    per layer against the dispatched token shard. Returns
    ``(logits_dispatched, aux_loss)``.
    """
    dt = cfg.jdtype
    x = jnp.take(params["embed"], tokens, axis=0).astype(dt)
    x = dispatch(x, attn_key)
    pos = get_position_ids(attn_key)
    mesh = get_mesh(attn_key) if ep_axis is not None else None

    aux_total = jnp.zeros((), jnp.float32)

    def layer(x, lyr):
        x = attn_block(x, lyr, cfg, pos, attn_key)
        h = _rms_norm(x, lyr["mlp_norm"], cfg.norm_eps)
        y, aux = moe_ffn(h, lyr, cfg, mesh=mesh, ep_axis=ep_axis)
        return x + y, aux

    if cfg.remat:
        layer = jax.checkpoint(layer)

    for lyr in params["layers"]:
        x, aux = layer(x, lyr)
        aux_total = aux_total + aux

    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"].astype(dt)).astype(jnp.float32)
    return logits, aux_total / max(cfg.n_layers, 1)


def moe_loss_fn(params, cfg, tokens, labels, attn_key, ep_axis=None):
    logits, aux = moe_forward(params, cfg, tokens, attn_key, ep_axis)
    labels_d = dispatch(labels, attn_key)
    return masked_ce(logits, labels_d) + cfg.aux_loss_coef * aux


@partial(jax.jit, static_argnums=(1, 4, 5), donate_argnums=(0,))
def moe_train_step(
    params, cfg: MoEConfig, tokens, labels, attn_key, ep_axis=None,
    lr: float = 1e-4,
):
    loss, grads = jax.value_and_grad(moe_loss_fn)(
        params, cfg, tokens, labels, attn_key, ep_axis
    )
    params = jax.tree.map(
        lambda p, g: p - lr * g.astype(p.dtype), params, grads
    )
    return params, loss


# ---------------------------------------------------------------------------
# dense reference (testing): per-token full expert sum, no capacity drops
# ---------------------------------------------------------------------------


def moe_ffn_reference(h, lyr, cfg: MoEConfig):
    """O(S*E) dense reference of the MoE FFN — every token visits its top-k
    experts directly (no capacity, no drops). Ground truth for the routed
    implementation wherever no slot overflows."""
    h32 = h.astype(jnp.float32)
    logits = h32 @ lyr["router"]
    topv, topi = jax.lax.top_k(logits, cfg.top_k)
    gates = jax.nn.softmax(topv, axis=-1)  # (S, K)
    dt = h.dtype

    def expert(e, x):
        g = jax.nn.silu(x @ lyr["w_gate"][e].astype(dt))
        u = x @ lyr["w_up"][e].astype(dt)
        return (g * u) @ lyr["w_down"][e].astype(dt)

    all_out = jnp.stack(
        [expert(e, h) for e in range(cfg.n_experts)], axis=1
    )  # (S, E, dim)
    sel = jnp.take_along_axis(
        all_out, topi[:, :, None], axis=1
    )  # (S, K, dim)
    return jnp.sum(sel * gates[:, :, None].astype(dt), axis=1)
