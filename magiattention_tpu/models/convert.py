"""HuggingFace -> magiattention_tpu weight conversion.

The reference integrates with HF Transformers as the CP backend inside a
torch model (ref examples/transformers/); the TPU build's models are
JAX-native, so migration needs a weight bridge instead: this module maps a
HF ``LlamaForCausalLM`` state dict onto :mod:`models.llama`'s params pytree
(numerically exact — pinned by a logits-parity test against the torch
forward).

Layout notes (HF stores ``nn.Linear`` weight as (out, in); ours are
(in, out) matmul-ready, so every projection transposes):

- ``model.embed_tokens.weight (vocab, dim)`` -> ``embed`` (as-is)
- ``layers.N.self_attn.{q,k,v,o}_proj.weight`` -> ``wq/wk/wv/wo`` (T)
- ``layers.N.input_layernorm.weight`` -> ``attn_norm``
- ``layers.N.post_attention_layernorm.weight`` -> ``mlp_norm``
- ``layers.N.mlp.{gate,up,down}_proj.weight`` -> ``w_gate/w_up/w_down`` (T)
- ``model.norm.weight`` -> ``final_norm``; ``lm_head.weight`` -> ``lm_head`` (T)

The rotary convention matches (both rotate first-half/second-half pairs),
so no permutation of q/k rows is needed.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .llama import LlamaConfig


def config_from_hf(hf_config, dtype: str = "bfloat16") -> LlamaConfig:
    """LlamaConfig from a HF ``LlamaConfig``. ``dtype`` is the activation
    compute dtype (bf16 for training-speed parity on TPU; pass "float32"
    for bitwise-close parity checks against a fp32 torch forward)."""
    head_dim = getattr(hf_config, "head_dim", None) or (
        hf_config.hidden_size // hf_config.num_attention_heads
    )
    return LlamaConfig(
        dtype=dtype,
        vocab_size=hf_config.vocab_size,
        dim=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=hf_config.num_key_value_heads,
        head_dim=head_dim,
        ffn_hidden=hf_config.intermediate_size,
        rope_theta=float(hf_config.rope_theta),
        norm_eps=float(hf_config.rms_norm_eps),
    )


def _t(sd, key):
    w = sd[key]
    return jnp.asarray(np.asarray(w, dtype=np.float32))


def _sd_numpy(model) -> dict:
    """State dict -> fp32 numpy (``.float()`` first: torch bf16 tensors —
    how any real-size checkpoint is loaded — don't support ``.numpy()``)."""
    return {
        k: v.detach().cpu().float().numpy()
        for k, v in model.state_dict().items()
    }


def _attn_layer_entries(sd: dict, p: str) -> dict:
    """The backbone (attention + norms) per-layer mapping — shared by the
    Llama and Mixtral converters so a mapping fix reaches both."""
    return {
        "attn_norm": _t(sd, p + "input_layernorm.weight"),
        "wq": _t(sd, p + "self_attn.q_proj.weight").T,
        "wk": _t(sd, p + "self_attn.k_proj.weight").T,
        "wv": _t(sd, p + "self_attn.v_proj.weight").T,
        "wo": _t(sd, p + "self_attn.o_proj.weight").T,
        "mlp_norm": _t(sd, p + "post_attention_layernorm.weight"),
    }


def _top_level_entries(sd: dict, layers: list) -> dict:
    """embed/final_norm/lm_head (tied-weight fallback) + layers."""
    lm_head = (
        _t(sd, "lm_head.weight").T
        if "lm_head.weight" in sd
        else _t(sd, "model.embed_tokens.weight").T
    )
    return {
        "embed": _t(sd, "model.embed_tokens.weight"),
        "final_norm": _t(sd, "model.norm.weight"),
        "lm_head": lm_head,
        "layers": layers,
    }


def params_from_hf_state_dict(sd: dict, cfg: LlamaConfig) -> dict:
    """HF LlamaForCausalLM state dict (tensors or arrays) -> params pytree.

    Accepts torch tensors (call ``.detach().cpu()`` upstream or pass
    ``{k: v.numpy() for ...}``) or numpy arrays. ``lm_head.weight`` falls
    back to the embedding (tied weights) when absent.
    """
    layers = []
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        layers.append({
            **_attn_layer_entries(sd, p),
            "w_gate": _t(sd, p + "mlp.gate_proj.weight").T,
            "w_up": _t(sd, p + "mlp.up_proj.weight").T,
            "w_down": _t(sd, p + "mlp.down_proj.weight").T,
        })
    return _top_level_entries(sd, layers)


def load_hf_llama(model, dtype: str = "bfloat16") -> tuple[LlamaConfig, dict]:
    """(cfg, params) from a live HF ``LlamaForCausalLM`` instance."""
    cfg = config_from_hf(model.config, dtype=dtype)
    return cfg, params_from_hf_state_dict(_sd_numpy(model), cfg)


# ---------------------------------------------------------------------------
# Mixtral -> MoE family
# ---------------------------------------------------------------------------


def moe_config_from_hf(hf_config, dtype: str = "bfloat16",
                       capacity_factor: float = 1.25):
    """MoEConfig from a HF ``MixtralConfig`` (gating matches: softmax over
    the selected top-k router logits). Backbone fields come through
    :func:`config_from_hf` so a new base-field mapping reaches both
    families."""
    import dataclasses

    from .moe import MoEConfig

    base = dataclasses.asdict(config_from_hf(hf_config, dtype=dtype))
    return MoEConfig(
        **base,
        n_experts=hf_config.num_local_experts,
        top_k=hf_config.num_experts_per_tok,
        capacity_factor=capacity_factor,
    )


def moe_params_from_hf_state_dict(sd: dict, cfg) -> dict:
    """HF MixtralForCausalLM state dict -> MoE params pytree.

    Mixtral naming: ``block_sparse_moe.gate`` -> router;
    experts.N.{w1,w3,w2} -> w_gate/w_up/w_down (stacked on the expert dim,
    transposed to (in, out))."""
    layers = []
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        m = p + "block_sparse_moe."
        layers.append({
            **_attn_layer_entries(sd, p),
            "router": _t(sd, m + "gate.weight").T,
            "w_gate": jnp.stack([
                _t(sd, m + f"experts.{e}.w1.weight").T
                for e in range(cfg.n_experts)
            ]),
            "w_up": jnp.stack([
                _t(sd, m + f"experts.{e}.w3.weight").T
                for e in range(cfg.n_experts)
            ]),
            "w_down": jnp.stack([
                _t(sd, m + f"experts.{e}.w2.weight").T
                for e in range(cfg.n_experts)
            ]),
        })
    return _top_level_entries(sd, layers)


def load_hf_mixtral(model, dtype: str = "bfloat16",
                    capacity_factor: float = 1.25):
    """(cfg, params) from a live HF ``MixtralForCausalLM``. For exact
    parity checks against the torch forward use a LARGE capacity_factor
    (HF routes every token to its top-k experts with no capacity drops)."""
    cfg = moe_config_from_hf(model.config, dtype=dtype,
                             capacity_factor=capacity_factor)
    return cfg, moe_params_from_hf_state_dict(_sd_numpy(model), cfg)
