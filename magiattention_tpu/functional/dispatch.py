"""Dispatch / undispatch ops (ref: magi_attention/functional/dispatch.py:193-224).

dispatch permutes the global sequence into the load-balanced chunk order and
shards it over the cp axis; undispatch inverts. Implemented as plain gathers
with sharding constraints: XLA inserts the all-gather / reduce-scatter
(forward / transpose) collectives — the reference's hand-written
all_gather_v + unpermute (+ `_UndispatchPartialGradFunc` reduce-scatter
backward, ref :70-189) fall out of AD.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.profiling import instrument_scope


@instrument_scope
def dispatch_func(
    x: jax.Array,
    position_ids: np.ndarray,
    mesh: Mesh,
    cp_axis: str,
) -> jax.Array:
    """Global (natural order) -> dispatched (chunk-permuted, cp-sharded).

    Args:
        x: ``(total_seqlen, ...)`` in natural order (any sharding).
        position_ids: ``(cp, shard)`` host array — global row of each local row.

    Returns:
        ``(total_seqlen, ...)`` permuted so rank r's shard is rows
        ``position_ids[r]``, sharded P(cp_axis) on dim 0.
    """
    idx = jnp.asarray(np.asarray(position_ids).reshape(-1))
    y = jnp.take(x, idx, axis=0)
    return jax.lax.with_sharding_constraint(
        y, NamedSharding(mesh, P(cp_axis, *([None] * (x.ndim - 1))))
    )


@instrument_scope
def undispatch_func(
    y: jax.Array,
    unpermute_index: np.ndarray,
    mesh: Mesh,
    cp_axis: str,
) -> jax.Array:
    """Dispatched -> global natural order (inverse permutation)."""
    idx = jnp.asarray(np.asarray(unpermute_index))
    x = jnp.take(y, idx, axis=0)
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(cp_axis, *([None] * (y.ndim - 1))))
    )
