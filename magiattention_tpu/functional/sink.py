"""Attention-sink support (ref: extensions/fa{2,3,4}_interface_with_sink.py,
ref_attn.py init_lse_with_sink; layout math calc_lse_sink,
magi_attention/functional/utils.py:235).

Sink tokens contribute learnable logits to every query row's softmax
normalization but no value vectors. Layouts (ref utils.py:244-247):

    sh:  ``(s_sink, h)``     — one shared sink strip for every query row
    ssh: ``(sq, s_sink, h)`` — per-query-row sink logits
    shd: ``(s_sink, h, d)``  — NotImplementedError, matching the reference
                               exactly (utils.py:277 "not supported yet")

With per-row sink lse ``L_i = logsumexp_j sink[(i,)j,h]``:

    lse' = logaddexp(lse, L)                         (per row, per head)
    out' = out * exp(lse - lse')

Gradients use the same final-lse identity as the distributed merge: the
kernel backward runs against lse', which renormalizes dq/dk/dv exactly, and
    sh:  dsink[j, h]    = -sum_i exp(sink[j,h] - lse'[i,h]) * delta[i,h]
    ssh: dsink[i, j, h] = -exp(sink[i,j,h] - lse'[i,h]) * delta[i,h]
with delta = rowsum(do * out').
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def check_sink_layout(sink_layout: str) -> None:
    """The ONE place the supported-layout set is decided (ref
    _check_sink_layout, fa3_interface_with_sink.py:411; 'shd' raises
    exactly as the reference's calc_lse_sink does, utils.py:277)."""
    if sink_layout == "shd":
        raise NotImplementedError(
            "sink_layout='shd' is not supported — matching the reference "
            "(magi_attention/functional/utils.py:277)"
        )
    if sink_layout not in ("sh", "ssh"):
        raise ValueError(f"invalid sink_layout: {sink_layout!r}")


def _sink_lse(sink: jax.Array, sink_layout: str, seqlen_q: int) -> jax.Array:
    """Per-row sink normalizer ``(s, h)`` (ref calc_lse_sink, utils.py:235)."""
    check_sink_layout(sink_layout)
    s32 = sink.astype(jnp.float32)
    if sink_layout == "sh":
        if sink.ndim != 2:
            raise ValueError(f"'sh' sink must be (s_sink, h), got {sink.shape}")
        return jnp.broadcast_to(
            jax.scipy.special.logsumexp(s32, axis=0)[None, :],
            (seqlen_q, sink.shape[1]),
        )
    if sink.ndim != 3 or sink.shape[0] != seqlen_q:
        raise ValueError(
            f"'ssh' sink must be (seqlen_q={seqlen_q}, s_sink, h), "
            f"got {sink.shape}"
        )
    return jax.scipy.special.logsumexp(s32, axis=1)


def apply_sink_fwd(
    out: jax.Array,
    lse: jax.Array,
    sink: jax.Array,
    sink_layout: str = "sh",
) -> tuple[jax.Array, jax.Array]:
    """(out, lse) without sink -> (out', lse') with sink folded in.

    Args:
        out: ``(s, h, dv)``; lse: ``(s, h)`` fp32; sink: see module doc.
    """
    sink_lse = _sink_lse(sink, sink_layout, lse.shape[0])  # (s, h)
    neg = jnp.isneginf(lse)
    lse_new = jnp.logaddexp(jnp.where(neg, -jnp.inf, lse), sink_lse)
    w = jnp.exp(jnp.where(neg, -jnp.inf, lse - jnp.where(jnp.isneginf(lse_new), 0.0, lse_new)))
    out_new = (out.astype(jnp.float32) * w[..., None]).astype(out.dtype)
    return out_new, lse_new


def sink_bwd(
    sink: jax.Array,
    lse_final: jax.Array,
    delta: jax.Array,
    sink_layout: str = "sh",
) -> jax.Array:
    """dsink from the final lse and delta (ref compute_dsink,
    fa3_interface_with_sink.py:371).

    Args:
        sink: layout per module doc; lse_final: ``(s, h)``; delta: ``(s, h)``
            = rowsum(do * out_final), fp32.
    """
    check_sink_layout(sink_layout)
    # rows with -inf lse' have no mass anywhere -> w = 0
    lse_safe = jnp.where(jnp.isneginf(lse_final), jnp.inf, lse_final)
    if sink_layout == "sh":
        # p_sink[i, j, h] = exp(sink[j,h] - lse'[i,h])
        w = jnp.exp(
            sink.astype(jnp.float32)[None, :, :] - lse_safe[:, None, :]
        )
        return (-jnp.einsum("ijh,ih->jh", w, delta)).astype(sink.dtype)
    w = jnp.exp(sink.astype(jnp.float32) - lse_safe[:, None, :])
    return (-w * delta[:, None, :]).astype(sink.dtype)
