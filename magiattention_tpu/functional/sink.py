"""Attention-sink support (ref: extensions/fa{2,3,4}_interface_with_sink.py,
ref_attn.py init_lse_with_sink).

Sink tokens contribute learnable logits to every query row's softmax
normalization but no value vectors: with per-token-per-head sink logits
``sink (s_sink, h)``,

    lse' = logaddexp(lse, logsumexp_j sink[j])       (per row, per head)
    out' = out * exp(lse - lse')

Gradients use the same final-lse identity as the distributed merge: the
kernel backward runs against lse', which renormalizes dq/dk/dv exactly, and
    dsink[j, h] = -sum_i exp(sink[j,h] - lse'[i,h]) * delta[i,h]
with delta = rowsum(do * out').
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def apply_sink_fwd(
    out: jax.Array, lse: jax.Array, sink: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """(out, lse) without sink -> (out', lse') with sink folded in.

    Args:
        out: ``(s, h, dv)``; lse: ``(s, h)`` fp32; sink: ``(s_sink, h)``.
    """
    sink_lse = jax.scipy.special.logsumexp(
        sink.astype(jnp.float32), axis=0
    )  # (h,)
    neg = jnp.isneginf(lse)
    lse_new = jnp.logaddexp(jnp.where(neg, -jnp.inf, lse), sink_lse[None, :])
    w = jnp.exp(jnp.where(neg, -jnp.inf, lse - jnp.where(jnp.isneginf(lse_new), 0.0, lse_new)))
    out_new = (out.astype(jnp.float32) * w[..., None]).astype(out.dtype)
    return out_new, lse_new


def sink_bwd(
    sink: jax.Array, lse_final: jax.Array, delta: jax.Array
) -> jax.Array:
    """dsink from the final lse and delta (ref functional/utils.py sink_bwd).

    Args:
        sink: ``(s_sink, h)``; lse_final: ``(s, h)``; delta: ``(s, h)`` =
            rowsum(do * out_final), fp32.
    """
    # p_sink[i, j, h] = exp(sink[j,h] - lse'[i,h])
    w = jnp.exp(
        sink.astype(jnp.float32)[None, :, :]
        - jnp.where(jnp.isneginf(lse_final), jnp.inf, lse_final)[:, None, :]
    )  # rows with -inf lse' have no mass anywhere -> w = 0
    return (-jnp.einsum("ijh,ih->jh", w, delta)).astype(sink.dtype)
