"""Distributed roll over the dispatched layout (ref: magi_attention/functional/roll.py).

``torch.roll`` on the global sequence while tensors live in the dispatched
(chunk-permuted, cp-sharded) layout — used for multi-token-prediction label
shifting. The reference implements this with batched P2P (roll_p2p :448);
on TPU the rolled permutation composes with the dispatch permutation into a
single static gather, and XLA lowers the cross-shard rows to collectives.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..meta.collection.dispatch_meta import DispatchMeta


def roll_index(meta: DispatchMeta, shifts: int) -> np.ndarray:
    """Gather index implementing a global roll on dispatched tensors.

    out_disp[flat_pos] = in_disp[idx[flat_pos]] where out corresponds to the
    globally-rolled sequence re-dispatched with the same permutation.
    """
    pos = meta.position_ids.reshape(-1)  # local row -> global row
    unperm = meta.unpermute_index  # global row -> local row
    src_global = (pos - shifts) % meta.total_seqlen
    return unperm[src_global].astype(np.int32)


def roll_func(
    x: jax.Array,
    meta: DispatchMeta,
    shifts: int,
    mesh: Mesh,
    cp_axis: str,
) -> jax.Array:
    """Roll the dispatched tensor by ``shifts`` global positions."""
    idx = jnp.asarray(roll_index(meta, shifts))
    y = jnp.take(x, idx, axis=0)
    return jax.lax.with_sharding_constraint(
        y, NamedSharding(mesh, P(cp_axis, *([None] * (x.ndim - 1))))
    )
