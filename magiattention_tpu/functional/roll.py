"""Distributed roll over the dispatched layout (ref: magi_attention/functional/roll.py).

``torch.roll`` on the global sequence while tensors live in the dispatched
(chunk-permuted, cp-sharded) layout — used for multi-token-prediction label
shifting. The reference implements this with batched segment-wise P2P
(roll_p2p :448); the TPU lowering is the same idea expressed as collectives:
a host-planned per-rank split into

- self rows (the overwhelming majority when ``|shifts| < chunk_size``):
  a local gather, no wire traffic;
- cross rows, grouped by ring distance: one ``jax.lax.ppermute`` round per
  active distance, each padded only to that distance's max pair — no
  all-gather ever materializes (VERDICT r1 weak item 6).

AD transposes the gather+ppermute program into the inverse roll for free.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from ..utils.compat import shard_map

from ..meta.collection.dispatch_meta import DispatchMeta


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def roll_index(meta: DispatchMeta, shifts: int) -> np.ndarray:
    """Gather index implementing a global roll on dispatched tensors.

    out_disp[flat_pos] = in_disp[idx[flat_pos]] where out corresponds to the
    globally-rolled sequence re-dispatched with the same permutation.
    (Kept as the dense-oracle for tests and the cp=1 shortcut.)
    """
    pos = meta.position_ids.reshape(-1)  # local row -> global row
    unperm = meta.unpermute_index  # global row -> local row
    src_global = (pos - shifts) % meta.total_seqlen
    return unperm[src_global].astype(np.int32)


def make_roll_plan(
    meta: DispatchMeta, shifts: int, align: int = 8
) -> tuple[np.ndarray, np.ndarray, tuple[int, ...], tuple[int, ...]]:
    """Host plan for the segment-wise roll.

    Returns:
        send_idx: (cp, sum_caps) — local rows each rank sends, concatenated
            per ring distance (rows for dst = (rank + delta) % cp, in the
            destination's output order).
        asm_idx: (cp, shard) — assembly gather over [local shard | recv
            buffer] producing the rolled local shard.
        deltas, caps: active ring distances and their padded capacities.
    """
    cp = meta.cp_size
    shard = meta.shard_seqlen
    total = meta.total_seqlen
    pos = np.asarray(meta.position_ids)  # (cp, shard)
    unperm = np.asarray(meta.unpermute_index)

    u = unperm[(pos - shifts) % total]  # (cp, shard) flat source rows
    src_rank = (u // shard).astype(np.int32)
    src_local = (u % shard).astype(np.int32)

    # per-pair row counts: dst r needs rows from src s
    counts = np.zeros((cp, cp), dtype=np.int64)  # [src][dst]
    for r in range(cp):
        for s, c in zip(*np.unique(src_rank[r], return_counts=True)):
            counts[int(s), r] = int(c)

    deltas, caps = [], []
    for delta in range(1, cp):
        mx = max(int(counts[(r - delta) % cp, r]) for r in range(cp))
        if mx > 0:
            deltas.append(delta)
            caps.append(_round_up(mx, align))
    cum = {}
    off = 0
    for delta, c in zip(deltas, caps):
        cum[delta] = off
        off += c
    sum_caps = off

    send_idx = np.zeros((cp, max(sum_caps, 1)), dtype=np.int32)
    asm_idx = np.zeros((cp, shard), dtype=np.int32)
    for r in range(cp):
        self_m = src_rank[r] == r
        asm_idx[r][self_m] = src_local[r][self_m]
        for s in range(cp):
            if s == r or counts[s, r] == 0:
                continue
            delta = (r - s) % cp
            m = src_rank[r] == s
            rows = src_local[r][m]  # in dst output order
            base = cum[delta]
            send_idx[s, base: base + rows.size] = rows
            asm_idx[r][m] = shard + base + np.arange(
                rows.size, dtype=np.int32
            )
    return send_idx, asm_idx, tuple(deltas), tuple(caps)


def roll_rows(
    x: jax.Array,
    send_idx: jax.Array,
    asm_idx: jax.Array,
    deltas: tuple[int, ...],
    caps: tuple[int, ...],
    cp: int,
    axis_name: str,
) -> jax.Array:
    """Segment-wise roll inside shard_map: local gather + ppermute rounds
    (the ring loop is :func:`group_cast_rows_pp` with an identity receive
    selector; the roll-specific part is only the final [local | received]
    assembly gather)."""
    from ..comm.primitives import group_cast_rows_pp

    parts = [x]
    if deltas:
        sum_caps = sum(caps)
        parts.append(
            group_cast_rows_pp(
                x, send_idx,
                jnp.arange(sum_caps, dtype=jnp.int32),
                deltas, caps, cp, axis_name,
            )
        )
    buf = jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
    return jnp.take(buf, asm_idx, axis=0)


def roll_func(
    x: jax.Array,
    meta: DispatchMeta,
    shifts: int,
    mesh: Mesh,
    cp_axis: str,
) -> jax.Array:
    """Roll the dispatched tensor by ``shifts`` global positions."""
    cp = meta.cp_size
    if cp == 1 or shifts % meta.total_seqlen == 0:
        idx = jnp.asarray(roll_index(meta, shifts))
        return jnp.take(x, idx, axis=0)

    send_idx, asm_idx, deltas, caps = make_roll_plan(meta, shifts)
    spec = P(cp_axis, *([None] * (x.ndim - 1)))

    def f(x, si, ai):
        return roll_rows(x, si[0], ai[0], deltas, caps, cp, cp_axis)

    return shard_map(
        f,
        mesh=mesh,
        in_specs=(spec, P(cp_axis), P(cp_axis)),
        out_specs=spec,
        check_vma=False,
    )(x, jnp.asarray(send_idx), jnp.asarray(asm_idx))
