"""The CP engine (ref: magi_attention/functional/dist_attn.py:142,3101).

``DistAttnRuntime`` turns the solver's host plans (CommMeta + CalcMeta) into a
single SPMD function over the CP mesh axis:

- no-overlap path (ref :3305): GroupCast all remote kv, concatenate with the
  local shard, run ONE merged FFA kernel. Simplest, fewest launches.
- multi-stage overlap path (ref :3195-3266): run the host kernel and one FFA
  per stage against that stage's receive buffer, lse-merging partials. The
  per-stage all_to_alls have no data dependence on earlier compute, so XLA's
  async collective scheduler hides stage i+1's communication under stage i's
  compute — replacing the reference's stream/event + KernelBarrier machinery.

Backward: jax AD. The kernel has a custom VJP (Pallas dq/dkv kernels); the
GroupCast gathers + all_to_all transpose to scatter-add + reverse all_to_all,
which IS GroupReduce — zero-redundant dkv reduction with no hand-written comm
(replacing _reduce_partial_dkv, ref :2123). The lse-merge transposes through
jnp autodiff (replacing _reduce_partial_out_lse, ref :1979).

SPMD note: per-rank metadata (slice lists, index arrays, FFA plans) is padded
to rank-uniform shapes and passed as sharded operands, so one traced program
serves every rank — the TPU answer to the reference's per-rank host code.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from ..utils.compat import shard_map

from ..comm.primitives import cast_rows, reduce_rows
from ..env import comm as env_comm
from ..env import general as env_general
from ..env import resilience as env_resilience
from ..kernels.ffa import (
    FFAParams,
    _bwd_plan_slices,
    bwd_mode_key,
    bwd_modeled_cost,
    ffa_bwd_pallas_dispatch,
    ffa_delta_pallas_dispatch,
    ffa_fwd_pallas_dispatch,
    _should_interpret,
    ffa_attn_with_plan,
    resolved_bwd_mode,
)
from ..kernels.ffa_plan import build_ffa_plan, pad_plan
from ..meta.collection.calc_meta import AttnArg, CalcMeta
from ..meta.collection.comm_meta import CommMeta
from ..utils.profiling import instrument_scope, profile_scope
from .utils import lse_weighted_reduce
from .. import telemetry


def _head_major(x: jax.Array, sp: int) -> jax.Array:
    """(s, h, d) -> (h, sp, d) padded to sp rows."""
    return jnp.pad(x, ((0, sp - x.shape[0]), (0, 0), (0, 0))).transpose(1, 0, 2)


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def _multi_ffa(q, ks, vs, arrays_list, params_list):
    """Merged multi-part FFA: part i attends q against (ks[i], vs[i]) with its
    own plan; partials are lse-merged into one (out, lse, max_logits).

    The VJP is the distributed-flash identity (ref dist_attn.py bwd loop
    :3561): each part's backward kernel runs against the FINAL merged lse and
    delta = rowsum(do * out_final), which makes per-part dq/dkv contributions
    exact — no gradient flows through the merge weights themselves.
    max_logits is the elementwise MAX over parts (ref reduce_max_logits,
    dist_attn.py:550); it is a non-differentiable auxiliary output.
    """
    out, lse, ml, _, _ = _multi_ffa_impl(q, ks, vs, arrays_list, params_list)
    return out, lse, ml


def _multi_ffa_impl(q, ks, vs, arrays_list, params_list):
    outs, lses = [], []
    ml = None
    for i, (k, v, arrs, prm) in enumerate(
        zip(ks, vs, arrays_list, params_list)
    ):
        sqp = prm.num_q_tiles * prm.block_q
        skp = prm.num_k_tiles * prm.block_k
        q_t = _head_major(q, sqp)
        # compute always in q's dtype: k/v parts may arrive fp32 from the
        # high-precision-reduce cast (hp_group_cast) so their cotangents
        # stay fp32 through the wire reduce
        k_t = _head_major(k.astype(q.dtype), skp)
        v_t = _head_major(v.astype(q.dtype), skp)
        with profile_scope(f"ffa_fwd_stage{i}"):
            out_t, lse_t, ml_p = ffa_fwd_pallas_dispatch(
                prm, *arrs[:3], q_t, k_t, v_t
            )
        outs.append(out_t.transpose(1, 0, 2)[: q.shape[0]])
        lses.append(lse_t.T[: q.shape[0]])
        ml = ml_p if ml is None else jnp.maximum(ml, ml_p)
    with profile_scope("lse_merge"):
        out, lse = lse_weighted_reduce(jnp.stack(outs), jnp.stack(lses))
    return out, lse, ml, outs, lses


def _multi_ffa_fwd(q, ks, vs, arrays_list, params_list):
    out, lse, ml, _, _ = _multi_ffa_impl(q, ks, vs, arrays_list, params_list)
    # residuals keep the PRIMAL-dtype parts: under HP reduce the remote
    # parts are fp32 (2x residual HBM — the flag's documented cost) so
    # their cotangents legally leave fp32 for the wire reduce
    return (out, lse, ml), (q, ks, vs, out, lse, arrays_list)


def _multi_ffa_bwd(params_list, res, cts):
    do, _, _ = cts  # lse/max_logits cotangents ignored (auxiliary outputs)
    q, ks, vs, out, lse, arrays_list = res
    sq = q.shape[0]
    # delta = rowsum(do ⊙ out) on the MXU-free VPU path (Pallas kernel),
    # computed once at part 0's tile geometry and shared by every part
    prm0 = params_list[0]
    sqp0 = prm0.num_q_tiles * prm0.block_q
    with profile_scope("ffa_bwd_delta"):
        delta = ffa_delta_pallas_dispatch(
            prm0, _head_major(out, sqp0), _head_major(do, sqp0)
        ).T[:sq]  # (sq, hq)

    dq_total = None
    dks, dvs = [], []
    for k, v, arrs, prm in zip(ks, vs, arrays_list, params_list):
        sqp = prm.num_q_tiles * prm.block_q
        skp = prm.num_k_tiles * prm.block_k
        q_t = _head_major(q, sqp)
        k_t = _head_major(k.astype(q.dtype), skp)
        v_t = _head_major(v.astype(q.dtype), skp)
        do_t = _head_major(do, sqp)
        # pad lse with -inf, delta with 0 for rows beyond sq
        lse_t = jnp.pad(
            lse, ((0, sqp - sq), (0, 0)), constant_values=float("-inf")
        ).T
        delta_t = jnp.pad(delta, ((0, sqp - sq), (0, 0))).T
        dq_arrs, dkv_arrs = _bwd_plan_slices(arrs)
        with profile_scope("ffa_bwd"):
            dq_t, dk_t, dv_t = ffa_bwd_pallas_dispatch(
                prm, dq_arrs, dkv_arrs, q_t, k_t, v_t, do_t, lse_t, delta_t
            )
        # dk/dv already per kv head (dkv kernel sums the GQA group); the
        # kernels emit fp32, so the casts are identity under HP reduce
        dq = dq_t.transpose(1, 0, 2)[:sq].astype(q.dtype)
        dq_total = dq if dq_total is None else dq_total + dq
        dks.append(dk_t.transpose(1, 0, 2)[: k.shape[0]].astype(k.dtype))
        dvs.append(dv_t.transpose(1, 0, 2)[: v.shape[0]].astype(v.dtype))
    return dq_total, tuple(dks), tuple(dvs), None


_multi_ffa.defvjp(_multi_ffa_fwd, _multi_ffa_bwd)


def _cast_any(x, ops, kind, axis_name):
    """cast_rows extended with the hierarchical tier
    (kind ``("hier", dcn_axis, ici_axis)``)."""
    if kind[0] == "hier":
        from ..comm.hier import hier_group_cast_rows

        return hier_group_cast_rows(
            x, ops[0], ops[1], ops[2], ops[3], kind[1], kind[2]
        )
    return cast_rows(x, ops, kind, axis_name)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def hp_group_cast(x, ops, kind, axis_name, shard_len, in_dtype):
    """GroupCast whose transpose (GroupReduce) runs in fp32 on the wire.

    Forward sends x in its own dtype (bf16 wire, unchanged) and upcasts the
    receive buffer to fp32; backward reduces the fp32 cotangent through the
    collective and casts to x's dtype only AFTER the cross-rank sum — the
    reference's high-precision partial-grad reduce (_reduce_partial_dkv,
    magi_attention/functional/dist_attn.py:2123, enabled by
    MAGI_ATTENTION_BACKWARD_HIGH_PRECISION_REDUCE). Doubles backward comm
    bytes; removes the cp-way low-precision summation error. XLA folds the
    fwd up/down-cast pair around the kernel's compute cast, so the fp32
    receive buffer never persists.
    """
    return _cast_any(x, ops, kind, axis_name).astype(jnp.float32)


def _hp_group_cast_fwd(x, ops, kind, axis_name, shard_len, in_dtype):
    return hp_group_cast(x, ops, kind, axis_name, shard_len, in_dtype), ops


def _hp_group_cast_bwd(kind, axis_name, shard_len, in_dtype, res, g):
    ops = res
    if kind[0] == "hier":
        # transpose via jax.vjp of the cast itself (same trick as the
        # ragged tier in reduce_rows) — no hand-maintained mirror plan
        zeros = jnp.zeros((shard_len, *g.shape[1:]), g.dtype)
        _, vjp_fn = jax.vjp(
            lambda z: _cast_any(z, ops, kind, axis_name), zeros
        )
        (red,) = vjp_fn(g)
    else:
        red = reduce_rows(g, ops, kind, axis_name, shard_len)
    return red.astype(in_dtype), None


hp_group_cast.defvjp(_hp_group_cast_fwd, _hp_group_cast_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def hp_group_cast_all(x, ops_list, kinds, axis_name, shard_len, in_dtype):
    """All stages of the GroupCast — local fp32 copy first, then one fp32
    receive buffer per stage — behind ONE custom VJP.

    Per-stage :func:`hp_group_cast` downcasts each reduced cotangent to the
    input dtype independently, so JAX's implicit cotangent accumulation
    still sums the (stages+1) dkv partials in bf16 — only approximately the
    reference's _reduce_partial_dkv, which keeps every partial fp32 and
    casts once (magi_attention/functional/dist_attn.py:2123; ADVICE r4).
    Spanning local shard + all stages here lets the backward reduce each
    stage's cotangent in fp32 on the wire, sum ALL partials (including the
    local shard's) in fp32, and cast to the input dtype exactly once.
    """
    parts = [x.astype(jnp.float32)]
    for ops, kind in zip(ops_list, kinds):
        parts.append(_cast_any(x, ops, kind, axis_name).astype(jnp.float32))
    return tuple(parts)


def _hp_all_fwd(x, ops_list, kinds, axis_name, shard_len, in_dtype):
    return (
        hp_group_cast_all(x, ops_list, kinds, axis_name, shard_len, in_dtype),
        ops_list,
    )


def _hp_all_bwd(kinds, axis_name, shard_len, in_dtype, res, g):
    ops_list = res
    total = g[0]  # local-shard cotangent, fp32 (part 0 is the fp32 upcast)
    for gi, ops, kind in zip(g[1:], ops_list, kinds):
        if kind[0] == "hier":
            # transpose via jax.vjp of the cast itself (same trick as the
            # ragged tier in reduce_rows) — no hand-maintained mirror plan
            zeros = jnp.zeros((shard_len, *gi.shape[1:]), gi.dtype)
            _, vjp_fn = jax.vjp(
                lambda z, o=ops, kk=kind: _cast_any(z, o, kk, axis_name),
                zeros,
            )
            (red,) = vjp_fn(gi)
        else:
            red = reduce_rows(gi, ops, kind, axis_name, shard_len)
        total = total + red
    return total.astype(in_dtype), None


hp_group_cast_all.defvjp(_hp_all_fwd, _hp_all_bwd)


def _ragged_arrays(s) -> tuple[jax.Array, ...]:
    """Whole-mesh arrays for the ragged_all_to_all GroupCast tier, derived
    from a stage's a2a plan (true per-pair sizes; the receive buffer lands
    directly in the solver's src-asc layout).

    Returns (send_row_idx (cp, send_cap), input_offsets (cp, cp),
    send_sizes (cp, cp), output_offsets (cp, cp), recv_sizes (cp, cp))."""
    counts = s.send_counts.astype(np.int64)  # [src][dst]
    cp = counts.shape[0]
    send_tot = counts.sum(axis=1)
    send_cap = max(int(send_tot.max()), 1)
    send_row_idx = np.zeros((cp, send_cap), dtype=np.int32)
    input_offsets = np.zeros((cp, cp), dtype=np.int32)
    for src in range(cp):
        off = 0
        for dst in range(cp):
            n = int(counts[src, dst])
            input_offsets[src, dst] = off
            if n:
                send_row_idx[src, off: off + n] = s.send_idx[src, dst, :n]
                off += n
    # [src][dst]: where src's segment lands at dst = sum of earlier sources
    output_offsets = (
        np.cumsum(counts, axis=0) - counts
    ).astype(np.int32)
    recv_sizes = counts.T.astype(np.int32)  # [dst][src]
    return (
        jnp.asarray(send_row_idx),
        jnp.asarray(input_offsets),
        jnp.asarray(s.send_counts.astype(np.int32)),
        jnp.asarray(output_offsets),
        jnp.asarray(recv_sizes),
    )


def _stack_plans(args: list[AttnArg], sq: int, sk: int, bq: int, bk: int,
                 policy_dq: tuple[int, int] | None = None,
                 policy_dkv: tuple[int, int] | None = None):
    """Per-rank FFA plans -> rank-stacked arrays padded to a common size.

    Returns ``(stacked_arrays, dims)`` where dims feeds
    ``DistAttnRuntime._ffa_params``. When the env bwd-tile overrides
    (MAGI_ATTENTION_FFA_BLOCK_*_D{Q,KV}) — or the auto-tile policy's
    per-pass picks (``policy_dq``/``policy_dkv``; env wins) — are active
    and compatible with this plan group's padded geometry, the stack
    carries 12 arrays (fwd6 + dq3 + dkv3) and dims includes the FFAParams
    override fields — so the distributed runtimes honor the same tuning
    flags as single-device ``ffa_attn``.
    """
    from ..kernels.ffa import assemble_bwd_overrides

    def build_stack(blq: int, blk: int, fields: tuple[str, ...]):
        plans = [
            build_ffa_plan(
                a.q_ranges, a.k_ranges, a.d_lo, a.d_hi, sq, sk, blq, blk
            )
            for a in args
        ]
        w = max(p.num_work for p in plans)
        wt = max(p.num_work_t for p in plans)
        padded = [pad_plan(p, w, wt) for p in plans]
        stacked = tuple(
            jnp.asarray(np.stack([getattr(p, f) for p in padded]))
            for f in fields
        )
        return stacked, plans[0].num_q_tiles, plans[0].num_k_tiles, w, wt

    fwd_fields = ("work_qt", "work_kt", "meta", "work_qt_t", "work_kt_t",
                  "meta_t")
    stacked, nqt, nkt, w, wt = build_stack(bq, bk, fwd_fields)

    def build_triple(blocks, kind):
        if kind == "dq":
            triple, _, _, w2, _ = build_stack(*blocks, fwd_fields[0:3])
            return triple, w2
        triple, _, _, _, wt2 = build_stack(*blocks, fwd_fields[3:6])
        return triple, wt2

    stacked, overrides = assemble_bwd_overrides(
        stacked, bq, bk, nqt, nkt, build_triple,
        policy_dq=policy_dq, policy_dkv=policy_dkv,
    )
    return stacked, (nqt, nkt, w, wt, overrides)


class DeferredTilePolicy:
    """Deferred auto-tile state shared by the CP runtimes.

    Auto-tile must score the VMEM guard with the REAL head dims and dtype
    (r3 advisor finding), which are only known at the first calc_attn —
    so plan building defers when the policy is active. Subclasses provide
    ``_build_plans(blk_q, blk_k)`` and ``_tile_geoms() -> (geoms, sq, sk)``.
    """

    def _init_tile_policy(self, block_q, block_k) -> None:
        from ..kernels import registry as kernel_registry

        self._plan_sig = None
        self._auto_tile_pending = False
        # set by the resilience ladder when the FFA path is abandoned for
        # the reference backend (resilience/fallback.py); wins over env
        self._backend_override: str | None = None
        # per-pass picks from the auto-tile policy, consumed by the
        # subclasses' _build_plans via _stack_plans (env overrides win)
        self._policy_bwd: tuple = (None, None)
        # telemetry signatures (computed lazily; mask sig is plan-stable)
        self._tel_mask_sig: str | None = None
        self._tel_env_sig: tuple | None = None
        if (
            block_q is None and block_k is None
            and not kernel_registry.tiles_pinned()
        ):
            from ..kernels.tile_policy import auto_tile_enabled

            self._auto_tile_pending = auto_tile_enabled()
        if not self._auto_tile_pending:
            self._build_plans(block_q, block_k)

    def _ensure_auto_plans(self, d: int, dv: int, itemsize: int) -> None:
        """Choose tiles with the real data signature; rebuild on change."""
        if not self._auto_tile_pending:
            return
        sig = (d, dv, itemsize)
        if self._plan_sig == sig:
            return
        from ..kernels.tile_policy import choose_blocks_per_pass_multi

        geoms, sq, sk = self._tile_geoms()
        try:
            (blk_q, blk_k), pol_dq, pol_dkv = choose_blocks_per_pass_multi(
                geoms, sq, sk, d, dv, itemsize
            )
        except Exception as e:
            # a failed VMEM scoring pass must not kill the step: the
            # clamped defaults are always lowerable (docs/resilience.md)
            if not env_resilience.is_fallback_enable():
                raise
            from ..resilience.fallback import record_resilience_event

            record_resilience_event(
                "recovered", "vmem_check",
                action_detail="default_blocks", error=type(e).__name__,
            )
            (blk_q, blk_k), pol_dq, pol_dkv = (None, None), None, None
        self._policy_bwd = (pol_dq, pol_dkv)
        self._build_plans(blk_q, blk_k)
        self._plan_sig = sig

    # -- observatory signatures (telemetry/store.py join keys) ----------

    def _policy_key(self) -> dict:
        """The calc_attn registry/measurement key: mask-class signature x
        mesh x env snapshot. Keyed exactly like store.ingest_event's
        calc_attn measurement rows, so the registry's measured-history
        lookup joins against this runtime's own recorded steps."""
        return {
            "mask_sig": self._mask_signature(),
            "mesh_sig": self._mesh_signature(),
            "env_sig": self._env_signature(),
        }

    def _mask_signature(self) -> str:
        """Digest of the mask-class geometry (slice arrays + shard lens);
        plan-stable, so computed once per runtime."""
        sig = self._tel_mask_sig
        if sig is None:
            geoms, sq, sk = self._tile_geoms()
            h = hashlib.md5(repr((sq, sk, len(geoms))).encode())
            for g in geoms:
                for a in g:
                    h.update(np.ascontiguousarray(a).tobytes())
            sig = h.hexdigest()[:16]
            self._tel_mask_sig = sig
        return sig

    def _mesh_signature(self) -> str:
        return repr((
            tuple(sorted(self.mesh.shape.items())),
            self.cp_axis,
            getattr(self, "head_axis", None),
        ))

    def _env_signature(self) -> str:
        """Digest of the behavior-affecting env snapshot (memoized per
        snapshot value — flips mid-life re-key the policy lookups)."""
        snap = env_general.snapshot_env()
        cached = self._tel_env_sig
        if cached is not None and cached[0] == snap:
            return cached[1]
        sig = hashlib.md5(repr(snap).encode()).hexdigest()[:16]
        self._tel_env_sig = (snap, sig)
        return sig

    @property
    def backend(self) -> str:
        """Kernel backend via the registry's ``calc_attn`` decision: an
        explicit MAGI_ATTENTION_KERNEL_BACKEND pins it, otherwise the
        policy cache / measured history / the 'ffa' default decide. A
        resilience-ladder override (sticky degradation to the reference
        path) wins over everything."""
        if self._backend_override is not None:
            return self._backend_override
        from ..kernels import registry as kernel_registry

        return kernel_registry.calc_attn_backend(self._policy_key())


@dataclass(eq=False)
class DistAttnRuntime(DeferredTilePolicy):
    """Compiled-plan holder for one (mask, mesh, config) combination."""

    comm_meta: CommMeta
    calc_meta: CalcMeta
    mesh: Mesh
    cp_axis: str | tuple[str, str]  # 2-tuple = 2D (dcn, ici) cp mesh
    softmax_scale: float | None = None
    softcap: float = 0.0
    block_q: int | None = None
    block_k: int | None = None
    use_overlap: bool | None = None  # None -> overlap iff >1 stage
    # tensor parallelism: shard the head dim over this mesh axis (composes
    # with cp — the reference delegates TP to the host framework, SURVEY
    # §2.8; on TPU the attention itself runs TP-sharded in the same
    # shard_map, no host framework needed)
    head_axis: str | None = None

    def __post_init__(self) -> None:
        cm, km = self.comm_meta, self.calc_meta
        self.cp_size = len(km.host_args)
        kv_shard = km.kv_shard_len
        self.num_stages = len(cm.kv_stages)
        if self.use_overlap is None:
            self.use_overlap = self.num_stages > 1

        self._init_tile_policy(self.block_q, self.block_k)

        # comm arrays (host-planned, stacked over ranks)
        self._hier = (
            isinstance(self.cp_axis, tuple)
            and env_comm.is_hierarchical_comm_enable()
            and cm.kv_host_ranges is not None
        )
        if self._hier:
            # each stage runs the 2-phase (DCN x ICI) cast; the final
            # receive buffer is flat-identical (comm/hier.py), so CalcMeta
            # is untouched. Solver-built plans (s.hier_plan, emitted when
            # the solver knew the 2D mesh shape) are used directly — they
            # were cached and verified with the rest of the plan; stages
            # planned without a mesh shape are re-planned here from their
            # transfer tables (identical construction)
            from ..comm.hier import make_hier_group_cast_plan

            dcn_axis, ici_axis = self.cp_axis
            n_outer = self.mesh.shape[dcn_axis]
            n_inner = self.mesh.shape[ici_axis]
            self._hier_arrays = []
            for st, s in enumerate(cm.kv_stages):
                plan = s.hier_plan
                if (
                    plan is None
                    or plan.n_outer != n_outer
                    or plan.n_inner != n_inner
                ):
                    plan = make_hier_group_cast_plan(
                        s.transfer_table, cm.kv_host_ranges, n_outer,
                        n_inner, alignment=128, r_max=s.r_max,
                        shard_len=kv_shard,
                    )
                self._hier_arrays.append(tuple(
                    jnp.asarray(a) for a in (
                        plan.a_send_idx, plan.a_recv_sel,
                        plan.b_send_idx, plan.b_recv_sel,
                    )
                ))
        # unified per-stage cast operand tuples (flat/pp: 2 arrays; hier: 4)
        # + per-stage static lowering descriptors (host-chosen, cheapest
        # wire volume — see GroupCollectiveArg.lowering)
        if self._hier:
            self._cast_ops = self._hier_arrays
            self._cast_kinds = [("hier",)] * len(self._hier_arrays)
        else:
            # per-stage tier from the solver's AUTO choice (s.lowering);
            # the ragged tier only appears there when the backend supports
            # it (env_comm.is_ragged_grpcoll_enable at plan time)
            self._cast_ops = []
            self._cast_kinds = []
            for s in cm.kv_stages:
                if s.lowering == "ragged":
                    self._cast_ops.append(_ragged_arrays(s))
                    self._cast_kinds.append(("ragged", s.r_max))
                elif s.lowering == "ppermute":
                    self._cast_ops.append(
                        (jnp.asarray(s.pp_send_idx), jnp.asarray(s.pp_recv_sel))
                    )
                    self._cast_kinds.append(
                        ("pp", s.pp_deltas, s.pp_caps, self.cp_size)
                    )
                else:
                    self._cast_ops.append(
                        (jnp.asarray(s.send_idx), jnp.asarray(s.recv_sel))
                    )
                    self._cast_kinds.append(("a2a",))

        # merged slice arrays for the jnp (sdpa) backend path: (cp, N, 2)/(cp, N)
        n_max = max(a.num_slices for a in km.merged_args) or 1
        padded = [a.pad_to(n_max) for a in km.merged_args]
        self._merged_slices = tuple(
            jnp.asarray(np.stack([getattr(a, f) for a in padded]))
            for f in ("q_ranges", "k_ranges", "d_lo", "d_hi")
        )

    def _build_plans(self, blk_q, blk_k) -> None:
        """Stack the per-rank FFA plans for the chosen (or default) tiles.

        May run inside a jit trace (auto-tile defers to the first
        calc_attn), so the plan constants are forced concrete — caching
        trace-local tracers on ``self`` would leak them into later traces.
        """
        with jax.ensure_compile_time_eval():
            with telemetry.stage_timer("build_plans"):
                self._build_plans_impl(blk_q, blk_k)

    def _build_plans_impl(self, blk_q, blk_k) -> None:
        from ..kernels.ffa import default_blocks

        self._tel_plan_groups = None  # recomputed per plan build
        km = self.calc_meta
        shard = km.shard_len
        kv_shard = km.kv_shard_len
        total_recv = sum(km.recv_len_per_stage)
        bq, bk = default_blocks(shard, kv_shard + total_recv, blk_q, blk_k)
        self._bq, self._bk = bq, bk
        pol_dq, pol_dkv = getattr(self, "_policy_bwd", (None, None))

        # merged (no-overlap) plan
        self._merged_arrays, self._merged_dims = _stack_plans(
            km.merged_args, shard, kv_shard + total_recv, bq, bk,
            policy_dq=pol_dq, policy_dkv=pol_dkv,
        )

        if self.use_overlap:
            # stage geometries clamp bk; policy picks that don't divide a
            # stage's padded grid silently inherit (resolve gate)
            self._host_arrays, self._host_dims = _stack_plans(
                km.host_args, shard, kv_shard,
                bq, min(bk, _ceil_to(kv_shard, 128)),
                policy_dq=pol_dq, policy_dkv=pol_dkv,
            )
            self._stage_arrays = []
            self._stage_dims = []
            for st in range(self.num_stages):
                rl = km.recv_len_per_stage[st]
                sa, sdims = _stack_plans(
                    km.remote_args_per_stage[st], shard, rl,
                    bq, min(bk, _ceil_to(rl, 128)),
                    policy_dq=pol_dq, policy_dkv=pol_dkv,
                )
                self._stage_arrays.append(sa)
                self._stage_dims.append(sdims)
        if telemetry.enabled():
            self._plan_group_stats()

    def _plan_group_stats(self) -> list[dict]:
        """Padded-grid work accounting per executed kernel group, cached for
        the attn_step record (the per-plan ``ffa_plan`` records carry the
        same numbers at build; caching here lets every step report estimated
        vs executed work without re-walking the plans)."""
        km = self.calc_meta
        cp = self.cp_size

        def grp(name, dims, bq, bk):
            w = dims[2]  # rank-uniform padded work-item count
            return {
                "name": name, "block_q": bq, "block_k": bk, "num_work": w,
                "padded_elems": cp * w * bq * bk,
            }

        bq, bk = self._bq, self._bk
        if self.use_overlap:
            groups = [grp("host", self._host_dims, bq,
                          min(bk, _ceil_to(km.kv_shard_len, 128)))]
            for st, d in enumerate(self._stage_dims):
                rl = km.recv_len_per_stage[st]
                groups.append(
                    grp(f"stage{st}", d, bq, min(bk, _ceil_to(rl, 128)))
                )
        else:
            groups = [grp("merged", self._merged_dims, bq, bk)]
        self._tel_plan_groups = groups
        self._tel_band_elems = sum(
            telemetry.band_area(a.q_ranges, a.k_ranges, a.d_lo, a.d_hi)
            for a in km.merged_args
        )
        return groups

    def _attn_step_payload(self, q, k, v) -> dict:
        """One attention step's telemetry payload (callers gate on
        ``telemetry.enabled()``). Comm rows were planned dtype-blind; bytes
        resolve here where head dims and dtypes are known — k and v rows
        ride one fused collective, so a wire row carries both."""
        sq, hq, dh = q.shape
        _, hk, dv = v.shape
        row_bytes = hk * dh * k.dtype.itemsize + hk * dv * v.dtype.itemsize
        exec_map = {"pp": "ppermute", "a2a": "a2a", "ragged": "ragged",
                    "hier": "hier"}
        stages = []
        payload_total = wire_total = 0
        for st, s in enumerate(self.comm_meta.kv_stages):
            d = s.telemetry_dict(executed=exec_map[self._cast_kinds[st][0]])
            d["stage"] = st
            d["xprof_scope"] = f"group_cast_stage{st}"
            d["payload_bytes"] = d["payload_rows"] * row_bytes
            d["wire_bytes"] = d["wire_rows"] * row_bytes
            d["padding_bytes"] = d["padding_rows"] * row_bytes
            payload_total += d["payload_bytes"]
            wire_total += d["wire_bytes"]
            stages.append(d)
        payload = {
            "backend": self.backend,
            # observatory join keys (telemetry/store.py _ATTN_KEY_FIELDS)
            "mask_sig": self._mask_signature(),
            "mesh_sig": self._mesh_signature(),
            "env_sig": self._env_signature(),
            "q_shape": list(q.shape),
            "kv_shape": list(v.shape),
            "cp_size": self.cp_size,
            "overlap_degree": self.num_stages,
            "use_overlap": self.use_overlap,
            "seqlen_q_shard": sq,
            "heads_q": hq, "head_dim": dh, "heads_kv": hk, "head_dim_v": dv,
            "dtype": q.dtype.name,
            "row_bytes": row_bytes,
            "stages": stages,
            "payload_bytes_total": payload_total,
            "wire_bytes_total": wire_total,
            "padding_bytes_total": wire_total - payload_total,
        }
        # kernel-plan work accounting (absent on the sdpa backends when the
        # deferred auto-tile policy never ran, i.e. no FFA plans exist)
        if getattr(self, "_bq", None) is not None:
            if getattr(self, "_tel_plan_groups", None) is None:
                self._plan_group_stats()  # telemetry enabled after build
            band = self._tel_band_elems
            padded = sum(g["padded_elems"] for g in self._tel_plan_groups)
            # backward execution mode the dispatch will pick for this
            # geometry (fused one-pass vs split dq+dkv) — resolved on the
            # representative (host/merged) plan dims
            dims0 = self._host_dims if self.use_overlap else self._merged_dims
            prm0 = self._ffa_params(dims0, 1.0, hq // hk)
            bwd_mode = resolved_bwd_mode(
                prm0, prm0.num_q_tiles * prm0.block_q, dh, dv,
                q.dtype.itemsize,
            )
            payload.update(
                block_q=self._bq, block_k=self._bk,
                plan_groups=self._tel_plan_groups,
                band_elems=band,
                padded_elems=padded,
                # fwd FLOPs, FlashAttention-2 convention (perf_report.py)
                est_flops_fwd=4 * band * dh * hq,
                padded_flops_fwd=4 * padded * dh * hq,
                bwd_mode=bwd_mode,
                # the mode decision's registry/store key + modeled cost, so
                # the drift layer can compare choose_bwd_mode's prediction
                # against this step's measured wall time
                bwd_key=list(
                    bwd_mode_key(prm0, dh, dv, q.dtype.itemsize)
                ),
                bwd_cost=bwd_modeled_cost(
                    prm0, dh, dv, q.dtype.itemsize, bwd_mode
                ),
            )
        return payload

    def _tile_geoms(self):
        # per-mask tile choice scored on the merged per-rank geometries
        # (every rank runs the max-W padded grid)
        km = self.calc_meta
        return (
            [
                (a.q_ranges, a.k_ranges, a.d_lo, a.d_hi)
                for a in km.merged_args
            ],
            km.shard_len,
            km.kv_shard_len + sum(km.recv_len_per_stage),
        )

    def _kind(self, stage: int):
        """Static lowering descriptor for one stage — the ONE place the
        hier-vs-flat branch is decided (``_cast_any`` dispatches on it)."""
        if self._hier:
            dcn_axis, ici_axis = self.cp_axis
            return ("hier", dcn_axis, ici_axis)
        return self._cast_kinds[stage]

    def _axis(self):
        return None if self._hier else self.cp_axis

    def _cast(self, x, ops, stage: int = 0):
        """One stage's GroupCast inside shard_map (flat / pp / hierarchical)."""
        with profile_scope(f"group_cast_stage{stage}"):
            return _cast_any(
                x, tuple(o[0] for o in ops), self._kind(stage), self._axis()
            )

    def _cast_kv(self, k, v, ops, stage: int = 0):
        """Fused K|V GroupCast: one collective for both tensors (the
        reference's asymmetric-KV comm fuses along head_dim the same way,
        comm_meta.py:588-591 — valid for any d_k/d_v since rows coincide).
        HP reduce does NOT route here — it uses :meth:`_hp_parts_kv`, whose
        fused all-stage VJP is the only correct fp32 accumulation."""
        if k.dtype == v.dtype and k.shape[1] == v.shape[1]:
            kv = jnp.concatenate([k, v], axis=-1)
            kv_r = self._cast(kv, ops, stage)
            return kv_r[..., : k.shape[-1]], kv_r[..., k.shape[-1]:]
        return self._cast(k, ops, stage), self._cast(v, ops, stage)

    def _hp_parts_kv(self, k, v, cast_ops):
        """fp32 (local, *per-stage) parts of k and v under HP reduce.

        Routes through the fused :func:`hp_group_cast_all` so the backward
        sums EVERY dkv partial — local shard included — in fp32 and
        downcasts once (ADVICE r4). K|V fuse into one collective when rows
        coincide, as in :meth:`_cast_kv`."""
        kinds = tuple(self._kind(st) for st in range(len(cast_ops)))
        opsl = tuple(tuple(a[0] for a in ops) for ops in cast_ops)
        with profile_scope("group_cast_hp_all"):
            if k.dtype == v.dtype and k.shape[1] == v.shape[1]:
                kv = jnp.concatenate([k, v], axis=-1)
                parts = hp_group_cast_all(
                    kv, opsl, kinds, self._axis(), kv.shape[0], kv.dtype.name
                )
                return (
                    [p[..., : k.shape[-1]] for p in parts],
                    [p[..., k.shape[-1]:] for p in parts],
                )
            kp = hp_group_cast_all(
                k, opsl, kinds, self._axis(), k.shape[0], k.dtype.name
            )
            vp = hp_group_cast_all(
                v, opsl, kinds, self._axis(), v.shape[0], v.dtype.name
            )
            return list(kp), list(vp)

    # ------------------------------------------------------------------

    def _ffa_params(
        self, dims, scale, group, emit_max_logits: bool = False
    ) -> FFAParams:
        nqt, nkt, w, wt, overrides = dims
        return FFAParams(
            num_work=w, num_work_t=wt, num_q_tiles=nqt, num_k_tiles=nkt,
            block_q=self._bq, block_k=self._bk, **overrides,
            softmax_scale=scale, softcap=self.softcap, group=group,
            interpret=_should_interpret(),
            # the max-logits output costs an (hq, sqp, 128) fp32 HBM write
            # per kernel call — emitted only when the caller asks
            emit_max_logits=emit_max_logits,
        )

    @instrument_scope(name="DistAttnRuntime.calc_attn")
    def calc_attn(
        self,
        q: jax.Array,
        k: jax.Array,
        v: jax.Array,
        return_max_logits: bool = False,
    ):
        """Distributed attention over dispatched tensors.

        Args:
            q/k/v: ``(cp*shard, h, d)`` dispatched (permuted) layout, sharded
                over the cp mesh axis on dim 0.
            return_max_logits: also return the per-head max logit ``[hq]``
                fp32, all-reduced MAX across the cp axis (ref
                dist_attn.py:550 reduce_max_logits) — replicated over cp,
                sharded over head_axis when set.

        Returns:
            (out ``(cp*shard, hq, dv)``, lse ``(cp*shard, hq)`` fp32), same
            sharded layout; plus max_logits when requested.
        """
        impl = self._calc_attn_impl
        if env_resilience.is_resilience_active():
            # guarded path: injection recovery + numeric sentinels
            # (resilience/fallback.py); never reached with the flags off
            from ..resilience.fallback import run_calc_attn

            impl = partial(run_calc_attn, self)
        if not telemetry.enabled():
            return impl(q, k, v, return_max_logits)
        # wall_ms spans dispatch + (on first call) trace/compile; per-stage
        # DEVICE time lives in the xprof spans the stages' xprof_scope
        # fields name (docs/observability.md)
        with telemetry.stage_timer("calc_attn"):
            result = impl(q, k, v, return_max_logits)
        wall_ms = telemetry.get_collector().gauges.get(
            "time.calc_attn.last_ms"
        )
        telemetry.record_event(
            "attn_step",
            xprof_scope="DistAttnRuntime.calc_attn",
            wall_ms=wall_ms,
            **self._attn_step_payload(q, k, v),
        )
        return result

    def _calc_attn_impl(
        self,
        q: jax.Array,
        k: jax.Array,
        v: jax.Array,
        return_max_logits: bool = False,
    ):
        sq, hq, dh = q.shape
        _, hk, dv = v.shape
        group = hq // hk
        if self.head_axis is not None:
            tp = self.mesh.shape[self.head_axis]
            if hq % tp or hk % tp:
                raise ValueError(
                    f"head_axis={self.head_axis!r} (size {tp}) must divide "
                    f"both num_heads_q ({hq}) and num_heads_kv ({hk}) — "
                    f"GQA kv heads shard over TP too"
                )
        scale = (
            float(dh) ** -0.5
            if self.softmax_scale is None
            else self.softmax_scale
        )
        axis = self.cp_axis
        # data spec: seq dim over cp, head dim over tp (when given)
        spec = P(axis, self.head_axis)
        ml_spec = P(self.head_axis)
        out_specs = (
            (spec, spec, ml_spec) if return_max_logits else (spec, spec)
        )

        if self.backend in ("sdpa", "sdpa_online"):
            # jnp fake-backend path (fp32/fp64-exact distributed testing,
            # mirroring the reference's sdpa backend strategy): merged concat
            # buffer + dense band-mask replay, AD end-to-end
            from ..kernels.sdpa import dense_max_logits, sdpa_attn
            from ..kernels.sdpa_online import sdpa_online_attn

            dense_fn = sdpa_attn if self.backend == "sdpa" else sdpa_online_attn
            softcap = self.softcap

            def f(q, k, v, cast_ops, slices):
                parts_k, parts_v = [k], [v]
                for st, ops in enumerate(cast_ops):
                    kr, vr = self._cast_kv(k, v, ops, st)
                    parts_k.append(kr)
                    parts_v.append(vr)
                k_all = jnp.concatenate(parts_k, axis=0)
                v_all = jnp.concatenate(parts_v, axis=0)
                qr, kr, lo, hi = (a[0] for a in slices)
                out, lse = dense_fn(
                    q, k_all, v_all, qr, kr, None,
                    softmax_scale=scale, softcap=softcap,
                    d_lo=lo, d_hi=hi,
                )
                # lse is non-differentiable on the ffa backend (custom VJP
                # drops its cotangent); keep backends in agreement
                lse = jax.lax.stop_gradient(lse)
                if return_max_logits:
                    ml = dense_max_logits(
                        q, k_all, qr, kr, None,
                        softmax_scale=scale, softcap=softcap,
                        d_lo=lo, d_hi=hi,
                    )
                    return out, lse, jax.lax.pmax(jax.lax.stop_gradient(ml), axis)
                return out, lse

            fn = shard_map(
                f,
                mesh=self.mesh,
                in_specs=(spec, spec, spec,
                          [tuple(P(axis) for _ in ops)
                           for ops in self._cast_ops],
                          tuple(P(axis) for _ in self._merged_slices)),
                out_specs=out_specs,
                check_vma=False,
            )
            return fn(q, k, v, self._cast_ops, self._merged_slices)

        # auto-tile runs HERE (not __post_init__) so the VMEM guard sees
        # the real head dims and dtype (r3 advisor finding)
        self._ensure_auto_plans(dh, dv, q.dtype.itemsize)

        # fp32 wire reduce for partial dkv (ref decision at dist_attn.py
        # :243-248; default off there and here). The sdpa/jnp backends keep
        # plain AD (they are fp32-exact test backends already).
        hp_bwd = env_comm.is_bwd_high_precision_reduce_enable()

        if not self.use_overlap:
            params = self._ffa_params(
                self._merged_dims, scale, group, return_max_logits
            )

            def f(q, k, v, cast_ops, arrays):
                if hp_bwd:
                    # fused all-stage hp cast: receive buffers AND the
                    # local shard are fp32, and all dkv partials sum in
                    # fp32 with one final downcast (ADVICE r4)
                    kv_parts_k, kv_parts_v = self._hp_parts_kv(k, v, cast_ops)
                else:
                    kv_parts_k, kv_parts_v = [k], [v]
                    for st, ops in enumerate(cast_ops):
                        kr, vr = self._cast_kv(k, v, ops, st)
                        kv_parts_k.append(kr)
                        kv_parts_v.append(vr)
                k_all = jnp.concatenate(kv_parts_k, axis=0)
                v_all = jnp.concatenate(kv_parts_v, axis=0)
                local_arrays = tuple(a[0] for a in arrays)
                if return_max_logits:
                    out, lse, ml = ffa_attn_with_plan(
                        q, k_all, v_all, local_arrays, params,
                        return_max_logits=True,
                    )
                    return out, lse, jax.lax.pmax(jax.lax.stop_gradient(ml), axis)
                return ffa_attn_with_plan(q, k_all, v_all, local_arrays, params)

            fn = shard_map(
                f,
                mesh=self.mesh,
                in_specs=(spec, spec, spec,
                          [tuple(P(axis) for _ in ops)
                           for ops in self._cast_ops],
                          tuple(P(axis) for _ in self._merged_arrays)),
                out_specs=out_specs,
                check_vma=False,
            )
            return fn(q, k, v, self._cast_ops, self._merged_arrays)

        # multi-stage overlap path
        host_params = self._ffa_params(
            self._host_dims, scale, group, return_max_logits
        )
        stage_params = [
            self._ffa_params(d, scale, group, return_max_logits)
            for d in self._stage_dims
        ]

        all_params = (host_params, *stage_params)

        def f(q, k, v, cast_ops, host_arrays, stage_arrays):
            # issue every stage's collective up front: no data dependence on
            # compute, XLA overlaps them with the host + earlier-stage kernels
            if hp_bwd:
                # fused all-stage hp cast (local shard fp32 too): every dkv
                # partial sums in fp32, one downcast — _multi_ffa is
                # dtype-polymorphic per part, so this costs residual HBM
                # only (the flag's documented price), not compute dtype
                ks, vs = self._hp_parts_kv(k, v, cast_ops)
            else:
                ks, vs = [k], [v]
                for st, ops in enumerate(cast_ops):
                    kr, vr = self._cast_kv(k, v, ops, st)
                    ks.append(kr)
                    vs.append(vr)
            arrays_list = (tuple(a[0] for a in host_arrays),) + tuple(
                tuple(a[0] for a in sa) for sa in stage_arrays
            )
            out, lse, ml = _multi_ffa(
                q, tuple(ks), tuple(vs), arrays_list, all_params
            )
            if return_max_logits:
                return out, lse, jax.lax.pmax(jax.lax.stop_gradient(ml), axis)
            return out, lse

        fn = shard_map(
            f,
            mesh=self.mesh,
            in_specs=(spec, spec, spec,
                      [tuple(P(axis) for _ in ops)
                       for ops in self._cast_ops],
                      tuple(P(axis) for _ in self._host_arrays),
                      [tuple(P(axis) for _ in sa) for sa in self._stage_arrays]),
            out_specs=out_specs,
            check_vma=False,
        )
        return fn(q, k, v, self._cast_ops,
                  self._host_arrays, self._stage_arrays)


def dist_attn_func(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    runtime: DistAttnRuntime,
    return_max_logits: bool = False,
):
    """Functional entry (ref dist_attn.py:3714): (out, lse[, max_logits])
    over dispatched tensors. Precision override via MAGI_ATTENTION_PRECISION."""
    if env_general.precision() == "bf16":
        q, k, v = (t.astype(jnp.bfloat16) for t in (q, k, v))
    return runtime.calc_attn(q, k, v, return_max_logits=return_max_logits)


def _ceil_to(x: int, m: int) -> int:
    return max(m, -(-x // m) * m)
