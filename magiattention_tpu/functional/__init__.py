"""Functional runtime: kernel entry points, dispatch ops, CP engine."""
