"""Single-device flex-flash-attention entry point.

Ref API surface: magi_attention/functional/flex_flash_attn.py:1258 — same
contract (varlen-packed q/k/v + slice metadata arrays -> (out, AttnForwardMeta))
re-designed for JAX: backends are pure functions dispatched by env flag or
argument; differentiation is jax AD (sdpa backends) or a custom VJP pairing the
Pallas fwd/bwd kernels (ffa backend).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..common.forward_meta import AttnForwardMeta
from ..env import general as env_general
from .. import env as _env


def _as_range_array(ranges: Any, name: str) -> jax.Array:
    """Accept AttnRanges | array-like -> (N, 2) int32 jnp array."""
    if hasattr(ranges, "to_array"):
        arr = ranges.to_array()
    else:
        arr = np.asarray(ranges, dtype=np.int32)
    arr = jnp.asarray(arr, dtype=jnp.int32)
    if arr.ndim != 2 or arr.shape[-1] != 2:
        raise ValueError(f"{name} must have shape (N, 2), got {arr.shape}")
    return arr


def flex_flash_attn_func(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_ranges: Any,
    k_ranges: Any,
    attn_type_map: Any = None,
    *,
    softmax_scale: float | None = None,
    softcap: float = 0.0,
    sink: jax.Array | None = None,
    deterministic: bool = False,
    backend: str | None = None,
    return_max_logits: bool = False,
) -> tuple[jax.Array, AttnForwardMeta]:
    """Compute flex attention on one device.

    Args:
        q: ``[sq, hq, d]`` (varlen packed, no batch dim).
        k/v: ``[sk, hk, d] / [sk, hk, dv]``; ``hq % hk == 0`` (GQA).
        q_ranges/k_ranges: ``(N, 2)`` int32 slice ranges (AttnRanges accepted).
            Padding slices have ``q_start >= q_end`` and are skipped.
        attn_type_map: ``(N,)`` int32 (0=FULL 1=CAUSAL 2=INVCAUSAL 3=BICAUSAL);
            None = all FULL.
        backend: ffa | sdpa | sdpa_online; None = env
            ``MAGI_ATTENTION_KERNEL_BACKEND`` (default ffa).

    Returns:
        (out ``[sq, hq, dv]``, AttnForwardMeta(lse=``[sq, hq]`` fp32)).
    """
    qr = _as_range_array(q_ranges, "q_ranges")
    kr = _as_range_array(k_ranges, "k_ranges")
    if attn_type_map is None:
        tmap = jnp.zeros((qr.shape[0],), dtype=jnp.int32)
    else:
        tmap = jnp.asarray(np.asarray(attn_type_map), dtype=jnp.int32).reshape(-1)

    if backend is None:
        backend = env_general.kernel_backend()

    precision = env_general.precision()
    compute_dtype = jnp.float32
    if precision == "bf16":
        q, k, v = (t.astype(jnp.bfloat16) for t in (q, k, v))

    if backend == "sdpa":
        from ..kernels.sdpa import sdpa_attn

        out, lse = sdpa_attn(
            q, k, v, qr, kr, tmap,
            softmax_scale=softmax_scale, softcap=softcap,
            compute_dtype=compute_dtype,
        )
    elif backend == "sdpa_online":
        from ..kernels.sdpa_online import sdpa_online_attn

        out, lse = sdpa_online_attn(
            q, k, v, qr, kr, tmap,
            softmax_scale=softmax_scale, softcap=softcap,
            compute_dtype=compute_dtype,
        )
    elif backend == "ffa":
        from ..kernels.ffa import ffa_attn

        out, lse = ffa_attn(
            q, k, v, qr, kr, tmap,
            softmax_scale=softmax_scale, softcap=softcap,
        )
    else:
        raise ValueError(f"unknown kernel backend: {backend}")

    meta = AttnForwardMeta(lse=lse)
    if return_max_logits:
        # max logit per head; derive from lse lower bound is wrong — compute
        # via the sdpa path only when explicitly requested (testing aid).
        meta.max_logits = jnp.max(lse, axis=0)
    return out, meta
