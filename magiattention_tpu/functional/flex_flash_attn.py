"""Single-device flex-flash-attention entry point.

Ref API surface: magi_attention/functional/flex_flash_attn.py:1258 — same
contract (varlen-packed q/k/v + slice metadata arrays -> (out, AttnForwardMeta))
re-designed for JAX: backends are pure functions dispatched by env flag or
argument; differentiation is jax AD (sdpa backends) or a custom VJP pairing the
Pallas fwd/bwd kernels (ffa backend).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..common.forward_meta import AttnForwardMeta
from functools import partial

from ..env import general as env_general


def _as_range_array(ranges: Any, name: str) -> np.ndarray:
    """Accept AttnRanges | array-like -> (N, 2) int32 HOST array.

    Slice metadata must stay concrete even when the surrounding function is
    jit-traced (it parameterizes the kernel grid); converting to jnp here
    would stage it into the trace and break the host planners."""
    if hasattr(ranges, "to_array"):
        arr = ranges.to_array()
    else:
        arr = np.asarray(ranges)
    arr = np.asarray(arr, dtype=np.int32)
    if arr.ndim != 2 or arr.shape[-1] != 2:
        raise ValueError(f"{name} must have shape (N, 2), got {arr.shape}")
    return arr


def flex_flash_attn_func(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_ranges: Any,
    k_ranges: Any,
    attn_type_map: Any = None,
    *,
    softmax_scale: float | None = None,
    softcap: float = 0.0,
    sink: jax.Array | None = None,
    sink_layout: str = "sh",
    deterministic: bool = False,
    backend: str | None = None,
    return_max_logits: bool = False,
    d_lo: Any = None,
    d_hi: Any = None,
) -> tuple[jax.Array, AttnForwardMeta]:
    """Compute flex attention on one device.

    Args:
        q: ``[sq, hq, d]`` (varlen packed, no batch dim).
        k/v: ``[sk, hk, d] / [sk, hk, dv]``; ``hq % hk == 0`` (GQA).
        q_ranges/k_ranges: ``(N, 2)`` int32 slice ranges (AttnRanges accepted).
            Padding slices have ``q_start >= q_end`` and are skipped.
        attn_type_map: ``(N,)`` int32 (0=FULL 1=CAUSAL 2=INVCAUSAL 3=BICAUSAL);
            None = all FULL.
        backend: ffa | sdpa | sdpa_online; None = env
            ``MAGI_ATTENTION_KERNEL_BACKEND`` (default ffa).

    Returns:
        (out ``[sq, hq, dv]``, AttnForwardMeta(lse=``[sq, hq]`` fp32)).
    """
    qr = _as_range_array(q_ranges, "q_ranges")
    kr = _as_range_array(k_ranges, "k_ranges")
    if attn_type_map is None:
        # host constant (jnp.zeros would trace under jit, but the slice
        # metadata must stay concrete — it parameterizes the kernel grid)
        tmap = np.zeros((qr.shape[0],), dtype=np.int32)
    else:
        tmap = np.asarray(attn_type_map, dtype=np.int32).reshape(-1)

    if backend is None:
        backend = env_general.kernel_backend()

    precision = env_general.precision()
    compute_dtype = jnp.float32
    if precision == "bf16":
        q, k, v = (t.astype(jnp.bfloat16) for t in (q, k, v))

    if backend == "sdpa":
        from ..kernels.sdpa import sdpa_attn

        out, lse = sdpa_attn(
            q, k, v, qr, kr, tmap,
            softmax_scale=softmax_scale, softcap=softcap,
            compute_dtype=compute_dtype, d_lo=d_lo, d_hi=d_hi,
        )
    elif backend == "sdpa_online":
        from ..kernels.sdpa_online import sdpa_online_attn

        out, lse = sdpa_online_attn(
            q, k, v, qr, kr, tmap,
            softmax_scale=softmax_scale, softcap=softcap,
            compute_dtype=compute_dtype, d_lo=d_lo, d_hi=d_hi,
        )
    elif backend == "ffa":
        if sink is not None:
            out, lse = _ffa_with_sink(
                q, k, v, sink, qr, kr, tmap,
                softmax_scale=softmax_scale, softcap=softcap,
                d_lo=d_lo, d_hi=d_hi, sink_layout=sink_layout,
            )
        else:
            from ..kernels.ffa import ffa_attn

            res = ffa_attn(
                q, k, v, qr, kr, tmap,
                softmax_scale=softmax_scale, softcap=softcap,
                d_lo=d_lo, d_hi=d_hi, return_max_logits=return_max_logits,
            )
            if return_max_logits:
                out, lse, max_logits = res
            else:
                out, lse = res
    else:
        raise ValueError(f"unknown kernel backend: {backend}")

    if sink is not None and backend in ("sdpa", "sdpa_online"):
        # jnp backends are differentiated end-to-end by jax AD, so folding
        # the sink in afterwards is gradient-exact automatically
        from .sink import apply_sink_fwd

        out, lse = apply_sink_fwd(out, lse, sink, sink_layout)

    meta = AttnForwardMeta(lse=lse)
    if return_max_logits:
        # per-head max of the (scaled, softcapped) REAL attention logits —
        # the fwd kernel's tracked softmax max (ref forward_meta.py:21); the
        # sink's virtual logit is not included. The jnp backends use the
        # dense oracle.
        if backend == "ffa" and sink is None:
            meta.max_logits = max_logits
        else:
            from ..kernels.sdpa import dense_max_logits

            meta.max_logits = dense_max_logits(
                q, k, qr, kr, tmap,
                softmax_scale=softmax_scale, softcap=softcap,
                d_lo=d_lo, d_hi=d_hi,
            )
    return out, meta


# ---------------------------------------------------------------------------
# ffa + sink (custom VJP: kernel backward against the sink-adjusted lse)
# ---------------------------------------------------------------------------


def _ffa_with_sink(
    q, k, v, sink, qr, kr, tmap, *, softmax_scale, softcap,
    d_lo=None, d_hi=None, sink_layout="sh",
):
    from ..kernels.ffa import (
        FFAParams,
        _should_interpret,
        apply_bwd_overrides,
        default_blocks,
        get_ffa_plan,
        plan_arrays,
    )
    from ..kernels.mask_utils import types_to_bands

    qr_np = np.asarray(qr, dtype=np.int32)
    kr_np = np.asarray(kr, dtype=np.int32)
    tm_np = np.asarray(tmap, dtype=np.int32)
    if d_lo is None or d_hi is None:
        d_lo, d_hi = types_to_bands(qr_np, kr_np, tm_np)
    else:
        d_lo = np.asarray(d_lo, dtype=np.int32)
        d_hi = np.asarray(d_hi, dtype=np.int32)
    sq, hq, d = q.shape
    sk, hk, dv = v.shape
    scale = float(d) ** -0.5 if softmax_scale is None else float(softmax_scale)
    bq, bk = default_blocks(sq, sk)
    plan = get_ffa_plan(qr_np, kr_np, d_lo, d_hi, sq, sk, bq, bk)
    arrays, overrides = apply_bwd_overrides(
        plan_arrays(plan), qr_np, kr_np, d_lo, d_hi, sq, sk, bq, bk,
        plan.num_q_tiles, plan.num_k_tiles,
    )
    params = FFAParams(
        num_work=plan.num_work, num_work_t=plan.num_work_t,
        num_q_tiles=plan.num_q_tiles, num_k_tiles=plan.num_k_tiles,
        block_q=bq, block_k=bk, **overrides, softmax_scale=scale,
        softcap=float(softcap), group=hq // hk,
        interpret=_should_interpret(),
    )
    return _ffa_sink_core(q, k, v, sink, arrays, params, sink_layout)


@partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _ffa_sink_core(q, k, v, sink, arrays, params, sink_layout="sh"):
    out, lse = _ffa_sink_fwd_impl(q, k, v, sink, arrays, params, sink_layout)
    return out, lse


def _ffa_sink_fwd_impl(q, k, v, sink, arrays, params, sink_layout="sh"):
    from ..kernels.ffa import ffa_fwd_pallas_dispatch
    from .dist_attn import _head_major
    from .sink import apply_sink_fwd

    sqp = params.num_q_tiles * params.block_q
    skp = params.num_k_tiles * params.block_k
    out_t, lse_t, _ = ffa_fwd_pallas_dispatch(
        params, *arrays[:3],
        _head_major(q, sqp), _head_major(k, skp), _head_major(v, skp),
    )
    out = out_t.transpose(1, 0, 2)[: q.shape[0]]
    lse = lse_t.T[: q.shape[0]]
    return apply_sink_fwd(out, lse, sink, sink_layout)


def _ffa_sink_core_fwd(q, k, v, sink, arrays, params, sink_layout):
    out, lse = _ffa_sink_fwd_impl(q, k, v, sink, arrays, params, sink_layout)
    return (out, lse), (q, k, v, sink, out, lse, arrays)


def _ffa_sink_core_bwd(params, sink_layout, res, cts):
    from ..kernels.ffa import (
        _bwd_plan_slices,
        ffa_bwd_pallas_dispatch,
        ffa_delta_pallas_dispatch,
    )
    from .dist_attn import _head_major
    from .sink import sink_bwd

    do, _ = cts
    q, k, v, sink, out, lse, arrays = res
    sq = q.shape[0]
    sqp = params.num_q_tiles * params.block_q
    skp = params.num_k_tiles * params.block_k
    q_t, k_t, v_t = (
        _head_major(q, sqp), _head_major(k, skp), _head_major(v, skp)
    )
    do_t = _head_major(do, sqp)
    # delta via the Pallas rowsum kernel; padded rows are exactly zero
    # (zero-padded inputs), so (hq, sqp) doubles as delta_t and its
    # [:sq] rows feed sink_bwd
    delta_t = ffa_delta_pallas_dispatch(params, _head_major(out, sqp), do_t)
    delta = delta_t.T[:sq]
    lse_t = jnp.pad(
        lse, ((0, sqp - sq), (0, 0)), constant_values=float("-inf")
    ).T
    dq_arrs, dkv_arrs = _bwd_plan_slices(arrays)
    dq_t, dk_t, dv_t = ffa_bwd_pallas_dispatch(
        params, dq_arrs, dkv_arrs, q_t, k_t, v_t, do_t, lse_t, delta_t
    )
    # dk/dv already per kv head (dkv kernel sums the GQA group)
    dsink = sink_bwd(sink, lse, delta, sink_layout)
    return (
        dq_t.transpose(1, 0, 2)[:sq].astype(q.dtype),
        dk_t.transpose(1, 0, 2)[: k.shape[0]].astype(k.dtype),
        dv_t.transpose(1, 0, 2)[: v.shape[0]].astype(v.dtype),
        dsink,
        None,
    )


_ffa_sink_core.defvjp(_ffa_sink_core_fwd, _ffa_sink_core_bwd)
