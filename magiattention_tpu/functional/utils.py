"""Partial-attention merge math (ref: magi_attention/functional/utils.py).

The log-sum-exp merge identities used everywhere partial attention results are
combined: between online-softmax blocks inside the kernels, and between
host/remote partial results in the CP runtime (GroupReduce with op="lse").
All functions are -inf safe: a fully-masked partial (lse=-inf, out=0)
contributes nothing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def safe_logaddexp(a: jax.Array, b: jax.Array) -> jax.Array:
    """logaddexp that returns -inf (not nan) when both inputs are -inf."""
    both_inf = jnp.isneginf(a) & jnp.isneginf(b)
    m = jnp.maximum(a, b)
    m_safe = jnp.where(both_inf, 0.0, m)
    out = m_safe + jnp.log(jnp.exp(a - m_safe) + jnp.exp(b - m_safe))
    return jnp.where(both_inf, -jnp.inf, out)


def correct_attn_lse(lse1: jax.Array, lse2: jax.Array) -> jax.Array:
    """Merged lse of two partial attentions over disjoint key sets."""
    return safe_logaddexp(lse1, lse2)


def correct_attn_out_lse(
    out1: jax.Array,
    lse1: jax.Array,
    out2: jax.Array,
    lse2: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Merge two partial attention results over disjoint key sets.

    Args:
        out1/out2: ``[s, h, dv]`` partial outputs.
        lse1/lse2: ``[s, h]`` partial lse (fp32, -inf where empty).

    Returns:
        (out, lse) of the union, in the dtypes of the inputs.
    """
    lse = correct_attn_lse(lse1, lse2)
    w1 = jnp.exp(jnp.where(jnp.isneginf(lse1), -jnp.inf, lse1 - jnp.where(jnp.isneginf(lse), 0.0, lse)))
    w2 = jnp.exp(jnp.where(jnp.isneginf(lse2), -jnp.inf, lse2 - jnp.where(jnp.isneginf(lse), 0.0, lse)))
    out_dtype = out1.dtype
    out = (
        out1.astype(jnp.float32) * w1[..., None]
        + out2.astype(jnp.float32) * w2[..., None]
    )
    return out.astype(out_dtype), lse


def lse_weighted_reduce(
    outs: jax.Array,
    lses: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Merge P stacked partials in one shot.

    Args:
        outs: ``[P, s, h, dv]`` partial outputs.
        lses: ``[P, s, h]`` partial lses (fp32, -inf where empty).

    Returns:
        (out ``[s, h, dv]``, lse ``[s, h]``).
    """
    m = jnp.max(lses, axis=0)  # [s, h]
    all_inf = jnp.isneginf(m)
    m_safe = jnp.where(all_inf, 0.0, m)
    w = jnp.exp(lses - m_safe[None])  # [P, s, h]; exp(-inf - c) = 0
    denom = jnp.sum(w, axis=0)
    lse = jnp.where(all_inf, -jnp.inf, m_safe + jnp.log(denom))
    out = jnp.einsum(
        "pshd,psh->shd", outs.astype(jnp.float32), w
    ) / jnp.where(all_inf, 1.0, denom)[..., None]
    return out.astype(outs.dtype), lse
