"""Dynamic (qo-comm) CP engine.

Ref: magi_attention/functional/dist_attn.py qo-comm paths (_fetch_remote_q
:1625, _fetch_remote_qo_do_lse :1714, _reduce_partial_out_lse :1979,
_reduce_partial_dq :2302) — the execution of a `DynamicAttnPlan`:

forward (per rank, one shard_map program):
  q_buf  = [q | group_cast(q)]          k_buf/v_buf likewise
  out_buf, lse_buf = FFA(q_buf, k_buf, v_buf)
  partial rows return to q owners (group_cast of out/lse over `ret`),
  each owner lse-merges its row's contributions (merge_idx).

backward (custom VJP, the distributed-flash identity): the owner computes
delta = rowsum(do * out_final); (do, lse_final, delta) re-distribute to
compute ranks over the SAME q_cast plan (out_buf rows correspond 1:1 to
q_buf rows); each rank runs the FFA bwd kernels against the final lse/delta,
which makes per-part dq/dkv exact with no gradient through the merge
weights; dq/dkv partial rows reduce back to owners via the transposes of the
two forward casts (`group_reduce_rows`). No collective beyond the forward's
mirror image — zero-redundant in both directions.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from ..utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..comm.primitives import cast_rows, reduce_rows
from ..env import resilience as env_resilience
from ..kernels.ffa import (
    FFAParams,
    _bwd_plan_slices,
    bwd_mode_key,
    bwd_modeled_cost,
    ffa_bwd_pallas_dispatch,
    ffa_delta_pallas_dispatch,
    _should_interpret,
    default_blocks,
    ffa_attn_with_plan,
    resolved_bwd_mode,
)
from ..meta.collection.dynamic_meta import DynamicAttnPlan
from ..utils.profiling import instrument_scope, profile_scope
from .dist_attn import DeferredTilePolicy, _head_major, _stack_plans
from .utils import lse_weighted_reduce
from .. import telemetry

NEG_INF = float("-inf")


def _merge_rows(out_buf, lse_buf, ret_out, ret_lse, merge_idx):
    """lse-merge each local row's contributions.

    merge_idx: (shard, M) into [out_buf | ret_buf | dummy]."""
    h, dv = out_buf.shape[1], out_buf.shape[2]
    cat_out = jnp.concatenate(
        [out_buf, ret_out, jnp.zeros((1, h, dv), out_buf.dtype)], axis=0
    )
    cat_lse = jnp.concatenate(
        [lse_buf, ret_lse, jnp.full((1, h), NEG_INF, jnp.float32)], axis=0
    )
    co = jnp.take(cat_out, merge_idx, axis=0)  # (shard, M, h, dv)
    cl = jnp.take(cat_lse, merge_idx, axis=0)  # (shard, M, h)
    return lse_weighted_reduce(
        co.transpose(1, 0, 2, 3), cl.transpose(1, 0, 2)
    )


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _dyn_attn_shard(q, k, v, static, axis, comm, arrays):
    out, lse, ml, _, _, _ = _dyn_fwd_impl(q, k, v, static, axis, comm, arrays)
    return out, lse, ml


def _dyn_fwd_impl(q, k, v, static, axis, comm, arrays):
    params, shard, kv_shard, kinds, fwd_hp, _ = static
    q_kind, k_kind, r_kind = kinds
    (q_ops, k_ops, r_ops, (merge_idx,)) = comm
    with profile_scope("qo_comm_cast"):
        q_rem = cast_rows(q, q_ops, q_kind, axis)
        q_buf = jnp.concatenate([q, q_rem], axis=0)
        k_rem = cast_rows(k, k_ops, k_kind, axis)
        v_rem = cast_rows(v, k_ops, k_kind, axis)
        k_buf = jnp.concatenate([k, k_rem], axis=0)
        v_buf = jnp.concatenate([v, v_rem], axis=0)
    with profile_scope("ffa_fwd_dyn"):
        out_buf, lse_buf, ml = ffa_attn_with_plan(
            q_buf, k_buf, v_buf, arrays, params,
            return_max_logits=True,  # constant -inf unless params emit it
        )
    # fwd high-precision reduce (ref _reduce_partial_out_lse + env decision,
    # dist_attn.py:243): partial out rows return to their owners in fp32 —
    # 2x this wire, better lse-merge precision. lse is fp32 either way.
    ret_src = out_buf.astype(jnp.float32) if fwd_hp else out_buf
    ret_out = cast_rows(ret_src, r_ops, r_kind, axis)
    ret_lse = cast_rows(lse_buf, r_ops, r_kind, axis)
    out, lse = _merge_rows(out_buf, lse_buf, ret_out, ret_lse, merge_idx)
    return out.astype(out_buf.dtype), lse, ml, q_buf, k_buf, v_buf


def _dyn_fwd(q, k, v, static, axis, comm, arrays):
    out, lse, ml, _, _, _ = _dyn_fwd_impl(q, k, v, static, axis, comm, arrays)
    return (out, lse, ml), (q, k, v, out, lse, comm, arrays)


def _dyn_bwd(static, axis, res, cts):
    do, _, _ = cts  # lse/max_logits are auxiliary
    q, k, v, out, lse, comm, arrays = res
    params, shard, kv_shard, kinds, _, bwd_hp = static
    q_kind, k_kind, _ = kinds
    (q_ops, k_ops, _, _) = comm

    # rebuild compute buffers (refetch — cheaper than saving the buffers,
    # matching the reference's bwd-side comm)
    q_rem = cast_rows(q, q_ops, q_kind, axis)
    q_buf = jnp.concatenate([q, q_rem], axis=0)
    k_rem = cast_rows(k, k_ops, k_kind, axis)
    v_rem = cast_rows(v, k_ops, k_kind, axis)
    k_buf = jnp.concatenate([k, k_rem], axis=0)
    v_buf = jnp.concatenate([v, v_rem], axis=0)

    # owner-side final quantities, re-distributed over the q cast; delta
    # runs on the local shard rows (pre-cast), so pad to a block_q multiple
    bq = params.block_q
    sp = -(-out.shape[0] // bq) * bq
    delta = ffa_delta_pallas_dispatch(
        params, _head_major(out, sp), _head_major(do, sp)
    ).T[: out.shape[0]]  # (shard, hq)
    do_buf = jnp.concatenate(
        [do, cast_rows(do, q_ops, q_kind, axis)], axis=0
    )
    lse_buf = jnp.concatenate(
        [lse, cast_rows(lse, q_ops, q_kind, axis)], axis=0
    )
    delta_buf = jnp.concatenate(
        [delta, cast_rows(delta, q_ops, q_kind, axis)], axis=0
    )

    sqp = params.num_q_tiles * params.block_q
    skp = params.num_k_tiles * params.block_k
    q_t = _head_major(q_buf, sqp)
    k_t = _head_major(k_buf, skp)
    v_t = _head_major(v_buf, skp)
    do_t = _head_major(do_buf, sqp)
    nbuf = q_buf.shape[0]
    lse_t = jnp.pad(
        lse_buf, ((0, sqp - nbuf), (0, 0)), constant_values=NEG_INF
    ).T
    delta_t = jnp.pad(delta_buf, ((0, sqp - nbuf), (0, 0))).T

    dq_arrs, dkv_arrs = _bwd_plan_slices(arrays)
    dq_t, dk_t, dv_t = ffa_bwd_pallas_dispatch(
        params, dq_arrs, dkv_arrs, q_t, k_t, v_t, do_t, lse_t, delta_t
    )
    # dk/dv already per kv head (dkv kernel sums the GQA group)

    dq_buf = dq_t.transpose(1, 0, 2)[:nbuf]
    dk_buf = dk_t.transpose(1, 0, 2)[: k_buf.shape[0]]
    dv_buf = dv_t.transpose(1, 0, 2)[: v_buf.shape[0]]

    # the kernels emit fp32 partials; MAGI_ATTENTION_BWD_HIGH_PRECISION_REDUCE
    # keeps them fp32 through the wire reduce (2x bwd comm bytes, ref
    # _reduce_partial_dq/_reduce_partial_dkv); default reduces in the input
    # dtype (ref bwd_local_dkv_lp_init / bwd_local_dq_lp_init, :245-253)
    if not bwd_hp:
        dq_buf = dq_buf.astype(q.dtype)
        dk_buf = dk_buf.astype(k.dtype)
        dv_buf = dv_buf.astype(v.dtype)
    dq = dq_buf[:shard] + reduce_rows(
        dq_buf[shard:], q_ops, q_kind, axis, shard
    )
    dk = dk_buf[:kv_shard] + reduce_rows(
        dk_buf[kv_shard:], k_ops, k_kind, axis, kv_shard
    )
    dv = dv_buf[:kv_shard] + reduce_rows(
        dv_buf[kv_shard:], k_ops, k_kind, axis, kv_shard
    )
    return (
        dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
        None, None,
    )


_dyn_attn_shard.defvjp(_dyn_fwd, _dyn_bwd)


@dataclass(eq=False)
class DynamicDistAttnRuntime(DeferredTilePolicy):
    """Executable runtime for one DynamicAttnPlan (qo-comm engine)."""

    plan: DynamicAttnPlan
    mesh: Mesh
    cp_axis: str
    softmax_scale: float | None = None
    softcap: float = 0.0
    block_q: int | None = None
    block_k: int | None = None

    def __post_init__(self) -> None:
        p = self.plan
        # auto-tile defers to the first calc_attn where the real head
        # dims/dtype are known (DeferredTilePolicy; r3 advisor finding)
        self._init_tile_policy(self.block_q, self.block_k)

        def ops_of(cast):
            # per-stage tier from the solver's AUTO choice (cast.lowering)
            if cast.lowering == "ragged":
                from .dist_attn import _ragged_arrays

                return (_ragged_arrays(cast), ("ragged", cast.r_max))
            if cast.lowering == "ppermute":
                cp = cast.send_counts.shape[0]
                return (
                    (jnp.asarray(cast.pp_send_idx),
                     jnp.asarray(cast.pp_recv_sel)),
                    ("pp", cast.pp_deltas, cast.pp_caps, cp),
                )
            return (
                (jnp.asarray(cast.send_idx), jnp.asarray(cast.recv_sel)),
                ("a2a",),
            )

        (q_ops, self._q_kind) = ops_of(p.q_cast)
        (k_ops, self._k_kind) = ops_of(p.kv_cast)
        (r_ops, self._r_kind) = ops_of(p.ret)
        self._comm = (q_ops, k_ops, r_ops, (jnp.asarray(p.merge_idx),))

    def _build_plans(self, blk_q, blk_k) -> None:
        # may run inside a jit trace (deferred auto-tile): force the plan
        # constants concrete so no tracer is cached on self
        with jax.ensure_compile_time_eval(), \
                telemetry.stage_timer("build_plans"):
            p = self.plan
            bq, bk = default_blocks(p.q_buf_len, p.k_buf_len, blk_q, blk_k)
            self._bq, self._bk = bq, bk
            pol_dq, pol_dkv = getattr(self, "_policy_bwd", (None, None))
            self._arrays, self._dims = _stack_plans(
                p.attn_args, p.q_buf_len, p.k_buf_len, bq, bk,
                policy_dq=pol_dq, policy_dkv=pol_dkv,
            )

    def _attn_step_payload(self, q, k, v) -> dict:
        """One qo-comm step's telemetry payload (callers gate on
        ``telemetry.enabled()``). Per-stage row bytes differ: q rows, fused
        k|v rows, and returned partial out+lse rows each have their own
        width, resolved here where dtypes/head dims are known."""
        from ..env import comm as env_comm

        p = self.plan
        sq, hq, dh = q.shape
        _, hk, dv = v.shape
        exec_map = {"pp": "ppermute", "a2a": "a2a", "ragged": "ragged"}
        # partial out rows ride the ret cast in fp32 under the fwd HP reduce
        out_itemsize = (
            4 if env_comm.is_fwd_high_precision_reduce_enable()
            else q.dtype.itemsize
        )
        stage_defs = (
            ("q_cast", p.q_cast, self._q_kind, "qo_comm_cast",
             hq * dh * q.dtype.itemsize),
            ("kv_cast", p.kv_cast, self._k_kind, "qo_comm_cast",
             hk * dh * k.dtype.itemsize + hk * dv * v.dtype.itemsize),
            ("ret", p.ret, self._r_kind, "ffa_fwd_dyn",
             hq * dv * out_itemsize + hq * 4),  # + fp32 lse
        )
        stages = []
        payload_total = wire_total = 0
        for name, cast, kind, scope, row_bytes in stage_defs:
            d = cast.telemetry_dict(executed=exec_map[kind[0]])
            d["stage"] = name
            d["xprof_scope"] = scope
            d["row_bytes"] = row_bytes
            d["payload_bytes"] = d["payload_rows"] * row_bytes
            d["wire_bytes"] = d["wire_rows"] * row_bytes
            d["padding_bytes"] = d["padding_rows"] * row_bytes
            payload_total += d["payload_bytes"]
            wire_total += d["wire_bytes"]
            stages.append(d)
        payload = {
            "planner": "dynamic",
            "backend": self.backend,
            # observatory join keys (telemetry/store.py _ATTN_KEY_FIELDS)
            "mask_sig": self._mask_signature(),
            "mesh_sig": self._mesh_signature(),
            "env_sig": self._env_signature(),
            "q_shape": list(q.shape),
            "kv_shape": list(v.shape),
            "cp_size": self.mesh.shape[self.cp_axis],
            "overlap_degree": 1,  # qo-comm runs one compute stage
            "seqlen_q_shard": sq,
            "heads_q": hq, "head_dim": dh, "heads_kv": hk, "head_dim_v": dv,
            "dtype": q.dtype.name,
            "stages": stages,
            "payload_bytes_total": payload_total,
            "wire_bytes_total": wire_total,
            "padding_bytes_total": wire_total - payload_total,
        }
        if getattr(self, "_bq", None) is not None:
            cp = self.mesh.shape[self.cp_axis]
            w = self._dims[2]
            padded = cp * w * self._bq * self._bk
            band = sum(
                telemetry.band_area(a.q_ranges, a.k_ranges, a.d_lo, a.d_hi)
                for a in p.attn_args
            )
            # backward execution mode the combined dispatch will pick
            # (fused one-pass vs split dq+dkv) for this plan's geometry
            nqt, nkt, wn, wt, overrides = self._dims
            prm0 = FFAParams(
                num_work=wn, num_work_t=wt, num_q_tiles=nqt,
                num_k_tiles=nkt, block_q=self._bq, block_k=self._bk,
                **overrides, softmax_scale=1.0, softcap=self.softcap,
                group=hq // hk, interpret=_should_interpret(),
            )
            bwd_mode = resolved_bwd_mode(
                prm0, nqt * self._bq, dh, dv, q.dtype.itemsize
            )
            payload.update(
                block_q=self._bq, block_k=self._bk,
                band_elems=band,
                padded_elems=padded,
                est_flops_fwd=4 * band * dh * hq,
                padded_flops_fwd=4 * padded * dh * hq,
                bwd_mode=bwd_mode,
                bwd_key=list(
                    bwd_mode_key(prm0, dh, dv, q.dtype.itemsize)
                ),
                bwd_cost=bwd_modeled_cost(
                    prm0, dh, dv, q.dtype.itemsize, bwd_mode
                ),
            )
        return payload

    def _tile_geoms(self):
        p = self.plan
        return (
            [
                (a.q_ranges, a.k_ranges, a.d_lo, a.d_hi)
                for a in p.attn_args
            ],
            p.q_buf_len,
            p.k_buf_len,
        )

    @instrument_scope(name="DynamicDistAttnRuntime.calc_attn")
    def calc_attn(
        self,
        q: jax.Array,
        k: jax.Array,
        v: jax.Array,
        return_max_logits: bool = False,
    ):
        """(out, lse[, max_logits]) over dispatched tensors, qo-comm
        execution. lse is a non-differentiable auxiliary output on every
        backend (the ffa custom VJP ignores its cotangent, so the jnp
        backends stop_gradient it for cross-backend agreement).

        q/k/v: ``(cp*shard, h, d)`` dispatched layout sharded over cp axis.
        """
        impl = self._calc_attn_impl
        if env_resilience.is_resilience_active():
            # guarded path (resilience/fallback.py); dead with flags off
            from ..resilience.fallback import run_calc_attn

            impl = partial(run_calc_attn, self)
        if not telemetry.enabled():
            return impl(q, k, v, return_max_logits)
        with telemetry.stage_timer("calc_attn"):
            result = impl(q, k, v, return_max_logits)
        wall_ms = telemetry.get_collector().gauges.get(
            "time.calc_attn.last_ms"
        )
        telemetry.record_event(
            "attn_step",
            xprof_scope="DynamicDistAttnRuntime.calc_attn",
            wall_ms=wall_ms,
            **self._attn_step_payload(q, k, v),
        )
        return result

    def _calc_attn_impl(
        self,
        q: jax.Array,
        k: jax.Array,
        v: jax.Array,
        return_max_logits: bool = False,
    ):
        p = self.plan
        sq, hq, dh = q.shape
        _, hk, dv = v.shape
        group = hq // hk
        scale = (
            float(dh) ** -0.5
            if self.softmax_scale is None
            else self.softmax_scale
        )
        axis = self.cp_axis
        spec = P(axis)

        if self.backend in ("sdpa", "sdpa_online"):
            return self._calc_attn_sdpa(q, k, v, scale, return_max_logits)

        # auto-tile with the real head dims/dtype (r3 advisor finding)
        self._ensure_auto_plans(dh, dv, q.dtype.itemsize)
        nqt, nkt, w, wt, overrides = self._dims
        params = FFAParams(
            num_work=w, num_work_t=wt, num_q_tiles=nqt, num_k_tiles=nkt,
            block_q=self._bq, block_k=self._bk, **overrides,
            softmax_scale=scale, softcap=self.softcap, group=group,
            interpret=_should_interpret(),
            emit_max_logits=return_max_logits,
        )
        from ..env import comm as env_comm

        static = (
            params, p.shard_len, p.kv_shard_len,
            (self._q_kind, self._k_kind, self._r_kind),
            env_comm.is_fwd_high_precision_reduce_enable(),
            env_comm.is_bwd_high_precision_reduce_enable(),
        )

        def f(q, k, v, comm, arrays):
            comm_local = tuple(
                tuple(a[0] for a in grp) for grp in comm
            )
            arrays_local = tuple(a[0] for a in arrays)
            # each rank's compute covers its assigned rectangles, so the
            # cp MAX of the kernel's per-head max is the global per-head
            # max (ref dist_attn.py:550 reduce_max_logits)
            out, lse, ml = _dyn_attn_shard(
                q, k, v, static, axis, comm_local, arrays_local
            )
            if return_max_logits:
                return out, lse, jax.lax.pmax(jax.lax.stop_gradient(ml), axis)
            return out, lse

        out_specs = (spec, spec, P()) if return_max_logits else (spec, spec)
        fn = shard_map(
            f,
            mesh=self.mesh,
            in_specs=(spec, spec, spec,
                      tuple(
                          tuple(P(axis) for _ in grp) for grp in self._comm
                      ),
                      tuple(P(axis) for _ in self._arrays)),
            out_specs=out_specs,
            check_vma=False,
        )
        return fn(q, k, v, self._comm, self._arrays)

    # -- jnp fake-backend path (fp32/fp64-exact distributed testing) -------

    def _calc_attn_sdpa(self, q, k, v, scale, return_max_logits=False):
        from ..kernels.sdpa import dense_max_logits, sdpa_attn
        from ..kernels.sdpa_online import sdpa_online_attn

        p = self.plan
        dense_fn = (
            sdpa_attn if self.backend == "sdpa" else sdpa_online_attn
        )
        axis = self.cp_axis
        spec = P(axis)
        softcap = self.softcap

        # per-rank slice arrays, stacked (pure jnp path, jax AD end-to-end —
        # including the lse cotangent through the merge)
        n_max = max(a.num_slices for a in p.attn_args) or 1
        padded = [a.pad_to(n_max) for a in p.attn_args]
        slices = tuple(
            jnp.asarray(np.stack([getattr(a, f) for a in padded]))
            for f in ("q_ranges", "k_ranges", "d_lo", "d_hi")
        )

        q_kind, k_kind, r_kind = self._q_kind, self._k_kind, self._r_kind
        from ..env import comm as env_comm

        fwd_hp = env_comm.is_fwd_high_precision_reduce_enable()

        def f(q, k, v, comm, slices):
            q_ops, k_ops, r_ops, (merge_idx,) = tuple(
                tuple(a[0] for a in grp) for grp in comm
            )
            q_buf = jnp.concatenate(
                [q, cast_rows(q, q_ops, q_kind, axis)], axis=0
            )
            k_buf = jnp.concatenate(
                [k, cast_rows(k, k_ops, k_kind, axis)], axis=0
            )
            v_buf = jnp.concatenate(
                [v, cast_rows(v, k_ops, k_kind, axis)], axis=0
            )
            qr, kr, lo, hi = (a[0] for a in slices)
            out_buf, lse_buf = dense_fn(
                q_buf, k_buf, v_buf, qr, kr, None,
                softmax_scale=scale, softcap=softcap, d_lo=lo, d_hi=hi,
            )
            ret_src = out_buf.astype(jnp.float32) if fwd_hp else out_buf
            ret_out = cast_rows(ret_src, r_ops, r_kind, axis)
            ret_lse = cast_rows(lse_buf, r_ops, r_kind, axis)
            out, lse = _merge_rows(
                out_buf, lse_buf, ret_out, ret_lse, merge_idx
            )
            out = out.astype(out_buf.dtype)
            # lse is non-differentiable on the ffa backend (custom VJP drops
            # its cotangent); stop_gradient keeps the backends in agreement
            lse = jax.lax.stop_gradient(lse)
            if return_max_logits:
                ml = dense_max_logits(
                    q_buf, k_buf, qr, kr, None,
                    softmax_scale=scale, softcap=softcap, d_lo=lo, d_hi=hi,
                )
                return out, lse, jax.lax.pmax(jax.lax.stop_gradient(ml), axis)
            return out, lse

        out_specs = (spec, spec, P()) if return_max_logits else (spec, spec)
        fn = shard_map(
            f,
            mesh=self.mesh,
            in_specs=(spec, spec, spec,
                      tuple(
                          tuple(P(axis) for _ in grp) for grp in self._comm
                      ),
                      tuple(P(axis) for _ in slices)),
            out_specs=out_specs,
            check_vma=False,
        )
        return fn(q, k, v, self._comm, slices)
